#!/usr/bin/env python
"""Benchmark: contributivity sweeps through the production characteristic-
function engine, covering the BASELINE.md benchmark configs.

Configs (select with BENCH_CONFIG, default "1"):
  1  exact Shapley, MNIST-scale data, BENCH_PARTNERS partners (default 10 —
     the north star: 1023 coalitions; 3 reproduces config_quick_debug)
  2  TMCS, CIFAR10-scale data, 5 partners
  3  importance-sampling Shapley (BENCH_METHOD: IS_lin_S / IS_reg_S /
     AIS_Kriging_S), MNIST, 10 partners
  4  stratified MC Shapley (BENCH_METHOD: SMCS / WR_SMC), IMDB, 4 partners
  5  TMCS + Independent scores, CIFAR10, 8 partners with 2 corrupted
  6  multi-tenant sweep service (mplc_tpu/service/): BENCH_TENANTS exact
     Shapley games (default 2, distinct seeds) submitted to one
     SweepService — measures scheduler overhead, cross-tenant program
     packing (the sidecar's service row carries packed-batch counts and
     per-tenant fair-share cost attribution) and journaling cost
     (MPLC_TPU_SERVICE_SLICE / _MAX_PENDING / _FAULT_PLAN apply)
  7  service load/chaos harness (scripts/load_gen.py): BENCH_JOBS
     (default 1000) mixed-shape 1-epoch titanic games across 3 priority
     tiers against one SweepService under seeded chaos injection
     (default chaos@rate0.05:seed7 unless MPLC_TPU_SERVICE_FAULT_PLAN is
     set) — reports saturation throughput, per-tier p50/p95/p99 tail
     latency, fairness vs stride weights, shed/quarantine accounting,
     and equality-checks the overload invariant (every accepted job
     terminal, completed tenants bit-identical to solo runs).
     MPLC_TPU_SERVICE_WORKERS / _SHED_P99_SEC / _MAX_PENDING apply;
     the first benchmark of the system AS a service under load
  8  live contributivity tier (mplc_tpu/live/): one recorded game kept
     RESIDENT, its rounds re-appended as live aggregation rounds up to
     BENCH_LIVE_ROUNDS (default 4x the recording) — at each doubling of the resident
     history a fresh GTG query (round-stamp invalidated) and a warm
     (memoized) re-query are timed, so the sidecar's live block shows
     query latency vs resident rounds and the memo/banked warm path.
     The emitted metric is the final fresh-query latency at max
     residency (MPLC_TPU_LIVE_PRUNE_TAU / _MAX_ROUNDS apply)
  9  fleet sweep plane (mplc_tpu/parallel/fleet.py): ONE sweep statically
     partitioned into W disjoint coalition slices and executed across W
     OS processes, measured at BENCH_FLEET_DEVICES (default 1,2,4,8)
     total coalition shards — the MEASURED wall-clock-vs-shards scaling
     curve that replaces the projected v5e-8 number. Without an
     accelerator the points run as W single-device workers on the
     host-CPU mesh (provenance-flagged `cpu_mesh` in the sidecar; each
     point's number is the max per-shard SWEEP wall-clock — the fleet's
     measured critical path, with shard startup recorded separately per
     shard and the basis + sequential/concurrent mode in the sidecar). A
     deterministic-reduce equality pass (1-shard vs multi-shard, value
     ledgers diffed via obs/numerics.diff_ledgers) proves the W-shard
     merge bit-identical and feeds the sidecar's numerics block for the
     scripts/bench_diff.py gate. MPLC_TPU_FLEET_SHARDS caps the
     equality-pass shard count; the shared MPLC_TPU_COMPILE_CACHE_DIR
     program-bank manifest is what keeps W-1 of the W shards from
     recompiling (per-shard manifest-hit counts in the sidecar).
  10 live residency tier (mplc_tpu/live/residency.py): BENCH_LIVE_GAMES
     journal-backed live games (default 1000) of one shared scenario
     under a BENCH_LIVE_RESIDENT cap (default 128), pressure doubled
     from 125 games up — at each pressure point a game sample is
     cold-queried (LRU-evicted, so the query pays the WAL restore: the
     p99 FRESH-query latency) and re-queried warm (memo path), with
     eviction/restore totals and restore-latency quantiles in the
     sidecar's live block. A post-restore exact v(S) sweep feeds the
     numerics block, so the bench_diff gate proves evict->restore is
     bit-identical across commits (MPLC_TPU_LIVE_MAX_RESIDENT applies
     when set; the emitted metric is p99 fresh-query seconds at max
     pressure)
  11 fleet router chaos (mplc_tpu/service/router.py): BENCH_ROUTER_JOBS
     mixed-shape jobs (default 8) routed through a FleetRouter fronting
     BENCH_ROUTER_SHARDS inline SweepService shards (default 2, sliced
     quanta so jobs span many scheduling turns) while the router's own
     fault plan (MPLC_TPU_ROUTER_FAULT_PLAN, default
     shardkill@shard0:sec2) kills a shard mid-run — measures the routed
     wall-clock and the failover machinery end to end: the sidecar's
     router block carries routed/resubmit/re-pin/failover/exhausted
     totals and routing-latency quantiles, and the run equality-checks
     the router invariant (every routed job terminal, completed v(S)
     tables bit-identical to solo fault-free runs, failover exercised
     when a kill was planned). MPLC_TPU_ROUTER_BUDGET / _BACKOFF_SEC /
     _REPIN_OVERLOADS apply

Workload notes. The reference (saved_experiments results.csv) trains ONE
fedavg MNIST model in ~589 s wall-clock at 50 epochs and needs one full
training per distinct coalition (mplc/contributivity.py:92-136, :149-158).
Here the engine batches coalitions, groups them by size (a size-k coalition
trains k partner slots, not N masked ones), skips the per-minibatch val
evals the reference pays (record_val_history=False — only the early-stopping
column is evaluated), and — with multiple devices — shards batches over the
`coal` mesh axis.

Timing excludes compilation: a warm-up engine first evaluates one
full-width batch per coalition size (compiled executables are shared per
(model, config) via the trainer registry, and the engine pads every batch
of a call to one bucket width per size), then a fresh engine with a cold
memo cache — sharing the warm engine's device arrays via share_data_from,
so HBM holds ONE copy of the data — is timed end to end.

Baseline accounting: reference wall-clock scales ~linearly in epochs and in
the number of distinct coalition trainings, so
  baseline_seconds = 589 s * (epochs / 50) * synth_scale * n_trainings
                     (* 3030/589 for CIFAR10-shaped runs)
and vs_baseline = baseline_seconds / measured_seconds (higher is better).
For MC methods n_trainings = the timed run's first_charac_fct_calls_count —
the reference's own cost counter (contributivity.py:73).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Env knobs: BENCH_CONFIG, BENCH_PARTNERS, BENCH_EPOCHS (default 8),
BENCH_METHOD, BENCH_DTYPE (default bfloat16 on TPU, float32 on CPU),
MPLC_TPU_NO_SLOTS=1 for masked full-width execution, MPLC_TPU_SLOT_MERGE=0
/ MPLC_TPU_SLOT_POW2=1 for the exact / pow2 slot bucketings (default:
merged adjacent sizes), MPLC_TPU_PIPELINE_BATCHES=0 to opt out of batch
overlap, MPLC_TPU_BATCH_CAP_CEILING to lift the batch-cap autotune past
16, MPLC_TPU_STEP_WIDTH_MULT=k for the fused wide-step deviation mode
(k consecutive sub-batches per SGD step; default 1 = exact parity),
MPLC_TPU_SYNTH_SCALE for smaller data on CPU smoke runs,
MPLC_TPU_SYNTH_NOISE (default 0.75 here: accuracy must not saturate, or
every Shapley value degenerates to 1/N — BENCH_r02's flaw).
Fault tolerance (mplc_tpu/faults.py + the engine's recovery ladder):
MPLC_TPU_MAX_RETRIES / MPLC_TPU_RETRY_BACKOFF_SEC for transient-failure
retry, MPLC_TPU_MAX_CAP_HALVINGS for the OOM degradation ladder,
MPLC_TPU_FAULT_PLAN to inject deterministic faults. The telemetry sidecar
records a top-level "degraded" flag plus the report's resilience row, so
a number earned on a degraded run is never mistaken for a clean one.
Partner-level faults & trust: MPLC_TPU_PARTNER_FAULT_PLAN injects
dropout/straggler/noisy/glabel partner misbehavior (changes the GAME, so
it refuses cached replay); MPLC_TPU_SEED_ENSEMBLE=K batches K seed
replicas of every coalition through the same buckets and adds a `trust`
row (per-partner Shapley CIs + Kendall-tau rank stability) to the report
and sidecar.
Retrain-free estimators: BENCH_METHOD="GTG-Shapley" / "SVARM" (configs
2-5) route through coalition RECONSTRUCTION — one recorded grand-
coalition training run, then eval-only batches (MPLC_TPU_GTG_TRUNCATION,
MPLC_TPU_SVARM_SAMPLES); the sweep report grows a `reconstruction` row.
MPLC_TPU_COMPILE_CACHE_DIR points JAX's persistent compilation cache at a
program bank: the warm-up doubles as a cache prime and the sidecar's
`compile_cache` block records the cache-hit provenance (entry growth).
"""

import json
import math
import os
import sys
import threading
import time

import numpy as np

# ---------------------------------------------------------------------------
# Watchdogs: the TPU here sits behind a network tunnel that can wedge (a
# blocked await with an idle host, indistinguishable from a slow sweep
# without a deadline). A hung bench is strictly worse than a failed one —
# the driver records nothing either way, but a hang also eats the round.
# ---------------------------------------------------------------------------

_last_beat = time.monotonic()
# Set the moment the stall watchdog declares the run dead: suppresses any
# late _emit from a main thread that recovers mid-fallback (exactly one
# metric line may reach stdout) and parks main at exit so the process
# lives until the watchdog's os._exit.
_watchdog_fired = threading.Event()


def _beat():
    global _last_beat
    _last_beat = time.monotonic()


def _start_stall_watchdog(platform: str):
    """Abort when no device batch completes for BENCH_STALL_TIMEOUT
    seconds. Default 15 min on accelerators — measured batches take
    <= ~70 s (size-10 slot pipeline) and a residual compile <= ~3 min,
    so 900 s is ~4x any legitimate gap while wasting half as much of a
    wedged round as the previous 30 min default (the tunnel wedged twice
    on 2026-07-30; both times it stayed dead long past any timeout). On
    host-CPU runs there is no tunnel to wedge and a single compile+train
    step of the conv models can legitimately exceed any sane limit on
    this one-core box, so the watchdog is OFF unless BENCH_STALL_TIMEOUT
    is set explicitly."""
    default = "0" if platform == "cpu" else "900"
    limit = float(os.environ.get("BENCH_STALL_TIMEOUT", default))
    if limit <= 0:
        return

    def watch():
        while True:
            time.sleep(15)
            if time.monotonic() - _last_beat > limit:
                print(f"[bench] FATAL: no progress for {limit:.0f} s — "
                      "device tunnel presumed wedged, aborting",
                      file=sys.stderr, flush=True)
                _watchdog_fired.set()
                # The main thread is blocked on the wedged device call and
                # can't run the fallback; spawn it from here, then take the
                # whole process down with the child's exit code. (sys.exit
                # would only end this watchdog thread.) If the spawn itself
                # blows up, still _exit — a dead watchdog thread would
                # leave the wedged process hung forever.
                try:
                    if _fallback_allowed():
                        os._exit(_fallback_exit())
                finally:
                    os._exit(4)

    threading.Thread(target=watch, daemon=True).start()


def _devices_with_deadline():
    """jax.devices() with a timeout, or None when backend init blocks:
    init dials the tunnel and can hang forever when the remote grant is
    stuck. BENCH_INIT_TIMEOUT seconds (default 240), 0 disables."""
    import jax

    limit = float(os.environ.get("BENCH_INIT_TIMEOUT", "240"))
    if limit <= 0:
        return jax.devices()
    result = {}

    def init():
        try:
            result["devices"] = jax.devices()
        except BaseException as e:  # surfaced in the main thread below
            result["error"] = e

    t = threading.Thread(target=init, daemon=True)
    t.start()
    t.join(limit)
    if t.is_alive():
        print(f"[bench] jax backend init did not finish in "
              f"{limit:.0f} s — accelerator tunnel unresponsive",
              file=sys.stderr, flush=True)
        return None
    if "error" in result:
        raise result["error"]
    return result["devices"]


def _fallback_allowed() -> bool:
    return (os.environ.get("BENCH_CPU_FALLBACK", "1") != "0"
            and not os.environ.get("BENCH_IS_FALLBACK_CHILD"))


# the driver-shaped workload per config: the cached-record metric prefix a
# replay may match, for the epochs-8 default. Configs 2-5 hardcode their
# dataset/partner count in main(); only BENCH_METHOD (and the global knob
# list) can reshape them, and config 1 additionally reads
# BENCH_PARTNERS/BENCH_DATASET.
_REPLAY_SHAPES = {
    "1": "exact_shapley_mnist_10partners_8epochs",
    "2": "tmcs_cifar10_5partners_8epochs",
    "3": "is_lin_s_mnist_10partners_8epochs",
    "4": "smcs_imdb_4partners_8epochs",
    "5": "tmcs_cifar10_8partners_8epochs",
}

# Workload-shaping knobs shared by the cached-replay refusal AND the
# CPU-fallback env-strip: any set value makes a cached full-scale TPU
# number a DIFFERENT workload, and must not leak into the reduced CPU
# child. ONE list, referenced from both sites — PRs 1-6 each extended two
# hand-maintained copies in lockstep, which is exactly how a knob ends up
# in one list and not the other. (MPLC_TPU_SYNTH_NOISE is special-cased
# at each site: main() always sets it, so only a NON-default value
# refuses replay, and the fallback child re-sets its own.)
_WORKLOAD_KNOBS = (
    "BENCH_DTYPE", "MPLC_TPU_BATCH_CAP_CEILING",
    "MPLC_TPU_COALITIONS_PER_DEVICE",
    # the compile cache changes what a measured run PAYS (residual
    # compiles land inside the timed region), so a cached TPU number
    # from a different cache state is a different workload — and the CPU
    # child configures its own cache dir
    "MPLC_TPU_COMPILE_CACHE_DIR",
    # fenced batches run without overlap and pay an extra sync — a
    # different fence rate is a different measurement protocol
    "MPLC_TPU_DEVICE_FENCE_RATE",
    # deterministic-reduce pins a different reduction order — v(S)
    # itself changes, and the masked 2-D-family routing replaces slot
    # execution; the numerics audit runs extra capture trainings at
    # fence ordinals — both are different workloads entirely
    "MPLC_TPU_DETERMINISTIC_REDUCE", "MPLC_TPU_NUMERICS_AUDIT",
    # donation reshapes the HBM-derived batch cap (bucket widths) and the
    # bank reshapes what a measured run pays in compile time
    "MPLC_TPU_DONATE_BUFFERS", "MPLC_TPU_PROGRAM_BANK",
    "MPLC_TPU_EVAL_CHUNK", "MPLC_TPU_FAULT_PLAN",
    # the fleet knobs reshape the fleet bench's process topology (shard
    # count) and wire the process into a shared cross-shard state dir
    "MPLC_TPU_FLEET_SHARDS", "MPLC_TPU_FLEET_SHARD_ID",
    # the staleness window decides WHEN a router declares a silent shard
    # dead (and so when failover work lands inside the timed region)
    "MPLC_TPU_FLEET_STALE_SEC",
    "MPLC_TPU_FLEET_STATE_DIR",
    "MPLC_TPU_GTG_TRUNCATION",
    # the live-tier knobs change which coalitions a live query evaluates
    # (pruning), how deep reconstruction replays (round cap) and which
    # queries survive (deadline) — a different live workload entirely.
    # The residency cap decides which queries pay a WAL restore (the very
    # latency config 10 measures), ingestion opens the POST round path,
    # and the cluster knobs change a hierarchical query's coalition count
    "MPLC_TPU_LIVE_CLUSTERS", "MPLC_TPU_LIVE_CLUSTER_TAU",
    "MPLC_TPU_LIVE_INGEST", "MPLC_TPU_LIVE_MAX_RESIDENT",
    "MPLC_TPU_LIVE_MAX_ROUNDS", "MPLC_TPU_LIVE_PRUNE_TAU",
    "MPLC_TPU_LIVE_QUERY_DEADLINE_SEC",
    "MPLC_TPU_MAX_CAP_HALVINGS", "MPLC_TPU_MAX_RETRIES",
    "MPLC_TPU_NO_SLOTS", "MPLC_TPU_PARTNER_FAULT_PLAN",
    "MPLC_TPU_PARTNER_SHARDS", "MPLC_TPU_PIPELINE_BATCHES",
    # the raw-speed plane: precision changes the training/reconstruction
    # arithmetic itself (a bf16 number and an fp32 number are different
    # measurements — the sidecar's precision block carries the ledger
    # proof); the kernel knob swaps the reconstruction executable; the
    # planner knobs change WHICH estimator a method="auto" query runs
    "MPLC_TPU_PLANNER_ACCURACY", "MPLC_TPU_PLANNER_DEADLINE_SEC",
    "MPLC_TPU_PRECISION", "MPLC_TPU_RECON_KERNEL",
    "MPLC_TPU_RETRY_BACKOFF_SEC",
    # the router knobs reshape config 11's chaos workload: how many
    # redirects a job may spend, how long it backs off, when a sticky
    # pin breaks, which shard dies when, and whether the routed HTTP
    # surface is even served
    "MPLC_TPU_ROUTER_BACKOFF_SEC", "MPLC_TPU_ROUTER_BUDGET",
    "MPLC_TPU_ROUTER_FAULT_PLAN", "MPLC_TPU_ROUTER_REPIN_OVERLOADS",
    "MPLC_TPU_ROUTER_SERVE",
    "MPLC_TPU_SEED_ENSEMBLE",
    # the service knobs reshape the multi-tenant workload (injected
    # faults incl. chaos mode, slice granularity, admission bounds,
    # worker-pool concurrency, priority weighting, shed threshold)
    "MPLC_TPU_SERVICE_FAULT_PLAN", "MPLC_TPU_SERVICE_MAX_PENDING",
    "MPLC_TPU_SERVICE_PRIORITY_DEFAULT",
    # the retry floor reshapes every backoff the harness obeys (a higher
    # floor throttles the submission loop itself)
    "MPLC_TPU_SERVICE_RETRY_FLOOR_SEC",
    "MPLC_TPU_SERVICE_SHED_P99_SEC",
    "MPLC_TPU_SERVICE_SLICE", "MPLC_TPU_SERVICE_WORKERS",
    "MPLC_TPU_SLOT_MERGE", "MPLC_TPU_SLOT_POW2",
    "MPLC_TPU_STEP_WIDTH_MULT", "MPLC_TPU_SVARM_SAMPLES",
    "MPLC_TPU_SYNTH_SCALE")


def _replay_cached_tpu_result(repo_root: str | None = None) -> bool:
    """Tunnel down and this is a driver-shaped run (default workload for
    the selected config): prefer re-emitting a real TPU measurement of the
    SAME workload recorded earlier (scripts/r5_queue.sh runs the
    driver-shaped bench the moment the tunnel answers and saves the line
    to perf/r*/config<N>.json) over a reduced CPU-fallback number. The
    metric is suffixed `_cached` and the provenance (file, mtime) goes to
    stderr — this is a replayed measurement, never a fresh one. Returns
    True when a line was emitted."""
    config = os.environ.get("BENCH_CONFIG", "1")
    prefix = _REPLAY_SHAPES.get(config)
    if (prefix is None
            or os.environ.get("BENCH_EPOCHS", "8") != "8"
            or os.environ.get("BENCH_METRIC_SUFFIX")):
        return False
    if config == "1":
        # config 1 is the only config whose partner count / dataset are
        # env-shaped; they must sit at the driver defaults
        if (os.environ.get("BENCH_PARTNERS", "10") != "10"
                or os.environ.get("BENCH_DATASET", "mnist") != "mnist"):
            return False
    elif os.environ.get("BENCH_METHOD"):
        # configs 2-5: ANY set method refuses — even re-stating the
        # default would make the gate's strictness depend on string
        # comparison against per-config defaults duplicated here
        return False
    # any workload-shaping knob off its default makes the cached full-scale
    # measurement a DIFFERENT workload — same set _spawn_cpu_fallback strips
    # (MPLC_TPU_EVAL_CHUNK changes the compiled eval program and the
    # memory-derived batch cap, so it shapes the workload too; any SET
    # value refuses, so the pipelining opt-out "0" and merge opt-out "0"
    # also block replay of the default-workload number; the fault-tolerance
    # knobs reshape the run's schedule — injected faults, retry sleeps, cap
    # degradation — so a clean cached number must not stand in for them;
    # the partner-fault plan and seed ensemble reshape the GAME itself)
    for knob in _WORKLOAD_KNOBS:
        if os.environ.get(knob):
            return False
    # MPLC_TPU_SYNTH_NOISE is always set by the time this runs (main()
    # setdefaults the bench's own 0.75 before probing devices), so the
    # any-set rule above would always refuse; only a NON-default value
    # reshapes the synthetic data into a different workload
    if os.environ.get("MPLC_TPU_SYNTH_NOISE", "0.75") != "0.75":
        return False
    import glob
    repo = repo_root or os.path.dirname(os.path.abspath(__file__))
    best = None
    for path in glob.glob(os.path.join(repo, "perf", "r*",
                                       f"config{config}.json")):
        try:
            with open(path) as f:
                rec = json.loads(f.read().strip())
        except (OSError, ValueError):
            continue
        metric = rec.get("metric", "")
        if ("_cpu_fallback" in metric or "_cached" in metric
                or not metric.startswith(prefix)
                or not isinstance(rec.get("value"), (int, float))
                or "unit" not in rec):
            continue
        mtime = os.path.getmtime(path)
        if best is None or mtime > best[0]:
            best = (mtime, path, rec)
    if best is None:
        return False
    mtime, path, rec = best
    print(f"[bench] tunnel unreachable — replaying the TPU measurement from "
          f"{os.path.relpath(path, repo)} (file mtime "
          f"{time.strftime('%Y-%m-%d %H:%M UTC', time.gmtime(mtime))}; "
          f"approximate if the tree was re-checked-out); the metric is "
          f"suffixed _cached: it is NOT a fresh run",
          file=sys.stderr, flush=True)
    print(json.dumps({"metric": rec["metric"] + "_cached",
                      "value": rec["value"], "unit": rec["unit"],
                      "vs_baseline": rec.get("vs_baseline")}))
    # the telemetry sidecar makes the provenance machine-readable: this
    # number was REPLAYED, not measured by this process
    _write_telemetry({"source": "replayed_cache",
                      "replayed_from": os.path.relpath(path, repo),
                      "replayed_mtime": mtime,
                      "metric": rec["metric"] + "_cached",
                      "value": rec["value"]}, repo_root=repo)
    return True


def _fallback_exit() -> int:
    """Best available degraded result: cached TPU replay, else CPU child."""
    if _replay_cached_tpu_result():
        return 0
    return _spawn_cpu_fallback()


def _spawn_cpu_fallback() -> int:
    """The accelerator is unreachable. Rather than record nothing, re-exec
    a REDUCED benchmark on the host CPU — titanic, 3 partners, 2 epochs —
    with the metric explicitly suffixed `_cpu_fallback` so it can never be
    mistaken for a TPU number. Returns the child's exit code."""
    print("[bench] FALLBACK: re-running at reduced scale on the host CPU; "
          "the emitted metric is suffixed _cpu_fallback and is NOT a TPU "
          "measurement", file=sys.stderr, flush=True)
    import subprocess
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    # Accelerator-tuned knobs from the parent must not leak into the CPU
    # child, or fallback numbers vary with whatever TPU tuning was set —
    # and a tight accelerator stall/init timeout would re-arm the child's
    # watchdog, which is deliberately off on CPU.
    for knob in _WORKLOAD_KNOBS + (
            # the child's main() re-sets the canonical 0.75 — an
            # inherited custom noise would reshape the fallback number
            "MPLC_TPU_SYNTH_NOISE",
            "BENCH_STALL_TIMEOUT", "BENCH_INIT_TIMEOUT",
            # the child writes its own _cpu_fallback-suffixed sidecar;
            # inheriting an explicit path would race the parent's file
            # (and a device-profile dir makes no sense for the CPU
            # child either). Same rule for the live-telemetry sidecar
            # knobs: the child binding the parent's metrics port, or
            # writing flight/Chrome-trace files over the parent's, would
            # corrupt the telemetry of the process that spawned it
            "BENCH_TELEMETRY_FILE", "MPLC_TPU_TRACE_FILE",
            "MPLC_TPU_PROFILE_DIR", "MPLC_TPU_METRICS_PORT",
            "MPLC_TPU_METRICS_TOKEN",
            # the child writing the parent's value ledger would corrupt
            # the provenance artifact of the run that spawned it
            "MPLC_TPU_NUMERICS_LEDGER",
            "MPLC_TPU_FLIGHT_RECORDER_DIR",
            "MPLC_TPU_FLIGHT_RECORDER_SIZE",
            "MPLC_TPU_CHROME_TRACE_FILE",
            # the child is not a fleet shard: inheriting the parent's
            # fleet identity would stamp its trace records into the
            # parent run's merged timeline, and a peers list would make
            # the child scrape shards it has no business aggregating
            "MPLC_TPU_FLEET_RUN_ID",
            "MPLC_TPU_FLEET_COORD_TS",
            "MPLC_TPU_FLEET_PEERS"):
        env.pop(knob, None)
    env.update(
        # A clean PYTHONPATH drops the ambient accelerator registration,
        # so JAX_PLATFORMS=cpu is honored in the child. titanic: the only
        # family whose trainers compile in seconds on this one-core host
        # (the persistent CPU cache fails to reload AOT entries, so every
        # process pays its compiles in full).
        JAX_PLATFORMS="cpu", PYTHONPATH=repo,
        JAX_COMPILATION_CACHE_DIR=os.path.join(repo, ".jax_cache"),
        BENCH_IS_FALLBACK_CHILD="1", BENCH_METRIC_SUFFIX="_cpu_fallback",
        BENCH_CONFIG="1", BENCH_DATASET="titanic",
        BENCH_PARTNERS="3", BENCH_EPOCHS="2")
    return subprocess.run([sys.executable, os.path.abspath(__file__)],
                          env=env, cwd=repo).returncode


# Compile-cache provenance (main() fills it; _write_telemetry attaches it
# to every sidecar): a run whose entry count did not grow was served
# entirely from the persisted program bank. `warmup_skipped` (set by
# _warm_engine) records that the bank manifest proved every needed
# program was already persisted, so the compile-prime loop never ran.
_COMPILE_CACHE = {"dir": None, "entries_at_start": None,
                  "warmup_skipped": None}

REFERENCE_MNIST_FEDAVG_SECONDS = 589.0   # saved_experiments/.../results.csv mean
REFERENCE_CIFAR_FEDAVG_SECONDS = 3030.0  # 〃 (cifar10 fedavg random rows)
REFERENCE_EPOCH_BUDGET = 50


def _amounts(n_partners):
    """3 partners reproduces BASELINE config 1 ([0.4, 0.3, 0.3]); larger
    counts use a deliberately uneven (i+1)-proportional split so coalition
    values — and Shapley values — differ measurably between partners."""
    if n_partners == 3:
        a = [0.4, 0.3, 0.3]
    else:
        a = [float(i + 1) for i in range(n_partners)]
    return [x / sum(a) for x in a]


def _make_scenario(dataset_name, n_partners, epochs, dtype, corrupted=None,
                   seed=0):
    from mplc_tpu.scenario import Scenario

    sc = Scenario(partners_count=n_partners,
                  amounts_per_partner=_amounts(n_partners),
                  dataset_name=dataset_name,
                  multi_partner_learning_approach="fedavg",
                  aggregation_weighting="data-volume", epoch_count=epochs,
                  minibatch_count=10, gradient_updates_per_pass_count=8,
                  is_early_stopping=False, compute_dtype=dtype,
                  corrupted_datasets=corrupted,
                  experiment_path="/tmp/mplc_bench", is_dry_run=True,
                  seed=seed)
    sc.instantiate_scenario_partners()
    sc.split_data(is_logging_enabled=False)
    sc.compute_batch_sizes()
    sc.data_corruption()
    return sc


def _attach_progress(engine, label):
    """Per-device-batch stderr progress: a silent hour means a wedged
    tunnel, not a slow sweep — make the difference visible."""
    t0 = time.perf_counter()
    state = {"done": 0}

    def cb(done_now, remaining, slot_count):
        _beat()
        state["done"] += done_now
        print(f"[bench] {label}: +{done_now} coalitions "
              f"(slots={slot_count}, total {state['done']}, "
              f"{remaining} left in call) t={time.perf_counter() - t0:.0f}s",
              file=sys.stderr, flush=True)

    engine.progress = cb
    return engine


def _warm_engine(sc, shared_bank=False):
    """Compile every program the timed run will execute. The engine pads
    each evaluate() call to one bucket width per slot bucket
    (contrib/engine.py _run_batch / _slot_buckets), so warming with
    min(bucket count, n_dev*cap) distinct subsets per bucket — sizes
    grouped by engine._slot_width, overlap-halved cap mirrored — hits
    exactly the (width, slot-size) programs a full sweep uses. Adaptive MC
    methods can still trigger one smaller width on a late, short batch —
    that residual compile is accepted and visible, not hidden.

    `shared_bank` (the service bench): re-key the warm engine's program
    bank in SHARED (shape) scope before anything compiles, so one warm-up
    pass banks directly under the keys the SweepService's tenant engines
    acquire with — per-game keys would prime a bank the service never
    reads, paying every AOT compile twice."""
    from itertools import combinations, islice
    from math import comb

    from mplc_tpu.contrib.engine import CharacteristicEngine

    warm = _attach_progress(CharacteristicEngine(sc), "warm")
    if shared_bank and warm.program_bank is not None:
        from mplc_tpu.contrib.bank import ProgramBank
        warm.program_bank = ProgramBank(warm, shared=True)
    n = warm.partners_count
    # Program-bank warm-start: when the persistent bank manifest proves a
    # previous run already compiled EVERY (slots, width) program a full
    # sweep of this shape needs (into the persistent compile cache), the
    # compile-prime loop below is pure waste — the timed engine's bank
    # acquires serve straight from the persisted executables. The warm
    # engine is still returned for share_data_from (one HBM copy of the
    # data); `warmup_skipped` provenance lands in the telemetry sidecar's
    # compile_cache block.
    bank = warm.program_bank
    if bank is not None:
        from mplc_tpu.contrib.shapley import powerset_order
        plan = warm.sweep_plan(powerset_order(n))
        if plan and bank.holds_persistent(plan):
            print(f"[bench] warm-up: program bank already holds all "
                  f"{len(plan)} (slots, width) programs of this sweep "
                  "shape — loading them from the bank instead of running "
                  "the compile-prime training loop",
                  file=sys.stderr, flush=True)
            # acquire = deserialize from the persistent cache into the
            # process-global store, OUTSIDE the timed region — no
            # coalition actually trains (the old warm-up trained one
            # full-width batch per program). The timed engine's acquires
            # then hit the in-memory bank: compile row ~zero.
            for pipe, slot_count, width in plan:
                bank.acquire(pipe, slot_count, width)
            _COMPILE_CACHE["warmup_skipped"] = True
            return warm
    _COMPILE_CACHE["warmup_skipped"] = False
    n_dev = max(warm._sharding.num_devices if warm._sharding else 1, 1)
    # mirror _run_batch's effective cap: under the default batch overlap
    # the memory-derived cap is halved, and the warmed batch width must
    # equal the width the timed sweep will run
    ov_single = warm._pipeline_batches and warm.single_pipe.dispatches_async
    ov_multi = warm._pipeline_batches and warm.multi_pipe.dispatches_async

    n_singles = min(n, n_dev * warm._device_batch_cap(None, ov_single))
    print(f"[bench] warm-up: singles ({n_singles} coalitions, compiling "
          f"the single-partner pipeline)", file=sys.stderr, flush=True)
    warm.evaluate([(i,) for i in range(n_singles)])
    if warm._use_slots:
        # group sizes exactly as the sweep's _slot_buckets will (one merged
        # width can cover several sizes), so the warmed batch widths match
        # the timed run's — warming per raw size under merge mode would
        # compile narrower tail programs the sweep never executes
        by_width: dict[int, list[int]] = {}
        for k in range(2, n + 1):
            by_width.setdefault(warm._slot_width(k), []).append(k)
        for width, ks in sorted(by_width.items()):
            total = sum(comb(n, k) for k in ks)
            w = min(total, n_dev * warm._device_batch_cap(width, ov_multi))
            subsets = []
            for k in ks:
                subsets += list(islice(combinations(range(n), k),
                                       w - len(subsets)))
                if len(subsets) >= w:
                    break
            print(f"[bench] warm-up: sizes={ks} width={w} (compiling the "
                  f"{width}-slot pipeline)", file=sys.stderr, flush=True)
            warm.evaluate(subsets)
    else:
        w = min(2 ** n - 1 - n, n_dev * warm._device_batch_cap(None, ov_multi))
        multis = []
        for k in range(2, n + 1):
            multis += list(islice(combinations(range(n), k), w - len(multis)))
            if len(multis) >= w:
                break
        warm.evaluate(multis)
    return warm


def _fresh_engine(sc, warm):
    """Cold-cache engine sharing the warm engine's device arrays (ADVICE
    item: share_data_from halves bench HBM — one copy of the data)."""
    from mplc_tpu.contrib.engine import CharacteristicEngine
    sc._charac_engine = CharacteristicEngine(sc, share_data_from=warm)
    return sc._charac_engine


def _baseline_seconds(dataset_name, epochs, n_trainings):
    scale = float(os.environ.get("MPLC_TPU_SYNTH_SCALE", "1.0"))
    if dataset_name == "titanic":
        return 0.0  # no reference wall-clock exists (only an accuracy gate)
    per_training = (REFERENCE_CIFAR_FEDAVG_SECONDS
                    if dataset_name == "cifar10"
                    else REFERENCE_MNIST_FEDAVG_SECONDS)
    return per_training * (epochs / REFERENCE_EPOCH_BUDGET) * scale * n_trainings


def _fwd_flops_per_sample(engine):
    """Forward-pass FLOPs per sample from XLA's cost model (the trained
    model's inference program on one eval chunk, compiled once — cached by
    the persistent compilation cache); None when the backend doesn't
    expose cost analysis."""
    try:
        import jax
        model = engine.model
        dtype = engine.multi_pipe.trainer.cfg.dtype
        x = engine.val.x[0]
        f = jax.jit(lambda p, xx: model.apply(p, xx, train=False,
                                              compute_dtype=dtype))
        params = jax.eval_shape(model.init, jax.random.PRNGKey(0))
        c = f.lower(params, jax.ShapeDtypeStruct(x.shape, x.dtype)).compile()
        ca = c.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca["flops"]) / x.shape[0]
    except Exception as e:
        print(f"[bench] FLOPs estimate unavailable: {e}", file=sys.stderr)
        return None


def _peak_flops_per_chip():
    """bf16 peak of the attached chip (obs/devcost.py chip tables —
    Google Cloud TPU public spec pages); None = unknown kind."""
    import jax

    from mplc_tpu.obs import devcost
    kind = jax.devices()[0].device_kind.lower()
    peak = devcost.peak_flops_per_chip(kind)
    if peak is not None:
        return peak
    if kind == "cpu":
        # the CPU-fallback path, not a gap in the table: MFU is a TPU
        # metric and simply doesn't apply here
        print("[bench] host-CPU run: MFU not applicable", file=sys.stderr)
    else:
        print(f"[bench] unknown device_kind {kind!r}: no bf16-peak entry, "
              f"MFU line suppressed", file=sys.stderr)
    return None


def _compute_inputs(engine):
    """(fwd FLOPs/sample, fleet peak FLOPs, fleet HBM bytes/s) — the
    MFU-proxy and roofline inputs, probed ONCE per bench run and shared
    by the throughput note and the sweep report (the XLA cost-model
    lowering and the device-kind query are not free, and probing twice
    doubled their stderr notes). FLOPs prefer XLA's cost model, falling
    back to the analytic models/zoo estimate; peak/bandwidth are the
    whole attached fleet's (samples_trained aggregates across devices),
    None when the chip kind is unknown or host-CPU."""
    from mplc_tpu.obs import devcost
    flops = _fwd_flops_per_sample(engine)
    if flops is None:
        from mplc_tpu.models.zoo import fwd_flops_per_sample
        flops = fwd_flops_per_sample(engine.model.name)
    peak = _peak_flops_per_chip()
    return (flops, (peak * _ndev() if peak else None),
            devcost.fleet_hbm_bytes_per_s())


def _throughput_note(engine, elapsed, flops=None, fleet_peak=None):
    """Training throughput of the timed sweep: coalition-epochs/s, training
    samples/s, and a conservative model-FLOPs rate (fwd+bwd ~ 3x fwd; val /
    test evals and padded batch slots excluded — the true device rate is
    higher). The MFU estimate divides by the fleet's bf16 peak."""
    ep, sa = engine.epochs_trained, engine.samples_trained
    if not ep or elapsed <= 0:
        return
    line = (f"[bench] throughput: {ep} coalition-epochs "
            f"({ep / elapsed:.2f}/s), "
            f"{sa / elapsed / 1e3:.1f}k training samples/s")
    if flops:
        achieved = 3.0 * flops * sa / elapsed
        line += f", >={achieved / 1e12:.2f} TFLOP/s model compute"
        if fleet_peak:
            line += f" (>={100 * achieved / fleet_peak:.1f}% MFU)"
    print(line, file=sys.stderr, flush=True)


def _telemetry_path(repo_root: str | None = None) -> str | None:
    """Sidecar destination: BENCH_TELEMETRY_FILE wins (empty string
    disables); default is perf/telemetry_config<N><suffix>.json next to
    the driver's perf JSONs."""
    if "BENCH_TELEMETRY_FILE" in os.environ:
        return os.environ["BENCH_TELEMETRY_FILE"] or None
    repo = repo_root or os.path.dirname(os.path.abspath(__file__))
    cfg = os.environ.get("BENCH_CONFIG", "1")
    suffix = os.environ.get("BENCH_METRIC_SUFFIX", "")
    return os.path.join(repo, "perf", f"telemetry_config{cfg}{suffix}.json")


def _write_telemetry(payload: dict, repo_root: str | None = None) -> None:
    """Write the per-run telemetry sidecar (sweep report + provenance —
    `source` records whether the emitted number was fresh, replayed from
    cache, or a CPU fallback). Never fatal: telemetry must not take down a
    bench that measured successfully."""
    if _watchdog_fired.is_set():
        # same rule as _emit: once the watchdog declared the run dead, a
        # recovered main thread must not write a 'fresh' sidecar for it
        # (the fallback child owns the telemetry now)
        return
    try:
        path = _telemetry_path(repo_root)
        if path is None:
            return
        from mplc_tpu.obs.report import write_report
        payload = dict(payload)
        payload.setdefault("source",
                           "cpu_fallback"
                           if os.environ.get("BENCH_IS_FALLBACK_CHILD")
                           else "fresh")
        if _COMPILE_CACHE.get("dir"):
            from mplc_tpu.utils import compile_cache_entries
            before = _COMPILE_CACHE.get("entries_at_start")
            now = compile_cache_entries(_COMPILE_CACHE["dir"])
            payload.setdefault("compile_cache", {
                "dir": _COMPILE_CACHE["dir"],
                "entries_at_start": before,
                "entries_now": now,
                "new_entries": (now - before
                                if now is not None and before is not None
                                else None),
                # served-from-bank provenance: warm start means the prime
                # (an earlier run's warm-up) already held every program
                "warm_from_cache": bool(before) and now == before,
                # the bank-manifest proof that let _warm_engine skip its
                # compile-prime loop entirely (None = no bench warm-up
                # ran in this process, e.g. a replayed measurement)
                "warmup_skipped": _COMPILE_CACHE.get("warmup_skipped"),
            })
        if _NUMERICS_SIDECAR.get("block"):
            # the value-truth digest (obs/numerics.py ledger: engine
            # fingerprint + per-subset v(S) bits) — what the bench_diff
            # `numerics` gate compares across runs
            payload.setdefault("numerics", _NUMERICS_SIDECAR["block"])
        if _PRECISION_SIDECAR.get("block"):
            # the mixed-precision proof obligation: a non-fp32 run's
            # fp32-reference ledger diff (ulp histogram + tau-b) and
            # both wall-clocks — bench_diff's precision.tau_b row gates
            # on it
            payload.setdefault("precision", _PRECISION_SIDECAR["block"])
        write_report(path, payload)
        print(f"[bench] telemetry sidecar: {path}", file=sys.stderr,
              flush=True)
    except Exception as e:
        print(f"[bench] telemetry sidecar failed: {e}", file=sys.stderr,
              flush=True)


# the last measured engine's ledger digest, attached to the sidecar by
# _write_telemetry (None when MPLC_TPU_NUMERICS_LEDGER is unset)
_NUMERICS_SIDECAR: dict = {"block": None}

# the mixed-precision ledger-pair block (None on fp32 runs), attached to
# the sidecar by _write_telemetry — see _note_precision
_PRECISION_SIDECAR: dict = {"block": None}


def _note_numerics(engine) -> None:
    led = getattr(engine, "numerics_ledger", None)
    if led is None:
        return
    _NUMERICS_SIDECAR["block"] = {
        "engine_fingerprint": led.engine_fingerprint,
        "reduction_mode": led.meta.get("reduction_mode"),
        "topology": led.meta.get("topology"),
        "part_shards": led.meta.get("part_shards"),
        "entries": len(led.entries),
        "values": led.values_bits(),
    }


def _ledger_from_engine(engine):
    """The engine's value ledger, or an in-memory one built from its
    harvested v(S) table when MPLC_TPU_NUMERICS_LEDGER is unset — the
    precision pair must not depend on the ledger knob being on."""
    led = getattr(engine, "numerics_ledger", None)
    if led is not None and led.entries:
        return led
    import hashlib

    from mplc_tpu.obs import numerics as obs_num
    fp = hashlib.sha256(json.dumps(
        engine._fingerprint(), sort_keys=True).encode()).hexdigest()[:16]
    led = obs_num.ValueLedger(fp, meta={
        "precision": getattr(engine._multi_cfg, "precision", "fp32")})
    for s, v in engine.charac_fct_values.items():
        if s:  # the empty coalition's 0.0 carries no information
            led.record(s, float(v))
    return led


def _note_precision(timed, make_scenario):
    """The non-fp32 proof obligation (documented-deviation semantics,
    like STEP_WIDTH_MULT): a bf16/mixed bench run re-evaluates the SAME
    coalitions through an fp32 reference twin — sharing the timed
    engine's device data, compiles excluded via the span collector — and
    embeds the ledger diff (ulp histogram + Kendall tau-b) plus both
    wall-clocks in the sidecar. The speed number never ships without its
    numerics bill; bench_diff's precision.tau_b row gates on it."""
    from mplc_tpu import constants
    prec = getattr(timed._multi_cfg, "precision", "fp32")
    if prec == "fp32":
        return
    from mplc_tpu.contrib.engine import CharacteristicEngine
    from mplc_tpu.obs import numerics as obs_num
    from mplc_tpu.obs import trace as obs_trace
    from mplc_tpu.obs.report import sweep_report

    coalitions = sorted(s for s in timed.charac_fct_values if s)
    if not coalitions:
        return
    print(f"[bench] precision={prec}: running the fp32 reference twin "
          f"over the same {len(coalitions)} coalitions...",
          file=sys.stderr, flush=True)
    old = os.environ.get(constants.PRECISION_ENV)
    os.environ[constants.PRECISION_ENV] = "fp32"
    try:
        ref_sc = make_scenario()
        ref = _attach_progress(
            CharacteristicEngine(ref_sc, share_data_from=timed),
            "fp32-ref")
        with obs_trace.collect() as rtele:
            t0 = time.perf_counter()
            ref.evaluate(coalitions)
            ref_s = time.perf_counter() - t0
    finally:
        if old is None:
            os.environ.pop(constants.PRECISION_ENV, None)
        else:
            os.environ[constants.PRECISION_ENV] = old
    # the reference twin was never warmed: subtract its compile spans so
    # the recorded fp32 second is an executed-sweep second, comparable
    # to the warmed timed run
    ref_compile_s = sweep_report(rtele)["wallclock"]["compile_s"]
    ref_exec_s = max(ref_s - ref_compile_s, 0.0)
    diff = obs_num.diff_ledgers(_ledger_from_engine(timed),
                                _ledger_from_engine(ref))
    tau = diff.get("kendall_tau")
    _PRECISION_SIDECAR["block"] = {
        "mode": prec,
        "fp32_reference_s": ref_exec_s,
        "fp32_reference_compile_s": ref_compile_s,
        "tau_b": tau,
        "ulp": diff["ulp"],
        "histogram": diff["histogram"],
        "common": diff["common"],
        "drift": diff["drift"],
    }
    print("[bench] precision pair: tau_b="
          + (f"{tau:.3f}" if tau is not None else "n/a")
          + f"  max_ulp={diff['ulp']['max']}  fp32_ref={ref_exec_s:.1f}s"
          f" (+{ref_compile_s:.1f}s residual compile)",
          file=sys.stderr, flush=True)


def _degraded_run(rep: dict) -> bool:
    """True when the sweep recovered from faults rather than running
    clean — retries, OOM cap halvings, or CPU-degraded batches. Recorded
    top-level in the telemetry sidecar so BENCH_*.json says whether a
    number was earned on a degraded run without digging into the report."""
    r = rep.get("resilience") or {}
    return bool(r.get("retries") or r.get("cap_halvings")
                or r.get("cpu_batches"))


def _emit(metric, elapsed, baseline):
    if _watchdog_fired.is_set():
        # The stall watchdog already took over (its fallback child owns
        # stdout now); a recovered main thread must not add a second line.
        return
    print(json.dumps({
        "metric": metric + os.environ.get("BENCH_METRIC_SUFFIX", ""),
        "value": round(elapsed, 3),
        "unit": "s",
        # null, not 0.0, when no reference baseline exists (titanic):
        # 0.0 would read as "infinitely slower", null reads as N/A.
        "vs_baseline": round(baseline / elapsed, 3) if baseline else None,
    }))


def bench_exact_shapley(epochs, dtype):
    """Config 1 / north star: exact Shapley = all 2^N - 1 coalitions.
    BENCH_DATASET (default mnist) exists for the CPU-fallback path — the
    titanic logreg compiles in seconds where the CNNs cost ~40 min of XLA
    CPU compile on this one-core host."""
    from mplc_tpu.contrib.shapley import powerset_order, shapley_from_characteristic

    dataset = os.environ.get("BENCH_DATASET", "mnist")
    n_partners = int(os.environ.get("BENCH_PARTNERS", "10"))
    coalitions = powerset_order(n_partners)
    B = len(coalitions)

    sc = _make_scenario(dataset, n_partners, epochs, dtype)
    warm = _warm_engine(sc)
    print("[bench] compiled; timing...", file=sys.stderr)

    timed = _attach_progress(_fresh_engine(sc, warm), "timed")
    t0 = time.perf_counter()
    # a real device trace of the timed sweep when MPLC_TPU_PROFILE_DIR is
    # set (utils.profile_trace is a no-op otherwise); the span collector is
    # always on (in-memory, no device syncs) and feeds the sweep report —
    # any compile time it shows is a RESIDUAL compile the warm-up missed
    from mplc_tpu.obs import trace as obs_trace
    from mplc_tpu.utils import profile_trace
    with profile_trace(), obs_trace.collect() as tele:
        accs = timed.evaluate(coalitions)
        if timed.seed_ensemble > 1:
            # trust calibration rides the SAME sweep (replicas were extra
            # batch rows): emit the trust row inside the collected region
            # so the report + telemetry sidecar carry it
            from mplc_tpu.contrib.shapley import trust_summary
            trust = trust_summary(n_partners, timed.charac_fct_samples)
            obs_trace.event("contrib.trust", **trust)
            print(f"[bench] trust: K={trust['ensemble']} "
                  f"kendall_tau={trust['kendall_tau']:.3f}",
                  file=sys.stderr, flush=True)
    elapsed = time.perf_counter() - t0
    assert timed.first_charac_fct_calls_count == B

    values = {(): 0.0}
    for s, a in zip(coalitions, accs):
        values[s] = float(a)
    sv = shapley_from_characteristic(n_partners, values)
    print(f"[bench] coalition accs: min={accs.min():.4f} max={accs.max():.4f} "
          f"spread={accs.max() - accs.min():.4f}", file=sys.stderr)
    print(f"[bench] Shapley values: {np.round(sv, 4).tolist()}", file=sys.stderr)
    print(f"[bench] {elapsed:.1f} s for {B} coalitions = "
          f"{elapsed / B:.3f} s/coalition on {_ndev()} device(s); projected "
          f"v5e-8 (8-way coal sharding, zero-communication axis => ~linear): "
          f"{elapsed / 8:.1f} s", file=sys.stderr)
    flops, fleet_peak, fleet_hbm = _compute_inputs(timed)
    _throughput_note(timed, elapsed, flops, fleet_peak)
    metric = f"exact_shapley_{dataset}_{n_partners}partners_{epochs}epochs_wallclock"
    _note_numerics(timed)
    _note_precision(timed, lambda: _make_scenario(dataset, n_partners,
                                                  epochs, dtype))
    from mplc_tpu.obs.report import format_report, sweep_report
    rep = sweep_report(tele, flops_per_sample=flops, peak_flops=fleet_peak,
                       hbm_bytes_per_s=fleet_hbm)
    print(format_report(rep), file=sys.stderr, flush=True)
    _write_telemetry({"metric": metric, "wallclock_s": elapsed,
                      "devices": _ndev(), "degraded": _degraded_run(rep),
                      "report": rep})
    _emit(metric, elapsed, _baseline_seconds(dataset, epochs, B))


def bench_service(epochs, dtype):
    """Config 6: the multi-tenant sweep service. BENCH_TENANTS exact
    Shapley games of the same shape (distinct seeds) run through ONE
    SweepService with a journal, so the timed number covers scheduler
    overhead, per-value WAL fsyncs, and the cross-tenant program-packing
    win (the second tenant's buckets should be program-bank hits — the
    sidecar's service row says whether they were)."""
    from mplc_tpu.contrib.shapley import powerset_order
    from mplc_tpu.obs import trace as obs_trace
    from mplc_tpu.obs.report import format_report, sweep_report
    from mplc_tpu.service import SweepService

    dataset = os.environ.get("BENCH_DATASET", "mnist")
    n_partners = int(os.environ.get("BENCH_PARTNERS", "5"))
    tenants = int(os.environ.get("BENCH_TENANTS", "2"))
    B = len(powerset_order(n_partners))

    scenarios = [_make_scenario(dataset, n_partners, epochs, dtype,
                                seed=seed) for seed in range(tenants)]
    # prime the compiles OUTSIDE the timed region (same discipline as the
    # single-tenant configs): tenant 0's warm-up banks every program the
    # shape needs, and the service's shared-scope bank serves the rest
    # the warm engine banks under the SAME shared-scope keys the
    # service's tenant engines acquire with — one compile pass serves
    # every tenant of the shape
    warm = _warm_engine(scenarios[0], shared_bank=True)
    print("[bench] compiled; timing the service...", file=sys.stderr)

    journal = os.path.join("/tmp/mplc_bench", f"service_wal_{os.getpid()}.jsonl")
    t0 = time.perf_counter()
    with obs_trace.collect() as tele:
        svc = SweepService(journal_path=journal)
        jobs = [svc.submit(sc, tenant=f"tenant{i}")
                for i, sc in enumerate(scenarios)]
        for job in jobs:
            # consuming the stream doubles as watchdog liveness: every
            # harvested value is a beat
            for _ in job.stream(timeout=24 * 3600):
                _beat()
            job.result(timeout=60)
        svc.shutdown(drain=True)
    elapsed = time.perf_counter() - t0
    del warm

    rep = sweep_report(tele)
    svc_row = rep.get("service", {})
    print(f"[bench] service: {tenants} tenants x {B} coalitions in "
          f"{elapsed:.1f} s; packed_batches="
          f"{svc_row.get('cross_tenant_packed_batches')} "
          f"completed={svc_row.get('completed')}", file=sys.stderr)
    print(format_report(rep), file=sys.stderr, flush=True)
    metric = (f"service_{tenants}tenants_{dataset}_{n_partners}partners_"
              f"{epochs}epochs_wallclock")
    _write_telemetry({"metric": metric, "wallclock_s": elapsed,
                      "devices": _ndev(), "degraded": _degraded_run(rep),
                      "report": rep})
    _emit(metric, elapsed,
          _baseline_seconds(dataset, epochs, tenants * B))


def bench_load(epochs, dtype):
    """Config 7: the service load/chaos harness (scripts/load_gen.py).
    The timed quantity is the whole load run — submission with
    retry_after backoff, scheduling across priority tiers, chaos
    recovery, drain — and the headline artifacts are the sidecar's
    saturation/per-tier-latency/invariant blocks rather than the bare
    wall-clock (dtype is irrelevant: the games are 1-epoch titanic
    logregs; the service plumbing is what saturates)."""
    import importlib

    scripts_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "scripts")
    if scripts_dir not in sys.path:
        sys.path.insert(0, scripts_dir)
    load_gen = importlib.import_module("load_gen")

    jobs = int(os.environ.get("BENCH_JOBS", "1000"))
    load_epochs = int(os.environ.get("BENCH_LOAD_EPOCHS", "1"))
    chaos_plan = None
    if not os.environ.get("MPLC_TPU_SERVICE_FAULT_PLAN"):
        chaos_plan = "chaos@rate0.05:seed7"
    print(f"[bench] load harness: {jobs} jobs, chaos="
          f"{chaos_plan or os.environ.get('MPLC_TPU_SERVICE_FAULT_PLAN')}",
          file=sys.stderr, flush=True)
    report = load_gen.run_load(jobs=jobs, epochs=load_epochs,
                               chaos_plan=chaos_plan, beat=_beat)
    elapsed = report["wallclock_s"]
    inv = report["invariant"]
    sat = report["saturation"]
    print(f"[bench] load: {inv['accepted']} accepted in {elapsed:.1f} s "
          f"({sat['completed_jobs_per_s']:.2f} jobs/s, "
          f"{sat['completed_coalitions_per_s']:.1f} coalitions/s) "
          f"outcomes={report['outcomes']} invariant_holds={inv['holds']}",
          file=sys.stderr, flush=True)
    if not inv["holds"]:
        print(f"[bench] INVARIANT VIOLATION: stuck={inv['stuck_jobs']} "
              f"mismatched={inv['mismatched_jobs']}",
              file=sys.stderr, flush=True)
    metric = f"service_load_{jobs}jobs_wallclock"
    _write_telemetry({"metric": metric, "wallclock_s": elapsed,
                      "devices": _ndev(),
                      "invariant_holds": inv["holds"],
                      "load_report": report})
    _emit(metric, elapsed, 0.0)


def bench_router(epochs, dtype):
    """Config 11: the fleet-router chaos bench (module docstring). The
    timed quantity is the whole routed run — submission through the
    router's pick/redirect/backoff core, inline shard scheduling, the
    mid-run shard kill, journal-replay failover, drain — and the
    headline artifacts are the sidecar's router block (routing totals +
    latency quantiles) and the equality-checked router invariant
    (dtype is irrelevant: 1-epoch titanic logregs; the routing and
    failover machinery is what's measured)."""
    import importlib

    from mplc_tpu import faults
    from mplc_tpu.contrib.shapley import powerset_order
    from mplc_tpu.obs import trace as obs_trace
    from mplc_tpu.obs.report import sweep_report
    from mplc_tpu.service import FleetRouter, RoutedJobFailed, SweepService

    scripts_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "scripts")
    if scripts_dir not in sys.path:
        sys.path.insert(0, scripts_dir)
    load_gen = importlib.import_module("load_gen")

    jobs = int(os.environ.get("BENCH_ROUTER_JOBS", "8"))
    shards = int(os.environ.get("BENCH_ROUTER_SHARDS", "2"))
    plan = (os.environ.get(faults.ROUTER_FAULT_PLAN_ENV)
            or "shardkill@shard0:sec2")
    print(f"[bench] router: {jobs} jobs over {shards} inline shards, "
          f"plan={plan}", file=sys.stderr, flush=True)

    games = [(p, s) for p in (2, 3) for s in (0, 1)]
    services = {f"s{i}": SweepService(start=False, slice_coalitions=2)
                for i in range(shards)}
    router = FleetRouter(shards=services, fault_plan=plan,
                         backoff_sec=0.01)
    handles = []
    failed_routes = 0
    t0 = time.perf_counter()
    with obs_trace.collect() as recs:
        for i in range(jobs):
            p, s = games[i % len(games)]
            spec = {"partners": p, "seed": s, "epochs": 1,
                    "dataset": "titanic"}
            sc = load_gen.scenario_from_spec(spec)
            _beat()
            try:
                handles.append(
                    (router.submit(sc, tenant=f"tier{i % 3}", spec=spec),
                     p, s))
            except RoutedJobFailed:
                failed_routes += 1
        while router.pump():
            _beat()
            if time.perf_counter() - t0 > 3000:
                raise TimeoutError("router bench did not drain")
    elapsed = time.perf_counter() - t0
    router.close()
    for svc in services.values():
        svc.shutdown(drain=False)

    refs = {}
    outcomes, mismatched, stuck = {}, [], []
    for h, p, s in handles:
        outcomes[h.status] = outcomes.get(h.status, 0) + 1
        if not h.done:
            stuck.append(h.job_id)
            continue
        if h.status == "completed":
            if (p, s) not in refs:
                refs[(p, s)] = load_gen.solo_reference(
                    lambda p=p, s=s: load_gen.scenario_from_spec(
                        {"partners": p, "seed": s, "epochs": 1,
                         "dataset": "titanic"}))
                _beat()
            vals = h.values() or {}
            want = refs[(p, s)]
            if [vals.get(sub) for sub in powerset_order(p)] != \
                    [want[sub] for sub in powerset_order(p)]:
                mismatched.append(h.job_id)
    planned = len(faults.parse_router_fault_plan(plan))
    invariant_holds = (not stuck and not mismatched
                       and not failed_routes
                       and (router.stats["failovers"] >= 1
                            if planned else True))
    rep = sweep_report(recs)

    # the bit-identity digest: ONE fixed game's routed v(S) bits (the
    # 3-partner seed-0 game, present in every run) — the router
    # invariant says these bits never depend on which shard died, so CI
    # diffing them against the committed baseline turns any failover
    # value drift into a same-fingerprint numerics-gate failure
    import hashlib

    from mplc_tpu.obs import numerics as obs_num
    digest_spec = {"partners": 3, "seed": 0, "epochs": 1,
                   "dataset": "titanic"}
    rep_handle = next((h for h, p, s in handles
                       if (p, s) == (3, 0) and h.status == "completed"),
                      None)
    if rep_handle is not None:
        fp = hashlib.sha256(json.dumps(
            digest_spec, sort_keys=True).encode()).hexdigest()[:16]
        led = obs_num.ValueLedger(fp, meta={"precision": "fp32"})
        for s, v in (rep_handle.values() or {}).items():
            if s:
                led.record(s, float(v), source="routed")
        _NUMERICS_SIDECAR["block"] = {
            "engine_fingerprint": led.engine_fingerprint,
            "reduction_mode": "routed",
            "topology": None,
            "part_shards": None,
            "entries": len(led.entries),
            "values": led.values_bits(),
        }
    print(f"[bench] router: {len(handles)} routed in {elapsed:.1f} s "
          f"outcomes={outcomes} stats={router.stats} "
          f"invariant_holds={invariant_holds}",
          file=sys.stderr, flush=True)
    if not invariant_holds:
        print(f"[bench] INVARIANT VIOLATION: stuck={stuck} "
              f"mismatched={mismatched} failed_routes={failed_routes}",
              file=sys.stderr, flush=True)
    metric = f"router_{jobs}jobs_{shards}shards_wallclock"
    _write_telemetry({"metric": metric, "wallclock_s": elapsed,
                      "devices": _ndev(),
                      "invariant_holds": invariant_holds,
                      "router": {**router.stats,
                                 "jobs": len(handles),
                                 "shards": shards,
                                 "fault_plan": plan,
                                 "outcomes": outcomes,
                                 "route_s": (rep.get("router") or {}).get(
                                     "route_s"),
                                 "report_row": rep.get("router")}})
    _emit(metric, elapsed, 0.0)


def bench_live(epochs, dtype):
    """Config 8: the live contributivity tier. One grand-coalition
    recording seeds a RESIDENT LiveGame; its recorded rounds are then
    re-appended (cycled) as live aggregation rounds, and at every
    doubling of the resident history a FRESH query (the append
    invalidated the round-stamp, so reconstruction replays the whole
    stack) and a WARM re-query (memo + banked programs, zero device
    work) are timed. The sidecar's live block is the headline artifact:
    query latency vs resident rounds, memo-hit latency, evaluation and
    pruning counts. The emitted metric is the final fresh-query latency
    at max residency."""
    from mplc_tpu.live import LiveGame
    from mplc_tpu.obs import trace as obs_trace
    from mplc_tpu.obs.report import format_report, sweep_report

    dataset = os.environ.get("BENCH_DATASET", "mnist")
    n_partners = int(os.environ.get("BENCH_PARTNERS", "10"))
    # truncation off: every permutation prefix reconstructs, so the
    # fresh-query latency honestly scales with the resident history
    method_kw = dict(sv_accuracy=1.0, min_iter=16, perm_batch=8,
                     truncation=0.0)

    sc = _make_scenario(dataset, n_partners, epochs, dtype)
    print("[bench] recording the grand coalition for the live game...",
          file=sys.stderr, flush=True)
    with obs_trace.collect() as tele:
        t_all = time.perf_counter()
        game = LiveGame.from_recording(sc)
        base = game.round_history()
        # default residency target: 4x the recording (BENCH_LIVE_ROUNDS
        # overrides) — the recording length is epochs x minibatches, so
        # a fixed default would sit below the starting residency
        max_rounds = (int(os.environ.get("BENCH_LIVE_ROUNDS", "0"))
                      or 4 * game.rounds_resident)
        _beat()
        points = []
        i = 0
        last_fresh = None
        while True:
            t0 = time.perf_counter()
            r = game.query("GTG-Shapley", **method_kw)
            fresh_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            game.query("GTG-Shapley", **method_kw)  # warm: memoized
            warm_s = time.perf_counter() - t0
            last_fresh = fresh_s
            points.append({"rounds": game.rounds_resident,
                           "fresh_query_s": fresh_s,
                           "warm_query_s": warm_s,
                           "evaluations": r.evaluations})
            print(f"[bench] live: rounds={game.rounds_resident} "
                  f"fresh={fresh_s:.3f}s warm={warm_s * 1e3:.2f}ms "
                  f"evals={r.evaluations}", file=sys.stderr, flush=True)
            _beat()
            if game.rounds_resident >= max_rounds:
                break
            # double the resident history by cycling the recorded rounds
            target = min(max_rounds, 2 * game.rounds_resident)
            while game.rounds_resident < target:
                deltas, weights = base[i % len(base)]
                game.append_round(deltas, weights)
                i += 1
        elapsed = time.perf_counter() - t_all
    rep = sweep_report(tele)
    print(format_report(rep), file=sys.stderr, flush=True)
    metric = (f"live_query_{dataset}_{n_partners}partners_"
              f"{max_rounds}rounds_latency")
    # reconstruction-executable provenance + the kernel's headline
    # number: which path answered (fused Pallas kernel / interpreter /
    # scan reference) and the final fresh-query latency it delivered —
    # bench_diff's recon.kernel_query_s row compares THIS figure, so the
    # path that earned it rides next to it
    from mplc_tpu import constants as _const
    use_kernel, interpret = game._evaluator().kernel_plan()
    recon_block = {
        "kernel_mode": _const.recon_kernel_mode(),
        "use_kernel": bool(use_kernel),
        "interpret": bool(interpret),
        "precision": getattr(game.engine._multi_cfg, "precision", "fp32"),
        "kernel_query_s": last_fresh,
    }
    print(f"[bench] recon executable: "
          + ("pallas-kernel" if use_kernel and not interpret
             else "pallas-interpret" if use_kernel else "scan")
          + f" fresh_query={last_fresh:.3f}s", file=sys.stderr, flush=True)
    _write_telemetry({"metric": metric, "wallclock_s": elapsed,
                      "devices": _ndev(), "degraded": _degraded_run(rep),
                      "latency_vs_rounds": points, "recon": recon_block,
                      "report": rep})
    _emit(metric, last_fresh, 0.0)


def bench_residency(epochs, dtype):
    """Config 10: the bounded-residency live tier (live/residency.py).
    ONE recorded scenario seeds BENCH_LIVE_GAMES journal-backed live
    games (default 1000) sharing a single engine, under a
    BENCH_LIVE_RESIDENT residency cap (default 128). Game-count pressure
    doubles from 125 up to the total; at every point a spread sample of
    games is evicted and re-queried — the FRESH query pays admission +
    WAL replay + full reconstruction, the WARM re-query hits the memo —
    and nearest-rank p50/p99 of both are recorded per point. The
    sidecar's live block carries the headline `p99_fresh_query_s` and
    `restore_s` rows bench_diff gates on, plus the residency manager's
    eviction/restore totals; its numerics block is one representative
    game's POST-RESTORE exact v(S) bits, so the committed baseline pair
    proves evict -> restore -> query bit-identity in CI. The emitted
    metric is p99 fresh-query seconds at max pressure."""
    import hashlib
    import shutil
    import tempfile

    from mplc_tpu.live import LiveGame, residency
    from mplc_tpu.obs import numerics as obs_num
    from mplc_tpu.obs import trace as obs_trace
    from mplc_tpu.obs.report import format_report, sweep_report

    # titanic default: residency churn is the subject here, not model
    # cost — the logreg records in seconds on any backend
    dataset = os.environ.get("BENCH_DATASET", "titanic")
    n_partners = int(os.environ.get("BENCH_PARTNERS", "5"))
    total_games = max(2, int(os.environ.get("BENCH_LIVE_GAMES", "1000")))
    cap = int(os.environ.get("BENCH_LIVE_RESIDENT", "128"))
    rounds_per_game = int(os.environ.get("BENCH_LIVE_ROUNDS", "6"))
    sample_n = int(os.environ.get("BENCH_LIVE_SAMPLE", "32"))

    def _pctl_nr(xs, q):
        s = sorted(xs)
        return s[min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))]

    sc = _make_scenario(dataset, n_partners, epochs, dtype)
    work = tempfile.mkdtemp(prefix="mplc_residency_")
    residency.reset()
    residency.configure(cap)
    games = []
    try:
        print(f"[bench] residency: recording the shared scenario "
              f"({dataset}, {n_partners} partners)...",
              file=sys.stderr, flush=True)
        with obs_trace.collect() as tele:
            t_all = time.perf_counter()
            seed = LiveGame.from_recording(
                sc, tenant="seed", journal_path=os.path.join(work, "seed.wal"))
            engine = seed.engine
            base = seed.round_history()[:rounds_per_game]
            seed.close()
            _beat()

            # pressure ladder: 125 -> 250 -> 500 -> ... -> total_games
            pressures, p = [], min(125, total_games)
            while p < total_games:
                pressures.append(p)
                p *= 2
            pressures.append(total_games)

            points = []
            for pressure in pressures:
                while len(games) < pressure:
                    i = len(games)
                    g = LiveGame(sc, tenant=f"t{i:04d}", engine=engine,
                                 journal_path=os.path.join(work, f"t{i}.wal"))
                    for deltas, weights in base:
                        g.append_round(deltas, weights)
                    games.append(g)
                    if i % 50 == 0:
                        _beat()
                # spread sample across the whole tenancy (coldest included)
                idx = sorted({round(j * (pressure - 1) / max(1, sample_n - 1))
                              for j in range(min(sample_n, pressure))})
                fresh, warm = [], []
                for gi in idx:
                    g = games[gi]
                    g.evict()
                    t0 = time.perf_counter()
                    g.query("exact")
                    fresh.append(time.perf_counter() - t0)
                    t0 = time.perf_counter()
                    g.query("exact")  # warm: memoized
                    warm.append(time.perf_counter() - t0)
                st = residency.stats()
                point = {"games": pressure, "sampled": len(idx),
                         "p50_fresh_query_s": _pctl_nr(fresh, 0.50),
                         "p99_fresh_query_s": _pctl_nr(fresh, 0.99),
                         "p50_warm_query_s": _pctl_nr(warm, 0.50),
                         "p99_warm_query_s": _pctl_nr(warm, 0.99),
                         "resident": st["resident"],
                         "evicted": st["evicted"]}
                points.append(point)
                print(f"[bench] residency: games={pressure} "
                      f"resident={st['resident']}/{cap} "
                      f"fresh p50={point['p50_fresh_query_s'] * 1e3:.1f}ms "
                      f"p99={point['p99_fresh_query_s'] * 1e3:.1f}ms "
                      f"warm p99={point['p99_warm_query_s'] * 1e6:.0f}us",
                      file=sys.stderr, flush=True)
                _beat()

            # the bit-identity digest: one representative game's
            # post-restore exact v(S) — CI diffs these bits against the
            # committed baseline, so a restore that drifts fails the gate
            rep_game = games[-1]
            rep_game.evict()
            rep_game.query("exact")
            fp = hashlib.sha256(json.dumps(
                engine._fingerprint(),
                sort_keys=True).encode()).hexdigest()[:16]
            led = obs_num.ValueLedger(fp, meta={
                "precision": getattr(engine._multi_cfg, "precision", "fp32")})
            for s, v in rep_game._recon.values.items():
                if s:
                    led.record(s, float(v), source="live_restore")
            _NUMERICS_SIDECAR["block"] = {
                "engine_fingerprint": led.engine_fingerprint,
                "reduction_mode": "live_restore",
                "topology": None,
                "part_shards": None,
                "entries": len(led.entries),
                "values": led.values_bits(),
            }
            elapsed = time.perf_counter() - t_all
        rep = sweep_report(tele)
        print(format_report(rep), file=sys.stderr, flush=True)
        stats = residency.stats()
        top = points[-1]
        live_block = {
            "max_resident": cap,
            "total_games": total_games,
            "rounds_per_game": rounds_per_game,
            "p99_fresh_query_s": top["p99_fresh_query_s"],
            "p99_warm_query_s": top["p99_warm_query_s"],
            # the p50 WAL-restore second (the manager's retry_after_sec
            # basis) — bench_diff's live.restore_s row compares this
            "restore_s": residency.retry_after_sec(),
            "evictions": stats["evictions"],
            "restores": stats["restores"],
            "points": points,
        }
        metric = (f"live_residency_{dataset}_{total_games}games_"
                  f"cap{cap}_p99_fresh")
        print(f"[bench] residency: evictions={stats['evictions']} "
              f"restores={stats['restores']} "
              f"restore p50={live_block['restore_s'] * 1e3:.1f}ms",
              file=sys.stderr, flush=True)
        _write_telemetry({"metric": metric, "wallclock_s": elapsed,
                          "devices": _ndev(), "degraded": _degraded_run(rep),
                          "live": live_block, "report": rep})
        _emit(metric, top["p99_fresh_query_s"], 0.0)
    finally:
        for g in games:
            try:
                g.close()
            except Exception:
                pass
        residency.reset()
        shutil.rmtree(work, ignore_errors=True)


def bench_fleet(epochs, dtype):
    """Config 9: the fleet sweep plane — coalition-axis sharding across
    OS processes, with a MEASURED wall-clock-vs-shards curve (the number
    scripts/project_v5e8.py marks its pinned projection superseded by).

    Protocol: one compile-prime worker runs first (a single shard's
    slice — it banks every program of the sweep shape into the shared
    persistent cache + manifest), then each BENCH_FLEET_DEVICES point
    runs the whole sweep as W single-device worker processes over
    disjoint bucket-granular slices (concurrently with >= W cores,
    sequentially otherwise — recorded in the sidecar). Each point's
    number is the MAX per-shard SWEEP wall-clock: every shard's slice is
    genuinely executed and timed, the zero-communication coalition axis
    means shards never interact, and per-shard startup (scenario/data/
    engine build, paid once per resident worker) is recorded separately
    — the same timing-excludes-warm-up discipline every other config
    uses. A deterministic-reduce equality pass then proves the
    multi-shard merge bit-identical to the 1-shard run (diff_ledgers:
    zero ulp, tau-b 1.0) and feeds the sidecar numerics block."""
    import dataclasses as _dc
    import tempfile

    import jax

    from mplc_tpu import constants as mconstants
    from mplc_tpu.parallel import fleet

    platform = jax.devices()[0].platform
    cpu = platform == "cpu"
    dataset = os.environ.get("BENCH_DATASET",
                             "titanic" if cpu else "mnist")
    n_partners = int(os.environ.get("BENCH_PARTNERS", "10"))
    points = sorted({int(x) for x in os.environ.get(
        "BENCH_FLEET_DEVICES", "1,2,4,8").split(",") if x.strip()})
    if not cpu:
        # real accelerator: subprocess workers cannot re-initialize the
        # device grant this process already holds (the tunneled TPU is
        # exclusive), so everything — the measured point AND the
        # equality pass — runs IN-PROCESS: one sweep over the whole
        # attached fleet, equality shards executed sequentially in this
        # interpreter. A true multi-host fleet run launches one
        # `--worker` per host instead. Never mislabel W synthetic
        # points as device scaling.
        points = [len(jax.devices())]
    inproc = not cpu
    eq_shards = min(mconstants._env_positive_int(
        mconstants.FLEET_SHARDS_ENV, 0) or 4, max(points), 4)
    work = tempfile.mkdtemp(prefix="mplc_fleet_bench_")
    cores = os.cpu_count() or 1

    spec = fleet.FleetSpec(
        dataset=dataset, partners=n_partners, epochs=epochs, dtype=dtype,
        minibatch_count=10, gradient_updates_per_pass=8, seed=0,
        deterministic=False, pin_widths=True)

    # worker environment: inherit the workload knobs, share the compile
    # cache (the manifest IS the cross-shard no-recompile mechanism),
    # strip the parent's telemetry outputs (a worker appending to the
    # parent's trace/ledger/metrics port would corrupt them)
    env = dict(os.environ)
    for knob in ("MPLC_TPU_TRACE_FILE", "MPLC_TPU_METRICS_PORT",
                 "MPLC_TPU_CHROME_TRACE_FILE", "MPLC_TPU_PROFILE_DIR",
                 "MPLC_TPU_NUMERICS_LEDGER", "BENCH_TELEMETRY_FILE"):
        env.pop(knob, None)
    if _COMPILE_CACHE.get("dir"):
        env["MPLC_TPU_COMPILE_CACHE_DIR"] = _COMPILE_CACHE["dir"]
    dev_per_shard = 1 if cpu else None

    # compile prime: ONE worker over the LAST slice of the largest shard
    # count — the last slice is the only one guaranteed to touch every
    # bucket (a bucket of n jobs gives shard i the [i*n//W, (i+1)*n//W)
    # run, empty for small n except at i = W-1), so this single worker
    # banks every (slot, width) program of the sweep shape and every
    # point's workers then deserialize from the shared manifest instead
    # of compiling (all points run the same single-device programs; the
    # device axis here IS the shard count)
    W_max = max(points)
    if not inproc:
        print(f"[bench] fleet: priming the shared program bank "
              f"(1 worker, slice {W_max}/{W_max})",
              file=sys.stderr, flush=True)
        t0 = time.perf_counter()
        # a measurement without primed programs would time per-shard
        # COMPILES, not sweep scaling — run_worker_subprocess raises on
        # failure, so an unprimed fleet is never silently measured
        fleet.run_worker_subprocess(
            spec, W_max - 1, W_max, os.path.join(work, "prime"),
            devices=dev_per_shard, env=env, ledger=False, timeout=3600.0)
        _beat()
        print(f"[bench] fleet: prime worker finished in "
              f"{time.perf_counter() - t0:.1f} s",
              file=sys.stderr, flush=True)
    # in-process mode (real accelerator): run_shard pre-acquires every
    # banked program outside its timed sweep, so no separate prime is
    # needed — the first point's warmup_s carries the compiles

    curve = []
    base_wall = None
    for nd in points:
        W = nd if cpu else 1
        concurrent = cores >= W
        out = os.path.join(work, f"point{nd}dev")
        res = fleet.run_fleet(spec, W, out, devices_per_shard=dev_per_shard,
                              env=env, ledger=False, concurrent=concurrent,
                              inproc=inproc, timeout=7200.0)
        _beat()
        # the scaling number is the fleet's critical path under the
        # bench's timing-excludes-warm-up discipline: the max per-shard
        # SWEEP wall-clock (shard startup — scenario/data/engine build,
        # paid once per resident worker — is recorded per shard as
        # setup_s and in per_shard_wall_s, never hidden, never counted
        # into the scaling claim)
        fleet_wall = max(res.per_shard_sweep_s)
        if nd == points[0] and nd == 1:
            base_wall = fleet_wall
        # fleet-health shape of the point, beyond the scaling number:
        # straggler ratio (max/median shard sweep — 1.0 is a perfectly
        # balanced fleet), raw spread, and shard-count-normalized
        # throughput (coalitions per shard-second — the number that
        # should hold flat as W grows if sharding is efficient)
        sweeps = sorted(res.per_shard_sweep_s)
        mid = (sweeps[len(sweeps) // 2] if len(sweeps) % 2 else
               (sweeps[len(sweeps) // 2 - 1] + sweeps[len(sweeps) // 2]) / 2)
        straggler = (sweeps[-1] / mid) if mid > 0 else None
        coal_per_shard_s = (len(res.values) / (W * fleet_wall)
                            if fleet_wall > 0 else None)
        point = {
            "devices": nd, "shards": W,
            "devices_per_shard": dev_per_shard or "all",
            "fleet_wallclock_s": fleet_wall,
            "coordinator_wallclock_s": res.wallclock_s,
            "per_shard_wall_s": res.per_shard_wall_s,
            "per_shard_sweep_s": res.per_shard_sweep_s,
            "per_shard_setup_s": [
                r.get("setup_s") for r in res.shard_reports],
            "concurrent": concurrent,
            "straggler_ratio": straggler,
            "sweep_s_spread": sweeps[-1] - sweeps[0],
            "coalitions_per_shard_s": coal_per_shard_s,
            "speedup_vs_1": (base_wall / fleet_wall
                             if base_wall else None),
            "coalitions": len(res.values),
            "programs_planned": max(
                (r.get("programs_planned") or 0
                 for r in res.shard_reports), default=0),
            "manifest_hits_total": sum(
                r.get("manifest_hits") or 0 for r in res.shard_reports),
            "compile_cache_new_entries": sum(
                r.get("compile_cache_new_entries") or 0
                for r in res.shard_reports),
        }
        curve.append(point)
        print(f"[bench] fleet point: devices={nd} shards={W} "
              f"sweep={fleet_wall:.1f}s (max shard incl. setup "
              f"{max(res.per_shard_wall_s):.1f}s, coordinator "
              f"{res.wallclock_s:.1f}s"
              f"{', sequential' if not concurrent else ''}) "
              f"speedup_vs_1={point['speedup_vs_1'] or float('nan'):.2f}x "
              f"manifest_hits={point['manifest_hits_total']}/"
              f"{point['programs_planned'] * W}",
              file=sys.stderr, flush=True)

    # equality pass: deterministic reduce, 1 shard vs eq_shards shards,
    # value ledgers diffed — run_fleet RAISES on any drift
    eq_spec = _dc.replace(spec, epochs=min(epochs, 2), minibatch_count=2,
                          gradient_updates_per_pass=2, deterministic=True)
    print(f"[bench] fleet: equality pass (deterministic reduce, 1 vs "
          f"{eq_shards} shards)", file=sys.stderr, flush=True)
    ref = fleet.run_fleet(eq_spec, 1, os.path.join(work, "eq1"),
                          devices_per_shard=dev_per_shard, env=env,
                          concurrent=cores > 1, inproc=inproc,
                          timeout=3600.0)
    _beat()
    got = fleet.run_fleet(eq_spec, eq_shards, os.path.join(work, "eqW"),
                          devices_per_shard=dev_per_shard, env=env,
                          concurrent=cores >= eq_shards, inproc=inproc,
                          timeout=3600.0, verify_against=ref.ledger)
    _beat()
    diff = dict(got.diff or {})
    equality = {"shards": eq_shards, "comparable": diff.get("comparable"),
                "drift": diff.get("drift"), "ulp": diff.get("ulp"),
                "kendall_tau": diff.get("kendall_tau"),
                "common_subsets": diff.get("common")}
    print(f"[bench] fleet equality: {eq_shards}-shard merged ledger vs "
          f"1-shard — drift={equality['drift']} "
          f"max_ulp={(equality['ulp'] or {}).get('max')} "
          f"tau={equality['kendall_tau']}", file=sys.stderr, flush=True)
    # the det merged ledger is the sidecar's value-truth digest: the
    # bench_diff numerics gate compares these bits across runs
    led = got.ledger or {}
    _NUMERICS_SIDECAR["block"] = {
        "engine_fingerprint": led.get("engine_fingerprint"),
        "reduction_mode": (led.get("meta") or {}).get("reduction_mode"),
        "topology": (led.get("meta") or {}).get("topology"),
        "part_shards": (led.get("meta") or {}).get("part_shards"),
        "entries": len(led.get("entries") or {}),
        "values": {k: e["value_bits"]
                   for k, e in (led.get("entries") or {}).items()},
    }

    top = curve[-1]
    provenance = "cpu_mesh" if cpu else platform
    basis = "max_shard_sweep_wallclock"
    metric = (f"fleet_sweep_{dataset}_{n_partners}partners_{epochs}epochs_"
              f"{top['devices']}dev_wallclock"
              + ("_cpumesh" if cpu else ""))
    B = len(fleet.FleetSpec(partners=n_partners).all_subsets()) \
        if dataset != "titanic" else 0
    fleet_block = {
        "provenance": provenance,
        "host_cores": cores,
        "scaling_basis": basis,
        "basis_note": (
            "each point's number is the MAX per-shard sweep wall-clock: "
            "every shard's slice is genuinely executed and timed, shards "
            "share nothing mid-sweep (zero-communication coalition "
            "axis), and shard startup (scenario/data/engine build — "
            "paid once per resident worker) is recorded per shard as "
            "setup_s/per_shard_wall_s but excluded from the scaling "
            "number, mirroring every other config's timing-excludes-"
            "warm-up discipline"
            + ("; workers ran SEQUENTIALLY (host has fewer cores than "
               "shards) — on one-host-per-shard hardware the max IS the "
               "fleet wall-clock" if not top["concurrent"] else
               "; workers ran concurrently (coordinator wall-clock "
               "recorded beside it)")),
        "points": curve,
        "equality": equality,
        # headline fleet-health rows (top point + equality tau) for the
        # bench_diff gate: regressions in shard balance or normalized
        # throughput fail the diff even when the critical path holds
        "straggler_ratio": top["straggler_ratio"],
        "coalitions_per_shard_s": top["coalitions_per_shard_s"],
        "cross_shard_rank_tau": equality.get("kendall_tau"),
    }
    _write_telemetry({"metric": metric,
                      "wallclock_s": top["fleet_wallclock_s"],
                      "devices": top["devices"],
                      "degraded": False,
                      "fleet": fleet_block})
    _emit(metric, top["fleet_wallclock_s"],
          _baseline_seconds(dataset, epochs, B))


def _bench_method(dataset_name, n_partners, method, epochs, dtype,
                  corrupted=None, extra_methods=()):
    """Shared driver for the MC/IS/stratified configs: run
    compute_contributivity(method) on a cold engine, count trainings."""
    from mplc_tpu.contrib.contributivity import Contributivity

    sc = _make_scenario(dataset_name, n_partners, epochs, dtype, corrupted)
    warm = _warm_engine(sc)
    print("[bench] compiled; timing...", file=sys.stderr)

    timed = _attach_progress(_fresh_engine(sc, warm), "timed")
    # split wall-clock into engine-evaluate time vs host-side estimator
    # time (sampling, refits, stopping rule) — the estimator loops must
    # stay <10% of wall-clock now that the IS/SMC draws are tabulated
    engine_time = {"s": 0.0}
    orig_eval = timed.evaluate

    def _timed_eval(subsets):
        te = time.perf_counter()
        try:
            return orig_eval(subsets)
        finally:
            engine_time["s"] += time.perf_counter() - te

    timed.evaluate = _timed_eval
    from mplc_tpu.obs import trace as obs_trace
    from mplc_tpu.utils import profile_trace
    t0 = time.perf_counter()
    with profile_trace(), obs_trace.collect() as tele:
        contrib = Contributivity(sc)
        contrib.compute_contributivity(method)
        for m in extra_methods:
            Contributivity(sc).compute_contributivity(m)
    elapsed = time.perf_counter() - t0
    calls = timed.first_charac_fct_calls_count

    print(f"[bench] {method} scores: "
          f"{np.round(contrib.contributivity_scores, 4).tolist()}",
          file=sys.stderr)
    print(f"[bench] {elapsed:.1f} s for {calls} distinct coalition trainings "
          f"({elapsed / max(calls, 1):.3f} s each) on {_ndev()} device(s)",
          file=sys.stderr)
    host = elapsed - engine_time["s"]
    print(f"[bench] engine.evaluate {engine_time['s']:.1f} s, host-side "
          f"estimator {host:.1f} s ({100 * host / max(elapsed, 1e-9):.1f}% "
          f"of wall-clock)", file=sys.stderr)
    flops, fleet_peak, fleet_hbm = _compute_inputs(timed)
    _throughput_note(timed, elapsed, flops, fleet_peak)
    tag = method.lower().replace(" ", "_")
    metric = f"{tag}_{dataset_name}_{n_partners}partners_{epochs}epochs_wallclock"
    _note_numerics(timed)
    # the estimator's sampled coalitions are seed-pinned, so the twin
    # re-evaluates the exact subsets this run harvested
    _note_precision(timed, lambda: _make_scenario(dataset_name, n_partners,
                                                  epochs, dtype, corrupted))
    from mplc_tpu.obs.report import format_report, sweep_report
    rep = sweep_report(tele, flops_per_sample=flops, peak_flops=fleet_peak,
                       hbm_bytes_per_s=fleet_hbm)
    print(format_report(rep), file=sys.stderr, flush=True)
    _write_telemetry({"metric": metric, "wallclock_s": elapsed,
                      "devices": _ndev(), "degraded": _degraded_run(rep),
                      "report": rep})
    _emit(metric, elapsed, _baseline_seconds(dataset_name, epochs, calls))


def _ndev():
    import jax
    return len(jax.devices())


def main():
    # Must be set before mplc_tpu.data.datasets builds the synthetic sets
    # (set here, not at module import, so merely importing bench for its
    # helpers — as the tests do — leaves the process env untouched).
    os.environ.setdefault("MPLC_TPU_SYNTH_NOISE", "0.75")
    config = os.environ.get("BENCH_CONFIG", "1")
    epochs = int(os.environ.get("BENCH_EPOCHS", "8"))
    devices = _devices_with_deadline()
    if devices is None:
        sys.exit(_fallback_exit() if _fallback_allowed() else 3)
    platform = devices[0].platform
    _start_stall_watchdog(platform)
    try:
        # Persistent compilation cache: a bench run's ~15 min of slot-
        # pipeline compiles is paid once per (program, topology) — later
        # runs on the same chip (e.g. the driver's end-of-round run after a
        # manual one) reload executables from disk. MPLC_TPU_COMPILE_CACHE_DIR
        # overrides the repo-local default; either way the warm-up doubles
        # as a cache prime, and the telemetry sidecar records whether this
        # run grew the bank or was served from it (cache-hit provenance).
        import jax

        from mplc_tpu.utils import (compile_cache_entries,
                                    enable_compile_cache_from_env)
        cache_dir = enable_compile_cache_from_env()
        if cache_dir is None:
            cache_dir = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
            jax.config.update("jax_compilation_cache_dir", cache_dir)
        _COMPILE_CACHE.update(
            dir=cache_dir, entries_at_start=compile_cache_entries(cache_dir))
        print(f"[bench] persistent compile cache: {cache_dir} "
              f"({_COMPILE_CACHE['entries_at_start'] or 0} entries) — "
              "warm-up doubles as a cache prime", file=sys.stderr)
    except Exception as e:
        print(f"[bench] compile cache disabled: {e}", file=sys.stderr)
    default_dtype = "float32" if platform == "cpu" else "bfloat16"
    dtype = os.environ.get("BENCH_DTYPE", default_dtype)
    print(f"[bench] config={config} devices={devices} dtype={dtype} "
          f"epochs={epochs}", file=sys.stderr, flush=True)

    if config == "1":
        bench_exact_shapley(epochs, dtype)
    elif config == "2":
        _bench_method("cifar10", 5, os.environ.get("BENCH_METHOD", "TMCS"),
                      epochs, dtype)
    elif config == "3":
        _bench_method("mnist", 10, os.environ.get("BENCH_METHOD", "IS_lin_S"),
                      epochs, dtype)
    elif config == "4":
        _bench_method("imdb", 4, os.environ.get("BENCH_METHOD", "SMCS"),
                      epochs, dtype)
    elif config == "5":
        corrupted = ["corrupted", "corrupted"] + ["not_corrupted"] * 6
        _bench_method("cifar10", 8, os.environ.get("BENCH_METHOD", "TMCS"),
                      epochs, dtype, corrupted=corrupted,
                      extra_methods=("Independent scores",))
    elif config == "6":
        bench_service(epochs, dtype)
    elif config == "7":
        bench_load(epochs, dtype)
    elif config == "8":
        bench_live(epochs, dtype)
    elif config == "9":
        bench_fleet(epochs, dtype)
    elif config == "10":
        bench_residency(epochs, dtype)
    elif config == "11":
        bench_router(epochs, dtype)
    else:
        raise SystemExit(f"unknown BENCH_CONFIG={config!r} (use 1-11)")

    if _watchdog_fired.is_set():
        # The watchdog declared this run dead and its fallback child owns
        # stdout/exit; returning would kill the daemon thread (and the
        # child) mid-run. Park — the watchdog ends the process.
        threading.Event().wait()


if __name__ == "__main__":
    main()
