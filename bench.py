#!/usr/bin/env python
"""Benchmark: exact Shapley on MNIST-scale data, batched coalition sweep.

Workload (mirrors BASELINE.md configs[0] and the reference headline):
MNIST-shaped dataset (60k train), 3 partners [0.4, 0.3, 0.3], basic random
split, fedavg + data-volume aggregation, exact Shapley = all 2^3-1 = 7
coalition trainings. The reference (saved_experiments results.csv) trains
ONE such fedavg model in ~589 s wall-clock at 50 epochs; exact Shapley there
costs 7 serialized trainings. Here all 7 coalitions train together as one
vmapped (and, multi-chip, sharded) batch.

Baseline accounting: reference wall-clock scales ~linearly in epochs, so
  baseline_seconds = 589 s * (epoch_count / 50) * n_coalitions
and vs_baseline = baseline_seconds / measured_seconds (higher is better).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Env knobs: BENCH_PARTNERS (default 3), BENCH_EPOCHS (default 8),
BENCH_DTYPE (default bfloat16 on TPU, float32 on CPU),
MPLC_TPU_SYNTH_SCALE for smaller data on CPU smoke runs.
"""

import json
import os
import sys
import time

import numpy as np

REFERENCE_MNIST_FEDAVG_SECONDS = 589.0   # saved_experiments/.../results.csv mean
REFERENCE_EPOCH_BUDGET = 50


def main():
    import jax
    import jax.numpy as jnp

    from mplc_tpu.contrib.shapley import powerset_order, shapley_from_characteristic
    from mplc_tpu.data.datasets import load_mnist
    from mplc_tpu.data.partner import Partner
    from mplc_tpu.data.partition import (StackedPartners, compute_batch_sizes,
                                         split_basic, stack_eval_set)
    from mplc_tpu.mpl.engine import EvalSet, MplTrainer, TrainConfig
    from mplc_tpu.parallel.mesh import coalition_sharding
    from mplc_tpu import constants

    n_partners = int(os.environ.get("BENCH_PARTNERS", "3"))
    epochs = int(os.environ.get("BENCH_EPOCHS", "8"))
    platform = jax.devices()[0].platform
    default_dtype = "float32" if platform == "cpu" else "bfloat16"
    dtype = os.environ.get("BENCH_DTYPE", default_dtype)

    print(f"[bench] devices={jax.devices()} dtype={dtype} "
          f"partners={n_partners} epochs={epochs}", file=sys.stderr)

    ds = load_mnist()
    amounts = [0.4, 0.3, 0.3] if n_partners == 3 else \
        [1.0 / n_partners] * n_partners
    amounts = [a / sum(amounts) for a in amounts]
    partners = [Partner(i) for i in range(n_partners)]
    split_basic(ds, partners, amounts, "random", minibatch_count=10)
    compute_batch_sizes(partners, 10, 8, constants.MAX_BATCH_SIZE)

    stacked = StackedPartners.build(partners, 10)
    val = EvalSet(*stack_eval_set(ds.x_val, ds.y_val, 10, 2048))
    test = EvalSet(*stack_eval_set(ds.x_test, ds.y_test, 10, 2048))

    cfg = TrainConfig(approach="fedavg", aggregator="data-volume",
                      epoch_count=epochs, minibatch_count=10,
                      gradient_updates_per_pass=8, is_early_stopping=False,
                      record_partner_val=False, compute_dtype=dtype)
    trainer = MplTrainer(ds.model, cfg)

    coalitions = powerset_order(n_partners)
    B = len(coalitions)
    masks = np.zeros((B, n_partners), np.float32)
    for i, s in enumerate(coalitions):
        masks[i, list(s)] = 1.0
    masks = jnp.asarray(masks)
    rngs = jax.random.split(jax.random.PRNGKey(0), B)

    sharding = coalition_sharding()
    if sharding is not None and B % sharding.num_devices == 0:
        masks = jax.device_put(masks, sharding.batch_sharding)
        rngs = jax.device_put(rngs, sharding.batch_sharding)

    binit = jax.jit(jax.vmap(lambda r: trainer.init_state(r, n_partners)))

    def run_all_epochs(state, stacked, val, masks, rngs):
        return jax.vmap(trainer.epoch_chunk,
                        in_axes=(0, None, None, 0, 0, None))(
            state, stacked, val, masks, rngs, epochs)

    brun = jax.jit(run_all_epochs)
    bfin = jax.jit(jax.vmap(trainer.finalize, in_axes=(0, None)))

    # AOT-compile the exact executables used in the timed region (excluded
    # from the measurement, like any production sweep where the executable
    # is cached across the 2^N coalition batches), then execute once to warm
    # any lazy runtime initialization.
    state = binit(rngs)
    brun_c = brun.lower(state, stacked, val, masks, rngs).compile()
    bfin_c = bfin.lower(state, test).compile()
    warm = bfin_c(brun_c(state, stacked, val, masks, rngs), test)
    np.asarray(warm[1])
    print("[bench] compiled; timing...", file=sys.stderr)

    # Time until the scores are on the host: a host fetch is the only sync
    # that every backend (incl. the tunneled axon TPU) honors.
    t0 = time.perf_counter()
    state = binit(rngs)
    state = brun_c(state, stacked, val, masks, rngs)
    losses, accs = bfin_c(state, test)
    accs = np.asarray(accs)
    elapsed = time.perf_counter() - t0

    values = {(): 0.0}
    accs = np.asarray(accs)
    for s, a in zip(coalitions, accs):
        values[s] = float(a)
    sv = shapley_from_characteristic(n_partners, values)
    print(f"[bench] coalition accs: {np.round(accs, 4).tolist()}", file=sys.stderr)
    print(f"[bench] Shapley values: {np.round(sv, 4).tolist()}", file=sys.stderr)

    scale = float(os.environ.get("MPLC_TPU_SYNTH_SCALE", "1.0"))
    baseline = (REFERENCE_MNIST_FEDAVG_SECONDS * (epochs / REFERENCE_EPOCH_BUDGET)
                * scale * B)
    print(json.dumps({
        "metric": f"exact_shapley_mnist_{n_partners}partners_{epochs}epochs_wallclock",
        "value": round(elapsed, 3),
        "unit": "s",
        "vs_baseline": round(baseline / elapsed, 3),
    }))


if __name__ == "__main__":
    main()
