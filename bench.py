#!/usr/bin/env python
"""Benchmark: contributivity sweeps through the production characteristic-
function engine, covering the BASELINE.md benchmark configs.

Configs (select with BENCH_CONFIG, default "1"):
  1  exact Shapley, MNIST-scale data, BENCH_PARTNERS partners (default 10 —
     the north star: 1023 coalitions; 3 reproduces config_quick_debug)
  2  TMCS, CIFAR10-scale data, 5 partners
  3  importance-sampling Shapley (BENCH_METHOD: IS_lin_S / IS_reg_S /
     AIS_Kriging_S), MNIST, 10 partners
  4  stratified MC Shapley (BENCH_METHOD: SMCS / WR_SMC), IMDB, 4 partners
  5  TMCS + Independent scores, CIFAR10, 8 partners with 2 corrupted

Workload notes. The reference (saved_experiments results.csv) trains ONE
fedavg MNIST model in ~589 s wall-clock at 50 epochs and needs one full
training per distinct coalition (mplc/contributivity.py:92-136, :149-158).
Here the engine batches coalitions, groups them by size (a size-k coalition
trains k partner slots, not N masked ones), skips the per-minibatch val
evals the reference pays (record_val_history=False — only the early-stopping
column is evaluated), and — with multiple devices — shards batches over the
`coal` mesh axis.

Timing excludes compilation: a warm-up engine first evaluates one
full-width batch per coalition size (compiled executables are shared per
(model, config) via the trainer registry, and the engine pads every batch
of a call to one bucket width per size), then a fresh engine with a cold
memo cache — sharing the warm engine's device arrays via share_data_from,
so HBM holds ONE copy of the data — is timed end to end.

Baseline accounting: reference wall-clock scales ~linearly in epochs and in
the number of distinct coalition trainings, so
  baseline_seconds = 589 s * (epochs / 50) * synth_scale * n_trainings
                     (* 3030/589 for CIFAR10-shaped runs)
and vs_baseline = baseline_seconds / measured_seconds (higher is better).
For MC methods n_trainings = the timed run's first_charac_fct_calls_count —
the reference's own cost counter (contributivity.py:73).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Env knobs: BENCH_CONFIG, BENCH_PARTNERS, BENCH_EPOCHS (default 8),
BENCH_METHOD, BENCH_DTYPE (default bfloat16 on TPU, float32 on CPU),
MPLC_TPU_NO_SLOTS=1 for masked full-width execution, MPLC_TPU_SYNTH_SCALE
for smaller data on CPU smoke runs, MPLC_TPU_SYNTH_NOISE (default 0.75
here: accuracy must not saturate, or every Shapley value degenerates to
1/N — BENCH_r02's flaw).
"""

import json
import os
import sys
import time

# Must be set before mplc_tpu.data.datasets builds the synthetic sets.
os.environ.setdefault("MPLC_TPU_SYNTH_NOISE", "0.75")

import numpy as np

REFERENCE_MNIST_FEDAVG_SECONDS = 589.0   # saved_experiments/.../results.csv mean
REFERENCE_CIFAR_FEDAVG_SECONDS = 3030.0  # 〃 (cifar10 fedavg random rows)
REFERENCE_EPOCH_BUDGET = 50


def _amounts(n_partners):
    """3 partners reproduces BASELINE config 1 ([0.4, 0.3, 0.3]); larger
    counts use a deliberately uneven (i+1)-proportional split so coalition
    values — and Shapley values — differ measurably between partners."""
    if n_partners == 3:
        a = [0.4, 0.3, 0.3]
    else:
        a = [float(i + 1) for i in range(n_partners)]
    return [x / sum(a) for x in a]


def _make_scenario(dataset_name, n_partners, epochs, dtype, corrupted=None):
    from mplc_tpu.scenario import Scenario

    sc = Scenario(partners_count=n_partners,
                  amounts_per_partner=_amounts(n_partners),
                  dataset_name=dataset_name,
                  multi_partner_learning_approach="fedavg",
                  aggregation_weighting="data-volume", epoch_count=epochs,
                  minibatch_count=10, gradient_updates_per_pass_count=8,
                  is_early_stopping=False, compute_dtype=dtype,
                  corrupted_datasets=corrupted,
                  experiment_path="/tmp/mplc_bench", is_dry_run=True, seed=0)
    sc.instantiate_scenario_partners()
    sc.split_data(is_logging_enabled=False)
    sc.compute_batch_sizes()
    sc.data_corruption()
    return sc


def _warm_engine(sc):
    """Compile every program the timed run will execute. The engine pads
    each evaluate() call to one bucket width per coalition size
    (contrib/engine.py _run_batch), so warming with min(C(n,k), n_dev*cap)
    distinct subsets per size hits exactly the (width, slot-size) programs a
    full sweep uses. Adaptive MC methods can still trigger one smaller
    width on a late, short batch — that residual compile is accepted and
    visible, not hidden."""
    from itertools import combinations, islice
    from math import comb

    from mplc_tpu.contrib.engine import CharacteristicEngine

    warm = CharacteristicEngine(sc)
    n = warm.partners_count
    n_dev = max(warm._sharding.num_devices if warm._sharding else 1, 1)

    warm.evaluate([(i,) for i in
                   range(min(n, n_dev * warm._device_batch_cap(None)))])
    if warm._use_slots:
        for k in range(2, n + 1):
            w = min(comb(n, k), n_dev * warm._device_batch_cap(k))
            warm.evaluate(list(islice(combinations(range(n), k), w)))
    else:
        w = min(2 ** n - 1 - n, n_dev * warm._device_batch_cap(None))
        multis = []
        for k in range(2, n + 1):
            multis += list(islice(combinations(range(n), k), w - len(multis)))
            if len(multis) >= w:
                break
        warm.evaluate(multis)
    return warm


def _fresh_engine(sc, warm):
    """Cold-cache engine sharing the warm engine's device arrays (ADVICE
    item: share_data_from halves bench HBM — one copy of the data)."""
    from mplc_tpu.contrib.engine import CharacteristicEngine
    sc._charac_engine = CharacteristicEngine(sc, share_data_from=warm)
    return sc._charac_engine


def _baseline_seconds(dataset_name, epochs, n_trainings):
    scale = float(os.environ.get("MPLC_TPU_SYNTH_SCALE", "1.0"))
    per_training = (REFERENCE_CIFAR_FEDAVG_SECONDS
                    if dataset_name == "cifar10"
                    else REFERENCE_MNIST_FEDAVG_SECONDS)
    return per_training * (epochs / REFERENCE_EPOCH_BUDGET) * scale * n_trainings


def _emit(metric, elapsed, baseline):
    print(json.dumps({
        "metric": metric,
        "value": round(elapsed, 3),
        "unit": "s",
        "vs_baseline": round(baseline / elapsed, 3),
    }))


def bench_exact_shapley(epochs, dtype):
    """Config 1 / north star: exact Shapley = all 2^N - 1 coalitions."""
    from mplc_tpu.contrib.shapley import powerset_order, shapley_from_characteristic

    n_partners = int(os.environ.get("BENCH_PARTNERS", "10"))
    coalitions = powerset_order(n_partners)
    B = len(coalitions)

    sc = _make_scenario("mnist", n_partners, epochs, dtype)
    warm = _warm_engine(sc)
    print("[bench] compiled; timing...", file=sys.stderr)

    timed = _fresh_engine(sc, warm)
    t0 = time.perf_counter()
    accs = timed.evaluate(coalitions)
    elapsed = time.perf_counter() - t0
    assert timed.first_charac_fct_calls_count == B

    values = {(): 0.0}
    for s, a in zip(coalitions, accs):
        values[s] = float(a)
    sv = shapley_from_characteristic(n_partners, values)
    print(f"[bench] coalition accs: min={accs.min():.4f} max={accs.max():.4f} "
          f"spread={accs.max() - accs.min():.4f}", file=sys.stderr)
    print(f"[bench] Shapley values: {np.round(sv, 4).tolist()}", file=sys.stderr)
    print(f"[bench] {elapsed:.1f} s for {B} coalitions = "
          f"{elapsed / B:.3f} s/coalition on {_ndev()} device(s); projected "
          f"v5e-8 (8-way coal sharding, zero-communication axis => ~linear): "
          f"{elapsed / 8:.1f} s", file=sys.stderr)
    _emit(f"exact_shapley_mnist_{n_partners}partners_{epochs}epochs_wallclock",
          elapsed, _baseline_seconds("mnist", epochs, B))


def _bench_method(dataset_name, n_partners, method, epochs, dtype,
                  corrupted=None, extra_methods=()):
    """Shared driver for the MC/IS/stratified configs: run
    compute_contributivity(method) on a cold engine, count trainings."""
    from mplc_tpu.contrib.contributivity import Contributivity

    sc = _make_scenario(dataset_name, n_partners, epochs, dtype, corrupted)
    warm = _warm_engine(sc)
    print("[bench] compiled; timing...", file=sys.stderr)

    timed = _fresh_engine(sc, warm)
    t0 = time.perf_counter()
    contrib = Contributivity(sc)
    contrib.compute_contributivity(method)
    for m in extra_methods:
        Contributivity(sc).compute_contributivity(m)
    elapsed = time.perf_counter() - t0
    calls = timed.first_charac_fct_calls_count

    print(f"[bench] {method} scores: "
          f"{np.round(contrib.contributivity_scores, 4).tolist()}",
          file=sys.stderr)
    print(f"[bench] {elapsed:.1f} s for {calls} distinct coalition trainings "
          f"({elapsed / max(calls, 1):.3f} s each) on {_ndev()} device(s)",
          file=sys.stderr)
    tag = method.lower().replace(" ", "_")
    _emit(f"{tag}_{dataset_name}_{n_partners}partners_{epochs}epochs_wallclock",
          elapsed, _baseline_seconds(dataset_name, epochs, calls))


def _ndev():
    import jax
    return len(jax.devices())


def main():
    import jax

    config = os.environ.get("BENCH_CONFIG", "1")
    epochs = int(os.environ.get("BENCH_EPOCHS", "8"))
    platform = jax.devices()[0].platform
    default_dtype = "float32" if platform == "cpu" else "bfloat16"
    dtype = os.environ.get("BENCH_DTYPE", default_dtype)
    print(f"[bench] config={config} devices={jax.devices()} dtype={dtype} "
          f"epochs={epochs}", file=sys.stderr)

    if config == "1":
        bench_exact_shapley(epochs, dtype)
    elif config == "2":
        _bench_method("cifar10", 5, os.environ.get("BENCH_METHOD", "TMCS"),
                      epochs, dtype)
    elif config == "3":
        _bench_method("mnist", 10, os.environ.get("BENCH_METHOD", "IS_lin_S"),
                      epochs, dtype)
    elif config == "4":
        _bench_method("imdb", 4, os.environ.get("BENCH_METHOD", "SMCS"),
                      epochs, dtype)
    elif config == "5":
        corrupted = ["corrupted", "corrupted"] + ["not_corrupted"] * 6
        _bench_method("cifar10", 8, os.environ.get("BENCH_METHOD", "TMCS"),
                      epochs, dtype, corrupted=corrupted,
                      extra_methods=("Independent scores",))
    else:
        raise SystemExit(f"unknown BENCH_CONFIG={config!r} (use 1-5)")


if __name__ == "__main__":
    main()
