#!/usr/bin/env python
"""Benchmark: exact Shapley on MNIST-scale data through the production
characteristic-function engine.

Workload (mirrors BASELINE.md configs[0] and the reference headline):
MNIST-shaped dataset (60k train), BENCH_PARTNERS partners (default 3,
amounts [0.4, 0.3, 0.3]), basic random split, fedavg + data-volume
aggregation, exact Shapley = all 2^N-1 coalition trainings. The reference
(saved_experiments results.csv) trains ONE such fedavg model in ~589 s
wall-clock at 50 epochs; exact Shapley there costs 2^N-1 serialized
trainings. Here the engine batches coalitions, groups them by size (a
size-k coalition trains k partner slots, not N masked ones), and — with
multiple devices — shards each batch over the `coal` mesh axis.

Timing excludes compilation: a warm-up engine compiles and runs every
program once (executables are shared per (model, config) via the trainer
cache), then a fresh engine with an empty memo cache is timed end to end —
the exact production path (reference loop: contributivity.py:149-158).

Baseline accounting: reference wall-clock scales ~linearly in epochs, so
  baseline_seconds = 589 s * (epoch_count / 50) * n_coalitions
and vs_baseline = baseline_seconds / measured_seconds (higher is better).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Env knobs: BENCH_PARTNERS (default 3), BENCH_EPOCHS (default 8),
BENCH_DTYPE (default bfloat16 on TPU, float32 on CPU), MPLC_TPU_NO_SLOTS=1
to fall back to masked full-width execution, MPLC_TPU_SYNTH_SCALE for
smaller data on CPU smoke runs.
"""

import json
import os
import sys
import time

import numpy as np

REFERENCE_MNIST_FEDAVG_SECONDS = 589.0   # saved_experiments/.../results.csv mean
REFERENCE_EPOCH_BUDGET = 50


def _make_scenario(n_partners, epochs, dtype):
    from mplc_tpu.data.datasets import load_mnist
    from mplc_tpu.scenario import Scenario

    amounts = [0.4, 0.3, 0.3] if n_partners == 3 else \
        [1.0 / n_partners] * n_partners
    amounts = [a / sum(amounts) for a in amounts]
    sc = Scenario(partners_count=n_partners, amounts_per_partner=amounts,
                  dataset=load_mnist(), multi_partner_learning_approach="fedavg",
                  aggregation_weighting="data-volume", epoch_count=epochs,
                  minibatch_count=10, gradient_updates_per_pass_count=8,
                  is_early_stopping=False, compute_dtype=dtype,
                  experiment_path="/tmp/mplc_bench", is_dry_run=True, seed=0)
    sc.instantiate_scenario_partners()
    sc.split_data(is_logging_enabled=False)
    sc.compute_batch_sizes()
    return sc


def main():
    import jax

    from mplc_tpu.contrib.engine import CharacteristicEngine
    from mplc_tpu.contrib.shapley import powerset_order, shapley_from_characteristic

    n_partners = int(os.environ.get("BENCH_PARTNERS", "3"))
    epochs = int(os.environ.get("BENCH_EPOCHS", "8"))
    platform = jax.devices()[0].platform
    default_dtype = "float32" if platform == "cpu" else "bfloat16"
    dtype = os.environ.get("BENCH_DTYPE", default_dtype)

    print(f"[bench] devices={jax.devices()} dtype={dtype} "
          f"partners={n_partners} epochs={epochs}", file=sys.stderr)

    coalitions = powerset_order(n_partners)
    B = len(coalitions)

    # Warm-up: compile + run every (size-group) program once. The compiled
    # executables live on the shared per-(model, config) trainers, so the
    # timed engine below reuses them with a cold memo cache.
    sc = _make_scenario(n_partners, epochs, dtype)
    warm = CharacteristicEngine(sc)
    warm.evaluate(coalitions)
    print("[bench] compiled; timing...", file=sys.stderr)

    timed_engine = CharacteristicEngine(sc)
    t0 = time.perf_counter()
    accs = timed_engine.evaluate(coalitions)   # engine fetches scores to host
    elapsed = time.perf_counter() - t0
    assert timed_engine.first_charac_fct_calls_count == B

    values = {(): 0.0}
    for s, a in zip(coalitions, accs):
        values[s] = float(a)
    sv = shapley_from_characteristic(n_partners, values)
    print(f"[bench] coalition accs: {np.round(accs, 4).tolist()}", file=sys.stderr)
    print(f"[bench] Shapley values: {np.round(sv, 4).tolist()}", file=sys.stderr)

    scale = float(os.environ.get("MPLC_TPU_SYNTH_SCALE", "1.0"))
    baseline = (REFERENCE_MNIST_FEDAVG_SECONDS * (epochs / REFERENCE_EPOCH_BUDGET)
                * scale * B)
    print(json.dumps({
        "metric": f"exact_shapley_mnist_{n_partners}partners_{epochs}epochs_wallclock",
        "value": round(elapsed, 3),
        "unit": "s",
        "vs_baseline": round(baseline / elapsed, 3),
    }))


if __name__ == "__main__":
    main()
