#!/usr/bin/env python
"""North-star-scale SV parity: exact Shapley, 10 partners, 1023 coalitions,
production engine vs the pure-NumPy reference oracle, to 1e-3.

VERDICT r4 weak #7: the trained-SV parity oracle (tests/test_sv_parity.py)
proves engine==reference on 3-partner scenarios; the 1023-coalition
north-star run's parity evidence was extrapolated. This runs the SAME
independent NumPy re-implementation of the reference fedavg/single loops
(reference mplc/multi_partner_learning.py:230-332) over the full
10-partner powerset on the forced 8-device CPU mesh, sharing only the
per-coalition initial weights with the engine, and records max |Δv(S)|
and max |ΔSV| as a committed artifact (perf/r5/sv_parity_n10.json).
The gate is the BASELINE contract — Shapley SCORES to 1e-3 — plus a
v(S) sanity bound denominated in accuracy quanta (1/n_test): v(S) is a
step function of the test predictions, so borderline-sample flips from
float32-vs-float64 drift move it in 5e-4 jumps that say nothing about
the training-semantics parity the oracle exists to check.

The logreg family is used deliberately: the parity target is the
TRAINING/AGGREGATION/ES semantics at the north-star partner count — the
model family is orthogonal (the conv trainers go through the identical
mask-conditioned slot pipelines) and CNNs are uncompilable in bulk on
this one-core host.

Politeness: between engine batches the run sleeps while /tmp/tpu_busy
exists (the TPU queue's timed-phase flag) — the host has one core and
concurrent load skews the queue's host-side timings.
"""

import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tests"))

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # axon sitecustomize pins platform
jax.config.update("jax_compilation_cache_dir", os.path.join(ROOT, ".jax_cache"))

N_PARTNERS = int(os.environ.get("PARITY_PARTNERS", "10"))
OUT = os.environ.get("PARITY_OUT",
                     os.path.join(ROOT, "perf", "r5", "sv_parity_n10.json"))
BUSY_FLAG = "/tmp/tpu_busy"


def _polite_wait():
    waited = 0
    while os.path.exists(BUSY_FLAG):
        if waited == 0:
            print("[parity] TPU queue in a timed phase — pausing", flush=True)
        time.sleep(60)
        waited += 60
    if waited:
        print(f"[parity] resumed after {waited} s", flush=True)


def make_scenario():
    from test_sv_parity import _make_parity_scenario  # noqa: F401 (path check)
    from mplc_tpu.data.datasets import Dataset
    from mplc_tpu.models.zoo import TITANIC_LOGREG, TITANIC_NUM_FEATURES
    from mplc_tpu.scenario import Scenario

    rng = np.random.default_rng(123)
    n_train, n_test = 2600, 2000
    w_true = rng.normal(0, 1.2, TITANIC_NUM_FEATURES)

    def make(n):
        x = rng.normal(0, 1, (n, TITANIC_NUM_FEATURES)).astype(np.float32)
        y = (x @ w_true > 0).astype(np.float32)
        flip = rng.uniform(size=n) < 0.08
        y[flip] = 1 - y[flip]
        return x, y

    x, y = make(n_train)
    xt, yt = make(n_test)
    ds = Dataset("titanic", (TITANIC_NUM_FEATURES,), 2, x, y, xt, yt,
                 model=TITANIC_LOGREG, provenance="test")
    amounts = [i + 1.0 for i in range(N_PARTNERS)]
    amounts = [a / sum(amounts) for a in amounts]
    sc = Scenario(partners_count=N_PARTNERS, amounts_per_partner=amounts,
                  dataset=ds, multi_partner_learning_approach="fedavg",
                  aggregation_weighting="data-volume",
                  epoch_count=25, minibatch_count=1,
                  gradient_updates_per_pass_count=1,
                  experiment_path="/tmp/mplc_parity_n10", seed=5)
    sc.instantiate_scenario_partners()
    sc.split_data(is_logging_enabled=False)
    sc.compute_batch_sizes()
    sc.data_corruption()
    return sc


def main():
    from test_sv_parity import NumpyFedAvgOracle, _partners_val_test_arrays
    from mplc_tpu.contrib.engine import CharacteristicEngine
    from mplc_tpu.contrib.shapley import (powerset_order,
                                          shapley_from_characteristic)

    t_start = time.time()
    sc = make_scenario()
    eng = CharacteristicEngine(sc)
    print(f"[parity] devices={len(jax.devices())} partners={N_PARTNERS}",
          flush=True)

    done = {"n": 0}

    def progress(done_now, remaining, slot_count):
        done["n"] += done_now
        print(f"[parity] engine: +{done_now} (slots={slot_count}, "
              f"total {done['n']}, {remaining} left) t={time.time() - t_start:.0f}s",
              flush=True)
        _polite_wait()

    eng.progress = progress

    subsets = powerset_order(N_PARTNERS)
    _polite_wait()
    engine_vals = eng.evaluate(subsets)
    t_engine = time.time() - t_start
    print(f"[parity] engine done: {len(subsets)} coalitions in {t_engine:.0f}s",
          flush=True)

    partners_xy, val, test = _partners_val_test_arrays(sc)
    oracle = NumpyFedAvgOracle(partners_xy, val, test, epochs=sc.epoch_count)
    oracle_table = {(): 0.0}
    t0 = time.time()
    for idx, s in enumerate(subsets):
        params = jax.device_get(sc.dataset.model.init(eng._coalition_rng(s)))
        w0 = np.asarray(params["d1"]["w"], np.float64).reshape(-1)
        b0 = float(np.asarray(params["d1"]["b"]).reshape(()))
        if len(s) == 1:
            w, b = oracle.train_single(s[0], w0, b0)
        else:
            w, b = oracle.train_coalition(s, w0, b0)
        oracle_table[s] = oracle.accuracy(w, b)
        if (idx + 1) % 100 == 0:
            print(f"[parity] oracle: {idx + 1}/{len(subsets)} "
                  f"t={time.time() - t0:.0f}s", flush=True)
            _polite_wait()

    oracle_vals = np.array([oracle_table[s] for s in subsets])
    signed = engine_vals - oracle_vals
    dv = np.abs(signed)
    sv_engine = shapley_from_characteristic(N_PARTNERS, eng.charac_fct_values)
    sv_oracle = shapley_from_characteristic(N_PARTNERS, oracle_table)
    dsv = np.abs(sv_engine - sv_oracle)

    # Gate = the BASELINE contract: SHAPLEY SCORES to 1e-3. The per-
    # coalition v(S) is test ACCURACY over n_test samples — quantized at
    # 1/n_test (5e-4 here), so a raw 1e-3 bound on v(S) is a two-sample
    # bound that single borderline predictions flip (float32 engine vs
    # float64 oracle drift over 25 epochs); v(S) gets a sanity bound in
    # QUANTA instead, plus bias diagnostics (threshold-crossing noise must
    # be centered, not systematic).
    n_test = len(sc.dataset.y_test)
    quantum = 1.0 / n_test
    dv_quanta = dv / quantum
    result = {
        "partners": N_PARTNERS,
        "coalitions": len(subsets),
        "test_samples": n_test,
        "max_abs_vS_diff": float(dv.max()),
        "mean_abs_vS_diff": float(dv.mean()),
        "max_vS_diff_quanta": float(dv_quanta.max()),
        "mean_vS_diff_quanta": float(dv_quanta.mean()),
        "mean_signed_vS_diff_quanta": float((signed / quantum).mean()),
        "n_coalitions_over_1e3": int((dv > 1e-3).sum()),
        "max_abs_sv_diff": float(dsv.max()),
        "sv_engine": np.round(sv_engine, 6).tolist(),
        "sv_oracle": np.round(sv_oracle, 6).tolist(),
        "sv_spread": float(sv_oracle.max() - sv_oracle.min()),
        "engine_seconds": round(t_engine, 1),
        "oracle_seconds": round(time.time() - t0, 1),
        # contract: SV to 1e-3; sanity: worst v(S) within 10 accuracy
        # quanta, mean within 1 quantum, and flips unbiased (<0.5 quantum)
        "pass_sv_1e3": bool(dsv.max() < 1e-3),
        "pass_vS_sanity": bool(dv_quanta.max() <= 10
                               and dv_quanta.mean() <= 1.0
                               and abs((signed / quantum).mean()) < 0.5),
    }
    result["pass"] = bool(result["pass_sv_1e3"] and result["pass_vS_sanity"])
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(result, f, indent=1)
    print(f"[parity] {json.dumps(result)}", flush=True)
    print(f"[parity] {'PASS' if result['pass'] else 'FAIL'} "
          f"(SV to 1e-3 + v(S) quanta sanity at n={N_PARTNERS})", flush=True)
    sys.exit(0 if result["pass"] else 1)


if __name__ == "__main__":
    main()
