#!/bin/bash
# Round-5 priority-ordered TPU measurement queue (VERDICT r4 "Next round").
#
# Probes the axon tunnel with a bounded jax.devices() every 5 min (it wedges
# for multi-hour stretches — DESIGN_NOTES.md) and, whenever it answers, runs
# the next unfinished step. Steps are idempotent: a step whose .json output
# already holds a metric line is skipped, so the script can be restarted (or
# the tunnel can die mid-queue) without redoing finished work.
#
# Priority order (VERDICT r4 tasks 1, 2, 3, 4, 6):
#   1. width-scaling curve  — per-device widths 1/2/4/8/16 at fixed size 5;
#      the input the 300 s-bar v5e-8 projection is missing (task 1)
#   2. config 1, driver-shaped (plain bench.py) — north-star re-run; its
#      warm-up line also measures compile-cache reload (219 r4 entries are
#      on disk in .jax_cache), and its metric becomes the cached-TPU replay
#      for the round-close driver bench (tasks 2, 4)
#   3. short trace run (6 partners, ~3 min timed) with MPLC_TPU_PROFILE_DIR
#      — attributes the ~96% non-MFU time (task 3)
#   4-6. BASELINE configs 3, 4, 5 — the unmet measurement contract (task 2)
#   7. cap bisect 20/24 — is the cap=32 crash width-specific? (task 6)
#   8. pow2 north star — compile-count/tail-fill tradeoff, measured (task 4)
#   9. warm north-star rerun — cold-vs-warm within one tunnel session
#  10. supplementary methods (IS_reg_S, AIS_Kriging_S, WR_SMC)
#
# While a measured phase runs, /tmp/tpu_busy exists: CPU-side background
# jobs (the n=10 SV-parity run) poll it and pause — the host has ONE core
# and concurrent CPU load skews host-side timing.
set -u
cd "$(dirname "$0")/.."
OUT=${OUT:-/root/repo/perf/r5}
mkdir -p "$OUT"
BUSY=/tmp/tpu_busy
trap 'rm -f "$BUSY"' EXIT

probe() {
    timeout 90 python - <<'EOF'
import threading, sys
ok = []
def init():
    import jax
    ok.append(len(jax.devices()))
t = threading.Thread(target=init, daemon=True)
t.start(); t.join(75)
sys.exit(0 if ok else 1)
EOF
}

wait_for_tunnel() {
    rm -f "$BUSY"
    until probe; do
        echo "$(date +%T) tunnel down; retrying in 300 s"
        sleep 300
    done
    echo "$(date +%T) tunnel up"
    touch "$BUSY"
}

UNFINISHED=0  # per-pass count: steps still lacking their done marker

done_step() {  # a step is done when its json output contains a metric line
    [ -s "$1" ] && grep -q '"metric"' "$1"
}

run_bench() {  # run_bench <out-prefix> [ENV=V ...]
    local prefix=$1; shift
    if done_step "$prefix.json"; then
        return 0
    fi
    wait_for_tunnel
    echo "$(date +%T) running $(basename "$prefix"): $*"
    timeout 5400 env BENCH_CPU_FALLBACK=0 "$@" \
        python bench.py > "$prefix.json" 2> "$prefix.log"
    local rc=$?
    echo "$(date +%T) $(basename "$prefix") exit $rc: $(cat "$prefix.json")"
    done_step "$prefix.json" || UNFINISHED=$((UNFINISHED + 1))
}

run_local() {  # CPU-side step: no tunnel wait, no busy flag; done when
               # its log carries the DONE marker
    local log=$1 tmo=$2; shift 2
    if [ -s "$log" ] && grep -q '^QUEUE-STEP-DONE$' "$log"; then
        return 0
    fi
    echo "$(date +%T) running $(basename "$log"): $*"
    timeout "$tmo" "$@" > "$log" 2>&1
    local rc=$?
    [ $rc -eq 0 ] && echo 'QUEUE-STEP-DONE' >> "$log"
    echo "$(date +%T) $(basename "$log") exit $rc"
    [ $rc -ne 0 ] && UNFINISHED=$((UNFINISHED + 1))
    return 0
}

run_logged() {  # tunnel-needing variant: probe first, then share run_local
    local log=$1
    if [ -s "$log" ] && grep -q '^QUEUE-STEP-DONE$' "$log"; then
        return 0
    fi
    wait_for_tunnel
    run_local "$@"
}

one_pass() {
    # 1. width-scaling curve: block 48 = multiple of lcm(1,2,4,8,16), so no
    #    width pays padding; size 5 is the modal slot count of the north star
    run_logged "$OUT/width_curve.log" 3600 \
        python scripts/tune_coalition_cap.py --size 5 --block 48 \
        --caps 1,2,4,8,16 --partners 10 --epochs 8

    # 1b. the measured projection, the moment the curve exists (CPU-side)
    if grep -q '^QUEUE-STEP-DONE$' "$OUT/width_curve.log" 2>/dev/null; then
        run_local "$OUT/projection.log" 300 bash -c \
            "python scripts/project_v5e8.py --curve $OUT/width_curve.log && \
             python scripts/project_v5e8.py --curve $OUT/width_curve.log --pow2"
    fi

    # 2. driver-shaped north star (exact env shape the driver uses)
    run_bench "$OUT/config1"

    # 3. short profiled run: same model/pipelines as the north star
    run_bench "$OUT/trace_run" BENCH_PARTNERS=6 MPLC_TPU_PROFILE_DIR="$OUT/trace"

    # 3b. trace attribution (CPU-side), once the trace exists. A metric
    # WITHOUT a trace dir means the profiler silently failed — keep the
    # queue unfinished so the gap is loud, not swallowed.
    if done_step "$OUT/trace_run.json"; then
        if [ -d "$OUT/trace" ]; then
            run_local "$OUT/trace_analysis.log" 600 \
                python scripts/analyze_trace.py "$OUT/trace"
        else
            echo "$(date +%T) trace_run measured but $OUT/trace missing — profiler failed"
            UNFINISHED=$((UNFINISHED + 1))
        fi
    fi

    # 4-6. the unmeasured BASELINE configs
    run_bench "$OUT/config3" BENCH_CONFIG=3
    run_bench "$OUT/config4" BENCH_CONFIG=4
    run_bench "$OUT/config5" BENCH_CONFIG=5

    # 7. cap bisect: does >16 width survive below 32? (block 120 = lcm(20,24))
    run_logged "$OUT/cap_bisect.log" 3600 \
        python scripts/tune_coalition_cap.py --size 5 --block 120 \
        --caps 20,24 --partners 10 --epochs 8

    # 7b. if the bisect crashed, test the program-shape hypothesis before
    # calling the cap=32 crash axon-specific: same width with a halved
    # eval-chunk window (the other large activation in the program). No
    # donation toggle exists to rule out — the engine never uses
    # donate_argnums.
    if [ -s "$OUT/cap_bisect.log" ] && \
       ! grep -q '^QUEUE-STEP-DONE$' "$OUT/cap_bisect.log"; then
        run_logged "$OUT/cap_bisect_halfeval.log" 3600 \
            env MPLC_TPU_EVAL_CHUNK=1024 \
            python scripts/tune_coalition_cap.py --size 5 --block 96 \
            --caps 24,32 --partners 10 --epochs 8
    fi

    # 8-10. north-star variants: pow2 bucketing, a warm rerun, and batch
    # pipelining (double-buffered dispatch — the candidate fix for the
    # dispatch-gap share of the non-MFU time the trace run quantifies)
    mkdir -p "$OUT/pow2" "$OUT/warm" "$OUT/pipelined"
    run_bench "$OUT/pow2/config1" MPLC_TPU_SLOT_POW2=1
    run_bench "$OUT/warm/config1"
    run_bench "$OUT/pipelined/config1" MPLC_TPU_PIPELINE_BATCHES=1

    # 10. supplementary estimator methods
    run_bench "$OUT/config3_isreg" BENCH_CONFIG=3 BENCH_METHOD=IS_reg_S
    run_bench "$OUT/config3_ais" BENCH_CONFIG=3 BENCH_METHOD=AIS_Kriging_S
    run_bench "$OUT/config4_wrsmc" BENCH_CONFIG=4 BENCH_METHOD=WR_SMC
}

# A step that dies mid-run (tunnel wedge, timeout, watchdog exit 4) must be
# retried IN PRIORITY ORDER on the next pass, not abandoned: each pass
# re-walks the whole list (finished steps skip instantly), so a recovered
# tunnel always resumes from the highest-priority unfinished measurement.
for pass in 1 2 3 4 5 6 7 8 9 10; do
    UNFINISHED=0
    echo "$(date +%T) queue pass $pass"
    one_pass
    if [ "$UNFINISHED" -eq 0 ]; then
        rm -f "$BUSY"
        echo "$(date +%T) r5 queue complete: every step has its artifact"
        exit 0
    fi
    echo "$(date +%T) pass $pass ended with $UNFINISHED unfinished step(s); retrying"
    sleep 60
done
rm -f "$BUSY"
echo "$(date +%T) r5 queue giving up after 10 passes; unfinished steps remain"
exit 1
