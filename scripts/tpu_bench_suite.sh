#!/bin/bash
# Run the full BASELINE.md bench suite (configs 1-5) on the real TPU,
# waiting out tunnel outages: probe with a bounded jax.devices() before
# each config (the axon tunnel wedges for long stretches — see
# DESIGN_NOTES.md), re-probing every 5 min while it is down. Each config
# is bounded by `timeout` and runs with BENCH_CPU_FALLBACK=0 — a wedge
# mid-run aborts via bench.py's stall watchdog instead of emitting a
# misleading CPU-fallback metric. Outputs: $OUT/config<N>.json (the one
# metric line) and $OUT/config<N>.log (progress + throughput notes).
set -u
cd "$(dirname "$0")/.."
OUT=${OUT:-/tmp/bench_r3}
mkdir -p "$OUT"

probe() {
    timeout 90 python - <<'EOF'
import threading, sys
ok = []
def init():
    import jax
    ok.append(len(jax.devices()))
t = threading.Thread(target=init, daemon=True)
t.start(); t.join(75)
sys.exit(0 if ok else 1)
EOF
}

wait_for_tunnel() {
    until probe; do
        echo "$(date +%T) tunnel down; retrying in 300 s"
        sleep 300
    done
    echo "$(date +%T) tunnel up"
}

run_config() {
    local c=$1; shift
    wait_for_tunnel
    echo "$(date +%T) running config $c"
    timeout 5400 env BENCH_CPU_FALLBACK=0 BENCH_CONFIG="$c" "$@" \
        python bench.py > "$OUT/config$c.json" 2> "$OUT/config$c.log"
    local rc=$?   # before any command substitution clobbers $?
    echo "$(date +%T) config $c exit $rc: $(cat "$OUT/config$c.json")"
}

run_config 1 BENCH_PARTNERS=10   # the north star: 1023 coalitions
run_config 2
run_config 3
run_config 4
run_config 5
echo "$(date +%T) suite done"

# Compile-vs-padding tradeoff (VERDICT r3 #3): re-run the north star with
# power-of-two slot bucketing (4 compiled pipelines instead of 9) and with
# a second back-to-back run to measure whether the persistent compile
# cache reloads on TPU (cold-to-warm delta). Opt out with SKIP_EXTRAS=1.
if [ "${SKIP_EXTRAS:-0}" != "1" ]; then
    OUTBAK=$OUT
    OUT="$OUTBAK/pow2";  mkdir -p "$OUT"
    run_config 1 BENCH_PARTNERS=10 MPLC_TPU_SLOT_POW2=1
    OUT="$OUTBAK/warm";  mkdir -p "$OUT"
    run_config 1 BENCH_PARTNERS=10   # same-process-count rerun: warm cache?
    OUT=$OUTBAK
    echo "$(date +%T) extras done"
fi
