#!/usr/bin/env python
"""Merge a fleet run's per-shard span traces into ONE Perfetto timeline.

A W-shard fleet run (python -m mplc_tpu.parallel.fleet, or any
`run_fleet` caller) leaves in its out_dir:

    trace_coordinator.jsonl      the coordinator's span stream
    trace_shardI.jsonl           each worker's span stream (W files)
    result_shardI.json           worker results incl. the clock echo
    fleet_trace_manifest.json    coordinator spawn/done-seen timestamps

This script rebases every shard stream onto the coordinator clock
(midpoint rule over the 4-timestamp handshake — see
obs/fleet_view._clock_offset) and emits one Chrome trace-event JSON:
one track group (process) per shard, flow arrows from each
`fleet.shard` dispatch event to that shard's `fleet.shard_run` root
span. Load the output at https://ui.perfetto.dev.

Usage:
    python scripts/fleet_trace_merge.py OUT_DIR [-o fleet_trace.json]

Exits non-zero when the out_dir holds no shard streams.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from mplc_tpu.obs import fleet_view  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-shard fleet traces into one Perfetto "
                    "timeline")
    ap.add_argument("out_dir", help="fleet run output dir (holds "
                                    "trace_shardI.jsonl et al.)")
    ap.add_argument("-o", "--output", default=None,
                    help="merged trace path (default: "
                         "OUT_DIR/fleet_trace.json)")
    args = ap.parse_args(argv)
    merged = fleet_view.merge_fleet_traces(args.out_dir)
    if merged["shard_tracks"] == 0:
        print(f"[fleet-trace] no trace_shardI.jsonl streams found in "
              f"{args.out_dir}", file=sys.stderr)
        return 1
    out_path = args.output or os.path.join(args.out_dir,
                                           "fleet_trace.json")
    tmp = f"{out_path}.tmp"
    with open(tmp, "w") as f:
        json.dump(merged["trace"], f)
    os.replace(tmp, out_path)
    print(json.dumps({
        "out": out_path,
        "shard_tracks": merged["shard_tracks"],
        "flow_links": merged["flow_links"],
        "records": merged["records"],
        "clock_offsets_s": {k: round(v, 6)
                            for k, v in merged["offsets"].items()},
        "torn_lines": merged["torn_lines"],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
