#!/usr/bin/env python
"""Summarize a jax.profiler trace: device-busy fraction and top ops.

Usage: python scripts/analyze_trace.py <profile_dir_or_xplane.pb>

Loads the newest *.xplane.pb under the given directory with
jax.profiler.ProfileData and reports, per device plane:
  - the trace wall span (first event start -> last event end),
  - total XLA-op busy time and the busy fraction of the span,
  - the top ops by accumulated duration.

This quantifies VERDICT r3 weak #7: the bench's ">= X TFLOP/s" line is a
lower bound from XLA's cost model; the busy fraction here is the measured
answer to "where do the other ~96% of peak go" — on this workload the gap
is device idle (per-batch dispatch latency over the tunnel) plus tiny-op
overhead, not slow matmuls.

This tool reads XLA-level xplane traces only. For the SPAN-level view —
the engine's own dispatch/harvest/retry instrumentation recorded to
MPLC_TPU_TRACE_FILE — use scripts/trace_to_perfetto.py, which converts
the span JSONL into Chrome trace-event JSON loadable in Perfetto.
"""

import glob
import os
import sys
from collections import defaultdict


def newest_xplane(path):
    if os.path.isfile(path):
        return path
    hits = sorted(glob.glob(os.path.join(path, "**", "*.xplane.pb"),
                            recursive=True), key=os.path.getmtime)
    if not hits:
        raise SystemExit(f"no *.xplane.pb under {path}")
    return hits[-1]


def summarize(pb_path):
    import jax

    pd = jax.profiler.ProfileData.from_file(pb_path)
    print(f"trace: {pb_path}")
    for plane in pd.planes:
        if not plane.name.startswith("/device:"):
            continue
        for line in plane.lines:
            if line.name not in ("XLA Ops", "XLA Modules"):
                continue
            per_op = defaultdict(float)
            t_min, t_max, busy = None, None, 0.0
            n = 0
            for ev in line.events:
                start, dur = ev.start_ns, ev.duration_ns
                t_min = start if t_min is None else min(t_min, start)
                end = start + dur
                t_max = end if t_max is None else max(t_max, end)
                busy += dur
                per_op[ev.name] += dur
                n += 1
            if not n:
                continue
            span = t_max - t_min
            # span==0: a line holding one instantaneous event; busy>span:
            # overlapping async ops (the naive busy sum double-counts) —
            # flag both rather than print a bogus fraction as fact
            if span > 0:
                note = " [overlapping events: busy>span]" if busy > span else ""
                frac = f"{min(100 * busy / span, 100.0):.1f}% of span"
            else:
                note = ""
                frac = "busy fraction n/a: zero span"
            print(f"\n{plane.name} / {line.name}: {n} events, "
                  f"span {span / 1e9:.3f} s, busy {busy / 1e9:.3f} s "
                  f"({frac}){note}")
            if line.name == "XLA Ops":
                top = sorted(per_op.items(), key=lambda kv: -kv[1])[:12]
                for name, dur in top:
                    print(f"  {dur / 1e9:9.3f} s  {100 * dur / busy:5.1f}%  "
                          f"{name[:90]}")


if __name__ == "__main__":
    summarize(newest_xplane(sys.argv[1] if len(sys.argv) > 1 else "."))
