#!/usr/bin/env python
"""Measure coalitions-per-device batch width on the real chip.

The north-star sweep runs tiny-CNN training steps sequentially inside one
compiled program (80 scan steps/epoch of sub-batches <= ~128 samples) —
the chip is latency-bound, not FLOP-bound, so widening the vmapped
coalition batch should raise throughput almost linearly until the MXU or
HBM saturates. This times a fixed block of same-size coalitions at
several widths and prints s/coalition for each, steady-state (the block
is evaluated once to compile, then re-timed on a fresh engine sharing the
device data).

Usage: python scripts/tune_coalition_cap.py [--size 5] [--block 64]
       [--caps 16,32,64] [--partners 10] [--epochs 8]
"""

import argparse
import os
import sys
import time
from itertools import combinations, islice

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=5)
    ap.add_argument("--block", type=int, default=64)
    ap.add_argument("--caps", default="16,32,64")
    ap.add_argument("--partners", type=int, default=10)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--dataset", default=os.environ.get("BENCH_DATASET", "mnist"))
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args()

    # the sweep must measure the default engine path: ambient engine-mode
    # knobs would silently change what is being timed (the sharding tests
    # delenv these for the same reason)
    for knob in ("MPLC_TPU_PARTNER_SHARDS", "MPLC_TPU_NO_SLOTS",
                 "MPLC_TPU_SLOT_POW2", "MPLC_TPU_SLOT_MERGE",
                 "MPLC_TPU_PIPELINE_BATCHES", "MPLC_TPU_BATCH_CAP_CEILING"):
        if os.environ.pop(knob, None) is not None:
            print(f"[tune] ignoring ambient {knob}", file=sys.stderr)

    os.environ.setdefault("MPLC_TPU_SYNTH_NOISE", "0.75")
    import jax
    if os.environ.get("JAX_PLATFORMS"):
        # honor an explicit platform override — the axon sitecustomize pins
        # the config value at startup, so the env var alone is ignored
        # (same bootstrap as tests/conftest.py)
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.dirname(
                          os.path.abspath(__file__))), ".jax_cache"))
    import numpy as np

    import bench
    from mplc_tpu.contrib.engine import CharacteristicEngine

    caps = [int(c) for c in args.caps.split(",")]
    if args.size > args.partners:
        ap.error(f"--size {args.size} exceeds --partners {args.partners}")
    # a fair comparison needs zero padding at EVERY width: the engine pads
    # each batch to its bucket width and padded slots cost real training
    # compute, so the block must divide evenly by every swept cap
    import math
    lcm = math.lcm(*caps)
    block = -(-args.block // lcm) * lcm
    if block != args.block:
        print(f"block {args.block} -> {block} (multiple of lcm{tuple(caps)}="
              f"{lcm}, so no cap pays padding)", flush=True)

    sc = bench._make_scenario(args.dataset, args.partners, args.epochs, args.dtype)
    subsets = list(islice(combinations(range(args.partners), args.size), block))
    if len(subsets) < block:
        ap.error(f"only {len(subsets)} size-{args.size} coalitions exist for "
                 f"{args.partners} partners; need {block} for a padding-free "
                 "comparison — lower --block or --caps")
    results = {}
    shared = None
    for cap in caps:
        os.environ["MPLC_TPU_COALITIONS_PER_DEVICE"] = str(cap)
        warm = CharacteristicEngine(sc, share_data_from=shared)
        shared = shared or warm
        t0 = time.perf_counter()
        warm.evaluate(subsets)          # compile + first run
        compile_and_run = time.perf_counter() - t0
        timed = CharacteristicEngine(sc, share_data_from=shared)
        t0 = time.perf_counter()
        accs = timed.evaluate(subsets)  # steady state
        dt = time.perf_counter() - t0
        assert np.isfinite(accs).all()
        results[cap] = dt / len(subsets)
        print(f"cap={cap:3d}: {dt:6.1f} s for {len(subsets)} size-{args.size} "
              f"coalitions = {results[cap]:.3f} s/coalition "
              f"(compile+first: {compile_and_run:.0f} s)", flush=True)
    best = min(results, key=results.get)
    print(f"best cap: {best} ({results[best]:.3f} s/coalition)")


if __name__ == "__main__":
    main()
