#!/usr/bin/env python
"""Diff two value-provenance ledgers (obs/numerics.py, the numeric-truth
plane's artifact): per-subset ulp-distance histogram, max/percentile
drift, and the Kendall-tau of the induced v(S) ranking.

A ledger records every harvested v(S) with its EXACT float bits plus the
float path that produced it (topology, device count, reduction mode, slot
width, OOM rungs), keyed by (subset bitmask, engine fingerprint). Diffing
two ledgers answers the question the 2-D shard_map drift sat on for ten
PRs: did two runs — different topologies, device counts, toolchains —
compute the SAME game, bit for bit, and if not, by how much and does the
drift flip the value ranking (the correctness stake per "On the
Volatility of Shapley-Based Contribution Metrics", PAPERS.md).

Usage:
    python scripts/drift_diff.py A.json B.json [--json] [--gate]

Exit codes: 0 = comparable and zero drift (or --gate not set and the
ledgers merely differ), 1 = --gate set and drift detected, 2 = usage /
unreadable ledger / fingerprint mismatch (different games are not drift
— they are a comparison error) / zero common subsets (a gate that
compared nothing must not read green).

Same-seed self-test contract (tests/test_numerics.py): two ledgers from
identical runs diff to zero drift, max_ulp 0, tau 1.0.
"""

from __future__ import annotations

import argparse
import json
import sys


def format_diff(res: dict, label_a: str, label_b: str) -> str:
    lines = [f"ledger drift: {label_a} vs {label_b}"]
    ma, mb = res.get("meta_a", {}), res.get("meta_b", {})
    lines.append(
        f"  float paths: a=({ma.get('topology')}, part={ma.get('part_shards')}, "
        f"dev={ma.get('n_devices')}, {ma.get('reduction_mode')})  "
        f"b=({mb.get('topology')}, part={mb.get('part_shards')}, "
        f"dev={mb.get('n_devices')}, {mb.get('reduction_mode')})")
    if not res["same_fingerprint"]:
        lines.append("  ! engine fingerprints differ — these ledgers "
                     "describe DIFFERENT GAMES, not drift")
        return "\n".join(lines)
    u = res["ulp"]
    lines.append(
        f"  subsets: common={res['common']}  only_a={res['only_a']}  "
        f"only_b={res['only_b']}")
    lines.append(
        f"  ulp drift: max={u['max']}  p99={u['p99']}  p50={u['p50']}  "
        f"nonzero={u['nonzero']}/{res['common']}")
    if res["histogram"]:
        buckets = sorted(res["histogram"].items(),
                         key=lambda kv: (kv[0] != "0", kv[0]))
        lines.append("  histogram: "
                     + "  ".join(f"{k}:{v}" for k, v in buckets))
    tau = res["kendall_tau"]
    lines.append("  ranking kendall-tau: "
                 + (f"{tau:.4f}" if tau is not None else "n/a"))
    if not res["common"]:
        lines.append("  NOTHING COMPARED — no common subsets")
    elif not res["drift"]:
        lines.append("  ZERO DRIFT — bit-identical values")
    else:
        lines.append("  DRIFT DETECTED")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Diff two value-provenance ledgers "
                    "(per-subset ulp drift + ranking tau).")
    ap.add_argument("ledger_a")
    ap.add_argument("ledger_b")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw diff dict as JSON")
    ap.add_argument("--gate", action="store_true",
                    help="exit 1 when any common subset's value bits "
                         "differ")
    args = ap.parse_args(argv)

    from mplc_tpu.obs import numerics

    try:
        a = numerics.ValueLedger.load(args.ledger_a)
        b = numerics.ValueLedger.load(args.ledger_b)
    except (OSError, ValueError, KeyError) as e:
        print(f"[drift_diff] error: {e}", file=sys.stderr)
        return 2
    res = numerics.diff_ledgers(a, b)
    if args.json:
        print(json.dumps(res, indent=2))
    else:
        print(format_diff(res, args.ledger_a, args.ledger_b))
    if not res["same_fingerprint"]:
        print("[drift_diff] error: fingerprint mismatch — different "
              "games cannot be drift-compared", file=sys.stderr)
        return 2
    if not res["common"]:
        # same game but ZERO overlapping subsets: the diff compared
        # nothing, and a gate that compared nothing must not read green
        # (same invariant as bench_diff's dir-mode exit 2)
        print("[drift_diff] error: ledgers share no common subsets — "
              "nothing was compared", file=sys.stderr)
        return 2
    if args.gate and res["drift"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    sys.exit(main())
