#!/usr/bin/env python
"""Batch-granular v5e-8 projection for the north-star sweep.

VERDICT r4 weak #1: dividing single-chip wall-clock by 8 assumes every
batch splits 8 ways for free, but the 8-device engine schedules *fewer,
wider* buckets and per-size rounding leaves tail batches narrow. This
script replaces wall-clock/8 with a simulation of the actual 8-device
bucket schedule (mirroring contrib/engine.py::_bucket_size and
_run_batch's one-width-per-call grouping), where every input is a
measurement:

  - per-(slot-size, width-16) batch times parsed from a single-chip
    config1.log (the "[bench] timed:" progress lines);
  - a width-scaling factor r(w) = t_batch(w) / t_batch(16) fitted as
    t(w) = a*w + c to scripts/tune_coalition_cap.py output at widths
    1/2/4/8/16 (width_curve.log). Until that file exists, the script
    brackets with the two priors instead: pure-linear (a>0, c=0 — the
    optimistic wall-clock/8 regime) and latency-flat (a=0 — the
    pessimistic DESIGN_NOTES hypothesis).

Usage:
  python scripts/project_v5e8.py [--log perf/r4/config1.log]
      [--curve perf/r5/width_curve.log] [--ndev 8] [--cap 16]
      [--partners 10] [--pow2]
"""

import argparse
import math
import os
import re
from math import comb


def bucket_size(n: int, n_dev: int, cap_per_dev: int) -> int:
    """Mirror of mplc_tpu/contrib/engine.py::_bucket_size."""
    cap = n_dev * cap_per_dev
    b = n_dev
    while b < min(n, cap):
        b *= 2
    return min(b, cap)


def parse_batch_times(log_path):
    """Per-slot-size batch durations (s) from the timed progress lines.

    Returns {slot_count_or_None: [durations]}, plus the width each size ran
    at (all batches of one evaluate() call share one bucket width)."""
    pat = re.compile(r"\[bench\] timed: \+(\d+) coalitions \(slots=(\w+), "
                     r"total \d+, \d+ left in call\) t=(\d+)s")
    rows = []
    with open(log_path) as f:
        for line in f:
            m = pat.search(line)
            if m:
                n, slots, t = m.groups()
                rows.append((int(n),
                             None if slots == "None" else int(slots), int(t)))
    if not rows:
        raise SystemExit(f"no timed progress lines in {log_path}")
    times = {}
    prev_t = 0
    for n, slots, t in rows:
        times.setdefault(slots, []).append(t - prev_t)
        prev_t = t
    return times


def parse_width_curve(curve_path):
    """(width, per-batch seconds) pairs from tune_coalition_cap.py output:
    `cap= 16:  123.4 s for 48 size-5 coalitions = 2.571 s/coalition ...`
    Per-batch time at width w = (s/coalition) * w."""
    pat = re.compile(r"cap=\s*(\d+):\s*([\d.]+) s for (\d+) size-\d+ "
                     r"coalitions = ([\d.]+) s/coalition")
    pts = []
    with open(curve_path) as f:
        for line in f:
            m = pat.search(line)
            if m:
                w, total, block, per_coal = m.groups()
                pts.append((int(w), float(total) / (int(block) / int(w))))
    return sorted(pts)


def fit_affine(pts):
    """Least-squares t(w) = a*w + c over the measured (w, t_batch) points."""
    n = len(pts)
    sw = sum(w for w, _ in pts)
    st = sum(t for _, t in pts)
    sww = sum(w * w for w, _ in pts)
    swt = sum(w * t for w, t in pts)
    denom = n * sww - sw * sw
    a = (n * swt - sw * st) / denom
    c = (st - a * sw) / n
    return a, c


def schedule(n_partners, n_dev, cap, pow2):
    """The 8-device bucket schedule: [(slot_width, batch_width, count)].
    Mirrors engine.evaluate: singles in one call, then one call per slot
    bucket (per size, or per pow2-width group)."""
    out = []
    b = bucket_size(min(n_partners, n_dev * cap), n_dev, cap)
    out.append((1, b, math.ceil(n_partners / b)))
    if pow2:
        groups = {}
        for k in range(2, n_partners + 1):
            w = min(1 << (k - 1).bit_length(), n_partners)
            groups[w] = groups.get(w, 0) + comb(n_partners, k)
    else:
        groups = {k: comb(n_partners, k) for k in range(2, n_partners + 1)}
    for slot_w in sorted(groups):
        n = groups[slot_w]
        b = bucket_size(min(n, n_dev * cap), n_dev, cap)
        out.append((slot_w, b, math.ceil(n / b)))
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--log", default="perf/r4/config1.log")
    ap.add_argument("--curve", default="perf/r5/width_curve.log")
    ap.add_argument("--ndev", type=int, default=8)
    ap.add_argument("--cap", type=int, default=16)
    ap.add_argument("--partners", type=int, default=10)
    ap.add_argument("--pow2", action="store_true")
    args = ap.parse_args()

    times = parse_batch_times(args.log)

    # representative width-16 batch time per slot size (median over the
    # size's batches; every batch of a call is padded to the same width)
    def median(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2]

    t16 = {}
    for slots, ds in times.items():
        k = 1 if slots is None else slots
        t16[k] = float(median(ds))

    # size-10 ran width-1 single-chip (1 coalition); sizes 2..9 + singles
    # ran width-16. Models below re-express t16[10] from its width-1 point.
    t10_w1 = t16.get(10)

    models = {}
    pts = parse_width_curve(args.curve) if os.path.exists(args.curve) else []
    if len(pts) >= 2:  # one point (a wedge-truncated log) can't fit a line
        a, c = fit_affine(pts)
        t_16 = a * 16 + c
        models["measured-affine"] = lambda w, a=a, c=c, t=t_16: (a * w + c) / t
        print(f"width curve {args.curve}: t_batch(w) = {a:.3f}*w + {c:.3f} s "
              f"(points: {pts})")
    else:
        print(f"no usable width curve at {args.curve} (need >= 2 points, "
              f"have {len(pts)}) — bracketing with priors")
    models["linear(optimistic)"] = lambda w: w / 16.0
    models["flat(pessimistic)"] = lambda w: 1.0

    sched = schedule(args.partners, args.ndev, args.cap, args.pow2)
    mode = "pow2" if args.pow2 else "per-size"
    print(f"\nschedule ({mode}, ndev={args.ndev}, cap={args.cap}): "
          f"(slot_width, batch_width, n_batches) = {sched}")

    for name, r in models.items():
        total = 0.0
        rows = []
        for slot_w, b, nb in sched:
            per_dev_w = b / args.ndev
            if slot_w in t16 and (slot_w != 10 or t10_w1 is None):
                base = t16[slot_w]
            elif slot_w == 10 and t10_w1 is not None:
                # measured at width 1; re-express at width 16 via r
                base = t10_w1 * r(16) / max(r(1), 1e-9)
            else:
                # pow2 width with no measured size (can't happen for n=10:
                # widths {2,4,8,10} are all measured sizes)
                base = t16[min(t16, key=lambda k: abs(k - slot_w))]
            bt = base * r(per_dev_w) / r(16)
            total += bt * nb
            rows.append(f"  slots={slot_w:2d} width/dev={per_dev_w:5.1f} "
                        f"batches={nb} t/batch={bt:6.1f}s  sum={bt * nb:7.1f}s")
        print(f"\n[{name}] projected {args.partners}-partner sweep on "
              f"{args.ndev} devices: {total:.0f} s")
        for row in rows:
            print(row)


if __name__ == "__main__":
    main()
