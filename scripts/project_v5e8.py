#!/usr/bin/env python
"""Batch-granular v5e-8 projection for the north-star sweep.

VERDICT r4 weak #1: dividing single-chip wall-clock by 8 assumes every
batch splits 8 ways for free, but the 8-device engine schedules *fewer,
wider* buckets and per-size rounding leaves tail batches narrow. This
script replaces wall-clock/8 with a simulation of the actual 8-device
bucket schedule (mirroring contrib/engine.py::_bucket_size and
_run_batch's one-width-per-call grouping), where every input is a
measurement:

  - per-(slot-size, width-16) batch times parsed from a single-chip
    config1.log (the "[bench] timed:" progress lines);
  - a width-scaling factor r(w) = t_batch(w) / t_batch(16) fitted as
    t(w) = a*w + c to scripts/tune_coalition_cap.py output at widths
    1/2/4/8/16 (width_curve.log). Until that file exists, the script
    brackets with the two priors instead: pure-linear (a>0, c=0 — the
    optimistic wall-clock/8 regime) and latency-flat (a=0 — the
    pessimistic DESIGN_NOTES hypothesis).

Usage:
  python scripts/project_v5e8.py [--log perf/r4/config1.log]
      [--curve perf/r5/width_curve.log] [--ndev 8] [--cap 16]
      [--partners 10] [--pow2 | --merge]
      [--telemetry perf/telemetry_config1.json]

--log also accepts a structured JSONL trace (MPLC_TPU_TRACE_FILE): batch
durations then come from measured engine.batch spans instead of progress-
line differencing, and --telemetry prints a sweep's measured
prep/dispatch/harvest split (the engine.prep row) next to the projection.
"""

import argparse
import math
import os
import re
from math import comb


def bucket_size(n: int, n_dev: int, cap_per_dev: int) -> int:
    """Mirror of mplc_tpu/contrib/engine.py::_bucket_size."""
    cap = n_dev * cap_per_dev
    b = n_dev
    while b < min(n, cap):
        b *= 2
    return min(b, cap)


def _call_groups(rows):
    """Group progress rows into engine batch calls and mark evaluate()
    boundaries. The 'left in call' counter reaches 0 at the end of every
    _run_batch call (one slot bucket); a trailing incomplete call (wedge
    mid-run) is dropped. Inside ONE engine.evaluate() the bucket calls run
    back-to-back in ascending slot order (singles first), so a call whose
    slot order does NOT increase over its predecessor's starts a new
    evaluate() — the host gap before it (estimator code, sampler refits,
    Kriging fits) is host time, not batch time. Yields (call_rows,
    starts_new_evaluate). The log's first call is anchored at t=0 (the
    progress timer starts right before the first evaluate), so it is not
    a boundary.

    Known blind spot: an evaluate() that begins at a STRICTLY larger slot
    size than the previous call's last bucket is indistinguishable from an
    intra-evaluate transition in the log, so that boundary is missed and
    its first batch keeps the old cross-call delta. In practice IS/MC
    blocks nearly always re-request the small sizes first (size 2/3
    pairs), so the missed case is rare; fixing it for good needs an
    explicit evaluate-id in the progress line."""
    calls = []
    cur = []
    for r in rows:
        cur.append(r)
        if r[2] == 0:
            calls.append(cur)
            cur = []
    prev_order = None
    for call in calls:
        order = 1 if call[0][1] is None else call[0][1]
        yield call, (prev_order is not None and order <= prev_order)
        prev_order = order


def parse_trace_records(path):
    """Records from a structured JSONL trace (MPLC_TPU_TRACE_FILE);
    malformed lines (a truncated tail from a wedge mid-write) are
    skipped, not fatal."""
    import json
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue
    return records


def parse_trace_batch_times(path):
    """{slot_count_or_None: [durations]} from a JSONL trace's engine.batch
    events. Strictly better input than the progress-line deltas: each
    event's `dur` is a measured dispatch-start -> harvest-end span, so no
    prev_t differencing — and therefore no reset-at-boundary rule — is
    needed; cross-evaluate host gaps (estimator code, Kriging refits) can
    never pollute a cell by construction. Under batch pipelining
    consecutive spans overlap (a utilization view), which medians absorb
    the same way they absorb residual-compile outliers."""
    times = {}
    for rec in parse_trace_records(path):
        if rec.get("name") != "engine.batch":
            continue
        a = rec.get("attrs") or {}
        slots = a.get("slot_count")
        times.setdefault(slots, []).append(float(rec.get("dur") or 0.0))
    return times


def parse_trace_split(path):
    """The prep/dispatch/harvest wall-clock split summed from a JSONL
    trace — the measured view of the host-side dispatch gap the sweep
    fusion work attacks."""
    split = {"evaluate_s": 0.0, "prep_s": 0.0, "dispatch_s": 0.0,
             "harvest_s": 0.0}
    for rec in parse_trace_records(path):
        key = {"engine.evaluate": "evaluate_s", "engine.prep": "prep_s",
               "engine.dispatch": "dispatch_s",
               "engine.harvest": "harvest_s"}.get(rec.get("name"))
        if key:
            split[key] += float(rec.get("dur") or 0.0)
    return split


def _telemetry_row(path, key):
    """One row of a bench telemetry sidecar's report — the shared loader
    behind every load_telemetry_* accessor. Rows absent from older report
    schemas (or from runs that don't produce them) load as {} rather than
    failing, so old perf artifacts keep working."""
    import json
    with open(path) as f:
        rec = json.load(f)
    return dict(rec.get("report", {}).get(key, {}) or {})


def load_telemetry_split(path):
    """The wall-clock split from a bench telemetry sidecar
    (perf/telemetry_config<N>.json). Pre-prep-span sidecars (older report
    schema) load with prep_s = 0 rather than failing."""
    w = _telemetry_row(path, "wallclock")
    w.setdefault("prep_s", 0.0)
    return w


def load_telemetry_compute(path):
    """The compute/MFU-proxy row — the measured intensity the
    projection's width-scaling assumptions rest on."""
    return _telemetry_row(path, "compute")


def load_telemetry_resilience(path):
    """The resilience row: retries, OOM cap halvings, CPU-degraded
    batches. A projection fed by a degraded run's numbers is projecting
    the DEGRADED schedule — the printout flags it."""
    return _telemetry_row(path, "resilience")


def load_telemetry_trust(path):
    """The seed-ensemble trust row (per-partner Shapley CIs + Kendall-tau
    rank stability); single-seed runs have no row and load as {}."""
    return _telemetry_row(path, "trust")


def load_telemetry_reconstruction(path):
    """The retrain-free reconstruction row (GTG-Shapley/SVARM runs):
    recorded-update memory, reconstructions/s, and the train-vs-eval pass
    split; retraining-only runs (and pre-reconstruction schemas) load as
    {}."""
    return _telemetry_row(path, "reconstruction")


def load_telemetry_hbm(path):
    """The hbm row (buffer donation, PR 8): modeled per-coalition HBM,
    the donation saving, and the coalition-cap autotune before vs after
    donation. Pre-donation sidecars load as {}."""
    return _telemetry_row(path, "hbm")


def load_telemetry_service(path):
    """The multi-tenant service row (BENCH_CONFIG=6): job outcomes,
    cross-tenant packed batches, and per-tenant fair-share cost
    attribution. Single-tenant runs (and pre-service schemas) load as
    {}."""
    return _telemetry_row(path, "service")


def load_telemetry_live(path):
    """The live contributivity row (BENCH_CONFIG=8): query/memo-hit
    counts, reconstruction evaluations, DPVS-pruned coalitions and
    fresh-query latency quantiles. Batch-only runs (and pre-live
    schemas) load as {}."""
    return _telemetry_row(path, "live")


def _telemetry_block(path, key):
    """A TOP-LEVEL sidecar block (alongside `numerics`/`fleet`) — unlike
    `_telemetry_row`, not nested under `report`. Absent blocks (older
    sidecars, fp32/scan runs that produce none) load as {}."""
    import json
    with open(path) as f:
        rec = json.load(f)
    return dict(rec.get(key) or {})


def load_telemetry_precision(path):
    """The mixed-precision block (non-fp32 runs, ISSUE 17): the fp32
    reference twin's executed seconds + the ledger-pair tau-b/ulp
    evidence that licenses the speed mode. fp32 runs load as {}."""
    return _telemetry_block(path, "precision")


def load_telemetry_recon(path):
    """The reconstruction-kernel block (BENCH_CONFIG=8): the resolved
    scan-vs-kernel path and the fresh-query latency bench_diff gates as
    `recon.kernel_query_s`. Pre-kernel sidecars load as {}."""
    return _telemetry_block(path, "recon")


def load_measured_fleet(path):
    """The measured fleet-scaling sidecar (BENCH_CONFIG=9,
    perf/telemetry_config9.json): {} when the sidecar is absent, invalid
    or carries no fleet points. The PRECEDENCE RULE lives on this
    accessor: when a measured curve exists, main() prints it and marks
    the pinned 280-300 s projection SUPERSEDED (pins kept above for
    comparison); when it doesn't, the projection stands and says so."""
    import json
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return {}
    fl = rec.get("fleet") or {}
    if not fl.get("points"):
        return {}
    return {"metric": rec.get("metric"),
            "wallclock_s": rec.get("wallclock_s"),
            "devices": rec.get("devices"), **fl}


def format_measured_fleet(measured, path):
    """The measured-vs-projected printout (shared with the test pin)."""
    lines = [f"MEASURED fleet scaling (BENCH_CONFIG=9 sidecar {path}, "
             f"provenance={measured.get('provenance')}, "
             f"basis={measured.get('scaling_basis')}):"]
    for p in measured["points"]:
        sp = p.get("speedup_vs_1")
        lines.append(
            f"  devices={p['devices']:2d} shards={p['shards']} "
            f"wall={p['fleet_wallclock_s']:.1f}s speedup_vs_1="
            + (f"{sp:.2f}x" if sp else "n/a"))
    eq = measured.get("equality") or {}
    if eq:
        lines.append(
            f"  equality: {eq.get('shards')}-shard merged ledger vs "
            f"1-shard drift={eq.get('drift')} "
            f"max_ulp={(eq.get('ulp') or {}).get('max')} "
            f"tau={eq.get('kendall_tau')}")
    note = ""
    if measured.get("provenance") == "cpu_mesh":
        note = ("; cpu_mesh provenance — a host-CPU mesh measurement, "
                "not a TPU number")
    lines.append(
        "  >>> the pinned 280-300 s v5e-8 PROJECTION above is SUPERSEDED "
        "by this measured wall-clock-vs-shards curve (projection pins "
        f"kept above for comparison{note})")
    return "\n".join(lines)


def parse_batch_times(log_path):
    """Per-slot-size batch durations (s), from either input kind:

    - a `*.jsonl` structured trace -> parse_trace_batch_times (measured
      per-batch spans, no differencing);
    - a bench stderr log -> the timed progress lines below. All batches of
      one evaluate() call share one bucket width. prev_t resets at
      evaluate() boundaries: the first batch after a boundary absorbs
      inter-call host/compile time, so its duration is unknowable from the
      log and it contributes no sample (ADVICE r5)."""
    if str(log_path).endswith(".jsonl"):
        times = parse_trace_batch_times(log_path)
        if not times:
            raise SystemExit(f"no engine.batch events in {log_path}")
        return times
    rows = parse_timed_rows(log_path)
    if not rows:
        raise SystemExit(f"no timed progress lines in {log_path}")
    times = {}
    prev_t = 0
    for call, boundary in _call_groups(rows):
        for idx, (_n, slots, _left, t) in enumerate(call):
            if idx == 0 and boundary:
                prev_t = t  # reset: the cross-evaluate gap is not batch time
                continue
            times.setdefault(slots, []).append(t - prev_t)
            prev_t = t
    return times


_TIMED_ROW = re.compile(r"\[bench\] timed: \+(\d+) coalitions \(slots=(\w+), "
                        r"total \d+, (\d+) left in call\) t=(\d+)s")


def parse_timed_rows(log_path):
    """Shared row parser for the '[bench] timed:' progress lines:
    yields (n_coalitions, slots_or_None, left_in_call, cumulative_t)."""
    rows = []
    with open(log_path) as f:
        for line in f:
            m = _TIMED_ROW.search(line)
            if m:
                n, slots, left, t = m.groups()
                rows.append((int(n),
                             None if slots == "None" else int(slots),
                             int(left), int(t)))
    return rows


def parse_is_log_ratios(log_path, record_cap=16):
    """Width-scaling ratio points mined from an IS-workload bench log
    (e.g. perf/r4/config3_attempt1_wedged.log). IS evaluate() calls have
    varying missing-counts, so their batches ran at bucket widths
    1/2/4/8/16 across slot sizes — a free width-scaling dataset. The
    FIRST occurrence of each (slots, width) program pays its residual
    compile (warm-up only compiles one width per size), so only
    steady-state repeats count. `record_cap` must be the cap the MINED
    run used (it determines the recorded bucket widths — independent of
    the --cap being projected). Returns (w, t(k,w)/t(k, w_max)) ratio
    points pooled over slot sizes k that have a full-width cell, with
    w_max = the mined run's single-device full width.

    prev_t resets at evaluate() boundaries (_call_groups): a batch whose
    delta spans host-side estimator work between evaluate() calls would
    otherwise pollute its steady-state cell — the IS workload's narrow
    (width 1/2) buckets are single-batch calls, exactly the cells where a
    host gap dwarfs the real batch time (ADVICE r5). The per-cell
    first-occurrence drop below still excludes residual compiles that land
    mid-evaluate (the first batch of a new (slots, width) program)."""
    rows = parse_timed_rows(log_path)
    w_max = bucket_size(record_cap, 1, record_cap)
    durs = {}
    prev_t = 0
    for call, boundary in _call_groups(rows):
        call_total = sum(r[0] for r in call)
        b = bucket_size(call_total, 1, record_cap)
        for idx, r in enumerate(call):
            if idx == 0 and boundary:
                prev_t = r[3]  # reset: cross-evaluate host gap excluded
                continue
            durs.setdefault((r[1], b), []).append(r[3] - prev_t)
            prev_t = r[3]
    steady = {kw: sum(ds[1:]) / len(ds[1:])
              for kw, ds in durs.items() if len(ds) > 1 and kw[0] is not None}
    pts = []
    for (k, w), t in sorted(steady.items()):
        t_full = steady.get((k, w_max))
        if t_full and w != w_max:
            pts.append((w, t / t_full))
    return pts, steady


def parse_width_curve(curve_path):
    """(width, per-batch seconds) pairs from tune_coalition_cap.py output:
    `cap= 16:  123.4 s for 48 size-5 coalitions = 2.571 s/coalition ...`
    Per-batch time at width w = (s/coalition) * w."""
    pat = re.compile(r"cap=\s*(\d+):\s*([\d.]+) s for (\d+) size-\d+ "
                     r"coalitions = ([\d.]+) s/coalition")
    pts = []
    with open(curve_path) as f:
        for line in f:
            m = pat.search(line)
            if m:
                w, total, block, per_coal = m.groups()
                pts.append((int(w), float(total) / (int(block) / int(w))))
    return sorted(pts)


def fit_affine(pts):
    """Least-squares t(w) = a*w + c over the measured (w, t_batch) points."""
    n = len(pts)
    sw = sum(w for w, _ in pts)
    st = sum(t for _, t in pts)
    sww = sum(w * w for w, _ in pts)
    swt = sum(w * t for w, t in pts)
    denom = n * sww - sw * sw
    a = (n * swt - sw * st) / denom
    c = (st - a * sw) / n
    return a, c


def schedule(n_partners, n_dev, cap, pow2, merge=False):
    """The 8-device bucket schedule: [(slot_width, batch_width, count)].
    Mirrors engine.evaluate: singles in one call, then one call per slot
    bucket (per size, per merged adjacent-size pair, or per pow2-width
    group — engine._slot_width)."""
    out = []
    b = bucket_size(min(n_partners, n_dev * cap), n_dev, cap)
    out.append((1, b, math.ceil(n_partners / b)))
    if pow2:
        groups = {}
        for k in range(2, n_partners + 1):
            w = min(1 << (k - 1).bit_length(), n_partners)
            groups[w] = groups.get(w, 0) + comb(n_partners, k)
    elif merge:
        groups = {}
        for k in range(2, n_partners + 1):
            w = min(k + (k % 2 == 0), n_partners)
            groups[w] = groups.get(w, 0) + comb(n_partners, k)
    else:
        groups = {k: comb(n_partners, k) for k in range(2, n_partners + 1)}
    for slot_w in sorted(groups):
        n = groups[slot_w]
        b = bucket_size(min(n, n_dev * cap), n_dev, cap)
        out.append((slot_w, b, math.ceil(n / b)))
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--log", default="perf/r4/config1.log")
    ap.add_argument("--curve", default="perf/r5/width_curve.log")
    ap.add_argument("--islog", default="perf/r4/config3_attempt1_wedged.log",
                    help="IS-workload log to mine steady-state width ratios "
                         "from ('' disables)")
    ap.add_argument("--islog-cap", type=int, default=16,
                    help="the coalition cap the MINED run used (sets its "
                         "recorded bucket widths; independent of --cap)")
    ap.add_argument("--ndev", type=int, default=8)
    ap.add_argument("--cap", type=int, default=16)
    ap.add_argument("--partners", type=int, default=10)
    ap.add_argument("--pow2", action="store_true")
    ap.add_argument("--merge", action="store_true",
                    help="schedule with merged adjacent slot sizes "
                         "(MPLC_TPU_SLOT_MERGE, the engine default)")
    ap.add_argument("--telemetry", default="",
                    help="bench telemetry sidecar (telemetry_config<N>.json)"
                         " — prints the measured prep/dispatch/harvest split")
    ap.add_argument("--fleet-telemetry",
                    default="perf/telemetry_config9.json",
                    help="measured fleet-scaling sidecar (BENCH_CONFIG=9); "
                         "when it exists the measured curve is printed and "
                         "the pinned projection marked superseded "
                         "('' disables the check)")
    args = ap.parse_args()

    if args.telemetry:
        if not os.path.exists(args.telemetry):
            raise SystemExit(f"no telemetry sidecar at {args.telemetry}")
        w = load_telemetry_split(args.telemetry)
        gap = w.get("evaluate_s", 0.0) - w["prep_s"] \
            - w.get("dispatch_s", 0.0) - w.get("harvest_s", 0.0)
        print(f"measured split {args.telemetry}: "
              f"evaluate={w.get('evaluate_s', 0.0):.1f}s "
              f"prep={w['prep_s']:.1f}s "
              f"dispatch={w.get('dispatch_s', 0.0):.1f}s "
              f"harvest={w.get('harvest_s', 0.0):.1f}s "
              f"(other host gap ~{gap:.1f}s)")
        c = load_telemetry_compute(args.telemetry)
        if c.get("train_samples"):
            fps = c.get("model_flops_per_s")
            mfu = c.get("mfu_proxy")
            # same T/G/M scale ladder as obs.report.format_report, so a
            # CPU-mesh sidecar prints MFLOP/s instead of 0.000T
            fps_txt = ("" if not fps else
                       " model_flops/s=" +
                       (f"{fps / 1e12:.2f}T" if fps >= 1e12 else
                        f"{fps / 1e9:.2f}G" if fps >= 1e9 else
                        f"{fps / 1e6:.2f}M"))
            print(f"measured compute: samples={c['train_samples']} "
                  f"partner_passes={c.get('partner_passes', 0)}" + fps_txt
                  + (f" mfu_proxy={100 * mfu:.2f}%" if mfu is not None
                     else " mfu_proxy=n/a")
                  + " — the per-step intensity the width-scaling model "
                    "assumes; projection band unchanged by this row")
        r = load_telemetry_resilience(args.telemetry)
        if r.get("retries") or r.get("cap_halvings") or r.get("cpu_batches"):
            print(f"measured resilience: retries={r.get('retries', 0)} "
                  f"cap_halvings={r.get('cap_halvings', 0)} "
                  f"cpu_batches={r.get('cpu_batches', 0)} — DEGRADED run: "
                  "its batch times mix recovery overhead (and possibly the "
                  "CPU rung) into the device schedule; prefer a clean "
                  "sidecar for projection")
        rc = load_telemetry_reconstruction(args.telemetry)
        if rc.get("reconstructions") or rc.get("recording_partner_passes"):
            mem = rc.get("recorded_update_bytes")
            rps = rc.get("reconstructions_per_s")
            # train_partner_passes is the run's GLOBAL training total: in
            # a mixed run (e.g. exact Shapley + GTG) it includes the
            # retraining estimators' passes, so the recording run's own
            # cost is reported from its dedicated field
            rec_p = rc.get("recording_partner_passes") or 0
            tot_p = rc.get("train_partner_passes") or 0
            passes = f" training_passes={rec_p} (recording run)"
            if tot_p > rec_p:
                passes += (f" + {tot_p - rec_p} from retraining "
                           "estimators in the same run")
            print("measured reconstruction: "
                  f"rounds={rc.get('recorded_rounds') or '?'} "
                  "update_mem="
                  + (f"{mem / 1e6:.1f}MB" if mem is not None else "n/a")
                  + f" reconstructions={rc.get('reconstructions', 0)}"
                  + " recons/s="
                  + (f"{rps:.1f}" if rps is not None else "n/a")
                  + passes + " eval_batches="
                  + str(rc.get('recon_batches', 0)))
            P = rc.get("recorded_partners")
            rounds = rc.get("recorded_rounds")
            if P and rounds:
                # projected exact-vs-GTG from the recorded pass counters:
                # the exact sweep trains every coalition (slot execution:
                # |S| passes per round), GTG trains ONLY the recording
                # run. Both sides use the MEASURED recording cost as the
                # rounds basis (rec_p < P x rounds under early stopping;
                # the projection assumes coalitions stop like the grand
                # run did) so this line agrees with the measured
                # training_passes printed above.
                gtg_passes = rec_p or P * rounds
                exact_passes = sum(comb(P, k) * k
                                   for k in range(1, P + 1)) \
                    * gtg_passes // P
                print(f"projected exact-vs-GTG at P={P}: exact sweep "
                      f"~{exact_passes} training partner passes vs GTG "
                      f"recording {gtg_passes} "
                      f"({exact_passes / gtg_passes:.0f}x fewer; projected "
                      "training wall-clock ~= exact band / that factor, "
                      "plus the eval-only reconstruction time above — "
                      "reconstruction batches are training-free)")
        h = load_telemetry_hbm(args.telemetry)
        if h.get("per_coalition_bytes"):
            # the donation/HBM view: the projected schedule's bucket
            # widths assume the measured run's coalition cap — a cap that
            # rises with donation on (cap_after_donation), so a
            # donation-off sidecar projects a narrower schedule than the
            # engine now runs
            per = h["per_coalition_bytes"]
            saved = h.get("donated_bytes_per_coalition") or 0
            print(f"measured hbm: per_coalition={per / 1e6:.1f}MB "
                  f"donation={'on' if h.get('donation') else 'off'} "
                  f"saving={saved / 1e6:.1f}MB/coalition "
                  f"cap {h.get('cap_before_donation', '?')}->"
                  f"{h.get('cap_after_donation', '?')} "
                  f"(effective {h.get('cap_effective', '?')}) — widths in "
                  "the schedule below assume the effective cap")
        svc = load_telemetry_service(args.telemetry)
        if svc.get("jobs"):
            # multi-tenant service sidecars: whether the cross-tenant
            # program packing actually fired (packed=0 on a two-tenant
            # run means the shapes differed and every tenant compiled its
            # own programs — a projection from it overstates the
            # steady-state multi-tenant rate), plus how the measured
            # span-seconds split across tenants
            shares = ", ".join(
                f"{name}={100 * (t.get('cost_share') or 0):.0f}%"
                for name, t in (svc.get("per_tenant") or {}).items())
            print(f"measured service: jobs={svc['jobs']} "
                  f"completed={svc.get('completed', 0)} "
                  f"quarantined={svc.get('quarantined', 0)} "
                  f"cancelled={svc.get('cancelled', 0)} "
                  f"packed_batches={svc.get('cross_tenant_packed_batches', 0)}"
                  + (f" cost_share[{shares}]" if shares else "")
                  + " — multi-tenant run: per-batch times below include "
                    "scheduler slicing and per-value journal fsyncs")
        lv = load_telemetry_live(args.telemetry)
        if lv.get("queries"):
            # live-tier sidecars (BENCH_CONFIG=8): sub-second-query
            # evidence — fresh-query latency vs the memoized warm path,
            # and how much DPVS pruning cut the evaluation schedule. A
            # projection from a live sidecar describes QUERY latency, not
            # sweep throughput.
            q = lv.get("query_s") or {}
            p50 = q.get("p50")
            print(f"measured live: queries={lv['queries']} "
                  f"memo_hits={lv.get('memo_hits', 0)} "
                  f"evaluations={lv.get('evaluations', 0)} "
                  f"pruned={lv.get('pruned_coalitions', 0)} "
                  f"rounds={lv.get('rounds_resident', '?')} "
                  "fresh-query p50="
                  + (f"{p50:.3f}s" if p50 is not None else "n/a")
                  + " — latency-vs-rounds table in the sidecar's "
                    "latency_vs_rounds block")
        pr = load_telemetry_precision(args.telemetry)
        if pr.get("mode"):
            # non-fp32 runs: the speedup this sidecar's batch times embody
            # is only admissible with this block's rank agreement — a
            # projection from a tau-degraded run projects a run bench_diff
            # would refuse
            ulp = pr.get("ulp") or {}
            tau = pr.get("tau_b")
            ref = pr.get("fp32_reference_s")
            print(f"measured precision: mode={pr['mode']} tau_b="
                  + (f"{tau:.4f}" if tau is not None else "n/a")
                  + " fp32_reference="
                  + (f"{ref:.1f}s" if ref is not None else "n/a")
                  + f" ulp_max={ulp.get('max')} p99={ulp.get('p99')} over "
                  f"{pr.get('common', '?')} subsets — batch times below "
                  "are the speed mode's; the fp32 twin's executed "
                  "seconds are the like-for-like baseline")
        rk = load_telemetry_recon(args.telemetry)
        if rk.get("kernel_mode"):
            path_txt = ("fused-kernel"
                        + (" (interpret)" if rk.get("interpret") else "")
                        if rk.get("use_kernel") else "scan")
            kq = rk.get("kernel_query_s")
            print(f"measured recon path: {path_txt} "
                  f"(MPLC_TPU_RECON_KERNEL={rk['kernel_mode']}, "
                  f"precision={rk.get('precision', 'fp32')}) fresh-query="
                  + (f"{kq:.3f}s" if kq is not None else "n/a")
                  + " — the bench_diff recon.kernel_query_s row; scan "
                  "fallback means this sidecar measured the reference "
                  "path, not the kernel")
        t = load_telemetry_trust(args.telemetry)
        if t.get("ensemble"):
            # the sweep's answer-trust view (absent in single-seed,
            # trust-free sidecars and every pre-trust schema — both print
            # nothing). `source` distinguishes a seed-ensemble row (K seed
            # replicas; batch times cover K x rows per coalition, which
            # the projection inherits as-is) from a retrain-free MC row
            # (mc_blocks: pseudo-replicas of ONE run's sample stream).
            tau = t.get("kendall_tau")
            print(f"measured trust: ensemble={t['ensemble']}"
                  + (f" source={t['source']}" if t.get("source") else "")
                  + " kendall_tau="
                  + (f"{tau:.3f}" if tau is not None else "n/a")
                  + " — per-partner CIs in the sidecar's report.trust row")
        print()

    times = parse_batch_times(args.log)

    # representative width-16 batch time per slot size (median over the
    # size's batches; every batch of a call is padded to the same width)
    def median(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2]

    t16 = {}
    for slots, ds in times.items():
        k = 1 if slots is None else slots
        t16[k] = float(median(ds))

    # size-10 ran width-1 single-chip (1 coalition); sizes 2..9 + singles
    # ran width-16. Models below re-express t16[10] from its width-1 point.
    t10_w1 = t16.get(10)

    models = {}
    pts = parse_width_curve(args.curve) if os.path.exists(args.curve) else []
    if len(pts) >= 2:  # one point (a wedge-truncated log) can't fit a line
        a, c = fit_affine(pts)
        t_16 = a * 16 + c
        models["measured-affine"] = lambda w, a=a, c=c, t=t_16: (a * w + c) / t
        print(f"width curve {args.curve}: t_batch(w) = {a:.3f}*w + {c:.3f} s "
              f"(points: {pts})")
    else:
        print(f"no usable width curve at {args.curve} (need >= 2 points, "
              f"have {len(pts)}) — bracketing with priors")
    if args.islog and os.path.exists(args.islog):
        ratio_pts, _ = parse_is_log_ratios(args.islog, args.islog_cap)
        w_full = bucket_size(args.islog_cap, 1, args.islog_cap)
        if len(ratio_pts) >= 2:
            # fit r(w) = alpha*w + beta over the pooled ratio points,
            # anchored by construction at r(w_full) ~ 1
            a, c = fit_affine(ratio_pts + [(w_full, 1.0)])
            models["measured-r4-islog"] = \
                lambda w, a=a, c=c: max(a * w + c, 1e-6)
            print(f"IS-log width ratios from {args.islog} "
                  f"(steady-state batches only): r(w) = {a:.4f}*w + {c:.3f}")
            print(f"  points (w, t/t{w_full}): "
                  + ", ".join(f"({w}, {r:.3f})" for w, r in ratio_pts))
    models["linear(optimistic)"] = lambda w: w / 16.0
    models["flat(pessimistic)"] = lambda w: 1.0

    sched = schedule(args.partners, args.ndev, args.cap, args.pow2,
                     merge=args.merge)
    mode = "pow2" if args.pow2 else "merge" if args.merge else "per-size"
    print(f"\nschedule ({mode}, ndev={args.ndev}, cap={args.cap}): "
          f"(slot_width, batch_width, n_batches) = {sched}")

    for name, r in models.items():
        total = 0.0
        rows = []
        for slot_w, b, nb in sched:
            per_dev_w = b / args.ndev
            if slot_w in t16 and (slot_w != 10 or t10_w1 is None):
                base = t16[slot_w]
            elif slot_w == 10 and t10_w1 is not None:
                # measured at width 1; re-express at width 16 via r
                base = t10_w1 * r(16) / max(r(1), 1e-9)
            else:
                # pow2 width with no measured size (can't happen for n=10:
                # widths {2,4,8,10} are all measured sizes)
                base = t16[min(t16, key=lambda k: abs(k - slot_w))]
            bt = base * r(per_dev_w) / r(16)
            total += bt * nb
            rows.append(f"  slots={slot_w:2d} width/dev={per_dev_w:5.1f} "
                        f"batches={nb} t/batch={bt:6.1f}s  sum={bt * nb:7.1f}s")
        print(f"\n[{name}] projected {args.partners}-partner sweep on "
              f"{args.ndev} devices: {total:.0f} s")
        for row in rows:
            print(row)

    # precedence rule: a MEASURED fleet-scaling curve (BENCH_CONFIG=9
    # sidecar) supersedes the pinned projection above; the pins stay
    # printed for comparison either way
    if args.fleet_telemetry:
        measured = load_measured_fleet(args.fleet_telemetry)
        if measured:
            print("\n" + format_measured_fleet(measured,
                                               args.fleet_telemetry))
        else:
            print("\nno measured BENCH_CONFIG=9 fleet sidecar at "
                  f"{args.fleet_telemetry} — the pinned projection above "
                  "STANDS (run the fleet bench to supersede it)")


if __name__ == "__main__":
    main()
