#!/usr/bin/env python
"""Synthetic load & chaos harness for the multi-tenant sweep service —
the benchmark that finds the service's ceiling instead of assuming it.

`BENCH_CONFIG=7` (bench.py) and the fast-tier chaos smoke
(tests/test_load_harness.py) both drive `run_load()`: submit a stream of
mixed-shape contributivity games (different partner counts and seeds)
across priority tiers against ONE running `SweepService`, optionally
while a seeded chaos plan (`MPLC_TPU_SERVICE_FAULT_PLAN=
chaos@rate0.05:seed7`, faults.py) injects random crash/stall/transient
faults, then measure what the service did about it:

  - **saturation throughput** — completed jobs/s and coalitions/s with
    the admission queue held at its bound by the submission loop (the
    loop backs off on `ServiceOverloaded` by the error's own
    `retry_after_sec` hint, so the harness also exercises the backoff
    contract it documents);
  - **per-tier tail latency** — exact p50/p95/p99 of queue wait,
    time-to-first-value and end-to-end seconds per priority tier (each
    tier submits under its own tenant name, so the sweep report's
    per-tenant slo row and the live /metrics histograms line up with
    the harness's own quantiles);
  - **fairness** — each tier's share of completed work vs its
    stride-scheduling weight (`tier + 1`), plus the service row's
    per-tenant cost_share;
  - **shed / quarantine accounting** — every non-completed outcome by
    class, with rejected-at-admission and overload-backoff counts.

And the robustness INVARIANT, equality-checked on every run (`report
["invariant"]`): every ACCEPTED job reaches a terminal state —
completed, shed, cancelled, or quarantined — none lost, none hung
(`stuck == 0`); every shed job carries a classified `JobShed` (never a
silent drop); and every COMPLETED job's v(S) table is bit-identical to
a solo fault-free engine run of the same game, chaos and overload
notwithstanding.

Standalone:

    JAX_PLATFORMS=cpu python scripts/load_gen.py --jobs 200 \
        --chaos 0.05 --chaos-seed 7 --workers 2 --out load_report.json

The service under test is in-process (the engine is a library, not an
RPC server yet); /metrics is scraped over real HTTP when
`MPLC_TPU_METRICS_PORT` is set, so the telemetry plane is exercised
end-to-end too.

Fleet-router chaos mode (`--router`): the driver spawns N REAL shard
subprocesses (`--router-shard` server mode: SweepService + ShardServer
behind the telemetry server's `/router/*` surface, heartbeating into a
shared fleet state dir), fronts them with a `FleetRouter` discovered
purely from that state dir, routes a stream of jobs through it, then
SIGKILLs one shard mid-run per the `shardkill@<shard>:sec<F>` plan.
The router must detect the corpse (stale heartbeat -> failed /healthz
probe), drain it from the table, replay its journal and resubmit its
incomplete jobs to survivors — and the invariant extends the solo one:
every routed job terminal, every completed v(S) table bit-identical to
a solo fault-free run INCLUDING the failed-over ones, and (when a kill
was planned) at least one failover actually happened. Exit 1 on drift:

    JAX_PLATFORMS=cpu python scripts/load_gen.py --router --jobs 8 \
        --router-shards 2 --fault-plan 'shardkill@shard0:sec3'
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _amounts(n):
    a = [float(i + 1) for i in range(n)]
    return [x / sum(a) for x in a]


def default_scenario_builder(partners: int, seed: int, epochs: int = 1,
                             dataset: str = "titanic"):
    """A builder returning a FRESH small Scenario per call (each job gets
    its own — engines must never share mutable scenario state across
    concurrent workers). titanic: the only family whose trainers compile
    in seconds on CPU."""
    def build():
        from mplc_tpu.scenario import Scenario
        sc = Scenario(partners_count=partners,
                      amounts_per_partner=_amounts(partners),
                      dataset_name=dataset,
                      multi_partner_learning_approach="fedavg",
                      aggregation_weighting="data-volume",
                      epoch_count=epochs, minibatch_count=2,
                      gradient_updates_per_pass_count=2,
                      is_early_stopping=False,
                      experiment_path="/tmp/mplc_loadgen", is_dry_run=True,
                      seed=seed)
        sc.instantiate_scenario_partners()
        sc.split_data(is_logging_enabled=False)
        sc.compute_batch_sizes()
        sc.data_corruption()
        return sc
    return build


def _quantiles(samples) -> dict:
    from mplc_tpu.service.admission import nearest_rank
    return {"p50": nearest_rank(samples, 0.50),
            "p95": nearest_rank(samples, 0.95),
            "p99": nearest_rank(samples, 0.99),
            "max": max(samples) if samples else None,
            "count": len(samples)}


def _scrape_metrics() -> "dict | None":
    """GET /metrics off the live telemetry server (when one is up) and
    keep the service-level counter samples — proof the Prometheus plane
    survives a load run, and a second accounting source to cross-check
    the harness's own counts."""
    from mplc_tpu.obs import export as obs_export
    srv = obs_export.active_server()
    if srv is None:
        return None
    import urllib.request
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10) as r:
            text = r.read().decode()
    except Exception as e:
        return {"error": str(e)[:200]}
    out = {}
    for line in text.splitlines():
        if line.startswith("mplc_service_") and " " in line \
                and "_bucket" not in line and not line.startswith("#"):
            name, _, val = line.rpartition(" ")
            try:
                out[name] = float(val)
            except ValueError:
                continue
    return out


def _scrape_fleet() -> "dict | None":
    """Cluster-level view for `--fleet`: collect the aggregated fleet
    snapshot from whatever sources the environment names — HTTP peers
    (`MPLC_TPU_FLEET_PEERS`), a shared fleet state dir
    (`MPLC_TPU_FLEET_STATE_DIR`), or a fleet out_dir of result files —
    so a load run against one shard of a fleet still reports the
    CLUSTER-true SLO quantiles (merged histograms, exact at log2-bucket
    granularity), not just its own shard's."""
    from mplc_tpu.obs import fleet_view
    coll = fleet_view.collector_from_env()
    if coll is None:
        return {"error": "no fleet sources configured (set "
                         "MPLC_TPU_FLEET_PEERS or "
                         "MPLC_TPU_FLEET_STATE_DIR)"}
    try:
        snap = coll.collect()
    except Exception as e:
        return {"error": str(e)[:200]}
    return {"shard_count": snap.get("shard_count"),
            "fresh_shards": snap.get("fresh_shards"),
            "merged_sources": snap.get("merged_sources"),
            "slo": snap.get("slo"),
            "device_seconds_total": snap.get("device_seconds_total"),
            "shards": snap.get("shards")}


def solo_reference(builder) -> dict:
    """Fault-free solo-engine v(S) table for one game — the bit-identity
    oracle. Runs OUTSIDE the service on a private engine, exactly the
    solo run the service's isolation invariant is stated against."""
    from mplc_tpu.contrib.engine import CharacteristicEngine
    from mplc_tpu.contrib.shapley import powerset_order
    eng = CharacteristicEngine(builder())
    subsets = powerset_order(eng.partners_count)
    eng.evaluate(subsets)
    return {s: eng.charac_fct_values[s] for s in subsets}


def run_load(jobs: int = 1000,
             partner_shapes=(2, 3),
             game_seeds=(0, 1, 2),
             tiers=(0, 1, 2),
             epochs: int = 1,
             dataset: str = "titanic",
             chaos_plan: "str | None" = None,
             workers: "int | None" = None,
             max_pending: "int | None" = None,
             slice_coalitions: "int | None" = None,
             shed_p99_sec: "float | None" = None,
             threaded: bool = True,
             journal_path=None,
             timeout_sec: float = 24 * 3600,
             beat=None,
             scenario_builder=default_scenario_builder) -> dict:
    """Drive one load run and return the report dict (module docstring).

    `chaos_plan` is a full `MPLC_TPU_SERVICE_FAULT_PLAN` string (chaos
    and/or explicit entries), installed for the service's lifetime and
    restored afterwards. `threaded=False` runs the deterministic inline
    harness (`start=False` + `step()`) the fast-tier smoke uses: the
    submission loop interleaves stepping with submitting, so overload,
    shedding and chaos all fire on a fixed, replayable schedule.
    `beat` is an optional liveness callback (the bench watchdog)."""
    import numpy as np

    from mplc_tpu import faults
    from mplc_tpu.obs import trace as obs_trace
    from mplc_tpu.service import (JobShed, ServiceOverloaded,
                                  ServiceRejected, SweepService)

    beat = beat or (lambda: None)
    games = [(p, s, scenario_builder(p, s, epochs=epochs, dataset=dataset))
             for p in partner_shapes for s in game_seeds]

    env_key = faults.SERVICE_FAULT_PLAN_ENV
    saved_plan = os.environ.get(env_key)
    if chaos_plan is not None:
        os.environ[env_key] = chaos_plan
    try:
        svc = SweepService(start=threaded, workers=workers,
                           max_pending=max_pending,
                           slice_coalitions=slice_coalitions,
                           shed_p99_sec=shed_p99_sec,
                           journal_path=journal_path)
    finally:
        if chaos_plan is not None:
            if saved_plan is None:
                os.environ.pop(env_key, None)
            else:
                os.environ[env_key] = saved_plan

    accepted = []          # (job handle, game index, tier)
    rejected_plan = 0
    overload_backoffs = 0
    retry_after_hints = []
    t0 = time.monotonic()
    deadline = t0 + timeout_sec

    with obs_trace.collect() as recs:
        for i in range(jobs):
            gi = i % len(games)
            tier = tiers[i % len(tiers)]
            builder = games[gi][2]
            sc = builder()
            while True:
                beat()
                try:
                    job = svc.submit(sc, tenant=f"tier{tier}",
                                     priority=tier)
                    accepted.append((job, gi, tier))
                    break
                except ServiceOverloaded as e:
                    # the backpressure contract under test: back off by
                    # the error's own hint instead of hammering submit
                    overload_backoffs += 1
                    retry_after_hints.append(e.retry_after_sec)
                    if threaded:
                        time.sleep(min(max(e.retry_after_sec, 0.005), 1.0))
                    else:
                        if not svc.step():
                            time.sleep(0.005)
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            "load run could not drain the admission "
                            "queue within timeout_sec")
                except ServiceRejected:
                    rejected_plan += 1
                    break
        # drain every accepted job to a terminal state
        if threaded:
            stuck = []
            for job, _, _ in accepted:
                remaining = max(deadline - time.monotonic(), 0.0)
                if not job._done.wait(remaining):
                    stuck.append(job.job_id)
                beat()
            svc.shutdown(drain=True, timeout=max(
                deadline - time.monotonic(), 1.0))
        else:
            svc.run_until_idle()
            svc.shutdown(drain=False)
            stuck = [job.job_id for job, _, _ in accepted if not job.done]
    elapsed = time.monotonic() - t0

    # -- outcome accounting + the invariant -------------------------------
    from mplc_tpu.contrib.shapley import powerset_order

    refs: dict = {}
    outcomes: dict = {}
    mismatched = []
    unclassified_sheds = []
    completed_coalitions = 0
    for job, gi, tier in accepted:
        outcomes[job.status] = outcomes.get(job.status, 0) + 1
        if job.status == "completed":
            partners, seed, builder = games[gi]
            if gi not in refs:
                refs[gi] = solo_reference(builder)
                beat()
            subsets = powerset_order(partners)
            got = np.array([job.values[s] for s in subsets])
            want = np.array([refs[gi][s] for s in subsets])
            completed_coalitions += len(subsets)
            if not np.array_equal(got, want):
                mismatched.append(job.job_id)
        elif job.status == "shed":
            if not isinstance(job.error, JobShed) or \
                    job.error.retry_after_sec < 0.0:
                unclassified_sheds.append(job.job_id)

    terminal = {"completed", "shed", "cancelled", "quarantined"}
    invariant = {
        "accepted": len(accepted),
        "terminal": sum(v for k, v in outcomes.items() if k in terminal),
        "stuck": len(stuck),
        "stuck_jobs": stuck[:20],
        "nonterminal_statuses": sorted(
            k for k in outcomes if k not in terminal),
        "completed_games_checked": len(refs),
        "values_bit_identical_to_solo": not mismatched,
        "mismatched_jobs": mismatched[:20],
        "sheds_classified": not unclassified_sheds,
        "holds": (not stuck and not mismatched and not unclassified_sheds
                  and all(k in terminal for k in outcomes)),
    }

    # -- per-tier latency + fairness from the collected trace -------------
    per_tier: dict = {}
    job_events = [r for r in recs if r.get("name") == "service.job"]
    for tier in sorted(set(tiers)):
        tn = f"tier{tier}"
        evs = [r["attrs"] for r in job_events
               if r.get("attrs", {}).get("tenant") == tn]
        done = [a for a in evs if a.get("status") == "completed"]
        per_tier[str(tier)] = {
            "weight": tier + 1,
            "jobs": len(evs),
            "completed": len(done),
            "shed": sum(1 for a in evs if a.get("status") == "shed"),
            "queue_wait_s": _quantiles(
                [a["queue_wait_sec"] for a in evs
                 if a.get("queue_wait_sec") is not None]),
            "ttfv_s": _quantiles(
                [a["ttfv_sec"] for a in evs
                 if a.get("ttfv_sec") is not None]),
            "e2e_s": _quantiles(
                [a["seconds"] for a in done
                 if a.get("seconds") is not None]),
        }
    total_weight = sum(t + 1 for t in tiers) or 1
    total_completed = sum(t["completed"] for t in per_tier.values()) or 1
    for tier in per_tier.values():
        tier["completed_share"] = tier["completed"] / total_completed
        tier["weight_share"] = tier["weight"] / total_weight

    from mplc_tpu.obs.report import sweep_report
    rep = sweep_report(recs)

    return {
        "params": {
            "jobs": jobs, "partner_shapes": list(partner_shapes),
            "game_seeds": list(game_seeds), "tiers": list(tiers),
            "epochs": epochs, "dataset": dataset,
            "chaos_plan": chaos_plan, "workers": svc._n_workers,
            "max_pending": svc._max_pending,
            "slice_coalitions": svc._slice,
            "shed_p99_sec": svc._admission.shed_p99_sec,
            "threaded": threaded,
        },
        "wallclock_s": elapsed,
        "saturation": {
            "accepted": len(accepted),
            "completed_jobs_per_s": outcomes.get("completed", 0) / elapsed
            if elapsed else None,
            "completed_coalitions_per_s": completed_coalitions / elapsed
            if elapsed else None,
            "completed_coalitions": completed_coalitions,
            "overload_backoffs": overload_backoffs,
            "retry_after_hint_s": _quantiles(retry_after_hints),
            "rejected_by_fault_plan": rejected_plan,
        },
        "outcomes": outcomes,
        "per_tier": per_tier,
        "invariant": invariant,
        "admission": svc._admission.view(),
        "metrics_scrape": _scrape_metrics(),
        "service_report": {k: rep[k] for k in ("service", "slo",
                                               "resilience")
                           if k in rep},
    }


def scenario_from_spec(spec: dict):
    """Rebuild a Scenario from a wire spec (`FleetRouter.submit(spec=)`)
    — the `scenario_builder` a `--router-shard` server injects into its
    `ShardServer`. Both the shard and the driver's solo oracle build
    from the SAME spec, so bit-identity is a statement about the game,
    not about pickling."""
    return default_scenario_builder(
        partners=int(spec.get("partners", 3)),
        seed=int(spec.get("seed", 0)),
        epochs=int(spec.get("epochs", 1)),
        dataset=spec.get("dataset", "titanic"))()


def run_router_shard(shard_id: str, workers: "int | None" = None,
                     slice_coalitions: "int | None" = None) -> int:
    """One shard server process: a threaded `SweepService` journaling
    into the fleet state dir, wrapped in a `ShardServer` and exposed on
    an ephemeral telemetry port. Runs until SIGTERM (clean drain) or
    SIGKILL (the chaos case — the WAL is the only thing left behind,
    which is exactly what failover replays)."""
    import signal

    from mplc_tpu import constants
    from mplc_tpu.obs import export as obs_export
    from mplc_tpu.service import SweepService
    from mplc_tpu.service.router import ShardServer

    state_dir = os.environ.get(constants.FLEET_STATE_DIR_ENV)
    if not state_dir:
        print(f"[router-shard] {constants.FLEET_STATE_DIR_ENV} must be "
              "set", file=sys.stderr)
        return 2
    os.environ.setdefault(constants.FLEET_SHARD_ID_ENV, shard_id)
    os.environ.setdefault(obs_export.ROUTER_SERVE_ENV, "1")
    os.environ.setdefault(obs_export.METRICS_PORT_ENV, "0")
    obs_export.maybe_start_from_env()

    svc = SweepService(start=True, workers=workers or 1,
                       slice_coalitions=slice_coalitions,
                       journal_path=os.path.join(state_dir,
                                                 f"{shard_id}.wal"))
    server = ShardServer(svc, scenario_from_spec)
    stop = {"flag": False}

    def _term(signum, frame):
        stop["flag"] = True
    signal.signal(signal.SIGTERM, _term)
    print(f"[router-shard] {shard_id} up on port "
          f"{obs_export.active_port()}", file=sys.stderr)
    try:
        while not stop["flag"]:
            time.sleep(0.1)
    finally:
        server.close()
        svc.shutdown(drain=False)
    return 0


def run_router(jobs: int = 8,
               shards: int = 2,
               partner_shapes=(2, 3),
               game_seeds=(0, 1),
               epochs: int = 1,
               dataset: str = "titanic",
               fault_plan: "str | None" = None,
               slice_coalitions: "int | None" = 2,
               stale_sec: float = 2.0,
               timeout_sec: float = 600.0,
               out_dir: "str | None" = None) -> dict:
    """The multi-process router chaos run (module docstring): spawn the
    shard fleet, route `jobs` jobs through a state-dir-discovered
    `FleetRouter`, SIGKILL shards per `fault_plan`, and equality-check
    the router invariant. Returns the report dict."""
    import shutil
    import signal
    import subprocess
    import tempfile

    from mplc_tpu import constants, faults
    from mplc_tpu.contrib.shapley import powerset_order
    from mplc_tpu.parallel import fleet
    from mplc_tpu.service import FleetRouter, RoutedJobFailed

    own_dir = out_dir is None
    state_dir = out_dir or tempfile.mkdtemp(prefix="mplc_router_")
    plan = faults.parse_router_fault_plan(fault_plan or "")
    # the corpse-detection clock: a killed shard's heartbeat must go
    # stale (then fail its /healthz probe) within seconds, not the
    # 30s production default
    os.environ[constants.FLEET_STALE_SEC_ENV] = str(stale_sec)
    os.environ[constants.FLEET_STATE_DIR_ENV] = state_dir
    credential = os.environ.get(constants.METRICS_TOKEN_ENV) or None

    shard_ids = [f"s{i}" for i in range(shards)]
    procs: dict = {}
    for sid in shard_ids:
        env = dict(os.environ)
        env[constants.FLEET_STATE_DIR_ENV] = state_dir
        env[constants.FLEET_SHARD_ID_ENV] = sid
        env["MPLC_TPU_ROUTER_SERVE"] = "1"
        env["MPLC_TPU_METRICS_PORT"] = "0"
        cmd = [sys.executable, os.path.abspath(__file__),
               "--router-shard", "--shard-id", sid]
        if slice_coalitions:
            cmd += ["--slice", str(slice_coalitions)]
        procs[sid] = subprocess.Popen(cmd, env=env)

    def _fire_due(t0: float) -> None:
        # the driver owns the processes, so the driver wields the axe:
        # SIGKILL (no drain, no journal close) — the router is NOT told
        # and must detect the corpse through the state dir + probe
        for entry in plan:
            if entry.get("_fired") or time.monotonic() - t0 < \
                    entry["at_sec"]:
                continue
            entry["_fired"] = True
            name = entry["shard"]
            sid = name if name in procs else (
                shard_ids[int(name[5:])]
                if name.startswith("shard") and name[5:].isdigit()
                and int(name[5:]) < len(shard_ids) else None)
            if sid is None or procs[sid].poll() is not None:
                continue
            print(f"[router] SIGKILL shard {sid} at "
                  f"t+{entry['at_sec']}s", file=sys.stderr)
            procs[sid].send_signal(signal.SIGKILL)

    report: dict = {"params": {
        "jobs": jobs, "shards": shards, "fault_plan": fault_plan,
        "slice_coalitions": slice_coalitions, "stale_sec": stale_sec,
        "partner_shapes": list(partner_shapes),
        "game_seeds": list(game_seeds)}}
    router = None
    try:
        # readiness: every shard must publish a port before routing
        deadline = time.monotonic() + 120.0
        while True:
            view = fleet.cluster_view(state_dir)
            up = [sid for sid, row in view["shards"].items()
                  if row.get("port")]
            if len(up) >= shards:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {up} of {shard_ids} shards published a port")
            if any(p.poll() is not None for p in procs.values()):
                raise RuntimeError("a shard process died during startup")
            time.sleep(0.1)

        router = FleetRouter(state_dir=state_dir, credential=credential)
        games = [(p, s) for p in partner_shapes for s in game_seeds]
        handles = []
        failed_routes = []
        t0 = time.monotonic()
        run_deadline = t0 + timeout_sec
        for i in range(jobs):
            _fire_due(t0)
            p, s = games[i % len(games)]
            spec = {"partners": p, "seed": s, "epochs": epochs,
                    "dataset": dataset}
            try:
                h = router.submit(spec=spec, tenant=f"tier{i % 3}")
                handles.append((h, p, s))
            except RoutedJobFailed as e:
                failed_routes.append(str(e))
        while True:
            _fire_due(t0)
            pending = [h for h, _, _ in handles if not h.done]
            if not pending:
                break
            if time.monotonic() > run_deadline:
                break
            router.pump()
            for h in pending:
                h.values()      # polls remote status, latches _final
            time.sleep(0.05)

        # -- the invariant ------------------------------------------------
        refs: dict = {}
        outcomes: dict = {}
        mismatched, stuck, unclassified = [], [], []
        for h, p, s in handles:
            outcomes[h.status] = outcomes.get(h.status, 0) + 1
            if not h.done:
                stuck.append(h.job_id)
                continue
            if h.status == "failed" and not isinstance(
                    h._error, RoutedJobFailed):
                unclassified.append(h.job_id)
            if h.status == "completed":
                if (p, s) not in refs:
                    refs[(p, s)] = solo_reference(
                        lambda p=p, s=s: scenario_from_spec(
                            {"partners": p, "seed": s, "epochs": epochs,
                             "dataset": dataset}))
                vals = h.values() or {}
                want = refs[(p, s)]
                subsets = powerset_order(p)
                if [vals.get(sub) for sub in subsets] != \
                        [want[sub] for sub in subsets]:
                    mismatched.append(h.job_id)
        planned_kills = len(plan)
        invariant = {
            "accepted": len(handles),
            "failed_routes": failed_routes[:10],
            "stuck": len(stuck), "stuck_jobs": stuck[:20],
            "completed_games_checked": len(refs),
            "values_bit_identical_to_solo": not mismatched,
            "mismatched_jobs": mismatched[:20],
            "failures_classified": not unclassified,
            "planned_kills": planned_kills,
            "failovers": router.stats["failovers"],
            "failover_exercised": (router.stats["failovers"] >= 1
                                   if planned_kills else True),
            "holds": (not stuck and not mismatched and not unclassified
                      and (router.stats["failovers"] >= 1
                           if planned_kills else True)),
        }
        report.update({
            "wallclock_s": time.monotonic() - t0,
            "outcomes": outcomes,
            "router": dict(router.stats),
            "routing_table": router.varz_view()["table"],
            "invariant": invariant,
        })
    finally:
        if router is not None:
            router.close()
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=30)
            except Exception:
                p.kill()
        if own_dir:
            shutil.rmtree(state_dir, ignore_errors=True)
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--jobs", type=int, default=1000)
    ap.add_argument("--chaos", type=float, default=0.0,
                    help="chaos fault rate (0 disables)")
    ap.add_argument("--chaos-seed", type=int, default=7)
    ap.add_argument("--workers", type=int, default=None)
    ap.add_argument("--max-pending", type=int, default=None)
    ap.add_argument("--slice", type=int, default=None)
    ap.add_argument("--shed-p99-sec", type=float, default=None)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--timeout-sec", type=float, default=24 * 3600)
    ap.add_argument("--fleet", action="store_true",
                    help="attach the aggregated fleet snapshot (cluster-"
                         "true SLO quantiles) from MPLC_TPU_FLEET_PEERS / "
                         "MPLC_TPU_FLEET_STATE_DIR to the report")
    ap.add_argument("--out", default=None,
                    help="write the JSON report here (default stdout)")
    ap.add_argument("--router", action="store_true",
                    help="multi-process fleet-router chaos mode: spawn "
                         "--router-shards shard subprocesses, route "
                         "--jobs jobs through a FleetRouter, SIGKILL "
                         "shards per --fault-plan, verify the router "
                         "invariant (exit 1 on drift)")
    ap.add_argument("--router-shards", type=int, default=2)
    ap.add_argument("--fault-plan", default=None,
                    help="router chaos plan, e.g. 'shardkill@shard0:sec3'"
                         " (default: MPLC_TPU_ROUTER_FAULT_PLAN)")
    ap.add_argument("--stale-sec", type=float, default=2.0,
                    help="fleet heartbeat staleness window for corpse "
                         "detection in --router mode")
    ap.add_argument("--router-shard", action="store_true",
                    help=argparse.SUPPRESS)   # internal server mode
    ap.add_argument("--shard-id", default="s0", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.router_shard:
        return run_router_shard(args.shard_id, workers=args.workers,
                                slice_coalitions=args.slice)
    if args.router:
        from mplc_tpu import faults
        fault_plan = (args.fault_plan
                      if args.fault_plan is not None
                      else os.environ.get(faults.ROUTER_FAULT_PLAN_ENV))
        report = run_router(jobs=args.jobs, shards=args.router_shards,
                            epochs=args.epochs, fault_plan=fault_plan,
                            slice_coalitions=args.slice or 2,
                            stale_sec=args.stale_sec,
                            timeout_sec=min(args.timeout_sec, 600.0))
        text = json.dumps(report, indent=2, default=str)
        if args.out:
            with open(args.out, "w") as f:
                f.write(text + "\n")
            print(f"[load_gen] report: {args.out}", file=sys.stderr)
        else:
            print(text)
        inv = report["invariant"]
        print(f"[load_gen] router invariant holds: {inv['holds']} "
              f"(accepted={inv['accepted']} stuck={inv['stuck']} "
              f"bit_identical={inv['values_bit_identical_to_solo']} "
              f"failovers={inv['failovers']})", file=sys.stderr)
        return 0 if inv["holds"] else 1

    chaos_plan = (f"chaos@rate{args.chaos}:seed{args.chaos_seed}"
                  if args.chaos > 0 else None)
    report = run_load(jobs=args.jobs, chaos_plan=chaos_plan,
                      workers=args.workers, max_pending=args.max_pending,
                      slice_coalitions=args.slice,
                      shed_p99_sec=args.shed_p99_sec, epochs=args.epochs,
                      timeout_sec=args.timeout_sec)
    if args.fleet:
        report["fleet"] = _scrape_fleet()
    text = json.dumps(report, indent=2, default=str)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"[load_gen] report: {args.out}", file=sys.stderr)
    else:
        print(text)
    inv = report["invariant"]
    print(f"[load_gen] invariant holds: {inv['holds']} "
          f"(accepted={inv['accepted']} stuck={inv['stuck']} "
          f"bit_identical={inv['values_bit_identical_to_solo']})",
          file=sys.stderr)
    return 0 if inv["holds"] else 1


if __name__ == "__main__":
    sys.exit(main())
