#!/usr/bin/env python
"""Synthetic load & chaos harness for the multi-tenant sweep service —
the benchmark that finds the service's ceiling instead of assuming it.

`BENCH_CONFIG=7` (bench.py) and the fast-tier chaos smoke
(tests/test_load_harness.py) both drive `run_load()`: submit a stream of
mixed-shape contributivity games (different partner counts and seeds)
across priority tiers against ONE running `SweepService`, optionally
while a seeded chaos plan (`MPLC_TPU_SERVICE_FAULT_PLAN=
chaos@rate0.05:seed7`, faults.py) injects random crash/stall/transient
faults, then measure what the service did about it:

  - **saturation throughput** — completed jobs/s and coalitions/s with
    the admission queue held at its bound by the submission loop (the
    loop backs off on `ServiceOverloaded` by the error's own
    `retry_after_sec` hint, so the harness also exercises the backoff
    contract it documents);
  - **per-tier tail latency** — exact p50/p95/p99 of queue wait,
    time-to-first-value and end-to-end seconds per priority tier (each
    tier submits under its own tenant name, so the sweep report's
    per-tenant slo row and the live /metrics histograms line up with
    the harness's own quantiles);
  - **fairness** — each tier's share of completed work vs its
    stride-scheduling weight (`tier + 1`), plus the service row's
    per-tenant cost_share;
  - **shed / quarantine accounting** — every non-completed outcome by
    class, with rejected-at-admission and overload-backoff counts.

And the robustness INVARIANT, equality-checked on every run (`report
["invariant"]`): every ACCEPTED job reaches a terminal state —
completed, shed, cancelled, or quarantined — none lost, none hung
(`stuck == 0`); every shed job carries a classified `JobShed` (never a
silent drop); and every COMPLETED job's v(S) table is bit-identical to
a solo fault-free engine run of the same game, chaos and overload
notwithstanding.

Standalone:

    JAX_PLATFORMS=cpu python scripts/load_gen.py --jobs 200 \
        --chaos 0.05 --chaos-seed 7 --workers 2 --out load_report.json

The service under test is in-process (the engine is a library, not an
RPC server yet); /metrics is scraped over real HTTP when
`MPLC_TPU_METRICS_PORT` is set, so the telemetry plane is exercised
end-to-end too.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _amounts(n):
    a = [float(i + 1) for i in range(n)]
    return [x / sum(a) for x in a]


def default_scenario_builder(partners: int, seed: int, epochs: int = 1,
                             dataset: str = "titanic"):
    """A builder returning a FRESH small Scenario per call (each job gets
    its own — engines must never share mutable scenario state across
    concurrent workers). titanic: the only family whose trainers compile
    in seconds on CPU."""
    def build():
        from mplc_tpu.scenario import Scenario
        sc = Scenario(partners_count=partners,
                      amounts_per_partner=_amounts(partners),
                      dataset_name=dataset,
                      multi_partner_learning_approach="fedavg",
                      aggregation_weighting="data-volume",
                      epoch_count=epochs, minibatch_count=2,
                      gradient_updates_per_pass_count=2,
                      is_early_stopping=False,
                      experiment_path="/tmp/mplc_loadgen", is_dry_run=True,
                      seed=seed)
        sc.instantiate_scenario_partners()
        sc.split_data(is_logging_enabled=False)
        sc.compute_batch_sizes()
        sc.data_corruption()
        return sc
    return build


def _quantiles(samples) -> dict:
    from mplc_tpu.service.admission import nearest_rank
    return {"p50": nearest_rank(samples, 0.50),
            "p95": nearest_rank(samples, 0.95),
            "p99": nearest_rank(samples, 0.99),
            "max": max(samples) if samples else None,
            "count": len(samples)}


def _scrape_metrics() -> "dict | None":
    """GET /metrics off the live telemetry server (when one is up) and
    keep the service-level counter samples — proof the Prometheus plane
    survives a load run, and a second accounting source to cross-check
    the harness's own counts."""
    from mplc_tpu.obs import export as obs_export
    srv = obs_export.active_server()
    if srv is None:
        return None
    import urllib.request
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10) as r:
            text = r.read().decode()
    except Exception as e:
        return {"error": str(e)[:200]}
    out = {}
    for line in text.splitlines():
        if line.startswith("mplc_service_") and " " in line \
                and "_bucket" not in line and not line.startswith("#"):
            name, _, val = line.rpartition(" ")
            try:
                out[name] = float(val)
            except ValueError:
                continue
    return out


def _scrape_fleet() -> "dict | None":
    """Cluster-level view for `--fleet`: collect the aggregated fleet
    snapshot from whatever sources the environment names — HTTP peers
    (`MPLC_TPU_FLEET_PEERS`), a shared fleet state dir
    (`MPLC_TPU_FLEET_STATE_DIR`), or a fleet out_dir of result files —
    so a load run against one shard of a fleet still reports the
    CLUSTER-true SLO quantiles (merged histograms, exact at log2-bucket
    granularity), not just its own shard's."""
    from mplc_tpu.obs import fleet_view
    coll = fleet_view.collector_from_env()
    if coll is None:
        return {"error": "no fleet sources configured (set "
                         "MPLC_TPU_FLEET_PEERS or "
                         "MPLC_TPU_FLEET_STATE_DIR)"}
    try:
        snap = coll.collect()
    except Exception as e:
        return {"error": str(e)[:200]}
    return {"shard_count": snap.get("shard_count"),
            "fresh_shards": snap.get("fresh_shards"),
            "merged_sources": snap.get("merged_sources"),
            "slo": snap.get("slo"),
            "device_seconds_total": snap.get("device_seconds_total"),
            "shards": snap.get("shards")}


def solo_reference(builder) -> dict:
    """Fault-free solo-engine v(S) table for one game — the bit-identity
    oracle. Runs OUTSIDE the service on a private engine, exactly the
    solo run the service's isolation invariant is stated against."""
    from mplc_tpu.contrib.engine import CharacteristicEngine
    from mplc_tpu.contrib.shapley import powerset_order
    eng = CharacteristicEngine(builder())
    subsets = powerset_order(eng.partners_count)
    eng.evaluate(subsets)
    return {s: eng.charac_fct_values[s] for s in subsets}


def run_load(jobs: int = 1000,
             partner_shapes=(2, 3),
             game_seeds=(0, 1, 2),
             tiers=(0, 1, 2),
             epochs: int = 1,
             dataset: str = "titanic",
             chaos_plan: "str | None" = None,
             workers: "int | None" = None,
             max_pending: "int | None" = None,
             slice_coalitions: "int | None" = None,
             shed_p99_sec: "float | None" = None,
             threaded: bool = True,
             journal_path=None,
             timeout_sec: float = 24 * 3600,
             beat=None,
             scenario_builder=default_scenario_builder) -> dict:
    """Drive one load run and return the report dict (module docstring).

    `chaos_plan` is a full `MPLC_TPU_SERVICE_FAULT_PLAN` string (chaos
    and/or explicit entries), installed for the service's lifetime and
    restored afterwards. `threaded=False` runs the deterministic inline
    harness (`start=False` + `step()`) the fast-tier smoke uses: the
    submission loop interleaves stepping with submitting, so overload,
    shedding and chaos all fire on a fixed, replayable schedule.
    `beat` is an optional liveness callback (the bench watchdog)."""
    import numpy as np

    from mplc_tpu import faults
    from mplc_tpu.obs import trace as obs_trace
    from mplc_tpu.service import (JobShed, ServiceOverloaded,
                                  ServiceRejected, SweepService)

    beat = beat or (lambda: None)
    games = [(p, s, scenario_builder(p, s, epochs=epochs, dataset=dataset))
             for p in partner_shapes for s in game_seeds]

    env_key = faults.SERVICE_FAULT_PLAN_ENV
    saved_plan = os.environ.get(env_key)
    if chaos_plan is not None:
        os.environ[env_key] = chaos_plan
    try:
        svc = SweepService(start=threaded, workers=workers,
                           max_pending=max_pending,
                           slice_coalitions=slice_coalitions,
                           shed_p99_sec=shed_p99_sec,
                           journal_path=journal_path)
    finally:
        if chaos_plan is not None:
            if saved_plan is None:
                os.environ.pop(env_key, None)
            else:
                os.environ[env_key] = saved_plan

    accepted = []          # (job handle, game index, tier)
    rejected_plan = 0
    overload_backoffs = 0
    retry_after_hints = []
    t0 = time.monotonic()
    deadline = t0 + timeout_sec

    with obs_trace.collect() as recs:
        for i in range(jobs):
            gi = i % len(games)
            tier = tiers[i % len(tiers)]
            builder = games[gi][2]
            sc = builder()
            while True:
                beat()
                try:
                    job = svc.submit(sc, tenant=f"tier{tier}",
                                     priority=tier)
                    accepted.append((job, gi, tier))
                    break
                except ServiceOverloaded as e:
                    # the backpressure contract under test: back off by
                    # the error's own hint instead of hammering submit
                    overload_backoffs += 1
                    retry_after_hints.append(e.retry_after_sec)
                    if threaded:
                        time.sleep(min(max(e.retry_after_sec, 0.005), 1.0))
                    else:
                        if not svc.step():
                            time.sleep(0.005)
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            "load run could not drain the admission "
                            "queue within timeout_sec")
                except ServiceRejected:
                    rejected_plan += 1
                    break
        # drain every accepted job to a terminal state
        if threaded:
            stuck = []
            for job, _, _ in accepted:
                remaining = max(deadline - time.monotonic(), 0.0)
                if not job._done.wait(remaining):
                    stuck.append(job.job_id)
                beat()
            svc.shutdown(drain=True, timeout=max(
                deadline - time.monotonic(), 1.0))
        else:
            svc.run_until_idle()
            svc.shutdown(drain=False)
            stuck = [job.job_id for job, _, _ in accepted if not job.done]
    elapsed = time.monotonic() - t0

    # -- outcome accounting + the invariant -------------------------------
    from mplc_tpu.contrib.shapley import powerset_order

    refs: dict = {}
    outcomes: dict = {}
    mismatched = []
    unclassified_sheds = []
    completed_coalitions = 0
    for job, gi, tier in accepted:
        outcomes[job.status] = outcomes.get(job.status, 0) + 1
        if job.status == "completed":
            partners, seed, builder = games[gi]
            if gi not in refs:
                refs[gi] = solo_reference(builder)
                beat()
            subsets = powerset_order(partners)
            got = np.array([job.values[s] for s in subsets])
            want = np.array([refs[gi][s] for s in subsets])
            completed_coalitions += len(subsets)
            if not np.array_equal(got, want):
                mismatched.append(job.job_id)
        elif job.status == "shed":
            if not isinstance(job.error, JobShed) or \
                    job.error.retry_after_sec < 0.0:
                unclassified_sheds.append(job.job_id)

    terminal = {"completed", "shed", "cancelled", "quarantined"}
    invariant = {
        "accepted": len(accepted),
        "terminal": sum(v for k, v in outcomes.items() if k in terminal),
        "stuck": len(stuck),
        "stuck_jobs": stuck[:20],
        "nonterminal_statuses": sorted(
            k for k in outcomes if k not in terminal),
        "completed_games_checked": len(refs),
        "values_bit_identical_to_solo": not mismatched,
        "mismatched_jobs": mismatched[:20],
        "sheds_classified": not unclassified_sheds,
        "holds": (not stuck and not mismatched and not unclassified_sheds
                  and all(k in terminal for k in outcomes)),
    }

    # -- per-tier latency + fairness from the collected trace -------------
    per_tier: dict = {}
    job_events = [r for r in recs if r.get("name") == "service.job"]
    for tier in sorted(set(tiers)):
        tn = f"tier{tier}"
        evs = [r["attrs"] for r in job_events
               if r.get("attrs", {}).get("tenant") == tn]
        done = [a for a in evs if a.get("status") == "completed"]
        per_tier[str(tier)] = {
            "weight": tier + 1,
            "jobs": len(evs),
            "completed": len(done),
            "shed": sum(1 for a in evs if a.get("status") == "shed"),
            "queue_wait_s": _quantiles(
                [a["queue_wait_sec"] for a in evs
                 if a.get("queue_wait_sec") is not None]),
            "ttfv_s": _quantiles(
                [a["ttfv_sec"] for a in evs
                 if a.get("ttfv_sec") is not None]),
            "e2e_s": _quantiles(
                [a["seconds"] for a in done
                 if a.get("seconds") is not None]),
        }
    total_weight = sum(t + 1 for t in tiers) or 1
    total_completed = sum(t["completed"] for t in per_tier.values()) or 1
    for tier in per_tier.values():
        tier["completed_share"] = tier["completed"] / total_completed
        tier["weight_share"] = tier["weight"] / total_weight

    from mplc_tpu.obs.report import sweep_report
    rep = sweep_report(recs)

    return {
        "params": {
            "jobs": jobs, "partner_shapes": list(partner_shapes),
            "game_seeds": list(game_seeds), "tiers": list(tiers),
            "epochs": epochs, "dataset": dataset,
            "chaos_plan": chaos_plan, "workers": svc._n_workers,
            "max_pending": svc._max_pending,
            "slice_coalitions": svc._slice,
            "shed_p99_sec": svc._admission.shed_p99_sec,
            "threaded": threaded,
        },
        "wallclock_s": elapsed,
        "saturation": {
            "accepted": len(accepted),
            "completed_jobs_per_s": outcomes.get("completed", 0) / elapsed
            if elapsed else None,
            "completed_coalitions_per_s": completed_coalitions / elapsed
            if elapsed else None,
            "completed_coalitions": completed_coalitions,
            "overload_backoffs": overload_backoffs,
            "retry_after_hint_s": _quantiles(retry_after_hints),
            "rejected_by_fault_plan": rejected_plan,
        },
        "outcomes": outcomes,
        "per_tier": per_tier,
        "invariant": invariant,
        "admission": svc._admission.view(),
        "metrics_scrape": _scrape_metrics(),
        "service_report": {k: rep[k] for k in ("service", "slo",
                                               "resilience")
                           if k in rep},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--jobs", type=int, default=1000)
    ap.add_argument("--chaos", type=float, default=0.0,
                    help="chaos fault rate (0 disables)")
    ap.add_argument("--chaos-seed", type=int, default=7)
    ap.add_argument("--workers", type=int, default=None)
    ap.add_argument("--max-pending", type=int, default=None)
    ap.add_argument("--slice", type=int, default=None)
    ap.add_argument("--shed-p99-sec", type=float, default=None)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--timeout-sec", type=float, default=24 * 3600)
    ap.add_argument("--fleet", action="store_true",
                    help="attach the aggregated fleet snapshot (cluster-"
                         "true SLO quantiles) from MPLC_TPU_FLEET_PEERS / "
                         "MPLC_TPU_FLEET_STATE_DIR to the report")
    ap.add_argument("--out", default=None,
                    help="write the JSON report here (default stdout)")
    args = ap.parse_args(argv)

    chaos_plan = (f"chaos@rate{args.chaos}:seed{args.chaos_seed}"
                  if args.chaos > 0 else None)
    report = run_load(jobs=args.jobs, chaos_plan=chaos_plan,
                      workers=args.workers, max_pending=args.max_pending,
                      slice_coalitions=args.slice,
                      shed_p99_sec=args.shed_p99_sec, epochs=args.epochs,
                      timeout_sec=args.timeout_sec)
    if args.fleet:
        report["fleet"] = _scrape_fleet()
    text = json.dumps(report, indent=2, default=str)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"[load_gen] report: {args.out}", file=sys.stderr)
    else:
        print(text)
    inv = report["invariant"]
    print(f"[load_gen] invariant holds: {inv['holds']} "
          f"(accepted={inv['accepted']} stuck={inv['stuck']} "
          f"bit_identical={inv['values_bit_identical_to_solo']})",
          file=sys.stderr)
    return 0 if inv["holds"] else 1


if __name__ == "__main__":
    sys.exit(main())
