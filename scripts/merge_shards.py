#!/usr/bin/env python
"""Concatenate a sharded grid run's per-host results into one results.csv.

`python main.py -f cfg.yml --grid-shard I/N` leaves results_shard0..N-1.csv
in the shared experiments/<name>_shardedN/ folder; this stitches them into
the standard results.csv (sorted by the scenario_id and random_state
columns) that the analysis notebooks and downstream tooling expect, then
renames the shard files to *.merged so the notebooks' results*.csv glob
never double-counts rows.

Refuses a partial merge: the folder name encodes the shard count N, and
each host touches .shardI.done as its LAST act (main.py) — a missing
marker means that host is still running (or crashed), even if its csv
already exists with partial rows. Override with --force only when the
missing hosts' slices are genuinely abandoned.

Usage: python scripts/merge_shards.py experiments/<name>_shardedN [-o OUT]
"""

import argparse
import glob
import os
import re
import sys


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("folder", help="the shared <name>_shardedN experiment folder")
    ap.add_argument("-o", "--out", default=None,
                    help="output csv (default: <folder>/results.csv)")
    ap.add_argument("--force", action="store_true",
                    help="merge even when shard files are missing")
    ap.add_argument("--keep", action="store_true",
                    help="leave the shard files in place (NOTE: the analysis "
                         "notebook's results*.csv glob will then read every "
                         "row twice)")
    args = ap.parse_args(argv)

    import pandas as pd

    files = sorted(glob.glob(os.path.join(args.folder, "results_shard*.csv")))
    if not files:
        ap.error(f"no results_shard*.csv in {args.folder!r}")
    # abspath first: a relative spelling like "." must still expose the
    # _shardedN suffix, or the completeness check silently disarms
    m = re.search(r"_sharded(\d+)$",
                  os.path.normpath(os.path.abspath(args.folder)))
    expected = int(m.group(1)) if m else None
    done = set()
    for f in glob.glob(os.path.join(args.folder, ".shard*.done")):
        dm = re.search(r"\.shard(\d+)\.done$", f)
        if dm:
            done.add(int(dm.group(1)))
    if not args.force:
        if expected is not None:
            required = set(range(expected))
        else:
            # folder was renamed/copied and lost its _shardedN suffix: we
            # can't know N, but every shard csv present must at least have
            # its own done marker or its host may still be appending
            required = {int(re.search(r"results_shard(\d+)\.csv$", f).group(1))
                        for f in files}
        missing = sorted(required - done)
        if missing:
            ap.error(f"{args.folder} has no done markers for shards "
                     f"{missing} — those hosts are still running or crashed "
                     "(csv presence is not completion: rows append as "
                     "scenarios finish). --force to merge anyway")
    df = pd.concat([pd.read_csv(f) for f in files], ignore_index=True)
    sort_cols = [c for c in ("scenario_id", "random_state") if c in df.columns]
    if sort_cols:
        df = df.sort_values(sort_cols, kind="stable")
    out = args.out or os.path.join(args.folder, "results.csv")
    df.to_csv(out, index=False)
    if not args.keep:
        for f in files:
            os.replace(f, f + ".merged")
        # retire the markers with the csvs: a later re-run into this
        # deterministic folder must not inherit stale completion signals
        for i in sorted(done):
            marker = os.path.join(args.folder, f".shard{i}.done")
            if os.path.exists(marker):
                os.remove(marker)
    print(f"merged {len(files)} shard files, {len(df)} rows -> {out}"
          + ("" if args.keep else " (shard files renamed to *.merged)"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
