#!/usr/bin/env python
"""Perf-trajectory gate: diff two bench telemetry sidecars row by row.

The repo records a telemetry sidecar per bench run
(`perf/telemetry_config<N>.json`, bench.py `_write_telemetry`) but until
this script nothing COMPARED them — the BENCH trajectory existed only as
disconnected JSON blobs, and a perf regression surfaced only if a human
eyeballed two files. This tool turns any two sidecars (or two run
directories of them) into a per-row delta table with a configurable
regression threshold, and exits non-zero when a tracked metric regressed
past it — a perf gate a driver (or CI) can wire in front of a merge.

Usage:
    python scripts/bench_diff.py OLD.json NEW.json [--threshold 0.10]
    python scripts/bench_diff.py perf_run_A/ perf_run_B/ [--threshold ...]

Directory mode pairs up `telemetry_config*.json` files by name and
diffs each pair (files present on only one side are reported, not
fatal). Exit codes: 0 = no regression, 1 = at least one row regressed
past the threshold, 2 = usage/JSON error, no matching pairs, or
matched pairs that shared NO comparable rows at all (a gate that
compared nothing must not read green — but a pair merely missing some
newer rows still gates the rest).

Every compared row is DIRECTION-aware ("lower" = smaller is better,
"higher" = bigger is better); rows missing from either side are skipped
(schema growth — e.g. the device/roofline rows appearing — is never a
regression). Provenance guards: a fresh number diffed against a
`cpu_fallback` or `replayed_cache` sidecar is flagged as incomparable
(the scales differ), and a `degraded: true` side is annotated — a
number earned through the OOM ladder is not a like-for-like baseline.

Value-truth gate: sidecars carrying a `numerics` block (the
obs/numerics.py ledger digest) additionally diff their per-subset v(S)
bits — same-fingerprint runs whose values drifted fail regardless of
the perf threshold (`numerics.max_ulp` / `numerics.p99_ulp` /
`numerics.rank_tau` rows); pre-numerics sidecars skip the gate
silently, fingerprint mismatches are noted and never gated.

Precision gate: a sidecar carrying a `precision` block (a bf16/mixed
run's ledger diff against its own fp32 reference twin) gates on the
pair's Kendall tau-b with a HARD floor (`--tau-threshold`, default
0.99; exactly 1.0 when the block claims mode fp32) — cross-precision
sidecar pairs have different engine fingerprints BY DESIGN (precision
is fingerprinted), so this block is their value truth and satisfies
`--gate` where the numerics gate cannot run. The live bench's
`recon.kernel_query_s` row tracks the fused-kernel fresh-query latency.
The fleet-router bench's `router.*` rows (config 11 sidecar) track
end-to-end routing latency quantiles and the redirect/exhaustion totals
of its planned-kill chaos run.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

# row path in the sidecar -> direction. Paths walk nested dicts; the
# `per_width[slots,width]` rows are expanded dynamically below.
_ROWS = {
    "wallclock_s": "lower",
    "report.wallclock.evaluate_s": "lower",
    "report.wallclock.compile_s": "lower",
    "report.wallclock.prep_s": "lower",
    "report.wallclock.dispatch_s": "lower",
    "report.wallclock.harvest_s": "lower",
    "report.memo.hit_rate": "higher",
    "report.batches.pad_waste_fraction": "lower",
    "report.compute.samples_per_s": "higher",
    "report.compute.model_flops_per_s": "higher",
    "report.compute.mfu_proxy": "higher",
    "report.compute.mfu_xla": "higher",
    "report.device_time.device_s": "lower",
    "report.resilience.retries": "lower",
    "report.resilience.cap_halvings": "lower",
    # fleet-health rows (config 9 sidecar, `fleet` block at top level —
    # that sidecar has no `report` wrapper so these paths are absolute):
    # shard balance (max/median sweep, 1.0 = perfectly balanced) and
    # shard-count-normalized throughput — a fleet can hold its critical
    # path while quietly growing a straggler; these rows catch that
    "fleet.straggler_ratio": "lower",
    "fleet.coalitions_per_shard_s": "higher",
    # raw-speed plane rows: the live bench's fresh-query latency under
    # the resolved reconstruction executable (config 8 sidecar `recon`
    # block), and the mixed-precision run's fp32-reference wall-clock
    # (the speedup's denominator — it shrinking means the REFERENCE got
    # faster, which is fine, hence "lower")
    "recon.kernel_query_s": "lower",
    "precision.fp32_reference_s": "lower",
    # residency-tier rows (config 10 sidecar, `live` block at top
    # level): p99 fresh-query latency at max game pressure (a fresh
    # query pays admission + WAL replay + full reconstruction) and the
    # p50 WAL-restore second (the manager's retry_after_sec basis)
    "live.p99_fresh_query_s": "lower",
    "live.restore_s": "lower",
    # fleet-router rows (config 11 sidecar, `router` block at top
    # level): end-to-end routing latency through the pick/redirect/
    # backoff core, and the totals the chaos plan makes deterministic —
    # resubmits and budget exhaustions growing means the router started
    # paying (or losing) more redirects for the same planned kill
    "router.route_s.p50": "lower",
    "router.route_s.p99": "lower",
    "router.resubmits": "lower",
    "router.budget_exhausted": "lower",
}

#: a non-fp32 run's Kendall tau-b against its own fp32 reference twin
#: below this is a HARD regression (rank agreement is the contract that
#: licenses the speed mode) — override with --tau-threshold
TAU_B_THRESHOLD = 0.99


def _get_path(doc: dict, path: str):
    node = doc
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node if isinstance(node, (int, float)) else None


def extract_rows(doc: dict) -> dict:
    """`{row_name: (value, direction)}` for every tracked numeric row a
    sidecar carries. Tolerates bare reports (no `report` wrapper) and
    pre-devcost sidecars (absent rows are just absent)."""
    if "report" not in doc and "wallclock" in doc:
        doc = {"report": doc}
    rows = {}
    for path, direction in _ROWS.items():
        v = _get_path(doc, path)
        if v is not None:
            rows[path] = (float(v), direction)
    # per-bucket throughput: one row per (slots, width) program
    for r in (doc.get("report", {}).get("per_width") or []):
        v = r.get("coalitions_per_s")
        if v is not None:
            name = (f"report.per_width[{r.get('slot_count')},"
                    f"{r.get('width')}].coalitions_per_s")
            rows[name] = (float(v), "higher")
    # per-program roofline: achieved FLOP/s per (slots, width)
    for r in ((doc.get("report", {}).get("roofline") or {})
              .get("programs") or []):
        v = r.get("achieved_flops_per_s")
        if v is not None:
            name = (f"report.roofline[{r.get('slot_count')},"
                    f"{r.get('width')}].achieved_flops_per_s")
            rows[name] = (float(v), "higher")
    return rows


def _provenance(doc: dict) -> str:
    return str(doc.get("source") or "fresh")


def _ulp_distance(a_bits: str, b_bits: str) -> int:
    """ulp distance between two hex-encoded double bit patterns (the
    ledger's value encoding — obs/numerics.py), dependency-free so the
    gate runs without importing the package."""
    import struct

    def ordinal(bits: str) -> int:
        (i,) = struct.unpack(">q", bytes.fromhex(bits))
        return i if i >= 0 else -(i & 0x7FFFFFFFFFFFFFFF)

    if a_bits == b_bits:
        return 0
    return abs(ordinal(a_bits) - ordinal(b_bits))


def _kendall_tau(a: list, b: list):
    """Tie-aware Kendall tau-b (identical lists score exactly 1.0).
    Delegates to the package's O(n log n) Knight implementation — the
    ledger holds one value per SUBSET, so a quadratic pair loop would
    hang the gate at real partner counts; the quadratic fallback below
    only covers running this script with the package unimportable, and
    caps itself rather than hang."""
    n = len(a)
    if n < 2:
        return None
    try:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from mplc_tpu.obs.numerics import kendall_tau_b
        return kendall_tau_b(a, b)
    except ImportError:
        pass
    if n > 4096:  # quadratic fallback: refuse to hang, report nothing
        return None
    conc = disc = ties_a = ties_b = 0
    for i in range(n):
        for j in range(i + 1, n):
            da, db = a[i] - a[j], b[i] - b[j]
            if da == 0 and db == 0:
                continue
            if da == 0:
                ties_a += 1
            elif db == 0:
                ties_b += 1
            elif da * db > 0:
                conc += 1
            else:
                disc += 1
    denom = ((conc + disc + ties_a) * (conc + disc + ties_b)) ** 0.5
    return (conc - disc) / denom if denom else None


def _numerics_rows(old: dict, new: dict, notes: list):
    """The value-truth gate: when BOTH sidecars carry a `numerics` block
    (obs/numerics.py ledger digest: engine fingerprint + per-subset value
    bits), any bit drift between same-game runs is a regression — v(S)
    changed, which is a correctness event, not a perf delta. Sidecars
    that PREDATE the block are skipped silently (schema growth is never
    a regression), and fingerprint mismatches are noted, never gated
    (different games are not drift)."""
    no, nn = old.get("numerics"), new.get("numerics")
    if not (isinstance(no, dict) and isinstance(nn, dict)):
        return []
    if no.get("engine_fingerprint") != nn.get("engine_fingerprint"):
        notes.append("numerics: engine fingerprints differ — different "
                     "games, value drift not gated")
        return []
    vo, vn = no.get("values") or {}, nn.get("values") or {}
    common = sorted(set(vo) & set(vn))
    if not common:
        return []
    import struct
    dists = [_ulp_distance(vo[k], vn[k]) for k in common]
    fo = [struct.unpack(">d", bytes.fromhex(vo[k]))[0] for k in common]
    fn_ = [struct.unpack(">d", bytes.fromhex(vn[k]))[0] for k in common]
    sd = sorted(dists)
    p99 = sd[min(max(int(0.99 * len(sd)), 1), len(sd)) - 1]
    tau = _kendall_tau(fo, fn_)
    rows = []
    for name, val in (("numerics.max_ulp", max(dists)),
                      ("numerics.p99_ulp", p99)):
        rows.append({"row": name, "old": 0.0, "new": float(val),
                     "delta_frac": float(val), "direction": "lower",
                     "regressed": val > 0})
    if tau is not None:
        rows.append({"row": "numerics.rank_tau", "old": 1.0,
                     "new": float(tau), "delta_frac": float(tau) - 1.0,
                     "direction": "higher", "regressed": tau < 1.0})
    if any(r["regressed"] for r in rows):
        notes.append(f"numerics: v(S) DRIFTED on {sum(1 for d in dists if d)}"
                     f"/{len(common)} subsets (max {max(dists)} ulp) — "
                     "same-fingerprint runs must be bit-identical")
    return rows


def _precision_rows(old: dict, new: dict, notes: list,
                    tau_threshold: float = TAU_B_THRESHOLD):
    """The mixed-precision gate: a sidecar carrying a `precision` block
    (bench.py `_note_precision` — a non-fp32 run's ledger diff against
    its own fp32 reference twin) gates on the pair's Kendall tau-b. The
    threshold is HARD (correctness, not a perf delta): a new-side tau-b
    below `tau_threshold` regresses regardless of the perf threshold,
    and any tau-b below 1.0 while the block claims mode fp32 is always
    a regression (an fp32 run must rank-agree with its fp32 twin
    exactly). The old side's tau-b, when present, is the displayed
    baseline; absent (e.g. an fp32 baseline sidecar, which has no
    block) it defaults to the contract value 1.0."""
    pn = new.get("precision")
    if not isinstance(pn, dict) or pn.get("tau_b") is None:
        return []
    po = old.get("precision")
    tau = float(pn["tau_b"])
    baseline = (float(po["tau_b"])
                if isinstance(po, dict) and po.get("tau_b") is not None
                else 1.0)
    hard_fp32 = str(pn.get("mode", "")) == "fp32" and tau < 1.0
    regressed = hard_fp32 or tau < tau_threshold
    rows = [{"row": "precision.tau_b", "old": baseline, "new": tau,
             "delta_frac": tau - baseline, "direction": "higher",
             "regressed": regressed}]
    ulp = pn.get("ulp") or {}
    if ulp.get("max") is not None:
        # informational, never gated: the bf16 ulp spread is the
        # documented deviation the tau gate licenses
        notes.append(f"precision: mode={pn.get('mode')} ledger pair ulp "
                     f"max={ulp.get('max')} p99={ulp.get('p99')} over "
                     f"{pn.get('common')} subsets")
    if regressed:
        notes.append(
            "precision: tau_b DROPPED below the hard gate ("
            + (f"fp32 pair must be exactly 1.0, got {tau:.4f}"
               if hard_fp32 else
               f"{tau:.4f} < {tau_threshold}") + ") — the "
            f"{pn.get('mode')} speed mode lost rank agreement with its "
            "fp32 reference")
    return rows


def diff_sidecars(old: dict, new: dict, threshold: float,
                  tau_threshold: float = TAU_B_THRESHOLD) -> dict:
    """Compare two sidecar documents. Returns
    {rows: [...], regressions: [...], notes: [...], comparable: bool}.

    A row REGRESSES when its fractional delta moves in the bad direction
    by more than `threshold` (e.g. wallclock +12% at threshold 0.10).
    Rows whose old value is 0 are skipped (no stable base)."""
    notes = []
    po, pn = _provenance(old), _provenance(new)
    comparable = po == pn
    if not comparable:
        notes.append(f"provenance mismatch: old={po} new={pn} — scales "
                     "differ, deltas reported but NOT gated")
    for side, doc in (("old", old), ("new", new)):
        if doc.get("degraded"):
            notes.append(f"{side} run was DEGRADED (retries/OOM ladder) — "
                         "not a like-for-like baseline")
    rows_old = extract_rows(old)
    rows_new = extract_rows(new)
    out_rows = []
    regressions = []
    for name in sorted(set(rows_old) & set(rows_new)):
        v_old, direction = rows_old[name]
        v_new = rows_new[name][0]
        if v_old == 0:
            continue
        delta = (v_new - v_old) / abs(v_old)
        bad = delta if direction == "lower" else -delta
        regressed = comparable and bad > threshold
        row = {"row": name, "old": v_old, "new": v_new,
               "delta_frac": delta, "direction": direction,
               "regressed": regressed}
        out_rows.append(row)
        if regressed:
            regressions.append(row)
    # the numerics (value-truth) gate rides beside the perf rows: bit
    # drift between same-fingerprint runs is always a regression (the
    # threshold does not soften correctness), but only when both sides
    # carry the block AND the provenance comparison holds
    for row in _numerics_rows(old, new, notes):
        row["regressed"] = row["regressed"] and comparable
        out_rows.append(row)
        if row["regressed"]:
            regressions.append(row)
    # the precision tau-b gate is INTRA-sidecar truth (the new run vs
    # its own fp32 reference twin), so it gates even across provenance-
    # incomparable pairs — rank agreement is not a scale question
    for row in _precision_rows(old, new, notes, tau_threshold):
        out_rows.append(row)
        if row["regressed"]:
            regressions.append(row)
    only_old = sorted(set(rows_old) - set(rows_new))
    only_new = sorted(set(rows_new) - set(rows_old))
    if only_old:
        notes.append(f"rows only in old (skipped): {only_old}")
    if only_new:
        notes.append(f"rows only in new (skipped): {only_new}")
    return {"rows": out_rows, "regressions": regressions, "notes": notes,
            "comparable": comparable, "compared_rows": len(out_rows)}


def format_diff(result: dict, label: str = "", threshold: float = 0.1
                ) -> str:
    lines = []
    head = f"bench diff{f' [{label}]' if label else ''} " \
           f"(threshold {threshold:.0%}):"
    lines.append(head)
    for note in result["notes"]:
        lines.append(f"  ! {note}")
    for row in result["rows"]:
        arrow = "REGRESSED" if row["regressed"] else (
            "improved" if (row["delta_frac"] < 0) == (
                row["direction"] == "lower") and row["delta_frac"] != 0
            else "~")
        lines.append(
            f"  {row['row']:60s} {row['old']:>12.4g} -> "
            f"{row['new']:>12.4g}  {row['delta_frac']:+.1%}  [{arrow}]")
    n = len(result["regressions"])
    lines.append(f"  {n} regression(s)" if n else "  no regressions")
    return "\n".join(lines)


def _load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _pairs(old_dir: str, new_dir: str):
    """Matching `telemetry_config*.json` names across two run dirs."""
    names_old = {os.path.basename(p) for p in glob.glob(
        os.path.join(old_dir, "telemetry_config*.json"))}
    names_new = {os.path.basename(p) for p in glob.glob(
        os.path.join(new_dir, "telemetry_config*.json"))}
    for name in sorted(names_old & names_new):
        yield name, os.path.join(old_dir, name), os.path.join(new_dir, name)
    for name in sorted(names_old ^ names_new):
        where = "old" if name in names_old else "new"
        print(f"[bench_diff] {name} present only in {where} — skipped",
              file=sys.stderr)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Diff two bench telemetry sidecars (or run dirs) "
                    "with a regression threshold.")
    ap.add_argument("old", help="baseline sidecar .json (or directory)")
    ap.add_argument("new", help="candidate sidecar .json (or directory)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="fractional regression gate (default 0.10)")
    ap.add_argument("--tau-threshold", type=float, default=TAU_B_THRESHOLD,
                    help="hard floor for a precision ledger-pair's Kendall "
                         f"tau-b (default {TAU_B_THRESHOLD}; an fp32 "
                         "pair's floor is always exactly 1.0)")
    ap.add_argument("--gate", action="store_true",
                    help="strict CI mode: exit 2 unless the diff actually "
                         "compared rows between provenance-comparable "
                         "sidecars AND a value-truth gate ran — either "
                         "the numerics gate (both sides carried a "
                         "same-fingerprint numerics block) or the "
                         "precision tau-b gate (the new side carried a "
                         "ledger-pair block). Cross-precision pairs have "
                         "DIFFERENT fingerprints by design (precision is "
                         "part of the engine fingerprint), so the "
                         "precision gate is their value truth. A gate "
                         "that compared nothing, or that silently "
                         "skipped the value bits, must not read green")
    args = ap.parse_args(argv)

    try:
        dir_mode = os.path.isdir(args.old) and os.path.isdir(args.new)
        if dir_mode:
            jobs = list(_pairs(args.old, args.new))
            if not jobs:
                # a gate that compared NOTHING must not read as green —
                # an empty/renamed artifact dir is a misconfiguration
                print(f"[bench_diff] error: no matching "
                      f"telemetry_config*.json pairs between {args.old} "
                      f"and {args.new}", file=sys.stderr)
                return 2
        else:
            jobs = [("", args.old, args.new)]
        regressed = False
        compared_total = 0
        numerics_rows = 0
        precision_rows = 0
        incomparable = 0
        for label, p_old, p_new in jobs:
            result = diff_sidecars(_load(p_old), _load(p_new),
                                   args.threshold,
                                   tau_threshold=args.tau_threshold)
            print(format_diff(result, label or os.path.basename(p_new),
                              args.threshold))
            regressed = regressed or bool(result["regressions"])
            compared_total += result.get("compared_rows", 0)
            numerics_rows += sum(1 for r in result["rows"]
                                 if r["row"].startswith("numerics."))
            precision_rows += sum(1 for r in result["rows"]
                                  if r["row"].startswith("precision.tau"))
            incomparable += 0 if result["comparable"] else 1
        if args.gate:
            problems = []
            if not compared_total:
                problems.append("zero rows compared")
            if incomparable:
                problems.append(f"{incomparable} pair(s) provenance-"
                                "incomparable (deltas not gated)")
            if not numerics_rows and not precision_rows:
                problems.append("the value-truth gate never ran "
                                "(neither a same-fingerprint numerics "
                                "block pair nor a precision ledger-pair "
                                "block)")
            if problems:
                print("[bench_diff] --gate error: "
                      + "; ".join(problems), file=sys.stderr)
                return 2
        if dir_mode and not compared_total:
            # name-matched pairs existed but every one of them diffed
            # ZERO rows (schema-disjoint sidecars — e.g. a run dir whose
            # files predate every tracked row): that is still a gate
            # that compared nothing, distinct from pairs that legally
            # skip a few newer rows (those still compare the rest)
            print("[bench_diff] error: matched pairs shared no comparable "
                  "rows — nothing was actually gated", file=sys.stderr)
            return 2
    except (OSError, ValueError) as e:
        print(f"[bench_diff] error: {e}", file=sys.stderr)
        return 2
    return 1 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
