#!/usr/bin/env python
"""Convert a span JSONL trace (MPLC_TPU_TRACE_FILE) into Chrome
trace-event JSON loadable in Perfetto (https://ui.perfetto.dev).

Usage: python scripts/trace_to_perfetto.py <trace.jsonl> [-o out.json]

The output shows the engine's compile/dispatch/harvest overlap as
per-thread tracks (engine.batch and bank.compile slices side by side is
the pipelining/AOT-overlap picture the sweep report only totals), with
flow arrows linking retries, OOM degrades and service re-queues to the
batches/slices they recovered. Tolerates a torn tail line (a process
killed mid-append) and reports how many lines were skipped.

For XLA-level device traces (*.xplane.pb from MPLC_TPU_PROFILE_DIR) use
scripts/analyze_trace.py instead — this tool covers the span-level
(host/scheduling) view.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mplc_tpu.obs.chrome_trace import convert  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="span JSONL -> Chrome trace-event JSON (Perfetto)")
    ap.add_argument("trace", help="span JSONL file (MPLC_TPU_TRACE_FILE)")
    ap.add_argument("-o", "--out", default=None,
                    help="output path (default: <trace>.chrome.json)")
    args = ap.parse_args(argv)
    if not os.path.exists(args.trace):
        ap.error(f"trace file not found: {args.trace}")
    summary = convert(args.trace, args.out)
    line = (f"{summary['out']}: {summary['events']} trace events from "
            f"{summary['records']} records, {summary['flows']} flow links")
    if summary["torn_lines"]:
        line += f", {summary['torn_lines']} torn line(s) skipped"
    print(line)
    print("load it at https://ui.perfetto.dev (or chrome://tracing)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
