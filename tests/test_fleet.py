"""Fleet sweep plane (mplc_tpu/parallel/fleet.py) + mesh satellites.

The headline invariant: a W-shard fleet sweep under
MPLC_TPU_DETERMINISTIC_REDUCE=1 merges into a value ledger with ZERO ulp
drift and Kendall tau-b == 1.0 against the single-shard run — across
shard counts, across the transient/OOM fault ladder on one shard, and
across a real OS-process boundary (workers at a DIFFERENT device count
than this test process's 8-device mesh: the cross-topology bit-identity
PR 14's deterministic mode earned, now exercised through the fleet
merge)."""

import dataclasses
import json
import os
from pathlib import Path

import numpy as np
import pytest

from mplc_tpu.obs.numerics import diff_ledgers
from mplc_tpu.parallel import fleet
from mplc_tpu.parallel.mesh import make_2d_mesh, make_multihost_mesh

REPO = Path(__file__).resolve().parents[1]

SPEC = fleet.FleetSpec()  # titanic, 3 partners, 2 epochs, deterministic


@pytest.fixture(scope="module")
def ref_fleet(tmp_path_factory):
    """The 1-shard deterministic reference (in-process, on the test
    suite's 8-device mesh) every equality test diffs against."""
    out = tmp_path_factory.mktemp("fleet_ref")
    return fleet.run_fleet(SPEC, 1, str(out), inproc=True)


# ---------------------------------------------------------------------------
# mesh satellites
# ---------------------------------------------------------------------------

def test_make_2d_mesh_raises_valueerror_on_bad_grid():
    """A mis-sized grid must raise ValueError naming the counts — a bare
    assert vanishes under python -O and would hand shard_map a silently
    wrong partition."""
    import jax
    n = len(jax.devices())
    with pytest.raises(ValueError, match=f"needs 6 devices, have {n}"):
        make_2d_mesh(3, 2, jax.devices())
    # the happy path still builds
    mesh = make_2d_mesh(n // 2, 2)
    assert dict(mesh.shape) == {"coal": n // 2, "part": 2}


def test_multihost_mesh_coal_spans_hosts_part_stays_local():
    """The N x 8 fleet mesh: `coal` spans hosts, `part` stays inside one
    host's device group (on the single-process test mesh every device
    shares process_index 0, so the shape rule is what's checkable: 8
    devices at part=2 -> [4, 2], part must divide the local count)."""
    import jax
    n = len(jax.devices())
    mesh = make_multihost_mesh(part=2)
    assert dict(mesh.shape) == {"coal": n // 2, "part": 2}
    # every part-row holds devices of ONE host (process_index constant)
    grid = mesh.devices
    for row in grid:
        assert len({getattr(d, "process_index", 0) for d in row}) == 1
    with pytest.raises(ValueError, match="divide"):
        make_multihost_mesh(part=3)
    # deterministic layout: same call, same grid
    again = make_multihost_mesh(part=2)
    assert [[d.id for d in row] for row in again.devices] \
        == [[d.id for d in row] for row in grid]


# ---------------------------------------------------------------------------
# slice planning + width pinning
# ---------------------------------------------------------------------------

def _tiny_engine(partners=4):
    sc = dataclasses.replace(SPEC, partners=partners,
                             deterministic=False).build_scenario()
    from mplc_tpu.contrib.engine import CharacteristicEngine
    return CharacteristicEngine(sc)


def test_plan_slices_is_a_bucket_granular_disjoint_cover():
    engine = _tiny_engine(partners=5)
    from mplc_tpu.contrib.shapley import powerset_order
    subsets = list(powerset_order(5))
    for W in (1, 2, 3, 4):
        slices = fleet.plan_slices(engine, subsets, W)
        assert len(slices) == W
        flat = [s for sl in slices for s in sl]
        assert len(flat) == len(set(flat)) == len(subsets)  # disjoint cover
        # bucket-granular: within each shard, every slot bucket's
        # members are contiguous runs of the full bucket order
        for sl in slices:
            widths = [engine._slot_width(len(s)) for s in sl if len(s) > 1]
            assert widths == sorted(widths)
    # deterministic
    assert fleet.plan_slices(engine, subsets, 3) \
        == fleet.plan_slices(engine, subsets, 3)


def test_pin_fleet_widths_keeps_slice_widths_at_full_sweep_plan():
    """A shard slice smaller than the full bucket must still run at the
    full sweep's batch width — identical programs across shards is what
    lets the shared bank manifest serve W-1 of W shards."""
    engine = _tiny_engine(partners=4)
    from mplc_tpu.contrib.shapley import powerset_order
    subsets = list(powerset_order(4))
    pipe = engine._slot_pipe(3)  # merge mode: sizes 2+3 ride width 3
    small = engine._planned_width(3, 3, pipe)
    pinned = engine.pin_fleet_widths(subsets)
    assert pinned, "expected a non-empty width plan"
    full = engine._planned_width(3, 3, pipe)
    # the full sweep has C(4,2)+C(4,3)=10 width-3 jobs; a 3-job slice
    # must now bucket at the full plan's width, not its own smaller one
    assert full == pinned[3] >= small
    # the OOM ladder un-pins: a degraded cap re-buckets at the degraded
    # width, never the stale plan's
    engine._cap_halvings = 1
    assert engine._planned_width(3, 3, pipe) <= full
    engine._cap_halvings = 0


# ---------------------------------------------------------------------------
# the equality contract
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("shards", [2, 4])
def test_fleet_merge_bit_identical_to_single_shard(ref_fleet, tmp_path,
                                                   shards):
    """W-shard deterministic fleet == 1-shard run, bit for bit: zero ulp
    on every subset, tau-b exactly 1.0, full coverage."""
    res = fleet.run_fleet(SPEC, shards, str(tmp_path / f"w{shards}"),
                          inproc=True, verify_against=ref_fleet.ledger)
    assert res.values == ref_fleet.values
    d = res.diff
    assert d["comparable"] and not d["drift"]
    assert d["ulp"]["max"] == 0 and d["kendall_tau"] == 1.0
    assert d["common"] == len(SPEC.all_subsets())


def test_fleet_equality_survives_fault_ladder_on_one_shard(ref_fleet,
                                                           tmp_path):
    """One shard rides the transient-retry AND OOM cap-halving rungs
    (deterministic injection); the merged ledger must still be
    bit-identical — recovery never changes v(S), even sharded."""
    res = fleet.run_fleet(
        SPEC, 2, str(tmp_path / "faulty"), inproc=True,
        per_shard_env={1: {"MPLC_TPU_FAULT_PLAN":
                           "transient@batch1,oom@batch2",
                           "MPLC_TPU_RETRY_BACKOFF_SEC": "0"}},
        verify_against=ref_fleet.ledger)
    assert not res.diff["drift"] and res.diff["kendall_tau"] == 1.0
    assert res.values == ref_fleet.values


def test_fleet_merge_refuses_partial_and_overlap(ref_fleet, tmp_path):
    out = tmp_path / "partial"
    fleet.run_shard(SPEC, 0, 2, str(out))
    # shard 1 never ran: no marker -> refusal naming the missing shard
    with pytest.raises(fleet.FleetMergeError, match=r"shards \[1\]"):
        fleet.merge_shard_results(SPEC, 2, str(out))
    # force merges what exists — a deliberate partial (the operator's
    # "those hosts are genuinely abandoned" override, same semantics as
    # merge_shards.py --force)
    values, merged, reports = fleet.merge_shard_results(
        SPEC, 2, str(out), force=True)
    assert 0 < len(values) < len(SPEC.all_subsets())
    assert merged is not None and len(reports) == 1
    # a stale done marker without a result file is also a refusal
    (out / ".shard1.done").write_text("1")
    with pytest.raises(fleet.FleetMergeError, match="no result file"):
        fleet.merge_shard_results(SPEC, 2, str(out))


def test_merge_ledgers_refuses_fingerprint_mismatch_and_overlap():
    a = {"schema": 1, "engine_fingerprint": "aaaa", "meta": {},
         "entries": {"0x3": {"value_bits": "00" * 8}}}
    b_fp = {"schema": 1, "engine_fingerprint": "bbbb", "meta": {},
            "entries": {"0x5": {"value_bits": "00" * 8}}}
    with pytest.raises(fleet.FleetMergeError, match="different games"):
        fleet.merge_ledgers([a, b_fp])
    b_dup = {"schema": 1, "engine_fingerprint": "aaaa", "meta": {},
             "entries": {"0x3": {"value_bits": "00" * 8}}}
    with pytest.raises(fleet.FleetMergeError, match="more than one shard"):
        fleet.merge_ledgers([a, b_dup])
    merged = fleet.merge_ledgers([a, {"schema": 1,
                                      "engine_fingerprint": "aaaa",
                                      "meta": {},
                                      "entries": {"0x5": {
                                          "value_bits": "00" * 8}}}])
    assert set(merged["entries"]) == {"0x3", "0x5"}
    assert merged["meta"]["fleet_shards"] == 2


def test_merged_cache_is_loadable_by_an_engine(ref_fleet):
    """The coordinator's merged memo is a full valid engine cache:
    load_cache accepts it (checksum + fingerprint) and a fully-memoized
    evaluate() returns the merged values without training."""
    path = os.path.join(ref_fleet.out_dir, "cache_merged.json")
    assert os.path.exists(path)
    with fleet._env_overlay({"MPLC_TPU_DETERMINISTIC_REDUCE": "1"}):
        sc = SPEC.build_scenario()
        from mplc_tpu.contrib.engine import CharacteristicEngine
        engine = CharacteristicEngine(sc)
    engine.load_cache(path)
    before = engine.first_charac_fct_calls_count
    got = engine.evaluate(SPEC.all_subsets())
    assert engine.first_charac_fct_calls_count == before  # zero training
    want = np.array([ref_fleet.values[s] for s in SPEC.all_subsets()])
    np.testing.assert_array_equal(got, want)


def test_fleet_subprocess_workers_cross_topology_equality(ref_fleet,
                                                          tmp_path):
    """The real process boundary: 2 worker SUBPROCESSES at ONE device
    each (vs this suite's 8-device mesh) produce a merged ledger
    bit-identical to the in-process 1-shard reference — process-axis
    sharding composes with PR 14's cross-topology determinism. Also
    checks the merge_shards-style completion markers landed."""
    env = {"PYTHONPATH": str(REPO),
           "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
           "HOME": os.environ.get("HOME", "/root"),
           "JAX_PLATFORMS": "cpu",
           "MPLC_TPU_SYNTH_SCALE":
               os.environ.get("MPLC_TPU_SYNTH_SCALE", "0.02"),
           "JAX_COMPILATION_CACHE_DIR": str(REPO / ".jax_cache")}
    out = tmp_path / "subproc"
    res = fleet.run_fleet(SPEC, 2, str(out), env=env, devices_per_shard=1,
                          timeout=600, verify_against=ref_fleet.ledger)
    assert not res.diff["drift"] and res.diff["kendall_tau"] == 1.0
    assert res.values == ref_fleet.values
    reps = {}
    for i in range(2):
        assert (out / f".shard{i}.done").exists()
        rep = json.loads((out / f"result_shard{i}.json").read_text())
        assert rep["devices"] == 1
        assert rep["deterministic"] is True
        reps[i] = rep
    assert (out / "ledger_merged.json").exists()

    # -- the fleet observability plane, over the same real-subprocess run --
    from mplc_tpu.obs import fleet_view
    from mplc_tpu.obs import metrics as obs_metrics
    # trace context: both workers echoed the coordinator's run id + their
    # shard identity and clock readings in the handshake
    run_ids = {reps[i]["fleet"]["run_id"] for i in (0, 1)}
    assert len(run_ids) == 1 and run_ids.pop().startswith("fleet-")
    assert {reps[i]["fleet"]["shard_id"] for i in (0, 1)} \
        == {"shard0", "shard1"}
    for i in (0, 1):
        clk = reps[i]["clock"]
        assert clk["coord_spawn_ts"] is not None
        assert clk["worker_end_ts"] >= clk["worker_start_ts"]
    # ONE merged Perfetto timeline: a track group per shard, a flow link
    # per dispatch, every shard rebased onto the coordinator clock
    merged = fleet_view.merge_fleet_traces(str(out))
    assert merged["shard_tracks"] == 2 and merged["flow_links"] == 2
    assert set(merged["offsets"]) == {"0", "1"}
    # same-host subprocesses share a clock: the midpoint offsets must be
    # tiny (sanity for the rebase arithmetic, not a skew measurement)
    assert all(abs(off) < 60.0 for off in merged["offsets"].values())
    # ONE aggregated snapshot: one entry per shard, and the merged
    # histograms are EXACTLY the pooled per-shard samples — merged
    # bucket arrays are elementwise sums and the quantiles re-derive
    # from them with the same estimator
    snap = fleet_view.cluster_snapshot(out_dir=str(out))
    assert set(snap["shards"]) == {"shard0", "shard1"}
    assert snap["fresh_shards"] == 2 and snap["merged_sources"] == 2
    per_shard = [reps[i]["metrics"]["histograms"] for i in (0, 1)]
    checked = 0
    for key, mh in snap["merged"]["histograms"].items():
        pooled = [0] * len(mh["bucket_counts"])
        for hs in per_shard:
            for j, c in enumerate((hs.get(key) or {})
                                  .get("bucket_counts") or []):
                pooled[j] += c
        assert mh["bucket_counts"] == pooled, key
        if mh["count"]:
            checked += 1
            for q, want in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
                assert mh[q] == obs_metrics.bucket_quantile(
                    pooled, mh["count"], mh["min"], mh["max"], want), key
    assert checked > 0  # real histograms flowed through the merge


# ---------------------------------------------------------------------------
# cross-shard service state
# ---------------------------------------------------------------------------

def test_publish_and_cluster_view(tmp_path):
    d = str(tmp_path / "state")
    fleet.publish_shard_state(d, "alpha", {"queue_depth": 3,
                                           "jobs_pending": 5})
    fleet.publish_shard_state(d, "beta", {"queue_depth": 1,
                                          "jobs_pending": 1})
    view = fleet.cluster_view(d)
    assert view["live_shards"] == 2 and view["stale_shards"] == 0
    assert view["cluster_queue_depth"] == 4
    assert view["cluster_jobs_pending"] == 6
    assert view["least_loaded"] == "beta"
    # stale shards are flagged, kept visible, and excluded from totals
    stale = os.path.join(d, "shard_alpha.json")
    doc = json.loads(open(stale).read())
    doc["ts"] -= 3600
    with open(stale, "w") as f:
        json.dump(doc, f)
    view = fleet.cluster_view(d)
    assert view["stale_shards"] == 1 and view["cluster_queue_depth"] == 1
    assert view["shards"]["alpha"]["stale"] is True
    # a shard that published closed=true (shutting down) is never a
    # redirect target and leaves the live totals
    fleet.publish_shard_state(d, "beta", {"queue_depth": 1,
                                          "jobs_pending": 1,
                                          "closed": True})
    fleet.publish_shard_state(d, "gamma", {"queue_depth": 7,
                                           "jobs_pending": 7})
    view = fleet.cluster_view(d)
    assert view["least_loaded"] == "gamma"
    assert view["cluster_queue_depth"] == 7
    # an empty/missing dir degrades to an empty view, never raises
    empty = fleet.cluster_view(str(tmp_path / "nope"))
    assert empty["live_shards"] == 0 and empty["least_loaded"] is None


def test_service_publishes_fleet_state_and_healthz_block(tmp_path,
                                                         monkeypatch):
    from mplc_tpu.service import SweepService
    d = str(tmp_path / "fleet_state")
    monkeypatch.setenv("MPLC_TPU_FLEET_STATE_DIR", d)
    monkeypatch.setenv("MPLC_TPU_FLEET_SHARD_ID", "alpha")
    svc = SweepService(start=False)
    try:
        svc._publish_fleet_state(force=True)
        hv = svc.health_view()
        assert "fleet" in hv
        assert hv["fleet"]["shard_id"] == "alpha"
        assert "alpha" in hv["fleet"]["shards"]
        assert hv["fleet"]["shards"]["alpha"]["queue_depth"] == 0
    finally:
        svc.shutdown(drain=False)


def test_service_overload_carries_cluster_redirect_hint(tmp_path,
                                                        monkeypatch):
    import types

    from mplc_tpu.service import SweepService
    from mplc_tpu.service.scheduler import ServiceOverloaded
    d = str(tmp_path / "fleet_state")
    monkeypatch.setenv("MPLC_TPU_FLEET_STATE_DIR", d)
    monkeypatch.setenv("MPLC_TPU_FLEET_SHARD_ID", "alpha")
    fleet.publish_shard_state(d, "beta", {"queue_depth": 0,
                                          "jobs_pending": 0})
    svc = SweepService(start=False, max_pending=0)
    try:
        with pytest.raises(ServiceOverloaded) as exc:
            svc.submit(types.SimpleNamespace(partners_count=3))
        assert "beta" in str(exc.value)
        assert exc.value.cluster is not None
        assert exc.value.cluster["least_loaded"] == "beta"
    finally:
        svc.shutdown(drain=False)


def test_service_without_fleet_dir_is_unchanged(monkeypatch):
    from mplc_tpu.service import SweepService
    monkeypatch.delenv("MPLC_TPU_FLEET_STATE_DIR", raising=False)
    svc = SweepService(start=False)
    try:
        hv = svc.health_view()
        assert "fleet" not in hv
        svc._publish_fleet_state(force=True)  # no-op, no dir created
    finally:
        svc.shutdown(drain=False)
