"""Fixed-seed regression pins for the adaptive estimators' allocation math.

VERDICT r4 weak #6/#7: two estimator-fidelity corners (the ITMCS
interpolation-slope freeze, the SMCS/WR_SMC variance bookkeeping) reproduce
reference quirks with no oracle locking them, and the IS weight identity was
only argued, not enumerated. Each test here re-derives the estimator's
arithmetic INDEPENDENTLY in plain NumPy — consuming the identical rng stream
where the estimator is stochastic — on an analytic characteristic function,
so any drift in the allocation math (a "fixed" slope, an un-squared
variance, a reweighted proposal) fails loudly.

Reference semantics pinned:
  - ITMCS interpolation arithmetic: the slope (v_all - prefix) / size_of_rest
    computed over the REMAINING PERMUTED partners and applied per permuted
    step (/root/reference/mplc/contributivity.py:257-322; mplc_tpu
    contrib/contributivity.py:233-237). Two deliberate notes: (a) the
    reference sums sizes by perm POSITION j..n-1 — an upstream indexing bug;
    this repo uses the permuted partners, and the oracle pins that choice;
    (b) the "slope freeze at first truncation" is mathematically
    unobservable — the interpolated prefix moves linearly toward v_all, so
    a recomputed slope telescopes to the frozen one; the replica below
    still fails if the arithmetic (not just the caching) drifts.
  - SMCS accumulates var[k] += sigma2[k,s]**2 / n_ks (sigma2 SQUARED — the
    reference's variance-of-variance bookkeeping, reference :727-819).
  - WR_SMC applies the finite-population factor (1/m - 1/C(N-1,s)) to the
    per-stratum sample variance (reference :823-938).
  - IS: for any proposal tabulated from |approx increments|, the importance
    weight must make the estimator exactly unbiased — enumerated here, no
    sampling (reference :326-439).
"""

import numpy as np
import pytest
from scipy.stats import norm

from mplc_tpu.contrib.contributivity import Contributivity
from mplc_tpu.contrib.sampling import (ExactSubsetSampler,
                                       SizeStratifiedSubsetSampler,
                                       WithoutReplacementRanks,
                                       combination_mask_table, randbelow,
                                       shapley_size_prob, unrank_combination)
from mplc_tpu.contrib.shapley import (powerset_order,
                                      shapley_from_characteristic)

from test_contrib import fake_scenario

from math import comb


def saturating_game(phi, lift=1.3):
    """Non-additive: v(S) = min(1, lift * sum phi_i). The min() kink makes
    marginals permutation-dependent, so truncation fires mid-permutation
    and per-stratum variances differ — the adaptive paths all activate."""
    return lambda s: min(1.0, lift * sum(phi[i] for i in s))


def full_table(n, v_fn):
    t = {(): 0.0}
    for s in powerset_order(n):
        t[s] = v_fn(s)
    return t


# ---------------------------------------------------------------------------
# ITMCS: the interpolation slope is frozen at the first truncated position
# ---------------------------------------------------------------------------

def itmcs_oracle(n, v_fn, sizes, sv_accuracy, alpha, truncation,
                 freeze_slope=True, perm_batch=16, seed=17):
    """Independent NumPy walk of the ITMCS estimator. freeze_slope=False
    recomputes the slope at every truncated step — provably equivalent (the
    telescoping argument in the module docstring); asserted below as a
    consistency check on the replica itself."""
    rng = np.random.default_rng(seed)
    q = norm.ppf((1 - alpha) / 2)
    v_all = v_fn(tuple(range(n)))
    sizes = np.asarray(sizes)
    contributions = np.zeros((0, n))
    t, v_max = 0, 0.0
    while t < 100 or t < q ** 2 * v_max / sv_accuracy ** 2:
        perms = [rng.permutation(n) for _ in range(perm_batch)]
        rows = np.zeros((perm_batch, n))
        for k in range(perm_batch):
            prefix = 0.0
            slope = None
            for j in range(n):
                if abs(v_all - prefix) >= truncation:
                    new_val = v_fn(tuple(sorted(perms[k][:j + 1])))
                else:
                    if slope is None or not freeze_slope:
                        slope = (v_all - prefix) / max(sizes[perms[k][j:]].sum(), 1)
                    new_val = prefix + slope * sizes[perms[k][j]]
                rows[k, perms[k][j]] = new_val - prefix
                prefix = new_val
        contributions = np.vstack([contributions, rows])
        t += perm_batch
        v_max = np.max(np.var(contributions, axis=0))
    return np.mean(contributions, axis=0)


def test_itmcs_interpolation_arithmetic_pinned():
    n = 4
    phi = [0.05, 0.15, 0.3, 0.5]
    v_fn = saturating_game(phi)
    sc = fake_scenario(n, v_fn)
    sizes = [len(p.y_train) for p in sc.partners_list]

    c = Contributivity(sc)
    c.interpol_TMC(sv_accuracy=0.05, alpha=0.9, truncation=0.3)

    frozen = itmcs_oracle(n, v_fn, sizes, 0.05, 0.9, 0.3, freeze_slope=True)
    refit = itmcs_oracle(n, v_fn, sizes, 0.05, 0.9, 0.3, freeze_slope=False)

    # the telescoping equivalence must hold on the replica itself
    np.testing.assert_allclose(frozen, refit, atol=1e-12)
    # the estimator's arithmetic matches the independent replica — note the
    # replica interpolates: agreement at 1e-12 proves the engine
    # interpolated identically, not that it evaluated everything exactly
    np.testing.assert_allclose(c.contributivity_scores, frozen, atol=1e-12)


# ---------------------------------------------------------------------------
# SMCS: adaptive allocation + the sigma2**2 / n variance bookkeeping
# ---------------------------------------------------------------------------

def smcs_oracle(n, v_fn, sv_accuracy, alpha, seed=17):
    """Independent replica of the stratified-MC loop, same rng stream.
    Statistics are recomputed from the raw increment lists each iteration
    (np.var / np.mean), not carried incrementally — so any drift in the
    estimator's bookkeeping (not just its draws) diverges."""
    rng = np.random.default_rng(seed)
    gamma, beta = 0.2, 0.0075
    t, v_max = 0, 0.0
    sigma2 = np.zeros((n, n))
    mu = np.zeros((n, n))
    continuer = np.ones((n, n), bool)
    incs = [[[] for _ in range(n)] for _ in range(n)]
    table = full_table(n, v_fn)
    while continuer.any() or (1 - alpha) < v_max / sv_accuracy ** 2:
        t += 1
        e = (1 + 1 / (1 + np.exp(gamma / beta))
             - 1 / (1 + np.exp(-(t - gamma * n) / (beta * n))))
        for k in range(n):
            if sigma2[k].sum() == 0:
                p = np.repeat(1 / n, n)
            else:
                p = np.repeat(1 / n, n) * (1 - e) + sigma2[k] / sigma2[k].sum() * e
            strata = rng.choice(np.arange(n), 1, p=p)[0]
            u = rng.uniform()
            others = np.delete(np.arange(n), k)
            total = comb(n - 1, int(strata))
            idx = min(int(u * total), total - 1)
            S = tuple(int(i) for i in
                      others[unrank_combination(n - 1, int(strata), idx)])
            inc = table[tuple(sorted(S + (k,)))] - table[S]
            incs[k][strata].append(inc)
            sigma2[k, strata] = np.var(incs[k][strata])
            mu[k, strata] = np.mean(incs[k][strata])
        var = np.zeros(n)
        for k in range(n):
            for s in range(n):
                m = len(incs[k][s])
                var[k] += np.inf if m == 0 else sigma2[k, s] ** 2 / m
                if m > 20:
                    continuer[k, s] = False
            var[k] /= n ** 2
        v_max = var.max()
    return np.mean(mu, axis=1), np.sqrt(var)


def test_smcs_allocation_and_variance_pinned():
    n = 4
    phi = [0.05, 0.15, 0.3, 0.5]
    v_fn = saturating_game(phi)
    sc = fake_scenario(n, v_fn)

    c = Contributivity(sc)
    c.Stratified_MC(sv_accuracy=0.05, alpha=0.95)

    shap, std = smcs_oracle(n, v_fn, 0.05, 0.95)
    np.testing.assert_allclose(c.contributivity_scores, shap, atol=1e-12)
    np.testing.assert_allclose(c.scores_std, std, atol=1e-12)


# ---------------------------------------------------------------------------
# WR_SMC: without-replacement pools + the finite-population factor
# ---------------------------------------------------------------------------

def wr_smc_oracle(n, v_fn, sv_accuracy, alpha, seed=17):
    """Independent replica of the without-replacement stratified loop. The
    per-stratum variance uses np.var(ddof=1) and the factor
    (1/m - 1/C(n-1, strata)) — algebraically the reference's factorial form,
    derived separately from the estimator's."""
    rng = np.random.default_rng(seed)
    t, v_max = 0, 0.0
    sigma2 = np.zeros((n, n))
    mu = np.zeros((n, n))
    continuer = np.ones((n, n), bool)
    incs = [[[] for _ in range(n)] for _ in range(n)]
    pools = [[WithoutReplacementRanks(comb(n - 1, s)) for s in range(n)]
             for _ in range(n)]
    table = full_table(n, v_fn)
    while continuer.any() or (1 - alpha) < v_max / sv_accuracy ** 2:
        t += 1
        for k in range(n):
            if continuer[k].any():
                p = continuer[k].astype(float) / continuer[k].sum()
            elif sigma2[k].sum() == 0:
                continue
            else:
                p = sigma2[k] / sigma2[k].sum()
            strata = rng.choice(np.arange(n), 1, p=p)[0]
            if pools[k][strata].total <= 0:
                continuer[k, strata] = False
                continue
            rank = pools[k][strata].pop_random(rng)
            others = np.delete(np.arange(n), k)
            S = tuple(int(i) for i in
                      others[unrank_combination(n - 1, int(strata), rank)])
            inc = table[tuple(sorted(S + (k,)))] - table[S]
            incs[k][strata].append(inc)
            m = len(incs[k][strata])
            mu[k, strata] = np.mean(incs[k][strata])
            raw = np.var(incs[k][strata], ddof=1) if m > 1 else 0.0
            sigma2[k, strata] = raw * (1.0 / m - 1.0 / comb(n - 1, int(strata)))
        var = np.zeros(n)
        for k in range(n):
            for s in range(n):
                m = len(incs[k][s])
                var[k] += np.inf if m == 0 else sigma2[k, s] ** 2 / m
                if m > 20 or m >= comb(n - 1, s):
                    continuer[k, s] = False
            var[k] /= n ** 2
        v_max = var.max()
    return np.mean(mu, axis=1), np.sqrt(var)


def test_wr_smc_allocation_and_variance_pinned():
    n = 4
    phi = [0.05, 0.15, 0.3, 0.5]
    v_fn = saturating_game(phi)
    sc = fake_scenario(n, v_fn)

    c = Contributivity(sc)
    c.without_replacment_SMC(sv_accuracy=0.05, alpha=0.95)

    shap, std = wr_smc_oracle(n, v_fn, 0.05, 0.95)
    np.testing.assert_allclose(c.contributivity_scores, shap, atol=1e-12)
    np.testing.assert_allclose(c.scores_std, std, atol=1e-12)


# ---------------------------------------------------------------------------
# IS weight identity: exact unbiasedness by enumeration, both samplers,
# on a NON-degenerate (non-constant-increment) game
# ---------------------------------------------------------------------------

def _true_sv(n, v_fn):
    return shapley_from_characteristic(n, full_table(n, v_fn))


@pytest.mark.parametrize("k", [0, 2, 4])
def test_exact_sampler_weight_identity(k):
    n = 5
    phi = [0.05, 0.1, 0.15, 0.3, 0.4]
    v_fn = saturating_game(phi)
    table = full_table(n, v_fn)
    members = np.delete(np.arange(n), k)

    def batch_fn(masks):
        # a deliberately IMPERFECT increment model (biased, non-constant):
        # weights must cancel any proposal shape exactly
        return 0.3 + (masks @ np.linspace(1, 2, n - 1)) ** 1.5

    s = ExactSubsetSampler(n, k, batch_fn)
    # E[increment * weight] under the tabulated proposal, enumerated:
    # p(idx) = P_shapley(|S|)|f(S)| / renorm, weight = renorm / |f(S)|
    probs = np.array([shapley_size_prob(int(sz), n)
                      for sz in combination_mask_table(n - 1)[1]])
    est = 0.0
    for idx in range(len(s.masks)):
        S = tuple(int(i) for i in members[s.masks[idx]])
        inc = table[tuple(sorted(S + (k,)))] - table[S]
        p_idx = probs[idx] * s.f[idx] / s.renorm
        _, w = s.draw(max(s._cdf[idx] - 1e-12, 0.0))
        est += p_idx * inc * w
    np.testing.assert_allclose(est, _true_sv(n, v_fn)[k], atol=1e-10)


@pytest.mark.parametrize("k", [0, 3])
def test_stratified_sampler_weight_identity(k):
    n = 5
    phi = [0.05, 0.1, 0.15, 0.3, 0.4]
    v_fn = saturating_game(phi)
    table = full_table(n, v_fn)
    members = np.delete(np.arange(n), k)

    def batch_fn(masks):
        return 0.3 + (masks @ np.linspace(1, 2, n - 1)) ** 1.5

    s = SizeStratifiedSubsetSampler(n, k, batch_fn,
                                    np.random.default_rng(3))
    # E over (size ~ p_l, S | size ~ uniform), enumerated per stratum:
    # weight(l) = 1/(n p_l) must cancel p_l for ANY probe quality
    from itertools import combinations as it_comb
    est = 0.0
    for length in range(n):
        sub_mean = np.mean([
            table[tuple(sorted(tuple(int(i) for i in S) + (k,)))]
            - table[tuple(int(i) for i in S)]
            for S in it_comb(members, length)])
        est += s._p[length] * sub_mean * s._weight_per_size[length]
    np.testing.assert_allclose(est, _true_sv(n, v_fn)[k], atol=1e-10)


# ---------------------------------------------------------------------------
# IS_lin end-to-end fixed-seed pin: loop + sampler + weights reproduced
# ---------------------------------------------------------------------------

def is_lin_oracle(n, v_fn, sizes, sv_accuracy, alpha, seed=17, block=8):
    """Independent replica of IS_lin: tabulates the linear-interpolation
    proposal with its own enumeration (must coincide with the estimator's
    size-ascending lexicographic table to stay rng-synchronized — that
    order is itself reference semantics) and re-runs the sampling loop."""
    rng = np.random.default_rng(seed)
    q = -norm.ppf((1 - alpha) / 2)
    table = full_table(n, v_fn)
    v_all = table[tuple(range(n))]
    sizes = np.asarray(sizes, float)

    cdfs, renorms, fs, mask_tables = [], [], [], []
    for k in range(n):
        members = np.delete(np.arange(n), k)
        first = table[(k,)]
        last = v_all - table[tuple(sorted(set(range(n)) - {k}))]
        rows, szs = combination_mask_table(n - 1)
        beta = (rows @ sizes[members]) / sizes.sum()
        f = np.abs((1 - beta) * first + beta * last)
        w = np.array([shapley_size_prob(int(x), n) for x in szs]) * f
        cdfs.append(np.cumsum(w) / w.sum())
        renorms.append(w.sum())
        fs.append(f)
        mask_tables.append((rows, members))

    contributions = []
    t, v_max = 0, 0.0
    while t < 100 or t < 4 * q ** 2 * v_max / sv_accuracy ** 2:
        for _ in range(block):
            row = np.zeros(n)
            for k in range(n):
                u = rng.uniform()
                idx = min(int(np.searchsorted(cdfs[k], u, side="right")),
                          len(cdfs[k]) - 1)
                rows, members = mask_tables[k]
                S = tuple(int(i) for i in members[rows[idx]])
                inc = table[tuple(sorted(S + (k,)))] - table[S]
                row[k] = inc * renorms[k] / max(fs[k][idx], 1e-300)
            contributions.append(row)
        t += block
        v_max = np.max(np.var(np.asarray(contributions), axis=0))
    return np.mean(np.asarray(contributions), axis=0)


def test_is_lin_fixed_seed_pinned():
    n = 4
    phi = [0.05, 0.15, 0.3, 0.5]
    v_fn = saturating_game(phi)
    sc = fake_scenario(n, v_fn)
    sizes = [len(p.y_train) for p in sc.partners_list]

    c = Contributivity(sc)
    c.IS_lin(sv_accuracy=0.05, alpha=0.95)

    oracle = is_lin_oracle(n, v_fn, sizes, 0.05, 0.95)
    np.testing.assert_allclose(c.contributivity_scores, oracle, atol=1e-12)


# ---------------------------------------------------------------------------
# randbelow: the big-int uniform used by SMCS/WR_SMC above 2^53
# ---------------------------------------------------------------------------

def test_randbelow_matches_rng_bytes_stream():
    # same rejection walk, re-derived; also pins the byte order/shift
    n = comb(60, 25)  # > 2^53: the path float inverse-CDF can't take
    rng1, rng2 = np.random.default_rng(9), np.random.default_rng(9)
    for _ in range(50):
        v = randbelow(rng1, n)
        bits = n.bit_length()
        nbytes = (bits + 7) // 8
        while True:
            r = int.from_bytes(rng2.bytes(nbytes), "little") >> (nbytes * 8 - bits)
            if r < n:
                break
        assert v == r < n
