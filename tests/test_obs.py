"""The observability layer: span nesting/timing, JSONL schema round-trip,
metrics snapshot correctness, the no-op path with MPLC_TPU_TRACE_FILE
unset, compile-event tracking, and an end-to-end smoke test that a tiny
CharacteristicEngine sweep produces a well-formed sweep report whose memo
accounting, padding waste and epoch counts match hand-computed values."""

import json
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mplc_tpu.obs import metrics, report, trace


@pytest.fixture(autouse=True)
def _clean_obs(monkeypatch):
    """Isolate each test: no ambient trace file, fresh metrics registry."""
    monkeypatch.delenv("MPLC_TPU_TRACE_FILE", raising=False)
    metrics.reset()
    yield
    metrics.reset()


# -- spans -------------------------------------------------------------------

def test_span_nesting_and_timing():
    with trace.collect() as recs:
        with trace.span("outer", label="a") as outer:
            with trace.span("inner") as inner:
                pass
        with trace.span("sibling") as sib:
            pass
    assert [r["name"] for r in recs] == ["inner", "outer", "sibling"]
    by_name = {r["name"]: r for r in recs}
    # nesting: inner's parent is outer; siblings are roots
    assert by_name["inner"]["parent"] == by_name["outer"]["id"]
    assert by_name["outer"]["parent"] is None
    assert by_name["sibling"]["parent"] is None
    # timing: monotonic durations, outer covers inner
    assert outer.duration >= inner.duration >= 0.0
    assert by_name["outer"]["dur"] == outer.duration
    assert by_name["outer"]["attrs"] == {"label": "a"}
    assert sib.duration >= 0.0


def test_start_span_end_and_cancel():
    with trace.collect() as recs:
        sp = trace.start_span("explicit", k=1)
        sp.end()
        dropped = trace.start_span("dropped")
        dropped.cancel()
        # cancel still measures (contributivity's early-exit path relies
        # on end/cancel both recording duration)
        assert dropped.duration is not None
    assert [r["name"] for r in recs] == ["explicit"]
    # double-end is idempotent
    d = sp.duration
    sp.end()
    assert sp.duration == d


def test_leaked_inner_span_does_not_corrupt_nesting():
    with trace.collect() as recs:
        outer = trace.start_span("outer")
        trace.start_span("leaked")  # never ended
        outer.end()                 # pops through the leaked span
        with trace.span("next"):
            pass
    nxt = [r for r in recs if r["name"] == "next"][0]
    assert nxt["parent"] is None


def test_event_records_external_duration():
    import time as _time
    before = _time.time()
    with trace.collect() as recs:
        trace.event("trainer.compile", dur=1.25, fn="unit")
    assert recs[0]["dur"] == 1.25
    assert recs[0]["attrs"] == {"fn": "unit"}
    # ts marks the interval's START: events are emitted AFTER the
    # measured work, so ts is backdated by dur (timeline consumers would
    # otherwise draw the slice one duration too late)
    assert recs[0]["ts"] <= before - 1.25 + 1.0
    assert recs[0]["ts"] >= before - 1.25 - 1.0


def test_spans_are_thread_safe():
    with trace.collect() as recs:
        def work(tag):
            with trace.span(f"outer-{tag}"):
                with trace.span(f"inner-{tag}"):
                    pass
        threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert len(recs) == 8
    by_name = {r["name"]: r for r in recs}
    for i in range(4):
        # each thread's nesting is private: inner-i parents to outer-i
        assert by_name[f"inner-{i}"]["parent"] == by_name[f"outer-{i}"]["id"]


def test_worker_thread_spans_never_parent_to_submitter():
    """Cross-thread span parentage (the sweep-service shape): a worker
    thread's spans must NOT link to spans the SUBMITTING thread holds
    open while the worker runs — `parent` is per-thread nesting, never
    cross-thread causality. Pinned concurrently: the submitter keeps its
    span open for the worker's whole lifetime."""
    worker_done = threading.Event()
    worker_recs = {}

    def worker():
        # runs strictly inside the submitter's open "submit" span
        with trace.span("service.slice", tenant="t0") as outer:
            with trace.span("engine.dispatch") as inner:
                pass
        worker_recs["outer"] = outer
        worker_recs["inner"] = inner
        worker_done.set()

    with trace.collect() as recs:
        with trace.span("submit") as submit_span:
            t = threading.Thread(target=worker)
            t.start()
            assert worker_done.wait(10)
            t.join()
    by_id = {r["id"]: r for r in recs}
    slice_rec = next(r for r in recs if r["name"] == "service.slice")
    dispatch_rec = next(r for r in recs if r["name"] == "engine.dispatch")
    # the worker's root span is a ROOT, not a child of the submitter's
    # open span...
    assert slice_rec["parent"] is None
    # ...its own nesting is intact...
    assert dispatch_rec["parent"] == slice_rec["id"]
    # ...and no record of the worker thread parents into the submitter's
    submit_rec = by_id[submit_span.id]
    assert slice_rec["thread"] != submit_rec["thread"]
    for r in recs:
        if r["thread"] != slice_rec["thread"]:
            continue
        parent = r.get("parent")
        if parent is not None:
            assert by_id[parent]["thread"] == r["thread"]


def test_flight_ring_is_always_on_and_bounded():
    """Every closed span/event lands in the flight-recorder ring even
    with NO sink or collector active, and the ring is bounded."""
    ring_before = len(trace.flight_records())
    with trace.span("engine.evaluate", requested=1):
        pass
    trace.event("engine.fault", kind="transient", site="dispatch",
                ordinal=1)
    ring = trace.flight_records()
    assert len(ring) >= min(ring_before + 2, trace._flight_ring.maxlen)
    names = [r["name"] for r in ring[-2:]]
    assert names == ["engine.evaluate", "engine.fault"]
    assert trace._flight_ring.maxlen == 512  # env-unset default


# -- JSONL sink --------------------------------------------------------------

def test_jsonl_sink_round_trip(tmp_path, monkeypatch):
    path = tmp_path / "trace.jsonl"
    monkeypatch.setenv("MPLC_TPU_TRACE_FILE", str(path))
    with trace.span("engine.evaluate", requested=3, missing=2):
        with trace.span("engine.dispatch", width=8):
            pass
    lines = path.read_text().strip().splitlines()
    assert len(lines) == 2
    recs = [json.loads(l) for l in lines]
    for r in recs:
        assert set(r) == {"name", "id", "parent", "ts", "dur", "thread",
                          "attrs"}
        assert isinstance(r["dur"], float) and r["dur"] >= 0.0
    dispatch, evaluate = recs  # inner span closes (and is written) first
    assert dispatch["name"] == "engine.dispatch"
    assert dispatch["parent"] == evaluate["id"]
    assert evaluate["attrs"] == {"requested": 3, "missing": 2}


def test_noop_when_trace_file_unset(tmp_path):
    before = set(tmp_path.iterdir())
    with trace.span("hot.path", width=16) as sp:
        pass
    # duration still measured, but nothing emitted anywhere
    assert sp.duration is not None
    assert set(tmp_path.iterdir()) == before
    # the sink resolves to None with the env unset (a handle left over
    # from an earlier traced region is closed on re-sync)
    assert trace._sink_file() is None


# -- metrics -----------------------------------------------------------------

def test_metrics_snapshot_correctness():
    metrics.counter("c").inc()
    metrics.counter("c").inc(2.5)
    metrics.gauge("g").set(7)
    metrics.gauge("hw").set_max(10)
    metrics.gauge("hw").set_max(4)      # lower: high-water keeps 10
    for v in (0.0, 0.5, 1.0):
        metrics.histogram("h").observe(v)
    snap = metrics.snapshot()
    assert snap["counters"]["c"] == 3.5
    assert snap["gauges"]["g"] == 7
    assert snap["gauges"]["hw"] == 10
    # 0.5 and 1.0 sit exactly on log2 bucket bounds, so the estimates
    # are exact here
    h = dict(snap["histograms"]["h"])
    buckets = h.pop("bucket_counts")
    assert h == {
        "count": 3, "sum": 1.5, "min": 0.0, "max": 1.0, "mean": 0.5,
        "p50": 0.5, "p95": 1.0, "p99": 1.0}
    # the raw per-bucket counts ride the snapshot (the fleet merge's
    # exactness hinges on them): one slot per bound plus +Inf, and they
    # account for every observation
    assert len(buckets) == len(metrics.LOG_BUCKET_BOUNDS) + 1
    assert sum(buckets) == 3
    # registry is get-or-create; a name can't silently change type
    with pytest.raises(TypeError):
        metrics.gauge("c")
    metrics.reset()
    assert metrics.snapshot() == {"counters": {}, "gauges": {},
                                  "histograms": {}}


def test_labeled_metrics_are_distinct_series():
    """counter(name, tenant=...) creates one metric per (name, labels)
    pair, keyed `name{k=v}` in the snapshot; the unlabeled metric keeps
    its plain-name key (pre-label snapshot consumers unchanged)."""
    metrics.counter("svc.jobs").inc()
    metrics.counter("svc.jobs", tenant="a").inc(2)
    metrics.counter("svc.jobs", tenant="b").inc(3)
    # same labels -> same object, regardless of kwarg order games
    assert metrics.counter("svc.jobs", tenant="a") is \
        metrics.counter("svc.jobs", tenant="a")
    snap = metrics.snapshot()["counters"]
    assert snap["svc.jobs"] == 1
    assert snap["svc.jobs{tenant=a}"] == 2
    assert snap["svc.jobs{tenant=b}"] == 3
    # a labeled name can't silently change type either
    with pytest.raises(TypeError):
        metrics.histogram("svc.jobs", tenant="a")


def test_histogram_log_bucket_quantiles():
    """The fixed log2 buckets give p50/p95/p99 within one bucket (2x) of
    the true quantile, clamped to the observed range."""
    h = metrics.histogram("lat")
    for i in range(1, 101):
        h.observe(i / 100.0)  # 0.01 .. 1.00
    assert h.quantile(0.50) is not None
    # true p50 = 0.50; bucket upper bound is the next power of two
    assert 0.5 <= h.quantile(0.50) <= 1.0
    assert 0.95 <= h.quantile(0.95) <= 1.0
    assert h.quantile(0.99) <= 1.0  # clamped to observed max
    assert h.quantile(0.0) >= 0.01  # clamped to observed min
    # export_view carries the shared bounds + per-bucket counts summing
    # to the observation count (plus an overflow bucket)
    row = [r for r in metrics.export_view() if r["name"] == "lat"][0]
    assert row["kind"] == "histogram"
    assert len(row["bucket_counts"]) == len(row["bounds"]) + 1
    assert sum(row["bucket_counts"]) == 100
    # empty histogram: quantiles are None, not garbage
    assert metrics.histogram("empty").quantile(0.5) is None


def test_sample_device_memory_never_raises():
    # CPU backends have no memory_stats — must be a silent no-op
    metrics.sample_device_memory()


def test_sample_device_memory_counts_failures(monkeypatch):
    """A FAILING memory sample (dead tunnel, runtime raise) is counted in
    obs.memory_sample_errors and warned exactly once per process —
    silently-dead memory telemetry was the old behavior."""
    import jax

    def boom():
        raise RuntimeError("tunnel died")

    monkeypatch.setattr(jax, "local_devices", boom)
    monkeypatch.setattr(metrics, "_mem_sample_warned", False)
    with pytest.warns(UserWarning, match="sample_device_memory failed"):
        metrics.sample_device_memory()
    # second failure: counted, NOT warned again
    import warnings as _warnings
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        metrics.sample_device_memory()
    snap = metrics.snapshot()["counters"]
    assert snap["obs.memory_sample_errors"] == 2


# -- compile tracking --------------------------------------------------------

def test_compile_timed_fn_records_cache_growth():
    from mplc_tpu.mpl.engine import _CompileTimedFn

    f = _CompileTimedFn(jax.jit(lambda x: x + 1), "unit")
    with trace.collect() as recs:
        f(jnp.ones(3))   # first shape: compile
        f(jnp.ones(3))   # cached: no event
        f(jnp.ones(5))   # new shape: compile
    compiles = [r for r in recs if r["name"] == "trainer.compile"]
    assert len(compiles) == 2
    assert all(r["attrs"]["fn"] == "unit" for r in compiles)
    snap = metrics.snapshot()["counters"]
    assert snap["trainer.compiles_total"] == 2
    assert snap["trainer.compiles[unit]"] == 2
    assert snap["trainer.compile_seconds_total"] > 0
    # attribute passthrough to the wrapped jit (tests .lower() the jits)
    assert hasattr(f, "lower")


# -- contributivity spans ----------------------------------------------------

def test_estimator_timing_comes_from_span():
    from test_contrib import additive, fake_scenario

    from mplc_tpu.contrib.contributivity import Contributivity

    sc = fake_scenario(3, additive([0.1, 0.25, 0.65]))
    c = Contributivity(sc)
    with trace.collect() as recs:
        c.compute_SV()
    spans = [r for r in recs if r["name"] == "contributivity"]
    assert len(spans) == 1
    assert spans[0]["attrs"]["method"] == "Shapley"
    # single source of truth: the public timing IS the span duration
    assert c.computation_time_sec == spans[0]["dur"] > 0.0


# -- report + engine smoke ---------------------------------------------------

def test_report_format_and_write(tmp_path):
    rep = report.sweep_report([
        {"name": "engine.evaluate", "dur": 2.0,
         "attrs": {"requested": 4, "missing": 1}},
        {"name": "engine.prep", "dur": 0.25,
         "attrs": {"width": 8, "slot_count": 2, "coalitions": 6}},
        {"name": "engine.batch", "dur": 1.5,
         "attrs": {"width": 8, "slot_count": 2, "coalitions": 6,
                   "padding": 2, "epochs": 24}},
        {"name": "trainer.compile", "dur": 0.5, "attrs": {"fn": "brun"}},
    ])
    assert rep["memo"] == {"requested": 4, "hits": 3, "misses": 1,
                           "hit_rate": 0.75}
    assert rep["wallclock"]["prep_s"] == 0.25
    assert rep["batches"]["pad_waste_fraction"] == 0.25
    assert rep["per_width"][0]["coalitions_per_s"] == 4.0
    # a clean run says so explicitly: an all-zero resilience row
    assert rep["resilience"] == {
        "retries": 0, "backoff_s": 0.0, "cap_halvings": 0,
        "cpu_degraded": False, "cpu_batches": 0, "cpu_coalitions": 0,
        "ladder_exhausted": 0, "faults_injected": 0}
    text = report.format_report(rep)
    assert "hit_rate=75.0%" in text
    assert "pad_waste=25.0%" in text
    assert "prep=0.25s" in text
    assert "resilience  retries=0" in text
    # a report from an older run (no prep/resilience rows recorded)
    # still formats
    old = dict(rep, wallclock={k: v for k, v in rep["wallclock"].items()
                               if k != "prep_s"})
    old.pop("resilience")
    old_text = report.format_report(old)
    assert "prep=0.00s" in old_text
    assert "resilience" not in old_text
    path = tmp_path / "rep.json"
    report.write_report(str(path), rep)
    assert json.loads(path.read_text())["memo"]["hits"] == 3


def test_engine_smoke_sweep_report(tmp_path, monkeypatch):
    """A tiny real-engine sweep with tracing on: JSONL trace written,
    and the sweep report's memo counts, padding waste and epoch totals
    equal the hand-computed values for this workload."""
    from helpers import build_scenario, cluster_mlp_dataset

    from mplc_tpu.contrib.engine import CharacteristicEngine

    monkeypatch.setenv("MPLC_TPU_TRACE_FILE", str(tmp_path / "trace.jsonl"))
    sc = build_scenario(dataset=cluster_mlp_dataset(n=240), epoch_count=2)
    eng = CharacteristicEngine(sc)
    with trace.collect() as recs:
        eng.evaluate([(0,), (1,), (0, 1)])   # 3 misses
        eng.evaluate([(0,), (1,), (0, 1)])   # 3 hits, all memoized
    rep = report.sweep_report(recs)

    # memo accounting: 3 unique keys requested per call
    assert rep["memo"] == {"requested": 6, "hits": 3, "misses": 3,
                           "hit_rate": 0.5}
    # padding: the 8-device CPU mesh buckets both batches to width 8
    # (2 singles -> 6 padded; 1 size-2 coalition -> 7 padded)
    assert rep["batches"]["count"] == 2
    assert rep["batches"]["coalitions"] == 3
    assert rep["batches"]["padding"] == 6 + 7
    assert rep["batches"]["pad_waste_fraction"] == 13 / 16
    # epochs: ES off at epoch_count=2 <= patience, so every coalition
    # trains the full 2 epochs
    assert rep["batches"]["epochs_trained"] == 3 * 2
    assert eng.epochs_trained == 6
    # wall-clock split present; the cold engine compiled inside the region
    assert rep["wallclock"]["evaluate_s"] > 0
    assert rep["wallclock"]["prep_s"] > 0
    assert rep["wallclock"]["dispatch_s"] > 0
    assert rep["wallclock"]["harvest_s"] > 0
    assert rep["compiles"], "cold sweep must record compile events"
    assert rep["wallclock"]["compile_s"] > 0

    # metrics mirrored the same quantities
    snap = metrics.snapshot()
    assert snap["counters"]["engine.memo_hits"] == 3
    assert snap["counters"]["engine.memo_misses"] == 3
    assert snap["counters"]["engine.epochs_trained"] == 6
    assert snap["histograms"]["engine.pad_waste_fraction"]["count"] == 2

    # the JSONL trace parses line-by-line and contains the same spans
    lines = (tmp_path / "trace.jsonl").read_text().strip().splitlines()
    parsed = [json.loads(l) for l in lines]
    names = {r["name"] for r in parsed}
    assert {"engine.evaluate", "engine.prep", "engine.dispatch",
            "engine.harvest", "engine.batch"} <= names
    # dispatch/harvest spans nest under their evaluate span
    ev_ids = {r["id"] for r in parsed if r["name"] == "engine.evaluate"}
    for r in parsed:
        if r["name"] in ("engine.dispatch", "engine.harvest"):
            assert r["parent"] in ev_ids
