"""The Perfetto exporter (obs/chrome_trace.py + scripts/trace_to_perfetto):
span JSONL -> Chrome trace-event JSON, schema-validated, with
retry/degrade/requeue flow events intact and torn-tail tolerance.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from mplc_tpu.obs import chrome_trace, metrics, trace

ROOT = Path(__file__).resolve().parents[1]

# the trace-event phases the converter may legally emit
_PHASES = {"X", "M", "s", "f"}


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("MPLC_TPU_TRACE_FILE", raising=False)
    monkeypatch.delenv("MPLC_TPU_CHROME_TRACE_FILE", raising=False)
    metrics.reset()
    yield
    metrics.reset()


def _validate_schema(doc):
    """Minimal Chrome trace-event (JSON object form) schema check."""
    assert isinstance(doc, dict)
    assert isinstance(doc["traceEvents"], list)
    for ev in doc["traceEvents"]:
        assert set(ev) >= {"name", "ph", "ts", "pid", "tid"}, ev
        assert ev["ph"] in _PHASES, ev
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        if ev["ph"] == "X":
            assert ev["dur"] >= 1.0  # zero-dur records widened to 1 us
        if ev["ph"] in ("s", "f"):
            assert "id" in ev
        if ev["ph"] == "f":
            assert ev.get("bp") == "e"
    # flow pairs match up by id
    starts = {e["id"] for e in doc["traceEvents"] if e["ph"] == "s"}
    ends = {e["id"] for e in doc["traceEvents"] if e["ph"] == "f"}
    assert starts == ends


def test_synthetic_records_schema_and_flows(tmp_path):
    recs = [
        {"name": "engine.evaluate", "id": 1, "parent": None, "ts": 100.0,
         "dur": 2.0, "thread": 7, "attrs": {"requested": 3}},
        {"name": "engine.fault", "id": 2, "parent": 1, "ts": 100.1,
         "dur": 0.0, "thread": 7,
         "attrs": {"kind": "transient", "site": "dispatch", "ordinal": 1}},
        {"name": "engine.retry", "id": 3, "parent": 1, "ts": 100.2,
         "dur": 0.0, "thread": 7,
         "attrs": {"site": "dispatch", "attempt": 1, "ordinal": 1}},
        {"name": "engine.batch", "id": 4, "parent": 1, "ts": 100.5,
         "dur": 0.4, "thread": 7, "attrs": {"ordinal": 1, "width": 8}},
        # a different thread's batch with the same ordinal: must NOT be
        # the flow target of thread 7's retry
        {"name": "engine.batch", "id": 5, "parent": None, "ts": 100.3,
         "dur": 0.1, "thread": 9, "attrs": {"ordinal": 1, "width": 8}},
    ]
    doc = chrome_trace.to_chrome(recs)
    _validate_schema(doc)
    # retry + fault both link to ordinal-1 batch on thread 7
    flows = [e for e in doc["traceEvents"] if e["ph"] in ("s", "f")]
    assert {e["name"] for e in flows} == {"retry", "fault"}
    for e in flows:
        assert e["tid"] == 7
    # thread metadata present for both threads
    meta = [e for e in doc["traceEvents"]
            if e["ph"] == "M" and e["name"] == "thread_name"]
    assert {e["tid"] for e in meta} == {7, 9}
    # timestamps rebased to the earliest record
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert min(e["ts"] for e in xs) == 0.0


def test_requeue_flow_links_job_fault_to_next_slice():
    recs = [
        {"name": "service.slice", "id": 1, "parent": None, "ts": 10.0,
         "dur": 0.5, "thread": 1, "attrs": {"job": "job1", "tenant": "a"}},
        {"name": "service.job_fault", "id": 2, "parent": None, "ts": 10.6,
         "dur": 0.0, "thread": 1, "attrs": {"job": "job1", "attempt": 1}},
        {"name": "service.slice", "id": 3, "parent": None, "ts": 10.7,
         "dur": 0.5, "thread": 1, "attrs": {"job": "job2", "tenant": "b"}},
        {"name": "service.slice", "id": 4, "parent": None, "ts": 11.3,
         "dur": 0.5, "thread": 1, "attrs": {"job": "job1", "tenant": "a"}},
    ]
    doc = chrome_trace.to_chrome(recs)
    _validate_schema(doc)
    finish = next(e for e in doc["traceEvents"] if e["ph"] == "f")
    # the flow ends inside job1's NEXT slice (ts 11.3 -> rebased 1.3e6),
    # not job2's earlier one
    assert finish["name"] == "requeue"
    assert 1.3e6 <= finish["ts"] < 1.3e6 + 10


def test_real_sweep_jsonl_converts_with_retry_flows(tmp_path, monkeypatch):
    """Acceptance: a real engine sweep's JSONL (with an injected
    transient -> retry) converts to schema-valid Chrome JSON with the
    retry flow intact."""
    from helpers import build_scenario
    from mplc_tpu.contrib.engine import CharacteristicEngine

    trace_file = tmp_path / "sweep.jsonl"
    monkeypatch.setenv("MPLC_TPU_TRACE_FILE", str(trace_file))
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "transient@batch1")
    monkeypatch.setenv("MPLC_TPU_RETRY_BACKOFF_SEC", "0")
    sc = build_scenario(partners_count=3, dataset_name="titanic",
                        epoch_count=2, gradient_updates_per_pass_count=2)
    eng = CharacteristicEngine(sc)
    eng.evaluate([(0,), (1,), (0, 1), (0, 1, 2)])
    monkeypatch.delenv("MPLC_TPU_TRACE_FILE")
    trace._sink_file()  # re-sync: closes the sink so the file is complete

    summary = chrome_trace.convert(str(trace_file))
    assert summary["torn_lines"] == 0
    assert summary["records"] > 0
    doc = json.loads(Path(summary["out"]).read_text())
    _validate_schema(doc)
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"engine.evaluate", "engine.dispatch", "engine.harvest",
            "engine.batch"} <= names
    # the injected transient produced fault+retry flows to batch 1
    flows = {e["name"] for e in doc["traceEvents"] if e["ph"] == "s"}
    assert {"retry", "fault"} <= flows
    assert summary["flows"] >= 2


def test_torn_tail_tolerated_and_reported(tmp_path):
    path = tmp_path / "trace.jsonl"
    good = {"name": "engine.batch", "id": 1, "parent": None, "ts": 1.0,
            "dur": 0.1, "thread": 1, "attrs": {}}
    path.write_text(json.dumps(good) + "\n" + '{"name": "engine.ba')
    with pytest.warns(UserWarning, match="torn tail"):
        summary = chrome_trace.convert(str(path))
    assert summary["torn_lines"] == 1
    assert summary["records"] == 1
    doc = json.loads(Path(summary["out"]).read_text())
    _validate_schema(doc)
    assert doc["otherData"]["torn_lines"] == 1


def test_cli_script(tmp_path):
    path = tmp_path / "trace.jsonl"
    rec = {"name": "engine.batch", "id": 1, "parent": None, "ts": 1.0,
           "dur": 0.1, "thread": 1, "attrs": {"ordinal": 1}}
    path.write_text(json.dumps(rec) + "\n")
    out = tmp_path / "out.json"
    proc = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "trace_to_perfetto.py"),
         str(path), "-o", str(out)],
        capture_output=True, text=True, cwd=ROOT)
    assert proc.returncode == 0, proc.stderr
    assert "1 trace events" not in proc.stdout  # events incl. metadata
    assert "perfetto" in proc.stdout
    _validate_schema(json.loads(out.read_text()))
    # a missing input is a clean CLI error, not a traceback
    proc = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "trace_to_perfetto.py"),
         str(tmp_path / "nope.jsonl")],
        capture_output=True, text=True, cwd=ROOT)
    assert proc.returncode == 2
    assert "not found" in proc.stderr


def test_atexit_env_conversion(tmp_path):
    """MPLC_TPU_CHROME_TRACE_FILE: the interpreter-exit hook converts the
    span JSONL automatically (exercised in a child process, where the
    atexit actually fires)."""
    src = tmp_path / "t.jsonl"
    out = tmp_path / "t.chrome.json"
    code = (
        "from mplc_tpu.obs import trace\n"
        "with trace.span('engine.evaluate', requested=1):\n"
        "    trace.event('engine.batch', dur=0.1, ordinal=1)\n"
    )
    import os
    env = dict(os.environ, MPLC_TPU_TRACE_FILE=str(src),
               MPLC_TPU_CHROME_TRACE_FILE=str(out),
               JAX_PLATFORMS="cpu", PYTHONPATH=str(ROOT))
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, env=env, cwd=ROOT)
    assert proc.returncode == 0, proc.stderr
    assert out.exists()
    doc = json.loads(out.read_text())
    _validate_schema(doc)
    assert {e["name"] for e in doc["traceEvents"]} >= {
        "engine.evaluate", "engine.batch"}
