"""Buffer donation (ISSUE 8): the trainer's state-carrying jits declare
`donate_argnums`, halving param-side HBM per in-flight batch.

The governing invariant: donation is an ALIASING contract, never a
numerics change — donated and non-donated sweeps are BIT-IDENTICAL for
the fedavg slot path, the seq family and the retrain-free reconstruction
path, and a transient-failure retry after a donating dispatch recovers
bit-identically (the dispatch closures re-materialize every device input
from host arrays, so a dead donated buffer can never be re-submitted).
The savings are plumbed into the coalition-cap autotune: with donation
on, the modeled per-coalition state footprint halves and the computed
cap ceiling rises."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mplc_tpu import faults
from mplc_tpu.contrib.engine import CharacteristicEngine
from mplc_tpu.contrib.shapley import powerset_order
from mplc_tpu.obs import metrics, report, trace

SUBSETS = powerset_order(4)

_KNOBS = ("MPLC_TPU_DONATE_BUFFERS", "MPLC_TPU_PROGRAM_BANK",
          "MPLC_TPU_FAULT_PLAN", "MPLC_TPU_PIPELINE_BATCHES",
          "MPLC_TPU_SEED_ENSEMBLE", "MPLC_TPU_PARTNER_FAULT_PLAN",
          "MPLC_TPU_PARTNER_SHARDS", "MPLC_TPU_BATCH_CAP_CEILING")


@pytest.fixture(autouse=True)
def _env(monkeypatch):
    for k in _KNOBS:
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("MPLC_TPU_RETRY_BACKOFF_SEC", "0")
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "1")
    metrics.reset()
    yield
    metrics.reset()


def scenario(approach="fedavg", seed=9):
    from helpers import build_scenario
    return build_scenario(partners_count=4,
                          amounts_per_partner=[0.1, 0.2, 0.3, 0.4],
                          dataset_name="titanic", epoch_count=2,
                          gradient_updates_per_pass_count=2,
                          multi_partner_learning_approach=approach,
                          seed=seed)


_REF = {}


def reference(approach="fedavg", monkeypatch=None):
    """Non-donated, bank-less v(S) table, computed once per approach per
    pytest process (the autouse fixture guarantees a clean env)."""
    if approach not in _REF:
        monkeypatch.setenv("MPLC_TPU_DONATE_BUFFERS", "0")
        monkeypatch.setenv("MPLC_TPU_PROGRAM_BANK", "0")
        _REF[approach] = CharacteristicEngine(
            scenario(approach)).evaluate(SUBSETS)
        monkeypatch.delenv("MPLC_TPU_DONATE_BUFFERS")
        monkeypatch.delenv("MPLC_TPU_PROGRAM_BANK")
    return _REF[approach]


# -- bit-identity ------------------------------------------------------------

def test_donation_actually_consumes_the_state(monkeypatch):
    """Ground truth that donation is ON and really aliasing: the input
    state's buffers are deleted by a donating epoch chunk, and survive
    with the knob off."""
    monkeypatch.setenv("MPLC_TPU_PROGRAM_BANK", "0")
    eng = CharacteristicEngine(scenario())
    tr = eng.multi_pipe.trainer
    mask = jnp.ones((eng.partners_count,), jnp.float32)

    state = tr.init_state(jax.random.PRNGKey(0), eng.partners_count)
    new = tr.jit_epoch_chunk(state, eng.stacked, eng.val, mask,
                             jax.random.PRNGKey(1), n_epochs=1)
    assert jax.tree_util.tree_leaves(state.params)[0].is_deleted()
    assert not jax.tree_util.tree_leaves(new.params)[0].is_deleted()

    monkeypatch.setenv("MPLC_TPU_DONATE_BUFFERS", "0")
    state2 = tr.init_state(jax.random.PRNGKey(0), eng.partners_count)
    tr.jit_epoch_chunk(state2, eng.stacked, eng.val, mask,
                       jax.random.PRNGKey(1), n_epochs=1)
    assert not jax.tree_util.tree_leaves(state2.params)[0].is_deleted()


def test_donated_sweep_bit_identical_fedavg(monkeypatch):
    ref = reference("fedavg", monkeypatch)
    monkeypatch.setenv("MPLC_TPU_PROGRAM_BANK", "0")  # isolate donation
    vals = CharacteristicEngine(scenario()).evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)
    # the table must discriminate, or the equality contract is vacuous
    assert ref.max() - ref.min() > 1e-3


def test_donated_sweep_bit_identical_seq(monkeypatch):
    """The seq family routes through the slot engine's sequential
    partner scan — a different carry structure through the donating
    jits, equality-tested separately."""
    ref = reference("seq-with-final-agg", monkeypatch)
    monkeypatch.setenv("MPLC_TPU_PROGRAM_BANK", "0")
    vals = CharacteristicEngine(
        scenario("seq-with-final-agg")).evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)


def test_donated_reconstruction_bit_identical(monkeypatch):
    """The retrain-free path: the recording run's init params are copied
    out BEFORE the donating chunk loop consumes the state, and the
    reconstruction scan donates only its per-batch mask buffer — so
    donated and non-donated reconstructed v(S) tables are bit-identical."""
    from mplc_tpu.contrib.reconstruct import ReconstructionEvaluator

    monkeypatch.setenv("MPLC_TPU_PROGRAM_BANK", "0")
    monkeypatch.setenv("MPLC_TPU_DONATE_BUFFERS", "0")
    ref = ReconstructionEvaluator(
        CharacteristicEngine(scenario())).evaluate(SUBSETS)
    monkeypatch.delenv("MPLC_TPU_DONATE_BUFFERS")
    vals = ReconstructionEvaluator(
        CharacteristicEngine(scenario())).evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)


# -- the donation/retry rule -------------------------------------------------

def test_transient_retry_after_donating_dispatch_bit_identical(monkeypatch):
    """A donating dispatch that fails leaves its donated buffers DEAD;
    the retry must re-materialize every input from host arrays and
    recover bit-identically (extends the tests/test_faults.py pattern
    with donation explicitly on)."""
    ref = reference("fedavg", monkeypatch)
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "transient@batch2")
    eng = CharacteristicEngine(scenario())
    assert eng.multi_pipe._fin_donates  # donation really on
    vals = eng.evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)
    assert metrics.snapshot()["counters"]["engine.retries"] == 1


def test_harvest_redispatch_after_donation_bit_identical(monkeypatch):
    """Harvest-side transient: the re-dispatch rebuilds the SAME batch
    from host arrays after the first (donating) dispatch's buffers are
    gone."""
    ref = reference("fedavg", monkeypatch)
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "transient@harvest2")
    vals = CharacteristicEngine(scenario()).evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)
    assert metrics.snapshot()["counters"]["engine.retries"] == 1


def test_oom_ladder_with_donation_bit_identical(monkeypatch):
    """Donation composes with the OOM cap-halving ladder: re-bucketed
    batches re-materialize and retrain bit-identically."""
    ref = reference("fedavg", monkeypatch)
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "oom@batch2")
    eng = CharacteristicEngine(scenario())
    vals = eng.evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)
    assert eng._cap_halvings == 1


# -- cap autotune & the hbm row ----------------------------------------------

def _stub_memory(eng, param_bytes=64 << 20, hbm=8 << 30):
    """The memory-stats stub pattern from tests/test_dispatch_fusion.py:
    pin the model size and device limit so the autotune is deterministic
    and memory (not the ceiling) binds."""
    eng._param_bytes = param_bytes
    eng._hbm_bytes = hbm


def test_donation_raises_autotuned_cap(monkeypatch):
    """The HBM saving is plumbed into the cap autotune: with donation on
    the modeled per-coalition state footprint halves, so the computed
    coalitions-per-device ceiling RISES (here: exactly doubles, params
    dominating the activation window)."""
    monkeypatch.delenv("MPLC_TPU_COALITIONS_PER_DEVICE", raising=False)
    monkeypatch.setenv("MPLC_TPU_BATCH_CAP_CEILING", "1024")
    eng = CharacteristicEngine(scenario())
    _stub_memory(eng)
    cap_off = eng._autotuned_cap(None, False, False)
    cap_on = eng._autotuned_cap(None, False, True)
    assert cap_on > cap_off
    # the state term dominates at 64MB params, so the cap ~doubles
    # (floor rounding of the activation share can cost at most one slot)
    assert cap_on >= 2 * cap_off - 1
    # and the policy-following cap picks the donated number by default
    assert eng._device_batch_cap() == cap_on
    monkeypatch.setenv("MPLC_TPU_DONATE_BUFFERS", "0")
    assert eng._device_batch_cap() == cap_off


def test_hbm_row_reports_donation_saving_and_cap_uplift(monkeypatch):
    """The sweep report's hbm row: per-coalition footprint, the donation
    saving, cap before/after donation — and format_report renders it."""
    monkeypatch.setenv("MPLC_TPU_PROGRAM_BANK", "0")
    monkeypatch.delenv("MPLC_TPU_COALITIONS_PER_DEVICE", raising=False)
    monkeypatch.setenv("MPLC_TPU_BATCH_CAP_CEILING", "1024")
    eng = CharacteristicEngine(scenario())
    _stub_memory(eng)
    with trace.collect() as recs:
        eng.evaluate([(0,), (0, 1)])
    rep = report.sweep_report(recs)
    h = rep["hbm"]
    assert h["donation"] is True
    assert h["donated_bytes_per_coalition"] > 0
    assert h["cap_after_donation"] > h["cap_before_donation"]
    text = report.format_report(rep)
    assert "hbm" in text
    assert (f"cap {h['cap_before_donation']}->{h['cap_after_donation']}"
            in text)
    # old reports without the row still format
    old = dict(rep)
    old.pop("hbm")
    assert "hbm" not in report.format_report(old)


def test_memory_stats_requeried_after_degrade(monkeypatch):
    """ISSUE 8 satellite: the per-engine memory_stats snapshot must be
    invalidated on every engine.degrade event — the autotuner otherwise
    reasons from pre-fault memory after OOM cap-halving or CPU
    degradation."""
    monkeypatch.delenv("MPLC_TPU_COALITIONS_PER_DEVICE", raising=False)
    eng = CharacteristicEngine(scenario())
    calls = {"n": 0}

    class Dev:
        def memory_stats(self):
            calls["n"] += 1
            return {"bytes_limit": 8 << 30}

    monkeypatch.setattr(jax, "local_devices", lambda: [Dev()])
    eng._device_batch_cap()
    eng._device_batch_cap()
    assert calls["n"] == 1  # memoized on the happy path (PR 2 behavior)
    eng._degrade_cap(faults.InjectedOom("RESOURCE_EXHAUSTED: test"))
    eng._device_batch_cap()
    eng._device_batch_cap()
    assert calls["n"] == 2  # re-queried exactly once after the degrade
