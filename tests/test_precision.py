"""Mixed-precision modes: equality where promised, bounds where traded.

The contract under test (MPLC_TPU_PRECISION / TrainConfig.precision +
the fingerprint/ledger/memo keying that licenses the deviation):

1. **Resolution.** `constants.precision_mode()` resolves the env knob
   with the standard warn+fallback contract; `TrainConfig` freezes the
   resolved mode at construction and rejects invalid values; `cfg.dtype`
   routes mixed/bf16 compute to bfloat16.
2. **fp32 is not a deviation.** `MPLC_TPU_PRECISION=fp32` (explicit)
   computes BIT-identical characteristic values to the default
   (knob-unset) build — same fingerprint, same game.
3. **bf16 is a LICENSED deviation.** On the fixed-seed 4-partner game,
   bf16 v(S) stays within an absolute bound of the fp32 reference and
   the ledger diff's Kendall tau-b ranking agreement is exactly 1.0 —
   the same pair the bench sidecar embeds and `bench_diff --gate`
   enforces. The engine fingerprints differ (different game on disk).
4. **Stale caches refuse.** A cache saved under fp32 raises ValueError
   when loaded into a bf16 engine (and vice versa); a legacy cache with
   no precision field backfills to fp32 and loads into an fp32 engine.
5. **The live memo is precision-keyed** (ISSUE 17's small fix): every
   memoized live result carries the engine's precision in its key.
"""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from helpers import build_scenario, cluster_mlp_dataset
from mplc_tpu import constants
from mplc_tpu.contrib.contributivity import Contributivity
from mplc_tpu.mpl.engine import TrainConfig
from mplc_tpu.obs import numerics as obs_num


# ---------------------------------------------------------------------------
# 1. resolution
# ---------------------------------------------------------------------------

def test_precision_mode_env_resolution(monkeypatch):
    monkeypatch.delenv("MPLC_TPU_PRECISION", raising=False)
    assert constants.precision_mode() == "fp32"
    for mode in ("fp32", "mixed", "bf16"):
        monkeypatch.setenv("MPLC_TPU_PRECISION", mode)
        assert constants.precision_mode() == mode
    monkeypatch.setenv("MPLC_TPU_PRECISION", "fp64")
    with pytest.warns(UserWarning):
        assert constants.precision_mode() == "fp32"


def test_train_config_freezes_and_validates(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_PRECISION", "mixed")
    cfg = TrainConfig()
    assert cfg.precision == "mixed"
    # frozen at construction: a later env flip does not move the config
    monkeypatch.setenv("MPLC_TPU_PRECISION", "fp32")
    assert cfg.precision == "mixed"
    with pytest.raises(ValueError, match="precision"):
        TrainConfig(precision="fp64")


def test_dtype_routes_compute():
    assert TrainConfig(precision="fp32").dtype == jnp.float32
    assert TrainConfig(precision="mixed").dtype == jnp.bfloat16
    assert TrainConfig(precision="bf16").dtype == jnp.bfloat16
    # compute_dtype still decides under fp32, as it always has
    assert TrainConfig(precision="fp32",
                       compute_dtype="bfloat16").dtype == jnp.bfloat16


# ---------------------------------------------------------------------------
# 2/3. the fixed-seed 4-partner pin: fp32 equality, bf16 bound + tau-b
# ---------------------------------------------------------------------------

def _scenario_4p():
    """The strict-quality-ordering 4-partner game (one fully corrupted
    partner + graded amounts), small enough to retrain 15 coalitions."""
    return build_scenario(
        partners_count=4, amounts_per_partner=[0.05, 0.12, 0.28, 0.55],
        dataset=cluster_mlp_dataset(n=360, seed=11, scale=1.0),
        epoch_count=2, minibatch_count=2,
        samples_split_option=["basic", "random"],
        corrupted_datasets=[("glabel", 1.0), "not_corrupted",
                            "not_corrupted", "not_corrupted"])


def _exact_game(monkeypatch, mode):
    if mode is None:
        monkeypatch.delenv("MPLC_TPU_PRECISION", raising=False)
    else:
        monkeypatch.setenv("MPLC_TPU_PRECISION", mode)
    sc = _scenario_4p()
    Contributivity(sc).compute_SV()
    eng = sc._charac_engine
    return eng._fingerprint(), dict(eng.charac_fct_values)


def _ledger(fingerprint, values, mode):
    led = obs_num.ValueLedger(
        json.dumps(fingerprint, sort_keys=True),
        meta={"precision": mode})
    for subset, v in values.items():
        if subset:
            led.record(subset, v, source="exact")
    return led


@pytest.fixture(scope="module")
def fp32_game():
    mp = pytest.MonkeyPatch()
    try:
        yield _exact_game(mp, "fp32")
    finally:
        mp.undo()


def test_explicit_fp32_is_bit_identical_to_default(monkeypatch, fp32_game):
    fp_explicit, vals_explicit = fp32_game
    fp_default, vals_default = _exact_game(monkeypatch, None)
    assert fp_explicit == fp_default           # same game, same identity
    assert fp_explicit["precision"] == "fp32"
    assert vals_explicit.keys() == vals_default.keys()
    for subset, v in vals_default.items():
        assert vals_explicit[subset] == v      # BIT-identical, no tolerance


def test_bf16_is_bounded_and_rank_identical(monkeypatch, fp32_game):
    fp_ref, vals_ref = fp32_game
    fp_b16, vals_b16 = _exact_game(monkeypatch, "bf16")
    # different game on disk: the fingerprint carries the deviation
    assert fp_b16["precision"] == "bf16" and fp_b16 != fp_ref
    # value bound: bf16 compute moves the trajectory, but v(S) (a
    # test-set accuracy, quantized in 1/|test| steps) stays close
    for subset, v in vals_ref.items():
        assert abs(vals_b16[subset] - v) < 0.05
    # the ledger pair — exactly what the bench sidecar embeds — must
    # rank-agree perfectly: tau-b == 1.0 is the bench_diff hard gate
    diff = obs_num.diff_ledgers(_ledger(fp_ref, vals_ref, "fp32"),
                                _ledger(fp_b16, vals_b16, "bf16"))
    assert diff["common"] == 2 ** 4 - 1
    assert not diff["same_fingerprint"]        # cross-precision pair
    assert diff["kendall_tau"] == 1.0


def test_bf16_actually_moves_the_training_compute(monkeypatch):
    """The deviation is real at the compute layer: bf16 changes the
    recorded per-round update stream materially (it is not an fp32 run
    wearing a different fingerprint), even when the quantized test-set
    accuracy absorbs the difference."""
    import jax

    def deltas(mode):
        monkeypatch.setenv("MPLC_TPU_PRECISION", mode)
        sc = build_scenario(
            partners_count=3, amounts_per_partner=[0.2, 0.3, 0.5],
            dataset=cluster_mlp_dataset(n=240, seed=9, scale=1.0),
            epoch_count=2, minibatch_count=2)
        recon = Contributivity(sc)._reconstructor()
        return jax.tree_util.tree_leaves(recon.recorded.deltas)

    moved = [float(np.abs(np.asarray(a, np.float32)
                          - np.asarray(b, np.float32)).max())
             for a, b in zip(deltas("fp32"), deltas("bf16"))]
    assert max(moved) > 1e-3


# ---------------------------------------------------------------------------
# 4. stale caches refuse across precision modes
# ---------------------------------------------------------------------------

def _small_engine(monkeypatch, mode):
    monkeypatch.setenv("MPLC_TPU_PRECISION", mode)
    sc = build_scenario(
        partners_count=3, amounts_per_partner=[0.2, 0.3, 0.5],
        dataset=cluster_mlp_dataset(n=240, seed=9, scale=1.0),
        epoch_count=2, minibatch_count=2)
    return Contributivity(sc).engine  # constructs the engine, trains nothing


def test_cache_refuses_across_precision(monkeypatch, tmp_path):
    path = tmp_path / "cache.json"
    _small_engine(monkeypatch, "fp32").save_cache(path)
    with pytest.raises(ValueError, match="precision"):
        _small_engine(monkeypatch, "bf16").load_cache(path)
    # and the reverse direction
    path2 = tmp_path / "cache_b16.json"
    _small_engine(monkeypatch, "bf16").save_cache(path2)
    with pytest.raises(ValueError, match="precision"):
        _small_engine(monkeypatch, "fp32").load_cache(path2)


def test_legacy_cache_backfills_fp32(monkeypatch, tmp_path):
    path = tmp_path / "cache.json"
    _small_engine(monkeypatch, "fp32").save_cache(path)
    with open(path) as f:
        payload = json.load(f)
    # simulate a pre-precision (and pre-checksum) cache
    payload.pop("payload_sha256")
    payload["fingerprint"].pop("precision")
    with open(path, "w") as f:
        json.dump(payload, f)
    import warnings
    with warnings.catch_warnings():
        # the once-per-process legacy-cache warning may or may not fire
        # here depending on suite order — not this test's contract
        warnings.simplefilter("ignore", DeprecationWarning)
        _small_engine(monkeypatch, "fp32").load_cache(path)  # backfilled
    # the same legacy cache refuses a bf16 engine: backfill says fp32
    with pytest.raises(ValueError, match="precision"):
        _small_engine(monkeypatch, "bf16").load_cache(path)


# ---------------------------------------------------------------------------
# 5. the live memo is precision-keyed
# ---------------------------------------------------------------------------

def test_live_memo_key_carries_precision(monkeypatch):
    from mplc_tpu.live import LiveGame
    monkeypatch.delenv("MPLC_TPU_PRECISION", raising=False)
    sc = build_scenario(
        partners_count=3, amounts_per_partner=[0.2, 0.3, 0.5],
        dataset=cluster_mlp_dataset(n=240, seed=9, scale=1.0),
        epoch_count=2, minibatch_count=2)
    game = LiveGame(sc)
    game.query(method="exact")
    keys = list(game._results)
    assert keys and all(k[2] == "fp32" for k in keys)
    # a second identical query memo-hits (the key is stable)
    hits_key = keys[0]
    assert game._results[hits_key] is game.query(method="exact")


def test_engine_ledger_meta_carries_precision(monkeypatch, tmp_path):
    monkeypatch.setenv("MPLC_TPU_NUMERICS_LEDGER",
                       str(tmp_path / "ledger.json"))
    eng = _small_engine(monkeypatch, "bf16")
    assert eng.numerics_ledger is not None
    assert eng.numerics_ledger.meta.get("precision") == "bf16"
