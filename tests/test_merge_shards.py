"""Unit tests for scripts/merge_shards.py — previously only exercised
end-to-end through test_e2e's CLI grid-shard test. These pin the
refusal/override/warning semantics directly: partial-merge refusal on a
missing `.shardI.done` marker, the --force override, the --keep
double-count path, and the stitched csv's sort order."""

import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT / "scripts"))

import merge_shards  # noqa: E402


def _make_sharded_dir(tmp_path, n_shards, mark_done=None, name="exp"):
    """A <name>_shardedN folder with one csv per shard (rows deliberately
    out of global order) and done markers for `mark_done` (default all)."""
    import pandas as pd
    folder = tmp_path / f"{name}_sharded{n_shards}"
    folder.mkdir()
    for i in range(n_shards):
        # shard i owns scenario ids i, i+n, ... (the main.py slice rule);
        # write them in DESCENDING order so the merge must re-sort
        ids = sorted(range(i, 4 * n_shards, n_shards), reverse=True)
        pd.DataFrame({
            "scenario_id": ids,
            "random_state": [1] * len(ids),
            "value": [10 * x for x in ids],
        }).to_csv(folder / f"results_shard{i}.csv", index=False)
    for i in (range(n_shards) if mark_done is None else mark_done):
        (folder / f".shard{i}.done").touch()
    return folder


def test_missing_done_marker_refuses_merge(tmp_path):
    """csv presence is not completion: a shard whose marker is absent may
    still be appending rows, and the merge must refuse loudly."""
    folder = _make_sharded_dir(tmp_path, 2, mark_done=[0])
    with pytest.raises(SystemExit) as exc:
        merge_shards.main([str(folder)])
    assert exc.value.code == 2
    assert not (folder / "results.csv").exists()
    # the shard csvs are untouched — nothing was renamed or consumed
    assert (folder / "results_shard0.csv").exists()
    assert (folder / "results_shard1.csv").exists()


def test_force_overrides_missing_marker(tmp_path):
    import pandas as pd
    folder = _make_sharded_dir(tmp_path, 2, mark_done=[0])
    assert merge_shards.main([str(folder), "--force"]) == 0
    df = pd.read_csv(folder / "results.csv")
    assert len(df) == 8  # both shard csvs merged despite the gap


def test_merge_sorts_and_retires_shard_files(tmp_path):
    """The stitched csv is globally sorted by (scenario_id, random_state)
    even though every shard csv was written in descending order, and the
    default (non --keep) path renames the shard csvs to *.merged and
    removes the markers so a re-run can't inherit stale completion."""
    import pandas as pd
    folder = _make_sharded_dir(tmp_path, 2)
    assert merge_shards.main([str(folder)]) == 0
    df = pd.read_csv(folder / "results.csv")
    assert df["scenario_id"].tolist() == sorted(df["scenario_id"].tolist())
    assert df["scenario_id"].tolist() == list(range(8))
    for i in range(2):
        assert not (folder / f"results_shard{i}.csv").exists()
        assert (folder / f"results_shard{i}.csv.merged").exists()
        assert not (folder / f".shard{i}.done").exists()


def test_keep_leaves_shard_files_and_warns_double_count(tmp_path, capsys):
    """--keep leaves the shard csvs (and markers) in place — the
    double-count hazard the help text warns about: the notebooks'
    results*.csv glob would then read every row twice."""
    folder = _make_sharded_dir(tmp_path, 2)
    assert merge_shards.main([str(folder), "--keep"]) == 0
    out = capsys.readouterr().out
    assert "merged 2 shard files" in out
    # the rename note is absent — nothing was retired
    assert "renamed" not in out
    for i in range(2):
        assert (folder / f"results_shard{i}.csv").exists()
        assert (folder / f".shard{i}.done").exists()
    # the hazard is real: the glob the notebooks use now double-counts
    import glob as _glob
    assert len(_glob.glob(str(folder / "results*.csv"))) == 3


def test_renamed_folder_requires_per_csv_markers(tmp_path):
    """A folder that lost its _shardedN suffix can't know N — every csv
    present must then carry its own marker, or the merge refuses."""
    import shutil
    folder = _make_sharded_dir(tmp_path, 2, mark_done=[0])
    renamed = tmp_path / "copied_elsewhere"
    shutil.copytree(folder, renamed)
    with pytest.raises(SystemExit) as exc:
        merge_shards.main([str(renamed)])
    assert exc.value.code == 2
    (renamed / ".shard1.done").touch()
    assert merge_shards.main([str(renamed)]) == 0
