"""scripts/bench_diff.py — the perf-trajectory gate's deterministic
self-test: an injected regression is flagged past the threshold,
improvements and schema growth are not, provenance mismatches are
reported but never gated, and the directory mode pairs sidecars by
name. Pure JSON arithmetic — no jax, no engine."""

import copy
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "scripts"))

import bench_diff  # noqa: E402


def _sidecar(wallclock=10.0, samples_per_s=3000.0, hit_rate=0.5,
             source="fresh", degraded=False, with_device=True):
    doc = {
        "metric": "exact_shapley_mnist_10partners_8epochs_wallclock",
        "wallclock_s": wallclock,
        "source": source,
        "degraded": degraded,
        "report": {
            "wallclock": {"evaluate_s": wallclock * 0.9,
                          "compile_s": 1.0, "prep_s": 0.1,
                          "dispatch_s": 5.0, "harvest_s": 0.5},
            "memo": {"requested": 100, "hits": 50, "misses": 50,
                     "hit_rate": hit_rate},
            "batches": {"count": 10, "coalitions": 80, "padding": 20,
                        "pad_waste_fraction": 0.2},
            "compute": {"samples_per_s": samples_per_s,
                        "mfu_proxy": 0.3, "mfu_xla": 0.4},
            "resilience": {"retries": 0, "cap_halvings": 0},
            "per_width": [{"slot_count": 3, "width": 16,
                           "coalitions_per_s": 6.0}],
        },
    }
    if with_device:
        doc["report"]["device_time"] = {"device_s": wallclock * 0.5}
        doc["report"]["roofline"] = {"programs": [
            {"slot_count": 3, "width": 16,
             "achieved_flops_per_s": 2e12}]}
    return doc


def test_identical_sidecars_have_no_regressions():
    result = bench_diff.diff_sidecars(_sidecar(), _sidecar(), 0.10)
    assert result["comparable"] is True
    assert result["regressions"] == []
    assert all(r["delta_frac"] == 0 for r in result["rows"])


def test_injected_regression_is_flagged():
    old, new = _sidecar(), _sidecar(wallclock=15.0)   # +50% wall-clock
    result = bench_diff.diff_sidecars(old, new, 0.10)
    regressed = {r["row"] for r in result["regressions"]}
    assert "wallclock_s" in regressed
    assert "report.wallclock.evaluate_s" in regressed
    assert "report.device_time.device_s" in regressed
    text = bench_diff.format_diff(result, "self-test", 0.10)
    assert "REGRESSED" in text


def test_direction_awareness_and_threshold():
    # higher-is-better metrics regress when they DROP past the gate...
    old, new = _sidecar(), _sidecar(samples_per_s=1500.0, hit_rate=0.1)
    regressed = {r["row"] for r in
                 bench_diff.diff_sidecars(old, new, 0.10)["regressions"]}
    assert "report.compute.samples_per_s" in regressed
    assert "report.memo.hit_rate" in regressed
    # ...improvements in the good direction are never flagged
    better = _sidecar(wallclock=5.0, samples_per_s=6000.0)
    assert not bench_diff.diff_sidecars(_sidecar(), better,
                                        0.10)["regressions"]
    # ...and a drift inside the threshold passes
    close = _sidecar(wallclock=10.5)
    assert not bench_diff.diff_sidecars(_sidecar(), close,
                                        0.10)["regressions"]


def test_schema_growth_is_not_a_regression():
    """A pre-devcost sidecar vs one with device/roofline rows: rows
    present on only one side are skipped (noted), never gated."""
    old = _sidecar(with_device=False)
    result = bench_diff.diff_sidecars(old, _sidecar(), 0.10)
    assert not result["regressions"]
    assert any("only in new" in n for n in result["notes"])


def test_provenance_mismatch_reports_but_never_gates():
    old = _sidecar()
    new = _sidecar(wallclock=100.0, source="cpu_fallback")
    result = bench_diff.diff_sidecars(old, new, 0.10)
    assert result["comparable"] is False
    assert not result["regressions"]
    assert any("provenance mismatch" in n for n in result["notes"])
    deg = bench_diff.diff_sidecars(_sidecar(degraded=True), _sidecar(),
                                   0.10)
    assert any("DEGRADED" in n for n in deg["notes"])


def test_main_exit_codes_and_dir_mode(tmp_path, capsys):
    old_dir, new_dir = tmp_path / "rA", tmp_path / "rB"
    old_dir.mkdir(), new_dir.mkdir()
    (old_dir / "telemetry_config1.json").write_text(
        json.dumps(_sidecar()))
    (new_dir / "telemetry_config1.json").write_text(
        json.dumps(_sidecar(wallclock=20.0)))
    # a file on one side only is skipped, not fatal
    (new_dir / "telemetry_config6.json").write_text(
        json.dumps(_sidecar()))
    assert bench_diff.main([str(old_dir), str(new_dir)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out and "regression(s)" in out
    # same files -> clean gate
    same = copy.deepcopy(_sidecar())
    (new_dir / "telemetry_config1.json").write_text(json.dumps(same))
    assert bench_diff.main([str(old_dir), str(new_dir)]) == 0
    # unreadable input -> usage error, not a traceback
    assert bench_diff.main([str(old_dir / "missing.json"),
                            str(new_dir / "telemetry_config1.json")]) == 2


def test_dir_mode_with_zero_pairs_errors_instead_of_passing(tmp_path,
                                                            capsys):
    """An empty/renamed artifact dir must not read as a green gate."""
    a, b = tmp_path / "empty_a", tmp_path / "empty_b"
    a.mkdir(), b.mkdir()
    assert bench_diff.main([str(a), str(b)]) == 2
    assert "no matching" in capsys.readouterr().err


def _bits(v):
    import struct
    return struct.pack(">d", float(v)).hex()


def _with_numerics(doc, values):
    doc = copy.deepcopy(doc)
    doc["numerics"] = {"engine_fingerprint": "f" * 16,
                       "reduction_mode": "deterministic",
                       "entries": len(values), "values": values}
    return doc


def test_gate_mode_requires_the_numerics_gate_to_run(tmp_path, capsys):
    """--gate (the CI fleet gate): sidecars without same-fingerprint
    numerics blocks mean the value-truth comparison silently never ran —
    that must exit 2, not read green."""
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(_sidecar()))
    b.write_text(json.dumps(_sidecar()))
    # plain mode: green (no regressions)
    assert bench_diff.main([str(a), str(b)]) == 0
    # gate mode: the value gate never ran -> 2
    assert bench_diff.main([str(a), str(b), "--gate"]) == 2
    assert "never ran" in capsys.readouterr().err


def test_gate_mode_passes_on_bit_identical_values(tmp_path):
    vals = {"0x3": _bits(0.5), "0x5": _bits(0.625)}
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(_with_numerics(_sidecar(), vals)))
    b.write_text(json.dumps(_with_numerics(_sidecar(), vals)))
    assert bench_diff.main([str(a), str(b), "--gate"]) == 0


def test_gate_mode_flags_value_drift_as_regression(tmp_path):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(_with_numerics(
        _sidecar(), {"0x3": _bits(0.5), "0x5": _bits(0.625)})))
    b.write_text(json.dumps(_with_numerics(
        _sidecar(), {"0x3": _bits(0.5), "0x5": _bits(0.6250000001)})))
    assert bench_diff.main([str(a), str(b), "--gate"]) == 1


def test_gate_mode_refuses_provenance_incomparable_pairs(tmp_path, capsys):
    vals = {"0x3": _bits(0.5)}
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(_with_numerics(_sidecar(), vals)))
    b.write_text(json.dumps(_with_numerics(
        _sidecar(source="cpu_fallback"), vals)))
    # plain mode reports but does not gate; --gate refuses outright
    assert bench_diff.main([str(a), str(b)]) == 0
    assert bench_diff.main([str(a), str(b), "--gate"]) == 2
    assert "incomparable" in capsys.readouterr().err


def _with_precision(doc, tau, mode="bf16", with_ulp=True):
    doc = copy.deepcopy(doc)
    doc["precision"] = {"mode": mode, "tau_b": tau,
                        "fp32_reference_s": 2.0, "common": 15,
                        "drift": tau < 1.0}
    if with_ulp:
        doc["precision"]["ulp"] = {"max": 9e12, "p50": 0, "p99": 3e11,
                                   "nonzero": 3}
    return doc


def test_precision_tau_gate_passes_at_contract_value(tmp_path):
    """A bf16 sidecar whose ledger pair rank-agrees exactly satisfies
    --gate even without a same-fingerprint numerics block (the
    cross-precision pair's truth is INTRA-sidecar)."""
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(_with_precision(_sidecar(), 1.0)))
    b.write_text(json.dumps(_with_precision(_sidecar(), 1.0)))
    assert bench_diff.main([str(a), str(b), "--gate"]) == 0


def test_precision_tau_below_threshold_is_a_hard_regression():
    old = _with_precision(_sidecar(), 1.0)
    new = _with_precision(_sidecar(), 0.95)      # < 0.99 default floor
    result = bench_diff.diff_sidecars(old, new, 0.10)
    rows = {r["row"]: r for r in result["regressions"]}
    assert "precision.tau_b" in rows
    assert any("lost rank agreement" in n for n in result["notes"])
    # the floor is tunable: an explicitly looser gate admits the pair
    loose = bench_diff.diff_sidecars(old, new, 0.10, tau_threshold=0.9)
    assert not any(r["row"] == "precision.tau_b"
                   for r in loose["regressions"])


def test_fp32_pair_must_rank_agree_exactly():
    """mode=fp32 claiming tau < 1.0 regresses regardless of threshold:
    an fp32 run that disagrees with its fp32 twin is broken, not slow."""
    new = _with_precision(_sidecar(), 0.9999, mode="fp32")
    result = bench_diff.diff_sidecars(_sidecar(), new, 0.10,
                                      tau_threshold=0.5)
    assert any(r["row"] == "precision.tau_b"
               for r in result["regressions"])


def test_precision_baseline_defaults_to_contract_value():
    # an fp32 baseline sidecar has no precision block: displayed
    # baseline is the contract value 1.0, and the ulp spread is an
    # informational note, never a gated row
    result = bench_diff.diff_sidecars(
        _sidecar(), _with_precision(_sidecar(), 1.0), 0.10)
    row = [r for r in result["rows"] if r["row"] == "precision.tau_b"][0]
    assert row["old"] == 1.0 and not row["regressed"]
    assert any("ulp" in n for n in result["notes"])


def test_recon_kernel_query_latency_is_direction_aware():
    old, new = copy.deepcopy(_sidecar()), copy.deepcopy(_sidecar())
    old["recon"] = {"kernel_query_s": 0.10}
    new["recon"] = {"kernel_query_s": 0.20}     # 2x slower fresh query
    result = bench_diff.diff_sidecars(old, new, 0.10)
    assert any(r["row"] == "recon.kernel_query_s"
               for r in result["regressions"])
    faster = copy.deepcopy(old)
    faster["recon"]["kernel_query_s"] = 0.05
    assert not bench_diff.diff_sidecars(old, faster, 0.10)["regressions"]
