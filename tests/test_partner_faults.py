"""Partner-level fault model + trust-calibrated Shapley (ISSUE 6).

Three contracts under test:

1. **Determinism & exclusion equality.** Partner-fault runs are fully
   deterministic (same plan twice => bit-identical v(S) and Shapley
   values), and a partner dropped from epoch 1 is an exact null player:
   every faulty v(S) equals the fault-free v(S minus the partner) BIT
   FOR BIT — trainer-level masking + FedAvg renormalization reproduce
   exclusion exactly (rng canonicalized over the effective membership).

2. **Corruption vocabulary.** 'noisy'/'glabel' extend corrupted_datasets
   with seeded generators; unknown names now raise at Scenario
   construction with the valid list; the fault plan's data-plane entries
   ride the same operators.

3. **Seed-ensemble trust.** seed_ensemble=K packs K replicas as extra
   slot-batch rows (dispatch count grows SUB-linearly in K — asserted on
   the engine.batches counter), replica 0 is bit-identical to a K=1 run,
   and the Shapley path grows per-partner CIs + a Kendall-tau
   rank-stability score rendered as the report's `trust` row.
"""

import os

import numpy as np
import pytest

from mplc_tpu import faults
from mplc_tpu.contrib.contributivity import Contributivity
from mplc_tpu.contrib.engine import CharacteristicEngine
from mplc_tpu.contrib.shapley import (confidence_intervals, kendall_tau,
                                      powerset_order, rank_stability,
                                      shapley_from_characteristic,
                                      shapley_sample_matrix, trust_summary)
from mplc_tpu.obs import metrics, report, trace


def scenario(n=4, seed=9, **kw):
    from helpers import build_scenario
    amounts = {3: [0.2, 0.3, 0.5], 4: [0.1, 0.2, 0.3, 0.4]}[n]
    params = dict(partners_count=n, amounts_per_partner=amounts,
                  dataset_name="titanic", epoch_count=2,
                  gradient_updates_per_pass_count=2, seed=seed)
    params.update(kw)
    return build_scenario(**params)


SUBSETS = powerset_order(4)

_KNOBS = ("MPLC_TPU_PARTNER_FAULT_PLAN", "MPLC_TPU_SEED_ENSEMBLE",
          "MPLC_TPU_FAULT_PLAN")


@pytest.fixture(autouse=True)
def _env(monkeypatch):
    for k in _KNOBS:
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "1")
    metrics.reset()
    yield
    metrics.reset()


_REF = {}


def reference():
    """Fault-free single-seed v(S) for `scenario()`, once per process."""
    assert "MPLC_TPU_PARTNER_FAULT_PLAN" not in os.environ
    if "vals" not in _REF:
        _REF["vals"] = CharacteristicEngine(scenario()).evaluate(SUBSETS)
    return _REF["vals"]


# -- plan grammar ------------------------------------------------------------

def test_partner_plan_grammar_parses_all_kinds():
    plan = faults.parse_partner_fault_plan(
        "dropout@p2:epoch3, straggler@p0:delay2,noisy@p1:sigma0.1,"
        "glabel@p3:frac0.5,straggler@p2:delay1")
    assert plan == {2: {"dropout": 3, "straggler": 1},
                    0: {"straggler": 2},
                    1: {"noisy": 0.1},
                    3: {"glabel": 0.5}}
    assert faults.parse_partner_fault_plan(None) == {}
    assert faults.parse_partner_fault_plan("") == {}


def test_partner_plan_malformed_entries_warn_and_are_skipped():
    for bad in ("dropout@p2:delay3",        # kind/param mismatch
                "dropout@p2:epoch0",        # ordinal < 1
                "glabel@p1:frac1.5",        # out of [0, 1]
                "vanish@p1:epoch2",         # unknown kind
                "dropout@2:epoch3",         # missing 'p'
                "dropout@p2"):              # no param
        with pytest.warns(UserWarning, match="malformed entry"):
            assert faults.parse_partner_fault_plan(bad) == {}


def test_partner_plan_duplicate_keeps_first_and_warns():
    with pytest.warns(UserWarning, match="duplicate"):
        plan = faults.parse_partner_fault_plan(
            "dropout@p1:epoch2,dropout@p1:epoch5")
    assert plan == {1: {"dropout": 2}}


def test_partner_plan_views():
    plan = faults.parse_partner_fault_plan(
        "dropout@p0:epoch1,dropout@p2:epoch3,straggler@p1:delay2,"
        "noisy@p1:sigma0.2,glabel@p3:frac1.0")
    drops, delays = faults.trainer_fault_arrays(plan, 4)
    assert drops == (1, 0, 3, 0)
    assert delays == (0, 2, 0, 0)
    assert faults.forever_dropped(plan) == frozenset({0})
    assert faults.data_fault_specs(plan) == {1: [("noisy", 0.2)],
                                             3: [("glabel", 1.0)]}
    # no trainer faults at all -> both None (fault-free compiled programs)
    assert faults.trainer_fault_arrays(
        {1: {"noisy": 0.2}}, 4) == (None, None)
    # out-of-range ids clip with a warning
    with pytest.warns(UserWarning, match="ignoring entries"):
        clipped = faults.clip_partner_plan(plan, 2)
    assert set(clipped) == {0, 1}
    # canonical repr is sorted and stable
    assert faults.normalized_plan_repr(plan) == \
        "dropout@p0:1,noisy@p1:0.2,straggler@p1:2,dropout@p2:3,glabel@p3:1.0"


# -- dropout: determinism + exclusion equality (satellite 3) -----------------

def test_forever_dropout_equals_partner_excluded_runs(monkeypatch):
    """dropout@pK:epoch1: every faulty v(S) must BIT-IDENTICALLY equal
    the fault-free v(S \\ {K}) — the trainer-level mask + FedAvg weight
    renormalization reproduce exclusion exactly, and a coalition reduced
    to nothing takes v(empty) = 0."""
    ref = dict(zip(SUBSETS, reference()))
    monkeypatch.setenv("MPLC_TPU_PARTNER_FAULT_PLAN", "dropout@p2:epoch1")
    eng = CharacteristicEngine(scenario())
    vals = dict(zip(SUBSETS, eng.evaluate(SUBSETS)))
    for s in SUBSETS:
        eff = tuple(i for i in s if i != 2)
        expected = ref[eff] if eff else 0.0
        assert vals[s] == expected, (s, vals[s], expected)
    assert eng.first_charac_fct_calls_count == len(SUBSETS)


def test_forever_dropout_shapley_matches_restricted_game(monkeypatch):
    """The dropped partner is an exact null player: its Shapley value is
    0 and the survivors' values equal the (P-1)-partner restricted
    game's (the carrier property, on measured v(S) tables)."""
    ref = dict(zip(SUBSETS, reference()))
    monkeypatch.setenv("MPLC_TPU_PARTNER_FAULT_PLAN", "dropout@p2:epoch1")
    vals = dict(zip(SUBSETS, CharacteristicEngine(scenario()).evaluate(SUBSETS)))
    sv_f = shapley_from_characteristic(4, vals)
    assert sv_f[2] == 0.0
    # restricted 3-player game over partners {0, 1, 3} (remapped 0/1/2)
    remap = {0: 0, 1: 1, 3: 2}
    restricted = {tuple(sorted(remap[i] for i in s)): v
                  for s, v in ref.items() if 2 not in s}
    sv_r = shapley_from_characteristic(3, restricted)
    np.testing.assert_allclose(sv_f[[0, 1, 3]], sv_r, atol=1e-12)


def test_partner_fault_runs_are_deterministic(monkeypatch):
    """Same plan twice => bit-identical v(S) AND Shapley values (the
    satellite's determinism contract), for a mid-run dropout + straggler
    combination plan."""
    ref = reference()
    monkeypatch.setenv("MPLC_TPU_PARTNER_FAULT_PLAN",
                       "dropout@p1:epoch2,straggler@p0:delay2")
    a = CharacteristicEngine(scenario()).evaluate(SUBSETS)
    b = CharacteristicEngine(scenario()).evaluate(SUBSETS)
    np.testing.assert_array_equal(a, b)
    sv_a = shapley_from_characteristic(4, dict(zip(SUBSETS, a)))
    sv_b = shapley_from_characteristic(4, dict(zip(SUBSETS, b)))
    np.testing.assert_array_equal(sv_a, sv_b)
    # and the faults actually bit: the faulty game differs from clean
    assert not np.array_equal(a, ref)


def test_midrun_dropout_and_straggler_leave_unaffected_coalitions_alone(
        monkeypatch):
    """Faults on partner K must not perturb coalitions that exclude K:
    those subsets' v(S) stay bit-identical to the fault-free run's (the
    fault arrays ride the config, but only bound slots read them)."""
    ref = dict(zip(SUBSETS, reference()))
    monkeypatch.setenv("MPLC_TPU_PARTNER_FAULT_PLAN",
                       "dropout@p3:epoch2,straggler@p3:delay1")
    vals = dict(zip(SUBSETS, CharacteristicEngine(scenario()).evaluate(SUBSETS)))
    without_3 = [s for s in SUBSETS if 3 not in s]
    for s in without_3:
        assert vals[s] == ref[s], s
    # ...and coalitions WITH the faulted partner did change
    assert any(vals[s] != ref[s] for s in SUBSETS if 3 in s)


def test_all_members_dropped_midrun_keeps_finite_values(monkeypatch):
    """A round with zero survivors must keep the global params (not
    aggregate an all-zero weight vector into a zero model): values stay
    finite and deterministic."""
    monkeypatch.setenv("MPLC_TPU_PARTNER_FAULT_PLAN",
                       "dropout@p0:epoch2,dropout@p1:epoch2")
    eng = CharacteristicEngine(scenario())
    vals = eng.evaluate([(0, 1), (0,), (1,)])
    assert np.all(np.isfinite(vals))
    vals2 = CharacteristicEngine(scenario()).evaluate([(0, 1), (0,), (1,)])
    np.testing.assert_array_equal(vals, vals2)


def test_trainer_faults_require_fedavg(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_PARTNER_FAULT_PLAN", "dropout@p0:epoch2")
    with pytest.raises(ValueError, match="fedavg"):
        CharacteristicEngine(
            scenario(multi_partner_learning_approach="seq-pure"))


# -- corruption vocabulary (satellite 1) -------------------------------------

def test_unknown_corruption_raises_with_valid_names():
    with pytest.raises(ValueError, match="glabel"):
        scenario(corrupted_datasets=["not_corrupted", "bogus",
                                     "not_corrupted", "not_corrupted"])
    with pytest.raises(ValueError, match="one spec per partner"):
        scenario(corrupted_datasets=["not_corrupted"] * 3)


def test_noisy_and_glabel_corruptions_are_seeded():
    clean = scenario(seed=5)
    sc = scenario(seed=5, corrupted_datasets=[("noisy", 0.5),
                                              ("glabel", 1.0),
                                              "not_corrupted",
                                              "not_corrupted"])
    sc2 = scenario(seed=5, corrupted_datasets=[("noisy", 0.5),
                                               ("glabel", 1.0),
                                               "not_corrupted",
                                               "not_corrupted"])
    # noisy perturbs features, deterministically per seed
    assert not np.array_equal(sc.partners_list[0].x_train,
                              clean.partners_list[0].x_train)
    np.testing.assert_array_equal(sc.partners_list[0].x_train,
                                  sc2.partners_list[0].x_train)
    # glabel collapses the partner's labels onto ONE target class
    assert len(np.unique(np.asarray(sc.partners_list[1].y_train))) == 1
    # untouched partners stay untouched
    np.testing.assert_array_equal(sc.partners_list[2].x_train,
                                  clean.partners_list[2].x_train)


def test_plan_data_faults_apply_at_corruption_time(monkeypatch):
    clean = scenario(seed=5)
    monkeypatch.setenv("MPLC_TPU_PARTNER_FAULT_PLAN", "noisy@p1:sigma0.5")
    sc = scenario(seed=5)
    assert not np.array_equal(sc.partners_list[1].x_train,
                              clean.partners_list[1].x_train)
    np.testing.assert_array_equal(sc.partners_list[0].x_train,
                                  clean.partners_list[0].x_train)


# -- seed-ensemble sweeps ----------------------------------------------------

def test_ensemble_replica0_is_bit_identical_to_single_seed():
    ref = reference()
    eng = CharacteristicEngine(scenario(), seed_ensemble=3)
    vals = eng.evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)
    # every subset carries a full replica row, replica 0 = the point value
    assert set(eng.charac_fct_samples) == set(SUBSETS)
    for s in SUBSETS:
        arr = eng.charac_fct_samples[s]
        assert arr.shape == (3,) and not np.isnan(arr).any()
        assert arr[0] == eng.charac_fct_values[s]
    # the replicas are genuinely different games (different base seeds)
    assert any(len(set(eng.charac_fct_samples[s])) > 1 for s in SUBSETS)
    assert eng.first_charac_fct_calls_count == len(SUBSETS)


def test_ensemble_batches_grow_sublinearly(monkeypatch):
    """K replicas ride the SAME buckets as extra rows — the acceptance
    criterion's engine.batch dispatch count must grow sub-linearly in K
    (asserted via the obs counter, as the issue specifies)."""
    CharacteristicEngine(scenario()).evaluate(SUBSETS)
    b1 = metrics.snapshot()["counters"]["engine.batches"]
    metrics.reset()
    CharacteristicEngine(scenario(), seed_ensemble=4).evaluate(SUBSETS)
    b4 = metrics.snapshot()["counters"]["engine.batches"]
    assert b1 > 0 and b4 < 4 * b1, (b1, b4)


def test_ensemble_env_knob_drives_compute_sv_trust(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_SEED_ENSEMBLE", "3")
    sc = scenario()
    with trace.collect() as recs:
        c = Contributivity(sc)
        c.compute_contributivity("Shapley values")
    assert c.trust is not None
    assert c.trust["ensemble"] == 3
    assert -1.0 <= c.trust["kendall_tau"] <= 1.0
    assert len(c.trust["ci_low"]) == 4
    # the replica spread is the honest scores_std
    assert (np.asarray(c.scores_std) >= 0).all()
    assert np.any(np.asarray(c.trust["std"]) > 0)
    # CI brackets the mean
    assert np.all(np.asarray(c.trust["ci_low"])
                  <= np.asarray(c.trust["mean"]))
    assert np.all(np.asarray(c.trust["mean"])
                  <= np.asarray(c.trust["ci_high"]))
    # the trust event reached the collected trace -> report + rendering
    rep = report.sweep_report(recs)
    assert rep["trust"]["ensemble"] == 3
    assert "trust" in report.format_report(rep)


def test_ensemble_oom_recovery_does_not_double_count(monkeypatch):
    """A subset whose replica rows straddle two batches re-runs ALL its
    replicas when the second batch's harvest OOMs — the recovery must not
    re-store the already-stored replica-0 point estimate (that would
    inflate first_charac_fct_calls_count past the coalition count and
    trip bench's post-sweep assert)."""
    ref = reference()
    monkeypatch.setenv("MPLC_TPU_RETRY_BACKOFF_SEC", "0")
    # K=3 on 4 singles = 12 jobs at width 8: subset 2's replicas straddle
    # batches 1 and 2; the harvest-2 OOM forces the redo of subsets 2+3
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "oom@harvest2")
    eng = CharacteristicEngine(scenario(), seed_ensemble=3)
    vals = eng.evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)
    assert eng.first_charac_fct_calls_count == len(SUBSETS)
    for s in SUBSETS:
        assert not np.isnan(eng.charac_fct_samples[s]).any(), s


def test_ensemble_composes_with_forever_dropout(monkeypatch):
    """The two tentpole halves compose: under a seed ensemble EVERY
    replica honors the dropout-exclusion equality (rng canonicalization
    is per-row, so replica j of S u {k} trains replica j of S)."""
    monkeypatch.setenv("MPLC_TPU_PARTNER_FAULT_PLAN", "dropout@p2:epoch1")
    eng = CharacteristicEngine(scenario(), seed_ensemble=2)
    eng.evaluate(SUBSETS)
    for s in SUBSETS:
        eff = tuple(i for i in s if i != 2)
        if not eff:
            np.testing.assert_array_equal(eng.charac_fct_samples[s],
                                          np.zeros(2))
        elif eff != s:
            np.testing.assert_array_equal(eng.charac_fct_samples[s],
                                          eng.charac_fct_samples[eff])


def test_ensemble_rejected_in_2d_mode(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_PARTNER_SHARDS", "2")
    with pytest.raises(ValueError, match="2-D"):
        CharacteristicEngine(scenario(), seed_ensemble=2)


def test_ensemble_cache_roundtrip_and_fingerprint(tmp_path, monkeypatch):
    eng = CharacteristicEngine(scenario(), seed_ensemble=2)
    eng.evaluate(SUBSETS)
    path = tmp_path / "cache.json"
    eng.save_cache(path)
    resumed = CharacteristicEngine(scenario(), seed_ensemble=2)
    resumed.load_cache(path)
    assert resumed.charac_fct_values == eng.charac_fct_values
    for s, arr in eng.charac_fct_samples.items():
        np.testing.assert_array_equal(resumed.charac_fct_samples[s], arr)
    # a single-seed engine refuses the ensemble cache (different game
    # description), and a partner-fault plan refuses a clean cache
    with pytest.raises(ValueError, match="different scenario"):
        CharacteristicEngine(scenario()).load_cache(path)
    clean_path = tmp_path / "clean.json"
    clean = CharacteristicEngine(scenario())
    clean.evaluate(SUBSETS[:3])
    clean.save_cache(clean_path)
    monkeypatch.setenv("MPLC_TPU_PARTNER_FAULT_PLAN", "dropout@p1:epoch2")
    with pytest.raises(ValueError, match="different scenario"):
        CharacteristicEngine(scenario()).load_cache(clean_path)


# -- trust math --------------------------------------------------------------

def test_kendall_tau_and_rank_stability():
    assert kendall_tau([1, 2, 3], [10, 20, 30]) == 1.0
    assert kendall_tau([1, 2, 3], [3, 2, 1]) == -1.0
    assert kendall_tau([5.0], [1.0]) == 1.0
    samples = np.array([[0.1, 0.2, 0.3],
                        [0.15, 0.25, 0.35],
                        [0.1, 0.22, 0.31]])
    assert rank_stability(samples) == 1.0          # all replicas agree
    flipped = np.array([[0.1, 0.2, 0.3], [0.3, 0.2, 0.1]])
    assert rank_stability(flipped) == -1.0
    assert rank_stability(samples[:1]) == 1.0      # K = 1: trivially stable


def test_confidence_intervals_and_sample_matrix():
    n = 3
    phi = np.array([0.1, 0.25, 0.65])
    # additive game, replica j scaled by (1 + j/10): SV_j = phi * scale_j
    samples = {}
    for s in powerset_order(n):
        samples[s] = np.array([sum(phi[i] for i in s) * (1 + j / 10)
                               for j in range(4)])
    sv = shapley_sample_matrix(n, samples)
    assert sv.shape == (4, n)
    for j in range(4):
        np.testing.assert_allclose(sv[j], phi * (1 + j / 10), atol=1e-12)
    mean, lo, hi = confidence_intervals(sv)
    assert np.all(lo <= mean) and np.all(mean <= hi)
    assert np.all(hi - lo > 0)                     # genuine spread
    t = trust_summary(n, samples)
    assert t["ensemble"] == 4 and t["kendall_tau"] == 1.0
    np.testing.assert_allclose(t["mean"], mean)
    # K = 1 degenerates to zero-width intervals
    one = {s: arr[:1] for s, arr in samples.items()}
    t1 = trust_summary(n, one)
    assert t1["ci_low"] == t1["ci_high"] == t1["mean"]
    with pytest.raises(ValueError, match="empty replica table"):
        shapley_sample_matrix(n, {})


def test_sweep_report_without_trust_row_still_formats():
    rep = report.sweep_report([])
    assert "trust" not in rep
    assert "sweep report" in report.format_report(rep)
