"""Execute every analysis notebook's code cells end to end.

The notebooks are user-facing deliverables (reference ships runnable
analysis notebooks, /root/reference/notebooks/); nothing else would catch
API rot in them. Cells run with the kernel cwd at notebooks/ — the same
convention a real jupyter launch uses — on the CPU mesh, with matplotlib
headless.
"""

import json
import os
from pathlib import Path

import pytest

NOTEBOOKS_DIR = Path(__file__).resolve().parents[1] / "notebooks"


@pytest.mark.slow
@pytest.mark.parametrize("name", ["results_analysis.ipynb",
                                  "mpl_analysis.ipynb",
                                  "method_comparison.ipynb",
                                  "run_experiment_on_tpu.ipynb"])
def test_notebook_code_cells_execute(name, monkeypatch):
    monkeypatch.setenv("MPLBACKEND", "Agg")           # headless plotting
    monkeypatch.setenv("MPLC_TPU_SYNTH_SCALE", "0.02")
    monkeypatch.chdir(NOTEBOOKS_DIR)
    nb = json.loads((NOTEBOOKS_DIR / name).read_text())
    ns = {}
    for i, cell in enumerate(nb["cells"]):
        if cell["cell_type"] != "code":
            continue
        # strip IPython magics (%matplotlib inline, !pip ...) — they are
        # kernel directives, not Python
        src = "".join(l for l in cell["source"]
                      if not l.lstrip().startswith(("%", "!")))
        try:
            exec(compile(src, f"{name}:cell{i}", "exec"), ns)
        except Exception as e:
            pytest.fail(f"{name} cell {i} raised {e!r}\n--- cell source ---\n"
                        f"{src[:1500]}")


def test_notebooks_are_valid_json():
    names = sorted(p.name for p in NOTEBOOKS_DIR.glob("*.ipynb"))
    assert len(names) >= 4
    for p in NOTEBOOKS_DIR.glob("**/*.ipynb"):
        nb = json.loads(p.read_text())
        assert nb.get("cells"), f"{p} has no cells"
