"""Non-gating CPU throughput microbench (fast tier): prints steps/sec and
the MFU-proxy row for a small fedavg and a small seq sweep on every run,
so per-step-intensity regressions are visible in ordinary CI output
(`pytest -s`, or the captured stdout of a failing run) without waiting
for chip time.

Deliberately NON-GATING on the throughput numbers themselves — a loaded
CI box must not flake the suite — but the accounting structure (samples,
partner passes, a finite rate, the flops pipeline) is asserted, so a
regression that breaks the measurement (rather than slows the code)
still fails loudly.
"""

import numpy as np

from mplc_tpu.contrib.engine import CharacteristicEngine
from mplc_tpu.contrib.shapley import powerset_order
from mplc_tpu.models.zoo import fwd_flops_per_sample
from mplc_tpu.obs import trace
from mplc_tpu.obs.report import format_report, sweep_report


def _scenario(approach, n=4):
    from helpers import build_scenario
    amounts = [(i + 1) / (n * (n + 1) / 2) for i in range(n)]
    return build_scenario(partners_count=n, amounts_per_partner=amounts,
                          dataset_name="titanic", epoch_count=2,
                          gradient_updates_per_pass_count=2,
                          multi_partner_learning_approach=approach, seed=7)


def _microbench(approach):
    eng = CharacteristicEngine(_scenario(approach))
    subsets = powerset_order(4)
    with trace.collect() as recs:
        vals = eng.evaluate(subsets)
    assert np.isfinite(vals).all()
    rep = sweep_report(
        recs, flops_per_sample=fwd_flops_per_sample(eng.model.name))
    c = rep["compute"]
    # the accounting must be present and coherent — these gate
    assert c["train_samples"] == eng.samples_trained > 0
    assert c["partner_passes"] > 0
    assert c["samples_per_s"] and np.isfinite(c["samples_per_s"])
    assert c["model_flops_per_s"] and np.isfinite(c["model_flops_per_s"])
    assert c["mfu_proxy"] is None  # no peak-FLOPs figure for host CPUs
    # SGD steps executed: partner passes x gradient updates per pass
    gup = eng.multi_pipe.trainer.cfg.gradient_updates_per_pass
    mult = eng.multi_pipe.trainer.cfg.step_width_mult
    steps = c["partner_passes"] * ((gup + mult - 1) // mult)
    basis = rep["wallclock"]["evaluate_s"]
    print(f"\n[microbench] {approach}: {steps} SGD steps, "
          f"{steps / basis:.1f} steps/s, "
          f"{c['samples_per_s']:.0f} samples/s, "
          f"{c['model_flops_per_s'] / 1e6:.2f} MFLOP/s model compute "
          f"(CPU mesh; MFU-proxy n/a without a peak figure)")
    print(format_report(rep))
    return rep


def test_cpu_throughput_microbench_fedavg():
    rep = _microbench("fedavg")
    # fedavg routes through slot execution: no multi bucket may exceed
    # slot_count=4 passes per coalition-minibatch
    for row in rep["per_width"]:
        assert row["slot_count"] is None or row["slot_count"] <= 4


def test_cpu_throughput_microbench_seq():
    _microbench("seq-pure")


def test_value_ledger_host_overhead_on_microbench(tmp_path, monkeypatch):
    """The numeric-truth acceptance bound, measured where it bites: the
    per-value ledger hashing (obs/numerics.py) must add <5% host
    overhead to this sweep's work. Measured directly as hashing seconds
    per harvested value against the sweep's per-coalition wall-clock —
    the sweep itself is not re-timed (a loaded CI box must not flake the
    suite on a wall-clock ratio of two noisy runs)."""
    import time

    from mplc_tpu.obs import numerics

    monkeypatch.setenv("MPLC_TPU_NUMERICS_LEDGER",
                       str(tmp_path / "led.json"))
    eng = CharacteristicEngine(_scenario("fedavg"))
    subsets = powerset_order(4)
    t0 = time.perf_counter()
    eng.evaluate(subsets)
    sweep_s = time.perf_counter() - t0
    n = len(eng.numerics_ledger.entries)
    assert n == len(subsets)
    # re-measure the exact recording work the sweep paid, in isolation
    probe = numerics.ValueLedger("fp", dict(eng.numerics_ledger.meta))
    t0 = time.perf_counter()
    for s in subsets:
        probe.record(s, eng.charac_fct_values[s], slot_width=4)
    ledger_s = time.perf_counter() - t0
    frac = ledger_s / max(sweep_s, 1e-9)
    print(f"\n[microbench] ledger hashing: {1e6 * ledger_s / n:.1f} us/value, "
          f"{100 * frac:.3f}% of the sweep's host wall-clock")
    assert frac < 0.05, (
        f"ledger hashing cost {frac:.1%} of the sweep — the <5% "
        "numeric-truth overhead bound no longer holds")
