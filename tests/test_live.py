"""The live contributivity tier (mplc_tpu/live/): resident incremental
games, sub-second queries, DPVS pruning, journal recovery, and the
service's low-latency live job class.

The contract under test:

1. **Warm path = zero training.** `LiveGame.query` on a game fed by
   `append_round` completes with zero training batches — counter-asserted
   via `engine.partner_passes` and the `engine.batch` events (all
   `eval_only`) — and repeated queries at an unchanged round-stamp are
   memo hits whose latency does not scale with resident rounds.
2. **The incremental invariant.** Append K rounds one-at-a-time (querying
   in between) ≡ bit-identical to appending all K up front, for exact,
   GTG-Shapley and SVARM; a NON-invalidating (all-zero-weight) append
   preserves memoized values bit-identically; an invalidating append
   advances the round-stamp and a stale result is never served.
3. **Journal recovery.** kill→restart (a fresh LiveGame on the same WAL)
   answers queries bit-identically; a different game's journal is
   refused.
4. **DPVS pruning.** Off (tau=0) ⇒ bit-identical to the unpruned path;
   on ⇒ coalition evaluations measurably reduced (counter-asserted) with
   rank agreement inside the pinned Kendall-tau bound — including the
   >=20-partner (33, multi-word bitmask) smoke through the real engine.
5. **Service integration.** submit_live rides the existing admission/
   priority machinery one tier above the batch default, answers equal
   the direct query, and the resident game appears on /varz.
"""

import numpy as np
import pytest

import jax

from helpers import build_scenario, cluster_mlp_dataset
from mplc_tpu.contrib.shapley import (kendall_tau, powerset_order,
                                      shapley_from_characteristic)
from mplc_tpu.live import (LiveGame, LiveGameFull, info_scores,
                           low_information)
from mplc_tpu.obs import metrics
from mplc_tpu.obs import trace as obs_trace
from mplc_tpu.obs.report import format_report, sweep_report


# ---------------------------------------------------------------------------
# scenario + synthetic-round helpers (no training: rounds are appended)
# ---------------------------------------------------------------------------

def _scenario_3p(seed=3):
    return build_scenario(
        partners_count=3, amounts_per_partner=[0.2, 0.3, 0.5],
        dataset=cluster_mlp_dataset(n=240, seed=9, scale=1.0),
        epoch_count=2, minibatch_count=2, seed=seed)


def _synth_rounds(game, k, seed=0, scale=0.08):
    """k deterministic synthetic aggregation rounds shaped like the
    game's model params."""
    rng = np.random.default_rng(seed)
    P = game.engine.partners_count
    rounds = []
    for _ in range(k):
        deltas = jax.tree_util.tree_map(
            lambda l: rng.normal(0, scale, (P,) + l.shape).astype(l.dtype),
            game._init_params)
        w = rng.dirichlet(np.ones(P)).astype(np.float32)
        rounds.append((deltas, w))
    return rounds


def _zero_round(game):
    P = game.engine.partners_count
    deltas = jax.tree_util.tree_map(
        lambda l: np.zeros((P,) + l.shape, l.dtype), game._init_params)
    return deltas, np.zeros(P, np.float32)


@pytest.fixture(scope="module")
def scen3():
    return _scenario_3p()


# ---------------------------------------------------------------------------
# 1. warm path: zero training batches, memoized non-scaling queries
# ---------------------------------------------------------------------------

def test_warm_query_zero_training_and_memo(scen3):
    game = LiveGame(scen3)
    for deltas, w in _synth_rounds(game, 3, seed=1):
        game.append_round(deltas, w)
    metrics.reset()
    with obs_trace.collect() as records:
        r1 = game.query("exact")
    snap = metrics.snapshot()
    # zero training: no partner passes, every engine.batch eval-only
    assert snap["counters"].get("engine.partner_passes", 0) == 0
    assert snap["counters"].get("engine.epochs_trained", 0) == 0
    batches = [rec for rec in records if rec["name"] == "engine.batch"]
    assert batches and all(b["attrs"].get("eval_only") for b in batches)
    assert all(b["attrs"]["partner_passes"] == 0 for b in batches)
    assert r1.evaluations > 0 and r1.stamp == game.round_stamp

    # warm re-query: a memo hit — the SAME result object, no device work
    with obs_trace.collect() as records2:
        r2 = game.query("exact")
    assert r2 is r1
    assert not [rec for rec in records2 if rec["name"] == "engine.batch"]
    q = [rec for rec in records2 if rec["name"] == "live.query"]
    assert len(q) == 1 and q[0]["attrs"]["memo_hit"] is True
    # CPU-tier latency pin: the memoized path answers without touching
    # the reconstruction stack at all, so it cannot scale with rounds
    assert q[0]["dur"] < 0.05


def test_non_invalidating_append_preserves_memo_bit_identically(scen3):
    game = LiveGame(scen3)
    for deltas, w in _synth_rounds(game, 2, seed=2):
        game.append_round(deltas, w)
    r1 = game.query("exact")
    stamp = game.round_stamp
    # pile on zero-weight rounds: resident count grows, stamp does not
    for _ in range(4):
        assert game.append_round(*_zero_round(game)) == stamp
    assert game.rounds_resident == 6 and game.round_stamp == stamp
    with obs_trace.collect() as records:
        r2 = game.query("exact")
    assert r2 is r1  # bit-identical survival: the very same result
    assert not [rec for rec in records if rec["name"] == "engine.batch"]


def test_invalidating_append_never_serves_stale(scen3):
    game = LiveGame(scen3)
    rounds = _synth_rounds(game, 3, seed=4)
    game.append_round(*rounds[0])
    r1 = game.query("exact")
    game.append_round(*rounds[1])
    assert r1.stamp < game.round_stamp  # r1 is now STALE
    with obs_trace.collect() as records:
        r2 = game.query("exact")
    assert r2 is not r1 and r2.stamp == game.round_stamp
    # the recompute really ran device evaluations over the new stack
    assert [rec for rec in records if rec["name"] == "engine.batch"]
    assert r2.evaluations > 0


# ---------------------------------------------------------------------------
# 2. the incremental invariant: one-at-a-time == all-up-front
# ---------------------------------------------------------------------------

def test_incremental_equals_upfront_for_all_methods(scen3):
    game_a = LiveGame(scen3)
    game_b = LiveGame(scen3)
    rounds = _synth_rounds(game_a, 3, seed=5)
    kw = {"exact": {},
          "GTG-Shapley": dict(sv_accuracy=1.0, min_iter=8, perm_batch=4),
          "SVARM": dict(budget=24, block=8)}
    for deltas, w in rounds:
        game_a.append_round(deltas, w)
        game_a.query("exact")  # interleaved queries must not perturb
    for deltas, w in rounds:
        game_b.append_round(deltas, w)
    for method in ("exact", "GTG-Shapley", "SVARM"):
        ra = game_a.query(method, **kw[method])
        rb = game_b.query(method, **kw[method])
        np.testing.assert_array_equal(ra.scores, rb.scores), method


# ---------------------------------------------------------------------------
# 3. journal: kill -> restart -> query equality; foreign journals refused
# ---------------------------------------------------------------------------

def test_journal_kill_restart_query_equality(tmp_path):
    wal = str(tmp_path / "live_wal.jsonl")
    sc = _scenario_3p()
    game = LiveGame.from_recording(sc, journal_path=wal)
    for deltas, w in _synth_rounds(game, 2, seed=6):
        game.append_round(deltas, w)
    r = game.query("exact")
    r_gtg = game.query("GTG-Shapley", sv_accuracy=1.0, min_iter=8,
                       perm_batch=4)
    game.close()  # the "kill": the process's in-memory game is gone

    sc2 = _scenario_3p()
    metrics.reset()
    restored = LiveGame(sc2, journal_path=wal)
    assert restored.rounds_resident == game.rounds_resident
    assert restored.round_stamp == game.round_stamp
    assert metrics.snapshot()["counters"].get("live.games_recovered") == 1
    r2 = restored.query("exact")
    np.testing.assert_array_equal(r2.scores, r.scores)
    r2_gtg = restored.query("GTG-Shapley", sv_accuracy=1.0, min_iter=8,
                            perm_batch=4)
    np.testing.assert_array_equal(r2_gtg.scores, r_gtg.scores)
    restored.close()


def test_journal_partner_mismatch_refused(tmp_path):
    wal = str(tmp_path / "live_wal.jsonl")
    sc = _scenario_3p()
    game = LiveGame(sc, journal_path=wal)
    game.append_round(*_synth_rounds(game, 1, seed=7)[0])
    game.close()
    sc4 = build_scenario(
        partners_count=4, amounts_per_partner=[0.1, 0.2, 0.3, 0.4],
        dataset=cluster_mlp_dataset(n=240, seed=9, scale=1.0),
        epoch_count=2, minibatch_count=2)
    with pytest.raises(ValueError, match="refusing to restore"):
        LiveGame(sc4, journal_path=wal)


def test_journal_model_mismatch_refused(tmp_path):
    wal = str(tmp_path / "live_wal.jsonl")
    sc = _scenario_3p()
    game = LiveGame(sc, journal_path=wal)
    game.append_round(*_synth_rounds(game, 1, seed=24)[0])
    game.close()
    # same partner count, different model name: same-shape architectures
    # must not silently answer the wrong game
    import dataclasses
    sc2 = _scenario_3p()
    eng2 = LiveGame(sc2).engine
    eng2.model = dataclasses.replace(eng2.model, name="other_model")
    sc3 = _scenario_3p()
    sc3._charac_engine = eng2
    with pytest.raises(ValueError, match="model"):
        LiveGame(sc3, engine=eng2, journal_path=wal)


def test_varz_live_games_redacted_for_other_tenants():
    from mplc_tpu.obs.export import redact_varz

    doc = {"live_games": {
        "acme": {"tenant": "acme", "rounds_resident": 7, "round_stamp": 3,
                 "queries": 2, "results_cached": 1, "max_rounds": 4096,
                 "resident": False, "last_restore_s": 0.125,
                 "journal": "/secret/path/wal.jsonl"},
        "beta": {"tenant": "beta", "rounds_resident": 1, "round_stamp": 1,
                 "queries": 0, "results_cached": 0, "max_rounds": 4096,
                 "resident": True, "last_restore_s": 0.0,
                 "journal": None}}}
    red = redact_varz(doc, viewer="beta", key="master")
    assert "beta" in red["live_games"]  # the viewer keeps its own row
    assert red["live_games"]["beta"]["journal"] is None
    others = [v for k, v in red["live_games"].items() if k != "beta"]
    assert len(others) == 1 and others[0]["redacted"] is True
    # residency state is a load signal, not an identity: it survives
    # redaction so co-tenants can reason about cache pressure
    assert others[0]["resident"] is False
    assert others[0]["last_restore_s"] == 0.125
    body = str(red)
    assert "acme" not in body and "/secret/path" not in body


def test_from_recording_on_restored_journal_does_not_double(tmp_path):
    wal = str(tmp_path / "live_wal.jsonl")
    sc = _scenario_3p()
    game = LiveGame.from_recording(sc, journal_path=wal)
    n = game.rounds_resident
    assert n > 0
    game.close()
    game2 = LiveGame.from_recording(_scenario_3p(), journal_path=wal)
    assert game2.rounds_resident == n  # restored, not re-recorded
    game2.close()


# ---------------------------------------------------------------------------
# caps & validation
# ---------------------------------------------------------------------------

def test_resident_round_cap(scen3, monkeypatch):
    game = LiveGame(scen3, max_rounds=2)
    rounds = _synth_rounds(game, 3, seed=8)
    game.append_round(*rounds[0])
    game.append_round(*rounds[1])
    with pytest.raises(LiveGameFull, match="MPLC_TPU_LIVE_MAX_ROUNDS"):
        game.append_round(*rounds[2])
    # the env knob is the construction-time default
    monkeypatch.setenv("MPLC_TPU_LIVE_MAX_ROUNDS", "1")
    game2 = LiveGame(scen3)
    assert game2.max_rounds == 1


def test_append_round_validates_shapes(scen3):
    game = LiveGame(scen3)
    deltas, w = _synth_rounds(game, 1, seed=9)[0]
    bad = jax.tree_util.tree_map(lambda l: l[:1], deltas)  # wrong P axis
    with pytest.raises(ValueError, match="delta leaf has shape"):
        game.append_round(bad, w)
    with pytest.raises(ValueError):
        game.query("no-such-method")


def test_exact_query_partner_bound(scen3):
    game = LiveGame(scen3)
    game.engine.partners_count = 17  # force past the exact bound
    try:
        with pytest.raises(ValueError, match="GTG-Shapley or"):
            game.query("exact")
    finally:
        game.engine.partners_count = 3


# ---------------------------------------------------------------------------
# 4. DPVS pruning
# ---------------------------------------------------------------------------

def test_dpvs_score_arithmetic():
    # 2 partners, 2 rounds, single scalar-leaf "params": s_p = sum |w| * |d|
    rounds = [({"w": np.array([[2.0], [0.5]])}, np.array([0.5, 0.5])),
              ({"w": np.array([[1.0], [0.0]])}, np.array([1.0, 0.0]))]
    s = info_scores(rounds, 2)
    np.testing.assert_allclose(s, [0.5 * 2.0 + 1.0 * 1.0, 0.5 * 0.5])
    assert low_information(s, 0.5) == frozenset({1})
    # the max scorer is never pruned; tau=0 and all-zero scores prune nobody
    assert low_information(s, 1.0) == frozenset({1})
    assert low_information(s, 0.0) == frozenset()
    assert low_information(np.zeros(3), 0.9) == frozenset()


def test_prune_off_bit_identical_to_unpruned_reconstruction(scen3):
    """The exactness-preserving off switch: tau = 0 values equal an
    independently-driven unpruned reconstruction of the same game,
    bit-identically."""
    game = LiveGame(scen3)
    for deltas, w in _synth_rounds(game, 2, seed=10):
        game.append_round(deltas, w)
    r = game.query("exact", prune=0.0)
    recon = game._evaluator()
    recon.evaluate(powerset_order(3))
    manual = np.asarray(shapley_from_characteristic(3, recon.values))
    np.testing.assert_array_equal(r.scores, manual)


def test_prune_reduces_evaluations_with_rank_agreement(scen3):
    """6-partner synthetic game where two partners contribute
    near-nothing: pruning on must evaluate measurably fewer coalitions
    (counter-asserted), zero the low-information partners, and keep rank
    agreement with the unpruned answer."""
    sc = build_scenario(
        partners_count=6, amounts_per_partner=[1 / 6.0] * 6,
        dataset=cluster_mlp_dataset(n=360, seed=9, scale=1.0),
        epoch_count=2, minibatch_count=2)
    game = LiveGame(sc)
    rng = np.random.default_rng(11)
    P = 6
    # low-information partners carry proportionally low aggregation
    # weight too — the data-volume-weighted FedAvg regime DPVS's
    # negligible-marginal assumption rests on (a tiny-delta partner with
    # a LARGE weight would still dilute everyone else's renormalized
    # weights, and pruning it would not approximate the game)
    scale = np.array([1.0, 0.8, 0.6, 0.4, 1e-5, 1e-5])
    weights = (scale / scale.sum()).astype(np.float32)
    for _ in range(3):
        deltas = jax.tree_util.tree_map(
            lambda l: (rng.normal(0, 0.08, (P,) + l.shape)
                       * scale.reshape((P,) + (1,) * len(l.shape))
                       ).astype(l.dtype),
            game._init_params)
        game.append_round(deltas, weights)
    game_b = LiveGame(sc)  # a twin on the same engine, fresh evaluator
    for deltas, w in game.round_history():
        game_b.append_round(deltas, w)

    metrics.reset()
    pruned = game.query("exact", prune=0.05)
    unpruned = game_b.query("exact", prune=0.0)
    assert pruned.low_info == (4, 5)
    assert pruned.pruned_coalitions > 0
    assert metrics.snapshot()["counters"].get(
        "live.pruned_coalitions", 0) == pruned.pruned_coalitions
    # measurably fewer device evaluations: 2^4-1 projections vs 2^6-1
    assert pruned.evaluations == 15 and unpruned.evaluations == 63
    np.testing.assert_array_equal(pruned.scores[4:], 0.0)
    # rank agreement: exact among the informative partners; looser over
    # the full vector — the unpruned path credits every partner a
    # baseline-accuracy share from the empty-prefix term (a tiny-delta
    # singleton reconstructs to the INIT model, which scores chance
    # accuracy), exactly the null-player artifact pruning zeroes out
    assert kendall_tau(unpruned.scores[:4], pruned.scores[:4]) >= 0.8
    assert kendall_tau(unpruned.scores, pruned.scores) >= 0.5


def test_live_game_smoke_33_partners_with_pruning():
    """The >=20-partner smoke: a 33-partner game (multi-word bitmask
    plumbing — two uint32 fold words) recorded end-to-end through the
    real engine, queried through LiveGame.query with DPVS pruning on.
    Pinned: pruning reduces coalition evaluations and rank-agrees with
    the unpruned answer (Kendall tau >= 0.6 — measured 0.79 on the CPU
    tier; the 6 deliberately-tiny partners are the pruned set)."""
    P = 33
    amounts = [float(i + 8) for i in range(27)] + [1.0] * 6
    amounts = [a / sum(amounts) for a in amounts]
    sc = build_scenario(
        partners_count=P, amounts_per_partner=amounts,
        dataset=cluster_mlp_dataset(n=1600, seed=13, scale=1.5),
        epoch_count=2, minibatch_count=2)
    game = LiveGame.from_recording(sc)
    assert game.engine._rng_word_count == 2  # the multi-word regime
    s = info_scores(game.round_history(), P)
    assert low_information(s, 0.1) == frozenset(range(27, 33))
    kw = dict(sv_accuracy=1.0, min_iter=8, perm_batch=8, truncation=0.0)
    unpruned = game.query("GTG-Shapley", prune=0.0, **kw)
    pruned = game.query("GTG-Shapley", prune=0.1, **kw)
    assert pruned.low_info == tuple(range(27, 33))
    assert pruned.pruned_coalitions > 0
    assert 0 < pruned.evaluations < unpruned.evaluations
    np.testing.assert_array_equal(pruned.scores[27:], 0.0)
    assert kendall_tau(unpruned.scores, pruned.scores) >= 0.6


# ---------------------------------------------------------------------------
# program bank: recon executables under shared-scope keys
# ---------------------------------------------------------------------------

def test_recon_programs_banked_across_same_shape_games():
    from mplc_tpu.contrib.bank import reset_bank

    reset_bank()  # earlier tests of the same SHAPE already banked these
    sc = _scenario_3p(seed=21)
    game1 = LiveGame(sc)
    rounds = _synth_rounds(game1, 2, seed=12)
    for deltas, w in rounds:
        game1.append_round(deltas, w)
    metrics.reset()
    r1 = game1.query("exact")
    snap1 = metrics.snapshot()["counters"]
    compiles = snap1.get("bank.compiles", 0)
    assert compiles >= 1  # the recon programs were AOT-banked
    # a second game of the same shape: its evaluator is fresh (cold memo)
    # but the banked executables serve it with zero new compiles
    game2 = LiveGame(sc)
    for deltas, w in rounds:
        game2.append_round(deltas, w)
    metrics.reset()
    r2 = game2.query("exact")
    snap2 = metrics.snapshot()["counters"]
    assert snap2.get("bank.compiles", 0) == 0
    assert snap2.get("bank.hits", 0) >= 1
    np.testing.assert_array_equal(r1.scores, r2.scores)


# ---------------------------------------------------------------------------
# 5. service integration: the low-latency live job class
# ---------------------------------------------------------------------------

def test_service_live_query_job(monkeypatch):
    from mplc_tpu.service import ServiceError, SweepService

    monkeypatch.setenv("MPLC_TPU_LIVE_QUERY_DEADLINE_SEC", "30")
    svc = SweepService(start=False)
    with pytest.raises(ServiceError, match="no live game"):
        svc.submit_live("tenantX")
    with pytest.raises(ServiceError, match="no live game"):
        svc.append_round("tenantX", None, None)

    sc = _scenario_3p(seed=22)
    game = svc.live_game(sc, tenant="tenantX")
    assert svc.live_game(sc, tenant="tenantX") is game  # one per tenant
    for deltas, w in _synth_rounds(game, 2, seed=13):
        svc.append_round("tenantX", deltas, w)
    with pytest.raises(ValueError, match="unknown live query method"):
        svc.submit_live("tenantX", method="TMCS")
    # deterministic caller mistakes fail at SUBMIT, never as a
    # retried-then-quarantined job fault
    with pytest.raises(ValueError, match=r"\[0, 1\]"):
        svc.submit_live("tenantX", prune=5.0)
    game.engine.partners_count = 17
    try:
        with pytest.raises(ValueError, match="limited to"):
            svc.submit_live("tenantX", method="exact")
    finally:
        game.engine.partners_count = 3

    with obs_trace.collect() as records:
        job = svc.submit_live("tenantX", method="exact")
        # the low-latency class: one tier above the batch default
        assert job.priority == svc._priority_default + 1
        assert job.deadline_sec == 30.0
        assert job.method == "live:exact"
        svc.run_until_idle()
    scores = job.result(timeout=5)
    direct = game.query("exact")
    np.testing.assert_array_equal(np.asarray(scores), direct.scores)
    assert job.live_result is not None
    assert job.live_result.stamp == game.round_stamp
    # the resident game survives job completion (engines are shared,
    # never released) and shows on /varz
    assert game.engine.stacked is not None
    varz = svc.varz_view()
    assert varz["live_games"]["tenantX"]["rounds_resident"] == 2
    import json
    json.dumps(varz["live_games"])  # the /varz row must serialize
    # the job's quantum emitted the usual service spans + the live row
    rep = sweep_report(records)
    assert rep["live"]["queries"] >= 1
    assert "live" in format_report(rep)
    svc.shutdown(drain=False)


def test_prune_tau_out_of_range(scen3, monkeypatch):
    game = LiveGame(scen3)
    game.append_round(*_synth_rounds(game, 1, seed=23)[0])
    with pytest.raises(ValueError, match=r"\[0, 1\]"):
        game.query("exact", prune=1.5)
    # the env knob degrades with a warning (typo'd-knob contract) —
    # pruning off, NOT an all-partners prune returning silent zeros
    monkeypatch.setenv("MPLC_TPU_LIVE_PRUNE_TAU", "2.5")
    with pytest.warns(UserWarning, match="outside"):
        r = game.query("exact")
    assert r.prune_tau == 0.0 and r.pruned_coalitions == 0


def test_concurrent_live_queries_same_tenant_serialize():
    """Two live-query jobs for ONE tenant on a two-worker pool: the
    game-lock serialization must keep both quanta correct — same answer,
    no clobbered progress hook, no double billing crash."""
    from mplc_tpu.service import SweepService

    svc = SweepService(workers=2)
    try:
        sc = _scenario_3p(seed=31)
        game = svc.live_game(sc, tenant="t2w")
        for deltas, w in _synth_rounds(game, 2, seed=15):
            svc.append_round("t2w", deltas, w)
        jobs = [svc.submit_live("t2w", method="exact") for _ in range(3)]
        results = [np.asarray(j.result(timeout=120)) for j in jobs]
        for r in results[1:]:
            np.testing.assert_array_equal(results[0], r)
        assert all(j.status == "completed" for j in jobs)
        assert all(j.values for j in jobs)  # snapshotted under the lock
        assert game.engine.progress is None  # hooks fully unwound
    finally:
        svc.shutdown(drain=False)


def test_query_result_describe_roundtrips(scen3):
    import json
    game = LiveGame(scen3)
    game.append_round(*_synth_rounds(game, 1, seed=14)[0])
    r = game.query("exact")
    doc = r.describe()
    json.dumps(doc)
    assert doc["method"] == "exact" and doc["rounds"] == 1
    json.dumps(game.describe())


# ---------------------------------------------------------------------------
# report row schema
# ---------------------------------------------------------------------------

def test_live_report_row_schema():
    recs = [
        {"name": "live.append", "dur": 0.0, "attrs": {"tenant": "t"}},
        {"name": "live.query", "dur": 0.42,
         "attrs": {"tenant": "t", "method": "GTG-Shapley", "rounds": 7,
                   "stamp": 3, "memo_hit": False, "evaluations": 40,
                   "pruned": 12}},
        {"name": "live.query", "dur": 0.001,
         "attrs": {"tenant": "t", "method": "GTG-Shapley", "rounds": 7,
                   "stamp": 3, "memo_hit": True, "evaluations": 0,
                   "pruned": 0}},
        {"name": "live.recover", "dur": 0.0,
         "attrs": {"tenant": "t", "rounds": 7, "stamp": 3}},
    ]
    rep = sweep_report(recs)
    lv = rep["live"]
    assert lv["queries"] == 2 and lv["memo_hits"] == 1
    assert lv["evaluations"] == 40 and lv["pruned_coalitions"] == 12
    assert lv["rounds_appended"] == 1 and lv["recovered_games"] == 1
    assert lv["rounds_resident"] == 7
    assert lv["query_s"]["count"] == 1  # memo hits excluded from latency
    assert lv["query_s"]["p50"] == pytest.approx(0.42)
    txt = format_report(rep)
    assert "live" in txt and "memo_hits=1" in txt
    # record streams without live events keep the old schema exactly
    assert "live" not in sweep_report(
        [{"name": "engine.evaluate", "dur": 0.1,
          "attrs": {"requested": 1, "missing": 1}}])
