"""Fleet observability plane (obs/fleet_view.py + the fleet.py wiring).

The plane's three contracts, each tested against the mechanism rather
than the happy path:

  - MERGED METRICS ARE EXACT: because every histogram shares
    metrics.LOG_BUCKET_BOUNDS, `merge_snapshots` over W per-shard
    snapshots must report the SAME quantiles as one histogram fed the
    pooled raw samples — not an average of per-shard quantiles.
  - ONE TIMELINE PER RUN: a fleet run leaves per-shard trace streams
    plus a coordinator stream and a clock manifest; merge_fleet_traces
    rebases every shard onto the coordinator clock (midpoint rule) and
    links each dispatch to its shard's root span with a flow arrow.
  - ONE INCIDENT PER FAILED RUN: a shard killed mid-sweep yields
    exactly one timestamped bundle with the flight dump, trace tail,
    ledger digest and cluster snapshot — never W scattered artifacts,
    never an exception that masks the original failure.
"""

import json
import os
import random

import pytest

from mplc_tpu.obs import fleet_view
from mplc_tpu.obs import metrics as obs_metrics
from mplc_tpu.obs import trace as obs_trace
from mplc_tpu.parallel import fleet


# ---------------------------------------------------------------------------
# merge_snapshots: exactness
# ---------------------------------------------------------------------------

def _hist_snapshot_entry(h):
    """snapshot()-shaped dict for one bare Histogram object."""
    return {"count": h.count, "sum": h.total,
            "min": h.min if h.count else None,
            "max": h.max if h.count else None,
            "p50": h.quantile(0.50), "p95": h.quantile(0.95),
            "p99": h.quantile(0.99),
            "bucket_counts": list(h.bucket_counts)}


def test_merged_quantiles_equal_pooled_sample_quantiles():
    """The exactness claim, tested sample-for-sample: W per-shard
    histograms merged via merge_snapshots must report IDENTICAL
    p50/p95/p99 (and count/sum/min/max/bucket_counts) to one histogram
    that observed the pooled raw samples — for every quantile, because
    the shared log2 buckets make the merge lossless."""
    rng = random.Random(7)
    key = "service.queue_wait_sec{tenant=t0}"
    pooled = obs_metrics.Histogram("service.queue_wait_sec",
                                   {"tenant": "t0"})
    snaps = []
    for _shard in range(4):
        h = obs_metrics.Histogram("service.queue_wait_sec",
                                  {"tenant": "t0"})
        for _ in range(rng.randrange(5, 120)):
            v = rng.lognormvariate(-2.0, 3.0)  # spans many log2 buckets
            h.observe(v)
            pooled.observe(v)
        snaps.append({"histograms": {key: _hist_snapshot_entry(h)}})
    merged = obs_metrics.merge_snapshots(snaps)["histograms"][key]
    want = _hist_snapshot_entry(pooled)
    assert merged["count"] == want["count"]
    assert merged["sum"] == pytest.approx(want["sum"])
    assert merged["min"] == want["min"] and merged["max"] == want["max"]
    assert merged["bucket_counts"] == want["bucket_counts"]
    for q in ("p50", "p95", "p99"):
        assert merged[q] == want[q], (q, merged[q], want[q])
    # and not just the three shortcuts: every quantile agrees, because
    # the estimator runs over identical bucket arrays
    for q in (0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99, 1.0):
        got = obs_metrics.bucket_quantile(
            merged["bucket_counts"], merged["count"], merged["min"],
            merged["max"], q)
        assert got == pooled.quantile(q), q


def test_merge_snapshots_counter_gauge_semantics():
    a = {"counters": {"engine.batches": 3, "fleet.incidents": 1},
         "gauges": {"engine.device_mem_high_water_bytes": 100}}
    b = {"counters": {"engine.batches": 4},
         "gauges": {"engine.device_mem_high_water_bytes": 900,
                    "unset": None}}
    out = obs_metrics.merge_snapshots([a, b, None, "junk"])
    assert out["counters"]["engine.batches"] == 7
    assert out["counters"]["fleet.incidents"] == 1
    # gauges are high-water marks: the fleet value is the worst shard's
    assert out["gauges"]["engine.device_mem_high_water_bytes"] == 900
    assert out["gauges"]["unset"] is None
    # an empty-count histogram entry still yields an empty merged entry
    out2 = obs_metrics.merge_snapshots(
        [{"histograms": {"h": {"count": 0}}}])
    assert out2["histograms"]["h"]["count"] == 0
    assert out2["histograms"]["h"]["p99"] is None


# ---------------------------------------------------------------------------
# trace context propagation + clock rebase
# ---------------------------------------------------------------------------

def test_trace_records_stamped_with_fleet_context():
    """While the coordinator's env injection is in effect, EVERY emitted
    record carries fleet_run/fleet_shard — the correlation fields the
    merge keys on; outside the overlay nothing is stamped."""
    with fleet._env_overlay({obs_trace.FLEET_RUN_ID_ENV: "fleet-abc123",
                             obs_trace.FLEET_TRACE_SHARD_ENV: "shard3"}):
        with obs_trace.collect() as recs:
            obs_trace.event("fleet.scrape", shard="s", source="t", ok=True)
    assert recs[0]["fleet_run"] == "fleet-abc123"
    assert recs[0]["fleet_shard"] == "shard3"
    with fleet._env_overlay({obs_trace.FLEET_RUN_ID_ENV: None,
                             obs_trace.FLEET_TRACE_SHARD_ENV: None}):
        with obs_trace.collect() as recs2:
            obs_trace.event("fleet.scrape", shard="s", source="t", ok=True)
    assert "fleet_run" not in recs2[0]
    assert "fleet_shard" not in recs2[0]


def test_clock_offset_midpoint_rule():
    """offset = ((spawn - start) + (done - end)) / 2: symmetric
    spawn/teardown latency cancels, a pure clock skew survives intact;
    missing done-seen degrades one-sided, no handshake at all -> 0."""
    manifest = {"spawn_ts": {"0": 100.0}, "done_seen_ts": {"0": 110.0}}
    # worker clock runs 5 s BEHIND: start/end read 5 less than truth,
    # with 1 s spawn latency and 1 s teardown latency on each side
    result = {"clock": {"worker_start_ts": 96.0, "worker_end_ts": 104.0}}
    off = fleet_view._clock_offset(manifest, result, 0)
    assert off == pytest.approx(5.0)
    # one-sided fallback (crashed shard: no done-seen record)
    off1 = fleet_view._clock_offset({"spawn_ts": {"0": 100.0}},
                                    result, 0)
    assert off1 == pytest.approx(100.0 - 96.0)
    # no handshake at all
    assert fleet_view._clock_offset({}, None, 0) == 0.0


def test_merge_fleet_traces_inproc_run(tmp_path):
    """A real (tiny, inproc) 2-shard fleet run merges into ONE Perfetto
    document: one track group per shard, one flow link per dispatch,
    coordinator records deduped from the shard streams, and every
    shard's offset present in the manifest-driven rebase."""
    out = str(tmp_path / "run")
    res = fleet.run_fleet(fleet.FleetSpec(), 2, out, inproc=True)
    assert len(res.values) == 7
    merged = fleet_view.merge_fleet_traces(out)
    assert merged["shard_tracks"] == 2
    assert merged["flow_links"] == 2
    assert merged["torn_lines"] == 0
    assert set(merged["offsets"]) == {"0", "1"}
    ev = merged["trace"]["traceEvents"]
    # the coordinator's stream must not re-contain shard records (the
    # inproc collector saw them; dedupe is by the fleet_shard stamp)
    coord_named = [e for e in ev if e.get("pid") == 1 and e["ph"] == "X"]
    assert all(not (e["args"] or {}).get("fleet_shard")
               for e in coord_named)
    # one process_name metadata row per track group
    names = {e["args"]["name"] for e in ev
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert names == {"fleet coordinator", "shard 0", "shard 1"}
    # flow arrows pair s/f records under one id, landing on shard pids
    flows = [e for e in ev if e.get("cat") == "flow"]
    assert sorted(e["ph"] for e in flows) == ["f", "f", "s", "s"]
    assert {e["pid"] for e in flows if e["ph"] == "f"} == {10, 11}
    # the run id is stamped through to the merged doc
    run_id = merged["trace"]["otherData"]["run_id"]
    assert run_id and run_id.startswith("fleet-")
    shard_recs = [e for e in ev if e["ph"] == "X"
                  and (e["args"] or {}).get("fleet_shard")]
    assert shard_recs
    assert all(e["args"]["fleet_run"] == run_id for e in shard_recs)

    # the aggregated snapshot over the same out_dir sees both shards
    snap = fleet_view.cluster_snapshot(out_dir=out)
    assert set(snap["shards"]) == {"shard0", "shard1"}
    assert snap["fresh_shards"] == 2 and snap["merged_sources"] == 2


# ---------------------------------------------------------------------------
# collector sources + /fleet rendering
# ---------------------------------------------------------------------------

def test_collector_state_dir_source_merges_published_metrics(tmp_path):
    d = str(tmp_path / "state")
    snapA = {"counters": {"service.device_seconds{tenant=t0}": 2.0}}
    snapB = {"counters": {"service.device_seconds{tenant=t0}": 3.0,
                          "service.device_seconds{tenant=t1}": 1.0}}
    fleet.publish_shard_state(d, "alpha", {"queue_depth": 1,
                                           "metrics": snapA})
    fleet.publish_shard_state(d, "beta", {"queue_depth": 2,
                                          "metrics": snapB})
    out = fleet_view.FleetCollector(state_dir=d).collect()
    assert out["shard_count"] == 2 and out["fresh_shards"] == 2
    assert out["merged_sources"] == 2
    assert out["device_seconds_total"] == pytest.approx(6.0)
    assert out["tenant_device_seconds"] == {"t0": pytest.approx(5.0),
                                            "t1": pytest.approx(1.0)}
    # the state-dir cluster totals ride along (minus the raw shard rows)
    assert out["cluster"]["cluster_queue_depth"] == 3
    # and the per-shard rows never retain the raw metrics payload (the
    # merged view is the product; rows stay scannable)
    assert all("metrics" not in r for r in out["shards"].values())


def test_cluster_view_clamps_future_ts_to_age_zero(tmp_path):
    """A publisher whose clock runs AHEAD (cross-host skew) must read as
    freshly published — age 0.0, live — not as negative-age/stale."""
    d = str(tmp_path / "state")
    fleet.publish_shard_state(d, "alpha", {"queue_depth": 2})
    p = os.path.join(d, "shard_alpha.json")
    doc = json.loads(open(p).read())
    doc["ts"] += 3600  # one hour in the future
    with open(p, "w") as f:
        json.dump(doc, f)
    view = fleet.cluster_view(d)
    assert view["shards"]["alpha"]["age_sec"] == 0.0
    assert view["shards"]["alpha"]["stale"] is False
    assert view["live_shards"] == 1 and view["cluster_queue_depth"] == 2


def test_cluster_view_default_strips_embedded_metrics(tmp_path):
    """The /healthz fleet block is UNAUTHENTICATED: a shard that
    published its metrics snapshot (tenant-labeled series) must not have
    it ride the default view; the collector opts in explicitly."""
    d = str(tmp_path / "state")
    fleet.publish_shard_state(
        d, "alpha", {"queue_depth": 0, "metrics": {
            "counters": {"service.device_seconds{tenant=secret}": 1.0}}})
    assert "metrics" not in fleet.cluster_view(d)["shards"]["alpha"]
    withm = fleet.cluster_view(d, include_metrics=True)
    assert "counters" in withm["shards"]["alpha"]["metrics"]


def test_publish_shard_state_failure_is_counted_never_raised(tmp_path):
    """satellite: a failing publish (state dir path occupied by a FILE)
    must not raise, must increment fleet.state_publish_errors, and must
    warn exactly once per process."""
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("x")
    before = obs_metrics.counter("fleet.state_publish_errors").value
    saved = fleet._publish_warned
    fleet._publish_warned = False
    try:
        fleet.publish_shard_state(str(blocker), "alpha", {})
        fleet.publish_shard_state(str(blocker), "alpha", {})
    finally:
        fleet._publish_warned = saved
    after = obs_metrics.counter("fleet.state_publish_errors").value
    assert after == before + 2


def test_fleet_metrics_text_uses_fleet_prefix():
    h = obs_metrics.Histogram("service.queue_wait_sec", {"tenant": "t0"})
    h.observe(0.5)
    merged = obs_metrics.merge_snapshots([{
        "counters": {"engine.batches": 5},
        "gauges": {"g.x": 2},
        "histograms": {"service.queue_wait_sec{tenant=t0}":
                       _hist_snapshot_entry(h)},
    }])
    text = fleet_view.fleet_metrics_text(merged)
    assert "mplc_fleet_engine_batches 5" in text
    assert 'mplc_fleet_service_queue_wait_sec_bucket{le="+Inf",' \
           'tenant="t0"} 1' in text
    # federation double-count protection: no bare mplc_engine_... series
    assert "\nmplc_engine_batches" not in text


def test_redact_varz_hashes_fleet_topology_keeps_load_scalars():
    from mplc_tpu.obs import export
    doc = {"fleet": {"shards": {"alpha": {"shard": "alpha",
                                          "queue_depth": 3,
                                          "stale": False}},
                     "least_loaded": "alpha", "shard_id": "alpha"},
           "shards": {"peer:h1:9090": {"peer": "h1:9090", "ok": True,
                                       "queue_depth": 1}}}
    out = export.redact_varz(doc, viewer="tenantA", key="master")
    fv = out["fleet"]
    assert "alpha" not in fv["shards"]
    (tag,) = fv["shards"]
    assert tag.startswith("shard-")
    row = fv["shards"][tag]
    assert row["shard"] == tag  # same identity -> same opaque tag
    assert row["queue_depth"] == 3 and row["stale"] is False
    assert fv["least_loaded"] == tag and fv["shard_id"] == tag
    (ptag,) = out["shards"]
    prow = out["shards"][ptag]
    assert prow["peer"].startswith("shard-") and "h1" not in prow["peer"]
    assert prow["queue_depth"] == 1


# ---------------------------------------------------------------------------
# the incident bundle
# ---------------------------------------------------------------------------

def test_killed_shard_yields_exactly_one_incident_bundle(tmp_path):
    """A shard killed mid-sweep (crash@batch1 — InjectedCrash is a
    BaseException, simulating a process kill) fails the fleet run AND
    leaves exactly ONE incident dir bundling the dead shard's flight
    dump, trace tail, ledger digest and the cluster snapshot."""
    from pathlib import Path
    repo = Path(__file__).resolve().parents[1]
    env = {"PYTHONPATH": str(repo),
           "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
           "HOME": os.environ.get("HOME", "/root"),
           "JAX_PLATFORMS": "cpu",
           "MPLC_TPU_SYNTH_SCALE":
               os.environ.get("MPLC_TPU_SYNTH_SCALE", "0.02"),
           "JAX_COMPILATION_CACHE_DIR": str(repo / ".jax_cache")}
    out = tmp_path / "killed"
    with pytest.raises(fleet.FleetError):
        fleet.run_fleet(
            fleet.FleetSpec(), 2, str(out), env=env, devices_per_shard=1,
            timeout=600,
            per_shard_env={1: {"MPLC_TPU_FAULT_PLAN": "crash@batch1"}})
    incidents = sorted(p for p in os.listdir(out)
                       if p.startswith("incident_"))
    assert len(incidents) == 1, incidents
    inc = out / incidents[0]
    bundle = json.loads((inc / "incident.json").read_text())
    assert bundle["reason"] == "shard_failure"
    assert bundle["failed_shards"] == [1]
    art = bundle["shard_artifacts"]["1"]
    # the dying worker's last act was a flight dump into the per-shard
    # flight dir the coordinator injected — copied into the bundle
    assert art["flight_dumps"], art
    assert all((inc / name).exists() for name in art["flight_dumps"])
    dump = json.loads((inc / art["flight_dumps"][0]).read_text())
    assert dump["reason"] == "fleet_worker_crash"
    # trace tail of the killed shard's stream, beside it
    assert (inc / art["trace_tail"]).exists()
    assert art["trace_tail_records"] > 0
    assert art["log_tail"]
    # the crash fired before the ledger was written — the digest honestly
    # reports its absence rather than inventing one
    assert art["ledger_digest"] is None
    # cluster snapshot: shard 0 finished (fresh), shard 1 did not
    cl = bundle["cluster"]
    assert cl["shards"]["shard0"]["fresh"] is True
    # the failure is counted and the incident event is registered
    assert obs_metrics.counter("fleet.incidents").value >= 1
    # the healthy shard's trace stream + the coordinator's landed too,
    # so a manual fleet_trace_merge over the failed run still works
    merged = fleet_view.merge_fleet_traces(str(out))
    assert merged["shard_tracks"] >= 1
