"""Streaming round ingestion: `POST /live/<tenant>/round` on the
telemetry server (obs/export.py) feeding `LiveGame.append_round` through
the service's registered sink (service/scheduler.py) — round arrival
with no in-process call.

The contract under test:

1. **Opt-in existence.** The mutating route only EXISTS when
   `MPLC_TPU_LIVE_INGEST=1` — without the knob every POST is a 404
   (probes learn nothing), with it an ingested round advances the
   resident game's stamp exactly like an in-process append.
2. **Authenticated tenancy.** In token mode the per-tenant HMAC
   credential must match the PATH tenant: tenant B's token cannot
   append into tenant A's game (401), the operator master can, and a
   missing/garbage token is denied.
3. **Error contract.** Unknown tenant 404, malformed document 400, and
   a full game 429 carrying the `retry_after_sec` hint in both the
   standard Retry-After header and the JSON body.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from helpers import build_scenario, cluster_mlp_dataset
from mplc_tpu.live.game import _encode_tree
from mplc_tpu.obs import export as obs_export


def _scenario_3p(seed=3):
    return build_scenario(
        partners_count=3, amounts_per_partner=[0.2, 0.3, 0.5],
        dataset=cluster_mlp_dataset(n=240, seed=9, scale=1.0),
        epoch_count=2, minibatch_count=2, seed=seed)


def _wire_round(game, seed=0, scale=0.08):
    """One live_round wire document: the exact [[shape, dtype, values]]
    triples the WAL journals."""
    rng = np.random.default_rng(seed)
    P = game.engine.partners_count
    deltas = jax.tree_util.tree_map(
        lambda l: rng.normal(0, scale, (P,) + l.shape).astype(l.dtype),
        game._init_params)
    w = rng.dirichlet(np.ones(P)).astype(np.float32)
    return {"deltas": _encode_tree(deltas), "weights": w.tolist()}


def _post(url, doc, token=None):
    """(status, parsed-JSON-body-or-None, headers)."""
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read() or b"null"), \
                dict(resp.headers)
    except urllib.error.HTTPError as e:
        body = e.read()
        try:
            parsed = json.loads(body) if body else None
        except ValueError:
            parsed = None
        return e.code, parsed, dict(e.headers)


@pytest.fixture()
def live_service(monkeypatch):
    """A stopped-scheduler service with one resident game ("acme") and a
    loopback telemetry server, ingestion knob ON."""
    from mplc_tpu.service import SweepService

    monkeypatch.delenv("MPLC_TPU_METRICS_TOKEN", raising=False)
    monkeypatch.setenv("MPLC_TPU_LIVE_INGEST", "1")
    svc = SweepService(start=False)
    game = svc.live_game(_scenario_3p(seed=61), tenant="acme")
    srv = obs_export.TelemetryServer(0)
    try:
        yield svc, game, f"http://127.0.0.1:{srv.port}"
    finally:
        srv.close()
        svc.shutdown(drain=False)


def test_ingested_round_equals_in_process_append(live_service):
    svc, game, base = live_service
    doc = _wire_round(game, seed=62)
    status, ack, _ = _post(f"{base}/live/acme/round", doc)
    assert status == 200
    assert ack == {"tenant": "acme", "stamp": game.round_stamp,
                   "rounds_resident": 1}
    assert game.rounds_resident == 1
    # the decoded round is bit-identical to an in-process append of the
    # same arrays: a twin game fed directly answers identically
    twin = svc.live_game(_scenario_3p(seed=61), tenant="twin")
    deltas, w = game.round_history()[0]
    twin.append_round(deltas, w)
    np.testing.assert_array_equal(twin.query("exact").scores,
                                  game.query("exact").scores)


def test_route_does_not_exist_without_opt_in(live_service, monkeypatch):
    _, game, base = live_service
    monkeypatch.delenv("MPLC_TPU_LIVE_INGEST")
    status, _, _ = _post(f"{base}/live/acme/round", _wire_round(game))
    assert status == 404
    assert game.rounds_resident == 0


def test_unknown_tenant_404_and_malformed_400(live_service):
    _, game, base = live_service
    status, body, _ = _post(f"{base}/live/nobody/round", _wire_round(game))
    assert status == 404 and "nobody" in body["error"]
    # malformed: not JSON at all
    req = urllib.request.Request(
        f"{base}/live/acme/round", data=b"not json", method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400
    # malformed: JSON but not the wire shape
    status, body, _ = _post(f"{base}/live/acme/round",
                            {"deltas": [[1, 2]], "weights": "x"})
    assert status == 400 and "malformed" in body["error"]
    assert game.rounds_resident == 0


def test_tenant_tokens_are_path_bound(live_service, monkeypatch):
    svc, game, base = live_service
    monkeypatch.setenv("MPLC_TPU_METRICS_TOKEN", "master-secret")
    acme_tok = obs_export.tenant_token("master-secret", "acme")
    beta_tok = obs_export.tenant_token("master-secret", "beta")
    doc = _wire_round(game, seed=63)

    # no credential / garbage credential: denied
    assert _post(f"{base}/live/acme/round", doc)[0] == 401
    assert _post(f"{base}/live/acme/round", doc, token="nope")[0] == 401
    # tenant B's valid credential cannot write into A's game, even
    # claiming its own identity in the query string
    status, _, _ = _post(f"{base}/live/acme/round?tenant=beta", doc,
                         token=beta_tok)
    assert status == 401
    assert game.rounds_resident == 0
    # the right tenant's credential and the operator master both land
    status, ack, _ = _post(f"{base}/live/acme/round?tenant=acme", doc,
                           token=acme_tok)
    assert status == 200 and ack["rounds_resident"] == 1
    status, ack, _ = _post(f"{base}/live/acme/round", doc,
                           token="master-secret")
    assert status == 200 and ack["rounds_resident"] == 2


def test_full_game_429_with_retry_after(live_service):
    svc, _, base = live_service
    capped = svc.live_game(_scenario_3p(seed=61), tenant="capped",
                           max_rounds=1)
    doc = _wire_round(capped, seed=64)
    assert _post(f"{base}/live/capped/round", doc)[0] == 200
    status, body, headers = _post(f"{base}/live/capped/round", doc)
    assert status == 429
    assert "MPLC_TPU_LIVE_MAX_ROUNDS" in body["error"]
    assert body["retry_after_sec"] == 0.0
    assert headers["Retry-After"] == "1"  # floored at the header's 1 s
    assert capped.rounds_resident == 1
