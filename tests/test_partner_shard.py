"""Partner-axis sharding under the numeric-truth plane's deterministic-
reduction mode: sharded fedavg/lflip is BIT-IDENTICAL to the unsharded
reference.

History: from PR 3 to PR 13 these were `xfail(strict=False)` — the 2-D
shard_map path drifted from the unsharded run beyond any principled
tolerance (adam chaotically amplifies reduction-order ulps). The numerics
audit (obs/numerics.py) root-caused the drift to THREE interacting
sources — the aggregation psum's grouping order, in-program threefry
stream generation beside a collective, and per-topology compilation of
loop bodies — and `MPLC_TPU_DETERMINISTIC_REDUCE=1` eliminates all three
(ordered fold over all-gathered terms, hoisted data streams, unrolled
round loops). The unsharded reference is the SAME program family on a
1-device `part` mesh: the whole partner axis resident on one device,
the gather collective over the singleton axis moving nothing. Equality
is exact (`assert_array_equal`), not a tolerance.

The plain-jit (non-shard_map) embedding of the same trainer still rounds
a few lanes differently per batch width on this toolchain — that residual
is the audit's documented finding (DESIGN_NOTES.md "2-D shard_map numeric
drift — closed"), not a silent xfail.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mplc_tpu.data.partition import StackedPartners, stack_eval_set
from mplc_tpu.data.partner import Partner
from mplc_tpu.models import TITANIC_LOGREG
from mplc_tpu.mpl.engine import EvalSet, MplTrainer, TrainConfig
from mplc_tpu.parallel.mesh import make_mesh
from mplc_tpu.parallel.partner_shard import PartnerShardedTrainer


@pytest.fixture(scope="module")
def eight_partner_problem():
    rng = np.random.default_rng(3)
    w = rng.normal(size=27)

    def make(n):
        x = rng.normal(size=(n, 27)).astype(np.float32)
        y = (x @ w > 0).astype(np.float32)
        return x, y

    partners = []
    for i, n in enumerate([60, 80, 100, 120, 60, 80, 100, 120]):
        p = Partner(i)
        p.x_train, p.y_train = make(n)
        partners.append(p)
    stacked = StackedPartners.build(partners, 1)
    val = EvalSet(*stack_eval_set(*make(100), 1, 128))
    test = EvalSet(*stack_eval_set(*make(100), 1, 128))
    return stacked, val, test


def _cfg(partner_axis=None, deterministic=None):
    return TrainConfig(approach="fedavg", aggregator="data-volume",
                       epoch_count=2, minibatch_count=2,
                       gradient_updates_per_pass=2, is_early_stopping=False,
                       record_partner_val=False, partner_axis=partner_axis,
                       deterministic_reduce=deterministic)


def _run_sharded(model, cfg, n_devices, stacked, val, test, coal_mask, rng,
                 partners=8, epochs=2):
    mesh = make_mesh(jax.devices()[:n_devices], "part")
    sharded = PartnerShardedTrainer(MplTrainer(model, cfg), mesh)
    state = sharded.init_state(rng, partners)
    state = sharded.epoch_chunk(state, stacked, val, coal_mask, rng, epochs)
    _, acc = sharded.finalize(state, test)
    return state, float(acc)


def test_partner_sharded_matches_unsharded(eight_partner_problem):
    """Deterministic-reduce retires the historical drift xfail: the
    4-way partner-sharded run reproduces the 1-device reference BIT FOR
    BIT — params, score, and the val histories computed on every shard."""
    stacked, val, test = eight_partner_problem
    coal_mask = jnp.array([1, 1, 0, 1, 1, 1, 0, 1], jnp.float32)
    rng = jax.random.PRNGKey(0)

    # unsharded reference: the same program family on ONE device (whole
    # partner axis resident, singleton gather axis)
    ref_state, acc_ref = _run_sharded(
        TITANIC_LOGREG, _cfg("part", deterministic=True), 1,
        stacked, val, test, coal_mask, rng)

    # partners sharded 4-ways
    sh_state, acc_sh = _run_sharded(
        TITANIC_LOGREG, _cfg("part", deterministic=True), 4,
        stacked, val, test, coal_mask, rng)

    for a, b in zip(jax.tree_util.tree_leaves(ref_state.params),
                    jax.tree_util.tree_leaves(sh_state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert acc_ref == acc_sh
    # val histories computed on every shard must agree with the
    # reference EXACTLY (identical params, identical replicated eval)
    np.testing.assert_array_equal(np.asarray(ref_state.val_loss_h),
                                  np.asarray(sh_state.val_loss_h))
    # 2-way sharding takes a different grouping of the same fold — still
    # bit-identical under the pinned order
    sh2_state, acc_sh2 = _run_sharded(
        TITANIC_LOGREG, _cfg("part", deterministic=True), 2,
        stacked, val, test, coal_mask, rng)
    for a, b in zip(jax.tree_util.tree_leaves(ref_state.params),
                    jax.tree_util.tree_leaves(sh2_state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert acc_ref == acc_sh2


def test_partner_sharded_default_mode_still_drifts_documented(
        eight_partner_problem):
    """The DEFAULT (order-sensitive) reduction still drifts across
    topologies — the audit's finding, kept measured here so a toolchain
    change that silently restores agreement is noticed (the old
    xfail(strict=False)'s purpose, inverted into a real assertion pair):
    the sharded default run must stay within loose float distance of the
    reference (same game), and the deterministic mode must be exactly
    equal where the default is not guaranteed to be."""
    stacked, val, test = eight_partner_problem
    coal_mask = jnp.array([1, 1, 0, 1, 1, 1, 0, 1], jnp.float32)
    rng = jax.random.PRNGKey(0)
    ref_state, acc_ref = _run_sharded(
        TITANIC_LOGREG, _cfg("part", deterministic=False), 1,
        stacked, val, test, coal_mask, rng)
    sh_state, acc_sh = _run_sharded(
        TITANIC_LOGREG, _cfg("part", deterministic=False), 4,
        stacked, val, test, coal_mask, rng)
    # same game at coarse tolerance: the drift is chaotic-small, not wrong
    for a, b in zip(jax.tree_util.tree_leaves(ref_state.params),
                    jax.tree_util.tree_leaves(sh_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0.35)
    assert abs(acc_ref - acc_sh) < 0.2


def test_partner_sharded_lflip_matches_unsharded():
    """lflip is the other partner-parallel approach: its per-partner theta
    ([P, K, K]) and theta history ([E, P, K, K]) shard over `part`
    (partner_shard.train_state_specs lflip=True) and the EM draws are keyed
    by global partner index — under deterministic-reduce the sharded run
    must reproduce the 1-device reference's params, score, AND theta
    trajectory bit for bit."""
    from helpers import cluster_mlp_model, make_cluster_data

    mlp = cluster_mlp_model(4)
    rng_np = np.random.default_rng(7)
    centers = rng_np.normal(size=(4, 16)).astype(np.float32) * 2.0

    def make(n):
        return make_cluster_data(rng_np, n, centers)

    partners = []
    for i, n in enumerate([40, 60, 40, 60, 40, 60, 40, 60]):
        p = Partner(i)
        p.x_train, p.y_train = make(n)
        partners.append(p)
    stacked = StackedPartners.build(partners, 4)
    val = EvalSet(*stack_eval_set(*make(80), 4, 128))
    test = EvalSet(*stack_eval_set(*make(80), 4, 128))

    def cfg():
        return TrainConfig(approach="lflip", aggregator="data-volume",
                           epoch_count=2, minibatch_count=2,
                           gradient_updates_per_pass=2,
                           is_early_stopping=False, record_partner_val=False,
                           partner_axis="part", deterministic_reduce=True)

    coal_mask = jnp.array([1, 1, 0, 1, 1, 1, 0, 1], jnp.float32)
    rng = jax.random.PRNGKey(0)

    ref_state, acc_ref = _run_sharded(mlp, cfg(), 1, stacked, val, test,
                                      coal_mask, rng)
    sh_state, acc_sh = _run_sharded(mlp, cfg(), 4, stacked, val, test,
                                    coal_mask, rng)

    for a, b in zip(jax.tree_util.tree_leaves(ref_state.params),
                    jax.tree_util.tree_leaves(sh_state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert acc_ref == acc_sh
    np.testing.assert_array_equal(np.asarray(ref_state.theta),
                                  np.asarray(sh_state.theta))
    np.testing.assert_array_equal(np.asarray(ref_state.theta_h),
                                  np.asarray(sh_state.theta_h))


def test_partner_sharding_rejects_sequential():
    with pytest.raises(ValueError):
        TrainConfig(approach="seq-pure", partner_axis="part")


def test_partner_sharding_requires_divisible_partner_count(eight_partner_problem):
    mesh = make_mesh(jax.devices()[:4], "part")
    tr = MplTrainer(TITANIC_LOGREG, _cfg("part"))
    sharded = PartnerShardedTrainer(tr, mesh)
    with pytest.raises(ValueError):
        sharded.init_state(jax.random.PRNGKey(0), 6)
