"""Partner-axis sharding: sharded fedavg/lflip must equal the unsharded run.

The per-partner RNG streams are keyed by global partner index, so the only
difference between a sharded and an unsharded run is the reduction order of
the aggregation psum — results must match to float tolerance.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mplc_tpu.data.partition import StackedPartners, stack_eval_set
from mplc_tpu.data.partner import Partner
from mplc_tpu.models import TITANIC_LOGREG
from mplc_tpu.mpl.engine import EvalSet, MplTrainer, TrainConfig
from mplc_tpu.parallel.mesh import make_mesh
from mplc_tpu.parallel.partner_shard import PartnerShardedTrainer


@pytest.fixture(scope="module")
def eight_partner_problem():
    rng = np.random.default_rng(3)
    w = rng.normal(size=27)

    def make(n):
        x = rng.normal(size=(n, 27)).astype(np.float32)
        y = (x @ w > 0).astype(np.float32)
        return x, y

    partners = []
    for i, n in enumerate([60, 80, 100, 120, 60, 80, 100, 120]):
        p = Partner(i)
        p.x_train, p.y_train = make(n)
        partners.append(p)
    stacked = StackedPartners.build(partners, 1)
    val = EvalSet(*stack_eval_set(*make(100), 1, 128))
    test = EvalSet(*stack_eval_set(*make(100), 1, 128))
    return stacked, val, test


def _cfg(partner_axis=None):
    return TrainConfig(approach="fedavg", aggregator="data-volume",
                       epoch_count=2, minibatch_count=2,
                       gradient_updates_per_pass=2, is_early_stopping=False,
                       record_partner_val=False, partner_axis=partner_axis)


# Known numeric drift on the current jax_graft build: the 2-D shard_map
# partner-sharded paths diverge from the unsharded reference beyond any
# principled tolerance (~5% relative on titanic params after 2 epochs —
# adam's sqrt-normalization chaotically amplifies the psum reduction-order
# difference, so a pinned tolerance would be seed-shaped, not justified).
# Tracked in DESIGN_NOTES.md "2-D shard_map numeric drift"; strict=False so
# a toolchain that restores agreement turns these back green silently.
_SHARD_MAP_DRIFT = pytest.mark.xfail(
    strict=False,
    reason="2-D shard_map numeric drift on current jax_graft toolchain "
           "(DESIGN_NOTES.md); psum reduction-order divergence amplified "
           "by adam")


@_SHARD_MAP_DRIFT
def test_partner_sharded_matches_unsharded(eight_partner_problem):
    stacked, val, test = eight_partner_problem
    coal_mask = jnp.array([1, 1, 0, 1, 1, 1, 0, 1], jnp.float32)
    rng = jax.random.PRNGKey(0)

    # unsharded reference run
    tr = MplTrainer(TITANIC_LOGREG, _cfg())
    state = tr.init_state(rng, 8)
    state = tr.jit_epoch_chunk(state, stacked, val, coal_mask, rng, n_epochs=2)
    _, acc_ref = tr.jit_finalize(state, test)
    params_ref = jax.tree_util.tree_leaves(state.params)

    # partners sharded 4-ways
    mesh = make_mesh(jax.devices()[:4], "part")
    str_ = MplTrainer(TITANIC_LOGREG, _cfg("part"))
    sharded = PartnerShardedTrainer(str_, mesh)
    sstate = sharded.init_state(rng, 8)
    sstate = sharded.epoch_chunk(sstate, stacked, val, coal_mask, rng, 2)
    _, acc_sh = sharded.finalize(sstate, test)

    for a, b in zip(params_ref, jax.tree_util.tree_leaves(sstate.params)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    assert np.isclose(float(acc_ref), float(acc_sh), atol=1e-5)
    # val histories computed on every shard must agree with the reference
    assert np.allclose(np.asarray(state.val_loss_h),
                       np.asarray(sstate.val_loss_h), atol=1e-4)


@_SHARD_MAP_DRIFT
def test_partner_sharded_lflip_matches_unsharded():
    """lflip is the other partner-parallel approach: its per-partner theta
    ([P, K, K]) and theta history ([E, P, K, K]) shard over `part`
    (partner_shard.train_state_specs lflip=True) and the EM draws are keyed
    by global partner index — the sharded run must reproduce the unsharded
    params, score, AND theta trajectory."""
    from helpers import cluster_mlp_model, make_cluster_data

    mlp = cluster_mlp_model(4)
    rng_np = np.random.default_rng(7)
    centers = rng_np.normal(size=(4, 16)).astype(np.float32) * 2.0

    def make(n):
        return make_cluster_data(rng_np, n, centers)

    partners = []
    for i, n in enumerate([40, 60, 40, 60, 40, 60, 40, 60]):
        p = Partner(i)
        p.x_train, p.y_train = make(n)
        partners.append(p)
    stacked = StackedPartners.build(partners, 4)
    val = EvalSet(*stack_eval_set(*make(80), 4, 128))
    test = EvalSet(*stack_eval_set(*make(80), 4, 128))

    def cfg(partner_axis=None):
        return TrainConfig(approach="lflip", aggregator="data-volume",
                           epoch_count=2, minibatch_count=2,
                           gradient_updates_per_pass=2,
                           is_early_stopping=False, record_partner_val=False,
                           partner_axis=partner_axis)

    coal_mask = jnp.array([1, 1, 0, 1, 1, 1, 0, 1], jnp.float32)
    rng = jax.random.PRNGKey(0)

    tr = MplTrainer(mlp, cfg())
    state = tr.init_state(rng, 8)
    state = tr.jit_epoch_chunk(state, stacked, val, coal_mask, rng, n_epochs=2)
    _, acc_ref = tr.jit_finalize(state, test)

    mesh = make_mesh(jax.devices()[:4], "part")
    sharded = PartnerShardedTrainer(MplTrainer(mlp, cfg("part")), mesh)
    sstate = sharded.init_state(rng, 8)
    sstate = sharded.epoch_chunk(sstate, stacked, val, coal_mask, rng, 2)
    _, acc_sh = sharded.finalize(sstate, test)

    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(sstate.params)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    assert np.isclose(float(acc_ref), float(acc_sh), atol=1e-5)
    assert np.allclose(np.asarray(state.theta), np.asarray(sstate.theta),
                       atol=1e-5)
    assert np.allclose(np.asarray(state.theta_h), np.asarray(sstate.theta_h),
                       atol=1e-5, equal_nan=True)


def test_partner_sharding_rejects_sequential():
    with pytest.raises(ValueError):
        TrainConfig(approach="seq-pure", partner_axis="part")


def test_partner_sharding_requires_divisible_partner_count(eight_partner_problem):
    mesh = make_mesh(jax.devices()[:4], "part")
    tr = MplTrainer(TITANIC_LOGREG, _cfg("part"))
    sharded = PartnerShardedTrainer(tr, mesh)
    with pytest.raises(ValueError):
        sharded.init_state(jax.random.PRNGKey(0), 6)
