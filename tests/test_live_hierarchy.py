"""Hierarchical/grouped Shapley (mplc_tpu/live/hierarchy.py): live
queries past the 16-partner exact wall.

The contract under test:

1. **Deterministic clustering.** Score-balanced contiguous chunks over
   the descending DPVS order, index-tiebroken; `cluster_tau` pulls the
   low-information tail into one shared cluster appended last.
2. **Exactness where the game allows it.** On an additive game the
   grouped decomposition recovers the exact Shapley value through BOTH
   split rungs (exact intra subgame and info-proportional), and
   efficiency (`sum(scores) == v(grand)`) holds by construction on
   arbitrary games.
3. **The planner rung.** `method="auto"` routes live games past the
   exact wall to "hierarchical" with the cluster knobs FROZEN into the
   plan, and the journaled plan replays bit-identically (re-running
   `plan.method` + `plan.method_kw` reproduces the auto answer's bits).
4. **The end-to-end quality floors.** A real 100-partner game answers
   through the planner's hierarchical rung (31 macro coalitions),
   rank-agreeing with an unpruned sampled (SVARM) reference within a
   pinned Kendall-tau floor and separating a planted contribution tier;
   at 12 partners — where the exact answer is computable — the grouped
   decomposition's tau against EXACT Shapley is pinned much higher.
"""

import numpy as np
import pytest

import jax

from helpers import build_scenario, cluster_mlp_dataset
from mplc_tpu.contrib.planner import plan_query
from mplc_tpu.contrib.shapley import kendall_tau
from mplc_tpu.live import LiveGame
from mplc_tpu.live.hierarchy import (INTRA_EXACT_MAX, MAX_CLUSTERS,
                                     cluster_partners, default_clusters,
                                     estimate_evaluations,
                                     hierarchical_shapley, resolve_clusters,
                                     resolve_cluster_tau)


class _SyntheticEv:
    """An evaluator double with the batched `evaluate(subsets)` surface:
    v(S) = sum of per-partner worths + synergy * C(|S|, 2)."""

    def __init__(self, worth, synergy=0.0):
        self.worth = np.asarray(worth, float)
        self.synergy = float(synergy)

    def evaluate(self, subsets):
        return np.array([
            self.worth[list(s)].sum()
            + self.synergy * (len(s) * (len(s) - 1)) / 2.0
            for s in subsets])


# ---------------------------------------------------------------------------
# 1. clustering
# ---------------------------------------------------------------------------

def test_cluster_partners_is_deterministic_and_balanced():
    scores = np.array([5.0, 4.0, 3.0, 2.0, 1.0, 4.0, 3.0, 0.5])
    got = cluster_partners(scores, 3)
    # descending score order (index-tiebroken) chopped into contiguous
    # near-equal chunks: [0,1,5 | 2,6,3 | 4,7], each sorted ascending
    assert got == ((0, 1, 5), (2, 3, 6), (4, 7))
    assert got == cluster_partners(scores, 3)  # pure

    # the tau tail: sub-threshold partners share ONE cluster, last
    with_tail = cluster_partners(scores, 3, tau=0.3)
    assert with_tail[-1] == (4, 7)  # 1.0 and 0.5 are below 0.3 * 5.0
    assert with_tail == ((0, 1, 5), (2, 3, 6), (4, 7))
    # every partner appears exactly once
    flat = sorted(p for c in with_tail for p in c)
    assert flat == list(range(8))


def test_cluster_count_resolution():
    assert default_clusters(5) == 3
    assert default_clusters(17) == 5
    assert default_clusters(100) == 10
    assert default_clusters(10_000) == MAX_CLUSTERS
    # explicit out-of-range fails fast; the env knob degrades (clamped)
    with pytest.raises(ValueError, match="exact"):
        resolve_clusters(100, MAX_CLUSTERS + 1)
    assert resolve_clusters(100, 5) == 5


def test_cluster_env_knobs(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_LIVE_CLUSTERS", "40")
    with pytest.warns(UserWarning, match="clamped"):
        assert resolve_clusters(100) == MAX_CLUSTERS
    monkeypatch.setenv("MPLC_TPU_LIVE_CLUSTERS", "7")
    assert resolve_clusters(100) == 7
    monkeypatch.setenv("MPLC_TPU_LIVE_CLUSTER_TAU", "1.5")
    with pytest.warns(UserWarning, match="outside"):
        assert resolve_cluster_tau() == 0.0
    monkeypatch.setenv("MPLC_TPU_LIVE_CLUSTER_TAU", "0.2")
    assert resolve_cluster_tau() == 0.2
    with pytest.raises(ValueError, match=r"\[0, 1\]"):
        resolve_cluster_tau(2.0)


def test_estimate_evaluations_cost_model():
    # 100 partners, 10 clusters of 10: macro 2^10-1 + 10 * (2^10-1)
    assert estimate_evaluations(100, 10) == 1023 + 10 * 1023
    # clusters past INTRA_EXACT_MAX fall to the proportional split:
    # only the macro powerset is billed
    assert estimate_evaluations(100, 5) == 31
    # singleton clusters need no intra split
    assert estimate_evaluations(4, 4) == 15


# ---------------------------------------------------------------------------
# 2. exactness / efficiency
# ---------------------------------------------------------------------------

def test_additive_game_recovers_exact_shapley_both_split_rungs():
    rng = np.random.default_rng(5)
    # 30 partners, 2 clusters of 15 (> INTRA_EXACT_MAX): the
    # info-proportional rung — on an additive game with info == worth
    # the proportional share IS the exact value
    worth = rng.uniform(0.1, 1.0, 30)
    scores, detail = hierarchical_shapley(
        _SyntheticEv(worth), 30, worth, clusters=2)
    np.testing.assert_allclose(scores, worth, atol=1e-9)
    assert detail["proportional_splits"] == 2
    assert detail["exact_splits"] == 0
    assert detail["coalitions_evaluated"] == 3  # the macro powerset only

    # 20 partners, 5 clusters of 4 (<= INTRA_EXACT_MAX): the exact
    # intra-subgame rung, which needs no info/worth agreement at all
    worth20 = rng.uniform(0.1, 1.0, 20)
    info = rng.uniform(0.1, 1.0, 20)  # deliberately unrelated
    scores20, detail20 = hierarchical_shapley(
        _SyntheticEv(worth20), 20, info, clusters=5)
    np.testing.assert_allclose(scores20, worth20, atol=1e-9)
    assert detail20["exact_splits"] == 5


def test_efficiency_holds_on_non_additive_games():
    rng = np.random.default_rng(6)
    worth = rng.uniform(0.0, 1.0, 40)
    ev = _SyntheticEv(worth, synergy=0.03)  # cross-partner interactions
    grand = float(ev.evaluate([tuple(range(40))])[0])
    for k in (2, 3, 6):
        scores, detail = hierarchical_shapley(ev, 40, worth, clusters=k)
        assert np.isclose(scores.sum(), grand, atol=1e-8), k
        assert len(detail["clusters"]) == k
    # all-zero info: proportional splits degrade to equal shares, and
    # efficiency still holds
    scores, _ = hierarchical_shapley(ev, 40, np.zeros(40), clusters=2)
    assert np.isclose(scores.sum(), grand, atol=1e-8)


# ---------------------------------------------------------------------------
# 3. the planner rung + journaled-plan replay
# ---------------------------------------------------------------------------

def test_planner_routes_large_live_games_to_hierarchical():
    plan = plan_query(100, live=True)
    assert plan.method == "hierarchical"
    # the knobs are frozen into the plan at plan time (replayability)
    assert plan.method_kw == {"clusters": 10, "cluster_tau": 0.0}
    assert plan.prune_tau == 0.0
    assert plan.est_evals == estimate_evaluations(100, 10)
    # batch (non-live) queries have no resident rounds to reconstruct
    # cluster unions from — the rung is live-only
    assert plan_query(100, live=False).method != "hierarchical"
    # under the exact wall the exact rung still wins
    assert plan_query(12, live=True).method == "exact"
    # a deadline too tight even for the grouped sweep falls through to
    # the sampled estimators
    tight = plan_query(100, None, 0.001, eval_sec=1.0, live=True)
    assert tight.method in ("GTG-Shapley", "SVARM")


def test_auto_query_journaled_plan_replays_bit_identically():
    P = 20
    sc = build_scenario(
        partners_count=P, amounts_per_partner=[1.0 / P] * P,
        dataset=cluster_mlp_dataset(n=800, seed=17, scale=1.2),
        epoch_count=2, minibatch_count=2)
    game = LiveGame(sc)
    rng = np.random.default_rng(18)
    for _ in range(2):
        deltas = jax.tree_util.tree_map(
            lambda l: rng.normal(0, 0.08, (P,) + l.shape).astype(l.dtype),
            game._init_params)
        game.append_round(deltas,
                          rng.dirichlet(np.ones(P)).astype(np.float32))
    auto = game.query("auto")
    assert auto.plan is not None and auto.plan.method == "hierarchical"
    assert auto.plan.method_kw == {"clusters": 5, "cluster_tau": 0.0}
    # the journal replay path: the plan's frozen (method, tau, kwargs)
    # alone reproduce the auto answer's bits on a fresh twin game
    twin = LiveGame(sc)
    for deltas, w in game.round_history():
        twin.append_round(deltas, w)
    replay = twin.query(auto.plan.method, prune=auto.plan.prune_tau,
                        **auto.plan.method_kw)
    assert replay.scores.tobytes() == auto.scores.tobytes()
    game.close()
    twin.close()


# ---------------------------------------------------------------------------
# 4. the end-to-end quality floors
# ---------------------------------------------------------------------------

def test_hundred_partner_auto_query_end_to_end(monkeypatch):
    """A 100-partner game (4 bitmask fold words) answered through the
    planner's hierarchical rung against the REAL engine, on REAL
    recorded rounds with a planted contribution tier (20 big partners
    with 16x the data of the 80 tiny ones).

    At this scale NO reference is exact, and the affordable sampled
    references barely resolve per-partner ranks: two strong independent
    references (GTG at 256 permutations vs SVARM at 8000 evaluations)
    only agree with EACH OTHER at tau ~0.35 on this game, and GTG's
    self-agreement across permutation budgets is ~0.25. The pinned
    floor is therefore modest — tau >= 0.1 vs unpruned SVARM (measured
    0.17, deterministic seeds) — and the sharp assertions are the ones
    the references CAN answer: both estimators must separate the
    planted tier, and the grouped decomposition must conserve v(grand)
    exactly. The hierarchy-vs-EXACT quality floor lives in the
    12-partner test below, where exact is computable.

    `MPLC_TPU_LIVE_CLUSTERS=5` keeps clusters past INTRA_EXACT_MAX, so
    the sweep is 31 macro coalitions — the million-tenant shape where
    hierarchy pays for itself."""
    P = 100
    monkeypatch.setenv("MPLC_TPU_LIVE_CLUSTERS", "5")
    amounts = np.array([4.0] * 20 + [0.25] * 80)
    sc = build_scenario(
        partners_count=P,
        amounts_per_partner=(amounts / amounts.sum()).tolist(),
        dataset=cluster_mlp_dataset(n=8000, seed=19, scale=1.5),
        epoch_count=3, minibatch_count=4)
    game = LiveGame.from_recording(sc)
    assert game.engine._rng_word_count == 4  # the multi-word regime

    r = game.query("auto")
    assert r.plan is not None and r.plan.method == "hierarchical"
    assert r.plan.method_kw["clusters"] == 5
    assert r.evaluations == 31  # the macro powerset, nothing else
    assert np.isfinite(r.scores).all() and r.scores.shape == (P,)
    # efficiency against the evaluator's own memoized grand coalition
    grand = game._recon.values[tuple(range(P))]
    assert np.isclose(r.scores.sum(), grand, atol=1e-6)

    ref = game.query("SVARM", prune=0.0, budget=4000, block=256)
    assert kendall_tau(ref.scores, r.scores) >= 0.1
    # the planted tier: big partners out-score tiny ones on average,
    # under BOTH the hierarchical rung and the sampled reference
    assert r.scores[:20].mean() > r.scores[20:].mean()
    assert ref.scores[:20].mean() > ref.scores[20:].mean()
    game.close()


def test_twelve_partner_hierarchical_vs_exact_tau_floor():
    """The decomposition-quality floor where EXACT Shapley is
    computable: a 12-partner recorded game with graded data amounts,
    grouped into 4 exact-intra clusters, must rank-agree with the exact
    answer at tau >= 0.4 (measured 0.52, deterministic seeds). This is
    the pin the 100-partner test cannot provide — its sampled
    references self-agree worse than this floor."""
    P = 12
    amounts = np.array([float(i + 4) for i in range(P)])
    sc = build_scenario(
        partners_count=P,
        amounts_per_partner=(amounts / amounts.sum()).tolist(),
        dataset=cluster_mlp_dataset(n=2400, seed=23, scale=1.5),
        epoch_count=2, minibatch_count=2)
    game = LiveGame.from_recording(sc)
    exact = game.query("exact")
    hier = game.query("hierarchical", clusters=4)
    assert kendall_tau(exact.scores, hier.scores) >= 0.4
    # grouped efficiency matches the exact decomposition's total
    assert np.isclose(hier.scores.sum(), exact.scores.sum(), atol=1e-6)
    game.close()
