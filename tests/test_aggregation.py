"""Aggregation op: weight policies and masked reduction parity with np.average."""

import jax.numpy as jnp
import numpy as np
import pytest

from mplc_tpu.ops.aggregation import aggregate, aggregation_weights, broadcast


def test_uniform_weights_masked():
    w = aggregation_weights("uniform", jnp.array([1., 1., 0.]),
                            jnp.array([10, 20, 30]), jnp.array([0.5, 0.6, 0.7]))
    assert np.allclose(np.asarray(w), [0.5, 0.5, 0.0])


def test_data_volume_weights():
    w = aggregation_weights("data-volume", jnp.array([1., 1., 1.]),
                            jnp.array([10, 20, 70]), jnp.zeros(3))
    assert np.allclose(np.asarray(w), [0.1, 0.2, 0.7])


def test_local_score_weights():
    w = aggregation_weights("local-score", jnp.array([1., 0., 1.]),
                            jnp.array([1, 1, 1]), jnp.array([0.2, 0.9, 0.6]))
    assert np.allclose(np.asarray(w), [0.25, 0.0, 0.75])


def test_unknown_aggregator_raises():
    with pytest.raises(KeyError):
        aggregation_weights("nope", jnp.ones(2), jnp.ones(2), jnp.ones(2))


def test_aggregate_matches_np_average():
    rng = np.random.default_rng(0)
    stacked = {"w": jnp.asarray(rng.normal(size=(3, 4, 5)).astype(np.float32)),
               "b": jnp.asarray(rng.normal(size=(3, 5)).astype(np.float32))}
    weights = np.array([0.2, 0.3, 0.5], np.float32)
    out = aggregate(stacked, jnp.asarray(weights))
    ref_w = np.average(np.asarray(stacked["w"]), axis=0, weights=weights)
    ref_b = np.average(np.asarray(stacked["b"]), axis=0, weights=weights)
    assert np.allclose(np.asarray(out["w"]), ref_w, atol=1e-6)
    assert np.allclose(np.asarray(out["b"]), ref_b, atol=1e-6)


def test_broadcast_round_trip():
    params = {"w": jnp.arange(6.0).reshape(2, 3)}
    st = broadcast(params, 4)
    assert st["w"].shape == (4, 2, 3)
    back = aggregate(st, jnp.full((4,), 0.25))
    assert np.allclose(np.asarray(back["w"]), np.asarray(params["w"]), atol=1e-6)
