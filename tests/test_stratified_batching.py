"""Stratified-MC speculative-lookahead batching (ISSUE 13 satellite).

The stratified methods (SMCS / WR_SMC) keep their per-iteration adaptive
allocation rule bit-identically — the sequential-oracle pins in
tests/test_estimator_regression.py stay the authority on that — but now
route multi-iteration batches through the CharacteristicEngine: each
iteration's evaluate() call also carries the next `lookahead`
iterations' draws, simulated on a CLONED rng under the current
allocation. Contracts pinned here:

  - speculation never changes the estimator: lookahead=4 scores/std are
    bit-identical to lookahead=0 (v(S) is batch-invariant + the real rng
    stream is untouched);
  - speculation actually batches: with lookahead on, evaluate() calls
    carry more than one iteration's pairs and most later iterations
    arrive fully memoized (strictly fewer calls that still need device
    work than the sequential schedule);
  - the cloned-rng/cloned-pool plumbing leaves the live WR pools intact.
"""

import numpy as np

from mplc_tpu.contrib.contributivity import Contributivity
from mplc_tpu.contrib.sampling import WithoutReplacementRanks

from test_contrib import fake_scenario


def _saturating(phi):
    return lambda s: min(1.0, 1.3 * sum(phi[i] for i in s))


def _instrument(sc):
    """Wrap the fake engine's evaluate() to record, per call, how many
    UNIQUE requested keys still needed evaluation at call entry."""
    eng = sc._charac_engine
    calls = []
    orig = eng.evaluate

    def evaluate(subsets):
        keys = [tuple(sorted(int(i) for i in s)) for s in subsets]
        unique = list(dict.fromkeys(keys))
        missing = [k for k in unique if k not in eng.charac_fct_values]
        calls.append({"requested": len(unique), "missing": len(missing)})
        return orig(subsets)

    eng.evaluate = evaluate
    return calls


def _run(method, lookahead):
    phi = [0.05, 0.15, 0.3, 0.5]
    sc = fake_scenario(4, _saturating(phi))
    calls = _instrument(sc)
    c = Contributivity(sc)
    if method == "SMCS":
        c.Stratified_MC(sv_accuracy=0.05, alpha=0.95, lookahead=lookahead)
    else:
        c.without_replacment_SMC(sv_accuracy=0.05, alpha=0.95,
                                 lookahead=lookahead)
    return c, calls


def test_smcs_lookahead_bit_identical():
    seq, _ = _run("SMCS", 0)
    spec, _ = _run("SMCS", 4)
    np.testing.assert_array_equal(seq.contributivity_scores,
                                  spec.contributivity_scores)
    np.testing.assert_array_equal(seq.scores_std, spec.scores_std)


def test_wr_smc_lookahead_bit_identical():
    seq, _ = _run("WR_SMC", 0)
    spec, _ = _run("WR_SMC", 4)
    np.testing.assert_array_equal(seq.contributivity_scores,
                                  spec.contributivity_scores)
    np.testing.assert_array_equal(seq.scores_std, spec.scores_std)


def _assert_batched(method):
    n = 4
    _, seq_calls = _run(method, 0)
    _, spec_calls = _run(method, 4)
    # sequential schedule: every call carries at most one iteration's 2N
    # pairs; the speculative schedule packs multiple iterations per call
    assert max(c["requested"] for c in seq_calls) <= 2 * n + 1
    assert max(c["requested"] for c in spec_calls) > 2 * n + 1
    # ... and converts later iterations into pure memo hits: strictly
    # fewer calls still needing device work than the sequential path
    seq_device = sum(1 for c in seq_calls if c["missing"])
    spec_device = sum(1 for c in spec_calls if c["missing"])
    assert spec_device < seq_device


def test_smcs_lookahead_batches_iterations():
    _assert_batched("SMCS")


def test_wr_smc_lookahead_batches_iterations():
    _assert_batched("WR_SMC")


def test_wr_pool_clone_leaves_live_pool_untouched():
    rng = np.random.default_rng(0)
    pool = WithoutReplacementRanks(10)
    pool.pop_random(rng)
    clone = Contributivity._clone_pool(pool)
    # draining the clone must not consume the live pool
    while clone.total:
        clone.pop_random(rng)
    assert pool.total == 9
    drawn = {pool.pop_random(rng) for _ in range(9)}
    assert len(drawn) == 9  # still a without-replacement permutation
