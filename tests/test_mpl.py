"""MPL engine + approach classes: training behavior, masking, early stopping."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mplc_tpu.data.partition import StackedPartners, stack_eval_set
from mplc_tpu.data.partner import Partner
from mplc_tpu.data.datasets import to_categorical
from mplc_tpu.models import MNIST_CNN, TITANIC_LOGREG
from mplc_tpu.mpl.engine import EvalSet, MplTrainer, TrainConfig
from mplc_tpu.mpl.approaches import (MULTI_PARTNER_LEARNING_APPROACHES,
                                     FederatedAverageLearning,
                                     SinglePartnerLearning)


@pytest.fixture(scope="module")
def small_logreg_problem():
    """Fast linearly-separable problem on the tiny logistic model."""
    rng = np.random.default_rng(0)
    w = rng.normal(size=27)
    def make(n):
        x = rng.normal(size=(n, 27)).astype(np.float32)
        y = (x @ w > 0).astype(np.float32)
        return x, y
    partners = []
    for i, n in enumerate([200, 150, 100]):
        p = Partner(i)
        p.x_train, p.y_train = make(n)
        partners.append(p)
    stacked = StackedPartners.build(partners, 1)
    val = EvalSet(*stack_eval_set(*make(120), 1, 128))
    test = EvalSet(*stack_eval_set(*make(120), 1, 128))
    return stacked, val, test


def _run(trainer, stacked, val, mask, n_epochs, rng=0):
    state = trainer.init_state(jax.random.PRNGKey(rng), stacked.x.shape[0])
    run = jax.jit(trainer.epoch_chunk, static_argnames=("n_epochs",))
    return run(state, stacked, val, mask, jax.random.PRNGKey(rng + 1),
               n_epochs=n_epochs)


@pytest.mark.parametrize("approach", ["fedavg", "seq-pure", "seqavg",
                                      "seq-with-final-agg"])
def test_all_approaches_learn(small_logreg_problem, approach):
    stacked, val, test = small_logreg_problem
    cfg = TrainConfig(approach=approach, aggregator="data-volume", epoch_count=4,
                      minibatch_count=2, gradient_updates_per_pass=4,
                      is_early_stopping=False, record_partner_val=False)
    tr = MplTrainer(TITANIC_LOGREG, cfg)
    state = _run(tr, stacked, val, jnp.ones(3), 4)
    _, acc = jax.jit(tr.finalize)(state, test)
    assert float(acc) > 0.8, f"{approach} failed to learn: acc={float(acc)}"


def test_coalition_mask_excludes_partner(small_logreg_problem):
    """An inactive partner must not influence training: a coalition of
    {0} with partners 1,2 masked must equal training on partner 0 data only."""
    stacked, val, test = small_logreg_problem
    cfg = TrainConfig(approach="fedavg", aggregator="uniform", epoch_count=2,
                      minibatch_count=2, gradient_updates_per_pass=2,
                      is_early_stopping=False, record_partner_val=False)
    tr = MplTrainer(TITANIC_LOGREG, cfg)
    state_masked = _run(tr, stacked, val, jnp.array([1., 0., 0.]), 2)

    # same training with a stack containing only partner 0
    solo = StackedPartners(stacked.x[:1], stacked.y[:1], stacked.mask[:1],
                           stacked.sizes[:1])
    tr1 = MplTrainer(TITANIC_LOGREG, cfg)
    state_solo = _run(tr1, solo, val, jnp.ones(1), 2)

    for a, b in zip(jax.tree_util.tree_leaves(state_masked.params),
                    jax.tree_util.tree_leaves(state_solo.params)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.parametrize("approach", ["fedavg", "seq-pure", "seqavg",
                                      "seq-with-final-agg"])
def test_batched_coalitions_match_individual(small_logreg_problem, approach):
    """vmapped mask batch must give the same scores as one-at-a-time runs —
    the seq family runs through the same vmapped multi pipe. (lflip, the
    remaining sweepable approach, gets its own categorical-model case
    below: the binary logreg fixture has num_outputs=1, degenerate for a
    KxK flip matrix.)"""
    stacked, val, test = small_logreg_problem
    cfg = TrainConfig(approach=approach, aggregator="uniform", epoch_count=2,
                      minibatch_count=2, gradient_updates_per_pass=2,
                      is_early_stopping=False, record_partner_val=False)
    tr = MplTrainer(TITANIC_LOGREG, cfg)
    masks = jnp.array([[1, 1, 0], [0, 1, 1], [1, 1, 1]], jnp.float32)
    rngs = jnp.stack([jax.random.PRNGKey(5)] * 3)

    binit = jax.jit(jax.vmap(lambda r: tr.init_state(r, 3)))
    brun = jax.jit(jax.vmap(tr.epoch_chunk, in_axes=(0, None, None, 0, 0, None)),
                   static_argnames=("n_epochs",))
    bfin = jax.jit(jax.vmap(tr.finalize, in_axes=(0, None)))
    bstate = brun(binit(rngs), stacked, val, masks, rngs, 2)
    _, batch_accs = bfin(bstate, test)

    for i in range(3):
        state = tr.init_state(jax.random.PRNGKey(5), 3)
        run = jax.jit(tr.epoch_chunk, static_argnames=("n_epochs",))
        state = run(state, stacked, val, masks[i], jax.random.PRNGKey(5), n_epochs=2)
        _, acc = jax.jit(tr.finalize)(state, test)
        assert np.isclose(float(acc), float(batch_accs[i]), atol=1e-5)


def test_batched_coalitions_match_individual_lflip():
    """lflip batched-coalition parity on a categorical model: theta is
    vmapped per-coalition state alongside params, so a regression specific
    to the batched lflip path would be invisible to the logreg cases."""
    from helpers import cluster_mlp_model, make_cluster_data

    mlp = cluster_mlp_model(4)
    rng_np = np.random.default_rng(11)
    centers = rng_np.normal(size=(4, 16)).astype(np.float32) * 2.0
    from mplc_tpu.data.partition import StackedPartners, stack_eval_set
    from mplc_tpu.data.partner import Partner

    partners = []
    for i, n in enumerate([40, 60, 50]):
        p = Partner(i)
        p.x_train, p.y_train = make_cluster_data(rng_np, n, centers)
        partners.append(p)
    stacked = StackedPartners.build(partners, 4)
    val = EvalSet(*stack_eval_set(*make_cluster_data(rng_np, 60, centers), 4, 64))
    test = EvalSet(*stack_eval_set(*make_cluster_data(rng_np, 60, centers), 4, 64))

    cfg = TrainConfig(approach="lflip", aggregator="uniform", epoch_count=2,
                      minibatch_count=2, gradient_updates_per_pass=2,
                      is_early_stopping=False, record_partner_val=False)
    tr = MplTrainer(mlp, cfg)
    masks = jnp.array([[1, 1, 0], [0, 1, 1], [1, 1, 1]], jnp.float32)
    rngs = jnp.stack([jax.random.PRNGKey(5)] * 3)

    binit = jax.jit(jax.vmap(lambda r: tr.init_state(r, 3)))
    brun = jax.jit(jax.vmap(tr.epoch_chunk, in_axes=(0, None, None, 0, 0, None)),
                   static_argnames=("n_epochs",))
    bfin = jax.jit(jax.vmap(tr.finalize, in_axes=(0, None)))
    bstate = brun(binit(rngs), stacked, val, masks, rngs, 2)
    _, batch_accs = bfin(bstate, test)

    for i in range(3):
        state = tr.init_state(jax.random.PRNGKey(5), 3)
        run = jax.jit(tr.epoch_chunk, static_argnames=("n_epochs",))
        state = run(state, stacked, val, masks[i], jax.random.PRNGKey(5), n_epochs=2)
        _, acc = jax.jit(tr.finalize)(state, test)
        assert np.isclose(float(acc), float(batch_accs[i]), atol=1e-5)
        # per-partner theta matches too (inactive partners keep theta0)
        np.testing.assert_allclose(np.asarray(bstate.theta[i]),
                                   np.asarray(state.theta), atol=1e-5)


def test_early_stopping_freezes(small_logreg_problem):
    stacked, val, test = small_logreg_problem
    cfg = TrainConfig(approach="fedavg", aggregator="uniform", epoch_count=8,
                      minibatch_count=2, gradient_updates_per_pass=2,
                      is_early_stopping=True, patience=2, record_partner_val=False)
    tr = MplTrainer(TITANIC_LOGREG, cfg)
    state = _run(tr, stacked, val, jnp.ones(3), 8)
    nb = int(state.nb_epochs_done)
    assert 1 <= nb <= 8
    if bool(state.done) and nb < 8:
        # frozen: history rows after stopping remain NaN
        assert np.isnan(np.asarray(state.val_loss_h)[nb:, 0]).all()


def test_single_trainer(small_logreg_problem):
    stacked, val, test = small_logreg_problem
    cfg = TrainConfig(approach="single", aggregator="uniform", epoch_count=4,
                      minibatch_count=2, gradient_updates_per_pass=4,
                      is_early_stopping=False, record_partner_val=False)
    tr = MplTrainer(TITANIC_LOGREG, cfg)
    state = _run(tr, stacked, val, jnp.array([0., 1., 0.]), 4)
    _, acc = jax.jit(tr.finalize)(state, test)
    assert float(acc) > 0.75


def test_history_matrices_filled(small_logreg_problem):
    stacked, val, test = small_logreg_problem
    cfg = TrainConfig(approach="fedavg", aggregator="uniform", epoch_count=2,
                      minibatch_count=3, gradient_updates_per_pass=2,
                      is_early_stopping=False, record_partner_val=True)
    tr = MplTrainer(TITANIC_LOGREG, cfg)
    state = _run(tr, stacked, val, jnp.ones(3), 2)
    assert not np.isnan(np.asarray(state.val_loss_h)).any()
    ph = np.asarray(state.partner_h)  # [4, P, E, MB]
    assert ph.shape == (4, 3, 2, 3)
    assert not np.isnan(ph).any()


@pytest.mark.parametrize("slot_count,ids", [(2, [0, 2]), (3, [0, 2, -1])])
def test_slot_execution_matches_masked(small_logreg_problem, slot_count, ids):
    """A size-2 coalition of 3 partners trained via 2 (or 3, one padded)
    slots must produce bit-identical training to the masked path — RNG
    streams are keyed by partner id in both."""
    stacked, val, test = small_logreg_problem
    base = dict(approach="fedavg", aggregator="data-volume", epoch_count=2,
                minibatch_count=2, gradient_updates_per_pass=2,
                is_early_stopping=False, record_partner_val=True)
    tr_mask = MplTrainer(TITANIC_LOGREG, TrainConfig(**base))
    tr_slot = MplTrainer(TITANIC_LOGREG, TrainConfig(slot_count=slot_count, **base))
    rng = jax.random.PRNGKey(4)

    run_m = jax.jit(tr_mask.epoch_chunk, static_argnames=("n_epochs",))
    s1 = run_m(tr_mask.init_state(rng, 3), stacked, val,
               jnp.array([1., 0., 1.]), rng, n_epochs=2)
    run_s = jax.jit(tr_slot.epoch_chunk, static_argnames=("n_epochs",))
    s2 = run_s(tr_slot.init_state(rng, 3), stacked, val,
               jnp.array(ids, jnp.int32), rng, n_epochs=2)

    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(s2.params)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    assert np.allclose(np.asarray(s1.val_loss_h), np.asarray(s2.val_loss_h),
                       atol=1e-5)
    # active partners' history rows match; unused slot rows stay NaN
    ph1, ph2 = np.asarray(s1.partner_h), np.asarray(s2.partner_h)
    for p in (0, 2):
        assert np.allclose(ph1[:, p], ph2[:, p], atol=1e-5)
    assert np.isnan(ph2[:, 1]).all()
    _, a1 = jax.jit(tr_mask.finalize)(s1, test)
    _, a2 = jax.jit(tr_slot.finalize)(s2, test)
    assert np.isclose(float(a1), float(a2), atol=1e-6)


def test_slot_config_guards():
    # the seq family joined fedavg as slot-executable (seq slot epochs);
    # lflip (per-partner theta state is [P]-indexed) and single do not
    assert TrainConfig(approach="seqavg", slot_count=2).slot_count == 2
    assert TrainConfig(approach="seq-pure", slot_count=2).slot_count == 2
    with pytest.raises(ValueError):
        TrainConfig(approach="lflip", slot_count=2)
    with pytest.raises(ValueError):
        TrainConfig(approach="single", slot_count=2)
    with pytest.raises(ValueError):
        TrainConfig(approach="fedavg", slot_count=2, partner_axis="part")
    with pytest.raises(ValueError):
        TrainConfig(step_width_mult=0)


# -- approach classes over a real scenario ----------------------------------

def test_registry_keys():
    assert set(MULTI_PARTNER_LEARNING_APPROACHES) == {
        "fedavg", "seq-pure", "seq-with-final-agg", "seqavg", "lflip"}


@pytest.fixture(scope="module")
def logreg_class_scenario():
    """A 3-partner titanic scenario for the class-API tests: the logreg
    trainer compiles in seconds on CPU, where the CNN costs minutes — the
    conv-backed class path is covered by the `slow`-marked variants."""
    from helpers import build_scenario
    return build_scenario(dataset_name="titanic")


def test_fedavg_class_runs(logreg_class_scenario):
    mpl = FederatedAverageLearning(logreg_class_scenario)
    score = mpl.fit()
    assert 0.0 <= score <= 1.0
    assert mpl.learning_computation_time > 0
    hist = mpl.history
    assert hist.score == score
    assert hist.history["mpl_model"]["val_loss"].shape == (4, 2)
    df = hist.partners_to_dataframe()
    assert set(["Partner", "Epoch", "Minibatch"]).issubset(df.columns)


@pytest.mark.slow
def test_fedavg_class_runs_cnn(quick_scenario):
    mpl = FederatedAverageLearning(quick_scenario)
    score = mpl.fit()
    assert 0.0 <= score <= 1.0
    assert mpl.history.history["mpl_model"]["val_loss"].shape == (4, 2)


def test_fedavg_requires_multiple_partners(quick_scenario):
    import copy
    sc = copy.copy(quick_scenario)
    sc.partners_list = quick_scenario.partners_list[:1]
    with pytest.raises(ValueError):
        FederatedAverageLearning(sc)


def test_single_partner_class(logreg_class_scenario):
    sc = logreg_class_scenario
    mpl = SinglePartnerLearning(sc, partner=sc.partners_list[0])
    score = mpl.fit()
    assert 0.0 <= score <= 1.0


def test_single_partner_class_stages_only_its_partner(logreg_class_scenario):
    """The class path's analogue of the engine's sliced-singles rule: a
    SinglePartnerLearning over a multi-partner scenario must stage a
    [1, n_own, ...] tensor — its own partner's rows only, never the whole
    scenario's stacked axis padded to the LARGEST partner."""
    sc = logreg_class_scenario
    # pick a partner that is NOT the largest, so a regression that stages
    # the full scenario (P rows, Nmax = max partner size) fails loudly on
    # both axes
    partner = min(sc.partners_list, key=lambda p: len(p.x_train))
    assert len(sc.partners_list) > 1
    assert len(partner.x_train) < max(len(p.x_train)
                                      for p in sc.partners_list)
    mpl = SinglePartnerLearning(sc, partner=partner)
    stacked, _val, _test = mpl._stage()
    assert stacked.x.shape[0] == 1          # P = 1, not the scenario's P
    assert stacked.x.shape[1] == len(partner.x_train)  # own Nmax
    assert int(stacked.sizes[0]) == len(partner.x_train)


@pytest.mark.slow
def test_single_partner_class_cnn(quick_scenario):
    mpl = SinglePartnerLearning(quick_scenario,
                                partner=quick_scenario.partners_list[0])
    score = mpl.fit()
    assert 0.0 <= score <= 1.0
