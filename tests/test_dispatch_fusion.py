"""Sweep-level dispatch fusion (ISSUE 2): the vectorized batch prep, the
merged slot bucketing, the default-on batch pipelining and the batch-cap
plumbing.

The contract under test: every fused path produces BIT-IDENTICAL v(S)
values to the path it replaced — the per-subset rng fold, the per-batch
Python fill loops, the per-size slot programs and the sequential harvest
are pure dispatch-shape changes, never numerics changes — while compiling
fewer programs and padding fewer batch rows.
"""

import types

import numpy as np
import pytest

import jax

from mplc_tpu.contrib.engine import (BatchedTrainerPipeline,
                                     CharacteristicEngine)
from mplc_tpu.contrib.shapley import powerset_order


def _scenario(n=5, **kw):
    from helpers import build_scenario
    amounts = [(i + 1) / (n * (n + 1) / 2) for i in range(n)]
    params = dict(partners_count=n, amounts_per_partner=amounts,
                  dataset_name="titanic", epoch_count=2,
                  gradient_updates_per_pass_count=2, seed=11)
    params.update(kw)
    return build_scenario(**params)


# -- vectorized rng fold -----------------------------------------------------

def _rng_dummy(partners_count, seed=7):
    """The rng helpers only touch seed / partners_count / the cached seed
    key — a bare namespace exercises them without building an engine."""
    return types.SimpleNamespace(
        seed=seed, partners_count=partners_count,
        _rng_word_count=max(1, (partners_count + 31) // 32),
        _seed_key=jax.random.PRNGKey(seed))


def _batch_keys(dummy, subsets):
    words, n_words = CharacteristicEngine._rng_fold_words(dummy, subsets)
    sel = np.arange(len(subsets))
    return np.asarray(
        CharacteristicEngine._batch_rngs(dummy, words, n_words, sel))


def test_vectorized_rng_fold_matches_scalar_loop():
    """The jitted vmapped fold must reproduce _coalition_rng's key stream
    bit-for-bit — same coalition, same training — for every subset shape,
    including the empty tuple the base rng uses."""
    dummy = _rng_dummy(10)
    subsets = [(), (0,), (9,), (0, 1), (2, 5, 7), tuple(range(10))]
    keys = _batch_keys(dummy, subsets)
    for k, s in zip(keys, subsets):
        np.testing.assert_array_equal(
            k, np.asarray(CharacteristicEngine._coalition_rng(dummy, s)), s)


def test_vectorized_rng_fold_matches_past_32_partners():
    """>= 32 partners folds the membership bitmask in MULTIPLE uint32
    words, and the scalar loop folds only up to the highest non-zero word
    — a subset of low indices folds ONCE even at 40 partners. The
    vectorized fold must reproduce both the word packing and the variable
    fold count exactly."""
    dummy = _rng_dummy(40)
    subsets = [(), (0,), (31,), (32,), (39,), (5, 33), (0, 31, 32, 39),
               (38, 39), tuple(range(40))]
    words, n_words = CharacteristicEngine._rng_fold_words(dummy, subsets)
    assert words.shape == (len(subsets), 2)
    # low-index subsets fold once; any index >= 32 forces the second word
    by_subset = dict(zip(subsets, n_words))
    assert by_subset[(31,)] == 1 and by_subset[(0,)] == 1
    assert by_subset[(32,)] == 2 and by_subset[(5, 33)] == 2
    assert by_subset[()] == 1
    keys = _batch_keys(dummy, subsets)
    for k, s in zip(keys, subsets):
        np.testing.assert_array_equal(
            k, np.asarray(CharacteristicEngine._coalition_rng(dummy, s)), s)
    # distinct subsets must still get distinct streams
    assert len({tuple(k) for k in keys}) == len(subsets)


def test_coalition_array_scatter_matches_fill_loop():
    """The whole-call NumPy scatter must equal the old per-row fill loops
    for both the slot-id and the mask layout."""
    dummy = types.SimpleNamespace(partners_count=6)
    subsets = [(0, 3), (1, 2, 5), (4,), (0, 1, 2, 3, 4, 5)]
    ids = CharacteristicEngine._coalition_arrays(dummy, subsets, 6)
    masks = CharacteristicEngine._coalition_arrays(dummy, subsets, None)
    for j, s in enumerate(subsets):
        ref_ids = np.full(6, -1, np.int32)
        ref_ids[:len(s)] = sorted(s)
        np.testing.assert_array_equal(ids[j], ref_ids)
        ref_mask = np.zeros(6, np.float32)
        ref_mask[list(s)] = 1.0
        np.testing.assert_array_equal(masks[j], ref_mask)


# -- slot-merge bucketing ----------------------------------------------------

def test_slot_merge_is_default_and_pairs_adjacent_sizes(monkeypatch):
    monkeypatch.delenv("MPLC_TPU_SLOT_MERGE", raising=False)
    monkeypatch.delenv("MPLC_TPU_SLOT_POW2", raising=False)
    monkeypatch.delenv("MPLC_TPU_PARTNER_SHARDS", raising=False)
    sc = _scenario(5)
    eng = CharacteristicEngine(sc)
    assert eng._slot_merge and sc.slot_bucketing == "merge"
    # even sizes ride the next odd size's program, capped at P
    assert [eng._slot_width(k) for k in range(2, 6)] == [3, 3, 5, 5]
    # a 10-partner sweep plans ceil(9/2) = 5 programs instead of 9
    eng.partners_count = 10
    assert sorted({eng._slot_width(k) for k in range(2, 11)}) == \
        [3, 5, 7, 9, 10]


def test_slot_merge_bit_identical_to_exact_pow2_and_masked(monkeypatch):
    """The acceptance contract: the full 5-partner v(S) table is
    bit-identical across masked / exact / pow2 / merge execution — the -1
    unused-slot convention plus global-partner-id rng keying make mixed
    widths exact, and inactive slots contribute exactly-zero aggregation
    weight."""
    subsets = powerset_order(5)
    monkeypatch.delenv("MPLC_TPU_PARTNER_SHARDS", raising=False)
    monkeypatch.delenv("MPLC_TPU_SLOT_POW2", raising=False)

    monkeypatch.setenv("MPLC_TPU_SLOT_MERGE", "0")
    exact_eng = CharacteristicEngine(_scenario(5))
    assert exact_eng.scenario.slot_bucketing == "exact"
    exact = exact_eng.evaluate(subsets)

    monkeypatch.delenv("MPLC_TPU_SLOT_MERGE", raising=False)
    merge_eng = CharacteristicEngine(_scenario(5))
    merge = merge_eng.evaluate(subsets)
    # sizes (2,3) share the 3-slot program, (4,5) the 5-slot one
    assert sorted(merge_eng._slot_pipes) == [3, 5]
    np.testing.assert_array_equal(merge, exact)

    monkeypatch.setenv("MPLC_TPU_SLOT_POW2", "1")
    pow2 = CharacteristicEngine(_scenario(5)).evaluate(subsets)
    np.testing.assert_array_equal(pow2, exact)

    monkeypatch.delenv("MPLC_TPU_SLOT_POW2", raising=False)
    monkeypatch.setenv("MPLC_TPU_NO_SLOTS", "1")
    masked_eng = CharacteristicEngine(_scenario(5))
    assert masked_eng.scenario.slot_bucketing == "masked"
    masked = masked_eng.evaluate(subsets)
    np.testing.assert_array_equal(masked, exact)

    # the table must discriminate, or the equality contract is vacuous
    assert exact.max() - exact.min() > 1e-3


def test_merge_mode_compiles_fewer_programs_and_pads_less(monkeypatch):
    """The obs-metrics regression of the acceptance criteria: on a
    synthetic 10-partner full sweep (CPU mesh, cap=2 -> the width-16
    batches of the single-chip cap-16 regime), merge mode runs <= 5 slot
    programs (vs 9 exact) and records strictly lower summed batch padding.
    Training is stubbed out — the engine's real scheduling, padding and
    accounting run; only the device work is skipped."""
    from mplc_tpu.obs import trace
    from mplc_tpu.obs.report import sweep_report

    def fake_scores_async(self, masks, rngs, stacked, val, test, base_rng):
        b = int(masks.shape[0])
        return lambda: (np.full(b, 0.5, np.float32),
                        np.full(b, 2, np.int32))

    monkeypatch.setattr(BatchedTrainerPipeline, "scores_async",
                        fake_scores_async)
    # training is stubbed out, so AOT program-bank compiles would be pure
    # waste here (the bank compiles REAL executables the stub never runs)
    monkeypatch.setenv("MPLC_TPU_PROGRAM_BANK", "0")
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "2")
    monkeypatch.delenv("MPLC_TPU_SLOT_POW2", raising=False)
    monkeypatch.delenv("MPLC_TPU_PARTNER_SHARDS", raising=False)
    subsets = powerset_order(10)

    def run(merge_env):
        if merge_env is None:
            monkeypatch.delenv("MPLC_TPU_SLOT_MERGE", raising=False)
        else:
            monkeypatch.setenv("MPLC_TPU_SLOT_MERGE", merge_env)
        eng = CharacteristicEngine(_scenario(10))
        with trace.collect() as recs:
            eng.evaluate(subsets)
        assert eng.first_charac_fct_calls_count == 1023
        rep = sweep_report(recs)
        programs = {(r["attrs"]["slot_count"], r["attrs"]["width"])
                    for r in recs if r["name"] == "engine.batch"
                    if r["attrs"]["slot_count"] is not None}
        return eng, rep, programs

    exact_eng, exact_rep, exact_programs = run("0")
    merge_eng, merge_rep, merge_programs = run(None)

    assert len(exact_eng._slot_pipes) == 9
    assert len(merge_eng._slot_pipes) <= 5
    assert len(merge_programs) <= 5 < len(exact_programs)
    # every batch of every program runs at width 16 here except the lone
    # size-10 coalition's — identical program SHAPE count, fewer programs
    assert merge_rep["batches"]["padding"] < exact_rep["batches"]["padding"]
    # both modes trained every coalition exactly once
    assert merge_rep["batches"]["coalitions"] == \
        exact_rep["batches"]["coalitions"] == 1023
    # the pad-waste histogram mirrored the same totals
    from mplc_tpu.obs import metrics
    assert metrics.snapshot()["histograms"][
        "engine.pad_waste_fraction"]["count"] > 0


# -- pipelining defaults & the 2-D singles overlap ---------------------------

def test_pipelining_is_default_on_with_opt_out(monkeypatch):
    monkeypatch.delenv("MPLC_TPU_PIPELINE_BATCHES", raising=False)
    monkeypatch.delenv("MPLC_TPU_PARTNER_SHARDS", raising=False)
    eng = CharacteristicEngine(_scenario(3))
    assert eng._pipeline_batches
    monkeypatch.setenv("MPLC_TPU_PIPELINE_BATCHES", "0")
    assert not CharacteristicEngine(_scenario(3))._pipeline_batches


def test_pipelined_singles_sliced_matches_sequential(monkeypatch):
    """The 2-D data-sliced singles path now overlaps batches too (its
    host-side slice rebuild is exactly the gap overlap hides). Results
    must be bit-identical to the sequential harvest, the cached
    per-bucket-width pipeline must be reused across calls, and cap=1
    forces 2 batches so the pending/drain protocol really runs."""
    monkeypatch.setenv("MPLC_TPU_PARTNER_SHARDS", "2")
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "1")
    n = 8
    singles = [(i,) for i in range(n)]

    monkeypatch.setenv("MPLC_TPU_PIPELINE_BATCHES", "0")
    ref_vals = CharacteristicEngine(_scenario(n)).evaluate(singles)

    monkeypatch.delenv("MPLC_TPU_PIPELINE_BATCHES", raising=False)
    eng = CharacteristicEngine(_scenario(n))
    assert eng._pipe2d is not None
    progressed = []
    eng.progress = lambda done, rem, slots: progressed.append((done, rem))
    vals = eng.evaluate(singles)
    np.testing.assert_array_equal(vals, ref_vals)
    # 8 singles over a 4-wide coal mesh at cap=1 = two width-4 batches,
    # each drained exactly once
    assert progressed == [(4, 4), (4, 0)]
    # one cached pipeline, keyed by the bucket width
    assert list(eng._singles_pipes) == [4]
    pipe = eng._singles_pipes[4]
    eng.charac_fct_values = {(): 0.0}  # force re-evaluation
    eng.evaluate(singles)
    assert eng._singles_pipes[4] is pipe  # reused, not rebuilt


# -- batch-cap plumbing ------------------------------------------------------

def test_malformed_cap_env_warns_and_falls_back(monkeypatch):
    """A malformed MPLC_TPU_COALITIONS_PER_DEVICE must warn and fall back
    to the autotune (same contract as MPLC_TPU_EVAL_CHUNK) instead of
    crashing mid-sweep."""
    monkeypatch.delenv("MPLC_TPU_PARTNER_SHARDS", raising=False)
    eng = CharacteristicEngine(_scenario(3))
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "sixteen")
    with pytest.warns(UserWarning, match="MPLC_TPU_COALITIONS_PER_DEVICE"):
        cap = eng._device_batch_cap()
    assert 1 <= cap <= 16
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "-3")
    with pytest.warns(UserWarning):
        assert 1 <= eng._device_batch_cap() <= 16
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "24")
    assert eng._device_batch_cap() == 24


def test_cap_ceiling_env_lifts_autotune_past_16(monkeypatch):
    """MPLC_TPU_BATCH_CAP_CEILING lifts the constant ceiling the
    HBM-derived autotune is clamped to (merge mode bounds the program
    count, so wider buckets no longer multiply compiles by 9)."""
    monkeypatch.delenv("MPLC_TPU_COALITIONS_PER_DEVICE", raising=False)
    monkeypatch.delenv("MPLC_TPU_BATCH_CAP_CEILING", raising=False)
    monkeypatch.delenv("MPLC_TPU_PARTNER_SHARDS", raising=False)
    eng = CharacteristicEngine(_scenario(3))
    eng._hbm_bytes = 1 << 50  # memory never binds: the ceiling does
    assert eng._device_batch_cap() == 16
    monkeypatch.setenv("MPLC_TPU_BATCH_CAP_CEILING", "64")
    assert eng._device_batch_cap() == 64
    # malformed ceiling falls back to the default 16, with a warning
    monkeypatch.setenv("MPLC_TPU_BATCH_CAP_CEILING", "wide")
    with pytest.warns(UserWarning, match="MPLC_TPU_BATCH_CAP_CEILING"):
        assert eng._device_batch_cap() == 16


def test_memory_stats_queried_once_per_engine(monkeypatch):
    """_device_batch_cap caches the device memory limit: memory_stats
    crosses the tunnel on remote backends and was being re-queried every
    _run_batch call."""
    monkeypatch.delenv("MPLC_TPU_COALITIONS_PER_DEVICE", raising=False)
    monkeypatch.delenv("MPLC_TPU_PARTNER_SHARDS", raising=False)
    eng = CharacteristicEngine(_scenario(3))
    calls = {"n": 0}

    class Dev:
        def memory_stats(self):
            calls["n"] += 1
            return {"bytes_limit": 8 << 30}

    monkeypatch.setattr(jax, "local_devices", lambda: [Dev()])
    first = eng._device_batch_cap()
    for _ in range(5):
        assert eng._device_batch_cap() == first
    assert calls["n"] == 1
