"""The load/chaos harness (scripts/load_gen.py) and the overload
invariant it enforces.

Fast tier: a deterministic chaos smoke — fixed seed, ~20 small jobs,
inline (`start=False`) stepped scheduling — asserting the acceptance
invariant end-to-end: under chaos + overload every ACCEPTED job reaches
a terminal state (completed / shed / cancelled / quarantined — none
lost, none hung), shed jobs are classified `JobShed` (never silent),
and every completed job's values are BIT-IDENTICAL to a solo fault-free
run of the same game.

Slow tier (`-m slow`): a ~60 s threaded soak with a real worker pool,
chaos injection and admission-bound overload.
"""

import os
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "scripts"))

import load_gen  # noqa: E402

from mplc_tpu.obs import metrics  # noqa: E402

_KNOBS = ("MPLC_TPU_SERVICE_FAULT_PLAN", "MPLC_TPU_SERVICE_MAX_PENDING",
          "MPLC_TPU_SERVICE_SLICE", "MPLC_TPU_SERVICE_WORKERS",
          "MPLC_TPU_SERVICE_PRIORITY_DEFAULT",
          "MPLC_TPU_SERVICE_SHED_P99_SEC", "MPLC_TPU_FAULT_PLAN",
          "MPLC_TPU_MAX_RETRIES", "MPLC_TPU_SEED_ENSEMBLE",
          "MPLC_TPU_PARTNER_FAULT_PLAN")


@pytest.fixture(autouse=True)
def _env(monkeypatch):
    for k in _KNOBS:
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("MPLC_TPU_RETRY_BACKOFF_SEC", "0")
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "1")
    metrics.reset()
    yield
    metrics.reset()


def _small_builder(partners, seed, epochs=1, dataset="titanic"):
    """Tiny 2-epoch titanic games via the shared test recipe — same
    trainer-registry programs as the rest of the suite, so the smoke
    pays no extra compiles."""
    def build():
        from helpers import build_scenario
        amounts = [1.0 / partners] * partners
        return build_scenario(partners_count=partners,
                              amounts_per_partner=amounts,
                              dataset_name=dataset, epoch_count=2,
                              gradient_updates_per_pass_count=2,
                              seed=seed)
    return build


def test_chaos_smoke_invariant_holds_under_chaos_and_overload():
    """The deterministic fast-tier chaos smoke: 20 mixed-shape jobs, a
    high chaos rate (so faults actually fire at this job count), a tiny
    admission bound (so the ServiceOverloaded/backoff path runs), all on
    the inline stepped harness — then the acceptance invariant."""
    report = load_gen.run_load(
        jobs=20, partner_shapes=(2, 3), game_seeds=(0, 1),
        tiers=(0, 1), threaded=False, max_pending=5, slice_coalitions=3,
        chaos_plan="chaos@rate0.3:seed7", timeout_sec=300,
        scenario_builder=_small_builder)
    inv = report["invariant"]
    assert inv["holds"], inv
    assert inv["accepted"] == 20
    assert inv["terminal"] == 20
    assert inv["stuck"] == 0
    assert inv["values_bit_identical_to_solo"] is True
    assert report["outcomes"].get("completed", 0) > 0
    # chaos actually fired at rate 0.3 x 20 jobs (deterministic: the
    # draws depend only on (seed, ordinal)) — crash/transient ones show
    # as injected engine faults and re-queued attempts, stalls as
    # service.stall events; seed 7 yields both classes in 20 ordinals
    res = report["service_report"]["resilience"]
    assert res["faults_injected"] > 0
    # the harness hit the admission bound and backed off cleanly
    assert report["saturation"]["overload_backoffs"] > 0
    # per-tier latency quantiles are present for both tiers
    for tier in ("0", "1"):
        row = report["per_tier"][tier]
        assert row["jobs"] > 0
        assert row["queue_wait_s"]["p50"] is not None
        assert row["e2e_s"]["p99"] is not None
    # the sweep report's service row agrees with the harness outcomes
    svc_row = report["service_report"]["service"]
    assert svc_row["completed"] == report["outcomes"]["completed"]


def test_chaos_smoke_is_deterministic_in_outcomes():
    """Same seed + same submission order => same outcome counts and the
    same faults, under the inline harness (the replayability the chaos
    grammar promises)."""
    kw = dict(jobs=12, partner_shapes=(2,), game_seeds=(0,),
              tiers=(0,), threaded=False, max_pending=12,
              slice_coalitions=4, chaos_plan="chaos@rate0.4:seed11",
              timeout_sec=300, scenario_builder=_small_builder)
    r1 = load_gen.run_load(**kw)
    metrics.reset()
    r2 = load_gen.run_load(**kw)
    assert r1["outcomes"] == r2["outcomes"]
    assert (r1["service_report"]["resilience"]["faults_injected"]
            == r2["service_report"]["resilience"]["faults_injected"])
    assert r1["invariant"]["holds"] and r2["invariant"]["holds"]


def test_load_with_shedding_classifies_and_accounts():
    """Overload + a breached shed SLO: lowest-tier jobs shed (classified,
    counted), higher tiers complete bit-identically, invariant holds."""
    report = load_gen.run_load(
        jobs=10, partner_shapes=(2,), game_seeds=(0,),
        tiers=(0, 1), threaded=False, max_pending=10, slice_coalitions=3,
        shed_p99_sec=1e-9, timeout_sec=300,
        scenario_builder=_small_builder)
    inv = report["invariant"]
    assert inv["holds"], inv
    assert report["outcomes"].get("shed", 0) > 0
    assert inv["sheds_classified"] is True
    # shed accounting agrees across the three sources: harness outcomes,
    # the sweep report's service row, and the admission view
    assert (report["service_report"]["service"]["shed"]
            == report["outcomes"]["shed"])
    assert report["admission"]["shed_total"] == report["outcomes"]["shed"]
    # shedding is lowest-tier-first: tier 0 bears the brunt (tier 1 is
    # only reachable once tier 0 has no never-started jobs left)
    assert report["per_tier"]["0"]["shed"] > 0
    assert report["per_tier"]["0"]["shed"] >= report["per_tier"]["1"]["shed"]


@pytest.mark.slow
def test_soak_threaded_worker_pool_under_chaos():
    """The ~60 s soak: a real worker pool, chaos, and admission-bound
    overload, end to end through the threaded scheduler. The invariant
    must hold with REAL thread interleaving, not just the deterministic
    inline schedule. (SOAK_JOBS env trims/extends the default ~1200-job,
    roughly-one-minute run for slower/faster boxes.)"""
    jobs = int(os.environ.get("SOAK_JOBS", "1200"))
    report = load_gen.run_load(
        jobs=jobs, partner_shapes=(2, 3), game_seeds=(0, 1, 2),
        tiers=(0, 1, 2), threaded=True, workers=2, max_pending=8,
        slice_coalitions=3, chaos_plan="chaos@rate0.15:seed3",
        timeout_sec=900, scenario_builder=_small_builder)
    inv = report["invariant"]
    assert inv["holds"], inv
    assert inv["accepted"] == jobs and inv["stuck"] == 0
    assert report["outcomes"].get("completed", 0) > 0
    assert report["saturation"]["completed_jobs_per_s"] > 0
    # every tier made progress: weighted scheduling, not starvation
    for tier in ("0", "1", "2"):
        assert report["per_tier"][tier]["completed"] > 0


def test_bench_config7_knob_is_wired():
    """BENCH_CONFIG=7 dispatches to bench_load (static check — the real
    run is the benchmark, not a unit test)."""
    import importlib
    repo = str(Path(__file__).resolve().parents[1])
    if repo not in sys.path:
        sys.path.insert(0, repo)
    bench = importlib.import_module("bench")
    import inspect
    assert hasattr(bench, "bench_load")
    src = inspect.getsource(bench.main)
    assert 'config == "7"' in src and "bench_load" in src
