"""Unit tests for the vectorized IS/SMC subset samplers.

The exact sampler must reproduce the reference's inverse-CDF walk
(/root/reference/mplc/contributivity.py:326-439 semantics) bit-for-bit in
subset choice; the stratified sampler must stay unbiased for any probe
quality. Oracles here re-implement the reference's per-draw enumeration
directly.
"""

from itertools import combinations
from math import comb, factorial

import numpy as np
import pytest

from mplc_tpu.contrib.sampling import (ExactSubsetSampler,
                                       SizeStratifiedSubsetSampler,
                                       WithoutReplacementRanks,
                                       combination_mask_table,
                                       make_importance_sampler, randbelow,
                                       shapley_size_prob, unrank_combination)


def reference_walk(n, k, approx_increment, u):
    """The reference's per-draw power-set walk (size-asc, lexicographic)."""
    list_k = np.delete(np.arange(n), k)
    renorm = 0.0
    for length in range(len(list_k) + 1):
        for subset in combinations(list_k, length):
            renorm += shapley_size_prob(len(subset), n) * abs(
                approx_increment(subset))
    cum = 0.0
    last = ()
    for length in range(len(list_k) + 1):
        for subset in combinations(list_k, length):
            cum += shapley_size_prob(len(subset), n) * abs(
                approx_increment(subset))
            last = subset
            if cum / renorm > u:
                return np.array(subset, int), renorm
    return np.array(last, int), renorm


def test_mask_table_matches_reference_enumeration_order():
    m = 5
    masks, sizes = combination_mask_table(m)
    ref = [tuple(c) for length in range(m + 1)
           for c in combinations(range(m), length)]
    got = [tuple(np.flatnonzero(row)) for row in masks]
    assert got == ref
    assert list(sizes) == [len(s) for s in ref]


def test_unrank_combination_round_trip():
    m, length = 7, 3
    ref = list(combinations(range(m), length))
    for rank, subset in enumerate(ref):
        assert tuple(unrank_combination(m, length, rank)) == subset
    assert unrank_combination(m, 0, 0) == []


def test_exact_sampler_matches_reference_walk():
    n, k = 6, 2
    rng = np.random.default_rng(7)
    # a random positive-ish increment model keyed on subset membership
    coef = rng.normal(size=n)

    def scalar_inc(subset):
        return 0.3 + np.sum(coef[list(subset)]) if len(subset) else 0.3

    members = np.delete(np.arange(n), k)

    def batch_inc(masks):
        return 0.3 + masks @ coef[members]

    sampler = ExactSubsetSampler(n, k, batch_inc)
    for u in rng.uniform(size=50):
        want, renorm = reference_walk(n, k, scalar_inc, u)
        got, weight = sampler.draw(float(u))
        assert np.array_equal(got, want)
        assert weight == pytest.approx(renorm / abs(scalar_inc(tuple(want))),
                                       rel=1e-9)


def test_exact_sampler_distribution():
    """Empirical draw frequencies match P(|S|)·|f(S)| / renorm."""
    n, k = 4, 0
    members = np.delete(np.arange(n), k)

    def batch_inc(masks):
        return 1.0 + masks.sum(axis=1).astype(float)

    sampler = ExactSubsetSampler(n, k, batch_inc)
    rng = np.random.default_rng(0)
    counts = {}
    draws = 20000
    for u in rng.uniform(size=draws):
        s, _ = sampler.draw(float(u))
        key = tuple(int(x) for x in s)
        counts[key] = counts.get(key, 0) + 1
    for length in range(n):
        for subset in combinations(members, length):
            p = shapley_size_prob(length, n) * (1.0 + length) / sampler.renorm
            got = counts.get(tuple(subset), 0) / draws
            assert got == pytest.approx(p, abs=0.02)


def test_stratified_sampler_is_unbiased_on_additive_game():
    """E[weight * marginal] over the two-stage proposal must equal the
    Shapley value, regardless of the probe model."""
    n, k = 12, 3
    rng = np.random.default_rng(5)
    phi = rng.uniform(0.1, 1.0, size=n)

    def batch_inc(masks):
        # deliberately crude probe model: constant
        return np.ones(masks.shape[0])

    sampler = SizeStratifiedSubsetSampler(n, k, batch_inc, rng)
    est = []
    for u in rng.uniform(size=4000):
        S, weight = sampler.draw(float(u), rng)
        # additive game: marginal of k is phi[k] for every S — the estimate
        # must average to phi[k] exactly if the weights are exact
        est.append(weight * phi[k])
    # sum over sizes of p_l * weight_l = sum 1/n per size = 1 exactly
    assert np.mean(est) == pytest.approx(phi[k], rel=1e-9)


def test_stratified_sampler_weight_identity():
    """P_shapley(l)·C(n-1,l) = 1/n exactly, so p_l · weight_l = 1/n per size
    and the n sizes sum to 1 — the invariant that makes the estimator exact."""
    n, k = 15, 0
    rng = np.random.default_rng(1)
    sampler = SizeStratifiedSubsetSampler(
        n, k, lambda masks: np.ones(masks.shape[0]), rng)
    assert np.allclose(sampler._p * sampler._weight_per_size, 1.0 / n)
    assert np.sum(sampler._p * sampler._weight_per_size) == pytest.approx(1.0)


def test_make_importance_sampler_switches_modes():
    rng = np.random.default_rng(0)
    fn = lambda masks: np.ones(masks.shape[0])  # noqa: E731
    assert isinstance(make_importance_sampler(5, 0, fn, rng),
                      ExactSubsetSampler)
    assert isinstance(
        make_importance_sampler(5, 0, fn, rng, max_exact_bits=3),
        SizeStratifiedSubsetSampler)


def test_randbelow_uniform_and_in_range():
    rng = np.random.default_rng(3)
    big = comb(80, 40)  # far beyond int64
    for _ in range(100):
        assert 0 <= randbelow(rng, big) < big
    counts = np.zeros(7, int)
    for _ in range(7000):
        counts[randbelow(rng, 7)] += 1
    assert counts.min() > 800  # roughly uniform


def test_without_replacement_pool_is_exhaustive_permutation():
    rng = np.random.default_rng(2)
    pool = WithoutReplacementRanks(factorial(3) * 5 // 6)  # 5
    seen = [pool.pop_random(rng) for _ in range(len(pool) + 0)]
    while len(pool):
        seen.append(pool.pop_random(rng))
    assert sorted(seen) == list(range(5))
    with pytest.raises(IndexError):
        pool.pop_random(rng)
