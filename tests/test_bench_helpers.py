"""Unit coverage for bench.py's pure helpers — the bench is the driver's
perf contract, so its accounting and watchdog plumbing get real tests."""

import importlib
import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
bench = importlib.import_module("bench")


def test_amounts_are_normalized_and_uneven():
    for n in (3, 4, 10):
        a = bench._amounts(n)
        assert len(a) == n
        assert np.isclose(sum(a), 1.0)
    assert bench._amounts(3) == [0.4, 0.3, 0.3]
    a10 = bench._amounts(10)
    # deliberately uneven so coalition values differ between partners
    assert a10[0] < a10[-1]


def test_baseline_seconds_accounting(monkeypatch):
    monkeypatch.delenv("MPLC_TPU_SYNTH_SCALE", raising=False)
    base = bench._baseline_seconds("mnist", 50, 1)
    assert base == pytest.approx(bench.REFERENCE_MNIST_FEDAVG_SECONDS)
    # linear in epochs and in the number of coalition trainings
    assert bench._baseline_seconds("mnist", 25, 4) == pytest.approx(2 * base)
    assert bench._baseline_seconds("cifar10", 50, 1) == pytest.approx(
        bench.REFERENCE_CIFAR_FEDAVG_SECONDS)
    monkeypatch.setenv("MPLC_TPU_SYNTH_SCALE", "0.5")
    assert bench._baseline_seconds("mnist", 50, 1) == pytest.approx(base / 2)


def test_progress_callback_reports_and_beats(capsys):
    class FakeEngine:
        progress = None

    eng = bench._attach_progress(FakeEngine(), "timed")
    bench._last_beat = 0.0  # sentinel: only a real _beat() can restore it
    eng.progress(16, 100, 3)
    eng.progress(16, 84, 3)
    err = capsys.readouterr().err
    assert "timed: +16 coalitions" in err
    assert "total 32" in err
    assert bench._last_beat > 0.0, "progress callback must feed the watchdog"


@pytest.mark.slow
def test_bench_method_driver_end_to_end(capsys):
    """The configs-2..5 code path (_bench_method: warm engine -> fresh
    engine sharing device data -> compute_contributivity -> one metric
    line + throughput note), driven on the fast titanic family. Configs
    2-5 differ from this run only in dataset/model and method args."""
    bench._bench_method("titanic", 3, "TMCS", epochs=2, dtype="float32",
                        extra_methods=("Independent scores",))
    out = capsys.readouterr()
    import json
    lines = [l for l in out.out.splitlines() if l.strip().startswith("{")]
    assert len(lines) == 1, f"exactly one metric line expected: {out.out!r}"
    metric = json.loads(lines[0])
    assert metric["metric"].startswith("tmcs_titanic_3partners")
    assert metric["value"] > 0 and metric["unit"] == "s"
    assert "TMCS scores:" in out.err
    assert "throughput:" in out.err


def test_devices_deadline_returns_none_on_hang(monkeypatch):
    """A backend init that never returns yields None, not a hang."""
    monkeypatch.setenv("BENCH_INIT_TIMEOUT", "0.2")
    import threading
    hang = threading.Event()

    class FakeJax:
        @staticmethod
        def devices():
            hang.wait(5)
            return []

    monkeypatch.setitem(sys.modules, "jax", FakeJax())
    assert bench._devices_with_deadline() is None
    hang.set()


def test_fallback_env_strip_covers_workload_knobs():
    """The CPU fallback child must not inherit any workload-shaping knob;
    both the replay refusal and the env strip now iterate the ONE shared
    bench._WORKLOAD_KNOBS list (ADVICE r5 caught the eval-chunk knob
    missing from both hand-maintained copies; the shared list makes that
    class of drift impossible)."""
    import inspect
    assert "_WORKLOAD_KNOBS" in inspect.getsource(
        bench._replay_cached_tpu_result)
    assert "_WORKLOAD_KNOBS" in inspect.getsource(bench._spawn_cpu_fallback)
    for knob in ("MPLC_TPU_EVAL_CHUNK", "BENCH_DTYPE",
                 "MPLC_TPU_BATCH_CAP_CEILING",
                 "MPLC_TPU_COALITIONS_PER_DEVICE", "MPLC_TPU_NO_SLOTS",
                 "MPLC_TPU_PARTNER_SHARDS", "MPLC_TPU_PIPELINE_BATCHES",
                 "MPLC_TPU_SLOT_MERGE", "MPLC_TPU_SLOT_POW2",
                 "MPLC_TPU_STEP_WIDTH_MULT", "MPLC_TPU_SYNTH_SCALE",
                 "MPLC_TPU_PARTNER_FAULT_PLAN", "MPLC_TPU_SEED_ENSEMBLE"):
        assert knob in bench._WORKLOAD_KNOBS, \
            f"{knob} missing from bench._WORKLOAD_KNOBS"


def test_cpu_fallback_refuses_to_recurse(monkeypatch):
    """The fallback child must never spawn another fallback."""
    monkeypatch.setenv("BENCH_IS_FALLBACK_CHILD", "1")
    assert not bench._fallback_allowed()
    monkeypatch.delenv("BENCH_IS_FALLBACK_CHILD")
    monkeypatch.setenv("BENCH_CPU_FALLBACK", "0")
    assert not bench._fallback_allowed()
    monkeypatch.setenv("BENCH_CPU_FALLBACK", "1")
    assert bench._fallback_allowed()


def test_metric_suffix_labels_fallback(monkeypatch, capsys):
    import json
    monkeypatch.setenv("BENCH_METRIC_SUFFIX", "_cpu_fallback")
    bench._emit("m", 2.0, 4.0)
    rec = json.loads(capsys.readouterr().out)
    assert rec["metric"] == "m_cpu_fallback"
    assert rec["vs_baseline"] == 2.0


def test_no_baseline_emits_null_not_zero(capsys):
    import json
    bench._emit("m", 2.0, 0.0)
    rec = json.loads(capsys.readouterr().out)
    assert rec["vs_baseline"] is None
    assert bench._baseline_seconds("titanic", 8, 100) == 0.0


def test_emit_suppressed_once_watchdog_fires(capsys):
    """After the watchdog takes over, a recovered main thread must not add
    a second metric line to stdout."""
    bench._watchdog_fired.set()
    try:
        bench._emit("m", 1.0, 1.0)
        assert capsys.readouterr().out == ""
    finally:
        bench._watchdog_fired.clear()


def test_importing_bench_leaves_env_alone(monkeypatch):
    """Importing bench for its helpers (as this file does at collection
    time) must not harden the synthetic datasets for the whole pytest
    process — MPLC_TPU_SYNTH_NOISE is set inside main() only."""
    import os
    monkeypatch.delenv("MPLC_TPU_SYNTH_NOISE", raising=False)
    importlib.reload(bench)
    assert "MPLC_TPU_SYNTH_NOISE" not in os.environ


def _write_record(root, sub, metric, value=2133.0, vs=45.0, config="1",
                  **extra):
    d = root / "perf" / sub
    d.mkdir(parents=True, exist_ok=True)
    rec = {"metric": metric, "value": value, "unit": "s", "vs_baseline": vs}
    rec.update(extra)
    (d / f"config{config}.json").write_text(__import__("json").dumps(rec))
    return d / f"config{config}.json"


_ALL_REPLAY_KNOBS = (
    "BENCH_CONFIG", "BENCH_PARTNERS", "BENCH_EPOCHS", "BENCH_DATASET",
    "BENCH_METHOD", "BENCH_METRIC_SUFFIX", "BENCH_DTYPE",
    "MPLC_TPU_SYNTH_SCALE", "MPLC_TPU_SLOT_POW2", "MPLC_TPU_SLOT_MERGE",
    "MPLC_TPU_BATCH_CAP_CEILING", "MPLC_TPU_NO_SLOTS",
    "MPLC_TPU_PARTNER_SHARDS", "MPLC_TPU_COALITIONS_PER_DEVICE",
    "MPLC_TPU_EVAL_CHUNK", "MPLC_TPU_PIPELINE_BATCHES",
    "MPLC_TPU_STEP_WIDTH_MULT", "MPLC_TPU_PARTNER_FAULT_PLAN",
    "MPLC_TPU_SEED_ENSEMBLE")


def _clean_replay_env(monkeypatch):
    for knob in _ALL_REPLAY_KNOBS:
        monkeypatch.delenv(knob, raising=False)


def test_replay_emits_newest_valid_record(tmp_path, monkeypatch, capsys):
    """Tunnel-down replay: the newest real TPU config1 record is re-emitted
    with an explicit _cached suffix; fallback and already-cached records
    are never replayed."""
    import json
    import os
    import time

    _clean_replay_env(monkeypatch)
    old = _write_record(tmp_path, "r4",
                        "exact_shapley_mnist_10partners_8epochs_wallclock",
                        value=2133.283, vs=45.192)
    new = _write_record(tmp_path, "r5",
                        "exact_shapley_mnist_10partners_8epochs_wallclock",
                        value=1999.0, vs=48.0)
    _write_record(tmp_path, "r3",
                  "exact_shapley_mnist_10partners_8epochs_wallclock_cpu_fallback",
                  value=0.02, vs=None)
    now = time.time()
    os.utime(old, (now - 100, now - 100))
    os.utime(new, (now, now))

    assert bench._replay_cached_tpu_result(str(tmp_path)) is True
    out = capsys.readouterr().out.strip()
    rec = json.loads(out)
    assert rec["metric"].endswith("_cached")
    assert rec["value"] == 1999.0      # the newest record wins
    assert rec["vs_baseline"] == 48.0


def test_replay_refuses_nondefault_workloads(tmp_path, monkeypatch, capsys):
    """Any workload-shaping env (different epochs, synth scale, pow2...)
    makes the cached full-scale record a DIFFERENT workload: no replay."""
    _write_record(tmp_path, "r5",
                  "exact_shapley_mnist_10partners_8epochs_wallclock")
    _clean_replay_env(monkeypatch)
    for knob, bad in (("BENCH_EPOCHS", "2"), ("BENCH_CONFIG", "7"),
                      ("BENCH_PARTNERS", "6"), ("BENCH_DATASET", "titanic"),
                      ("MPLC_TPU_SYNTH_SCALE", "0.25"),
                      ("MPLC_TPU_SLOT_POW2", "1"), ("BENCH_DTYPE", "float32"),
                      # the eval-chunk knob reshapes the compiled eval
                      # program + the memory-derived batch cap: a cached
                      # default-workload number must not be replayed for it
                      ("MPLC_TPU_EVAL_CHUNK", "1024"),
                      # opting OUT of the defaults is also a different
                      # workload: the sequential-harvest and per-size
                      # bucketing engines run other programs/schedules
                      ("MPLC_TPU_PIPELINE_BATCHES", "0"),
                      ("MPLC_TPU_SLOT_MERGE", "0"),
                      ("MPLC_TPU_BATCH_CAP_CEILING", "32"),
                      # the wide-step deviation mode trains a DIFFERENT
                      # trajectory even at its parity value when set —
                      # any SET value refuses, like the other knobs
                      ("MPLC_TPU_STEP_WIDTH_MULT", "2"),
                      ("MPLC_TPU_STEP_WIDTH_MULT", "1"),
                      ("BENCH_METRIC_SUFFIX", "_x")):
        monkeypatch.setenv(knob, bad)
        assert bench._replay_cached_tpu_result(str(tmp_path)) is False, knob
        monkeypatch.delenv(knob)
    assert capsys.readouterr().out.strip() == ""


def test_replay_skips_malformed_records(tmp_path, monkeypatch, capsys):
    """Truncated/hand-edited records (missing value/unit, bad JSON) are
    skipped rather than crashing the fallback path."""
    import json

    # the tests' conftest sets MPLC_TPU_SYNTH_SCALE ambiently — the
    # gate must see the driver's clean default env here
    _clean_replay_env(monkeypatch)
    d = tmp_path / "perf" / "r5"
    d.mkdir(parents=True)
    (d / "config1.json").write_text(
        '{"metric": "exact_shapley_mnist_10partners_8epochs_wallclock"}')
    assert bench._replay_cached_tpu_result(str(tmp_path)) is False
    (d / "config1.json").write_text("{not json")
    assert bench._replay_cached_tpu_result(str(tmp_path)) is False
    # a valid record alongside still wins
    _write_record(tmp_path, "r6",
                  "exact_shapley_mnist_10partners_8epochs_wallclock")
    assert bench._replay_cached_tpu_result(str(tmp_path)) is True
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["metric"].endswith("_cached")


def test_replay_accepts_config_2_to_5_shapes(tmp_path, monkeypatch, capsys):
    """The cached-replay gate covers every driver config, not just the
    north star: a default-shaped config-N run replays the newest real TPU
    config<N>.json record whose metric matches that config's workload."""
    import json

    shapes = {"2": "tmcs_cifar10_5partners_8epochs_wallclock",
              "3": "is_lin_s_mnist_10partners_8epochs_wallclock",
              "4": "smcs_imdb_4partners_8epochs_wallclock",
              "5": "tmcs_cifar10_8partners_8epochs_wallclock"}
    for cfg, metric in shapes.items():
        _clean_replay_env(monkeypatch)
        _write_record(tmp_path, "r5", metric, value=100.0 + float(cfg),
                      vs=10.0, config=cfg)
        monkeypatch.setenv("BENCH_CONFIG", cfg)
        assert bench._replay_cached_tpu_result(str(tmp_path)) is True, cfg
        rec = json.loads(capsys.readouterr().out.strip())
        assert rec["metric"] == metric + "_cached"
        assert rec["value"] == 100.0 + float(cfg)


def test_replay_config_shapes_refuse_cross_config_and_method(
        tmp_path, monkeypatch, capsys):
    """Strictness parity with the config-1 gate: a config-2 record never
    replays for a config-3 run (per-config file + metric prefix), ANY set
    BENCH_METHOD refuses for configs 2-5 (a method change is a different
    workload, even re-stating the default), and the workload-knob refusal
    applies identically."""
    _clean_replay_env(monkeypatch)
    _write_record(tmp_path, "r5", "tmcs_cifar10_5partners_8epochs_wallclock",
                  config="2")
    # config 3 must not pick up the config-2 record
    monkeypatch.setenv("BENCH_CONFIG", "3")
    assert bench._replay_cached_tpu_result(str(tmp_path)) is False
    # a config-2 record whose metric is another workload's is skipped too
    _write_record(tmp_path, "r6", "is_reg_s_mnist_10partners_8epochs_wallclock",
                  config="3")
    assert bench._replay_cached_tpu_result(str(tmp_path)) is False

    monkeypatch.setenv("BENCH_CONFIG", "2")
    assert bench._replay_cached_tpu_result(str(tmp_path)) is True
    capsys.readouterr()
    for knob, bad in (("BENCH_METHOD", "TMCS"),   # even the default refuses
                      ("BENCH_METHOD", "ITMCS"),
                      ("BENCH_EPOCHS", "2"),
                      ("MPLC_TPU_STEP_WIDTH_MULT", "2"),
                      ("MPLC_TPU_SLOT_MERGE", "0")):
        monkeypatch.setenv(knob, bad)
        assert bench._replay_cached_tpu_result(str(tmp_path)) is False, knob
        monkeypatch.delenv(knob)
    assert capsys.readouterr().out.strip() == ""
