"""Retrain-free estimators: GTG-Shapley reconstruction + SVARM sampling.

The contract under test (contrib/reconstruct.py + the GTG-Shapley/SVARM
methods in contrib/contributivity.py):

1. **Recording fidelity.** One grand-coalition run with
   `TrainConfig.record_updates` captures per-round per-partner deltas and
   weights such that replaying ALL of them reproduces the trained
   grand-coalition model — v(N) reconstructed == v(N) trained,
   bit-identical (the reconstruction scan applies exactly the recorded
   aggregations).
2. **Zero coalition training passes.** A 10-partner GTG-Shapley run pays
   training work ONLY for the single recording run:
   `engine.partner_passes` == P x epochs x minibatches, every other
   `engine.batch` event is `eval_only` with zero epochs/passes, and the
   eval batches ride the SAME merged slot buckets as a trained sweep.
3. **Estimator quality (fixed-seed 4-partner pin).** GTG-Shapley and
   SVARM scores rank-agree with the exact retrained Shapley values
   (`shapley.kendall_tau >= 0.8`) and each method's scores land inside
   its own PR-6-style trust confidence intervals.
4. **Fault ladder.** Both methods survive MPLC_TPU_FAULT_PLAN
   transient/OOM injection bit-identically to fault-free runs (the PR-4
   invariant extends to eval-only reconstruction batches).
5. **Guards & satellites.** record_updates x 2-D / slot / seq guards
   fail fast; the MPLC_TPU_COMPILE_CACHE_DIR program bank persists
   executables (even configured after a prior compile); per-method memo
   attribution reaches counters and the sweep report.

Estimator *arithmetic* is additionally pinned on analytic games (no
training at all) by pre-seating `engine._reconstruction` with a stub —
the documented test seam.
"""

import types

import numpy as np
import pytest

from helpers import build_scenario, cluster_mlp_dataset
from mplc_tpu.contrib.contributivity import Contributivity
from mplc_tpu.contrib.shapley import (kendall_tau, powerset_order,
                                      shapley_from_characteristic)
from mplc_tpu.mpl.engine import TrainConfig
from mplc_tpu.obs import metrics
from mplc_tpu.obs import trace as obs_trace
from mplc_tpu.obs.report import format_report, sweep_report

from test_contrib import fake_scenario


# ---------------------------------------------------------------------------
# shared scenarios (module-scoped: one recording run each)
# ---------------------------------------------------------------------------

def _scenario_4p():
    """4 partners with a strict quality ordering (one fully glabel-
    corrupted partner + graded data amounts) so rank agreement is a real
    assertion, not a tie."""
    return build_scenario(
        partners_count=4, amounts_per_partner=[0.05, 0.12, 0.28, 0.55],
        dataset=cluster_mlp_dataset(n=480, seed=11, scale=1.0),
        epoch_count=3, minibatch_count=2,
        samples_split_option=["basic", "random"],
        corrupted_datasets=[("glabel", 1.0), "not_corrupted",
                            "not_corrupted", "not_corrupted"])


@pytest.fixture(scope="module")
def scen4():
    sc = _scenario_4p()
    c = Contributivity(sc)
    c.compute_SV()
    return sc, np.array(c.contributivity_scores)


@pytest.fixture(scope="module")
def gtg10():
    """One 10-partner GTG-Shapley run with metrics + trace collected —
    the counter-asserted asymptotic-win evidence, shared by the
    zero-training-pass, bucket-riding, and report-row tests."""
    sc = build_scenario(
        partners_count=10, amounts_per_partner=[0.1] * 10,
        dataset=cluster_mlp_dataset(n=600, seed=7, scale=1.0),
        epoch_count=2, minibatch_count=2,
        samples_split_option=["basic", "random"])
    metrics.reset()
    with obs_trace.collect() as records:
        c = Contributivity(sc)
        c.GTG_Shapley(sv_accuracy=1.0, min_iter=16, perm_batch=8)
    return sc, c, list(records), metrics.snapshot()


# ---------------------------------------------------------------------------
# 1. recording fidelity
# ---------------------------------------------------------------------------

def test_recording_reproduces_grand_coalition():
    sc = build_scenario(
        partners_count=3, amounts_per_partner=[0.2, 0.3, 0.5],
        dataset=cluster_mlp_dataset(n=300, seed=5, scale=1.0),
        epoch_count=2, minibatch_count=2)
    c = Contributivity(sc)
    full = (0, 1, 2)
    v_trained = float(c.engine.evaluate([full])[0])
    recon = c._reconstructor()
    # replaying every recorded round over the full mask applies the same
    # aggregations the recording run applied, but as a different float
    # expression (g + sum(w~ * (p - g)) with renormalized weights vs
    # sum(w * p)) — equal to rounding, not guaranteed bit-equal, so the
    # accuracy must match to tight tolerance (it is exactly equal on the
    # CPU-float32 tier in practice)
    assert abs(float(recon.evaluate([full])[0]) - v_trained) < 1e-6
    rec = recon.recorded
    assert rec.partners_count == 3
    assert rec.rounds == 2 * 2 and rec.epochs_done == 2
    assert rec.training_passes == 3 * 2 * 2
    assert rec.memory_bytes > 0
    import jax
    for leaf in jax.tree_util.tree_leaves(rec.deltas):
        assert leaf.shape[:2] == (rec.rounds, 3)
    assert rec.weights.shape == (rec.rounds, 3)
    # reconstructed values live in their own memo, never the exact one
    assert full in recon.values
    assert len(c.engine.charac_fct_values) == 2  # () and the trained v(N)


# ---------------------------------------------------------------------------
# 2/3. fixed-seed 4-partner regression: rank agreement + trust CIs
# ---------------------------------------------------------------------------

def _assert_inside_own_ci(scores, trust):
    lo = np.asarray(trust["ci_low"])
    hi = np.asarray(trust["ci_high"])
    assert np.all(scores >= lo - 1e-9) and np.all(scores <= hi + 1e-9)


def test_gtg_rank_agreement_and_trust(scen4):
    sc, exact = scen4
    c = Contributivity(sc)
    c.GTG_Shapley(sv_accuracy=1.0, min_iter=800, perm_batch=16,
                  truncation=0.02)
    gtg = np.array(c.contributivity_scores)
    assert kendall_tau(exact, gtg) >= 0.8
    assert c.trust is not None
    assert set(c.trust) >= {"ensemble", "mean", "std", "ci_low",
                            "ci_high", "kendall_tau"}
    # MC pseudo-replica rows are tagged so they can't impersonate a
    # seed-ensemble trust row in the report/sidecar
    assert c.trust["source"] == "mc_blocks"
    assert c.trust["method"] == "GTG-Shapley"
    _assert_inside_own_ci(gtg, c.trust)


def test_svarm_rank_agreement_and_trust(scen4):
    sc, exact = scen4
    c = Contributivity(sc)
    c.SVARM(budget=640)  # 640 coalitions = 320 (A+, A-) pair draws
    sv = np.array(c.contributivity_scores)
    assert kendall_tau(exact, sv) >= 0.8
    assert c.trust is not None
    assert c.trust["source"] == "mc_blocks"
    assert c.trust["method"] == "SVARM"
    _assert_inside_own_ci(sv, c.trust)
    # SVARM's strata means converge to the reconstructed game's exact
    # Shapley — tie the sampler to its own ground truth, not just ranks
    recon = c._reconstructor()
    recon.evaluate(list(powerset_order(4)))
    recon_exact = np.array(shapley_from_characteristic(4, recon.values))
    assert np.all(np.abs(sv - recon_exact) < 0.15)


# ---------------------------------------------------------------------------
# 4. the asymptotic win, counter-asserted at 10 partners
# ---------------------------------------------------------------------------

def test_gtg_10p_zero_coalition_training_passes(gtg10):
    sc, c, records, snap = gtg10
    P, E, MB = 10, 2, 2
    passes = snap["counters"].get("engine.partner_passes", 0)
    # training passes come from the ONE recording run and nothing else:
    # P x epochs x minibatch partner passes total — vs ~2^P x that for
    # the exact sweep (the issue's O(2^P x P x epochs) bound)
    assert passes == P * E * MB
    assert snap["counters"].get("engine.epochs_trained") == E
    exact_sweep_passes = sum(
        __import__("math").comb(P, k) * min(k, 10) for k in range(1, P + 1)
    ) * E * MB
    assert passes * 50 < exact_sweep_passes
    batch_events = [r for r in records if r["name"] == "engine.batch"]
    recording = [r for r in batch_events if r["attrs"].get("recording")]
    evals = [r for r in batch_events if r["attrs"].get("eval_only")]
    assert len(recording) == 1
    assert recording[0]["attrs"]["partner_passes"] == passes
    assert len(evals) >= 1
    assert len(recording) + len(evals) == len(batch_events)
    for r in evals:
        assert r["attrs"]["epochs"] == 0
        assert r["attrs"]["partner_passes"] == 0
        assert r["attrs"]["samples"] == 0
    assert snap["counters"].get("engine.reconstructions", 0) >= 1


def test_reconstruction_rides_merged_slot_buckets(gtg10):
    sc, c, records, snap = gtg10
    eng = sc._charac_engine
    # every multi-partner eval batch's slot_count is one of the engine's
    # MERGED bucket widths (the same program family a trained sweep
    # compiles); singles ride the slot-less singles program (None)
    merged_widths = {eng._slot_width(k) for k in range(2, 11)}
    evals = [r for r in records if r["name"] == "engine.batch"
             and r["attrs"].get("eval_only")]
    multi_widths = {r["attrs"]["slot_count"] for r in evals
                    if r["attrs"]["slot_count"] is not None}
    assert multi_widths and multi_widths <= merged_widths


def test_reconstruction_report_row_and_memo_attribution(gtg10):
    sc, c, records, snap = gtg10
    rep = sweep_report(records, snap)
    rc = rep["reconstruction"]
    assert rc["recorded_partners"] == 10
    assert rc["recorded_rounds"] == 4
    assert rc["recorded_update_bytes"] > 0
    assert rc["recording_partner_passes"] == 40
    assert rc["train_partner_passes"] == 40       # recording run only
    assert rc["train_batches"] == 1
    assert rc["recon_batches"] >= 1
    assert rc["reconstructions"] >= 1
    assert rc["reconstructions_per_s"] is None or \
        rc["reconstructions_per_s"] > 0
    txt = format_report(rep)
    assert "reconstruct" in txt and "passes train/eval=40/0" in txt
    # per-method memo attribution (satellite): counters keyed by the
    # active estimator method, and a per_method row in the report memo
    assert "engine.memo_hits[GTG-Shapley]" in snap["counters"]
    assert "engine.memo_misses[GTG-Shapley]" in snap["counters"]
    pm = rep["memo"]["per_method"]["GTG-Shapley"]
    assert pm["requested"] == pm["hits"] + pm["misses"]
    assert pm["hits"] > 0   # permutation prefixes repeat across rounds


def test_per_method_memo_row_schema():
    # old (method-less) record streams keep the exact old memo schema
    recs = [{"name": "engine.evaluate", "dur": 0.1,
             "attrs": {"requested": 4, "missing": 2}}]
    assert "per_method" not in sweep_report(recs)["memo"]
    recs[0]["attrs"]["method"] = "SVARM"
    rep = sweep_report(recs)
    assert rep["memo"]["per_method"] == {
        "SVARM": {"requested": 4, "hits": 2, "misses": 2, "hit_rate": 0.5}}


# ---------------------------------------------------------------------------
# 5. fault-injection ladder: recovered == fault-free, bit-identically
# ---------------------------------------------------------------------------

def _small_scenario():
    return build_scenario(
        partners_count=3, amounts_per_partner=[0.2, 0.3, 0.5],
        dataset=cluster_mlp_dataset(n=240, seed=9, scale=1.0),
        epoch_count=2, minibatch_count=2)


def _run_method(method):
    sc = _small_scenario()
    c = Contributivity(sc)
    if method == "GTG-Shapley":
        c.GTG_Shapley(sv_accuracy=1.0, min_iter=16, perm_batch=8)
    else:
        c.SVARM(budget=48, block=16)
    return np.array(c.contributivity_scores)


@pytest.mark.parametrize("method", ["GTG-Shapley", "SVARM"])
@pytest.mark.parametrize("plan,expect", [
    # batch 1 is the recording run's dispatch; batch 2+ are eval batches
    ("transient@batch1,transient@batch3", "engine.retries"),
    ("oom@batch2", "engine.cap_halvings"),
])
def test_fault_ladder_bit_identical(monkeypatch, method, plan, expect):
    monkeypatch.delenv("MPLC_TPU_FAULT_PLAN", raising=False)
    clean = _run_method(method)
    metrics.reset()
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", plan)
    monkeypatch.setenv("MPLC_TPU_RETRY_BACKOFF_SEC", "0")
    faulted = _run_method(method)
    snap = metrics.snapshot()
    assert snap["counters"].get("engine.faults_injected", 0) >= 1
    assert snap["counters"].get(expect, 0) >= 1
    np.testing.assert_array_equal(clean, faulted)


def test_forever_dropped_null_player(monkeypatch):
    """The engine's exact-null-player rule reaches the reconstructor: an
    all-dropped coalition scores v = 0 (not the untrained init model's
    chance accuracy), and a dropped member's zero-weight rows renormalize
    away bit-identically to the partner-excluded coalition."""
    monkeypatch.setenv("MPLC_TPU_PARTNER_FAULT_PLAN", "dropout@p0:epoch1")
    sc = _small_scenario()
    c = Contributivity(sc)
    recon = c._reconstructor()
    v = recon.evaluate([(0,), (0, 1), (1,), (0, 1, 2), (1, 2)])
    assert v[0] == 0.0
    assert float(c.engine.evaluate([(0,)])[0]) == 0.0  # engine agrees
    assert v[1] == v[2]
    assert v[3] == v[4]


def test_seed_ensemble_trust_row_tagged():
    from mplc_tpu.contrib.shapley import trust_summary
    t = trust_summary(2, {(): np.zeros(3), (0,): np.full(3, .2),
                          (1,): np.full(3, .3), (0, 1): np.full(3, .6)})
    assert t["source"] == "seed_ensemble"


def test_cpu_rung_oom_propagates(monkeypatch):
    """An OOM raised on the terminal CPU rung must PROPAGATE (matching
    the engine's _run_groups_cpu), not re-enter the degrade ladder and
    livelock re-dispatching the same width-1 CPU batch forever."""
    from mplc_tpu import faults
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN",
                       "oom@batch2,oom@batch3,oom@batch4")
    monkeypatch.setenv("MPLC_TPU_MAX_CAP_HALVINGS", "1")
    monkeypatch.setenv("MPLC_TPU_RETRY_BACKOFF_SEC", "0")
    sc = _small_scenario()
    c = Contributivity(sc)
    recon = c._reconstructor()  # ordinal 1 = the recording run
    assert c.engine._max_cap_halvings == 1
    # ordinals 2+3: device dispatch OOMs exhaust the 1-rung ladder ->
    # CPU rung; ordinal 4: the CPU re-dispatch OOMs -> must raise
    with pytest.raises(Exception) as ei:
        recon.evaluate([(0, 1), (0, 2), (1, 2), (0, 1, 2)])
    assert faults.is_oom(ei.value)
    assert c.engine._cpu_degraded


# ---------------------------------------------------------------------------
# 6. guards: record_updates x slot/seq/2-D fails fast
# ---------------------------------------------------------------------------

def test_record_updates_config_guards():
    base = dict(minibatch_count=2, epoch_count=2,
                gradient_updates_per_pass=2)
    with pytest.raises(ValueError, match="fedavg"):
        TrainConfig(approach="seqavg", record_updates=True, **base)
    with pytest.raises(ValueError, match="slot"):
        TrainConfig(approach="fedavg", record_updates=True, slot_count=2,
                    **base)
    with pytest.raises(ValueError, match="2-D|partner-axis"):
        TrainConfig(approach="fedavg", record_updates=True,
                    partner_axis="partners", **base)


def test_method_span_not_leaked_on_reconstructor_failure():
    """A failing _reconstructor() must not leave the 'contributivity'
    method span open — a leaked span would mis-attribute every later
    method's memo counters via active_span."""
    sc = fake_scenario(3, lambda s: 0.5)
    sc._charac_engine._pipe2d = object()  # trips the 2-D guard
    c = Contributivity(sc)
    for call in (c.GTG_Shapley, c.SVARM):
        with pytest.raises(ValueError, match="2-D"):
            call()
        assert obs_trace.active_span("contributivity") is None


def test_svarm_env_budget_zero_is_silent_auto(monkeypatch):
    phi = [0.2, 0.3, 0.5]
    sc = _analytic(3, lambda s: sum(phi[i] for i in s))
    monkeypatch.setenv("MPLC_TPU_SVARM_SAMPLES", "0")
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # the documented auto value: no warn
        c = Contributivity(sc)
        c.SVARM(block=64)
    # auto budget (128 coalitions): MC-converged, not exact
    np.testing.assert_allclose(c.contributivity_scores, phi, atol=0.02)


def test_record_updates_2d_engine_guard():
    from mplc_tpu.contrib import reconstruct
    eng = types.SimpleNamespace(_pipe2d=object())
    with pytest.raises(ValueError, match="2-D"):
        reconstruct.record_updates(eng)
    with pytest.raises(ValueError, match="2-D"):
        reconstruct.ReconstructionEvaluator(eng)


# ---------------------------------------------------------------------------
# 7. estimator arithmetic on analytic games (no training at all)
# ---------------------------------------------------------------------------

class _StubRecon:
    """The documented `engine._reconstruction` test seam: a closed-form
    reconstructed game."""

    def __init__(self, fn):
        self.values = {(): 0.0}
        self._fn = fn

    def evaluate(self, subsets):
        keys = [tuple(sorted(int(i) for i in s)) for s in subsets]
        for k in keys:
            if k not in self.values:
                self.values[k] = float(self._fn(k))
        return np.array([self.values[k] for k in keys])


def _analytic(n, fn):
    sc = fake_scenario(n, fn)
    sc._charac_engine._reconstruction = _StubRecon(fn)
    return sc


def test_gtg_additive_game_is_exact():
    phi = [0.05, 0.10, 0.25, 0.40]
    sc = _analytic(4, lambda s: sum(phi[i] for i in s))
    c = Contributivity(sc)
    c.GTG_Shapley(sv_accuracy=1.0, min_iter=32, perm_batch=16,
                  truncation=0.0)
    # additive game: every permutation's marginal IS the partner value
    np.testing.assert_allclose(c.contributivity_scores, phi, atol=1e-12)


def test_gtg_svarm_converge_on_saturating_game():
    phi = [0.05, 0.10, 0.25, 0.40]
    fn = lambda s: min(1.0, 1.3 * sum(phi[i] for i in s))  # noqa: E731
    table = {(): 0.0}
    for s in powerset_order(4):
        table[s] = fn(s)
    exact = np.array(shapley_from_characteristic(4, table))
    c = Contributivity(_analytic(4, fn))
    c.GTG_Shapley(sv_accuracy=1.0, min_iter=400, perm_batch=16,
                  truncation=0.0)
    np.testing.assert_allclose(c.contributivity_scores, exact, atol=0.02)
    c2 = Contributivity(_analytic(4, fn))
    c2.SVARM(budget=2000)
    np.testing.assert_allclose(c2.contributivity_scores, exact, atol=0.02)


def test_svarm_exact_anchor_strata():
    # n=2: every stratum is an exact anchor, so SVARM is exact with ANY
    # budget — phi_i = (v({i}) + v(N) - v({j})) / 2
    vals = {(0,): 0.3, (1,): 0.5, (0, 1): 0.9}
    sc = _analytic(2, lambda s: vals[tuple(sorted(s))])
    c = Contributivity(sc)
    c.SVARM(budget=4, block=2)
    np.testing.assert_allclose(c.contributivity_scores,
                               [(0.3 + 0.9 - 0.5) / 2,
                                (0.5 + 0.9 - 0.3) / 2], atol=1e-12)


def test_gtg_env_truncation_knob(monkeypatch):
    phi = [0.2, 0.3, 0.5]
    sc = _analytic(3, lambda s: sum(phi[i] for i in s))
    monkeypatch.setenv("MPLC_TPU_GTG_TRUNCATION", "999")
    c = Contributivity(sc)
    c.GTG_Shapley(sv_accuracy=1.0, min_iter=8, perm_batch=8)
    # a huge threshold truncates EVERY position: all marginals collapse
    # to zero except none get past |v(N) - 0| >= 999 — scores all zero
    np.testing.assert_allclose(c.contributivity_scores, 0.0, atol=1e-12)


def test_svarm_env_budget_knob(monkeypatch):
    calls = []
    phi = [0.2, 0.3, 0.5]
    sc = _analytic(3, lambda s: sum(phi[i] for i in s))
    recon = sc._charac_engine._reconstruction
    orig = recon.evaluate
    recon.evaluate = lambda s: (calls.append(len(s)), orig(s))[1]
    monkeypatch.setenv("MPLC_TPU_SVARM_SAMPLES", "16")
    c = Contributivity(sc)
    c.SVARM(block=8)
    # anchors (1 + 3 + 3) + warm-up (6) + 2 blocks of 8 pair-draws:
    # the env budget bounds the sampled phase
    assert sum(calls) <= 1 + 6 + 6 + 2 * 16 + 4


# ---------------------------------------------------------------------------
# 8. persistent compile cache (MPLC_TPU_COMPILE_CACHE_DIR program bank)
# ---------------------------------------------------------------------------

def test_compile_cache_env(tmp_path, monkeypatch):
    import jax
    import jax.numpy as jnp

    from mplc_tpu import utils
    bank = tmp_path / "bank"
    monkeypatch.setenv("MPLC_TPU_COMPILE_CACHE_DIR", str(bank))
    try:
        assert utils.enable_compile_cache_from_env() == str(bank)
        # idempotent re-entry with an unchanged env
        assert utils.enable_compile_cache_from_env() == str(bank)
        # the bank captures programs even though this test process has
        # compiled plenty before the knob was read (the late-config case)
        f = jax.jit(lambda x: x * 2.5 + jnp.sin(x) * jnp.cos(x))
        f(jnp.arange(11.0)).block_until_ready()
        assert utils.compile_cache_entries(str(bank)) >= 1
    finally:
        jax.config.update("jax_compilation_cache_dir", None)
        utils._COMPILE_CACHE_CONFIGURED["dir"] = None
    assert utils.compile_cache_entries(None) is None
    assert utils.compile_cache_entries(str(tmp_path / "missing")) is None


def test_compile_cache_bad_path_warns(tmp_path, monkeypatch):
    from mplc_tpu import utils
    blocker = tmp_path / "file"
    blocker.write_text("x")
    monkeypatch.setenv("MPLC_TPU_COMPILE_CACHE_DIR",
                       str(blocker / "nested"))
    with pytest.warns(UserWarning, match="persistent compile cache"):
        assert utils.enable_compile_cache_from_env() is None


def test_compile_cache_unset_noop(monkeypatch):
    from mplc_tpu import utils
    monkeypatch.delenv("MPLC_TPU_COMPILE_CACHE_DIR", raising=False)
    assert utils.enable_compile_cache_from_env() is None
