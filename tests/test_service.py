"""The sweep service's fault matrix (mplc_tpu/service/).

Governing invariants, asserted throughout:

  - ISOLATION: faults attributable to tenant A's job (injected crash,
    OOM, transient, stall) never abort tenant B's job or perturb its
    values — B's v(S) table is BIT-IDENTICAL to a solo run of the same
    scenario on a private engine.
  - RECOVERY: a killed service restarts on its journal, quarantines a
    torn tail record, and completes every in-flight sweep bit-identically
    to an uninterrupted run.
  - PACKING: a two-tenant run of the same game shape compiles no more
    slot programs than the larger tenant alone would (program-bank hits
    asserted) and counts cross-tenant packed batches.
"""

import json
import os
import time
import warnings

import numpy as np
import pytest

from mplc_tpu import faults
from mplc_tpu.contrib.engine import CharacteristicEngine
from mplc_tpu.contrib.shapley import powerset_order
from mplc_tpu.obs import metrics, report, trace
from mplc_tpu.service import (JobCancelled, JobQuarantined,
                              JournalCorruptError, ServiceOverloaded,
                              ServiceRejected, SweepJob, SweepJournal,
                              SweepService)

P = 3
SUBSETS = powerset_order(P)

_SERVICE_KNOBS = ("MPLC_TPU_SERVICE_FAULT_PLAN",
                  "MPLC_TPU_SERVICE_MAX_PENDING", "MPLC_TPU_SERVICE_SLICE",
                  "MPLC_TPU_FAULT_PLAN", "MPLC_TPU_MAX_RETRIES",
                  "MPLC_TPU_MAX_CAP_HALVINGS", "MPLC_TPU_SEED_ENSEMBLE",
                  "MPLC_TPU_PARTNER_FAULT_PLAN")


@pytest.fixture(autouse=True)
def _service_env(monkeypatch):
    for k in _SERVICE_KNOBS:
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("MPLC_TPU_RETRY_BACKOFF_SEC", "0")
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "1")
    metrics.reset()
    yield
    metrics.reset()


def scenario(seed):
    from helpers import build_scenario
    return build_scenario(partners_count=P, dataset_name="titanic",
                          epoch_count=2, gradient_updates_per_pass_count=2,
                          seed=seed)


_REF = {}


def solo_values(seed):
    """Fault-free solo-engine v(S) for `scenario(seed)`, cached per
    process (the autouse fixture guarantees a clean env here)."""
    assert "MPLC_TPU_SERVICE_FAULT_PLAN" not in os.environ
    if seed not in _REF:
        _REF[seed] = CharacteristicEngine(scenario(seed)).evaluate(SUBSETS)
    return _REF[seed]


def values_of(job):
    return np.array([job.engine.charac_fct_values[s] for s in SUBSETS])


def _counter(name):
    return metrics.snapshot()["counters"].get(name, 0)


# -- service fault-plan grammar ---------------------------------------------

def test_service_plan_grammar():
    plan = faults.parse_service_fault_plan(
        "crash@job2:batch3, oom@job2:batch3,transient@job1:batch1,"
        "reject@job4,stall@job1:sec2.5")
    assert plan[2]["batch"] == {("dispatch", 3): ["crash", "oom"]}
    assert plan[1]["batch"] == {("dispatch", 1): ["transient"]}
    assert plan[1]["stall_sec"] == 2.5
    assert plan[4]["reject"] is True
    assert faults.parse_service_fault_plan(None) == {}
    assert faults.parse_service_fault_plan("") == {}


def test_service_plan_malformed_entries_warn_and_skip():
    with pytest.warns(UserWarning, match="malformed"):
        plan = faults.parse_service_fault_plan(
            "crash@job2,stall@job1,bogus@job3:batch1,crash@job1:batch2")
    assert list(plan) == [1]
    assert plan[1]["batch"] == {("dispatch", 2): ["crash"]}
    with pytest.warns(UserWarning, match="1-based"):
        assert faults.parse_service_fault_plan("crash@job0:batch1") == {}


# -- the happy path: multi-tenant bit-identity + packing ---------------------

def test_two_tenants_bit_identical_to_solo_and_packed(monkeypatch):
    """The acceptance pair: both tenants' values bit-identical to solo
    runs, cross-tenant packing observed (> 0 packed batches), and the
    service compiles no more slot programs than one tenant alone would
    (the second tenant's buckets are bank hits)."""
    ref_a, ref_b = solo_values(9), solo_values(11)
    hits0 = _counter("bank.hits")
    with trace.collect() as recs:
        svc = SweepService(start=False, slice_coalitions=3)
        ja = svc.submit(scenario(9), tenant="A")
        jb = svc.submit(scenario(11), tenant="B")
        svc.run_until_idle()
    assert ja.status == jb.status == "completed"
    np.testing.assert_array_equal(values_of(ja), ref_a)
    np.testing.assert_array_equal(values_of(jb), ref_b)
    # packing is real and observed
    assert _counter("service.cross_tenant_packed_batches") > 0
    # ... and cheap: the service region compiled exactly one tenant's
    # program set (singles + the merged slot bucket), not two
    one_tenant_programs = len(
        CharacteristicEngine(scenario(9)).sweep_plan(SUBSETS))
    bank_compiles = [r for r in recs if r["name"] == "bank.compile"]
    assert len(bank_compiles) <= one_tenant_programs
    assert _counter("bank.hits") > hits0
    # the sweep report carries the service row with fair-share cost
    rep = report.sweep_report(recs)
    svc_row = rep["service"]
    assert svc_row["jobs"] == 2 and svc_row["completed"] == 2
    assert svc_row["cross_tenant_packed_batches"] > 0
    shares = [t["cost_share"] for t in svc_row["per_tenant"].values()]
    assert len(shares) == 2 and abs(sum(shares) - 1.0) < 1e-9
    text = report.format_report(rep)
    assert "service     jobs=2" in text and "tenant[A]" in text


def test_exact_shapley_scores_match_solo_table():
    from mplc_tpu.contrib.shapley import shapley_from_characteristic

    svc = SweepService(start=False)
    job = svc.submit(scenario(9), tenant="A")
    svc.run_until_idle()
    vals = {(): 0.0}
    vals.update({s: v for s, v in zip(SUBSETS, solo_values(9))})
    np.testing.assert_array_equal(
        job.result(1.0), shapley_from_characteristic(P, vals))


def test_stream_yields_every_value_incrementally():
    svc = SweepService(start=False, slice_coalitions=2)
    job = svc.submit(scenario(9), tenant="A")
    svc.run_until_idle()
    got = dict(job.stream(timeout=5))
    assert set(got) == set(SUBSETS)
    np.testing.assert_array_equal(
        np.array([got[s] for s in SUBSETS]), solo_values(9))


def test_threaded_service_completes_and_drains():
    svc = SweepService(start=True, slice_coalitions=4)
    ja = svc.submit(scenario(9), tenant="A")
    jb = svc.submit(scenario(11), tenant="B")
    np.testing.assert_array_equal(
        ja.result(timeout=300), ja.result(timeout=1))
    jb.result(timeout=300)
    svc.shutdown(drain=True, timeout=60)
    np.testing.assert_array_equal(values_of(ja), solo_values(9))
    np.testing.assert_array_equal(values_of(jb), solo_values(11))
    with pytest.raises(Exception, match="shut down"):
        svc.submit(scenario(9))


def test_estimator_method_job_matches_solo_run():
    """Non-exact methods run through the same isolation boundary; the
    scores are bit-identical to a solo Contributivity run."""
    from mplc_tpu.contrib.contributivity import Contributivity

    sc = scenario(9)
    solo = Contributivity(sc)
    solo.compute_contributivity("Independent scores")
    svc = SweepService(start=False)
    job = svc.submit(scenario(9), method="Independent scores", tenant="A")
    svc.run_until_idle()
    np.testing.assert_array_equal(
        job.result(1.0), np.asarray(solo.contributivity_scores))


# -- per-tenant fault isolation ----------------------------------------------

@pytest.mark.parametrize("entry", [
    "crash@job1:batch2",
    "oom@job1:batch2",
    "transient@job1:batch2",
    "stall@job1:sec0.2",
])
def test_tenant_a_fault_never_perturbs_tenant_b(monkeypatch, entry):
    """The isolation matrix: tenant A absorbs a crash / OOM / transient /
    stall and BOTH tenants still complete with values bit-identical to
    their solo runs (A recovers via the per-job retry or its engine's
    private ladder; B never notices)."""
    ref_a, ref_b = solo_values(9), solo_values(11)
    monkeypatch.setenv("MPLC_TPU_SERVICE_FAULT_PLAN", entry)
    svc = SweepService(start=False, slice_coalitions=3)
    ja = svc.submit(scenario(9), tenant="A")
    jb = svc.submit(scenario(11), tenant="B")
    svc.run_until_idle()
    assert jb.status == "completed"
    np.testing.assert_array_equal(values_of(jb), ref_b)
    assert ja.status == "completed"
    np.testing.assert_array_equal(values_of(ja), ref_a)
    if entry.startswith("crash"):
        assert ja.attempts == 1  # one failed attempt, then recovery
    if entry.startswith("oom"):
        # the OOM rode A's PRIVATE degrade ladder; B's engine never
        # stepped down a rung
        assert ja.engine._cap_halvings == 1
        assert jb.engine._cap_halvings == 0


def test_poison_job_quarantined_after_retry_budget(monkeypatch):
    """A job that crashes on every attempt is quarantined after
    MPLC_TPU_MAX_RETRIES instead of retrying forever; the other tenant
    completes bit-identically."""
    ref_b = solo_values(11)
    monkeypatch.setenv("MPLC_TPU_MAX_RETRIES", "1")
    monkeypatch.setenv("MPLC_TPU_SERVICE_FAULT_PLAN",
                       "crash@job1:batch1,crash@job1:batch2")
    svc = SweepService(start=False, slice_coalitions=3)
    ja = svc.submit(scenario(9), tenant="A")
    jb = svc.submit(scenario(11), tenant="B")
    svc.run_until_idle()
    assert ja.status == "quarantined"
    assert ja.engine is None  # device buffers released
    with pytest.raises(JobQuarantined, match="retry budget"):
        ja.result(1.0)
    assert _counter("service.jobs_quarantined") == 1
    assert jb.status == "completed"
    np.testing.assert_array_equal(values_of(jb), ref_b)


def test_permanent_failure_quarantines_without_retry(monkeypatch):
    """A classified-permanent error (here: a genuine bug in the job's
    scenario surface, surfacing at engine construction) must not burn
    retry attempts — poison quarantines on the first attempt."""
    svc = SweepService(start=False)
    sc = scenario(9)
    sc.multi_partner_learning_approach_key = "bogus-approach"
    job = svc.submit(sc, tenant="A")
    svc.run_until_idle()
    assert job.status == "quarantined"
    assert job.attempts == 1
    with pytest.raises(JobQuarantined, match="permanent failure"):
        job.result(1.0)


def test_unknown_method_is_a_clean_submit_error():
    svc = SweepService(start=False)
    with pytest.raises(ValueError, match="unknown contributivity method"):
        svc.submit(scenario(9), method="no-such-method", tenant="A")


# -- admission control / deadlines -------------------------------------------

def test_backpressure_rejects_with_clean_error(monkeypatch):
    svc = SweepService(start=False, max_pending=1)
    svc.submit(scenario(9), tenant="A")
    with pytest.raises(ServiceOverloaded, match="MPLC_TPU_SERVICE_MAX_PENDING"):
        svc.submit(scenario(11), tenant="B")
    assert _counter("service.jobs_rejected") == 1
    assert _counter("service.jobs_accepted") == 1


def test_fault_plan_reject_refuses_admission(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_SERVICE_FAULT_PLAN", "reject@job1")
    svc = SweepService(start=False)
    with pytest.raises(ServiceRejected):
        svc.submit(scenario(9), tenant="A")
    # the NEXT submission (ordinal 2) is admitted normally
    job = svc.submit(scenario(11), tenant="B")
    svc.run_until_idle()
    assert job.status == "completed"


def test_deadline_expiry_cancels_between_batches():
    """A job whose deadline expires mid-sweep is cancelled cooperatively
    at a batch boundary — no exception escapes the scheduler, harvested
    values are preserved, the engine (and its device buffers) is
    dropped, and later jobs run unaffected."""
    svc = SweepService(start=False, slice_coalitions=2)
    job = svc.submit(scenario(9), tenant="A", deadline_sec=1000.0)
    svc.step()  # partial progress under a live deadline
    harvested = len(job._stream)
    assert harvested > 0
    job.submitted_at -= 10_000  # expire the deadline mid-run
    svc.run_until_idle()
    assert job.status == "cancelled"
    assert job.engine is None
    assert len(job._stream) >= harvested  # nothing harvested was lost
    with pytest.raises(JobCancelled, match="deadline"):
        job.result(1.0)
    assert _counter("service.jobs_cancelled") == 1
    # the service keeps serving
    jb = svc.submit(scenario(11), tenant="B")
    svc.run_until_idle()
    assert jb.status == "completed"
    np.testing.assert_array_equal(values_of(jb), solo_values(11))


def test_deadline_cancels_cooperatively_at_batch_boundary(monkeypatch):
    """The cooperative path specifically: the deadline trips INSIDE a
    slice, at the engine's per-batch progress hook — the raise lands
    between batches, the in-flight drain completes (no double-raise),
    and everything harvested before the trip is preserved."""
    svc = SweepService(start=False, slice_coalitions=len(SUBSETS))
    job = svc.submit(scenario(9), tenant="A", deadline_sec=10_000.0)
    calls = {"n": 0}
    real = SweepJob._deadline_expired

    def fake(self):
        if self is not job:
            return real(self)
        calls["n"] += 1
        return calls["n"] > 1  # quantum-start check passes; batch 1 trips

    monkeypatch.setattr(SweepJob, "_deadline_expired", fake)
    svc.run_until_idle()
    assert job.status == "cancelled"
    assert job.engine is None
    assert job._stream  # the pre-cancel batch's harvest was kept
    with pytest.raises(JobCancelled, match="batch boundary"):
        job.result(1.0)


def test_deadline_already_expired_cancels_before_any_work():
    svc = SweepService(start=False)
    job = svc.submit(scenario(9), tenant="A", deadline_sec=0.0)
    time.sleep(0.01)
    svc.run_until_idle()
    assert job.status == "cancelled"
    assert job.engine is None


# -- journal + crash recovery ------------------------------------------------

def test_journal_append_replay_round_trip(tmp_path):
    path = tmp_path / "wal.jsonl"
    j = SweepJournal(path)
    recs = [{"type": "submit", "job": "a", "tenant": "t"},
            {"type": "value", "job": "a", "subset": [0, 2],
             "value": 0.123456789012345}]
    for r in recs:
        j.append(r)
    j.close()
    replayed, torn = SweepJournal.replay(path)
    assert replayed == recs and torn is False
    # float round-trips exactly
    assert replayed[1]["value"] == recs[1]["value"]
    assert SweepJournal.replay(tmp_path / "absent.jsonl") == ([], False)


def test_journal_torn_tail_quarantined_and_truncated(tmp_path):
    path = tmp_path / "wal.jsonl"
    j = SweepJournal(path)
    j.append({"type": "submit", "job": "a"})
    j.append({"type": "value", "job": "a", "subset": [0], "value": 0.5})
    j.close()
    good = path.read_bytes()
    path.write_bytes(good + b'{"sha256": "x", "rec": {"type": "val')
    with pytest.warns(UserWarning, match="torn"):
        replayed, torn = SweepJournal.replay(path)
    assert torn is True and len(replayed) == 2
    assert path.read_bytes() == good  # truncated back to the last record
    assert (tmp_path / "wal.jsonl.torn").exists()
    assert _counter("service.journal_torn_records") == 1
    # idempotent: a second replay of the repaired file is clean
    assert SweepJournal.replay(path) == (replayed, False)


def test_journal_checksum_mismatch_tail_is_torn(tmp_path):
    path = tmp_path / "wal.jsonl"
    j = SweepJournal(path)
    j.append({"type": "submit", "job": "a"})
    j.append({"type": "value", "job": "a", "subset": [0], "value": 0.5})
    j.close()
    lines = path.read_bytes().splitlines()
    doc = json.loads(lines[1])
    doc["rec"]["value"] = 0.75  # bit-flip the payload, keep the checksum
    path.write_bytes(lines[0] + b"\n" + json.dumps(doc).encode() + b"\n")
    with pytest.warns(UserWarning, match="checksum"):
        replayed, torn = SweepJournal.replay(path)
    assert torn is True and replayed == [{"type": "submit", "job": "a"}]


def test_journal_mid_file_corruption_raises(tmp_path):
    path = tmp_path / "wal.jsonl"
    j = SweepJournal(path)
    j.append({"type": "submit", "job": "a"})
    j.append({"type": "value", "job": "a", "subset": [0], "value": 0.5})
    j.close()
    lines = path.read_bytes().splitlines()
    path.write_bytes(b"garbage\n" + lines[1] + b"\n")
    with pytest.raises(JournalCorruptError, match="not a torn tail"):
        SweepJournal.replay(path)


def test_kill_and_restart_replays_journal_bit_identically(tmp_path):
    """The acceptance crash-recovery invariant, end-to-end: kill a
    two-tenant service mid-sweep (with a torn tail record from the kill
    landing mid-append), restart on the same journal, resubmit, and
    every sweep completes bit-identically to an uninterrupted run — the
    recovered jobs train only what was never journaled."""
    ref_a, ref_b = solo_values(9), solo_values(11)
    path = tmp_path / "service_wal.jsonl"
    svc1 = SweepService(journal_path=path, start=False, slice_coalitions=2)
    svc1.submit(scenario(9), tenant="A", job_id="gameA")
    svc1.submit(scenario(11), tenant="B", job_id="gameB")
    svc1.step()
    svc1.step()
    svc1.step()  # partial progress on both tenants, then the "kill":
    # the service object is abandoned with the journal mid-flight, the
    # kill landing mid-append (a torn final record)
    with open(path, "ab") as f:
        f.write(b'{"sha256": "dead", "rec": {"type": "value", "job"')

    with pytest.warns(UserWarning, match="torn"):
        svc2 = SweepService(journal_path=path, start=False,
                            slice_coalitions=2)
    rec = {r["job_id"]: r for r in svc2.recovered_jobs()}
    assert set(rec) == {"gameA", "gameB"}
    assert not rec["gameA"]["done"] and rec["gameA"]["values"] > 0
    ra = svc2.submit(scenario(9), tenant="A", job_id="gameA")
    rb = svc2.submit(scenario(11), tenant="B", job_id="gameB")
    svc2.run_until_idle()
    assert ra.status == rb.status == "completed"
    np.testing.assert_array_equal(values_of(ra), ref_a)
    np.testing.assert_array_equal(values_of(rb), ref_b)
    assert ra.recovered_values > 0
    assert _counter("service.jobs_recovered") >= 1
    # the recovered engines trained ONLY the never-journaled coalitions
    assert ra.engine._batch_ordinal < len(SUBSETS)
    svc2.shutdown()

    # a THIRD restart finds both jobs done: resubmission completes from
    # the journal alone, zero batches trained
    svc3 = SweepService(journal_path=path, start=False)
    rec3 = {r["job_id"]: r for r in svc3.recovered_jobs()}
    assert rec3["gameA"]["done"] and rec3["gameB"]["done"]
    fa = svc3.submit(scenario(9), tenant="A", job_id="gameA")
    svc3.run_until_idle()
    assert fa.status == "completed"
    assert fa.engine._batch_ordinal == 0
    np.testing.assert_array_equal(values_of(fa), ref_a)
    svc3.shutdown()


def test_restart_with_tenant_a_faults_still_isolates(tmp_path, monkeypatch):
    """Crash injection + journal recovery compose: tenant A crashes
    post-restart and both tenants still land bit-identical."""
    ref_a, ref_b = solo_values(9), solo_values(11)
    path = tmp_path / "wal.jsonl"
    svc1 = SweepService(journal_path=path, start=False, slice_coalitions=2)
    svc1.submit(scenario(9), tenant="A", job_id="gameA")
    svc1.submit(scenario(11), tenant="B", job_id="gameB")
    svc1.step()
    svc1.step()  # kill
    monkeypatch.setenv("MPLC_TPU_SERVICE_FAULT_PLAN", "crash@job1:batch1")
    svc2 = SweepService(journal_path=path, start=False, slice_coalitions=2)
    ra = svc2.submit(scenario(9), tenant="A", job_id="gameA")
    rb = svc2.submit(scenario(11), tenant="B", job_id="gameB")
    svc2.run_until_idle()
    assert ra.status == rb.status == "completed"
    assert ra.attempts == 1  # the injected crash cost one attempt
    np.testing.assert_array_equal(values_of(ra), ref_a)
    np.testing.assert_array_equal(values_of(rb), ref_b)
    svc2.shutdown()


def test_resubmitting_a_different_game_under_a_recovered_id_quarantines(
        tmp_path):
    """The journaled submission is the authority on which game a job_id
    names: resubmitting a DIFFERENT-shaped scenario under a recovered id
    must refuse to seed (and quarantine), never silently mix two games'
    v(S) tables."""
    from helpers import build_scenario

    path = tmp_path / "wal.jsonl"
    svc1 = SweepService(journal_path=path, start=False, slice_coalitions=2)
    svc1.submit(scenario(9), tenant="A", job_id="gameA")
    svc1.step()  # journal some 3-partner values, then "kill"
    svc2 = SweepService(journal_path=path, start=False)
    wrong = build_scenario(partners_count=4,
                           amounts_per_partner=[0.1, 0.2, 0.3, 0.4],
                           dataset_name="titanic", epoch_count=2,
                           gradient_updates_per_pass_count=2, seed=9)
    job = svc2.submit(wrong, tenant="A", job_id="gameA")
    svc2.run_until_idle()
    assert job.status == "quarantined"
    with pytest.raises(JobQuarantined, match="different game"):
        job.result(1.0)
    svc2.shutdown()


def test_completed_job_releases_device_state_but_keeps_values():
    """A long-lived service must not retain one game's device arrays per
    completed job: completion stashes the host-side v(S) table on the
    handle and drops the engine's stacked/eval data and pipelines."""
    svc = SweepService(start=False)
    job = svc.submit(scenario(9), tenant="A")
    svc.run_until_idle()
    assert job.status == "completed"
    assert job.engine.stacked is None and job.engine.val is None
    assert job.engine.multi_pipe is None and job.engine.program_bank is None
    # the handle keeps the full table (and the engine its memo/counters)
    np.testing.assert_array_equal(
        np.array([job.values[s] for s in SUBSETS]), solo_values(9))


def test_journal_write_failure_degrades_instead_of_killing_jobs(
        tmp_path, monkeypatch):
    """A WAL append failure on the async path (disk full mid-sweep) must
    degrade journaling loudly and let jobs finish — never unwind into the
    scheduler and leave handles blocked forever. The synchronous submit
    path propagates instead."""
    from mplc_tpu.service import journal as journal_mod

    path = tmp_path / "wal.jsonl"
    svc = SweepService(journal_path=path, start=False, slice_coalitions=3)
    job = svc.submit(scenario(9), tenant="A")

    def boom(self, recs):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(journal_mod.SweepJournal, "append_many", boom)
    svc.run_until_idle()
    assert job.status == "completed"
    assert svc._journal_broken
    np.testing.assert_array_equal(values_of(job), solo_values(9))
    # the synchronous path: submit refuses with a clean error and leaves
    # no phantom job occupying an admission slot
    with pytest.raises(Exception, match="WAL|journal"):
        svc.submit(scenario(11), tenant="B", job_id="neverin")
    assert "neverin" not in svc._jobs


def test_quarantine_and_cancel_are_journaled(tmp_path, monkeypatch):
    monkeypatch.setenv("MPLC_TPU_MAX_RETRIES", "1")
    monkeypatch.setenv("MPLC_TPU_SERVICE_FAULT_PLAN",
                       "crash@job1:batch1,crash@job1:batch2")
    path = tmp_path / "wal.jsonl"
    svc = SweepService(journal_path=path, start=False)
    ja = svc.submit(scenario(9), tenant="A", job_id="poison")
    svc.run_until_idle()
    assert ja.status == "quarantined"
    svc.shutdown()
    svc2 = SweepService(journal_path=path, start=False)
    rec = {r["job_id"]: r for r in svc2.recovered_jobs()}
    assert rec["poison"]["quarantined"] is True
