"""Adaptive query planner: the method="auto" routing table + journaling.

The contract under test (contrib/planner.py + the "auto" dispatch in
contrib/contributivity.py, live/game.py and service/scheduler.py):

1. **Routing table.** `plan_query` routes `(partners, accuracy_target,
   deadline_sec)` deterministically: exact while the 2^P - 1 sweep fits,
   GTG-Shapley when the game outgrows the exact table or the deadline
   excludes it, SVARM (budget clamped to the deadline) as deadlines
   tighten, DPVS-pruned GTG (live) / floor-budget SVARM (batch) below
   every estimator's floor. Every plan carries its reason and cost
   evidence.
2. **Replayability.** A plan resolves from its inputs alone (measured
   eval_sec is an INPUT, passed by the caller): the same triple yields
   an identical plan, `plan_from_dict(plan.describe())` round-trips, and
   re-running the journaled concrete method reproduces the auto query's
   scores bit-identically.
3. **Journaled dispatch.** `compute_contributivity("auto")` emits a
   `contrib.plan` event, stashes the plan on the Contributivity object
   and dispatches the CONCRETE method; `LiveGame.query(method="auto")`
   emits `live.plan` and returns the plan on the result; the sweep
   service's `submit_live(method="auto")` pins the plan into the WAL's
   submit record and the terminal `service.job` event.
"""

import json

import numpy as np
import pytest

from helpers import build_scenario, cluster_mlp_dataset
from mplc_tpu.contrib import planner
from mplc_tpu.contrib.contributivity import Contributivity
from mplc_tpu.contrib.planner import (QueryPlan, plan_from_dict,
                                      plan_query)
from mplc_tpu.obs import trace as obs_trace

from test_contrib import PHI3, additive, fake_scenario
from test_reconstruct import _StubRecon


# ---------------------------------------------------------------------------
# 1. the routing table (pure plan_query)
# ---------------------------------------------------------------------------

def test_exact_under_16_partners_with_loose_deadline():
    for n in (1, 2, 4, 8, 16):
        p = plan_query(n)
        assert p.method == "exact"
        assert p.est_evals == 2 ** n - 1
        assert p.prune_tau == 0.0
        assert "exact" in p.reason


def test_exact_when_sweep_fits_the_deadline():
    # 2^4 - 1 = 15 evals at 0.1 s each = 1.5 s <= 2 s
    p = plan_query(4, deadline_sec=2.0, eval_sec=0.1, cost_basis="meter")
    assert p.method == "exact"
    assert p.cost_basis == "meter"
    assert p.est_cost_sec == pytest.approx(1.5)


def test_gtg_when_game_outgrows_the_exact_table():
    p = plan_query(24)
    assert p.method == "GTG-Shapley"
    assert "P=24" in p.reason
    assert p.method_kw == {"sv_accuracy": p.accuracy_target}


def test_gtg_when_deadline_excludes_exact():
    # exact = 2^10 - 1 = 1023 evals > 500; GTG = 100 * 10 = 1000... also
    # over, so pick a deadline between the two budgets
    p = plan_query(10, deadline_sec=1001 * 0.05, eval_sec=0.05,
                   cost_basis="meter")
    assert p.method == "GTG-Shapley"
    assert "deadline" in p.reason


def test_accuracy_target_reaches_gtg_stopping_rule():
    p = plan_query(24, accuracy_target=0.005)
    assert p.method_kw == {"sv_accuracy": 0.005}
    assert p.accuracy_target == 0.005


def test_svarm_as_the_deadline_tightens_clamps_budget():
    # GTG needs 100 * 20 = 2000 evals; SVARM's floor for n=20 is
    # 2n + (n^2 - 2n) + 128 = 528 — a deadline affording 600 evals
    # routes SVARM with the budget clamped to what remains after the
    # anchor/warm-up overhead
    n, eval_sec = 20, 0.05
    p = plan_query(n, deadline_sec=600 * eval_sec, eval_sec=eval_sec,
                   cost_basis="meter")
    assert p.method == "SVARM"
    budget = p.method_kw["budget"]
    overhead = 2 * n + (n * n - 2 * n)
    assert budget == 600 - overhead
    assert budget >= 128
    assert budget <= max(4 * n * n, 128)


def test_pruned_rung_live_vs_floor_svarm_batch(monkeypatch):
    monkeypatch.delenv("MPLC_TPU_LIVE_PRUNE_TAU", raising=False)
    # a deadline below even SVARM's floor (n=20 floor = 528 evals)
    n, eval_sec = 20, 0.05
    live = plan_query(n, deadline_sec=10 * eval_sec, eval_sec=eval_sec,
                      cost_basis="meter", live=True)
    assert live.method == "GTG-Shapley"
    assert live.prune_tau == pytest.approx(0.5)
    assert "DPVS" in live.reason
    batch = plan_query(n, deadline_sec=10 * eval_sec, eval_sec=eval_sec,
                       cost_basis="meter", live=False)
    assert batch.method == "SVARM"
    assert batch.method_kw["budget"] == 128
    assert batch.prune_tau == 0.0
    assert "best-effort" in batch.reason


def test_pruned_rung_honors_env_tau(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_LIVE_PRUNE_TAU", "0.25")
    p = plan_query(20, deadline_sec=0.1, eval_sec=0.05,
                   cost_basis="meter", live=True)
    assert p.prune_tau == pytest.approx(0.25)


def test_planner_env_defaults(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_PLANNER_ACCURACY", "0.004")
    monkeypatch.setenv("MPLC_TPU_PLANNER_DEADLINE_SEC", "0.2")
    p = plan_query(20, eval_sec=0.05, cost_basis="meter")
    assert p.accuracy_target == 0.004
    assert p.deadline_sec == 0.2
    monkeypatch.delenv("MPLC_TPU_PLANNER_ACCURACY")
    monkeypatch.delenv("MPLC_TPU_PLANNER_DEADLINE_SEC")
    p2 = plan_query(20, eval_sec=0.05, cost_basis="meter")
    assert p2.accuracy_target == 0.02 and p2.deadline_sec is None


def test_plan_query_rejects_bad_partner_count():
    with pytest.raises(ValueError):
        plan_query(0)


# ---------------------------------------------------------------------------
# 2. replayability: pure resolution + describe round-trip
# ---------------------------------------------------------------------------

def test_same_inputs_yield_identical_plan():
    a = plan_query(12, 0.01, 30.0, eval_sec=0.02, cost_basis="meter")
    b = plan_query(12, 0.01, 30.0, eval_sec=0.02, cost_basis="meter")
    assert a == b  # frozen dataclass equality — fully deterministic


def test_plan_describe_round_trips_through_json():
    p = plan_query(20, deadline_sec=5.0, eval_sec=0.05,
                   cost_basis="bank_cost_model")
    doc = json.loads(json.dumps(p.describe()))
    q = plan_from_dict(doc)
    assert isinstance(q, QueryPlan)
    assert q == p


def test_estimate_eval_seconds_default_without_engine():
    sec, basis = planner.estimate_eval_seconds(None)
    assert basis == "default" and sec == planner.DEFAULT_EVAL_SEC


# ---------------------------------------------------------------------------
# 3. journaled dispatch through the three surfaces
# ---------------------------------------------------------------------------

def _analytic(n, fn):
    sc = fake_scenario(n, fn)
    sc._charac_engine._reconstruction = _StubRecon(fn)
    return sc


def test_compute_contributivity_auto_small_game_is_exact():
    sc = _analytic(3, additive(PHI3))
    c = Contributivity(sc)
    with obs_trace.collect() as records:
        c.compute_contributivity("auto")
    assert c.plan is not None and c.plan.method == "exact"
    np.testing.assert_allclose(c.contributivity_scores, PHI3, atol=1e-9)
    # zero sampling error: the exact rung's trust contract by construction
    np.testing.assert_allclose(c.scores_std, 0.0)
    events = [r for r in records if r["name"] == "contrib.plan"]
    assert len(events) == 1
    attrs = events[0]["attrs"]
    assert attrs["method"] == "exact" and attrs["partners"] == 3
    # the journaled event alone rebuilds the concrete plan
    assert plan_from_dict(attrs) == c.plan


def test_compute_contributivity_auto_large_game_samples():
    phi = [0.01 * (i + 1) for i in range(20)]
    sc = _analytic(20, additive(phi))
    c = Contributivity(sc)
    c.compute_contributivity("auto")
    assert c.plan.method == "GTG-Shapley"
    # additive game: GTG's sampled estimate lands near the true values
    np.testing.assert_allclose(c.contributivity_scores, phi, atol=0.01)


def _scenario_3p(seed=3):
    return build_scenario(
        partners_count=3, amounts_per_partner=[0.2, 0.3, 0.5],
        dataset=cluster_mlp_dataset(n=240, seed=9, scale=1.0),
        epoch_count=2, minibatch_count=2, seed=seed)


@pytest.fixture(scope="module")
def auto_game():
    from mplc_tpu.live import LiveGame
    return LiveGame(_scenario_3p())


def test_live_auto_query_returns_plan_and_replays(auto_game):
    game = auto_game
    with obs_trace.collect() as records:
        r = game.query(method="auto")
    assert r.plan is not None and r.plan.method == "exact"
    assert r.method == "exact"
    events = [x for x in records if x["name"] == "live.plan"]
    assert len(events) == 1 and events[0]["attrs"]["method"] == "exact"
    # replay: running the journaled concrete query reproduces the auto
    # answer bit-identically (same method + tau + kwargs => memo hit)
    r2 = game.query(method=r.plan.method, prune=r.plan.prune_tau,
                    **r.plan.method_kw)
    np.testing.assert_array_equal(np.asarray(r.scores),
                                  np.asarray(r2.scores))
    assert r.plan.describe() in [r.describe().get("plan"),
                                 r.describe()["plan"]]


def test_live_auto_tight_deadline_routes_pruned(auto_game):
    # deadline below every unpruned floor: the live rung prunes
    r = auto_game.query(method="auto", deadline_sec=1e-6)
    assert r.plan is not None
    assert r.plan.method == "GTG-Shapley" and r.plan.prune_tau > 0
    assert r.prune_tau == pytest.approx(r.plan.prune_tau)


def test_service_submit_live_auto_pins_plan_in_wal(tmp_path):
    from mplc_tpu.service import SweepService
    wal = str(tmp_path / "wal.jsonl")
    svc = SweepService(journal_path=wal)
    try:
        game = svc.live_game(_scenario_3p(), tenant="t0")
        with obs_trace.collect() as records:
            job = svc.submit_live("t0", method="auto")
            scores = job.result(timeout=600)
    finally:
        svc.shutdown(drain=False)
    assert job.plan is not None and job.plan.method == "exact"
    assert job.method == "live:exact"  # the CONCRETE method was queued
    assert job.live_result.plan == job.plan
    assert scores is not None and len(scores) == 3
    # WAL: the submit record carries the resolved plan verbatim
    # (journal lines wrap each record as {"sha256": ..., "rec": {...}})
    with open(wal) as f:
        recs = [json.loads(line)["rec"] for line in f if line.strip()]
    sub = [r for r in recs if r.get("type") == "submit"]
    assert len(sub) == 1 and sub[0]["plan"]["method"] == "exact"
    assert plan_from_dict(sub[0]["plan"]) == job.plan
    # the terminal service.job event surfaces the plan
    terminals = [r["attrs"] for r in records
                 if r["name"] == "service.job"]
    assert len(terminals) == 1
    assert terminals[0]["planned"] == "exact"
    assert plan_from_dict(terminals[0]["plan"]) == job.plan


def test_auto_is_a_registered_method():
    from mplc_tpu import constants
    assert "auto" in constants.CONTRIBUTIVITY_METHODS
