"""End-to-end tests: real Scenario.run() with training-backed contributivity,
the contributivity-ordering oracle, and the CLI driver.

Mirrors the reference e2e strategy (/root/reference/tests/
end_to_end_tests.py): threshold asserts on the final score and the semantic
oracle that a partner holding 90% of the data must out-score a partner
holding 10%, for every method.
"""

import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from mplc_tpu.data.datasets import Dataset, to_categorical
from mplc_tpu.models import MNIST_CNN
from mplc_tpu.scenario import Scenario

REPO = Path(__file__).resolve().parents[1]


def _mk_dataset(n=900, noise=0.25, seed=11):
    rng = np.random.default_rng(seed)
    protos = rng.uniform(0, 1, (10, 28, 28, 1)).astype(np.float32)
    def make(m):
        y = rng.integers(0, 10, m)
        x = np.clip(protos[y] + rng.normal(0, noise, (m, 28, 28, 1)), 0, 1)
        return x.astype(np.float32), to_categorical(y, 10)
    x, y = make(n)
    xt, yt = make(n // 4)
    return Dataset("mnist", (28, 28, 1), 10, x, y, xt, yt,
                   model=MNIST_CNN, provenance="test")


@pytest.mark.slow
def test_scenario_run_trains_to_threshold():
    sc = Scenario(partners_count=3, amounts_per_partner=[0.3, 0.3, 0.4],
                  dataset=_mk_dataset(), epoch_count=4, minibatch_count=2,
                  gradient_updates_per_pass_count=4, is_early_stopping=False,
                  experiment_path="/tmp/mplc_tpu_tests", seed=5)
    sc.run()
    assert sc.mpl.history.score > 0.8
    # artifacts written
    assert (sc.save_folder / "graphs" / "data_distribution.png").exists()
    assert (sc.save_folder / "model" / "mnist_final_weights.npz").exists()


@pytest.mark.slow
def test_contributivity_ordering_oracle():
    """0.1/0.9 split: the 0.9 partner must out-score the 0.1 partner for the
    training-backed methods (reference end_to_end_tests.py:54-73)."""
    sc = Scenario(partners_count=2, amounts_per_partner=[0.1, 0.9],
                  dataset=_mk_dataset(1200, noise=0.45, seed=13),
                  epoch_count=3, minibatch_count=2,
                  gradient_updates_per_pass_count=3, is_early_stopping=False,
                  methods=["Shapley values", "Independent scores", "TMCS"],
                  experiment_path="/tmp/mplc_tpu_tests", seed=6)
    sc.run()
    assert len(sc.contributivity_list) == 3
    for contrib in sc.contributivity_list:
        s = contrib.contributivity_scores
        assert s[1] > s[0], f"{contrib.name}: {s}"


@pytest.mark.slow
def test_sbs_lflip_pvrl_methods():
    sc = Scenario(partners_count=2, amounts_per_partner=[0.4, 0.6],
                  dataset=_mk_dataset(500, seed=17), epoch_count=3,
                  minibatch_count=2, gradient_updates_per_pass_count=2,
                  is_early_stopping=False,
                  methods=["Federated SBS linear", "Federated SBS quadratic",
                           "Federated SBS constant", "LFlip", "PVRL"],
                  experiment_path="/tmp/mplc_tpu_tests", seed=7)
    sc.run()
    assert len(sc.contributivity_list) == 5
    for contrib in sc.contributivity_list:
        assert np.isfinite(contrib.contributivity_scores).all(), contrib.name
        assert contrib.contributivity_scores.shape == (2,)
    df = sc.to_dataframe()
    assert len(df) == 5 * 2  # methods x partners
    assert "contributivity_score" in df.columns


@pytest.mark.slow
def test_cli_end_to_end(tmp_path):
    """`python main.py -f cfg.yml` writes results.csv (reference
    end_to_end_tests.py:36-42)."""
    cfg = tmp_path / "cfg.yml"
    cfg.write_text(
        "experiment_name: e2e_test\n"
        "n_repeats: 1\n"
        "scenario_params_list:\n"
        "  - dataset_name:\n"
        "      mnist: null\n"
        "    partners_count: [2]\n"
        "    amounts_per_partner: [[0.4, 0.6]]\n"
        "    samples_split_option: [['basic', 'random']]\n"
        "    multi_partner_learning_approach: ['fedavg']\n"
        "    aggregation_weighting: ['uniform']\n"
        "    epoch_count: [2]\n"
        "    minibatch_count: [2]\n"
        "    gradient_updates_per_pass_count: [2]\n"
        "    is_early_stopping: [False]\n"
        "    methods: [['Independent scores']]\n")
    env = {"MPLC_TPU_SYNTH_SCALE": "0.01", "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": str(REPO), "PATH": "/usr/bin:/bin:/usr/local/bin",
           "HOME": "/root"}
    res = subprocess.run([sys.executable, str(REPO / "main.py"), "-f", str(cfg)],
                         cwd=tmp_path, env=env, capture_output=True, text=True,
                         timeout=1200)
    assert res.returncode == 0, res.stderr[-3000:]
    exp_dirs = list((tmp_path / "experiments").glob("e2e_test_*"))
    assert exp_dirs, "experiment folder not created"
    results = exp_dirs[0] / "results.csv"
    assert results.exists()
    import pandas as pd
    df = pd.read_csv(results)
    assert (df["mpl_test_score"] > 0.5).all()
    assert (df["contributivity_method"] == "Independent scores raw").any()
