"""End-to-end tests: real Scenario.run() with training-backed contributivity,
the contributivity-ordering oracle, and the CLI driver.

Mirrors the reference e2e strategy (/root/reference/tests/
end_to_end_tests.py): threshold asserts on the final score and the semantic
oracle that a partner holding 90% of the data must out-score a partner
holding 10%, for every method.

Compile budget: XLA CPU compiles of the conv models dominate suite time, so
only TWO tests here train the heavyweight CNN — the threshold e2e and the
real-digits gate — and both use the `quick_scenario` shapes/config so ONE
compiled program is shared between them, test_mpl, and the persistent
compilation cache. The oracle and method-coverage tests run the
same full pipeline on models that compile in seconds (titanic logistic
regression; a tiny categorical MLP for lflip/PVRL).
"""

import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from mplc_tpu.data.datasets import Dataset
from mplc_tpu.scenario import Scenario

REPO = Path(__file__).resolve().parents[1]


@pytest.mark.slow
def test_scenario_run_trains_to_threshold(tiny_image_dataset):
    """The one CNN-backed e2e: same dataset/config as `quick_scenario`, so
    the compiled program is shared with test_mpl's class tests."""
    sc = Scenario(partners_count=3, amounts_per_partner=[0.2, 0.3, 0.5],
                  dataset=tiny_image_dataset, epoch_count=4, minibatch_count=2,
                  gradient_updates_per_pass_count=4, is_early_stopping=False,
                  experiment_path="/tmp/mplc_tpu_tests", seed=3)
    sc.run()
    assert sc.mpl.history.score > 0.7
    # artifacts written
    assert (sc.save_folder / "graphs" / "data_distribution.png").exists()
    assert (sc.save_folder / "model" / "mnist_final_weights.npz").exists()


def _digits_dataset():
    """REAL handwritten-digit data without network egress: sklearn's bundled
    UCI digits set (1797 genuine 8x8 scans), upsampled per-image to the
    28x28x1 MNIST geometry. Subsampled to the tiny_image_dataset sizes
    (700 train / 150 test) so the scenario below shares its compiled
    programs with the CNN e2e and test_mpl."""
    from sklearn.datasets import load_digits

    from mplc_tpu.data.datasets import to_categorical, upsample_digits_28x28
    from mplc_tpu.models import MNIST_CNN

    d = load_digits()
    x = upsample_digits_28x28(d.images)[..., None]
    y = to_categorical(d.target, 10)
    idx = np.random.default_rng(42).permutation(len(x))
    tr, te = idx[:700], idx[700:850]
    return Dataset("mnist", (28, 28, 1), 10, x[tr], y[tr], x[te], y[te],
                   model=MNIST_CNN, provenance="sklearn-digits")


@pytest.mark.slow
def test_real_digits_quality_gate():
    """The real-data pipeline proven on data that EXISTS on this box: the
    reference's CI quality gate runs on downloaded MNIST
    (end_to_end_tests.py:31-42), which zero-egress boxes can't fetch — the
    mnist.npz-gated tests below stay skipped here. This one runs the same
    fedavg pipeline on sklearn's bundled REAL handwritten digits instead,
    same scenario config as test_scenario_run_trains_to_threshold (shared
    compiled program), with the threshold the real data supports at this
    tiny epoch budget."""
    sc = Scenario(partners_count=3, amounts_per_partner=[0.2, 0.3, 0.5],
                  dataset=_digits_dataset(), epoch_count=4, minibatch_count=2,
                  gradient_updates_per_pass_count=4, is_early_stopping=False,
                  experiment_path="/tmp/mplc_tpu_tests", seed=3)
    sc.run()
    assert sc.mpl.history.score > 0.7


def _real_mnist_or_skip():
    from mplc_tpu.data.datasets import _find_cache, load_dataset
    if _find_cache("mnist.npz") is None:
        pytest.skip("no real mnist.npz cache provisioned "
                    "($MPLC_TPU_DATA_DIR or ~/.keras/datasets)")
    ds = load_dataset("mnist")
    assert ds.provenance.startswith("cache:")
    return ds


@pytest.mark.slow
def test_real_mnist_quality_gate():
    """The reference's real-data CI gate (end_to_end_tests.py:31-42 with
    tests/config_end_to_end_test_mnist.yml): 20% of REAL MNIST, 2 epochs,
    10 minibatches, fedavg -> test accuracy > 0.95. Skipped when no real
    mnist.npz is provisioned (this build box has no network egress); run
    wherever real data exists to prove the threshold on it."""
    ds = _real_mnist_or_skip()
    sc = Scenario(partners_count=3, amounts_per_partner=[0.4, 0.3, 0.3],
                  dataset=ds, dataset_proportion=0.2,
                  epoch_count=2, minibatch_count=10,
                  gradient_updates_per_pass_count=8, is_early_stopping=False,
                  experiment_path="/tmp/mplc_tpu_tests", seed=3)
    sc.run()
    assert sc.mpl.history.score > 0.95


@pytest.mark.slow
def test_real_mnist_contrib_ordering_gate():
    """The reference's real-data contributivity gate (end_to_end_tests.py:
    54-73 with config_end_to_end_test_contrib.yml): 10% of REAL MNIST,
    0.1/0.9 split, 1 epoch, Shapley + Independent scores — the 0.9 partner
    must out-score the 0.1 partner for both methods. Skip-gated like the
    quality gate above."""
    ds = _real_mnist_or_skip()
    sc = Scenario(partners_count=2, amounts_per_partner=[0.1, 0.9],
                  dataset=ds, dataset_proportion=0.1,
                  epoch_count=1, minibatch_count=10,
                  gradient_updates_per_pass_count=8, is_early_stopping=False,
                  methods=["Shapley values", "Independent scores"],
                  experiment_path="/tmp/mplc_tpu_tests", seed=3)
    sc.run()
    df = sc.to_dataframe()
    assert len(df) == 4  # 2 methods x 2 partners
    for method in df.contributivity_method.unique():
        cur = df[df.contributivity_method == method]
        small = cur.loc[cur.dataset_fraction_of_partner == 0.1,
                        "contributivity_score"].values
        big = cur.loc[cur.dataset_fraction_of_partner == 0.9,
                      "contributivity_score"].values
        assert small < big, f"{method}: {small} !< {big}"


@pytest.mark.slow
def test_contributivity_ordering_oracle():
    """0.1/0.9 split: the 0.9 partner must out-score the 0.1 partner for the
    training-backed methods (reference end_to_end_tests.py:54-73). Runs on
    the titanic logistic model: full pipeline, second-scale compiles."""
    sc = Scenario(partners_count=2, amounts_per_partner=[0.1, 0.9],
                  dataset_name="titanic",
                  epoch_count=6, minibatch_count=2,
                  gradient_updates_per_pass_count=3, is_early_stopping=False,
                  methods=["Shapley values", "Independent scores", "TMCS"],
                  experiment_path="/tmp/mplc_tpu_tests", seed=6)
    sc.run()
    assert sc.mpl.history.score > 0.65   # reference CI gate for titanic
    assert len(sc.contributivity_list) == 3
    for contrib in sc.contributivity_list:
        s = contrib.contributivity_scores
        assert s[1] > s[0], f"{contrib.name}: {s}"
    # resumability artifact
    assert (sc.save_folder / "coalition_cache.json").exists()


@pytest.mark.slow
def test_corrupted_partner_detection_oracle():
    """The data-plane fault-injection contract (SURVEY.md §5): corruption
    exists to let contributivity methods DETECT bad partners. Corrupt the
    LARGEST partner — data volume then argues for it, so only genuine
    detection can rank it last — and assert exact Shapley does."""
    sc = Scenario(partners_count=3, amounts_per_partner=[0.2, 0.3, 0.5],
                  dataset_name="titanic",
                  corrupted_datasets=["not_corrupted", "not_corrupted",
                                      "corrupted"],
                  epoch_count=6, minibatch_count=2,
                  gradient_updates_per_pass_count=3, is_early_stopping=False,
                  methods=["Shapley values"],
                  experiment_path="/tmp/mplc_tpu_tests", seed=6)
    sc.run()
    s = sc.contributivity_list[0].contributivity_scores
    assert s[2] < s[0] and s[2] < s[1], (
        f"fully label-flipped 0.5-partner must rank last: {s}")


def _cluster_mlp_dataset(n=600, num_classes=4, seed=20):
    """Tiny categorical problem: 4 Gaussian clusters, 2-layer MLP."""
    from helpers import cluster_mlp_dataset
    return cluster_mlp_dataset(n, num_classes, seed)


@pytest.mark.slow
def test_sbs_lflip_pvrl_methods():
    """History-backed and lflip/PVRL methods over a categorical model that
    compiles in seconds."""
    sc = Scenario(partners_count=2, amounts_per_partner=[0.4, 0.6],
                  dataset=_cluster_mlp_dataset(), epoch_count=3,
                  minibatch_count=2, gradient_updates_per_pass_count=2,
                  is_early_stopping=False,
                  methods=["Federated SBS linear", "Federated SBS quadratic",
                           "Federated SBS constant", "LFlip", "PVRL"],
                  experiment_path="/tmp/mplc_tpu_tests", seed=7)
    sc.run()
    assert len(sc.contributivity_list) == 5
    for contrib in sc.contributivity_list:
        assert np.isfinite(contrib.contributivity_scores).all(), contrib.name
        assert contrib.contributivity_scores.shape == (2,)
    df = sc.to_dataframe()
    assert len(df) == 5 * 2  # methods x partners
    assert "contributivity_score" in df.columns


@pytest.mark.slow
def test_cli_end_to_end(tmp_path):
    """`python main.py -f cfg.yml` writes results.csv (reference
    end_to_end_tests.py:36-42). Titanic = logistic model, fast compile."""
    cfg = tmp_path / "cfg.yml"
    cfg.write_text(
        "experiment_name: e2e_test\n"
        "n_repeats: 1\n"
        "scenario_params_list:\n"
        "  - dataset_name:\n"
        "      titanic: null\n"
        "    partners_count: [2]\n"
        "    amounts_per_partner: [[0.4, 0.6]]\n"
        "    samples_split_option: [['basic', 'random']]\n"
        "    multi_partner_learning_approach: ['fedavg']\n"
        "    aggregation_weighting: ['uniform']\n"
        "    epoch_count: [4]\n"
        "    minibatch_count: [2]\n"
        "    gradient_updates_per_pass_count: [3]\n"
        "    is_early_stopping: [False]\n"
        "    methods: [['Independent scores']]\n")
    env = {"MPLC_TPU_SYNTH_SCALE": "0.01", "JAX_PLATFORMS": "cpu",
           "JAX_COMPILATION_CACHE_DIR": str(REPO / ".jax_cache"),
           "PYTHONPATH": str(REPO), "PATH": "/usr/bin:/bin:/usr/local/bin",
           "HOME": "/root"}
    res = subprocess.run([sys.executable, str(REPO / "main.py"), "-f", str(cfg)],
                         cwd=tmp_path, env=env, capture_output=True, text=True,
                         timeout=1200)
    assert res.returncode == 0, res.stderr[-3000:]
    exp_dirs = list((tmp_path / "experiments").glob("e2e_test_*"))
    assert exp_dirs, "experiment folder not created"
    results = exp_dirs[0] / "results.csv"
    assert results.exists()
    import pandas as pd
    df = pd.read_csv(results)
    assert (df["mpl_test_score"] > 0.5).all()
    assert (df["contributivity_method"] == "Independent scores raw").any()


def test_cli_grid_shard_farm_out(tmp_path):
    """Multi-host scale-out of the scenario grid: `--grid-shard I/N` gives
    host I the slice I::N with GLOBAL scenario ids; all shards share ONE
    deterministic experiment folder (<name>_shardedN — concurrent launches
    must not race on folder creation) and each writes its own
    results_shardI.csv — the shards' union covers the grid exactly."""
    cfg = tmp_path / "cfg.yml"
    cfg.write_text(
        "experiment_name: shard_test\n"
        "n_repeats: 1\n"
        "scenario_params_list:\n"
        "  - dataset_name:\n"
        "      titanic: null\n"
        "    partners_count: [2]\n"
        "    amounts_per_partner: [[0.4, 0.6]]\n"
        "    samples_split_option: [['basic', 'random']]\n"
        "    multi_partner_learning_approach: ['fedavg']\n"
        "    aggregation_weighting: ['uniform', 'data-volume', 'local-score']\n"
        "    epoch_count: [2]\n"
        "    minibatch_count: [2]\n"
        "    gradient_updates_per_pass_count: [2]\n"
        "    is_early_stopping: [False]\n"
        "    methods: [['Independent scores']]\n")
    env = {"MPLC_TPU_SYNTH_SCALE": "0.01", "JAX_PLATFORMS": "cpu",
           "JAX_COMPILATION_CACHE_DIR": str(REPO / ".jax_cache"),
           "PYTHONPATH": str(REPO), "PATH": "/usr/bin:/bin:/usr/local/bin",
           "HOME": "/root"}
    for shard in ("0/2", "1/2"):
        res = subprocess.run(
            [sys.executable, str(REPO / "main.py"), "-f", str(cfg),
             "--grid-shard", shard],
            cwd=tmp_path, env=env, capture_output=True, text=True,
            timeout=1200)
        assert res.returncode == 0, res.stderr[-3000:]
    import pandas as pd
    shared = tmp_path / "experiments" / "shard_test_sharded2"
    assert shared.is_dir(), "shards must share one deterministic folder"
    assert not list((tmp_path / "experiments").glob("shard_test_2*")), \
        "sharded runs must not create timestamped folders"
    ids = {}
    for i in (0, 1):
        f = shared / f"results_shard{i}.csv"
        assert f.exists(), f"shard {i} wrote no results"
        assert (shared / f"config_shard{i}.yml").exists()
        assert (shared / f".shard{i}.done").exists(), \
            "finished host must leave its completion marker"
        ids[i] = set(pd.read_csv(f)["scenario_id"])
    # the 3-scenario grid (aggregation axis) is covered exactly once, with
    # GLOBAL ids: shard 0 owns {0, 2}, shard 1 owns {1}
    assert ids[0] == {0, 2} and ids[1] == {1}
    # merge refuses while a host looks unfinished (marker missing), then
    # stitches the standard results.csv and retires the shard files so the
    # notebooks' results*.csv glob can't double-count
    marker = shared / ".shard1.done"
    marker.unlink()
    res = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "merge_shards.py"),
         str(shared)], capture_output=True, text=True, timeout=300)
    assert res.returncode != 0 and "no done markers" in res.stderr
    marker.touch()
    res = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "merge_shards.py"),
         str(shared)], capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stderr[-2000:]
    merged = pd.read_csv(shared / "results.csv")
    assert sorted(set(merged["scenario_id"])) == [0, 1, 2]
    assert not list(shared.glob("results_shard*.csv"))   # retired to *.merged
    # a malformed spec is an argparse usage error BEFORE any filesystem
    # side effect — no junk experiment folder appears
    before = sorted((tmp_path / "experiments").iterdir())
    res = subprocess.run(
        [sys.executable, str(REPO / "main.py"), "-f", str(cfg),
         "--grid-shard", "2/2"],
        cwd=tmp_path, env=env, capture_output=True, text=True, timeout=300)
    assert res.returncode != 0
    assert "usage" in res.stderr.lower()
    assert sorted((tmp_path / "experiments").iterdir()) == before
