"""The AOT program bank (ISSUE 8): slot programs compiled ahead of first
dispatch, compile/execute overlap on a background thread, process-global
reuse, and the persistent manifest that turns the compile-cache dir into
a queryable bank (bench warm-up skip).

Invariants under test: banked and freshly-jit-compiled sweeps are
BIT-IDENTICAL (including under injected transient/OOM faults); a repeat
sweep of the same shape reports (near-)zero serial compile time; every
bucket after the first compiles on the background worker (overlapped),
so the serial compile row is the first bucket only."""

import json
import os

import numpy as np
import pytest

from mplc_tpu.contrib import bank as bank_mod
from mplc_tpu.contrib.engine import CharacteristicEngine
from mplc_tpu.contrib.shapley import powerset_order
from mplc_tpu.obs import metrics, report, trace

SUBSETS = powerset_order(4)

_KNOBS = ("MPLC_TPU_DONATE_BUFFERS", "MPLC_TPU_PROGRAM_BANK",
          "MPLC_TPU_FAULT_PLAN", "MPLC_TPU_PIPELINE_BATCHES",
          "MPLC_TPU_SEED_ENSEMBLE", "MPLC_TPU_PARTNER_FAULT_PLAN",
          "MPLC_TPU_PARTNER_SHARDS", "MPLC_TPU_COMPILE_CACHE_DIR")


@pytest.fixture(autouse=True)
def _env(monkeypatch):
    for k in _KNOBS:
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("MPLC_TPU_RETRY_BACKOFF_SEC", "0")
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "1")
    metrics.reset()
    bank_mod.reset_bank()
    yield
    metrics.reset()
    bank_mod.reset_bank()


def scenario(seed=9):
    from helpers import build_scenario
    return build_scenario(partners_count=4,
                          amounts_per_partner=[0.1, 0.2, 0.3, 0.4],
                          dataset_name="titanic", epoch_count=2,
                          gradient_updates_per_pass_count=2, seed=seed)


_REF = {}


def reference(monkeypatch):
    """Bank-less v(S), computed once per process (donation left at its
    default so this isolates the BANK, not donation)."""
    if "vals" not in _REF:
        monkeypatch.setenv("MPLC_TPU_PROGRAM_BANK", "0")
        _REF["vals"] = CharacteristicEngine(scenario()).evaluate(SUBSETS)
        monkeypatch.delenv("MPLC_TPU_PROGRAM_BANK")
    return _REF["vals"]


# -- bit-identity & the compile rows -----------------------------------------

def test_banked_sweep_bit_identical_and_overlapped(monkeypatch):
    """One cold banked sweep: bit-identical values, exactly one serial
    (foreground) bank compile — the first bucket — and every later
    bucket compiled on the background worker (overlapped), which the
    report separates from the serial compile row."""
    ref = reference(monkeypatch)
    with trace.collect() as recs:
        eng = CharacteristicEngine(scenario())
        assert eng.program_bank is not None
        vals = eng.evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)

    evts = [r["attrs"] for r in recs if r["name"] == "bank.compile"]
    # 4-partner merge plan: singles (foreground) + slot-3 + slot-4 buckets
    assert len(evts) == 3
    assert [a["overlapped"] for a in evts].count(False) == 1
    assert [a["overlapped"] for a in evts].count(True) == 2
    # the jit path never compiled: the bank served every dispatch
    assert not [r for r in recs if r["name"] == "trainer.compile"]

    rep = report.sweep_report(recs)
    pb = rep["program_bank"]
    assert (pb["compiles"], pb["compiles_overlapped"]) == (3, 2)
    assert pb["overlapped_s"] == rep["wallclock"]["compile_overlapped_s"]
    # any stall behind the background worker is booked as SERIAL time
    assert pb["waited_s"] <= rep["wallclock"]["compile_s"]
    assert rep["wallclock"]["compile_s"] > 0          # first bucket only
    assert rep["wallclock"]["compile_overlapped_s"] > 0
    assert rep["compiles"]                             # per-program view
    text = report.format_report(rep)
    assert "bank" in text and "compile_overlapped=" in text


def test_warm_bank_repeat_sweep_reports_zero_compile(monkeypatch):
    """The acceptance criterion: a repeat sweep of the same shape with a
    warm (process-global) bank compiles NOTHING — serial and overlapped
    compile rows both ~zero, every program served from the bank."""
    ref = reference(monkeypatch)
    CharacteristicEngine(scenario()).evaluate(SUBSETS)  # primes the bank
    with trace.collect() as recs:
        vals = CharacteristicEngine(scenario()).evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)
    rep = report.sweep_report(recs)
    assert rep["wallclock"]["compile_s"] == 0.0
    assert rep["wallclock"]["compile_overlapped_s"] == 0.0
    assert not [r for r in recs if r["name"] in ("bank.compile",
                                                 "trainer.compile")]
    assert metrics.snapshot()["counters"]["bank.hits"] >= 3


def test_banked_sweep_bit_identical_under_faults(monkeypatch):
    """Bank x PR-4 ladder: a transient retry re-dispatches through the
    SAME banked executable; an OOM re-bucket drops to the inline jit
    path at the degraded width — recovered values stay bit-identical."""
    ref = reference(monkeypatch)
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN",
                       "transient@batch2,oom@batch3")
    eng = CharacteristicEngine(scenario())
    vals = eng.evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)
    assert eng._cap_halvings == 1
    snap = metrics.snapshot()["counters"]
    assert snap["engine.retries"] == 1
    assert snap["engine.faults_injected"] == 2


def test_bank_disabled_restores_inline_jit_path(monkeypatch):
    """MPLC_TPU_PROGRAM_BANK=0: no bank is constructed, nothing AOT-
    compiles, and the sweep still produces the reference table through
    the inline jit path. (trainer.compile events are NOT asserted here:
    the shared trainer registry may already hold this config's compiled
    jits from earlier tests in the process.)"""
    ref = reference(monkeypatch)
    monkeypatch.setenv("MPLC_TPU_PROGRAM_BANK", "0")
    with trace.collect() as recs:
        eng = CharacteristicEngine(scenario())
        assert eng.program_bank is None
        vals = eng.evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)
    assert not [r for r in recs if r["name"] == "bank.compile"]


def test_sweep_plan_matches_executed_buckets_under_partner_faults(
        monkeypatch):
    """sweep_plan must mirror evaluate()'s routing EXACTLY — including
    under a partner fault plan, where coalitions classify by EFFECTIVE
    size but bucket widths come from the ORIGINAL membership and
    all-dropped coalitions never dispatch. A divergence here makes the
    bench warm-up prove (or pre-load) the wrong program set."""
    monkeypatch.setenv("MPLC_TPU_PARTNER_FAULT_PLAN", "dropout@p1:epoch1")
    eng = CharacteristicEngine(scenario())
    plan = eng.sweep_plan(SUBSETS)
    with trace.collect() as recs:
        eng.evaluate(SUBSETS)
    executed = {(r["attrs"]["slot_count"], r["attrs"]["width"])
                for r in recs if r["name"] == "engine.batch"}
    assert {(sc_, w) for _, sc_, w in plan} == executed
    # and fault-free plans match too (the base contract)
    monkeypatch.delenv("MPLC_TPU_PARTNER_FAULT_PLAN")
    eng2 = CharacteristicEngine(scenario(seed=17))
    plan2 = eng2.sweep_plan(SUBSETS)
    with trace.collect() as recs2:
        eng2.evaluate(SUBSETS)
    executed2 = {(r["attrs"]["slot_count"], r["attrs"]["width"])
                 for r in recs2 if r["name"] == "engine.batch"}
    assert {(sc_, w) for _, sc_, w in plan2} == executed2


# -- persistence: the manifest -----------------------------------------------

def test_manifest_persists_program_keys(tmp_path, monkeypatch):
    """With a compile-cache dir configured, every bank compile records
    its program key in the manifest — and a FRESH process (simulated by
    clearing the in-memory store) can prove it holds a sweep's whole
    program set without compiling anything."""
    monkeypatch.setenv("MPLC_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    eng = CharacteristicEngine(scenario())
    eng.evaluate(SUBSETS)
    manifest = tmp_path / bank_mod.MANIFEST_NAME
    assert manifest.exists()
    keys = set(json.loads(manifest.read_text())["programs"])
    assert len(keys) == 3  # singles + slot-3 + slot-4 programs

    bank_mod.reset_bank()  # simulate a process restart
    eng2 = CharacteristicEngine(scenario())
    plan = eng2.sweep_plan(SUBSETS)
    assert len(plan) == 3
    assert eng2.program_bank.holds_persistent(plan)
    # a different shape (different width plan) is NOT claimed
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "4")
    wider = eng2.sweep_plan(SUBSETS)
    assert any(w != pw for (_, _, w), (_, _, pw) in zip(wider, plan))
    assert not eng2.program_bank.holds_persistent(wider)


def test_no_manifest_dir_means_no_persistence(monkeypatch):
    """Without a cache dir there is nothing to prove warm starts from:
    holds_persistent is False and nothing is written anywhere."""
    monkeypatch.setattr(bank_mod, "manifest_dir", lambda: None)
    eng = CharacteristicEngine(scenario(seed=31))
    plan = eng.sweep_plan([(0,), (0, 1)])
    assert eng.program_bank.persistent_keys() == set()
    assert not eng.program_bank.holds_persistent(plan)


# -- bench warm-up skip ------------------------------------------------------

def test_bench_warmup_skips_compile_prime_on_warm_bank(tmp_path,
                                                       monkeypatch):
    """bench._warm_engine: the first run compiles (and records the
    manifest); a second run of the SAME sweep shape proves the bank
    holds every program and skips the compile-prime loop entirely,
    recording `warmup_skipped` provenance for the sidecar."""
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    import bench

    monkeypatch.setenv("MPLC_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    warm1 = bench._warm_engine(scenario())
    assert bench._COMPILE_CACHE["warmup_skipped"] is False
    assert warm1.first_charac_fct_calls_count > 0  # the prime really ran

    bank_mod.reset_bank()  # fresh process: only the manifest survives
    warm2 = bench._warm_engine(scenario())
    assert bench._COMPILE_CACHE["warmup_skipped"] is True
    assert warm2.first_charac_fct_calls_count == 0  # nothing evaluated

    # and the sidecar's compile_cache block carries the provenance
    sidecar = tmp_path / "telemetry.json"
    monkeypatch.setenv("BENCH_TELEMETRY_FILE", str(sidecar))
    bench._COMPILE_CACHE.update(dir=str(tmp_path), entries_at_start=1)
    bench._write_telemetry({"metric": "unit", "wallclock_s": 1.0},
                           repo_root=str(tmp_path))
    rec = json.loads(sidecar.read_text())
    assert rec["compile_cache"]["warmup_skipped"] is True
