"""Contributivity estimators against an analytic characteristic function.

An additive game v(S) = sum of per-partner values has Shapley value exactly
equal to each partner's value, with zero-variance marginals — so every
Shapley estimator must recover it. The engine is faked (no training), which
makes these the fast structural tests; end-to-end training-backed tests live
in test_e2e.py.
"""

import types

import numpy as np
import pytest

from mplc_tpu.contrib.contributivity import Contributivity, KrigingModel
from mplc_tpu.contrib.engine import CharacteristicEngine
from mplc_tpu.contrib.shapley import (bitmask_to_subset, powerset_order,
                                      shapley_from_characteristic,
                                      subset_to_bitmask)


class FakeEngine(CharacteristicEngine):
    """CharacteristicEngine with the trainers replaced by a closed-form v(S)."""

    def __init__(self, n, value_fn):
        self.partners_count = n
        self.value_fn = value_fn
        self.seed = 0
        self.charac_fct_values = {(): 0.0}
        self.increments_values = [dict() for _ in range(n)]
        self.first_charac_fct_calls_count = 0
        self._sharding = None

    def _run_batch(self, subsets, pipe=None):
        for s in subsets:
            self._store(s, float(self.value_fn(s)))

    def _fingerprint(self):
        return {"partners_count": self.partners_count, "seed": self.seed,
                "fake_game": True}

    def evaluate(self, subsets):
        keys = [tuple(sorted(int(i) for i in s)) for s in subsets]
        missing = [k for k in dict.fromkeys(keys) if k not in self.charac_fct_values]
        self._run_batch(missing)
        return np.array([self.charac_fct_values[k] for k in keys])


def fake_scenario(n, value_fn, sizes=None):
    sc = types.SimpleNamespace()
    sizes = sizes if sizes is not None else [100 * (i + 1) for i in range(n)]
    partners = []
    for i in range(n):
        p = types.SimpleNamespace(id=i, y_train=np.zeros(sizes[i]))
        partners.append(p)
    sc.partners_list = partners
    sc.seed = 0
    sc.multi_partner_learning_approach_key = "fedavg"
    sc._charac_engine = FakeEngine(n, value_fn)
    return sc


def additive(phi):
    return lambda s: sum(phi[i] for i in s)


PHI3 = [0.1, 0.25, 0.65]
PHI5 = [0.05, 0.1, 0.15, 0.3, 0.4]


# -- bit-twiddling exact SV --------------------------------------------------

def test_bitmask_round_trip():
    assert subset_to_bitmask((0, 2, 5)) == 0b100101
    assert bitmask_to_subset(0b100101) == (0, 2, 5)


def test_powerset_order_matches_reference_enumeration():
    from itertools import combinations
    n = 4
    ref = [tuple(j) for i in range(n) for j in combinations(range(n), i + 1)]
    assert powerset_order(n) == ref


def test_exact_sv_additive_game():
    n = 4
    phi = [0.4, 0.1, 0.3, 0.2]
    values = {s: sum(phi[i] for i in s) for s in powerset_order(n)}
    sv = shapley_from_characteristic(n, values)
    assert np.allclose(sv, phi, atol=1e-12)


def test_exact_sv_matches_permutation_oracle():
    """Parity oracle (SURVEY.md §4): the bit-twiddling SV must equal the
    textbook average-over-all-permutations marginal computation on a random
    characteristic function."""
    from itertools import permutations
    n = 5
    rng = np.random.default_rng(123)
    values = {s: float(rng.uniform()) for s in powerset_order(n)}
    sv = shapley_from_characteristic(n, values)

    def v(subset):
        return values[tuple(sorted(subset))] if subset else 0.0

    oracle = np.zeros(n)
    perms = list(permutations(range(n)))
    for perm in perms:
        prefix = []
        for i in perm:
            oracle[i] += v(prefix + [i]) - v(prefix)
            prefix.append(i)
    oracle /= len(perms)
    assert np.allclose(sv, oracle, atol=1e-12)


def test_exact_sv_symmetric_game():
    # v(S) = |S|^2: symmetric -> equal SVs summing to v(N)
    n = 3
    values = {s: len(s) ** 2 for s in powerset_order(n)}
    sv = shapley_from_characteristic(n, values)
    assert np.allclose(sv, [3.0, 3.0, 3.0])


# -- methods on the fake engine ---------------------------------------------

def test_compute_SV():
    sc = fake_scenario(3, additive(PHI3))
    c = Contributivity(sc)
    c.compute_SV()
    assert np.allclose(c.contributivity_scores, PHI3, atol=1e-9)
    assert c.first_charac_fct_calls_count == 7


def test_independent_scores():
    sc = fake_scenario(3, additive(PHI3))
    c = Contributivity(sc)
    c.compute_independent_scores()
    assert np.allclose(c.contributivity_scores, PHI3, atol=1e-9)


def test_tmcs_additive():
    sc = fake_scenario(5, additive(PHI5))
    c = Contributivity(sc)
    c.truncated_MC(sv_accuracy=0.05, alpha=0.9, truncation=0.0)
    assert np.allclose(c.contributivity_scores, PHI5, atol=1e-9)


def test_tmcs_truncation_saves_evaluations():
    sc = fake_scenario(5, additive(PHI5))
    c = Contributivity(sc)
    c.truncated_MC(sv_accuracy=0.05, alpha=0.9, truncation=0.5)
    # with truncation 0.5 some subsets (e.g. {1,2,3,4}: all its predecessors
    # have v within 0.5 of v(N)) can never be reached -> strictly fewer than
    # the full 2^5-1 coalition trainings
    assert c.first_charac_fct_calls_count < 31


def test_itmcs_additive():
    sc = fake_scenario(4, additive([0.1, 0.2, 0.3, 0.4]))
    c = Contributivity(sc)
    c.interpol_TMC(sv_accuracy=0.05, alpha=0.9, truncation=0.0)
    assert np.allclose(c.contributivity_scores, [0.1, 0.2, 0.3, 0.4], atol=1e-9)


def test_is_lin_additive():
    sc = fake_scenario(4, additive([0.1, 0.2, 0.3, 0.4]))
    c = Contributivity(sc)
    c.IS_lin(sv_accuracy=0.05, alpha=0.95)
    assert np.allclose(c.contributivity_scores, [0.1, 0.2, 0.3, 0.4], atol=1e-6)


def test_is_lin_additive_stratified_mode(monkeypatch):
    """Force the large-n two-stage sampler (contrib/sampling.py) through the
    IS_lin estimator: the exact-weight proposal must still recover the
    additive game's Shapley values."""
    import mplc_tpu.contrib.contributivity as contrib_mod
    from mplc_tpu.contrib import sampling
    orig = sampling.make_importance_sampler
    monkeypatch.setattr(
        contrib_mod, "make_importance_sampler",
        lambda n, k, fn, rng: orig(n, k, fn, rng, max_exact_bits=2))
    sc = fake_scenario(5, additive(PHI5))
    c = Contributivity(sc)
    c.IS_lin(sv_accuracy=0.05, alpha=0.95)
    assert np.allclose(c.contributivity_scores, PHI5, atol=0.02)


def test_is_lin_large_n_auto_selects_stratified():
    """At n=20 (n-1 > MAX_EXACT_BITS) the IS methods switch to the
    size-stratified sampler automatically; the estimator must still recover
    the additive game's values — and do it without tabulating 2^19 subsets
    (a few seconds of host work; the exact table would be minutes and GBs).
    """
    import time
    from mplc_tpu.contrib.sampling import (SizeStratifiedSubsetSampler,
                                           make_importance_sampler)
    n = 20
    # deterministic guard: the default factory picks the stratified sampler
    # at this n (the timing bound below is the backstop for regressions
    # that reintroduce exponential host work some other way)
    s = make_importance_sampler(
        n, 0, lambda masks: np.ones(masks.shape[0]), np.random.default_rng(0))
    assert isinstance(s, SizeStratifiedSubsetSampler)
    phi = list(np.linspace(0.01, 0.2, n))
    sc = fake_scenario(n, additive(phi))
    c = Contributivity(sc)
    t0 = time.perf_counter()
    c.IS_lin(sv_accuracy=0.05, alpha=0.95)
    host_elapsed = time.perf_counter() - t0
    assert np.allclose(c.contributivity_scores, phi, atol=0.02)
    assert host_elapsed < 60  # ~2-4 s normally; enumeration would be >>this


def test_is_reg_additive():
    phi = [0.1, 0.2, 0.3, 0.15, 0.25]
    sc = fake_scenario(5, additive(phi))
    c = Contributivity(sc)
    c.IS_reg(sv_accuracy=0.05, alpha=0.95)
    assert np.allclose(c.contributivity_scores, phi, atol=0.05)


def test_is_reg_small_n_falls_back_to_exact():
    sc = fake_scenario(3, additive(PHI3))
    c = Contributivity(sc)
    c.IS_reg()
    assert c.name == "IS_reg Shapley values"
    assert np.allclose(c.contributivity_scores, PHI3, atol=1e-9)


def test_ais_kriging_additive():
    phi = [0.1, 0.2, 0.3, 0.4]
    sc = fake_scenario(4, additive(phi))
    c = Contributivity(sc)
    c.AIS_Kriging(sv_accuracy=0.05, alpha=0.95, update=50)
    assert np.allclose(c.contributivity_scores, phi, atol=0.05)


def test_is_loop_refits_when_update_not_larger_than_block():
    """Adaptive refit must fire even when refit_every <= block (the old
    block-boundary-crossing condition was identically false there)."""
    import time
    sc = fake_scenario(4, additive([0.1, 0.2, 0.3, 0.4]))
    c = Contributivity(sc)
    n = 4

    def batch_fn_for(k):
        return lambda masks: np.ones(masks.shape[0])

    count = {"refits": 0}

    def refit():
        count["refits"] += 1
        return c._build_samplers(n, batch_fn_for)

    c._is_sampling_loop(n, c._build_samplers(n, batch_fn_for), 0.05, 0.95,
                        time.perf_counter(), "refit-probe", block=8,
                        refit_every=8, refit_fn=refit)
    assert count["refits"] >= 2


def test_smcs_additive():
    phi = [0.1, 0.2, 0.3, 0.4]
    sc = fake_scenario(4, additive(phi))
    c = Contributivity(sc)
    c.Stratified_MC(sv_accuracy=0.05, alpha=0.95)
    assert np.allclose(c.contributivity_scores, phi, atol=1e-9)


def test_wr_smc_additive():
    phi = [0.1, 0.2, 0.3, 0.4]
    sc = fake_scenario(4, additive(phi))
    c = Contributivity(sc)
    c.without_replacment_SMC(sv_accuracy=0.05, alpha=0.95)
    assert np.allclose(c.contributivity_scores, phi, atol=1e-9)


def test_dispatcher_unknown_method_is_ignored():
    sc = fake_scenario(3, additive(PHI3))
    c = Contributivity(sc)
    c.compute_contributivity("No such method")
    assert np.allclose(c.contributivity_scores, np.zeros(3))


def test_engine_cache_shared_between_methods():
    sc = fake_scenario(3, additive(PHI3))
    c1 = Contributivity(sc)
    c1.compute_SV()
    calls_after_sv = c1.first_charac_fct_calls_count
    c2 = Contributivity(sc)
    c2.compute_independent_scores()
    # singletons were already cached by the SV sweep
    assert c2.first_charac_fct_calls_count == calls_after_sv


def test_cache_save_load_roundtrip(tmp_path):
    sc = fake_scenario(3, additive(PHI3))
    c1 = Contributivity(sc)
    c1.compute_SV()
    path = tmp_path / "coalition_cache.json"
    sc._charac_engine.save_cache(path)

    sc2 = fake_scenario(3, additive(PHI3))
    sc2._charac_engine.load_cache(path)
    assert sc2._charac_engine.charac_fct_values == sc._charac_engine.charac_fct_values
    assert sc2._charac_engine.increments_values == sc._charac_engine.increments_values
    # a full SV sweep on the resumed engine trains nothing new
    calls_before = sc2._charac_engine.first_charac_fct_calls_count
    c2 = Contributivity(sc2)
    c2.compute_SV()
    assert sc2._charac_engine.first_charac_fct_calls_count == calls_before
    assert np.allclose(c2.contributivity_scores, PHI3, atol=1e-9)


def test_cache_load_rejects_mismatched_shape(tmp_path):
    sc = fake_scenario(3, additive(PHI3))
    Contributivity(sc).compute_SV()
    path = tmp_path / "cache.json"
    sc._charac_engine.save_cache(path)
    sc4 = fake_scenario(4, additive([0.1, 0.2, 0.3, 0.4]))
    with pytest.raises(ValueError):
        sc4._charac_engine.load_cache(path)


def test_kriging_model_interpolates():
    model = KrigingModel(1, lambda a, b: np.exp(-np.sum((np.asarray(a) - np.asarray(b)) ** 2)))
    X = [np.array([0.0]), np.array([1.0]), np.array([2.0])]
    Y = np.array([0.0, 1.0, 2.0])
    model.fit(X, Y)
    for x, y in zip(X, Y):
        assert abs(model.predict(x) - y) < 1e-4
