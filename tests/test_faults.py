"""Fault-tolerant sweep execution (mplc_tpu/faults.py + the engine's
recovery ladder): plan-grammar parsing, error classification, transient
retry/backoff, OOM cap degradation down to the per-batch CPU path,
crash/resume equivalence, and coalition-cache integrity.

The governing invariant, asserted throughout: a recovered sweep's v(S)
table is BIT-IDENTICAL to a fault-free run's — retries re-dispatch the
same per-coalition rng-fold streams, re-bucketing only moves batch
boundaries (row-independent vmapped training), and resume replays the
memo cache."""

import json
import os
import warnings

import numpy as np
import pytest

from mplc_tpu import faults
from mplc_tpu.contrib.engine import CacheIntegrityError, CharacteristicEngine
from mplc_tpu.contrib.shapley import powerset_order
from mplc_tpu.obs import metrics, report, trace


def scenario():
    from helpers import build_scenario
    return build_scenario(partners_count=5,
                          amounts_per_partner=[0.1, 0.15, 0.2, 0.25, 0.3],
                          dataset_name="titanic", epoch_count=2,
                          gradient_updates_per_pass_count=2, seed=9)


SUBSETS = powerset_order(5)

# cap=1 on the 8-device mesh: singles = batch 1 (width 8); merge-mode
# multis = width-3 bucket (sizes 2+3, 20 coalitions -> batches 2-4) then
# the width-5 bucket (sizes 4+5, 6 coalitions -> batch 5)
_FAULT_KNOBS = ("MPLC_TPU_FAULT_PLAN", "MPLC_TPU_MAX_RETRIES",
                "MPLC_TPU_MAX_CAP_HALVINGS", "MPLC_TPU_PIPELINE_BATCHES",
                "MPLC_TPU_PARTNER_FAULT_PLAN", "MPLC_TPU_SEED_ENSEMBLE")


@pytest.fixture(autouse=True)
def _fault_env(monkeypatch):
    for k in _FAULT_KNOBS:
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("MPLC_TPU_RETRY_BACKOFF_SEC", "0")
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "1")
    metrics.reset()
    yield
    metrics.reset()


_REF = {}


def reference():
    """Fault-free v(S) for `scenario()` under cap=1, computed once per
    pytest process (the autouse fixture guarantees a clean fault env at
    every call site)."""
    assert "MPLC_TPU_FAULT_PLAN" not in os.environ
    if "vals" not in _REF:
        _REF["vals"] = CharacteristicEngine(scenario()).evaluate(SUBSETS)
    return _REF["vals"]


# -- plan grammar ------------------------------------------------------------

def test_plan_grammar_parses_sites_and_repeats():
    plan = faults.parse_fault_plan(
        "transient@batch3, oom@batch5,crash@batch7,transient@harvest2,"
        "transient@batch3")
    assert plan == {("dispatch", 3): ["transient", "transient"],
                    ("dispatch", 5): ["oom"],
                    ("dispatch", 7): ["crash"],
                    ("harvest", 2): ["transient"]}
    assert faults.parse_fault_plan(None) == {}
    assert faults.parse_fault_plan("") == {}


def test_plan_malformed_entries_warn_and_are_skipped():
    with pytest.warns(UserWarning, match="malformed entry"):
        plan = faults.parse_fault_plan("bogus@batch3,transient@batch2")
    assert plan == {("dispatch", 2): ["transient"]}
    for bad in ("transient@epoch3", "transient@batch0", "oom@batch-1",
                "transient", "@batch3", "oom@batchx"):
        with pytest.warns(UserWarning, match="malformed entry"):
            assert faults.parse_fault_plan(bad) == {}


def test_injector_fires_each_entry_exactly_once():
    inj = faults.FaultInjector(faults.parse_fault_plan("transient@batch2"))
    inj.check("dispatch", 1)            # wrong ordinal: no-op
    inj.check("harvest", 2)             # wrong site: no-op
    with pytest.raises(faults.InjectedTransient):
        inj.check("dispatch", 2)
    inj.check("dispatch", 2)            # consumed: the retry goes through
    assert inj.injected == 1 and not inj.armed


# -- error classification ----------------------------------------------------

def test_error_classifier():
    from jaxlib.xla_extension import XlaRuntimeError

    assert faults.is_transient(faults.InjectedTransient("INTERNAL: x"))
    assert faults.is_transient(XlaRuntimeError("INTERNAL: device halted"))
    assert faults.is_transient(XlaRuntimeError("UNAVAILABLE: tunnel reset"))
    # a broken program/request fails identically on retry: permanent
    assert not faults.is_transient(
        XlaRuntimeError("INVALID_ARGUMENT: bad shape"))
    # host-side bugs are never transient
    assert not faults.is_transient(RuntimeError("INTERNAL: looks xla-ish"))
    assert not faults.is_transient(ValueError("nope"))
    # OOM is its own family, never blind-retried
    oom = XlaRuntimeError("RESOURCE_EXHAUSTED: 13.5G of 16G HBM")
    assert faults.is_oom(oom) and not faults.is_transient(oom)
    assert faults.is_oom(faults.InjectedOom("RESOURCE_EXHAUSTED: injected"))
    assert not faults.is_oom(faults.InjectedTransient("INTERNAL: x"))
    # the crash class is a BaseException: recovery code catching
    # Exception can never swallow it
    assert not isinstance(faults.InjectedCrash("kill"), Exception)


def test_transient_status_table_covers_service_layer_timeouts():
    """gRPC DEADLINE_EXCEEDED / UNAVAILABLE classify transient REGARDLESS
    of exception class: service-layer timeouts surface as plain
    RuntimeError/OSError on toolchains without the XlaRuntimeError
    symbol, and must ride the retry ladder instead of failing jobs.
    Other plain-exception messages stay non-transient (host bugs)."""
    for status in faults._TRANSIENT_STATUS:
        assert status in ("DEADLINE_EXCEEDED", "UNAVAILABLE")
        for cls in (RuntimeError, OSError, ConnectionError):
            assert faults.is_transient(cls(f"{status}: rpc timed out")), \
                (cls, status)
        # leading whitespace tolerated (lstrip'd, like the XLA statuses)
        assert faults.is_transient(RuntimeError(f"  {status}: x"))
    # the status must LEAD the message — a mention mid-sentence is not a
    # status code
    assert not faults.is_transient(
        RuntimeError("got error DEADLINE_EXCEEDED somewhere"))
    # ... and must be the whole TOKEN: a longer identifier that merely
    # starts with a status name is an application error, not a status
    assert not faults.is_transient(
        RuntimeError("UNAVAILABLE_RESOURCE: config bug"))
    assert not faults.is_transient(
        RuntimeError("DEADLINE_EXCEEDED2: odd custom error"))
    assert faults.is_transient(RuntimeError("UNAVAILABLE"))  # bare status
    # permanent statuses on plain exceptions stay permanent
    assert not faults.is_transient(RuntimeError("INVALID_ARGUMENT: x"))
    # a BaseException is never transient even with a transient status
    assert not faults.is_transient(
        faults.InjectedCrash("DEADLINE_EXCEEDED: kill"))
    # the classified ladder-exhaustion error is PERMANENT by construction
    err = faults.LadderExhaustedError("device OOM persisted", halvings=3)
    assert not faults.is_transient(err)
    assert not faults.is_oom(err)
    assert err.halvings == 3 and err.mode == "2d"


# -- transient retry ---------------------------------------------------------

def test_transient_dispatch_fault_retries_bit_identically(monkeypatch):
    ref = reference()
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "transient@batch2")
    eng = CharacteristicEngine(scenario())
    with trace.collect() as recs:
        vals = eng.evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)
    snap = metrics.snapshot()["counters"]
    assert snap["engine.retries"] == 1
    assert snap["engine.faults_injected"] == 1
    assert not eng._faults.armed
    rep = report.sweep_report(recs)
    assert rep["resilience"]["retries"] == 1
    assert rep["resilience"]["faults_injected"] == 1
    assert rep["resilience"]["cap_halvings"] == 0
    assert "resilience" in report.format_report(rep)


def test_transient_harvest_fault_redispatches_bit_identically(monkeypatch):
    ref = reference()
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "transient@harvest2")
    vals = CharacteristicEngine(scenario()).evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)
    assert metrics.snapshot()["counters"]["engine.retries"] == 1


def test_retry_budget_exhaustion_propagates(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_MAX_RETRIES", "2")
    # 3 attempts (initial + 2 retries) all fail -> the 3rd error propagates
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN",
                       "transient@batch1,transient@batch1,transient@batch1")
    eng = CharacteristicEngine(scenario())
    with pytest.raises(faults.InjectedTransient):
        eng.evaluate(SUBSETS)
    assert metrics.snapshot()["counters"]["engine.retries"] == 2


def test_backoff_is_exponential_and_bounded(monkeypatch):
    from mplc_tpu import constants

    monkeypatch.setenv("MPLC_TPU_RETRY_BACKOFF_SEC", "0.0")
    sleeps = []
    monkeypatch.setattr("time.sleep", sleeps.append)
    eng = CharacteristicEngine(scenario())
    eng._retry_backoff = 8.0  # pretend-large base; sleep is patched out
    eng._max_retries = 5
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 4:
            raise faults.InjectedTransient("INTERNAL: flaky")
        return "ok"

    assert eng._retry_transient(flaky, "dispatch") == "ok"
    assert sleeps == [8.0, 16.0, 30.0, 30.0]  # doubling, capped at 30 s
    assert constants.RETRY_BACKOFF_CAP_SEC == 30.0
    assert metrics.snapshot()["counters"]["engine.backoff_sec"] == sum(sleeps)


# -- OOM degradation ladder --------------------------------------------------

def test_oom_halves_cap_and_rebuckets_bit_identically(monkeypatch):
    ref = reference()
    # cap=2 -> width-16 multi batches; batch 2 (the first wide one)
    # completes, then the injected OOM on batch 3 halves to cap=1 -> the
    # remaining subsets re-bucket to width-8 batches
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "2")
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "oom@batch3")
    eng = CharacteristicEngine(scenario())
    with trace.collect() as recs:
        vals = eng.evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)
    assert eng._cap_halvings == 1 and not eng._cpu_degraded
    snap = metrics.snapshot()["counters"]
    assert snap["engine.cap_halvings"] == 1
    degrades = [r for r in recs if r["name"] == "engine.degrade"]
    assert [d["attrs"]["action"] for d in degrades] == ["halve_cap"]
    # every batch dispatched after the degrade ran at the halved width
    batch_widths = [r["attrs"]["width"] for r in recs
                    if r["name"] == "engine.batch"]
    assert 16 in batch_widths        # the pre-OOM width really was wider
    assert batch_widths[-1] == 8
    rep = report.sweep_report(recs)
    assert rep["resilience"]["cap_halvings"] == 1
    assert rep["resilience"]["cpu_batches"] == 0
    # each coalition was still trained exactly once
    assert eng.first_charac_fct_calls_count == len(SUBSETS)


def test_oom_at_harvest_recovers_bit_identically(monkeypatch):
    ref = reference()
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "oom@harvest2")
    eng = CharacteristicEngine(scenario())
    vals = eng.evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)
    assert eng._cap_halvings == 1
    assert eng.first_charac_fct_calls_count == len(SUBSETS)


def test_oom_on_pending_harvest_during_dispatch_oom_recovers(monkeypatch):
    """With async dispatch an OOM often surfaces at the in-flight batch's
    FETCH while the next batch's dispatch is also OOMing: both boundaries
    must ride the ladder (the pending drain inside the dispatch-OOM
    handler goes through the recover path, not a bare harvest)."""
    ref = reference()
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "oom@harvest2,oom@batch3")
    eng = CharacteristicEngine(scenario())
    vals = eng.evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)
    assert eng._cap_halvings == 2
    assert eng.first_charac_fct_calls_count == len(SUBSETS)


def test_fetch_retry_covers_redispatch_failures(monkeypatch):
    """A transient failure raised by the RE-dispatch itself (the
    correlated-outage case) must consume a retry, not escape the
    ladder."""
    eng = CharacteristicEngine(scenario())
    calls = {"redispatch": 0}

    def redispatch():
        calls["redispatch"] += 1
        if calls["redispatch"] == 1:
            raise faults.InjectedTransient("INTERNAL: redispatch flake")
        return lambda: "ok"

    def failing_fetch():
        raise faults.InjectedTransient("INTERNAL: fetch flake")

    meta = {"redispatch": redispatch, "ordinal": 0}
    assert eng._fetch_with_retry(failing_fetch, meta) == "ok"
    # 2 of the 3 retries consumed: the failed fetch, the failed re-dispatch
    assert metrics.snapshot()["counters"]["engine.retries"] == 2


def test_singles_sliced_oom_recovers_bit_identically(monkeypatch):
    """The 2-D mode's data-sliced singles path has its own OOM rung
    (recursion over the still-missing singles at the halved cap)."""
    singles = [(i,) for i in range(4)]

    def scenario_2d():
        from helpers import build_scenario
        return build_scenario(partners_count=4,
                              amounts_per_partner=[0.1, 0.2, 0.3, 0.4],
                              dataset_name="titanic", epoch_count=2,
                              gradient_updates_per_pass_count=2, seed=9)

    monkeypatch.setenv("MPLC_TPU_PARTNER_SHARDS", "2")
    ref_eng = CharacteristicEngine(scenario_2d())
    assert ref_eng._pipe2d is not None
    ref = ref_eng.evaluate(singles)

    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "oom@batch1")
    eng = CharacteristicEngine(scenario_2d())
    vals = eng.evaluate(singles)
    np.testing.assert_array_equal(vals, ref)
    assert eng._cap_halvings == 1 and not eng._cpu_degraded
    assert eng.first_charac_fct_calls_count == len(singles)
    # and a fetch-side OOM recovers too
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "oom@harvest1")
    eng2 = CharacteristicEngine(scenario_2d())
    np.testing.assert_array_equal(eng2.evaluate(singles), ref)
    assert eng2._cap_halvings == 1


def test_oom_ladder_ends_in_cpu_path_bit_identically(monkeypatch):
    ref = reference()
    monkeypatch.setenv("MPLC_TPU_MAX_CAP_HALVINGS", "1")
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "oom@batch2,oom@batch3")
    eng = CharacteristicEngine(scenario())
    with trace.collect() as recs:
        vals = eng.evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)
    assert eng._cpu_degraded
    snap = metrics.snapshot()["counters"]
    assert snap["engine.cpu_degraded_batches"] > 0
    assert snap["engine.cpu_degraded_coalitions"] > 0
    cpu_batches = [r for r in recs if r["name"] == "engine.batch"
                   and r["attrs"].get("degraded") == "cpu"]
    assert cpu_batches
    rep = report.sweep_report(recs)
    assert rep["resilience"]["cpu_degraded"] is True
    assert rep["resilience"]["cpu_batches"] == len(cpu_batches)
    assert rep["resilience"]["cpu_coalitions"] == sum(
        r["attrs"]["coalitions"] for r in cpu_batches)
    text = report.format_report(rep)
    assert "cpu_batches=" in text and "cap_halvings=2" in text
    assert eng.first_charac_fct_calls_count == len(SUBSETS)


# -- crash / resume ----------------------------------------------------------

def test_crash_resume_from_autosave_is_bit_identical(tmp_path, monkeypatch):
    """The autosave claim, end-to-end: kill a pipelined sweep (two batches
    in flight) mid-run via the crash fault, resume a FRESH engine from the
    autosave, and the final Shapley-sweep v(S) table is bit-identical to
    an uninterrupted run — with only the missing coalitions retrained."""
    ref = reference()
    path = tmp_path / "coalition_cache.json"
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "crash@batch4")
    eng = CharacteristicEngine(scenario())
    assert eng._pipeline_batches  # overlap on: the harder crash bound
    eng.autosave_path = path
    with pytest.raises(faults.InjectedCrash):
        eng.evaluate(SUBSETS)
    monkeypatch.delenv("MPLC_TPU_FAULT_PLAN")

    resumed = CharacteristicEngine(scenario())
    resumed.load_cache(path)
    done = resumed.first_charac_fct_calls_count
    assert 0 < done < len(SUBSETS)  # a partial run, genuinely resumed
    vals = resumed.evaluate(SUBSETS)
    np.testing.assert_array_equal(vals, ref)
    # only the missing coalitions were retrained
    assert resumed.first_charac_fct_calls_count == len(SUBSETS)


def test_crash_is_not_swallowed_by_retry_or_degradation(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "crash@batch1")
    eng = CharacteristicEngine(scenario())
    with pytest.raises(faults.InjectedCrash):
        eng.evaluate(SUBSETS)
    assert metrics.snapshot()["counters"].get("engine.retries") is None


# -- cache integrity ---------------------------------------------------------

def _saved_cache(tmp_path):
    from test_contrib import additive, fake_scenario

    sc = fake_scenario(3, additive([0.1, 0.25, 0.65]))
    eng = sc._charac_engine
    eng.evaluate(powerset_order(3))
    path = tmp_path / "cache.json"
    eng.save_cache(path)
    return eng, path


def test_save_cache_embeds_verifiable_checksum(tmp_path):
    import hashlib

    from test_contrib import additive, fake_scenario

    eng, path = _saved_cache(tmp_path)
    rec = json.loads(path.read_text())
    body = dict(rec)
    digest = body.pop("payload_sha256")
    assert digest == hashlib.sha256(json.dumps(body).encode()).hexdigest()
    fresh = fake_scenario(3, additive([0.1, 0.25, 0.65]))._charac_engine
    fresh.load_cache(path)
    assert fresh.charac_fct_values == eng.charac_fct_values


def test_truncated_cache_raises_integrity_error(tmp_path):
    from test_contrib import additive, fake_scenario

    _, path = _saved_cache(tmp_path)
    text = path.read_text()
    path.write_text(text[:len(text) // 2])
    fresh = fake_scenario(3, additive([0.1, 0.25, 0.65]))._charac_engine
    with pytest.raises(CacheIntegrityError, match="corrupt or truncated"):
        fresh.load_cache(path)


def test_bitflipped_cache_fails_checksum_never_poisons_vs(tmp_path):
    """Valid JSON with corrupted VALUES (the silent-poison case a
    truncation check can't catch) must fail the checksum, not load."""
    from test_contrib import additive, fake_scenario

    _, path = _saved_cache(tmp_path)
    rec = json.loads(path.read_text())
    rec["charac_fct_values"][1][1] += 0.25   # the poisoned v(S)
    path.write_text(json.dumps(rec))
    fresh = fake_scenario(3, additive([0.1, 0.25, 0.65]))._charac_engine
    with pytest.raises(CacheIntegrityError, match="checksum"):
        fresh.load_cache(path)


def test_legacy_cache_without_checksum_still_loads(tmp_path, monkeypatch):
    import mplc_tpu.contrib.engine as engine_mod
    from test_contrib import additive, fake_scenario

    monkeypatch.setattr(engine_mod, "_legacy_cache_warned", False)
    eng, path = _saved_cache(tmp_path)
    rec = json.loads(path.read_text())
    rec.pop("payload_sha256")
    path.write_text(json.dumps(rec))
    fresh = fake_scenario(3, additive([0.1, 0.25, 0.65]))._charac_engine
    # loads — but with a one-time deprecation warning: corruption in a
    # checksum-less cache is undetectable
    with pytest.warns(DeprecationWarning, match="UNVERIFIED"):
        fresh.load_cache(path)
    assert fresh.charac_fct_values == eng.charac_fct_values
    assert fresh._cache_needs_upgrade
    # one-time: a second legacy load in the same process stays silent
    fresh2 = fake_scenario(3, additive([0.1, 0.25, 0.65]))._charac_engine
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        fresh2.load_cache(path)
    # but a legacy-shaped file missing payload keys is still integrity-bad
    path.write_text(json.dumps({"fingerprint": rec["fingerprint"]}))
    with pytest.raises(CacheIntegrityError, match="missing keys"):
        fresh.load_cache(path)


def test_legacy_cache_upgrade_round_trip(tmp_path, monkeypatch):
    """The convergence satellite, end-to-end: a legacy (no-checksum)
    cache loads with a deprecation warning, the next autosave rewrites it
    in the checksummed format — even when the resumed sweep is fully
    memoized and no batch ever fires an autosave — and the rewritten file
    reloads silently and verified."""
    import mplc_tpu.contrib.engine as engine_mod
    from helpers import build_scenario

    def sc():
        return build_scenario(partners_count=3, dataset_name="titanic",
                              epoch_count=2,
                              gradient_updates_per_pass_count=2, seed=9)

    subs = powerset_order(3)
    eng = CharacteristicEngine(sc())
    ref = eng.evaluate(subs)
    path = tmp_path / "cache.json"
    eng.save_cache(path)
    rec = json.loads(path.read_text())
    rec.pop("payload_sha256")
    path.write_text(json.dumps(rec))

    monkeypatch.setattr(engine_mod, "_legacy_cache_warned", False)
    fresh = CharacteristicEngine(sc())
    with pytest.warns(DeprecationWarning):
        fresh.load_cache(path)
    fresh.autosave_path = path
    # a fully-cached sweep: every subset memo-hits, no batch runs (so no
    # per-batch autosave fires) — the upgrade still happens at the
    # evaluate() boundary
    vals = fresh.evaluate(subs)
    np.testing.assert_array_equal(vals, ref)
    assert fresh._batch_ordinal == 0
    upgraded = json.loads(path.read_text())
    assert "payload_sha256" in upgraded
    assert not fresh._cache_needs_upgrade
    # the obligation is to the LOADED file: with the autosave pointed at
    # a different path, the legacy file itself is still the one upgraded
    rec2 = dict(upgraded)
    rec2.pop("payload_sha256")
    path.write_text(json.dumps(rec2))
    other = CharacteristicEngine(sc())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # one-time warning already spent
        other.load_cache(path)
    elsewhere = tmp_path / "autosave_elsewhere.json"
    other.autosave_path = elsewhere
    other.evaluate(subs)
    assert "payload_sha256" in json.loads(path.read_text())
    assert not other._cache_needs_upgrade
    # the upgraded file round-trips verified and silent
    monkeypatch.setattr(engine_mod, "_legacy_cache_warned", False)
    final = CharacteristicEngine(sc())
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        final.load_cache(path)
    assert final.charac_fct_values == eng.charac_fct_values


def test_save_cache_fsyncs_before_replace(tmp_path, monkeypatch):
    """The durability fix: the temp file must be fsync'd BEFORE os.replace
    promotes it, or a power loss can promote an empty/partial file over a
    good cache despite the atomic-rename claim."""
    from test_contrib import additive, fake_scenario

    events = []
    real_fsync, real_replace = os.fsync, os.replace
    monkeypatch.setattr(
        os, "fsync", lambda fd: (events.append("fsync"), real_fsync(fd)))
    monkeypatch.setattr(
        os, "replace",
        lambda a, b: (events.append("replace"), real_replace(a, b)))
    _, path = _saved_cache(tmp_path)
    assert "fsync" in events and "replace" in events
    assert events.index("fsync") < events.index("replace")
    # and the written file round-trips
    fake_scenario(3, additive([0.1, 0.25, 0.65]))._charac_engine.load_cache(path)


# -- 2-D ladder exhaustion (the classified degrade dead end) -----------------

def test_2d_ladder_exhaustion_raises_classified_error(monkeypatch):
    """When cap-halvings run out in the 2-D partner-sharded mode (which
    has no CPU rung), the engine raises a classified, actionable
    `LadderExhaustedError` — never a raw XlaRuntimeError — and the
    exhaustion is recorded in the resilience report row."""
    def scenario_2d():
        from helpers import build_scenario
        return build_scenario(partners_count=4,
                              amounts_per_partner=[0.1, 0.2, 0.3, 0.4],
                              dataset_name="titanic", epoch_count=2,
                              gradient_updates_per_pass_count=2, seed=9)

    monkeypatch.setenv("MPLC_TPU_PARTNER_SHARDS", "2")
    monkeypatch.setenv("MPLC_TPU_MAX_CAP_HALVINGS", "1")
    # singles path: every rung (batch 1 and its recursion's batch 2) OOMs
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "oom@batch1,oom@batch2")
    eng = CharacteristicEngine(scenario_2d())
    with trace.collect() as recs:
        with pytest.raises(faults.LadderExhaustedError) as ei:
            eng.evaluate([(i,) for i in range(4)])
    err = ei.value
    assert err.mode == "2d" and err.halvings == 2
    # actionable: the message names the remedies and the root cause
    assert "MPLC_TPU_PARTNER_SHARDS" in str(err)
    assert "RESOURCE_EXHAUSTED" in str(err)
    # classified permanent: neither retried nor re-laddered
    assert not faults.is_transient(err) and not faults.is_oom(err)
    rep = report.sweep_report(recs)
    assert rep["resilience"]["ladder_exhausted"] == 1
    # exhaustion is NOT a rung: the two real halvings stay separate
    assert rep["resilience"]["cap_halvings"] == 2
    assert "ladder_exhausted=1" in report.format_report(rep)


def test_2d_multis_ladder_exhaustion_is_classified_too(monkeypatch):
    """The multi-coalition 2-D dispatch path's dead end is classified the
    same way (it used to re-raise the raw injected OOM)."""
    def scenario_2d():
        from helpers import build_scenario
        return build_scenario(partners_count=4,
                              amounts_per_partner=[0.1, 0.2, 0.3, 0.4],
                              dataset_name="titanic", epoch_count=2,
                              gradient_updates_per_pass_count=2, seed=9)

    monkeypatch.setenv("MPLC_TPU_PARTNER_SHARDS", "2")
    monkeypatch.setenv("MPLC_TPU_MAX_CAP_HALVINGS", "1")
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "oom@batch1,oom@batch2")
    eng = CharacteristicEngine(scenario_2d())
    with pytest.raises(faults.LadderExhaustedError) as ei:
        eng.evaluate([(0, 1), (0, 2), (1, 2), (0, 1, 2)])
    assert ei.value.__cause__ is not None  # chained from the device OOM
