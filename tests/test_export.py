"""The live telemetry plane (obs/export.py + obs/flight.py): Prometheus
rendering, the /metrics //healthz //varz endpoints gated on
MPLC_TPU_METRICS_PORT, per-tenant SLO histograms + the report's slo row,
and the crash flight recorder's postmortem dumps.

Acceptance invariants pinned here:
  - with the port set, a running SweepService serves Prometheus-parseable
    /metrics including per-tenant SLO histogram series, /varz with the
    job table, and /healthz that flips 503 on a worker stall;
  - with the port UNSET, no thread or socket is created;
  - a quarantined job writes a postmortem flight-recorder file whose
    ring buffer contains the failing batch's spans, referenced from the
    quarantine log line.
"""

import json
import logging
import os
import time
import types
import urllib.request

import numpy as np
import pytest

from mplc_tpu.obs import export, flight, metrics, report, trace
from mplc_tpu.service import JobQuarantined, SweepService
from mplc_tpu.service import scheduler as sched


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for k in ("MPLC_TPU_METRICS_PORT", "MPLC_TPU_SERVICE_FAULT_PLAN",
              "MPLC_TPU_FAULT_PLAN", "MPLC_TPU_MAX_RETRIES",
              "MPLC_TPU_METRICS_TOKEN"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("MPLC_TPU_RETRY_BACKOFF_SEC", "0")
    metrics.reset()
    yield
    export.stop()
    metrics.reset()


def _scenario(seed=0):
    from helpers import build_scenario
    return build_scenario(partners_count=3, dataset_name="titanic",
                          epoch_count=2,
                          gradient_updates_per_pass_count=2, seed=seed)


def _get(url):
    try:
        resp = urllib.request.urlopen(url, timeout=10)
        return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:  # 503 still carries a body
        return e.code, e.read().decode()


# -- Prometheus rendering -----------------------------------------------------

def test_prometheus_text_labels_buckets_and_types():
    metrics.counter("engine.retries").inc(3)
    metrics.gauge("engine.device_mem_high_water_bytes").set(1024)
    metrics.counter("trainer.compiles[brun]").inc()
    h = metrics.histogram("service.queue_wait_sec", tenant="t0")
    for v in (0.001, 0.002, 4.0):
        h.observe(v)
    text = export.prometheus_text()
    lines = text.splitlines()
    assert "# TYPE mplc_engine_retries counter" in lines
    assert "mplc_engine_retries 3" in lines
    assert "mplc_engine_device_mem_high_water_bytes 1024" in lines
    # the name[item] convention becomes an item label
    assert 'mplc_trainer_compiles{item="brun"} 1' in lines
    # histogram: cumulative buckets, +Inf, _sum/_count, labels quoted
    assert "# TYPE mplc_service_queue_wait_sec histogram" in lines
    inf = [l for l in lines if l.startswith(
        'mplc_service_queue_wait_sec_bucket{le="+Inf"')]
    assert inf and inf[0].endswith(" 3")
    assert 'tenant="t0"' in inf[0]
    assert 'mplc_service_queue_wait_sec_count{tenant="t0"} 3' in lines
    # bucket counts are CUMULATIVE and monotone
    buckets = [int(l.rsplit(" ", 1)[1]) for l in lines
               if "_bucket{" in l]
    assert buckets == sorted(buckets)
    # every sample line parses as "name{labels} value" or "name value"
    for l in lines:
        if l.startswith("#"):
            continue
        name, value = l.rsplit(" ", 1)
        float(value)


# -- the endpoints ------------------------------------------------------------

def test_service_serves_endpoints_when_port_set(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_METRICS_PORT", "0")  # ephemeral
    svc = SweepService(start=False)
    try:
        srv = export.active_server()
        assert srv is not None
        base = f"http://127.0.0.1:{srv.port}"

        job = svc.submit(_scenario(), tenant="tenantA")
        svc.run_until_idle()
        assert job.status == "completed"

        # /metrics: Prometheus-parseable, with the per-tenant SLO series
        status, text = _get(base + "/metrics")
        assert status == 200
        assert 'mplc_service_queue_wait_sec_bucket{le=' in text
        assert 'tenant="tenantA"' in text
        assert "mplc_service_slice_sec_count" in text
        assert "mplc_service_jobs_completed 1" in text

        # /varz: full JSON incl. the service job table and histogram
        # quantiles
        status, body = _get(base + "/varz")
        assert status == 200
        varz = json.loads(body)
        svc_row = varz[svc._provider_key]
        assert svc_row["jobs"][job.job_id]["status"] == "completed"
        assert svc_row["jobs"][job.job_id]["tenant"] == "tenantA"
        hist = varz["metrics"]["histograms"][
            "service.queue_wait_sec{tenant=tenantA}"]
        assert hist["count"] == 1 and hist["p50"] is not None

        # /healthz: healthy while idle
        status, body = _get(base + "/healthz")
        assert status == 200
        health = json.loads(body)
        assert health["healthy"] is True
        prov = health["providers"][svc._provider_key]
        assert prov["journal"] == "disabled"
        assert prov["worker_alive"] is True

        # unknown route -> 404, index -> 200
        assert _get(base + "/nope")[0] == 404
        assert _get(base + "/")[0] == 200
    finally:
        svc.shutdown()


def test_healthz_flips_on_worker_stall(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_METRICS_PORT", "0")
    svc = SweepService(start=False)
    try:
        base = f"http://127.0.0.1:{export.active_server().port}"
        # simulate a wedged quantum: a job is "running" and the heartbeat
        # is older than the stall bound
        svc._running_job = types.SimpleNamespace(job_id="jobX")
        svc._heartbeat = time.monotonic() - (sched.STALL_HEALTHY_SEC + 1)
        status, body = _get(base + "/healthz")
        assert status == 503
        health = json.loads(body)
        assert health["healthy"] is False
        prov = health["providers"][svc._provider_key]
        assert prov["stalled"] is True
        assert prov["running_job"] == "jobX"
        assert prov["worker_heartbeat_age_sec"] > sched.STALL_HEALTHY_SEC
        # recovery: a fresh beat with no running job flips back
        svc._running_job = None
        svc._heartbeat = time.monotonic()
        assert _get(base + "/healthz")[0] == 200
    finally:
        svc._running_job = None
        svc.shutdown()


def test_no_socket_or_thread_without_the_env(monkeypatch):
    monkeypatch.delenv("MPLC_TPU_METRICS_PORT", raising=False)
    assert export.maybe_start_from_env() is None
    svc = SweepService(start=False)
    try:
        assert export.active_server() is None
    finally:
        svc.shutdown()


def test_malformed_port_warns_and_stays_off(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_METRICS_PORT", "not-a-port")
    with pytest.warns(UserWarning, match="not a port number"):
        assert export.maybe_start_from_env() is None
    assert export.active_server() is None


def test_plain_port_binds_loopback_host_port_opts_in(monkeypatch):
    """The endpoints are unauthenticated: a bare port must bind loopback
    only, and `host:port` is the explicit wider-exposure opt-in."""
    monkeypatch.setenv("MPLC_TPU_METRICS_PORT", "0")
    srv = export.maybe_start_from_env()
    assert srv.host == "127.0.0.1"
    export.stop()
    monkeypatch.setenv("MPLC_TPU_METRICS_PORT", "0.0.0.0:0")
    srv = export.maybe_start_from_env()
    assert srv.host == "0.0.0.0"
    assert _get(f"http://127.0.0.1:{srv.port}/healthz")[0] in (200, 503)


def test_broken_provider_degrades_not_500():
    export.register_health("boom", lambda: 1 / 0)
    try:
        healthy, view = export.health_view()
        assert healthy is False
        assert "error" in view["providers"]["boom"]
    finally:
        export.unregister("boom")


# -- per-tenant SLO: the report row -------------------------------------------

def test_report_slo_row_from_service_records():
    with trace.collect() as recs:
        svc = SweepService(start=False)
        try:
            jobs = [svc.submit(_scenario(seed), tenant=f"t{seed}")
                    for seed in (0, 1)]
            svc.run_until_idle()
            for j in jobs:
                assert j.status == "completed"
        finally:
            svc.shutdown()
    rep = report.sweep_report(recs)
    slo = rep["slo"]
    assert set(slo) == {"t0", "t1"}
    for tn in ("t0", "t1"):
        row = slo[tn]
        assert row["jobs"] == 1
        assert row["queue_wait_s"]["p50"] is not None
        assert row["ttfv_s"]["p50"] is not None
        assert row["slice_s"]["count"] >= 1
        assert row["slice_s"]["p50"] <= row["slice_s"]["p99"]
        assert row["deadline_misses"] == 0
        assert row["retries"] == 0
    text = report.format_report(rep)
    assert "slo[t0]" in text and "deadline_misses=0" in text
    # live histograms observed the same series, labeled by tenant
    snap = metrics.snapshot()["histograms"]
    assert snap["service.queue_wait_sec{tenant=t0}"]["count"] == 1
    assert snap["service.time_to_first_value_sec{tenant=t1}"]["count"] == 1


def test_deadline_miss_counted_per_tenant():
    svc = SweepService(start=False)
    try:
        job = svc.submit(_scenario(), tenant="slow", deadline_sec=0.0)
        time.sleep(0.01)
        svc.run_until_idle()
        assert job.status == "cancelled"
        assert job.deadline_missed is True
    finally:
        svc.shutdown()
    snap = metrics.snapshot()["counters"]
    assert snap["service.deadline_misses{tenant=slow}"] == 1


# -- the crash flight recorder ------------------------------------------------

def test_quarantined_job_writes_postmortem_with_failing_batch_spans(
        monkeypatch, tmp_path, caplog):
    """The acceptance path: a job whose batches keep crashing quarantines
    AND leaves a postmortem file whose ring buffer holds the failing
    batch's spans; the quarantine log line references the file."""
    flight_dir = tmp_path / "flight"
    monkeypatch.setenv("MPLC_TPU_FLIGHT_RECORDER_DIR", str(flight_dir))
    monkeypatch.setenv("MPLC_TPU_MAX_RETRIES", "1")
    # attempt 1 crashes at batch 1; the retry's first batch is ordinal 2
    # (the engine keeps counting) and crashes too, exhausting the budget
    monkeypatch.setenv("MPLC_TPU_SERVICE_FAULT_PLAN",
                       "crash@job1:batch1,crash@job1:batch2")
    svc = SweepService(start=False)
    try:
        with trace.collect() as recs:
            job = svc.submit(_scenario(), tenant="victim")
            with caplog.at_level(logging.ERROR, logger="mplc_tpu"):
                svc.run_until_idle()
        assert job.status == "quarantined"
        with pytest.raises(JobQuarantined):
            job.result(timeout=1)
    finally:
        svc.shutdown()

    # slo retries mirror the LIVE counter exactly: only the re-queued
    # attempt counts, not the quarantining final one
    slo = report.sweep_report(recs)["slo"]["victim"]
    live = metrics.snapshot()["counters"]["service.job_retries{tenant=victim}"]
    assert slo["retries"] == live == 1

    dumps = sorted(flight_dir.glob("mplc_flight_job_quarantined_*.json"))
    assert dumps, "quarantine must write a postmortem flight record"
    payload = json.loads(dumps[-1].read_text())
    assert payload["reason"] == "job_quarantined"
    assert payload["extra"]["job"] == job.job_id
    assert payload["extra"]["tenant"] == "victim"
    # the ring holds the failing batch's spans. The ring is
    # process-global (earlier tests' records may precede), so scope the
    # assertions to records after THIS job's submit event.
    ring = payload["ring_records"]
    submit_idx = max(i for i, r in enumerate(ring)
                     if r["name"] == "service.submit"
                     and r["attrs"].get("job") == job.job_id)
    ours = ring[submit_idx:]
    names = [r["name"] for r in ours]
    # both failing attempts' injected faults, at their batch ordinals
    fault_ordinals = [r["attrs"]["ordinal"] for r in ours
                      if r["name"] == "engine.fault"]
    assert fault_ordinals == [1, 2]
    # and the batch machinery around them
    assert "engine.dispatch" in names
    assert "service.job_fault" in names
    assert payload["metrics"]["counters"]["engine.faults_injected"] >= 2
    # the quarantine log line references the postmortem path
    quarantine_logs = [r.message for r in caplog.records
                       if "quarantining job" in r.message]
    assert quarantine_logs and str(dumps[-1]) in quarantine_logs[-1]
    assert metrics.snapshot()["counters"]["obs.flight_dumps"] >= 1


def test_journal_corruption_writes_postmortem(monkeypatch, tmp_path):
    from mplc_tpu.service import JournalCorruptError, SweepJournal

    flight_dir = tmp_path / "flight"
    monkeypatch.setenv("MPLC_TPU_FLIGHT_RECORDER_DIR", str(flight_dir))
    path = tmp_path / "wal.jsonl"
    j = SweepJournal(path)
    j.append({"type": "submit", "job": "job1"})
    j.append({"type": "value", "job": "job1", "subset": [0], "value": 0.5})
    j.close()
    # corrupt the FIRST record (mid-file, good records after): not a torn
    # tail -> replay must refuse AND leave a postmortem
    lines = path.read_bytes().split(b"\n")
    lines[0] = lines[0][:-6] + b"xxxx}"
    path.write_bytes(b"\n".join(lines))
    with pytest.raises(JournalCorruptError, match="postmortem"):
        SweepJournal.replay(path)
    assert list(flight_dir.glob("mplc_flight_journal_corrupt_*.json"))


def test_flight_dump_never_raises(monkeypatch):
    # an unwritable directory: dump returns None instead of raising
    monkeypatch.setenv("MPLC_TPU_FLIGHT_RECORDER_DIR",
                       "/proc/definitely/not/writable")
    assert flight.dump("test_reason") is None


# -- bearer-token auth + tenant redaction (MPLC_TPU_METRICS_TOKEN) ------------

def _get_auth(url, token=None):
    req = urllib.request.Request(url)
    if token is not None:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        resp = urllib.request.urlopen(req, timeout=10)
        return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_token_gates_metrics_and_varz_but_not_healthz(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_METRICS_PORT", "0")
    monkeypatch.setenv("MPLC_TPU_METRICS_TOKEN", "s3cret")
    svc = SweepService(start=False)
    try:
        base = f"http://127.0.0.1:{export.active_server().port}"
        jobA = svc.submit(_scenario(0), tenant="tenantA")
        jobB = svc.submit(_scenario(1), tenant="tenantB")
        svc.run_until_idle()
        assert jobA.status == jobB.status == "completed"

        # no token / wrong token -> 401 on the data endpoints; a
        # non-ASCII header must 401 too, never TypeError the handler
        for url in ("/metrics", "/varz"):
            assert _get_auth(base + url)[0] == 401
            assert _get_auth(base + url, token="wrong")[0] == 401
            assert _get_auth(base + url, token="ümlaut")[0] == 401
        # liveness probes stay open (a 401ing health check reads "down")
        assert _get_auth(base + "/healthz")[0] in (200, 503)

        # the MASTER token is the operator credential: full /metrics and
        # a full, unredacted /varz
        status, text = _get_auth(base + "/metrics", token="s3cret")
        assert status == 200 and "mplc_service_jobs_completed" in text
        status, body = _get_auth(base + "/varz", token="s3cret")
        assert status == 200 and "tenantB" in body
        assert "redacted" not in body

        # the per-tenant credential authenticates the viewer claim: own
        # rows full, every other tenant redacted
        tokA = export.tenant_token("s3cret", "tenantA")
        status, body = _get_auth(base + "/varz?tenant=tenantA",
                                 token=tokA)
        assert status == 200
        jobs = json.loads(body)[svc._provider_key]["jobs"]
        rows = {r["tenant"]: r for r in jobs.values()}
        assert "tenantA" in rows and not rows["tenantA"].get("redacted")
        assert "tenantB" not in rows          # identity hashed away
        redacted = [r for r in jobs.values() if r.get("redacted")]
        assert redacted and redacted[0]["tenant"].startswith("tenant-")
        assert "method" not in redacted[0]    # work detail dropped
        assert "status" in redacted[0]        # scheduling facts kept
        # the raw tenant name must not appear anywhere in the body
        assert "tenantB" not in body

        # the viewer claim cannot be forged: tenant A's credential with
        # ?tenant=tenantB (or no claim at all) is denied, and a tenant
        # credential never unlocks the unredacted /metrics text
        assert _get_auth(base + "/varz?tenant=tenantB",
                         token=tokA)[0] == 401
        assert _get_auth(base + "/varz", token=tokA)[0] == 401
        assert _get_auth(base + "/metrics?tenant=tenantA",
                         token=tokA)[0] == 401
    finally:
        svc.shutdown(drain=False)


def test_unset_token_leaves_endpoints_open(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_METRICS_PORT", "0")
    svc = SweepService(start=False)
    try:
        base = f"http://127.0.0.1:{export.active_server().port}"
        assert _get(base + "/metrics")[0] == 200
        status, body = _get(base + "/varz")
        assert status == 200
        # no token -> no redaction marker anywhere
        assert "redacted" not in body
    finally:
        svc.shutdown(drain=False)


def test_redact_varz_rewrites_tenant_metric_labels():
    doc = {
        "metrics": {"counters": {
            'service.device_seconds{tenant=alice}': 1.5,
            'service.device_seconds{tenant=bob}': 2.5,
            "engine.retries": 0}},
        "svc": {"jobs": {"job1": {"tenant": "alice", "status": "running",
                                  "priority": 1, "age_sec": 2.0,
                                  "method": "Shapley values"}},
                "tenant_device_seconds": {"alice": 1.5, "bob": 2.5}},
    }
    out = export.redact_varz(doc, viewer="alice")
    counters = out["metrics"]["counters"]
    assert 'service.device_seconds{tenant=alice}' in counters
    assert 'service.device_seconds{tenant=bob}' not in counters
    assert counters["engine.retries"] == 0      # unlabeled keys untouched
    assert out["svc"]["jobs"]["job1"]["method"] == "Shapley values"
    tds = out["svc"]["tenant_device_seconds"]
    assert tds["alice"] == 1.5 and "bob" not in tds
    assert sum(v == 2.5 for v in tds.values()) == 1  # value kept, key hashed
    # a different viewer sees alice redacted instead — including the
    # caller-supplied job id, which is hashed out of the row KEY
    out2 = export.redact_varz(doc, viewer="bob")
    rows2 = out2["svc"]["jobs"]
    assert "job1" not in rows2
    (jid, red), = rows2.items()
    assert jid.startswith("job-") and red["redacted"] is True


def test_redact_health_hashes_job_ids():
    doc = {"healthy": True, "running_job": "acme-payroll-q3",
           "running_jobs": ["acme-payroll-q3", None],
           "providers": {"svc": {"workers": [
               {"worker": 0, "running_job": "acme-payroll-q3",
                "stalled": False}]}},
           "queue_depth": 3}
    out = export.redact_health(doc, key="tok")
    assert out["running_job"].startswith("job-")
    assert out["running_jobs"][0].startswith("job-")
    assert out["running_jobs"][1] is None
    worker = out["providers"]["svc"]["workers"][0]
    assert worker["running_job"].startswith("job-")
    assert worker["stalled"] is False and out["queue_depth"] == 3
    assert "acme-payroll-q3" not in json.dumps(out)
