"""Shared scenario/model builders for the test suite.

One recipe for "construct a small 3-partner scenario and run the full prep
sequence" (instantiate partners -> split -> batch sizes -> corruption), so
the class-API, sharding, and fixture scenarios can't silently diverge —
and one copy of the tiny categorical cluster MLP that the lflip/PVRL
trajectory (test_e2e) and EM-oracle (test_lflip_em) tests both exercise.
"""


def cluster_mlp_model(num_classes=4, in_features=16, hidden=32):
    """2-layer categorical MLP that compiles in seconds on CPU."""
    import optax

    import jax
    import jax.numpy as jnp

    from mplc_tpu.models import layers as L
    from mplc_tpu.models.core import Model

    def init(rng):
        r1, r2 = jax.random.split(rng)
        return {"d1": L.dense_init(r1, in_features, hidden),
                "d2": L.dense_init(r2, hidden, num_classes)}

    def apply(params, x, train=False, rng=None, compute_dtype=jnp.float32):
        h = jax.nn.relu(L.dense(params["d1"], x.astype(compute_dtype)))
        return L.dense(params["d2"], h).astype(jnp.float32)

    return Model("cluster_mlp", init, apply, "categorical", num_classes,
                 lambda: optax.adam(2e-2))


def make_cluster_data(rng, n, centers):
    """(x, one-hot y) for the Gaussian-cluster categorical problem: one
    draw per sample from `centers[y] + N(0, 1)`."""
    import numpy as np

    num_classes, features = centers.shape
    y = rng.integers(0, num_classes, n)
    x = (centers[y] + rng.normal(size=(n, features))).astype(np.float32)
    return x, np.eye(num_classes, dtype=np.float32)[y]


def cluster_mlp_dataset(n=600, num_classes=4, seed=20, scale=2.5):
    """Tiny categorical Dataset: Gaussian clusters + the 2-layer MLP."""
    import numpy as np

    from mplc_tpu.data.datasets import Dataset

    mlp = cluster_mlp_model(num_classes)
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(num_classes, 16)).astype(np.float32) * scale
    x, y = make_cluster_data(rng, n, centers)
    xt, yt = make_cluster_data(rng, n // 3, centers)
    return Dataset("clusters", (16,), num_classes, x, y, xt, yt,
                   model=mlp, provenance="test")


def build_scenario(**overrides):
    """A prepped 3-partner scenario; pass `dataset=` or `dataset_name=`
    plus any Scenario kwarg to override the quick defaults."""
    from mplc_tpu.scenario import Scenario

    params = dict(partners_count=3, amounts_per_partner=[0.2, 0.3, 0.5],
                  epoch_count=4, minibatch_count=2,
                  gradient_updates_per_pass_count=4, is_early_stopping=False,
                  experiment_path="/tmp/mplc_tpu_tests", seed=3)
    params.update(overrides)
    sc = Scenario(**params)
    sc.instantiate_scenario_partners()
    sc.split_data(is_logging_enabled=False)
    sc.compute_batch_sizes()
    sc.data_corruption()
    return sc
