"""Shared scenario builders for the test suite.

One recipe for "construct a small 3-partner scenario and run the full prep
sequence" (instantiate partners -> split -> batch sizes -> corruption), so
the class-API, sharding, and fixture scenarios can't silently diverge.
"""


def build_scenario(**overrides):
    """A prepped 3-partner scenario; pass `dataset=` or `dataset_name=`
    plus any Scenario kwarg to override the quick defaults."""
    from mplc_tpu.scenario import Scenario

    params = dict(partners_count=3, amounts_per_partner=[0.2, 0.3, 0.5],
                  epoch_count=4, minibatch_count=2,
                  gradient_updates_per_pass_count=4, is_early_stopping=False,
                  experiment_path="/tmp/mplc_tpu_tests", seed=3)
    params.update(overrides)
    sc = Scenario(**params)
    sc.instantiate_scenario_partners()
    sc.split_data(is_logging_enabled=False)
    sc.compute_batch_sizes()
    sc.data_corruption()
    return sc
