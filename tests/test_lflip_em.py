"""Unit oracle for the lflip EM update (`MplTrainer._lflip_flip`).

NumPy mirror of the reference scheme (multi_partner_learning.py:452-516):

  theta_[i, :] = preds[i, :] * theta[:, argmax(y_i)]; l1-normalize COLUMNS
  theta        = theta_.T @ y_batch;                  l1-normalize ROWS
  theta_       = recompute with the new theta;        l1-normalize COLUMNS
  y_flip[i]    ~ Categorical(theta_[i, :])  (first index with cdf >= u)

The oracle shares only the model's predictions (and the uniform draw for
the deterministic-flip check) with the engine — the EM algebra is
recomputed in NumPy. The full lflip training trajectory is covered by
tests/test_e2e.py::test_sbs_lflip_pvrl_methods; the discrete label
resampling makes a trajectory-level parity oracle flaky by construction,
so the EM step is pinned down here instead.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


K = 4      # classes
N = 12     # minibatch rows


@pytest.fixture(scope="module")
def lflip_parts():
    from helpers import cluster_mlp_model
    from mplc_tpu.mpl.engine import MplTrainer, TrainConfig

    model = cluster_mlp_model(K)
    cfg = TrainConfig(approach="lflip", aggregator="data-volume",
                      epoch_count=1, minibatch_count=1,
                      gradient_updates_per_pass=1, is_early_stopping=False)
    trainer = MplTrainer(model, cfg)
    params = model.init(jax.random.PRNGKey(0))

    rng = np.random.default_rng(77)
    x = rng.normal(size=(N, 16)).astype(np.float32)
    y = np.eye(K, dtype=np.float32)[rng.integers(0, K, N)]
    preds = np.asarray(jax.nn.softmax(model.apply(params, x), axis=-1),
                       np.float64)
    return trainer, model, params, x, y, preds


def _l1_cols(a):
    return a / np.maximum(np.sum(np.abs(a), axis=0, keepdims=True), 1e-12)


def _l1_rows(a):
    return a / np.maximum(np.sum(np.abs(a), axis=1, keepdims=True), 1e-12)


def _reference_em(preds, y, theta):
    """The reference's EM algebra, straight from the loop at
    multi_partner_learning.py:478-489 (row i scaled by theta[:, argmax y_i]
    == preds * (y @ theta.T) for one-hot y)."""
    theta_post = _l1_cols(preds * (y @ theta.T))
    new_theta = _l1_rows(theta_post.T @ y)
    theta_post2 = _l1_cols(preds * (y @ new_theta.T))
    return new_theta, theta_post2


def _run_flip(trainer, params, x, y, theta, rng):
    perm = jnp.arange(N, dtype=jnp.int32)
    new_theta, y_flip, idx, valid = trainer._lflip_flip(
        params, jnp.asarray(theta, jnp.float32), jnp.asarray(x),
        jnp.asarray(y), perm, jnp.asarray(N, jnp.int32), 0, N, rng)
    assert np.asarray(valid).all()
    return np.asarray(new_theta, np.float64), np.asarray(y_flip)


def test_lflip_theta_update_matches_reference_em(lflip_parts):
    trainer, model, params, x, y, preds = lflip_parts
    rng0 = np.random.default_rng(3)
    # a generic (non-uniform, non-identity) flip matrix, rows on the simplex
    theta = _l1_rows(rng0.uniform(0.1, 1.0, (K, K)))

    new_theta, y_flip = _run_flip(trainer, params, x, y, theta,
                                  jax.random.PRNGKey(9))
    oracle_theta, oracle_post = _reference_em(preds, y, theta)

    np.testing.assert_allclose(new_theta, oracle_theta, atol=1e-5)
    # rows of the updated flip matrix are distributions
    np.testing.assert_allclose(new_theta.sum(axis=1), np.ones(K), atol=1e-5)
    # resampled labels are one-hot over K classes
    assert y_flip.shape == (N, K)
    np.testing.assert_allclose(y_flip.sum(axis=1), np.ones(N), atol=0)


def test_lflip_identity_theta_keeps_confident_labels(lflip_parts):
    """With theta = I the posterior is proportional to preds * y — each
    row's distribution is a point mass on the observed label, so the draw
    must reproduce y exactly (no flipping), for any rng."""
    trainer, model, params, x, y, preds = lflip_parts
    theta = np.eye(K)

    _, y_flip = _run_flip(trainer, params, x, y, theta,
                          jax.random.PRNGKey(123))
    np.testing.assert_array_equal(y_flip, y)


def test_lflip_draw_follows_posterior(lflip_parts):
    """The categorical draw must follow the post-update posterior: with
    the engine's own uniform u (shared rng, like the parity oracles) the
    drawn class is the first index where the row cdf reaches u."""
    trainer, model, params, x, y, preds = lflip_parts
    rng0 = np.random.default_rng(5)
    theta = _l1_rows(rng0.uniform(0.1, 1.0, (K, K)))
    key = jax.random.PRNGKey(42)

    _, y_flip = _run_flip(trainer, params, x, y, theta, key)
    _, oracle_post = _reference_em(preds, y, theta)

    u = np.asarray(jax.random.uniform(key, (N, 1)), np.float64)
    cdf = np.cumsum(oracle_post, axis=1)
    u = u * np.maximum(cdf[:, -1:], 1e-12)
    expect = np.argmax(u <= cdf, axis=1)
    np.testing.assert_array_equal(np.argmax(y_flip, axis=1), expect)
