"""Device-time accounting (obs/devcost.py): XLA cost truth, sampled
device fences, and per-tenant device-seconds metering.

Acceptance invariants pinned here:
  - MPLC_TPU_DEVICE_FENCE_RATE sampling is DETERMINISTIC (pure in the
    batch ordinal) and fencing NEVER changes v(S): sweeps with fences
    off / every batch / default rate are bit-identical, including under
    the transient/OOM fault ladder;
  - fenced sweeps emit engine.device_fence events + device_sec batch
    attrs, and the report derives the device row (extrapolation rule),
    the roofline row and mfu_xla from them;
  - cost-analysis DEGRADATION is safe: a backend/bundle without
    cost_analysis() falls back to the analytic proxy with no report
    schema breakage, and pre-devcost sidecars still format;
  - the service meters per-tenant device-seconds (counter, /varz,
    service row cost_share) and the meter SURVIVES a restart via
    journal replay;
  - submit(profile=True) captures a jax.profiler trace of exactly that
    job's quanta with the path on the terminal event.
"""

import json
import os

import numpy as np
import pytest

from mplc_tpu.contrib import bank
from mplc_tpu.contrib.engine import CharacteristicEngine
from mplc_tpu.contrib.shapley import powerset_order
from mplc_tpu.obs import devcost, export, metrics, report, trace
from mplc_tpu.service import SweepService

SUBSETS4 = powerset_order(4)


@pytest.fixture(autouse=True)
def _env(monkeypatch):
    for k in ("MPLC_TPU_FAULT_PLAN", "MPLC_TPU_SERVICE_FAULT_PLAN",
              "MPLC_TPU_DEVICE_FENCE_RATE", "MPLC_TPU_MAX_RETRIES",
              "MPLC_TPU_SEED_ENSEMBLE", "MPLC_TPU_PARTNER_FAULT_PLAN",
              "MPLC_TPU_PROFILE_DIR", "MPLC_TPU_METRICS_TOKEN",
              "MPLC_TPU_SERVICE_WORKERS"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("MPLC_TPU_RETRY_BACKOFF_SEC", "0")
    # small cap => several device batches, so fence ordinals and the
    # fault plan's batch addresses actually land
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "1")
    metrics.reset()
    yield
    export.stop()
    metrics.reset()


def _scenario(seed=0, partners=4):
    from helpers import build_scenario
    return build_scenario(partners_count=partners,
                          amounts_per_partner=[1.0 / partners] * partners,
                          dataset_name="titanic", epoch_count=2,
                          gradient_updates_per_pass_count=2, seed=seed)


# -- fence schedule -----------------------------------------------------------

def test_fence_interval_parsing(monkeypatch):
    assert devcost.fence_interval() == 16            # default 1/16
    assert devcost.fence_interval(0.25) == 4
    assert devcost.fence_interval(1.0) == 1
    assert devcost.fence_interval(2.0) == 1          # clamp to every batch
    assert devcost.fence_interval(0) == 0            # off
    monkeypatch.setenv("MPLC_TPU_DEVICE_FENCE_RATE", "0.5")
    assert devcost.fence_interval() == 2
    monkeypatch.setenv("MPLC_TPU_DEVICE_FENCE_RATE", "nope")
    with pytest.warns(UserWarning):
        assert devcost.fence_interval() == 16        # warn + fallback


def test_should_fence_is_deterministic_and_covers_ordinal_one():
    # pure function of (ordinal, interval): two evaluations agree
    for interval in (1, 2, 16):
        seq = [devcost.should_fence(o, interval) for o in range(1, 65)]
        assert seq == [devcost.should_fence(o, interval)
                       for o in range(1, 65)]
        assert seq[0] is True                        # ordinal 1 samples
        assert sum(seq) == len([o for o in range(1, 65)
                                if o % interval == 1 % interval])
    assert not any(devcost.should_fence(o, 0) for o in range(1, 65))


# -- fencing never changes v(S) ----------------------------------------------

def _sweep_values(monkeypatch, fence_rate=None, fault_plan=None):
    if fence_rate is None:
        monkeypatch.delenv("MPLC_TPU_DEVICE_FENCE_RATE", raising=False)
    else:
        monkeypatch.setenv("MPLC_TPU_DEVICE_FENCE_RATE", str(fence_rate))
    if fault_plan is None:
        monkeypatch.delenv("MPLC_TPU_FAULT_PLAN", raising=False)
    else:
        monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", fault_plan)
    eng = CharacteristicEngine(_scenario())
    eng.evaluate(SUBSETS4)
    return dict(eng.charac_fct_values)


def test_fencing_is_bit_identical_including_fault_ladder(monkeypatch):
    """The acceptance invariant: v(S) under fencing (off / every batch /
    default rate) is bit-identical, clean AND across the transient/OOM
    recovery ladder."""
    base = _sweep_values(monkeypatch, fence_rate=0)
    assert _sweep_values(monkeypatch, fence_rate=1) == base
    assert _sweep_values(monkeypatch, fence_rate=None) == base
    plan = "transient@batch2,oom@batch3"
    assert _sweep_values(monkeypatch, fence_rate=1, fault_plan=plan) == base
    assert _sweep_values(monkeypatch, fence_rate=0, fault_plan=plan) == base


# -- fenced sweeps feed the report -------------------------------------------

def test_fenced_sweep_emits_samples_and_report_rows(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_DEVICE_FENCE_RATE", "1")
    eng = CharacteristicEngine(_scenario(seed=1))
    with trace.collect() as recs:
        eng.evaluate(SUBSETS4)
    fences = [r for r in recs if r["name"] == "engine.device_fence"]
    batches = [r for r in recs if r["name"] == "engine.batch"]
    assert fences, "rate=1 must fence every batch"
    assert len(fences) == len(batches)
    assert all(r["attrs"]["interval"] == 1 for r in fences)
    fenced = [b for b in batches if b["attrs"].get("device_sec") is not None]
    assert len(fenced) == len(batches)
    # the histogram + meter saw every sample
    assert metrics.histogram("engine.device_step_sec").count == len(batches)
    m = eng.device_meter.snapshot()
    assert m["fenced_batches"] == len(batches)
    assert m["fenced_coalitions"] == m["coalitions"] == len(SUBSETS4)
    sec, basis = eng.device_meter.device_seconds()
    assert basis == "fenced" and sec > 0

    rep = report.sweep_report(recs, peak_flops=1e12, hbm_bytes_per_s=1e11)
    dt = rep["device_time"]
    assert dt["basis"] == "fenced"
    assert dt["fenced_batches"] == len(batches)
    # every coalition fenced => extrapolation == the measured sum
    assert dt["device_s"] == pytest.approx(dt["device_step_s"]["sum"])
    # bank bundles carried XLA cost => roofline + mfu_xla present
    assert rep["roofline"]["programs"]
    assert rep["compute"]["mfu_xla"] is not None
    assert rep["compute"]["mfu_xla_basis"] == "device_fenced"
    text = report.format_report(rep)
    assert "device      fenced=" in text
    assert "roofline" in text and "mfu_xla=" in text


def test_default_rate_fences_a_strict_subset(monkeypatch):
    monkeypatch.delenv("MPLC_TPU_DEVICE_FENCE_RATE", raising=False)
    eng = CharacteristicEngine(_scenario(seed=2))
    with trace.collect() as recs:
        eng.evaluate(SUBSETS4)
    batches = [r for r in recs if r["name"] == "engine.batch"]
    fenced = [b for b in batches if b["attrs"].get("device_sec") is not None]
    # ordinal 1 always samples at the default 1/16 rate; a tiny sweep
    # (< 16 batches) fences exactly one batch
    assert len(fenced) >= 1
    assert [b["attrs"]["ordinal"] for b in fenced] == [
        o for o in (b["attrs"]["ordinal"] for b in batches)
        if devcost.should_fence(o, 16)]


# -- XLA cost truth: bank, manifest, degradation ------------------------------

def test_bank_bundles_carry_cost_and_manifest_persists_it(
        tmp_path, monkeypatch):
    monkeypatch.setattr(bank, "manifest_dir", lambda: str(tmp_path))
    bank.reset_bank()
    eng = CharacteristicEngine(_scenario(seed=3))
    with trace.collect() as recs:
        eng.evaluate(SUBSETS4)
    compiles = [r for r in recs if r["name"] == "bank.compile"]
    assert compiles and all(r["attrs"].get("flops") for r in compiles)
    with open(tmp_path / bank.MANIFEST_NAME) as f:
        doc = json.load(f)
    assert doc["programs"]
    assert doc["costs"], "compiled program costs must persist"
    costs = eng.program_bank.persistent_costs()
    assert set(costs) <= set(doc["programs"])
    assert all(c["flops"] > 0 for c in costs.values())
    assert bank.bank_stats()["costed_programs"] > 0


def test_cost_analysis_unavailable_degrades_to_analytic_proxy(
        monkeypatch):
    """Backends/executables without cost_analysis(): the bank banks
    cost-less bundles, the sweep still runs, and the report falls back
    to the analytic mfu_proxy with no schema breakage."""
    monkeypatch.setattr(devcost, "bundle_cost", lambda bundle: None)
    monkeypatch.setenv("MPLC_TPU_DEVICE_FENCE_RATE", "0")
    bank.reset_bank()
    eng = CharacteristicEngine(_scenario(seed=4))
    with trace.collect() as recs:
        vals = eng.evaluate(SUBSETS4)
    assert len(vals) == len(SUBSETS4)
    batches = [r for r in recs if r["name"] == "engine.batch"]
    assert batches and not any(b["attrs"].get("flops") for b in batches)
    rep = report.sweep_report(recs, flops_per_sample=1e6, peak_flops=1e12)
    assert "roofline" not in rep and "device_time" not in rep
    assert rep["compute"]["mfu_proxy"] is not None      # analytic fallback
    assert "mfu_xla" not in rep["compute"]
    report.format_report(rep)                           # renders


def test_partial_cost_and_inline_jit_batches_mix_safely():
    """A record stream mixing costed (banked) and cost-less (inline-jit
    / OOM-rebucketed fallback) batches reports the costed share only,
    and a partial cost (flops without bytes) renders with n/a cells."""
    recs = [
        {"name": "engine.batch", "dur": 1.0,
         "attrs": {"width": 8, "slot_count": 3, "coalitions": 4,
                   "padding": 4, "epochs": 8, "flops": 2e9}},
        {"name": "engine.batch", "dur": 1.0,
         "attrs": {"width": 8, "slot_count": 3, "coalitions": 4,
                   "padding": 4, "epochs": 8}},   # fallback width: no cost
    ]
    rep = report.sweep_report(recs, peak_flops=1e12)
    rl = rep["roofline"]["programs"]
    assert len(rl) == 1 and rl[0]["batches"] == 1
    assert rl[0]["arithmetic_intensity"] is None   # bytes unknown
    assert rl[0]["basis"] == "host_span"
    assert rep["compute"]["mfu_xla_basis"] == "host_span"
    text = report.format_report(rep)
    assert "AI=n/a" in text


def test_pre_devcost_sidecars_format_unchanged():
    """Old record streams (no device/cost attrs) keep the exact old
    schema, and an old service row without device_sec bills cost_share
    by span share."""
    recs = [
        {"name": "engine.evaluate", "dur": 2.0,
         "attrs": {"requested": 4, "missing": 1}},
        {"name": "engine.batch", "dur": 1.5,
         "attrs": {"width": 8, "slot_count": 2, "coalitions": 6,
                   "padding": 2, "epochs": 24}},
        {"name": "service.slice", "dur": 0.6, "attrs": {"tenant": "a"}},
        {"name": "service.slice", "dur": 0.4, "attrs": {"tenant": "b"}},
        {"name": "service.job", "attrs": {"job": "j1", "tenant": "a",
                                          "status": "completed"}},
    ]
    rep = report.sweep_report(recs)
    assert "device_time" not in rep and "roofline" not in rep
    svc = rep["service"]
    assert svc["cost_basis"] == "host_span"
    assert svc["per_tenant"]["a"]["cost_share"] == pytest.approx(0.6)
    assert svc["per_tenant"]["a"]["host_share"] == pytest.approx(0.6)
    report.format_report(rep)


# -- the meter ----------------------------------------------------------------

def test_device_meter_bases_and_delta():
    m = devcost.DeviceMeter(interval=4)
    m.note(4, span_sec=1.0, device_sec=0.5, flops=1e9, bytes_accessed=1e8)
    before = m.snapshot()
    m.note(4, span_sec=1.0)
    sec, basis = m.device_seconds()
    # 0.5 s over 4 fenced coalitions, extrapolated to 8
    assert (sec, basis) == (pytest.approx(1.0), "fenced")
    delta = devcost.meter_delta(before, m.snapshot())
    assert delta["batches"] == 1 and delta["fenced_batches"] == 0
    # the delta has no fenced sample and no peak -> host span
    assert devcost.estimate_device_seconds(delta) == (
        pytest.approx(1.0), "host_span")
    # cost model: flops scaled per-coalition over peak
    cm = {"coalitions": 8, "costed_coalitions": 4, "flops": 1e9,
          "fenced_coalitions": 0, "span_sec": 3.0}
    sec, basis = devcost.estimate_device_seconds(cm, peak_flops=1e12)
    assert (sec, basis) == (pytest.approx(2e-3), "cost_model")
    assert devcost.estimate_device_seconds({}) == (0.0, "none")
    assert devcost.merge_basis("host_span", "fenced") == "fenced"
    assert devcost.merge_basis(None, "cost_model") == "cost_model"


# -- service metering + journal replay ---------------------------------------

def test_service_meters_tenant_device_seconds_and_replay_restores(
        tmp_path, monkeypatch):
    monkeypatch.setenv("MPLC_TPU_DEVICE_FENCE_RATE", "1")
    journal = tmp_path / "wal.jsonl"
    with trace.collect() as recs:
        svc = SweepService(journal_path=journal, start=False)
        job = svc.submit(_scenario(seed=5), tenant="tA")
        svc.run_until_idle()
        assert job.status == "completed"
        svc.shutdown(drain=False)
    assert job.device_seconds > 0
    assert job.device_basis == "fenced"
    billed = metrics.counter("service.device_seconds", tenant="tA").value
    assert billed == pytest.approx(job.device_seconds)
    # /metrics exposition carries the per-tenant series
    assert 'mplc_service_device_seconds{tenant="tA"}' \
        in export.prometheus_text()
    # /varz carries the lifetime per-tenant meter and the per-job figure
    varz = svc.varz_view()
    assert varz["tenant_device_seconds"]["tA"] == pytest.approx(
        job.device_seconds, abs=1e-6)
    # the slice spans carry per-quantum billing; the report's service
    # row bills cost_share by device-seconds with host_share alongside
    slices = [r for r in recs if r["name"] == "service.slice"]
    assert sum(r["attrs"].get("device_sec") or 0 for r in slices) == \
        pytest.approx(job.device_seconds)
    assert any(r["attrs"].get("device_basis") == "fenced" for r in slices)
    rep = report.sweep_report(recs)
    svc_row = rep["service"]
    assert svc_row["cost_basis"] == "device_seconds"
    assert svc_row["per_tenant"]["tA"]["device_seconds"] == pytest.approx(
        job.device_seconds)
    assert svc_row["per_tenant"]["tA"]["cost_share"] == pytest.approx(1.0)
    assert svc_row["per_tenant"]["tA"]["host_share"] == pytest.approx(1.0)
    term = [r for r in recs if r["name"] == "service.job"][-1]
    assert term["attrs"]["device_seconds"] == pytest.approx(
        job.device_seconds)
    assert term["attrs"]["device_basis"] == "fenced"

    # SAME-process reconstruction first: the process-global counter
    # already holds the live billing, so replay must RAISE-to-total
    # (a no-op here), never blind-increment into a double count
    svc_same = SweepService(journal_path=journal, start=False)
    assert metrics.counter("service.device_seconds",
                           tenant="tA").value == pytest.approx(billed)
    svc_same.shutdown(drain=False)

    # kill -> restart (fresh process simulated by resetting the
    # registry): replay restores the tenant meter AND its counter
    metrics.reset()
    svc2 = SweepService(journal_path=journal, start=False)
    assert svc2._tenant_device_seconds["tA"] == pytest.approx(
        job.device_seconds)
    assert metrics.counter("service.device_seconds",
                           tenant="tA").value == pytest.approx(billed)
    assert svc2.varz_view()["tenant_device_seconds"]["tA"] > 0
    svc2.shutdown(drain=False)


def test_method_job_bills_host_span_when_unfenced_uncosted(
        tmp_path, monkeypatch):
    """A job with no fenced samples and no peak figure (CPU mesh) still
    bills SOMETHING, explicitly labeled host_span — never silently 0."""
    monkeypatch.setenv("MPLC_TPU_DEVICE_FENCE_RATE", "0")
    monkeypatch.setattr(devcost, "bundle_cost", lambda bundle: None)
    bank.reset_bank()
    svc = SweepService(start=False)
    job = svc.submit(_scenario(seed=6), tenant="tB")
    svc.run_until_idle()
    assert job.status == "completed"
    assert job.device_seconds > 0
    assert job.device_basis == "host_span"
    svc.shutdown(drain=False)


# -- per-job device profiling -------------------------------------------------

def test_profile_flag_wires_jax_profiler_per_job(tmp_path, monkeypatch):
    import jax
    calls = {"start": [], "stop": 0}
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls["start"].append(d))

    def _stop():
        calls["stop"] += 1
    monkeypatch.setattr(jax.profiler, "stop_trace", _stop)
    monkeypatch.setenv("MPLC_TPU_PROFILE_DIR", str(tmp_path / "prof"))
    with trace.collect() as recs:
        svc = SweepService(start=False)
        plain = svc.submit(_scenario(seed=7), tenant="tP")
        prof = svc.submit(_scenario(seed=8), tenant="tP", profile=True)
        svc.run_until_idle()
        svc.shutdown(drain=False)
    expected = os.path.join(str(tmp_path / "prof"), prof.job_id)
    # every start targeted the profiled job's own dir, starts == stops
    assert calls["start"] and set(calls["start"]) == {expected}
    assert calls["stop"] == len(calls["start"])
    assert prof.profile_path == expected
    assert plain.profile_path is None
    terms = {r["attrs"]["job"]: r["attrs"] for r in recs
             if r["name"] == "service.job"}
    assert terms[prof.job_id]["profile_path"] == expected
    assert "profile_path" not in terms[plain.job_id]


def test_profile_without_dir_is_noop(monkeypatch):
    import jax
    monkeypatch.delenv("MPLC_TPU_PROFILE_DIR", raising=False)
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: pytest.fail("must not start"))
    svc = SweepService(start=False)
    job = svc.submit(_scenario(seed=9), tenant="tQ", profile=True)
    svc.run_until_idle()
    assert job.status == "completed" and job.profile_path is None
    svc.shutdown(drain=False)


# -- Perfetto device track ----------------------------------------------------

def test_chrome_trace_draws_fences_on_device_track():
    from mplc_tpu.obs import chrome_trace
    recs = [
        {"name": "engine.batch", "ts": 1.0, "dur": 0.5, "thread": 7,
         "attrs": {"ordinal": 1, "width": 8, "coalitions": 4}},
        {"name": "engine.device_fence", "ts": 1.1, "dur": 0.2, "thread": 7,
         "attrs": {"ordinal": 1, "width": 8, "coalitions": 4,
                   "interval": 1}},
    ]
    doc = chrome_trace.to_chrome(recs)
    dev = [e for e in doc["traceEvents"]
           if e["ph"] == "X" and e["pid"] == 2]
    assert [e["name"] for e in dev] == ["engine.device_fence"]
    host = [e for e in doc["traceEvents"]
            if e["ph"] == "X" and e["pid"] == 1]
    assert [e["name"] for e in host] == ["engine.batch"]
    names = {(e.get("pid"), e["args"]["name"]) for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert (2, "device (fenced samples)") in names


def test_meter_excludes_eval_only_from_fenced_extrapolation():
    """Reconstruction (eval-only) coalitions cost orders of magnitude
    less than training ones: they bill at their own host span, never at
    the fenced training rate (the inflation bug class)."""
    m = devcost.DeviceMeter(interval=1)
    m.note(4, span_sec=2.0, device_sec=1.0)              # train, fenced
    m.note(100, span_sec=0.05, eval_only=True)           # recon evals
    sec, basis = m.device_seconds()
    # 1 s over 4 fenced TRAIN coalitions -> 1 s train + 0.05 s eval span
    # (the naive all-coalition rule would bill 26 s)
    assert basis == "fenced"
    assert sec == pytest.approx(1.05)
    # cost-model basis gets the same split
    cm = {"coalitions": 108, "eval_coalitions": 100, "eval_span_sec": 0.05,
          "costed_coalitions": 4, "flops": 4e9, "fenced_coalitions": 0}
    sec, basis = devcost.estimate_device_seconds(cm, peak_flops=1e12)
    assert basis == "cost_model"
    assert sec == pytest.approx(8e-3 + 0.05)


def test_report_device_row_excludes_recon_coalitions():
    recs = [
        {"name": "engine.batch", "dur": 2.0,
         "attrs": {"width": 8, "slot_count": 3, "coalitions": 4,
                   "padding": 4, "epochs": 8, "device_sec": 1.0}},
        {"name": "engine.batch", "dur": 0.05,
         "attrs": {"width": 8, "slot_count": 3, "coalitions": 100,
                   "padding": 0, "epochs": 0, "eval_only": True}},
    ]
    rep = report.sweep_report(recs)
    dt = rep["device_time"]
    assert dt["device_s"] == pytest.approx(1.0)   # train share only
    assert dt["eval_coalitions_excluded"] == 100


def test_failed_quantum_billing_reaches_the_report(monkeypatch):
    """A quantum that faults mid-run bills its device time to the
    counter AND the trace stream (a replacement service.slice event —
    the cancelled span never emits), so the report's per-tenant
    device_seconds agrees with /metrics for exactly the tenants whose
    faults consumed device time."""
    monkeypatch.setenv("MPLC_TPU_DEVICE_FENCE_RATE", "1")
    monkeypatch.setenv("MPLC_TPU_MAX_RETRIES", "1")
    monkeypatch.setenv("MPLC_TPU_SERVICE_FAULT_PLAN", "crash@job1:batch2")
    with trace.collect() as recs:
        svc = SweepService(start=False)
        job = svc.submit(_scenario(seed=11), tenant="tF")
        svc.run_until_idle()
        svc.shutdown(drain=False)
    # the injected crash fires once; the re-queued attempt completes —
    # what matters is that the FAULTED attempt's device time was billed
    # and surfaced, not dropped with the cancelled span
    assert job.status == "completed"
    assert job.device_seconds > 0
    billed = metrics.counter("service.device_seconds", tenant="tF").value
    assert billed == pytest.approx(job.device_seconds)
    slices = [r for r in recs if r["name"] == "service.slice"]
    faulted = [r for r in slices if r["attrs"].get("outcome") == "fault"]
    assert faulted and faulted[0]["attrs"]["device_sec"] > 0
    rep = report.sweep_report(recs)
    assert rep["service"]["per_tenant"]["tF"]["device_seconds"] == \
        pytest.approx(billed)


def test_cpu_degraded_batches_never_blend_into_fenced_rate(monkeypatch):
    """A mixed run (device batches fenced, OOM tail on the CPU rung)
    must not extrapolate the fenced device rate over CPU coalitions (or
    vice versa): the degraded class bills at its own host span."""
    # meter-level: device rate 0.1 s/coalition over 10 train coalitions,
    # plus 5 CPU coalitions that took 50 s of (synchronous) host span
    m = devcost.DeviceMeter(interval=1)
    m.note(10, span_sec=1.5, device_sec=1.0)
    m.note(5, span_sec=50.0, degraded=True)
    sec, basis = m.device_seconds()
    assert basis == "fenced"
    assert sec == pytest.approx(1.0 + 50.0)   # not (15/10)*1.0 blended
    # engine-level: an OOM-degraded sweep's CPU batches carry NO fence
    # samples (the rung no longer fences) and are excluded from the
    # report's extrapolation
    monkeypatch.setenv("MPLC_TPU_DEVICE_FENCE_RATE", "1")
    monkeypatch.setenv("MPLC_TPU_MAX_CAP_HALVINGS", "1")
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "oom@batch2,oom@batch3")
    eng = CharacteristicEngine(_scenario(seed=12))
    with trace.collect() as recs:
        eng.evaluate(SUBSETS4)
    batches = [r for r in recs if r["name"] == "engine.batch"]
    cpu = [b for b in batches if b["attrs"].get("degraded") == "cpu"]
    assert cpu, "the ladder must have reached the CPU rung"
    assert all(b["attrs"].get("device_sec") is None for b in cpu)
    rep = report.sweep_report(recs)
    dt = rep.get("device_time")
    if dt is not None:   # device batches before the ladder fenced
        assert dt["degraded_coalitions_excluded"] == sum(
            b["attrs"]["coalitions"] for b in cpu)
    snap = eng.device_meter.snapshot()
    assert snap["degraded_coalitions"] == sum(
        b["attrs"]["coalitions"] for b in cpu)


def test_cost_harvest_failure_never_discards_a_good_compile(monkeypatch):
    """An observability failure (exotic cost_analysis schema) must bank
    the bundle WITHOUT cost, not tombstone it as a failed compile."""
    def boom(bundle):
        raise RuntimeError("exotic cost schema")
    monkeypatch.setattr(devcost, "bundle_cost", boom)
    bank.reset_bank()
    eng = CharacteristicEngine(_scenario(seed=13))
    with trace.collect() as recs:
        vals = eng.evaluate(SUBSETS4)
    assert len(vals) == len(SUBSETS4)
    stats = bank.bank_stats()
    assert stats["failed_compiles"] == 0
    assert stats["programs"] > 0              # bundles really banked
    assert metrics.counter("bank.compiles").value > 0
    # non-numeric cost values degrade to None, never raise
    class Weird:
        def cost_analysis(self):
            return {"flops": ["not", "a", "number"]}
    assert devcost.cost_analysis(Weird()) is None


def test_failed_slice_events_keep_slo_accounting_clean(monkeypatch):
    """Outcome-bearing replacement slice events bill device time but
    never inflate slice counts, span-seconds or the slo quantiles —
    those must keep mirroring the live service.slice_sec histogram,
    which observes only successful quanta."""
    recs = [
        {"name": "service.slice", "dur": 1.0,
         "attrs": {"tenant": "a", "batches": 2, "coalitions": 4,
                   "device_sec": 0.5}},
        {"name": "service.slice", "dur": 9.0,
         "attrs": {"tenant": "a", "device_sec": 2.0,
                   "outcome": "fault"}},
        {"name": "service.job", "attrs": {"job": "j", "tenant": "a",
                                          "status": "completed"}},
    ]
    rep = report.sweep_report(recs)
    t = rep["service"]["per_tenant"]["a"]
    assert t["slices"] == 1 and t["failed_slices"] == 1
    assert t["seconds"] == pytest.approx(1.0)       # not 10.0
    assert t["device_seconds"] == pytest.approx(2.5)
    assert rep["slo"]["a"]["slice_s"]["count"] == 1  # failed dur excluded
