"""Scenario parameter surface, validation, grid expansion, results schema."""

import numpy as np
import pytest

from mplc_tpu.scenario import Scenario
from mplc_tpu.utils import get_scenario_params_list


def _tiny_kwargs(ds, **over):
    kw = dict(partners_count=3, amounts_per_partner=[0.3, 0.3, 0.4], dataset=ds,
              epoch_count=2, minibatch_count=2, gradient_updates_per_pass_count=2,
              is_early_stopping=False, experiment_path="/tmp/mplc_tpu_tests",
              is_dry_run=True)
    kw.update(over)
    return kw


def test_unknown_kwarg_raises(tiny_image_dataset):
    with pytest.raises(Exception, match="Unrecognised parameters"):
        Scenario(**_tiny_kwargs(tiny_image_dataset), not_a_param=1)


def test_unknown_approach_raises(tiny_image_dataset):
    with pytest.raises(KeyError):
        Scenario(**_tiny_kwargs(tiny_image_dataset),
                 multi_partner_learning_approach="nope")


def test_aggregation_alias_spellings(tiny_image_dataset):
    sc1 = Scenario(**_tiny_kwargs(tiny_image_dataset),
                   aggregation_weighting="data_volume")
    sc2 = Scenario(**_tiny_kwargs(tiny_image_dataset),
                   aggregation_weighting="data-volume")
    assert sc1.aggregation_name == sc2.aggregation_name == "data-volume"
    with pytest.raises(ValueError):
        Scenario(**_tiny_kwargs(tiny_image_dataset), aggregation_weighting="bogus")


def test_aggregation_kwarg_takes_effect(tiny_image_dataset):
    """`aggregation:` in a config must drive the weighting (the reference
    whitelists it but silently ignores it — SURVEY §7 quirk, fixed here)."""
    sc = Scenario(**_tiny_kwargs(tiny_image_dataset), aggregation="local-score")
    assert sc.aggregation_name == "local-score"
    # matching pair (after spelling normalization) is fine
    sc2 = Scenario(**_tiny_kwargs(tiny_image_dataset),
                   aggregation="data_volume", aggregation_weighting="data-volume")
    assert sc2.aggregation_name == "data-volume"
    with pytest.raises(ValueError, match="Conflicting aggregation"):
        Scenario(**_tiny_kwargs(tiny_image_dataset),
                 aggregation="uniform", aggregation_weighting="local-score")


def test_partner_shards_param_recorded(tiny_image_dataset):
    sc = Scenario(**_tiny_kwargs(tiny_image_dataset), partner_shards=3)
    assert sc.partner_shards == 3
    df = sc.to_dataframe()
    assert set(df["partner_shards"]) == {3}
    assert Scenario(**_tiny_kwargs(tiny_image_dataset)).partner_shards == 1
    with pytest.raises(ValueError, match="partner_shards"):
        Scenario(**_tiny_kwargs(tiny_image_dataset), partner_shards=-2)


def test_console_level_switchable_at_runtime(capsys):
    import logging
    from mplc_tpu import utils
    logger = logging.getLogger("mplc_tpu")
    saved_handlers = list(logger.handlers)
    saved_level = utils._console_filter.level
    try:
        utils.init_logger(debug=False)
        logger.debug("hidden-dbg")
        utils.set_console_level("DEBUG")
        logger.debug("shown-dbg")
        utils.set_console_level(logging.INFO)
        logger.debug("hidden-again")
        with pytest.raises(ValueError, match="unknown log level"):
            utils.set_console_level("verbose")
        out = capsys.readouterr().out
        assert "shown-dbg" in out
        assert "hidden-dbg" not in out
        assert "hidden-again" not in out
    finally:
        # init_logger bound a StreamHandler to pytest's capture stream;
        # restore the original handlers so later tests don't log into a
        # closed file
        for h in list(logger.handlers):
            logger.removeHandler(h)
        for h in saved_handlers:
            logger.addHandler(h)
        utils._console_filter.level = saved_level


def test_unknown_method_raises(tiny_image_dataset):
    with pytest.raises(Exception, match="not in methods list"):
        Scenario(**_tiny_kwargs(tiny_image_dataset), methods=["Not a method"])


def test_bad_dataset_proportion(tiny_image_dataset):
    with pytest.raises(AssertionError):
        Scenario(**_tiny_kwargs(tiny_image_dataset), dataset_proportion=0)


def test_default_split_is_basic_random(tiny_image_dataset):
    sc = Scenario(**_tiny_kwargs(tiny_image_dataset))
    assert (sc.samples_split_type, sc.samples_split_description) == ("basic", "random")


def test_corrupted_datasets_default(tiny_image_dataset):
    sc = Scenario(**_tiny_kwargs(tiny_image_dataset))
    assert sc.corrupted_datasets == ["not_corrupted"] * 3


def test_dry_run_skips_folder(tmp_path, tiny_image_dataset):
    sc = Scenario(**{**_tiny_kwargs(tiny_image_dataset),
                     "experiment_path": tmp_path / "exp", "is_dry_run": True})
    assert not sc.save_folder.exists()


def test_to_dataframe_without_contrib(tiny_image_dataset):
    sc = Scenario(**_tiny_kwargs(tiny_image_dataset))
    df = sc.to_dataframe()
    assert len(df) == 1
    assert "mpl_test_score" in df.columns


# -- grid expansion ----------------------------------------------------------

def test_grid_expansion_product():
    cfg = [{
        "dataset_name": ["mnist"],
        "partners_count": [3],
        "amounts_per_partner": [[0.2, 0.3, 0.5]],
        "epoch_count": [2, 4],
        "minibatch_count": [2, 3],
    }]
    params = get_scenario_params_list(cfg)
    assert len(params) == 4
    assert {p["epoch_count"] for p in params} == {2, 4}


def test_grid_expansion_mismatched_amounts_raises():
    cfg = [{
        "dataset_name": ["mnist"],
        "partners_count": [3],
        "amounts_per_partner": [[0.5, 0.5]],
    }]
    with pytest.raises(Exception, match="amounts_per_partner"):
        get_scenario_params_list(cfg)


def test_grid_expansion_dataset_dict_init_model():
    cfg = [{
        "dataset_name": {"mnist": None},
        "partners_count": [2],
        "amounts_per_partner": [[0.5, 0.5]],
    }]
    params = get_scenario_params_list(cfg)
    assert params[0]["dataset_name"] == "mnist"
    assert params[0]["init_model_from"] == "random_initialization"


# -- resume hardening --------------------------------------------------------

def _titanic_resume_kwargs(cache_path):
    return dict(partners_count=3, amounts_per_partner=[0.3, 0.3, 0.4],
                dataset_name="titanic", epoch_count=2, minibatch_count=2,
                gradient_updates_per_pass_count=2, is_early_stopping=False,
                methods=["Independent scores"],
                experiment_path="/tmp/mplc_tpu_tests", is_dry_run=True,
                seed=7, contributivity_cache_from=str(cache_path))


def test_run_quarantines_truncated_resume_cache(tmp_path, caplog):
    """Malformed JSON in contributivity_cache_from must not crash run()
    before any compute: the file is quarantined to *.corrupt, a warning
    names it, and the sweep starts cold."""
    import logging

    cache = tmp_path / "coalition_cache.json"
    cache.write_text('{"fingerprint": {"partners_count": 3}, "charac')
    sc = Scenario(**_titanic_resume_kwargs(cache))
    with caplog.at_level(logging.WARNING, logger="mplc_tpu"):
        assert sc.run() == 0
    assert not cache.exists()
    quarantined = tmp_path / "coalition_cache.json.corrupt"
    assert quarantined.exists()
    assert "quarantined" in caplog.text and "starting the sweep cold" in caplog.text
    # the sweep really ran cold: the singles were trained, not resumed
    assert sc._charac_engine.first_charac_fct_calls_count == 3
    scores = sc.contributivity_list[0].contributivity_scores
    assert np.isfinite(scores).all()


def test_run_still_raises_on_fingerprint_mismatch(tmp_path):
    """Quarantine covers INTEGRITY failures only: a valid cache built for
    a different scenario shape must still raise out of run() — silently
    recomputing would mask a configuration error."""
    import sys
    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent))
    from helpers import build_scenario
    from mplc_tpu.contrib.engine import CharacteristicEngine

    other = build_scenario(partners_count=4,
                           amounts_per_partner=[0.1, 0.2, 0.3, 0.4],
                           dataset_name="titanic", epoch_count=2,
                           gradient_updates_per_pass_count=2, seed=9)
    eng = CharacteristicEngine(other)
    eng.evaluate([(0,)])
    cache = tmp_path / "coalition_cache.json"
    eng.save_cache(cache)

    sc = Scenario(**_titanic_resume_kwargs(cache))
    with pytest.raises(ValueError, match="partners"):
        sc.run()
    assert cache.exists()  # a mismatched cache is NOT quarantined


def test_split_then_corruption_pipeline(tiny_image_dataset):
    sc = Scenario(**_tiny_kwargs(tiny_image_dataset),
                  corrupted_datasets=["not_corrupted", "permuted", ["shuffled", 0.5]])
    sc.instantiate_scenario_partners()
    sc.split_data(is_logging_enabled=False)
    sc.compute_batch_sizes()
    y_before = [p.y_train.copy() for p in sc.partners_list]
    sc.data_corruption()
    assert np.array_equal(sc.partners_list[0].y_train, y_before[0])
    assert not np.array_equal(sc.partners_list[1].y_train, y_before[1])
    # one-hot structure preserved everywhere
    for p in sc.partners_list:
        assert np.allclose(p.y_train.sum(axis=1), 1.0)
