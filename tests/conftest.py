"""Test harness setup: force a virtual 8-device CPU mesh BEFORE jax import.

Mirrors the reference test strategy (SURVEY.md §4): real objects on small
real configs, no fakes for the training path; multi-device behavior is
exercised on a host-platform device mesh.
"""

import os

# HARD override: the ambient environment pins JAX_PLATFORMS=axon (single
# real TPU chip behind a tunnel) and the axon sitecustomize sets the
# jax_platforms *config value* at interpreter startup — so an env-var
# override alone is ignored. Tests must run on the virtual 8-device CPU
# mesh instead of contending for the chip: set the XLA flag before backend
# init, then force the config back to cpu.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("MPLC_TPU_SYNTH_SCALE", "0.02")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent XLA compilation cache: the suite's cost is dominated by CPU
# compiles of the conv models; cache them across pytest runs.
from pathlib import Path  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  str(Path(__file__).resolve().parents[1] / ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _flight_dumps_to_tmp(tmp_path, monkeypatch):
    """The crash flight recorder defaults to the working directory; tests
    exercising quarantine/ladder/journal-corrupt paths must drop their
    postmortems in tmp, not the repo root."""
    monkeypatch.setenv("MPLC_TPU_FLIGHT_RECORDER_DIR",
                       str(tmp_path / "flight"))


@pytest.fixture(scope="session")
def tiny_image_dataset():
    """A small, learnable prototype-image dataset shared across tests."""
    from mplc_tpu.data.datasets import Dataset, to_categorical
    from mplc_tpu.models import MNIST_CNN

    rng = np.random.default_rng(7)
    protos = rng.uniform(0, 1, (10, 28, 28, 1)).astype(np.float32)
    def make(n):
        y = rng.integers(0, 10, n)
        x = np.clip(protos[y] + rng.normal(0, 0.25, (n, 28, 28, 1)), 0, 1).astype(np.float32)
        return x, to_categorical(y, 10)
    x, y = make(700)
    xt, yt = make(150)
    return Dataset("mnist", (28, 28, 1), 10, x, y, xt, yt,
                   model=MNIST_CNN, provenance="test")


@pytest.fixture(scope="session")
def quick_scenario(tiny_image_dataset):
    """A 3-partner fedavg scenario, split and ready to train."""
    from helpers import build_scenario
    return build_scenario(dataset=tiny_image_dataset)
