"""Trained-SV parity oracle (BASELINE.md "SV parity"; SURVEY.md §4).

An INDEPENDENT pure-NumPy re-implementation of the reference training loops:

  - fedavg coalitions: broadcast -> per-partner local pass (fresh optimizer,
    reference builds a new Keras model every fit_minibatch,
    multi_partner_learning.py:310-332) -> data-volume weighted average
    (mpl_utils.py:90-115), early stop on val_loss[e,0] vs val_loss[e-10,0]
    (multi_partner_learning.py:177-193);
  - single-partner coalitions: persistent optimizer + Keras-style
    "no improvement for PATIENCE epochs" early stopping
    (multi_partner_learning.py:230-275).

v(S) = test accuracy of the final global model; exact Shapley values from
the v table. The oracle shares ONLY the per-coalition initial weights with
the production engine (fetched via the engine's deterministic coalition
rng) — every gradient, optimizer update, aggregation and early-stopping
decision is recomputed in NumPy. Agreement to 1e-3 on the full v(S) table
and on the Shapley values validates the compiled coalition-masked/slotted
trainer against the reference semantics end to end.

The scenario uses minibatch_count=1 and gradient_updates_per_pass=1 so the
training math is permutation-invariant (one full-batch step per partner per
epoch) — RNG-dependent minibatch composition is covered by the
batched==serial and slotted==masked equivalence tests instead.
"""

import numpy as np
import pytest

import jax

PATIENCE = 10  # constants.PATIENCE, reference mplc/constants.py:10
ADAM_LR = 5e-2  # TITANIC_LOGREG optimizer (models/zoo.py)
ADAM_B1, ADAM_B2, ADAM_EPS = 0.9, 0.999, 1e-7


# ---------------------------------------------------------------------------
# NumPy reference trainer
# ---------------------------------------------------------------------------

def _sigmoid(z):
    return 1.0 / (1.0 + np.exp(-z))


def _bce_loss(z, y):
    # same stable form as ops/metrics.py sigmoid_binary_cross_entropy
    return float(np.mean(np.maximum(z, 0) - z * y + np.log1p(np.exp(-np.abs(z)))))


def _logreg_grad(w, b, x, y):
    z = x @ w + b
    d = (_sigmoid(z) - y) / len(y)          # [n]
    return x.T @ d, np.sum(d)


def _adam_step(g, m, v, t, lr=ADAM_LR):
    m = ADAM_B1 * m + (1 - ADAM_B1) * g
    v = ADAM_B2 * v + (1 - ADAM_B2) * g * g
    mh = m / (1 - ADAM_B1 ** t)
    vh = v / (1 - ADAM_B2 ** t)
    return -lr * mh / (np.sqrt(vh) + ADAM_EPS), m, v


class NumpyFedAvgOracle:
    """Reference fedavg loop on a logistic model, full-batch passes."""

    def __init__(self, partners_xy, val_xy, test_xy, epochs):
        self.partners_xy = partners_xy      # list of (x, y) per partner
        self.val_xy = val_xy
        self.test_xy = test_xy
        self.epochs = epochs

    def _val_loss(self, w, b):
        xv, yv = self.val_xy
        return _bce_loss(xv @ w + b, yv)

    def train_coalition(self, subset, w0, b0):
        """fedavg over the subset's partners; returns final (w, b)."""
        datas = [self.partners_xy[i] for i in subset]
        sizes = np.array([len(x) for x, _ in datas], float)
        agg_w = sizes / sizes.sum()          # data-volume weights
        w, b = w0.copy(), float(b0)
        vl_h = []
        for e in range(self.epochs):
            # global val loss recorded at the START of the minibatch
            # (multi_partner_learning.py:314)
            vl_h.append(self._val_loss(w, b))
            locals_ = []
            for x, y in datas:
                g_w, g_b = _logreg_grad(w, b, x, y)
                # fresh optimizer per partner pass -> first adam step
                up_w, _, _ = _adam_step(g_w, np.zeros_like(g_w),
                                        np.zeros_like(g_w), 1)
                up_b, _, _ = _adam_step(np.array([g_b]), np.zeros(1), np.zeros(1), 1)
                locals_.append((w + up_w, b + float(up_b[0])))
            w = sum(a * lw for a, (lw, _) in zip(agg_w, locals_))
            b = float(sum(a * lb for a, (_, lb) in zip(agg_w, locals_)))
            # reference early stop: val_loss[e,0] > val_loss[e-PATIENCE,0]
            if e >= PATIENCE and vl_h[e] > vl_h[e - PATIENCE]:
                break
        return w, b

    def train_single(self, i, w0, b0):
        """persistent-optimizer single training + Keras-style ES."""
        x, y = self.partners_xy[i]
        w, b = w0.copy(), float(b0)
        m_w = np.zeros_like(w)
        v_w = np.zeros_like(w)
        m_b = v_b = 0.0
        best, wait = np.inf, 0
        for t in range(1, self.epochs + 1):
            g_w, g_b = _logreg_grad(w, b, x, y)
            up_w, m_w, v_w = _adam_step(g_w, m_w, v_w, t)
            m_b = ADAM_B1 * m_b + (1 - ADAM_B1) * g_b
            v_b = ADAM_B2 * v_b + (1 - ADAM_B2) * g_b * g_b
            b += float(-ADAM_LR * (m_b / (1 - ADAM_B1 ** t))
                       / (np.sqrt(v_b / (1 - ADAM_B2 ** t)) + ADAM_EPS))
            w = w + up_w
            vl = self._val_loss(w, b)        # evaluated AFTER the epoch
            if vl < best:
                best, wait = vl, 0
            else:
                wait += 1
                if wait >= PATIENCE:
                    break
        return w, b

    def accuracy(self, w, b):
        xt, yt = self.test_xy
        return float(np.mean(((xt @ w + b) > 0) == (yt > 0.5)))


# ---------------------------------------------------------------------------
# fixture scenario: 3 partners, planted logistic data
# ---------------------------------------------------------------------------

def _make_parity_scenario(approach):
    from mplc_tpu.data.datasets import Dataset
    from mplc_tpu.models.zoo import TITANIC_LOGREG, TITANIC_NUM_FEATURES
    from mplc_tpu.scenario import Scenario

    rng = np.random.default_rng(123)
    n_train, n_test = 900, 2000
    w_true = rng.normal(0, 1.2, TITANIC_NUM_FEATURES)

    def make(n):
        x = rng.normal(0, 1, (n, TITANIC_NUM_FEATURES)).astype(np.float32)
        y = (x @ w_true > 0).astype(np.float32)
        flip = rng.uniform(size=n) < 0.08     # non-separable: scores differ
        y[flip] = 1 - y[flip]
        return x, y

    x, y = make(n_train)
    xt, yt = make(n_test)
    ds = Dataset("titanic", (TITANIC_NUM_FEATURES,), 2, x, y, xt, yt,
                 model=TITANIC_LOGREG, provenance="test")

    sc = Scenario(partners_count=3, amounts_per_partner=[0.1, 0.3, 0.6],
                  dataset=ds, multi_partner_learning_approach=approach,
                  aggregation_weighting="data-volume",
                  epoch_count=25, minibatch_count=1,
                  gradient_updates_per_pass_count=1,
                  experiment_path="/tmp/mplc_tpu_tests", seed=5)
    sc.instantiate_scenario_partners()
    sc.split_data(is_logging_enabled=False)
    sc.compute_batch_sizes()
    sc.data_corruption()
    return sc


@pytest.fixture(scope="module")
def parity_setup():
    return _make_parity_scenario("fedavg")


def _partners_val_test_arrays(sc):
    partners_xy = [(np.asarray(p.x_train, np.float64),
                    np.asarray(p.y_train, np.float64).reshape(-1))
                   for p in sorted(sc.partners_list, key=lambda p: p.id)]
    val = (np.asarray(sc.dataset.x_val, np.float64),
           np.asarray(sc.dataset.y_val, np.float64).reshape(-1))
    test = (np.asarray(sc.dataset.x_test, np.float64),
            np.asarray(sc.dataset.y_test, np.float64).reshape(-1))
    return partners_xy, val, test


def _assert_engine_matches_oracle(sc, eng, oracle, err_tag):
    """Run engine and oracle over the full 3-partner powerset from the same
    per-coalition initial weights; assert v(S) and exact SVs agree to 1e-3
    and that the scores discriminate (the saturated all-equal case —
    BENCH_r02's flaw — must fail, not silently pass). Returns the engine
    SVs for approach-specific assertions."""
    from mplc_tpu.contrib.shapley import (powerset_order,
                                          shapley_from_characteristic)

    subsets = powerset_order(3)
    engine_vals = eng.evaluate(subsets)

    oracle_table = {(): 0.0}
    for s in subsets:
        # identical initial weights: the engine's deterministic
        # per-coalition rng; everything downstream is NumPy
        params = jax.device_get(
            sc.dataset.model.init(eng._coalition_rng(s)))
        w0 = np.asarray(params["d1"]["w"], np.float64).reshape(-1)
        b0 = float(np.asarray(params["d1"]["b"]).reshape(()))
        if len(s) == 1:
            w, b = oracle.train_single(s[0], w0, b0)
        else:
            w, b = oracle.train_coalition(s, w0, b0)
        oracle_table[s] = oracle.accuracy(w, b)

    oracle_vals = np.array([oracle_table[s] for s in subsets])
    np.testing.assert_allclose(engine_vals, oracle_vals, atol=1e-3,
                               err_msg=f"{err_tag} v(S) table diverges from "
                                       "the NumPy reference implementation")

    sv_engine = shapley_from_characteristic(3, eng.charac_fct_values)
    sv_oracle = shapley_from_characteristic(3, oracle_table)
    np.testing.assert_allclose(sv_engine, sv_oracle, atol=1e-3)
    assert sv_oracle.max() - sv_oracle.min() > 2e-3
    return sv_engine


def test_trained_sv_parity_vs_numpy_oracle(parity_setup):
    from mplc_tpu.contrib.engine import CharacteristicEngine

    sc = parity_setup
    eng = CharacteristicEngine(sc)
    partners_xy, val, test = _partners_val_test_arrays(sc)
    oracle = NumpyFedAvgOracle(partners_xy, val, test, epochs=sc.epoch_count)
    sv_engine = _assert_engine_matches_oracle(sc, eng, oracle, "fedavg")
    # more data => more contribution on this planted task
    assert sv_engine[2] > sv_engine[0]


# ---------------------------------------------------------------------------
# sequential-family parity: one shared model visits partners in a fresh
# random order each round; the SAME model instance (and optimizer) is fit
# repeatedly across the chain (reference multi_partner_learning.py:337-385
# builds `model_for_round` once per minibatch). seq-pure never aggregates;
# seqavg ends every round with a data-volume weighted average of the
# chain snapshots (:412-433).
# ---------------------------------------------------------------------------

class NumpySeqOracle(NumpyFedAvgOracle):
    """Reference seq-pure/seqavg loop. Shares the visit-order randomness
    with the engine (it is rng, like the initial weights —
    `order_fn(subset, e)` returns the active partners in visit order);
    every gradient, the threaded Adam state, the seqavg aggregation and
    the early stop are recomputed in NumPy."""

    def __init__(self, partners_xy, val_xy, test_xy, epochs, order_fn,
                 aggregate=False):
        super().__init__(partners_xy, val_xy, test_xy, epochs)
        self.order_fn = order_fn
        self.aggregate = aggregate   # seqavg: round ends in a weighted avg

    def train_coalition(self, subset, w0, b0):
        w, b = w0.copy(), float(b0)
        sizes = {i: len(self.partners_xy[i][0]) for i in subset}
        vl_h = []
        for e in range(self.epochs):
            # val recorded at the START of the round (pre-chain model)
            vl_h.append(self._val_loss(w, b))
            # one optimizer per round, threaded through the partner chain
            m_w = np.zeros_like(w)
            v_w = np.zeros_like(w)
            m_b = np.zeros(1)
            v_b = np.zeros(1)
            t = 0
            snapshots = {}
            for i in self.order_fn(subset, e):
                x, y = self.partners_xy[i]
                g_w, g_b = _logreg_grad(w, b, x, y)
                t += 1
                up_w, m_w, v_w = _adam_step(g_w, m_w, v_w, t)
                up_b, m_b, v_b = _adam_step(np.array([g_b]), m_b, v_b, t)
                w = w + up_w
                b += float(up_b[0])
                snapshots[i] = (w.copy(), b)
            if self.aggregate:
                # seqavg: data-volume weighted mean of the partners' chain
                # snapshots (multi_partner_learning.py:412-433)
                total = sum(sizes.values())
                w = sum(sizes[i] / total * snapshots[i][0] for i in subset)
                b = float(sum(sizes[i] / total * snapshots[i][1] for i in subset))
            if e >= PATIENCE and vl_h[e] > vl_h[e - PATIENCE]:
                break
        return w, b


@pytest.mark.parametrize("approach", ["seq-pure", "seqavg"])
def test_trained_sv_parity_seq(approach):
    from mplc_tpu.contrib.engine import CharacteristicEngine

    sc = _make_parity_scenario(approach)
    eng = CharacteristicEngine(sc)

    def order_fn(subset, e):
        """The engine's visit-order keys, re-derived: epoch rng =
        fold_in(fold_in(K, i), e) with i the index inside the patience-
        sized epoch chunk (contrib/engine.py scores: chunk = patience;
        mpl/engine.py epoch_chunk/run_epoch), then
        rng_mb = fold_in(fold_in(rng, 1), mb_i=0) and
        keys = uniform(fold_in(rng_mb, 0), (P,)) with inactive partners
        pushed to the back (+1e3)."""
        K = eng._coalition_rng(tuple(subset))
        i_in_chunk = e % PATIENCE
        r = jax.random.fold_in(jax.random.fold_in(K, i_in_chunk), e)
        rng_mb = jax.random.fold_in(jax.random.fold_in(r, 1), 0)
        keys = np.asarray(jax.random.uniform(jax.random.fold_in(rng_mb, 0), (3,)))
        mask = np.zeros(3)
        mask[list(subset)] = 1.0
        keys = keys + (1.0 - mask) * 1e3
        return [int(p) for p in np.argsort(keys) if mask[p]]

    partners_xy, val, test = _partners_val_test_arrays(sc)
    oracle = NumpySeqOracle(partners_xy, val, test,
                            epochs=sc.epoch_count, order_fn=order_fn,
                            aggregate=(approach == "seqavg"))
    _assert_engine_matches_oracle(sc, eng, oracle, approach)
