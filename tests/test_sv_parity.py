"""Trained-SV parity oracle (BASELINE.md "SV parity"; SURVEY.md §4).

An INDEPENDENT pure-NumPy re-implementation of the reference training loops:

  - fedavg coalitions: broadcast -> per-partner local pass (fresh optimizer,
    reference builds a new Keras model every fit_minibatch,
    multi_partner_learning.py:310-332) -> data-volume weighted average
    (mpl_utils.py:90-115), early stop on val_loss[e,0] vs val_loss[e-10,0]
    (multi_partner_learning.py:177-193);
  - single-partner coalitions: persistent optimizer + Keras-style
    "no improvement for PATIENCE epochs" early stopping
    (multi_partner_learning.py:230-275).

v(S) = test accuracy of the final global model; exact Shapley values from
the v table. The oracle shares ONLY the per-coalition initial weights with
the production engine (fetched via the engine's deterministic coalition
rng) — every gradient, optimizer update, aggregation and early-stopping
decision is recomputed in NumPy. Agreement to 1e-3 on the full v(S) table
and on the Shapley values validates the compiled coalition-masked/slotted
trainer against the reference semantics end to end.

The fedavg / seq-pure / seqavg scenarios use minibatch_count=1 and
gradient_updates_per_pass=1 so the training math is permutation-invariant
(one full-batch step per partner per epoch). The seq-with-final-agg test
runs at minibatch_count=2 (at MB=1 it coincides with seqavg) and re-derives
the engine's minibatch windows from the shared rng streams, so RNG-dependent
minibatch composition is oracle-checked here too — complementing the
batched==serial and slotted==masked equivalence tests.
"""

import numpy as np
import pytest

import jax

PATIENCE = 10  # constants.PATIENCE, reference mplc/constants.py:10
ADAM_LR = 5e-2  # TITANIC_LOGREG optimizer (models/zoo.py)
ADAM_B1, ADAM_B2, ADAM_EPS = 0.9, 0.999, 1e-7


# ---------------------------------------------------------------------------
# NumPy reference trainer
# ---------------------------------------------------------------------------

def _sigmoid(z):
    return 1.0 / (1.0 + np.exp(-z))


def _bce_loss(z, y):
    # same stable form as ops/metrics.py sigmoid_binary_cross_entropy
    return float(np.mean(np.maximum(z, 0) - z * y + np.log1p(np.exp(-np.abs(z)))))


def _logreg_grad(w, b, x, y):
    z = x @ w + b
    d = (_sigmoid(z) - y) / len(y)          # [n]
    return x.T @ d, np.sum(d)


def _adam_step(g, m, v, t, lr=ADAM_LR):
    m = ADAM_B1 * m + (1 - ADAM_B1) * g
    v = ADAM_B2 * v + (1 - ADAM_B2) * g * g
    mh = m / (1 - ADAM_B1 ** t)
    vh = v / (1 - ADAM_B2 ** t)
    return -lr * mh / (np.sqrt(vh) + ADAM_EPS), m, v


class NumpyFedAvgOracle:
    """Reference fedavg loop on a logistic model, full-batch passes."""

    def __init__(self, partners_xy, val_xy, test_xy, epochs):
        self.partners_xy = partners_xy      # list of (x, y) per partner
        self.val_xy = val_xy
        self.test_xy = test_xy
        self.epochs = epochs

    def _val_loss(self, w, b):
        xv, yv = self.val_xy
        return _bce_loss(xv @ w + b, yv)

    def train_coalition(self, subset, w0, b0):
        """fedavg over the subset's partners; returns final (w, b)."""
        datas = [self.partners_xy[i] for i in subset]
        sizes = np.array([len(x) for x, _ in datas], float)
        agg_w = sizes / sizes.sum()          # data-volume weights
        w, b = w0.copy(), float(b0)
        vl_h = []
        for e in range(self.epochs):
            # global val loss recorded at the START of the minibatch
            # (multi_partner_learning.py:314)
            vl_h.append(self._val_loss(w, b))
            locals_ = []
            for x, y in datas:
                g_w, g_b = _logreg_grad(w, b, x, y)
                # fresh optimizer per partner pass -> first adam step
                up_w, _, _ = _adam_step(g_w, np.zeros_like(g_w),
                                        np.zeros_like(g_w), 1)
                up_b, _, _ = _adam_step(np.array([g_b]), np.zeros(1), np.zeros(1), 1)
                locals_.append((w + up_w, b + float(up_b[0])))
            w = sum(a * lw for a, (lw, _) in zip(agg_w, locals_))
            b = float(sum(a * lb for a, (_, lb) in zip(agg_w, locals_)))
            # reference early stop: val_loss[e,0] > val_loss[e-PATIENCE,0]
            if e >= PATIENCE and vl_h[e] > vl_h[e - PATIENCE]:
                break
        return w, b

    def train_single(self, i, w0, b0):
        """persistent-optimizer single training + Keras-style ES."""
        x, y = self.partners_xy[i]
        w, b = w0.copy(), float(b0)
        m_w = np.zeros_like(w)
        v_w = np.zeros_like(w)
        m_b = v_b = 0.0
        best, wait = np.inf, 0
        for t in range(1, self.epochs + 1):
            g_w, g_b = _logreg_grad(w, b, x, y)
            up_w, m_w, v_w = _adam_step(g_w, m_w, v_w, t)
            m_b = ADAM_B1 * m_b + (1 - ADAM_B1) * g_b
            v_b = ADAM_B2 * v_b + (1 - ADAM_B2) * g_b * g_b
            b += float(-ADAM_LR * (m_b / (1 - ADAM_B1 ** t))
                       / (np.sqrt(v_b / (1 - ADAM_B2 ** t)) + ADAM_EPS))
            w = w + up_w
            vl = self._val_loss(w, b)        # evaluated AFTER the epoch
            if vl < best:
                best, wait = vl, 0
            else:
                wait += 1
                if wait >= PATIENCE:
                    break
        return w, b

    def accuracy(self, w, b):
        xt, yt = self.test_xy
        return float(np.mean(((xt @ w + b) > 0) == (yt > 0.5)))


# ---------------------------------------------------------------------------
# fixture scenario: 3 partners, planted logistic data
# ---------------------------------------------------------------------------

def _make_parity_scenario(approach, minibatch_count=1):
    from mplc_tpu.data.datasets import Dataset
    from mplc_tpu.models.zoo import TITANIC_LOGREG, TITANIC_NUM_FEATURES
    from mplc_tpu.scenario import Scenario

    rng = np.random.default_rng(123)
    n_train, n_test = 900, 2000
    w_true = rng.normal(0, 1.2, TITANIC_NUM_FEATURES)

    def make(n):
        x = rng.normal(0, 1, (n, TITANIC_NUM_FEATURES)).astype(np.float32)
        y = (x @ w_true > 0).astype(np.float32)
        flip = rng.uniform(size=n) < 0.08     # non-separable: scores differ
        y[flip] = 1 - y[flip]
        return x, y

    x, y = make(n_train)
    xt, yt = make(n_test)
    ds = Dataset("titanic", (TITANIC_NUM_FEATURES,), 2, x, y, xt, yt,
                 model=TITANIC_LOGREG, provenance="test")

    sc = Scenario(partners_count=3, amounts_per_partner=[0.1, 0.3, 0.6],
                  dataset=ds, multi_partner_learning_approach=approach,
                  aggregation_weighting="data-volume",
                  epoch_count=25, minibatch_count=minibatch_count,
                  gradient_updates_per_pass_count=1,
                  experiment_path="/tmp/mplc_tpu_tests", seed=5)
    sc.instantiate_scenario_partners()
    sc.split_data(is_logging_enabled=False)
    sc.compute_batch_sizes()
    sc.data_corruption()
    return sc


@pytest.fixture(scope="module")
def parity_setup():
    return _make_parity_scenario("fedavg")


def _partners_val_test_arrays(sc):
    partners_xy = [(np.asarray(p.x_train, np.float64),
                    np.asarray(p.y_train, np.float64).reshape(-1))
                   for p in sorted(sc.partners_list, key=lambda p: p.id)]
    val = (np.asarray(sc.dataset.x_val, np.float64),
           np.asarray(sc.dataset.y_val, np.float64).reshape(-1))
    test = (np.asarray(sc.dataset.x_test, np.float64),
            np.asarray(sc.dataset.y_test, np.float64).reshape(-1))
    return partners_xy, val, test


def _assert_engine_matches_oracle(sc, eng, oracle, err_tag):
    """Run engine and oracle over the full 3-partner powerset from the same
    per-coalition initial weights; assert v(S) and exact SVs agree to 1e-3
    and that the scores discriminate (the saturated all-equal case —
    BENCH_r02's flaw — must fail, not silently pass). Returns the engine
    SVs for approach-specific assertions."""
    from mplc_tpu.contrib.shapley import (powerset_order,
                                          shapley_from_characteristic)

    subsets = powerset_order(3)
    engine_vals = eng.evaluate(subsets)

    oracle_table = {(): 0.0}
    for s in subsets:
        # identical initial weights: the engine's deterministic
        # per-coalition rng; everything downstream is NumPy
        params = jax.device_get(
            sc.dataset.model.init(eng._coalition_rng(s)))
        w0 = np.asarray(params["d1"]["w"], np.float64).reshape(-1)
        b0 = float(np.asarray(params["d1"]["b"]).reshape(()))
        if len(s) == 1:
            w, b = oracle.train_single(s[0], w0, b0)
        else:
            w, b = oracle.train_coalition(s, w0, b0)
        oracle_table[s] = oracle.accuracy(w, b)

    oracle_vals = np.array([oracle_table[s] for s in subsets])
    np.testing.assert_allclose(engine_vals, oracle_vals, atol=1e-3,
                               err_msg=f"{err_tag} v(S) table diverges from "
                                       "the NumPy reference implementation")

    sv_engine = shapley_from_characteristic(3, eng.charac_fct_values)
    sv_oracle = shapley_from_characteristic(3, oracle_table)
    np.testing.assert_allclose(sv_engine, sv_oracle, atol=1e-3)
    assert sv_oracle.max() - sv_oracle.min() > 2e-3
    return sv_engine


def test_trained_sv_parity_vs_numpy_oracle(parity_setup):
    from mplc_tpu.contrib.engine import CharacteristicEngine

    sc = parity_setup
    eng = CharacteristicEngine(sc)
    partners_xy, val, test = _partners_val_test_arrays(sc)
    oracle = NumpyFedAvgOracle(partners_xy, val, test, epochs=sc.epoch_count)
    sv_engine = _assert_engine_matches_oracle(sc, eng, oracle, "fedavg")
    # more data => more contribution on this planted task
    assert sv_engine[2] > sv_engine[0]


# ---------------------------------------------------------------------------
# sequential-family parity: one shared model visits partners in a fresh
# random order each round; the SAME model instance (and optimizer) is fit
# repeatedly across the chain (reference multi_partner_learning.py:337-385
# builds `model_for_round` once per minibatch). seq-pure never aggregates;
# seqavg ends every round with a data-volume weighted average of the
# chain snapshots (:412-433).
# ---------------------------------------------------------------------------

class NumpySeqOracle(NumpyFedAvgOracle):
    """Reference seq-pure/seqavg loop. Shares the visit-order randomness
    with the engine (it is rng, like the initial weights —
    `order_fn(subset, e)` returns the active partners in visit order);
    every gradient, the threaded Adam state, the seqavg aggregation and
    the early stop are recomputed in NumPy."""

    def __init__(self, partners_xy, val_xy, test_xy, epochs, order_fn,
                 aggregate=False):
        super().__init__(partners_xy, val_xy, test_xy, epochs)
        self.order_fn = order_fn
        self.aggregate = aggregate   # seqavg: round ends in a weighted avg

    def train_coalition(self, subset, w0, b0):
        w, b = w0.copy(), float(b0)
        sizes = {i: len(self.partners_xy[i][0]) for i in subset}
        vl_h = []
        for e in range(self.epochs):
            # val recorded at the START of the round (pre-chain model)
            vl_h.append(self._val_loss(w, b))
            # one optimizer per round, threaded through the partner chain
            m_w = np.zeros_like(w)
            v_w = np.zeros_like(w)
            m_b = np.zeros(1)
            v_b = np.zeros(1)
            t = 0
            snapshots = {}
            for i in self.order_fn(subset, e):
                x, y = self.partners_xy[i]
                g_w, g_b = _logreg_grad(w, b, x, y)
                t += 1
                up_w, m_w, v_w = _adam_step(g_w, m_w, v_w, t)
                up_b, m_b, v_b = _adam_step(np.array([g_b]), m_b, v_b, t)
                w = w + up_w
                b += float(up_b[0])
                snapshots[i] = (w.copy(), b)
            if self.aggregate:
                # seqavg: data-volume weighted mean of the partners' chain
                # snapshots (multi_partner_learning.py:412-433)
                total = sum(sizes.values())
                w = sum(sizes[i] / total * snapshots[i][0] for i in subset)
                b = float(sum(sizes[i] / total * snapshots[i][1] for i in subset))
            if e >= PATIENCE and vl_h[e] > vl_h[e - PATIENCE]:
                break
        return w, b


def _engine_epoch_rng(eng, subset, e):
    """The engine's per-epoch rng, re-derived: fold_in(fold_in(K, i), e)
    with i the index inside the patience-sized epoch chunk
    (contrib/engine.py scores: chunk = patience; mpl/engine.py
    epoch_chunk/run_epoch)."""
    K = eng._coalition_rng(tuple(subset))
    return jax.random.fold_in(jax.random.fold_in(K, e % PATIENCE), e)


def _seq_visit_order(eng, subset, e, mb_i):
    """The engine's visit-order keys, re-derived:
    rng_mb = fold_in(fold_in(rng_e, 1), mb_i) and
    keys = uniform(fold_in(rng_mb, 0), (P,)) with inactive partners
    pushed to the back (+1e3) (mpl/engine.py _seq_epoch)."""
    r = _engine_epoch_rng(eng, subset, e)
    rng_mb = jax.random.fold_in(jax.random.fold_in(r, 1), mb_i)
    keys = np.asarray(jax.random.uniform(jax.random.fold_in(rng_mb, 0), (3,)))
    mask = np.zeros(3)
    mask[list(subset)] = 1.0
    keys = keys + (1.0 - mask) * 1e3
    return [int(p) for p in np.argsort(keys) if mask[p]]


@pytest.mark.parametrize("approach", ["seq-pure", "seqavg"])
def test_trained_sv_parity_seq(approach):
    from mplc_tpu.contrib.engine import CharacteristicEngine

    sc = _make_parity_scenario(approach)
    eng = CharacteristicEngine(sc)

    def order_fn(subset, e):
        return _seq_visit_order(eng, subset, e, 0)

    partners_xy, val, test = _partners_val_test_arrays(sc)
    oracle = NumpySeqOracle(partners_xy, val, test,
                            epochs=sc.epoch_count, order_fn=order_fn,
                            aggregate=(approach == "seqavg"))
    _assert_engine_matches_oracle(sc, eng, oracle, approach)


# ---------------------------------------------------------------------------
# seq-with-final-agg parity. At minibatch_count=1 this approach coincides
# numerically with seqavg (both aggregate each partner's last chain snapshot
# once per epoch), so the test runs at minibatch_count=2 — per-epoch
# aggregation is then genuinely distinct from seqavg's per-minibatch one
# (reference multi_partner_learning.py:388-409 vs :412-433) — and the oracle
# re-derives the engine's minibatch windows from the shared rng streams the
# same way the seq test re-derives visit order.
# ---------------------------------------------------------------------------

class NumpySeqFinalAggOracle(NumpyFedAvgOracle):
    """Reference seq-with-final-agg loop: sequential partner chain per
    minibatch (fresh optimizer per minibatch, threaded along the chain), ONE
    data-volume weighted aggregation of each partner's last chain snapshot
    at the END of every epoch. Early stopping reads the global val loss
    recorded at the start of minibatch MB-1 (the seq-family column quirk,
    multi_partner_learning.py:299 vs seq variants)."""

    def __init__(self, partners_xy, val_xy, test_xy, epochs, mb_count,
                 order_fn, window_fn, single_perm_fn):
        super().__init__(partners_xy, val_xy, test_xy, epochs)
        self.mb_count = mb_count
        self.order_fn = order_fn            # (subset, e, mb_i) -> visit order
        self.window_fn = window_fn          # (subset, e, i, mb_i) -> row idx
        self.single_perm_fn = single_perm_fn  # (subset, e) -> epoch perm rows

    def train_coalition(self, subset, w0, b0):
        w, b = w0.copy(), float(b0)
        sizes = {i: len(self.partners_xy[i][0]) for i in subset}
        total = float(sum(sizes.values()))
        vl_h = []
        for e in range(self.epochs):
            snapshots = {}
            vl = np.inf
            for mb_i in range(self.mb_count):
                vl = self._val_loss(w, b)   # start-of-minibatch global val
                m_w = np.zeros_like(w)
                v_w = np.zeros_like(w)
                m_b = np.zeros(1)
                v_b = np.zeros(1)
                t = 0
                for i in self.order_fn(subset, e, mb_i):
                    x, y = self.partners_xy[i]
                    rows = self.window_fn(subset, e, i, mb_i)
                    g_w, g_b = _logreg_grad(w, b, x[rows], y[rows])
                    t += 1
                    up_w, m_w, v_w = _adam_step(g_w, m_w, v_w, t)
                    up_b, m_b, v_b = _adam_step(np.array([g_b]), m_b, v_b, t)
                    w = w + up_w
                    b += float(up_b[0])
                    snapshots[i] = (w.copy(), b)
            vl_h.append(vl)                 # ES column = minibatch MB-1
            # the per-EPOCH aggregation that defines this approach
            w = sum(sizes[i] / total * snapshots[i][0] for i in subset)
            b = float(sum(sizes[i] / total * snapshots[i][1] for i in subset))
            if e >= PATIENCE and vl_h[e] > vl_h[e - PATIENCE]:
                break
        return w, b

    def train_single(self, i, w0, b0):
        """Single-partner training at minibatch_count=2: TWO persistent-
        optimizer steps per epoch over halves of the epoch's shuffled perm
        (mpl/engine.py _single_epoch: steps = mb_count * gup)."""
        x, y = self.partners_xy[i]
        n = len(x)
        steps = self.mb_count               # gradient_updates_per_pass = 1
        sb = -(-n // steps)                 # ceil: samples per step
        w, b = w0.copy(), float(b0)
        m_w = np.zeros_like(w)
        v_w = np.zeros_like(w)
        m_b = v_b = 0.0
        best, wait = np.inf, 0
        t = 0
        for e in range(self.epochs):
            perm = self.single_perm_fn((i,), e)
            for g in range(steps):
                rows = perm[g * sb:min((g + 1) * sb, n)]
                g_w, g_b = _logreg_grad(w, b, x[rows], y[rows])
                t += 1
                up_w, m_w, v_w = _adam_step(g_w, m_w, v_w, t)
                m_b = ADAM_B1 * m_b + (1 - ADAM_B1) * g_b
                v_b = ADAM_B2 * v_b + (1 - ADAM_B2) * g_b * g_b
                b += float(-ADAM_LR * (m_b / (1 - ADAM_B1 ** t))
                           / (np.sqrt(v_b / (1 - ADAM_B2 ** t)) + ADAM_EPS))
                w = w + up_w
            vl = self._val_loss(w, b)       # evaluated AFTER the epoch
            if vl < best:
                best, wait = vl, 0
            else:
                wait += 1
                if wait >= PATIENCE:
                    break
        return w, b


def test_trained_sv_parity_seq_with_final_agg():
    from mplc_tpu.contrib.engine import CharacteristicEngine

    MB = 2
    sc = _make_parity_scenario("seq-with-final-agg", minibatch_count=MB)
    eng = CharacteristicEngine(sc)
    n_max = eng.stacked.n_max
    mask_np = np.asarray(eng.stacked.mask)
    sizes_np = np.asarray(eng.stacked.sizes)

    def partner_perm(subset, e, i):
        # _epoch_perms: per-partner key = fold_in(fold_in(rng_e, 0), i);
        # padding rows pushed to the back (+1e9)
        import jax.numpy as jnp
        r0 = jax.random.fold_in(_engine_epoch_rng(eng, subset, e), 0)
        keys = jax.random.uniform(jax.random.fold_in(r0, i), (n_max,)) \
            + (1.0 - jnp.asarray(mask_np[i])) * 1e9
        # jnp.argsort (stable) exactly as the engine: np's default quicksort
        # could order tied float32 keys differently across a window boundary
        return np.asarray(jnp.argsort(keys))

    def order_fn(subset, e, mb_i):
        return _seq_visit_order(eng, subset, e, mb_i)

    def window_fn(subset, e, i, mb_i):
        valid_mb = int(sizes_np[i]) // MB   # remainder rows dropped
        perm = partner_perm(subset, e, i)
        return perm[mb_i * valid_mb:(mb_i + 1) * valid_mb]

    def single_perm_fn(subset, e):
        # _single_epoch: keys = uniform(fold_in(rng_e, 0), (n_max,)) — no
        # per-partner fold (the lone partner is selected by the mask)
        import jax.numpy as jnp
        (i,) = subset
        r0 = jax.random.fold_in(_engine_epoch_rng(eng, subset, e), 0)
        keys = jax.random.uniform(r0, (n_max,)) \
            + (1.0 - jnp.asarray(mask_np[i])) * 1e9
        return np.asarray(jnp.argsort(keys))[:int(sizes_np[i])]

    partners_xy, val, test = _partners_val_test_arrays(sc)
    oracle = NumpySeqFinalAggOracle(partners_xy, val, test,
                                    epochs=sc.epoch_count, mb_count=MB,
                                    order_fn=order_fn, window_fn=window_fn,
                                    single_perm_fn=single_perm_fn)
    _assert_engine_matches_oracle(sc, eng, oracle, "seq-with-final-agg")
