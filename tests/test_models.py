"""Model zoo: shapes, loss/metric contracts, and one-step learning."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mplc_tpu.models import MODELS
from mplc_tpu.ops.metrics import masked_loss_and_metrics

INPUTS = {
    "mnist_cnn": ((4, 28, 28, 1), 10, "float32"),
    "cifar10_cnn": ((4, 32, 32, 3), 10, "float32"),
    "imdb_conv1d": ((4, 500), 1, "int32"),
    "esc50_cnn": ((2, 40, 431, 1), 50, "float32"),
    "titanic_logreg": ((4, 27), 1, "float32"),
}


@pytest.mark.parametrize("name", list(MODELS))
def test_init_apply_shapes(name):
    model = MODELS[name]
    shape, out_dim, dtype = INPUTS[name]
    rng = jax.random.PRNGKey(0)
    params = model.init(rng)
    if dtype == "int32":
        x = jnp.zeros(shape, jnp.int32)
    else:
        x = jnp.zeros(shape, jnp.float32)
    logits = model.apply(params, x, train=True, rng=rng)
    assert logits.shape == (shape[0], out_dim)
    assert logits.dtype == jnp.float32


@pytest.mark.parametrize("name", ["mnist_cnn", "titanic_logreg"])
def test_one_sgd_step_reduces_loss(name):
    model = MODELS[name]
    shape, out_dim, _ = INPUTS[name]
    rng = jax.random.PRNGKey(1)
    params = model.init(rng)
    x = jax.random.uniform(rng, shape)
    if model.loss_kind == "binary":
        y = jnp.ones((shape[0], 1))
    else:
        y = jax.nn.one_hot(jnp.arange(shape[0]) % out_dim, out_dim)
    mask = jnp.ones((shape[0],))
    opt = model.make_optimizer()
    opt_state = opt.init(params)

    def loss_fn(p):
        logits = model.apply(p, x, train=False)
        l, _, _ = masked_loss_and_metrics(model.loss_kind, logits, y, mask)
        return l

    l0, grads = jax.value_and_grad(loss_fn)(params)
    for _ in range(20):
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        l1, grads = jax.value_and_grad(loss_fn)(params)
    assert float(l1) < float(l0)


def test_masked_rows_do_not_affect_loss():
    model = MODELS["mnist_cnn"]
    params = model.init(jax.random.PRNGKey(0))
    x = jax.random.uniform(jax.random.PRNGKey(2), (6, 28, 28, 1))
    y = jax.nn.one_hot(jnp.arange(6) % 10, 10)
    mask_full = jnp.array([1, 1, 1, 0, 0, 0], jnp.float32)
    logits = model.apply(params, x)
    l_masked, a_masked, c = masked_loss_and_metrics("categorical", logits, y, mask_full)
    logits3 = model.apply(params, x[:3])
    l3, a3, c3 = masked_loss_and_metrics("categorical", logits3, y[:3], jnp.ones(3))
    assert np.isclose(float(l_masked), float(l3), atol=1e-6)
    assert np.isclose(float(a_masked), float(a3), atol=1e-6)
    assert float(c) == 3.0


def test_zero_mask_is_finite_and_zero_grad():
    model = MODELS["mnist_cnn"]
    params = model.init(jax.random.PRNGKey(0))
    x = jax.random.uniform(jax.random.PRNGKey(2), (4, 28, 28, 1))
    y = jax.nn.one_hot(jnp.arange(4) % 10, 10)

    def loss_fn(p):
        logits = model.apply(p, x)
        l, _, _ = masked_loss_and_metrics("categorical", logits, y, jnp.zeros(4))
        return l

    l, grads = jax.value_and_grad(loss_fn)(params)
    assert float(l) == 0.0
    flat = jax.tree_util.tree_leaves(grads)
    assert all(float(jnp.abs(g).max()) == 0.0 for g in flat)
