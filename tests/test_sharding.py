"""Multi-device coalition sharding on the forced 8-device CPU mesh."""

import numpy as np
import pytest

import jax

import __graft_entry__ as graft


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8


def test_coalition_sharding_helper():
    from mplc_tpu.parallel.mesh import coalition_sharding
    sh = coalition_sharding()
    assert sh is not None
    assert sh.num_devices == 8
    assert "coal" in sh.mesh.axis_names


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


def test_dryrun_multichip_2():
    graft.dryrun_multichip(2)


def test_entry_compiles():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 10)


def _logreg_scenario():
    """A 3-partner scenario on the titanic logistic model: the engine's
    sharded pipeline compiles in seconds (the CNN-backed sharded path is
    covered by the tiny-shape dryrun tests above)."""
    from helpers import build_scenario
    return build_scenario(dataset_name="titanic", epoch_count=2,
                          gradient_updates_per_pass_count=2, seed=9)


def test_engine_shards_over_devices():
    """The characteristic engine must produce correct per-coalition scores
    when the mask batch is sharded over all 8 devices."""
    from mplc_tpu.contrib.engine import CharacteristicEngine
    eng = CharacteristicEngine(_logreg_scenario())
    assert eng._sharding is not None
    subsets = [(0,), (1,), (2,), (0, 1), (0, 2), (1, 2), (0, 1, 2)]
    vals = eng.evaluate(subsets)
    assert vals.shape == (7,)
    assert np.isfinite(vals).all()
    assert eng.first_charac_fct_calls_count == 7
    # cache: second call costs nothing
    vals2 = eng.evaluate(subsets)
    assert eng.first_charac_fct_calls_count == 7
    assert np.array_equal(vals, vals2)
