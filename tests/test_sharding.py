"""Multi-device coalition sharding on the forced 8-device CPU mesh."""

import numpy as np
import pytest

import jax

import __graft_entry__ as graft


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8


def test_coalition_sharding_helper():
    from mplc_tpu.parallel.mesh import coalition_sharding
    sh = coalition_sharding()
    assert sh is not None
    assert sh.num_devices == 8
    assert "coal" in sh.mesh.axis_names


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


def test_dryrun_multichip_2():
    graft.dryrun_multichip(2)


def test_entry_compiles():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 10)


def _logreg_scenario():
    """A 3-partner scenario on the titanic logistic model: the engine's
    sharded pipeline compiles in seconds (the CNN-backed sharded path is
    covered by the tiny-shape dryrun tests above)."""
    from helpers import build_scenario
    return build_scenario(dataset_name="titanic", epoch_count=2,
                          gradient_updates_per_pass_count=2, seed=9)


def _collectives_in(hlo: str) -> list:
    return [op for op in
            ("all-reduce", "all-gather", "all-to-all",
             "collective-permute", "reduce-scatter",
             "collective-broadcast")
            if op in hlo]


def test_sharded_sweep_hlo_is_collective_free(monkeypatch):
    """Compiler-level lock on the zero-communication coal axis: the
    8-device GSPMD epoch-chunk programs the engine actually runs — BOTH the
    slot-execution path every fedavg sweep trains on (int32 slot ids,
    production default) and the masked full-width path (MPLC_TPU_NO_SLOTS /
    non-fedavg approaches) — must contain NO cross-device collective ops,
    with the engine's exact committed-input pattern (coal ids and rngs
    sharded P('coal'), data replicated). The linear v5e-8 projection in
    perf/ (single-chip seconds / n_chips) rests on this property; if a code
    change ever introduces a collective into a training body, this test
    names it and the path it appeared on."""
    monkeypatch.delenv("MPLC_TPU_PARTNER_SHARDS", raising=False)
    from mplc_tpu.contrib.engine import CharacteristicEngine

    eng = CharacteristicEngine(_logreg_scenario())
    assert eng._sharding is not None and eng._sharding.num_devices == 8
    assert eng._use_slots  # fedavg: the sweep really runs slot pipelines
    P = eng.partners_count
    B = 8  # one coalition per device
    rngs_host = jax.numpy.stack(
        [eng._coalition_rng((i % P,)) for i in range(B)])
    rngs = jax.device_put(rngs_host, eng._sharding.batch_sharding)

    found = {}
    # -- slot path: the program the north-star sweep executes (k=2 slots) --
    k = 2
    pipe = eng._slot_pipe(k)
    coal = np.full((B, k), -1, np.int32)
    coal[:, 0] = 0
    coal[np.arange(B) % 2 == 0, 1] = 1
    coal = jax.device_put(jax.numpy.asarray(coal),
                          eng._sharding.batch_sharding)
    state = pipe._init(rngs, P)
    hlo = pipe.trainer.jit_batched_epoch_chunk.lower(
        state, eng.stacked, eng.val, coal, rngs,
        pipe.trainer.cfg.epoch_count).compile().as_text()
    found["slot"] = _collectives_in(hlo)

    # -- masked full-width path (MPLC_TPU_NO_SLOTS / seq approaches) ------
    pipe = eng.multi_pipe
    coal = np.zeros((B, P), np.float32)
    coal[:, 0] = 1.0
    coal[np.arange(B) % 2 == 0, 1] = 1.0
    coal = jax.device_put(jax.numpy.asarray(coal),
                          eng._sharding.batch_sharding)
    state = pipe._init(rngs, P)
    hlo = pipe.trainer.jit_batched_epoch_chunk.lower(
        state, eng.stacked, eng.val, coal, rngs,
        pipe.trainer.cfg.epoch_count).compile().as_text()
    found["masked"] = _collectives_in(hlo)

    bad = {path: ops for path, ops in found.items() if ops}
    assert not bad, (
        f"sharded epoch-chunk program now contains collectives {bad}; the "
        "zero-communication scaling claim no longer holds")


def test_engine_shards_over_devices():
    """The characteristic engine must produce correct per-coalition scores
    when the mask batch is sharded over all 8 devices."""
    from mplc_tpu.contrib.engine import CharacteristicEngine
    eng = CharacteristicEngine(_logreg_scenario())
    assert eng._sharding is not None
    subsets = [(0,), (1,), (2,), (0, 1), (0, 2), (1, 2), (0, 1, 2)]
    vals = eng.evaluate(subsets)
    assert vals.shape == (7,)
    assert np.isfinite(vals).all()
    assert eng.first_charac_fct_calls_count == 7
    # cache: second call costs nothing
    vals2 = eng.evaluate(subsets)
    assert eng.first_charac_fct_calls_count == 7
    assert np.array_equal(vals, vals2)


def test_engine_throughput_accounting():
    """epochs_trained / samples_trained must count exactly the training
    work of non-padding coalitions: epochs * sum_i(size_i // MB * MB)."""
    from mplc_tpu.contrib.engine import CharacteristicEngine
    from mplc_tpu.contrib.shapley import powerset_order

    sc = _logreg_scenario()
    eng = CharacteristicEngine(sc)
    subsets = powerset_order(3)
    eng.evaluate(subsets)
    # epoch_count=2 <= patience, so early stopping is a no-op and every
    # coalition trains the full 2 epochs
    assert eng.epochs_trained == 2 * len(subsets)
    sizes = np.asarray(eng.stacked.sizes)
    mbc = eng.multi_pipe.trainer.cfg.minibatch_count
    # single trainer covers every valid row; multi trainers train the
    # floored minibatch window (remainder rows dropped)
    expect = 2 * sum(int(sizes[s[0]]) if len(s) == 1
                     else sum(int(sizes[i]) // mbc * mbc for i in s)
                     for s in subsets)
    assert eng.samples_trained == expect
    # the two formulas must actually differ here, or the distinction is
    # untested — a partner size must not divide minibatch_count evenly
    assert any(int(n) % mbc for n in sizes)
    # memo hits train nothing
    eng.evaluate(subsets)
    assert eng.epochs_trained == 2 * len(subsets)


def test_es_noop_skip_is_numerically_identical():
    """With epoch_count <= patience the engine builds trainers with early
    stopping off (the stop rule cannot fire; skipping it drops one val
    eval per epoch). The scores must be bit-identical to trainers with
    the flag forced on, as the reference always sets it
    (contributivity.py:102-106)."""
    import dataclasses

    from mplc_tpu.contrib.engine import BatchedTrainerPipeline, CharacteristicEngine
    from mplc_tpu.contrib.shapley import powerset_order
    from mplc_tpu.mpl.engine import MplTrainer

    subsets = powerset_order(3)
    eng = CharacteristicEngine(_logreg_scenario())
    assert not eng.multi_pipe.trainer.cfg.is_early_stopping
    fast = eng.evaluate(subsets)

    forced = CharacteristicEngine(_logreg_scenario())
    forced._multi_cfg = dataclasses.replace(forced._multi_cfg,
                                            is_early_stopping=True)
    forced.multi_pipe = BatchedTrainerPipeline(
        MplTrainer.get(forced.model, forced._multi_cfg),
        forced.partners_count)
    single_cfg = dataclasses.replace(forced.single_pipe.trainer.cfg,
                                     is_early_stopping=True)
    forced.single_pipe = BatchedTrainerPipeline(
        MplTrainer.get(forced.model, single_cfg), forced.partners_count)
    slow = forced.evaluate(subsets)
    np.testing.assert_array_equal(fast, slow)


# From PR 3 to PR 13 the four tests below were xfail(strict=False): the
# 2-D [coal x part] path drifted numerically past any justifiable
# tolerance and the collective-budget lock caught an unexplained
# whole-mesh all-reduce. The numeric-truth plane (obs/numerics.py)
# root-caused all of it — psum grouping order + in-program stream
# generation beside a collective + per-topology loop-body compilation,
# with the whole-mesh all-reduce attributed to the epoch-permutation
# tensors — and MPLC_TPU_DETERMINISTIC_REDUCE=1 eliminates every source:
# the tests now assert BIT-identity, unconditionally. Full evidence in
# DESIGN_NOTES.md "2-D shard_map numeric drift — closed".


def test_engine_2d_partner_sharded_matches_default(monkeypatch):
    """Under deterministic-reduce, MPLC_TPU_PARTNER_SHARDS=2 runs multis
    on a [4 coal x 2 part] mesh (masked path, ordered-fold aggregation
    over all-gathered terms). The full 4-partner v(S) table must be
    BIT-IDENTICAL to the deterministic unsharded engine (part=1: whole
    partner axis resident per device) — and the deterministic values
    must still match the default slot-execution engine to the historical
    float tolerance, so the pinned order stays anchored to the same
    game."""
    from helpers import build_scenario
    from mplc_tpu.contrib.engine import CharacteristicEngine
    from mplc_tpu.contrib.shapley import powerset_order

    def scenario():
        return build_scenario(partners_count=4,
                              amounts_per_partner=[0.1, 0.2, 0.3, 0.4],
                              dataset_name="titanic", epoch_count=2,
                              gradient_updates_per_pass_count=2, seed=9)

    subsets = powerset_order(4)
    # the default-mode engine must be genuinely 1-D even if the ambient
    # env pre-set the knobs
    monkeypatch.delenv("MPLC_TPU_PARTNER_SHARDS", raising=False)
    monkeypatch.delenv("MPLC_TPU_DETERMINISTIC_REDUCE", raising=False)
    default_vals = CharacteristicEngine(scenario()).evaluate(subsets)

    monkeypatch.setenv("MPLC_TPU_DETERMINISTIC_REDUCE", "1")
    ref_eng = CharacteristicEngine(scenario())
    # deterministic mode routes the masked path through the 2-D-family
    # pipeline with part=1 — the unsharded reference program
    assert ref_eng._pipe2d is not None and ref_eng._pipe2d.part_shards == 1
    assert ref_eng.scenario.slot_bucketing == "masked"
    ref_vals = ref_eng.evaluate(subsets)

    monkeypatch.setenv("MPLC_TPU_PARTNER_SHARDS", "2")
    eng = CharacteristicEngine(scenario())
    assert eng._pipe2d is not None and eng._pipe2d.part_shards == 2
    assert eng._pipe2d.coal_devices == 4
    vals = eng.evaluate(subsets)
    # the retired-xfail lock: partner-sharded == unsharded, bit for bit
    np.testing.assert_array_equal(vals, ref_vals)
    # anchored to the default engine's game at the historical tolerance
    np.testing.assert_allclose(ref_vals, default_vals, atol=1e-4)
    # the characteristic values must discriminate, or equality is vacuous
    assert ref_vals.max() - ref_vals.min() > 1e-3

    # indivisible shard counts fail fast, not silently fall back
    monkeypatch.setenv("MPLC_TPU_PARTNER_SHARDS", "3")
    with pytest.raises(ValueError, match="must divide"):
        CharacteristicEngine(scenario())


def test_slot_pow2_bucketing_matches_exact(monkeypatch):
    """MPLC_TPU_SLOT_POW2=1 rounds slot widths up to powers of two (fewer
    compiled pipelines for cold runs). Inactive slots are masked out of the
    aggregation, so the full v(S) table must match the tight per-size
    grouping to float tolerance — and only the bucketed widths compile."""
    from helpers import build_scenario
    from mplc_tpu.contrib.engine import CharacteristicEngine
    from mplc_tpu.contrib.shapley import powerset_order

    def scenario():
        return build_scenario(partners_count=5,
                              amounts_per_partner=[0.1, 0.15, 0.2, 0.25, 0.3],
                              dataset_name="titanic", epoch_count=2,
                              gradient_updates_per_pass_count=2, seed=11)

    subsets = powerset_order(5)
    monkeypatch.delenv("MPLC_TPU_SLOT_POW2", raising=False)
    monkeypatch.delenv("MPLC_TPU_PARTNER_SHARDS", raising=False)
    # merge is the default bucketing now: the tight per-size reference
    # needs the explicit opt-out
    monkeypatch.setenv("MPLC_TPU_SLOT_MERGE", "0")
    ref_eng = CharacteristicEngine(scenario())
    assert ref_eng.scenario.slot_bucketing == "exact"
    ref_vals = ref_eng.evaluate(subsets)
    assert sorted(ref_eng._slot_pipes) == [2, 3, 4, 5]

    monkeypatch.delenv("MPLC_TPU_SLOT_MERGE", raising=False)
    monkeypatch.setenv("MPLC_TPU_SLOT_POW2", "1")
    eng = CharacteristicEngine(scenario())
    assert eng.scenario.slot_bucketing == "pow2"
    vals = eng.evaluate(subsets)
    np.testing.assert_array_equal(vals, ref_vals)
    assert sorted(eng._slot_pipes) == [2, 4, 5]  # 3->4; 5 capped at P


def test_engine_2d_mode_via_scenario_param(monkeypatch):
    """`partner_shards` as a Scenario/YAML parameter (no env var) selects
    the 2-D engine mode; the env var still overrides, and the effective
    value is written back so results.csv records the mode actually run."""
    from helpers import build_scenario
    from mplc_tpu.contrib.engine import CharacteristicEngine

    def scenario(**kw):
        return build_scenario(partners_count=4,
                              amounts_per_partner=[0.1, 0.2, 0.3, 0.4],
                              dataset_name="titanic", epoch_count=2,
                              gradient_updates_per_pass_count=2, seed=9, **kw)

    monkeypatch.delenv("MPLC_TPU_PARTNER_SHARDS", raising=False)
    sc = scenario(partner_shards=2)
    eng = CharacteristicEngine(sc)
    assert eng._pipe2d is not None and eng._pipe2d.part_shards == 2
    assert sc.partner_shards == 2

    monkeypatch.setenv("MPLC_TPU_PARTNER_SHARDS", "1")
    sc2 = scenario(partner_shards=2)
    eng2 = CharacteristicEngine(sc2)
    assert eng2._pipe2d is None
    assert sc2.partner_shards == 1  # effective mode, not the ignored param


def test_engine_2d_lflip_matches_default(monkeypatch):
    """The 2-D pipeline's lflip state specs (theta [B,P,K,K] and theta_h
    [B,E,P,K,K] sharded over coal+part) only exist under lflip — the
    fedavg parity test never exercises them. Same retired-xfail contract:
    BIT-identity between the deterministic part=2 and part=1 engines."""
    from helpers import build_scenario, cluster_mlp_dataset
    from mplc_tpu.contrib.engine import CharacteristicEngine
    from mplc_tpu.contrib.shapley import powerset_order

    def scenario():
        return build_scenario(partners_count=4,
                              amounts_per_partner=[0.1, 0.2, 0.3, 0.4],
                              dataset=cluster_mlp_dataset(n=700, seed=13),
                              multi_partner_learning_approach="lflip",
                              epoch_count=2,
                              gradient_updates_per_pass_count=2, seed=9)

    subsets = powerset_order(4)
    monkeypatch.delenv("MPLC_TPU_PARTNER_SHARDS", raising=False)
    monkeypatch.setenv("MPLC_TPU_DETERMINISTIC_REDUCE", "1")
    ref_eng = CharacteristicEngine(scenario())
    assert ref_eng._pipe2d is not None and ref_eng._pipe2d.part_shards == 1
    ref_vals = ref_eng.evaluate(subsets)
    # the characteristic values must discriminate, or parity is vacuous
    assert ref_vals.max() - ref_vals.min() > 1e-3

    monkeypatch.setenv("MPLC_TPU_PARTNER_SHARDS", "2")
    eng = CharacteristicEngine(scenario())
    assert eng._pipe2d is not None
    assert eng._pipe2d.trainer.cfg.approach == "lflip"
    vals = eng.evaluate(subsets)
    np.testing.assert_array_equal(vals, ref_vals)


def test_autosave_checkpoints_every_batch(tmp_path, monkeypatch):
    """A crash mid-sweep must lose at most one device batch: with
    autosave_path set, the memo cache is persisted after EVERY batch
    (contrib/engine.py _run_batch) and a fresh engine can resume from the
    partial file without retraining what it covers."""
    from helpers import build_scenario
    from mplc_tpu.contrib.engine import CharacteristicEngine
    from mplc_tpu.contrib.shapley import powerset_order

    # one coalition per device per batch: bucket width floors at the
    # 8-device mesh, so 5 partners make the size-2 group (10 coalitions)
    # span TWO batches — the crash below lands mid-group, between them
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "1")

    def scenario():
        return build_scenario(partners_count=5,
                              amounts_per_partner=[0.1, 0.15, 0.2, 0.25, 0.3],
                              dataset_name="titanic", epoch_count=2,
                              gradient_updates_per_pass_count=2, seed=9)

    eng = CharacteristicEngine(scenario())
    path = tmp_path / "coalition_cache.json"
    eng.autosave_path = path
    checkpoints = []

    class Boom(RuntimeError):
        pass

    def crash_mid_group(done, remaining, slots):
        import json
        checkpoints.append(len(json.loads(path.read_text())
                               ["charac_fct_values"]))
        if len(checkpoints) == 2:
            raise Boom()

    eng.progress = crash_mid_group
    with pytest.raises(Boom):
        eng.evaluate(powerset_order(5))
    # the file survived the crash and grew STRICTLY per batch — the 2nd
    # checkpoint is the first 8-wide batch of the size-2 group
    assert len(checkpoints) == 2 and checkpoints[0] < checkpoints[1]
    assert eng.first_charac_fct_calls_count == 5 + 8
    # a fresh engine resumes from the partial file without retraining
    resumed = CharacteristicEngine(scenario())
    resumed.load_cache(path)
    assert resumed.first_charac_fct_calls_count == 5 + 8
    resumed.evaluate(powerset_order(5))
    assert resumed.first_charac_fct_calls_count == 31  # only the rest trained


@pytest.mark.slow
def test_full_ten_partner_sweep_sharded():
    """North-star-shaped sweep at test scale: all 2^10 - 1 coalitions of a
    10-partner titanic scenario, sharded over the 8-device mesh. Locks in
    the per-size slot pipelines, fixed-width batching and memoization at
    the BASELINE.md coalition count (the TPU bench differs only in model
    family and hardware)."""
    from helpers import build_scenario
    from mplc_tpu.contrib.engine import CharacteristicEngine
    from mplc_tpu.contrib.shapley import (powerset_order,
                                          shapley_from_characteristic)

    amounts = [(i + 1) / 55 for i in range(10)]
    sc = build_scenario(partners_count=10, amounts_per_partner=amounts,
                        dataset_name="titanic", epoch_count=2,
                        gradient_updates_per_pass_count=2, seed=4)
    eng = CharacteristicEngine(sc)
    subsets = powerset_order(10)
    assert len(subsets) == 1023
    vals = eng.evaluate(subsets)
    assert vals.shape == (1023,)
    assert np.isfinite(vals).all()
    assert eng.first_charac_fct_calls_count == 1023
    # the characteristic function must discriminate, not saturate
    assert vals.max() - vals.min() > 0.01
    sv = shapley_from_characteristic(10, eng.charac_fct_values)
    assert np.isfinite(sv).all()
    # efficiency: SVs sum to v(grand coalition)
    grand = eng.charac_fct_values[tuple(range(10))]
    assert np.isclose(sv.sum(), grand, atol=1e-5)


def test_2d_partner_sharded_hlo_collective_budget(monkeypatch):
    """Compiler-level lock on the deterministic 2-D [coal x part] path's
    communication budget, RE-DERIVED by the numeric-truth plane (the
    fifth retired drift xfail): under MPLC_TPU_DETERMINISTIC_REDUCE the
    epoch chunk communicates ONLY via all-gather (the ordered fold
    gathers the weighted terms and the raw weight vector over `part` —
    ops/aggregation.py), every gather must ride the part axis alone
    (replica groups of size part_shards, never the whole mesh), and the
    static site count is exactly rounds x (param leaves + 1 weight
    gather) for the unrolled loops — bounded with headroom below.

    The old default-mode lock xfailed on an unexplained whole-mesh
    all-reduce; the audit attributed it to the IN-PROGRAM epoch-
    permutation tensors (GSPMD reshards the [P_local, Nmax] perm/key
    arrays across the whole mesh), and stream hoisting removes those
    tensors from the program entirely — asserted here by the zero
    all-reduce count. Evidence: DESIGN_NOTES.md "2-D shard_map numeric
    drift — closed"."""
    import re

    from helpers import build_scenario
    from mplc_tpu.contrib.engine import CharacteristicEngine

    monkeypatch.setenv("MPLC_TPU_DETERMINISTIC_REDUCE", "1")
    monkeypatch.setenv("MPLC_TPU_PARTNER_SHARDS", "2")
    eng = CharacteristicEngine(build_scenario(
        partners_count=4, amounts_per_partner=[0.1, 0.2, 0.3, 0.4],
        dataset_name="titanic", epoch_count=2,
        gradient_updates_per_pass_count=2, seed=9))
    pipe = eng._pipe2d
    assert pipe is not None and pipe.part_shards == 2

    B = pipe.coal_devices  # one coalition per coal-mesh row
    P_count = eng.partners_count
    coal = np.zeros((B, P_count), np.float32)
    coal[:, 0] = 1.0
    coal[np.arange(B) % 2 == 0, 1] = 1.0
    coal = jax.device_put(jax.numpy.asarray(coal), pipe.batch_sharding)
    rngs = jax.device_put(
        jax.numpy.stack([eng._coalition_rng((i % P_count,)) for i in range(B)]),
        pipe.rng_sharding)
    state = pipe._init(rngs, P_count)
    n = pipe.trainer.cfg.epoch_count
    pipe._run(state, eng.stacked, eng.val, coal, rngs, n)  # populate cache
    streams = pipe.trainer.jit_gen_streams(rngs, n, eng.stacked.mask,
                                           batched=True)
    state = pipe._init(rngs, P_count)
    hlo = pipe._run_cache[n].lower(
        state, eng.stacked, eng.val, coal, rngs, streams).compile().as_text()

    forbidden = [op for op in _collectives_in(hlo) if op != "all-gather"]
    assert not forbidden, (
        f"deterministic 2-D epoch-chunk program now contains {forbidden}; "
        "the ordered-fold path must communicate via all-gather only — an "
        "all-reduce reappearing means either the psum came back or the "
        "partitioner is resharding in-program tensors again")

    ag_lines = [ln for ln in hlo.splitlines() if "all-gather" in ln
                and "replica_groups" in ln]
    assert ag_lines, "partner aggregation no longer produces any all-gather"

    group_sizes = set()
    for ln in ag_lines:
        m = re.search(r"replica_groups=\{\{([^}]*)\}", ln)
        if m:  # explicit list form: {{0,1},{2,3},...} — first group
            group_sizes.add(len(m.group(1).split(",")))
            continue
        # plain iota form: [n_groups, group_size] <= [n_devices]
        m = re.search(r"replica_groups=\[(\d+),(\d+)\]<=\[(\d+)\]", ln)
        if m:
            group_sizes.add(int(m.group(2)))
            continue
        # any other form must fail the lock loudly, not slip past it
        raise AssertionError(f"unrecognized replica_groups format in: {ln}")
    assert group_sizes == {pipe.part_shards}, (
        f"all-gather replica groups {group_sizes} != part axis width "
        f"{pipe.part_shards}: a collective is riding more than `part`")

    # Measured budget: the unrolled deterministic program emits one
    # weight gather + one gather per param leaf per aggregation round —
    # epochs x minibatches x (leaves + 1) = 2 x 2 x 3 = 12 for the
    # titanic logreg. 2x headroom below; a per-step or per-device blowup
    # lands far above it.
    cfg = pipe.trainer.cfg
    rounds = cfg.epoch_count * cfg.minibatch_count
    n_leaves = len(jax.tree_util.tree_leaves(state.params))
    assert len(ag_lines) <= 2 * rounds * (n_leaves + 1), (
        f"{len(ag_lines)} all-gathers in one epoch chunk — the "
        "deterministic fold's gather count is no longer one per "
        "aggregation site")


def test_pipeline_batches_matches_default(monkeypatch):
    """Batch pipelining (the default) double-buffers coalition batches:
    batch i+1 is dispatched before batch i's results are fetched, so the
    device crosses batch boundaries without idling through host-side
    bookkeeping. Results must be IDENTICAL to the sequential engine
    (MPLC_TPU_PIPELINE_BATCHES=0 opt-out) — the same compiled executables
    run on the same per-coalition rng streams; only the harvest point
    moves. cap=1 forces multiple batches per evaluate() call so the
    pending-harvest path really executes."""
    from helpers import build_scenario
    from mplc_tpu.contrib.engine import CharacteristicEngine
    from mplc_tpu.contrib.shapley import powerset_order

    def scenario():
        return build_scenario(partners_count=5,
                              amounts_per_partner=[0.1, 0.15, 0.2, 0.25, 0.3],
                              dataset_name="titanic", epoch_count=2,
                              gradient_updates_per_pass_count=2, seed=11)

    subsets = powerset_order(5)
    monkeypatch.setenv("MPLC_TPU_PIPELINE_BATCHES", "0")
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "1")
    seq_eng = CharacteristicEngine(scenario())
    assert not seq_eng._pipeline_batches
    ref_vals = seq_eng.evaluate(subsets)

    monkeypatch.delenv("MPLC_TPU_PIPELINE_BATCHES", raising=False)
    eng = CharacteristicEngine(scenario())
    assert eng._pipeline_batches  # overlap is the default now
    progressed = []
    eng.progress = lambda done, rem, slots: progressed.append((done, rem, slots))
    vals = eng.evaluate(subsets)

    np.testing.assert_array_equal(vals, ref_vals)
    # every coalition was reported exactly once, in order, per slot bucket:
    # within each bucket the remaining count must walk to exactly 0 with
    # each step consuming `done` coalitions — a double-harvest or dropped
    # final flush breaks the walk even when totals happen to match
    assert sum(d for d, _, _ in progressed) == len(subsets)
    by_bucket = {}
    for done, rem, slots in progressed:
        by_bucket.setdefault(slots, []).append((done, rem))
    for slots, steps in by_bucket.items():
        # r_k = r_{k-1} - done_k: each report consumes exactly its group
        for (_, r_prev), (d, r) in zip(steps, steps[1:]):
            assert r == r_prev - d, f"bucket {slots} mis-accounted: {steps}"
        assert steps[-1][1] == 0, f"bucket {slots} never drained: {steps}"


def test_pipeline_preserves_finished_batch_on_dispatch_failure(monkeypatch):
    """Pipelined mode's durability contract: when dispatching batch i+1
    fails, batch i — already computed on device — must still be stored
    (and autosaved) before the exception unwinds, and must NOT be
    recorded twice by the finally path."""
    from helpers import build_scenario
    from mplc_tpu.contrib.engine import BatchedTrainerPipeline, CharacteristicEngine
    from itertools import combinations

    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "1")
    monkeypatch.setenv("MPLC_TPU_PIPELINE_BATCHES", "1")
    eng = CharacteristicEngine(build_scenario(
        partners_count=5, amounts_per_partner=[0.1, 0.15, 0.2, 0.25, 0.3],
        dataset_name="titanic", epoch_count=2,
        gradient_updates_per_pass_count=2, seed=11))

    real = BatchedTrainerPipeline.scores_async
    calls = {"n": 0}

    def failing_second(self, *a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("simulated dispatch failure")
        return real(self, *a, **kw)

    monkeypatch.setattr(BatchedTrainerPipeline, "scores_async", failing_second)
    subsets = list(combinations(range(5), 2))  # 10 size-2 coalitions: 2 batches
    with pytest.raises(RuntimeError, match="simulated dispatch failure"):
        eng.evaluate(subsets)
    # batch 1 (8 coalitions, bucket width 8 at cap=1 on the 8-device mesh)
    # was harvested exactly once on the way out
    assert eng.first_charac_fct_calls_count == 8
    assert len([k for k in eng.charac_fct_values if k]) == 8


def test_pipeline_never_double_records_on_harvest_failure(monkeypatch):
    """A harvest (result fetch) that raises must not be retried by the
    drain path: retrying would double-count first_charac_fct_calls_count
    and the throughput accounting (or, with a transiently-failing fetch,
    record a batch twice). The flaky fetch here raises once, then would
    succeed — a buggy drain that re-harvests records 10 coalitions
    instead of 8."""
    from helpers import build_scenario
    from mplc_tpu.contrib.engine import BatchedTrainerPipeline, CharacteristicEngine
    from itertools import combinations

    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "1")
    monkeypatch.setenv("MPLC_TPU_PIPELINE_BATCHES", "1")
    eng = CharacteristicEngine(build_scenario(
        partners_count=5, amounts_per_partner=[0.1, 0.15, 0.2, 0.25, 0.3],
        dataset_name="titanic", epoch_count=2,
        gradient_updates_per_pass_count=2, seed=11))

    real = BatchedTrainerPipeline.scores_async
    calls = {"n": 0}

    def flaky_second_fetch(self, *a, **kw):
        calls["n"] += 1
        fetch = real(self, *a, **kw)
        if calls["n"] != 2:
            return fetch
        state = {"first": True}

        def flaky():
            if state["first"]:
                state["first"] = False
                raise RuntimeError("simulated harvest failure")
            return fetch()

        return flaky

    monkeypatch.setattr(BatchedTrainerPipeline, "scores_async",
                        flaky_second_fetch)
    subsets = list(combinations(range(5), 2))
    with pytest.raises(RuntimeError, match="simulated harvest failure"):
        eng.evaluate(subsets)
    # only batch 1's 8 coalitions recorded; the failed harvest of batch 2
    # was NOT retried into a double record
    assert eng.first_charac_fct_calls_count == 8
    assert len([k for k in eng.charac_fct_values if k]) == 8
