"""Pins for the batch-granular v5e-8 projection pipeline.

The 290 s north-star projection (perf/r5/PROJECTION_r4data.md) rests on
scripts/project_v5e8.py's log mining: call-boundary reconstruction,
bucket-width attribution, first-occurrence (residual-compile) exclusion,
and the affine width fit. These tests pin that analysis against the
committed r4 artifacts so a parser regression cannot silently move the
headline number, and pin the schedule model against the engine's real
_bucket_size.
"""

import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT / "scripts"))

import project_v5e8 as proj  # noqa: E402

R4_SWEEP = ROOT / "perf" / "r4" / "config1.log"
R4_ISLOG = ROOT / "perf" / "r4" / "config3_attempt1_wedged.log"


def test_bucket_size_matches_engine():
    from mplc_tpu.contrib.engine import _bucket_size
    for n in (1, 2, 5, 10, 16, 45, 120, 128, 210, 252, 1023):
        for n_dev in (1, 8):
            for cap in (1, 8, 16):
                assert proj.bucket_size(n, n_dev, cap) == _bucket_size(n, n_dev, cap)


@pytest.mark.skipif(not R4_SWEEP.exists(), reason="r4 artifact absent")
def test_sweep_log_batch_times():
    times = proj.parse_batch_times(str(R4_SWEEP))
    # the full 1023-coalition sweep: every slot size present, known medians
    assert set(times) == {None} | set(range(2, 11))
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    assert med(times[5]) == 31      # modal size, 16 batches
    assert med(times[9]) == 55      # width-16 size-9 batch
    assert med(times[10]) == 2      # the width-1 size-10 batch


@pytest.mark.skipif(not R4_ISLOG.exists(), reason="r4 artifact absent")
def test_is_log_mining_pins_the_measured_curve():
    pts, steady = proj.parse_is_log_ratios(str(R4_ISLOG), record_cap=16)
    # known steady-state cells (s/batch) from the wedged IS run
    assert steady[(3, 16)] == pytest.approx(18.43, abs=0.1)
    assert steady[(7, 8)] == pytest.approx(21.0, abs=0.1)
    # the formerly-polluted narrow cells are GONE: the IS log's width-1/2
    # buckets are single-batch calls sitting at evaluate() boundaries, so
    # their deltas were host estimator time, not batch time (ADVICE r5 —
    # prev_t now resets at those boundaries)
    assert (2, 2) not in steady
    assert (2, 1) not in steady
    assert (3, 1) not in steady
    # 4 pooled ratio points at widths 4/8, all well below flat scaling
    assert len(pts) == 4
    assert {w for w, _ in pts} == {4, 8}
    for w, r in pts:
        assert r < 0.6, (w, r)            # refutes the latency-bound prior
        assert r == pytest.approx(w / 16.0, abs=0.06)  # ~linear in width
    a, c = proj.fit_affine(pts + [(16, 1.0)])
    assert 0.055 <= a <= 0.072            # slope ~1/16
    assert abs(c) < 0.1                   # near-zero per-batch constant


def test_schedule_reproduces_engine_bucket_plan():
    # the exact 8-device plan PROJECTION_r4data.md's number is built on
    assert proj.schedule(10, 8, 16, pow2=False) == [
        (1, 16, 1), (2, 64, 1), (3, 128, 1), (4, 128, 2), (5, 128, 2),
        (6, 128, 2), (7, 128, 1), (8, 64, 1), (9, 16, 1), (10, 8, 1)]
    assert proj.schedule(10, 8, 16, pow2=True) == [
        (1, 16, 1), (2, 64, 1), (4, 128, 3), (8, 128, 5), (10, 16, 1)]
    # the merged-adjacent-size plan (engine default, MPLC_TPU_SLOT_MERGE):
    # 5 slot programs, the even size's tail filling the odd size's batches
    assert proj.schedule(10, 8, 16, pow2=False, merge=True) == [
        (1, 16, 1), (3, 128, 2), (5, 128, 4), (7, 128, 3), (9, 64, 1),
        (10, 8, 1)]


def test_schedule_merge_widths_match_engine_rule():
    from mplc_tpu.contrib.engine import CharacteristicEngine

    class _E:
        _slot_pow2 = False
        _slot_merge = True

    for n in (4, 5, 7, 10, 12):
        _E.partners_count = n
        eng_widths = {CharacteristicEngine._slot_width(_E, k)
                      for k in range(2, n + 1)}
        sched_widths = {w for w, _b, _n in
                        proj.schedule(n, 8, 16, pow2=False, merge=True)
                        if w > 1}
        assert sched_widths == eng_widths, n


def _trace_line(name, dur, **attrs):
    import json
    return json.dumps({"name": name, "id": 1, "parent": None, "ts": 0.0,
                       "dur": dur, "thread": 1, "attrs": attrs})


def test_trace_jsonl_batch_times_and_split(tmp_path):
    """A structured JSONL trace feeds the projection directly: engine.batch
    spans are measured durations, so cross-evaluate host gaps (the thing
    the log parser's reset-at-boundary rule exists to excise) cannot
    pollute any cell by construction — a batch recorded right after a long
    estimator pause carries its own dur. Malformed tail lines (wedge
    mid-write) are skipped."""
    trace = tmp_path / "sweep_trace.jsonl"
    lines = [
        _trace_line("engine.evaluate", 100.0, requested=20, missing=20),
        _trace_line("engine.prep", 0.5, width=16, slot_count=3),
        _trace_line("engine.dispatch", 0.2, width=16, slot_count=3),
        _trace_line("engine.batch", 31.0, width=16, slot_count=3,
                    coalitions=16, padding=0, epochs=128),
        _trace_line("engine.harvest", 30.0, width=16, slot_count=3),
        # an estimator pause happens HERE in wall-clock; the next batch's
        # dur is unaffected (no differencing)
        _trace_line("engine.batch", 33.0, width=16, slot_count=3,
                    coalitions=16, padding=0, epochs=128),
        _trace_line("engine.batch", 12.0, width=16, slot_count=None,
                    coalitions=10, padding=6, epochs=80),
        '{"truncated": ',
    ]
    trace.write_text("\n".join(lines) + "\n")
    times = proj.parse_batch_times(str(trace))
    assert times == {3: [31.0, 33.0], None: [12.0]}
    split = proj.parse_trace_split(str(trace))
    assert split == {"evaluate_s": 100.0, "prep_s": 0.5,
                     "dispatch_s": 0.2, "harvest_s": 30.0}


def test_telemetry_split_reads_prep_row(tmp_path):
    """The bench sidecar's wall-clock split — including the new
    engine.prep row — loads for the projection summary; a pre-prep-schema
    sidecar loads with prep_s = 0 instead of failing."""
    import json
    new = tmp_path / "telemetry_config1.json"
    new.write_text(json.dumps({
        "metric": "m", "wallclock_s": 300.0,
        "report": {"wallclock": {"evaluate_s": 290.0, "compile_s": 1.0,
                                 "prep_s": 2.5, "dispatch_s": 8.0,
                                 "harvest_s": 250.0}}}))
    w = proj.load_telemetry_split(str(new))
    assert w["prep_s"] == 2.5 and w["evaluate_s"] == 290.0
    old = tmp_path / "telemetry_old.json"
    old.write_text(json.dumps({
        "metric": "m",
        "report": {"wallclock": {"evaluate_s": 290.0, "compile_s": 1.0,
                                 "dispatch_s": 8.0, "harvest_s": 250.0}}}))
    assert proj.load_telemetry_split(str(old))["prep_s"] == 0.0


@pytest.mark.skipif(not R4_ISLOG.exists(), reason="r4 artifact absent")
def test_truncated_log_drops_incomplete_trailing_call(tmp_path):
    lines = R4_ISLOG.read_text().splitlines()
    cut = max(i for i, ln in enumerate(lines)
              if "left in call" in ln and " 0 left" not in ln)
    trunc = tmp_path / "trunc.log"
    trunc.write_text("\n".join(lines[:cut + 1]))
    pts, steady = proj.parse_is_log_ratios(str(trunc), record_cap=16)
    assert steady                   # still mines the complete calls
    assert (3, 16) in steady        # early complete calls survive the cut
    # cells that survive the cut agree with the full-log mining
    _, steady_full = proj.parse_is_log_ratios(str(R4_ISLOG), record_cap=16)
    for kw, v in steady.items():
        assert v == pytest.approx(steady_full[kw], rel=0.35), kw


@pytest.mark.skipif(not (R4_SWEEP.exists() and R4_ISLOG.exists()),
                    reason="r4 artifacts absent")
def test_headline_projection_number_is_stable():
    """End-to-end pin on the committed headline: the measured-r(w)
    batch-granular projection of the 10-partner sweep on 8 devices must
    stay in the documented band (PROJECTION_r4data.md: 290 s, bar 300 s).
    Any parser/model/schedule drift that moves the claim fails here."""
    times = proj.parse_batch_times(str(R4_SWEEP))

    def median(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2]

    t16 = {(1 if s is None else s): float(median(ds))
           for s, ds in times.items()}
    pts, _ = proj.parse_is_log_ratios(str(R4_ISLOG), record_cap=16)
    a, c = proj.fit_affine(pts + [(16, 1.0)])
    r = lambda w: max(a * w + c, 1e-6)  # noqa: E731

    total = 0.0
    for slot_w, b, nb in proj.schedule(10, 8, 16, pow2=False):
        per_dev_w = b / 8
        if slot_w == 10:
            base = t16[10] * r(16) / r(1)   # measured at width 1
        else:
            base = t16[slot_w]
        total += nb * base * r(per_dev_w) / r(16)
    assert 280 <= total <= 300, total


def test_telemetry_compute_row_loads_and_degrades(tmp_path):
    """load_telemetry_compute reads the sweep report's MFU-proxy row from
    a bench sidecar; pre-compute-schema sidecars load as {} (the
    projection prints nothing extra) instead of failing."""
    import json
    new = tmp_path / "telemetry_config1.json"
    new.write_text(json.dumps({
        "metric": "m",
        "report": {"wallclock": {"evaluate_s": 290.0},
                   "compute": {"train_samples": 1000, "partner_passes": 40,
                               "model_flops_per_s": 7.5e12,
                               "mfu_proxy": 0.038}}}))
    c = proj.load_telemetry_compute(str(new))
    assert c["train_samples"] == 1000
    assert c["mfu_proxy"] == 0.038
    old = tmp_path / "telemetry_old.json"
    old.write_text(json.dumps({
        "metric": "m", "report": {"wallclock": {"evaluate_s": 290.0}}}))
    assert proj.load_telemetry_compute(str(old)) == {}


def test_telemetry_trust_row_loads_and_degrades(tmp_path):
    """load_telemetry_trust reads the seed-ensemble trust row from a
    bench sidecar; single-seed and pre-trust-schema sidecars load as {}
    (the projection prints nothing extra) instead of failing — same
    compat contract as the resilience row."""
    import json
    new = tmp_path / "telemetry_config1.json"
    new.write_text(json.dumps({
        "metric": "m",
        "report": {"wallclock": {"evaluate_s": 290.0},
                   "trust": {"ensemble": 5, "kendall_tau": 0.87,
                             "mean": [0.1, 0.2], "ci_low": [0.05, 0.15],
                             "ci_high": [0.15, 0.25]}}}))
    t = proj.load_telemetry_trust(str(new))
    assert t["ensemble"] == 5
    assert t["kendall_tau"] == 0.87
    old = tmp_path / "telemetry_old.json"
    old.write_text(json.dumps({
        "metric": "m", "report": {"wallclock": {"evaluate_s": 290.0}}}))
    assert proj.load_telemetry_trust(str(old)) == {}


def test_telemetry_service_row_loads_and_degrades(tmp_path):
    """load_telemetry_service reads the multi-tenant service row from a
    BENCH_CONFIG=6 sidecar; single-tenant and pre-service-schema sidecars
    load as {} — same compat contract as the other rows."""
    import json
    new = tmp_path / "telemetry_config6.json"
    new.write_text(json.dumps({
        "metric": "m",
        "report": {"wallclock": {"evaluate_s": 1.0},
                   "service": {"jobs": 2, "completed": 2,
                               "quarantined": 0, "cancelled": 0,
                               "recovered": 0,
                               "cross_tenant_packed_batches": 3,
                               "per_tenant": {
                                   "a": {"seconds": 0.6, "cost_share": 0.6},
                                   "b": {"seconds": 0.4,
                                         "cost_share": 0.4}}}}}))
    svc = proj.load_telemetry_service(str(new))
    assert svc["jobs"] == 2
    assert svc["cross_tenant_packed_batches"] == 3
    old = tmp_path / "telemetry_old.json"
    old.write_text(json.dumps({
        "metric": "m", "report": {"wallclock": {"evaluate_s": 290.0}}}))
    assert proj.load_telemetry_service(str(old)) == {}


def _fleet_sidecar_doc():
    return {
        "metric": "fleet_sweep_titanic_10partners_8epochs_8dev_wallclock"
                  "_cpumesh",
        "wallclock_s": 4.0, "devices": 8,
        "fleet": {
            "provenance": "cpu_mesh",
            "scaling_basis": "max_shard_wallclock",
            "points": [
                {"devices": 1, "shards": 1, "fleet_wallclock_s": 12.0,
                 "speedup_vs_1": 1.0},
                {"devices": 8, "shards": 8, "fleet_wallclock_s": 4.0,
                 "speedup_vs_1": 3.0}],
            "equality": {"shards": 4, "drift": False,
                         "ulp": {"max": 0}, "kendall_tau": 1.0},
        },
    }


def test_load_measured_fleet_accessor_degrades(tmp_path):
    """{} for an absent sidecar, an invalid one, or one without fleet
    points (an ordinary config-1 sidecar) — only a real measured curve
    triggers the precedence rule."""
    import json
    assert proj.load_measured_fleet(str(tmp_path / "none.json")) == {}
    bad = tmp_path / "bad.json"
    bad.write_text("{torn")
    assert proj.load_measured_fleet(str(bad)) == {}
    plain = tmp_path / "telemetry_config1.json"
    plain.write_text(json.dumps({"metric": "m", "wallclock_s": 1.0}))
    assert proj.load_measured_fleet(str(plain)) == {}
    measured = tmp_path / "telemetry_config9.json"
    measured.write_text(json.dumps(_fleet_sidecar_doc()))
    m = proj.load_measured_fleet(str(measured))
    assert m["provenance"] == "cpu_mesh"
    assert m["points"][-1]["devices"] == 8
    out = proj.format_measured_fleet(m, str(measured))
    assert "SUPERSEDED" in out
    assert "not a TPU number" in out     # cpu_mesh provenance flagged
    assert "tau=1.0" in out


def test_projection_precedence_rule_in_main(tmp_path, capsys, monkeypatch):
    """The precedence rule end to end: without a measured BENCH_CONFIG=9
    sidecar the pinned projection STANDS; with one it is printed and
    marked SUPERSEDED (the projection pins stay printed either way)."""
    import json
    monkeypatch.chdir(ROOT)
    monkeypatch.setattr(sys, "argv", [
        "project_v5e8.py",
        "--fleet-telemetry", str(tmp_path / "none.json")])
    proj.main()
    out = capsys.readouterr().out
    assert "projected 10-partner sweep" in out    # the pins still print
    assert "STANDS" in out and "SUPERSEDED" not in out
    measured = tmp_path / "telemetry_config9.json"
    measured.write_text(json.dumps(_fleet_sidecar_doc()))
    monkeypatch.setattr(sys, "argv", [
        "project_v5e8.py", "--fleet-telemetry", str(measured)])
    proj.main()
    out = capsys.readouterr().out
    assert "projected 10-partner sweep" in out    # pins kept for compare
    assert "MEASURED fleet scaling" in out and "SUPERSEDED" in out


def test_telemetry_precision_and_recon_blocks_load_and_degrade(tmp_path):
    """load_telemetry_precision / load_telemetry_recon read the ISSUE-17
    top-level sidecar blocks; fp32/scan runs and pre-kernel sidecars
    load as {} — same compat contract as the report rows."""
    import json
    new = tmp_path / "telemetry_config8.json"
    new.write_text(json.dumps({
        "metric": "m",
        "report": {"wallclock": {"evaluate_s": 1.0}},
        "precision": {"mode": "bf16", "tau_b": 1.0,
                      "fp32_reference_s": 2.5, "common": 15,
                      "ulp": {"max": 9e12, "p99": 3e11, "nonzero": 3}},
        "recon": {"kernel_mode": "interpret", "use_kernel": True,
                  "interpret": True, "precision": "bf16",
                  "kernel_query_s": 0.123}}))
    pr = proj.load_telemetry_precision(str(new))
    assert pr["mode"] == "bf16" and pr["tau_b"] == 1.0
    rk = proj.load_telemetry_recon(str(new))
    assert rk["use_kernel"] is True and rk["kernel_query_s"] == 0.123
    old = tmp_path / "telemetry_old.json"
    old.write_text(json.dumps({
        "metric": "m", "report": {"wallclock": {"evaluate_s": 290.0}}}))
    assert proj.load_telemetry_precision(str(old)) == {}
    assert proj.load_telemetry_recon(str(old)) == {}
