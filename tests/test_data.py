"""Data layer: loaders, splits, corruption ops, stacking invariants."""

import numpy as np
import pytest

from mplc_tpu import constants
from mplc_tpu.data.datasets import (Dataset, load_dataset, to_categorical,
                                    synthetic_image_classification)
from mplc_tpu.data.partition import (StackedPartners, compute_batch_sizes,
                                     split_advanced, split_basic, stack_eval_set)
from mplc_tpu.data.partner import Partner


@pytest.mark.parametrize("name", constants.SUPPORTED_DATASETS_NAMES)
def test_builtin_loaders(name):
    ds = load_dataset(name)
    assert ds.name == name
    assert len(ds.x_train) > 0 and len(ds.x_val) > 0 and len(ds.x_test) > 0
    assert ds.x_train.shape[1:] == ds.input_shape
    assert ds.model is not None
    # global split is 90/10
    total = len(ds.x_train) + len(ds.x_val)
    assert abs(len(ds.x_val) / total - 0.1) < 0.02


def test_double_global_split_raises():
    x, y = synthetic_image_classification(np.random.default_rng(0), 50, (8, 8, 1), 3)
    ds = Dataset("d", (8, 8, 1), 3, x, to_categorical(y, 3), x, to_categorical(y, 3))
    with pytest.raises(Exception):
        ds.train_val_split_global()


def test_shorten_dataset_proportion():
    x, y = synthetic_image_classification(np.random.default_rng(0), 200, (8, 8, 1), 3)
    ds = Dataset("d", (8, 8, 1), 3, x, to_categorical(y, 3), x[:20], to_categorical(y[:20], 3))
    n0 = len(ds.x_train)
    ds.shorten_dataset_proportion(0.5)
    assert len(ds.x_train) == int(round(n0 * 0.5))


def _mk_dataset(n=300, c=4):
    x, y = synthetic_image_classification(np.random.default_rng(1), n, (6, 6, 1), c)
    return Dataset("d", (6, 6, 1), c, x, to_categorical(y, c),
                   x[:30], to_categorical(y[:30], c))


def test_split_basic_random_amounts():
    ds = _mk_dataset()
    partners = [Partner(i) for i in range(3)]
    split_basic(ds, partners, [0.5, 0.3, 0.2], "random", minibatch_count=2)
    n = len(ds.x_train)
    sizes = [len(p.x_train) for p in partners]
    assert sum(sizes) == n
    assert abs(sizes[0] / n - 0.5) < 0.02
    # deterministic: same seed-42 shuffle
    partners2 = [Partner(i) for i in range(3)]
    split_basic(ds, partners2, [0.5, 0.3, 0.2], "random", minibatch_count=2)
    assert np.array_equal(partners[0].x_train, partners2[0].x_train)


def test_split_basic_stratified_clusters():
    ds = _mk_dataset(400, 4)
    partners = [Partner(i) for i in range(4)]
    split_basic(ds, partners, [0.25, 0.25, 0.25, 0.25], "stratified", minibatch_count=2)
    # stratified: each partner covers a narrow label range
    for p in partners:
        assert len(p.clusters_list) <= 3


def test_split_basic_bad_amounts_raises():
    ds = _mk_dataset()
    partners = [Partner(i) for i in range(2)]
    with pytest.raises(AssertionError):
        split_basic(ds, partners, [0.5, 0.4], "random", minibatch_count=2)


def test_split_advanced():
    ds = _mk_dataset(600, 4)
    partners = [Partner(i) for i in range(3)]
    desc = [[2, "shared"], [2, "shared"], [1, "specific"]]
    split_advanced(ds, partners, [0.4, 0.4, 0.2], desc, minibatch_count=2)
    assert all(len(p.x_train) > 0 for p in partners)
    assert len(partners[2].clusters_list) == 1
    # specific partner's labels must be the single assigned cluster
    enc_labels = set(np.argmax(partners[2].y_train, axis=1).tolist())
    assert len(enc_labels) == 1


def test_compute_batch_sizes():
    partners = [Partner(i) for i in range(2)]
    for p, n in zip(partners, [100, 1000]):
        p.x_train = np.zeros((n, 2))
        p.y_train = np.zeros((n, 2))
    compute_batch_sizes(partners, minibatch_count=5,
                        gradient_updates_per_pass_count=2, max_batch_size=1 << 20)
    assert partners[0].batch_size == 10
    assert partners[1].batch_size == 100
    single = [partners[1]]
    compute_batch_sizes(single, 5, 2, 1 << 20)
    assert partners[1].batch_size == 500


# -- corruption ops ----------------------------------------------------------

def _one_hot_partner(n=60, c=5):
    p = Partner(0)
    y = np.random.default_rng(3).integers(0, c, n)
    p.y_train = to_categorical(y, c)
    p.x_train = np.zeros((n, 2), np.float32)
    return p


def test_corrupt_labels_offsets():
    p = _one_hot_partner()
    before = np.argmax(p.y_train, axis=1).copy()
    p.corrupt_labels(1.0)
    after = np.argmax(p.y_train, axis=1)
    # every label moved to class-1 (mod C)
    assert np.array_equal(after, (before - 1) % p.y_train.shape[1])
    assert np.allclose(p.y_train.sum(axis=1), 1.0)


def test_permute_labels_matrix_is_permutation():
    p = _one_hot_partner()
    p.permute_labels(1.0)
    m = p.corruption_matrix
    assert np.array_equal(m.sum(axis=0), np.ones(m.shape[0]))
    assert np.array_equal(m.sum(axis=1), np.ones(m.shape[0]))
    assert np.allclose(p.y_train.sum(axis=1), 1.0)


def test_random_labels_keeps_onehot():
    p = _one_hot_partner()
    p.random_labels(1.0)
    assert np.allclose(p.y_train.sum(axis=1), 1.0)
    assert ((p.y_train == 0) | (p.y_train == 1)).all()


def test_shuffle_labels_proportion():
    p = _one_hot_partner(100)
    before = p.y_train.copy()
    p.shuffle_labels(0.5)
    changed = (np.argmax(p.y_train, 1) != np.argmax(before, 1)).mean()
    assert 0.1 < changed < 0.6  # ~50% selected, each shuffle changes w.p. (C-1)/C
    assert np.allclose(p.y_train.sum(axis=1), 1.0)


def test_corruption_proportion_bounds():
    p = _one_hot_partner()
    with pytest.raises(ValueError):
        p.corrupt_labels(1.5)


def test_corruption_on_integer_labels():
    p = Partner(0)
    p.y_train = np.random.default_rng(0).integers(0, 4, 50)
    p.x_train = np.zeros((50, 2))
    p.permute_labels(1.0)
    assert p.y_train.ndim == 1  # demoted back to integer labels


# -- stacking ---------------------------------------------------------------

def test_stacked_partners_layout():
    partners = []
    for i, n in enumerate([20, 35, 10]):
        p = Partner(i)
        p.x_train = np.full((n, 3, 3, 1), i, np.float32)
        p.y_train = to_categorical(np.zeros(n, int), 4)
        partners.append(p)
    st = StackedPartners.build(partners, 4)
    assert st.x.shape == (3, 35, 3, 3, 1)
    assert st.sizes.tolist() == [20, 35, 10]
    assert float(st.mask[0].sum()) == 20
    assert float(st.mask[2, 10:].sum()) == 0
    assert float(st.x[2, 5, 0, 0, 0]) == 2.0


def test_stack_eval_set_chunks():
    x = np.arange(10, dtype=np.float32).reshape(10, 1)
    y = np.zeros((10, 2), np.float32)
    cx, cy, cm = stack_eval_set(x, y, 2, chunk=4)
    assert cx.shape == (3, 4, 1)
    assert float(cm.sum()) == 10
