"""Raw-data ingestion: Titanic CSV featurization and ESC-50 MFCC pipeline
(reference mplc/dataset.py:214-323 and :604-617), on tiny local fixtures —
no network, mirroring the reference's local_data cache behavior."""

import numpy as np
import pytest


TITANIC_CSV = """Survived,Pclass,Name,Sex,Age,Siblings/Spouses Aboard,Parents/Children Aboard,Fare
0,3,Mr. Owen Harris Braund,male,22,1,0,7.25
1,1,Mrs. John Bradley Cumings,female,38,1,0,71.2833
1,3,Miss. Laina Heikkinen,female,26,0,0,7.925
1,1,Mrs. Jacques Heath Futrelle,female,35,1,0,53.1
0,3,Mr. William Henry Allen,male,35,0,0,8.05
0,3,Mr. James Moran,male,27,0,0,8.4583
0,1,Mr. Timothy J McCarthy,male,54,0,0,51.8625
0,3,Master. Gosta Leonard Palsson,male,2,3,1,21.075
1,3,Mrs. Oscar W Johnson,female,27,0,2,11.1333
1,2,Mrs. Nicholas Nasser,female,14,1,0,30.0708
1,3,Miss. Marguerite Rut Sandstrom,female,4,1,1,16.7
1,1,Miss. Elizabeth Bonnell,female,58,0,0,26.55
"""


def test_titanic_csv_featurization(tmp_path):
    from mplc_tpu.data.datasets import featurize_titanic_csv
    from mplc_tpu.models.zoo import TITANIC_NUM_FEATURES

    csv = tmp_path / "titanic.csv"
    csv.write_text(TITANIC_CSV)
    x, y = featurize_titanic_csv(csv)
    assert x.shape == (12, TITANIC_NUM_FEATURES)
    assert x.dtype == np.float32
    np.testing.assert_array_equal(
        y, [0, 1, 1, 1, 0, 0, 0, 0, 1, 1, 1, 1])
    # column 0 = sex flag (case-insensitive, unlike the upstream bug)
    np.testing.assert_array_equal(
        x[:, 0], [1, 0, 0, 0, 1, 1, 1, 1, 0, 0, 0, 0])
    # column 1 = age passes through numerically
    assert x[0, 1] == 22.0 and x[7, 1] == 2.0
    # family size and is-alone derived features
    fam = x[:, 3]
    assert fam[7] == 4.0 and fam[4] == 0.0
    assert x[4, 5] == 1.0 and x[7, 5] == 0.0
    # honorific one-hots: every row carries exactly one title flag
    title_block = x[:, 9:]
    assert np.all(title_block.sum(axis=1) == 1.0)


def test_titanic_loader_prefers_raw_csv(tmp_path, monkeypatch):
    (tmp_path / "titanic.csv").write_text(TITANIC_CSV)
    monkeypatch.setenv("MPLC_TPU_DATA_DIR", str(tmp_path))
    from mplc_tpu.data.datasets import load_titanic
    ds = load_titanic()
    assert ds.provenance.startswith("raw:")
    assert ds.x_train.shape[1] == 27
    # 12 rows -> 10% test then 10% val of the rest
    total = len(ds.x_train) + len(ds.x_val) + len(ds.x_test)
    assert total == 12


def _write_sine_wav(path, freq, sr=8000, seconds=1.0):
    from scipy.io import wavfile
    t = np.arange(int(sr * seconds)) / sr
    data = (0.5 * np.sin(2 * np.pi * freq * t) * 32767).astype(np.int16)
    wavfile.write(path, sr, data)


def test_mfcc_shapes_and_discrimination():
    from mplc_tpu.data.audio import mfcc

    sr = 44100
    t = np.arange(sr * 5) / sr
    m = mfcc(np.sin(2 * np.pi * 440 * t), sr, n_mfcc=40)
    assert m.shape == (40, 431)          # the ESC-50 model input geometry
    assert np.isfinite(m).all()
    m2 = mfcc(np.sin(2 * np.pi * 1760 * t), sr, n_mfcc=40)
    # different pitches must land in measurably different cepstra
    assert np.abs(m - m2).mean() > 1.0


def test_esc50_raw_ingestion(tmp_path):
    from mplc_tpu.data.datasets import load_esc50_raw

    folder = tmp_path / "esc50"
    (folder / "audio").mkdir(parents=True)
    _write_sine_wav(folder / "audio" / "a.wav", 440)
    _write_sine_wav(folder / "audio" / "b.wav", 880)
    (folder / "esc50.csv").write_text(
        "filename,fold,target,category\na.wav,1,3,dog\nb.wav,1,17,pouring_water\n")

    x, y = load_esc50_raw(folder)
    assert x.shape == (2, 40, 431, 1)    # short clip padded to 431 frames
    assert x.dtype == np.float32
    np.testing.assert_array_equal(y, [3, 17])
    assert np.isfinite(x).all()
