"""Knob-hygiene static checks: every `MPLC_TPU_*` env knob the framework
reads must be registered in `constants.ENV_KNOBS`, and every registered
knob's class obligations must hold in bench.py — workload-shaping knobs
appear in BOTH the cached-replay refusal list and the CPU-fallback
env-strip list, sidecar knobs at least in the strip list.

PRs 1-3 each extended bench's two lists by hand; this test makes
forgetting one (or introducing an unregistered knob) a fast-tier failure
instead of a silently wrong cached-replay / fallback number.

Donation-policy lint (ISSUE 8 satellite): every `jax.jit` call under
`mplc_tpu/` must either declare `donate_argnums`/`donate_argnames`
(including an explicit empty tuple — the conditional donation idiom) or
appear in the no-donation allowlist below with a reason string. A jit
that silently omits the decision is how param-side HBM regresses: the
next state-carrying jit someone adds would hold two copies of its
buffers without anyone choosing that."""

import ast
import importlib
import inspect
import re
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
bench = importlib.import_module("bench")

from mplc_tpu import constants

REPO = Path(__file__).resolve().parents[1]
_KNOB_RE = re.compile(r"MPLC_TPU_[A-Z0-9_]+")


def _knobs_in_sources() -> set:
    found = set()
    files = [REPO / "bench.py", REPO / "main.py", REPO / "__graft_entry__.py"]
    files += sorted((REPO / "mplc_tpu").rglob("*.py"))
    files += sorted((REPO / "scripts").glob("*.py"))
    for f in files:
        found |= set(_KNOB_RE.findall(f.read_text()))
    return found


def test_every_knob_in_source_is_registered():
    """A new `MPLC_TPU_*` env var anywhere in the package/bench/scripts
    source must be added to constants.ENV_KNOBS with a class — that is
    what forces the bench-list decision to be made consciously."""
    unregistered = _knobs_in_sources() - set(constants.ENV_KNOBS)
    assert not unregistered, (
        f"env knobs {sorted(unregistered)} are read in the source tree but "
        "not registered in constants.ENV_KNOBS — register them (class "
        "'workload' | 'sidecar' | 'ambient') so the bench replay/fallback "
        "obligations are checked")


def test_static_scan_covers_the_service_package():
    """The knob scan and the donation lint both walk `mplc_tpu/` by
    rglob, so the service subpackage (mplc_tpu/service/) must be inside
    that walk — a knob read (or an undeclared jit) added there has to
    fail these checks, not hide in an unscanned directory."""
    service_dir = REPO / "mplc_tpu" / "service"
    assert service_dir.is_dir()
    scanned = set(sorted((REPO / "mplc_tpu").rglob("*.py")))
    svc_files = set(service_dir.glob("*.py"))
    assert svc_files and svc_files <= scanned
    # and the service's own knobs are registered with the workload class
    # (their values reshape the multi-tenant bench workload)
    for knob in ("MPLC_TPU_SERVICE_FAULT_PLAN",
                 "MPLC_TPU_SERVICE_MAX_PENDING", "MPLC_TPU_SERVICE_SLICE"):
        assert constants.ENV_KNOBS.get(knob) == "workload", knob


def test_static_scan_covers_the_live_package():
    """Same obligation for the live tier (mplc_tpu/live/) as PR 9
    established for service/: the knob scan, donation lint and span scan
    all walk `mplc_tpu/` by rglob, so the live subpackage must be inside
    that walk, its knobs registered workload-class, and its span names
    in the registry — a knob or span added there has to fail these
    checks, not hide in an unscanned directory."""
    live_dir = REPO / "mplc_tpu" / "live"
    assert live_dir.is_dir()
    scanned = set(sorted((REPO / "mplc_tpu").rglob("*.py")))
    live_files = set(live_dir.glob("*.py"))
    assert live_files and live_files <= scanned
    # the live knobs reshape what a live-query bench run computes
    # (pruning schedule, reconstruction depth, deadline survival)
    for knob in ("MPLC_TPU_LIVE_PRUNE_TAU", "MPLC_TPU_LIVE_MAX_ROUNDS",
                 "MPLC_TPU_LIVE_QUERY_DEADLINE_SEC",
                 # the residency/ingestion/hierarchy tier (ISSUE 18):
                 # cap, ingestion opt-in and clustering shape all change
                 # what a BENCH_CONFIG=10 run measures
                 "MPLC_TPU_LIVE_MAX_RESIDENT", "MPLC_TPU_LIVE_INGEST",
                 "MPLC_TPU_LIVE_CLUSTERS", "MPLC_TPU_LIVE_CLUSTER_TAU"):
        assert constants.ENV_KNOBS.get(knob) == "workload", knob
    # and the tier's trace vocabulary is registered (consumers: the
    # report's live row, the Perfetto exporter)
    from mplc_tpu.obs.trace import SPAN_REGISTRY
    for name in ("live.query", "live.append", "live.recover",
                 "live.evict", "live.restore", "live.ingest"):
        assert name in SPAN_REGISTRY, name


def test_registry_has_no_stale_entries():
    stale = set(constants.ENV_KNOBS) - _knobs_in_sources()
    assert not stale, (
        f"constants.ENV_KNOBS registers {sorted(stale)} but nothing in the "
        "source tree reads them — remove the dead entries")


def test_registry_classes_are_valid():
    assert set(constants.ENV_KNOBS.values()) <= {"workload", "sidecar",
                                                 "ambient"}


def test_workload_knobs_refuse_replay_and_strip_from_fallback():
    """Every workload-shaping knob must be covered by bench's cached-
    replay refusal AND the CPU-fallback env-strip: a cached TPU number is
    a different workload under any non-default value, and the reduced CPU
    child must not inherit parent tuning. Coverage is via the shared
    bench._WORKLOAD_KNOBS list (both functions must reference it) or a
    knob-specific special case in the function source (SYNTH_NOISE)."""
    src_replay = inspect.getsource(bench._replay_cached_tpu_result)
    src_spawn = inspect.getsource(bench._spawn_cpu_fallback)
    assert "_WORKLOAD_KNOBS" in src_replay, (
        "bench._replay_cached_tpu_result no longer iterates the shared "
        "_WORKLOAD_KNOBS list")
    assert "_WORKLOAD_KNOBS" in src_spawn, (
        "bench._spawn_cpu_fallback no longer iterates the shared "
        "_WORKLOAD_KNOBS list")
    for knob, klass in sorted(constants.ENV_KNOBS.items()):
        if klass != "workload":
            continue
        assert knob in bench._WORKLOAD_KNOBS or knob in src_replay, (
            f"workload knob {knob} missing from bench._WORKLOAD_KNOBS "
            "and not special-cased in _replay_cached_tpu_result")
        assert knob in bench._WORKLOAD_KNOBS or knob in src_spawn, (
            f"workload knob {knob} missing from bench._WORKLOAD_KNOBS "
            "and not special-cased in _spawn_cpu_fallback")


def test_workload_knobs_are_documented():
    """Docs-drift check: every workload-shaping knob in ENV_KNOBS must be
    mentioned in documentation.md — a knob the docs never name is a knob
    operators discover by reading source (or never), and the doc's knob
    sections silently rot as PRs add knobs."""
    doc = (REPO / "mplc_tpu" / "doc" / "documentation.md").read_text()
    missing = [k for k, klass in sorted(constants.ENV_KNOBS.items())
               if klass == "workload" and k not in doc]
    assert not missing, (
        f"workload knobs {missing} are registered in constants.ENV_KNOBS "
        "but never mentioned in mplc_tpu/doc/documentation.md — document "
        "them (what they shape, defaults, deviation semantics)")


def test_sidecar_knobs_are_stripped_from_fallback():
    """Sidecar/observability knobs must not leak into the CPU-fallback
    child (it writes its own sidecars); they do not refuse replay."""
    src_spawn = inspect.getsource(bench._spawn_cpu_fallback)
    for knob, klass in sorted(constants.ENV_KNOBS.items()):
        if klass == "sidecar":
            assert knob in src_spawn, (
                f"sidecar knob {knob} missing from "
                "bench._spawn_cpu_fallback's env-strip list")


# -- donation-policy lint ----------------------------------------------------
#
# (relpath, dotted enclosing scope) -> reason the jit deliberately does
# NOT donate. Every entry must stay live (a stale entry fails below) and
# carry a non-empty reason.
_NO_DONATION_ALLOWLIST = {
    ("mplc_tpu/mpl/engine.py", "MplTrainer.jit_finalize"):
        "the fit driver (mpl/approaches.py) and the partner-shard tests "
        "read state.params and the histories AFTER finalize",
    ("mplc_tpu/mpl/engine.py", "MplTrainer.jit_evaluate"):
        "callers (PVRL's reward eval) pass the LIVE carried params, which "
        "train on in the next epoch",
    ("mplc_tpu/mpl/engine.py", "MplTrainer.jit_batched_init"):
        "the rng batch is the only array input and the caller passes it "
        "again to the epoch chunk",
    ("mplc_tpu/mpl/engine.py", "MplTrainer.jit_gen_streams"):
        "the deterministic stream generator's inputs are the live rng "
        "batch and the stacked mask, both reused by the chunk call "
        "dispatched right after",
    ("mplc_tpu/contrib/engine.py", "_fold_bitmask_keys"):
        "inputs are tiny uint32 word arrays plus the engine's SHARED seed "
        "key, which every later batch folds again",
    ("mplc_tpu/contrib/engine.py", "_fold_bitmask_keys_seeded"):
        "the ensemble seed-row table is reused by every batch of the sweep",
    ("mplc_tpu/contrib/engine.py", "Batched2DTrainerPipeline.__init__"):
        "init2d's rng batch is reused by the epoch chunk (the run/fin jits "
        "built here DO declare donation)",
    ("mplc_tpu/parallel/partner_shard.py",
     "PartnerShardedTrainer.init_state"):
        "the rng input is reused by the epoch chunk's training streams",
    ("mplc_tpu/parallel/partner_shard.py", "PartnerShardedTrainer.finalize"):
        "tests/test_partner_shard.py reads state.params and the val "
        "histories AFTER finalize",
}


def _jit_calls(path: Path):
    """(dotted scope, lineno, declares_donation) for every jax.jit call —
    including bare `@jax.jit` decorators — in one source file."""
    tree = ast.parse(path.read_text())
    found = []
    stack = []

    def is_jax_jit(node):
        return (isinstance(node, ast.Attribute) and node.attr == "jit"
                and isinstance(node.value, ast.Name)
                and node.value.id == "jax")

    class Visitor(ast.NodeVisitor):
        def _scoped(self, node):
            stack.append(node.name)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if is_jax_jit(dec):  # bare @jax.jit: no kwargs possible
                        found.append((".".join(stack), dec.lineno, False))
            self.generic_visit(node)
            stack.pop()

        visit_FunctionDef = visit_AsyncFunctionDef = visit_ClassDef = _scoped

        def visit_Call(self, node):
            if is_jax_jit(node.func):
                declares = any(
                    kw.arg in ("donate_argnums", "donate_argnames")
                    for kw in node.keywords)
                found.append((".".join(stack), node.lineno, declares))
            self.generic_visit(node)

    Visitor().visit(tree)
    return found


def _all_jit_calls():
    out = []
    for f in sorted((REPO / "mplc_tpu").rglob("*.py")):
        rel = f.relative_to(REPO).as_posix()
        for scope, lineno, declares in _jit_calls(f):
            out.append((rel, scope, lineno, declares))
    return out


def test_every_jit_declares_a_donation_policy():
    """The HBM-regression guard: a `jax.jit` under mplc_tpu/ either
    declares donate_argnums (possibly conditionally empty) or is
    allowlisted with a reason for why its inputs must survive the call."""
    undeclared = [
        f"{rel}:{lineno} (in {scope or '<module>'})"
        for rel, scope, lineno, declares in _all_jit_calls()
        if not declares and (rel, scope) not in _NO_DONATION_ALLOWLIST]
    assert not undeclared, (
        "jax.jit calls without a donation policy: " + ", ".join(undeclared)
        + " — declare donate_argnums (donating the dead state argument, "
        "or an explicit () if nothing can be donated) or add the call's "
        "(file, scope) to _NO_DONATION_ALLOWLIST with a reason")


def test_donation_allowlist_is_not_stale_and_has_reasons():
    live = {(rel, scope) for rel, scope, _, declares in _all_jit_calls()
            if not declares}
    stale = set(_NO_DONATION_ALLOWLIST) - live
    assert not stale, (
        f"_NO_DONATION_ALLOWLIST entries {sorted(stale)} no longer match "
        "an undeclared jax.jit call — remove them (or the jit they "
        "described gained donate_argnums, which supersedes the entry)")
    for key, reason in _NO_DONATION_ALLOWLIST.items():
        assert isinstance(reason, str) and reason.strip(), (
            f"allowlist entry {key} needs a non-empty reason string")


# -- span-name hygiene --------------------------------------------------------
#
# Trace CONSUMERS (obs/report.py, obs/chrome_trace.py, the projection
# scripts) dispatch on span-name string literals; an instrumentation
# rename that skips the consumers silently empties a report row. The
# static scan below collects every literal name passed to
# span()/start_span()/event() in the package, bench and scripts, and
# enforces two-way agreement with the documented registry
# (obs/trace.py SPAN_REGISTRY).

def _span_call_names():
    """(relpath, lineno, name_or_None) for every span()/start_span()/
    event() call site; name is None when the first argument is not a
    string literal (itself a hygiene violation: tooling can't scan it)."""
    files = [REPO / "bench.py"]
    files += sorted((REPO / "mplc_tpu").rglob("*.py"))
    files += sorted((REPO / "scripts").glob("*.py"))
    out = []
    for f in files:
        rel = f.relative_to(REPO).as_posix()
        tree = ast.parse(f.read_text())
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name not in ("span", "start_span", "event") or not node.args:
                continue
            first = node.args[0]
            literal = (first.value
                       if isinstance(first, ast.Constant)
                       and isinstance(first.value, str) else None)
            out.append((rel, node.lineno, literal))
    return out


def test_every_span_name_is_registered():
    from mplc_tpu.obs.trace import SPAN_REGISTRY

    sites = _span_call_names()
    assert sites, "the scan found no span()/event() call sites at all"
    dynamic = [f"{rel}:{ln}" for rel, ln, name in sites if name is None]
    assert not dynamic, (
        "span()/event() call sites with a non-literal name: "
        + ", ".join(dynamic)
        + " — span names must be string literals so consumer tooling "
        "(report rows, the Perfetto exporter, this scan) can see them")
    unregistered = sorted({name for _, _, name in sites
                           if name not in SPAN_REGISTRY})
    assert not unregistered, (
        f"span/event names {unregistered} are emitted but not listed in "
        "obs.trace.SPAN_REGISTRY — register them (with a one-line "
        "description) so trace consumers can't silently drift from the "
        "instrumentation")


def test_span_registry_has_no_stale_entries():
    from mplc_tpu.obs.trace import SPAN_REGISTRY

    emitted = {name for _, _, name in _span_call_names() if name}
    stale = sorted(set(SPAN_REGISTRY) - emitted)
    assert not stale, (
        f"obs.trace.SPAN_REGISTRY lists {stale} but no call site emits "
        "them — remove the dead entries (or the instrumentation they "
        "described was renamed without updating the registry)")
    for name, desc in SPAN_REGISTRY.items():
        assert isinstance(desc, str) and desc.strip(), (
            f"SPAN_REGISTRY[{name!r}] needs a non-empty description")


def test_synth_noise_refusal_is_non_default_only(tmp_path, monkeypatch):
    """MPLC_TPU_SYNTH_NOISE is always set by bench.main() before the
    replay gate runs, so the gate must allow the bench's own 0.75 default
    and refuse any other value (a different noise level is different
    synthetic data — a different workload)."""
    from test_bench_helpers import _clean_replay_env, _write_record

    _clean_replay_env(monkeypatch)
    monkeypatch.delenv("MPLC_TPU_FAULT_PLAN", raising=False)
    monkeypatch.delenv("MPLC_TPU_RETRY_BACKOFF_SEC", raising=False)
    _write_record(tmp_path, "r5",
                  "exact_shapley_mnist_10partners_8epochs_wallclock")
    monkeypatch.setenv("MPLC_TPU_SYNTH_NOISE", "0.75")
    assert bench._replay_cached_tpu_result(str(tmp_path)) is True
    monkeypatch.setenv("MPLC_TPU_SYNTH_NOISE", "0.5")
    assert bench._replay_cached_tpu_result(str(tmp_path)) is False


def test_fault_knobs_refuse_replay(tmp_path, monkeypatch, capsys):
    """Any set fault-tolerance knob refuses cached replay — a clean
    cached number must not stand in for a run that was asked to inject
    faults or reshape its recovery schedule (even re-stating a default
    refuses, same strictness as the other workload knobs)."""
    from test_bench_helpers import _clean_replay_env, _write_record

    _clean_replay_env(monkeypatch)
    monkeypatch.delenv("MPLC_TPU_FAULT_PLAN", raising=False)
    monkeypatch.delenv("MPLC_TPU_RETRY_BACKOFF_SEC", raising=False)
    _write_record(tmp_path, "r5",
                  "exact_shapley_mnist_10partners_8epochs_wallclock")
    capsys.readouterr()
    assert bench._replay_cached_tpu_result(str(tmp_path)) is True
    capsys.readouterr()
    for knob, val in (("MPLC_TPU_FAULT_PLAN", "transient@batch3"),
                      ("MPLC_TPU_MAX_RETRIES", "3"),
                      ("MPLC_TPU_RETRY_BACKOFF_SEC", "0.5"),
                      ("MPLC_TPU_MAX_CAP_HALVINGS", "3")):
        monkeypatch.setenv(knob, val)
        assert bench._replay_cached_tpu_result(str(tmp_path)) is False, knob
        monkeypatch.delenv(knob)
    assert capsys.readouterr().out.strip() == ""
