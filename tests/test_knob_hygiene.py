"""Knob-hygiene static check: every `MPLC_TPU_*` env knob the framework
reads must be registered in `constants.ENV_KNOBS`, and every registered
knob's class obligations must hold in bench.py — workload-shaping knobs
appear in BOTH the cached-replay refusal list and the CPU-fallback
env-strip list, sidecar knobs at least in the strip list.

PRs 1-3 each extended bench's two lists by hand; this test makes
forgetting one (or introducing an unregistered knob) a fast-tier failure
instead of a silently wrong cached-replay / fallback number."""

import importlib
import inspect
import re
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
bench = importlib.import_module("bench")

from mplc_tpu import constants

REPO = Path(__file__).resolve().parents[1]
_KNOB_RE = re.compile(r"MPLC_TPU_[A-Z0-9_]+")


def _knobs_in_sources() -> set:
    found = set()
    files = [REPO / "bench.py", REPO / "main.py", REPO / "__graft_entry__.py"]
    files += sorted((REPO / "mplc_tpu").rglob("*.py"))
    files += sorted((REPO / "scripts").glob("*.py"))
    for f in files:
        found |= set(_KNOB_RE.findall(f.read_text()))
    return found


def test_every_knob_in_source_is_registered():
    """A new `MPLC_TPU_*` env var anywhere in the package/bench/scripts
    source must be added to constants.ENV_KNOBS with a class — that is
    what forces the bench-list decision to be made consciously."""
    unregistered = _knobs_in_sources() - set(constants.ENV_KNOBS)
    assert not unregistered, (
        f"env knobs {sorted(unregistered)} are read in the source tree but "
        "not registered in constants.ENV_KNOBS — register them (class "
        "'workload' | 'sidecar' | 'ambient') so the bench replay/fallback "
        "obligations are checked")


def test_registry_has_no_stale_entries():
    stale = set(constants.ENV_KNOBS) - _knobs_in_sources()
    assert not stale, (
        f"constants.ENV_KNOBS registers {sorted(stale)} but nothing in the "
        "source tree reads them — remove the dead entries")


def test_registry_classes_are_valid():
    assert set(constants.ENV_KNOBS.values()) <= {"workload", "sidecar",
                                                 "ambient"}


def test_workload_knobs_refuse_replay_and_strip_from_fallback():
    """Every workload-shaping knob must be covered by bench's cached-
    replay refusal AND the CPU-fallback env-strip: a cached TPU number is
    a different workload under any non-default value, and the reduced CPU
    child must not inherit parent tuning. Coverage is via the shared
    bench._WORKLOAD_KNOBS list (both functions must reference it) or a
    knob-specific special case in the function source (SYNTH_NOISE)."""
    src_replay = inspect.getsource(bench._replay_cached_tpu_result)
    src_spawn = inspect.getsource(bench._spawn_cpu_fallback)
    assert "_WORKLOAD_KNOBS" in src_replay, (
        "bench._replay_cached_tpu_result no longer iterates the shared "
        "_WORKLOAD_KNOBS list")
    assert "_WORKLOAD_KNOBS" in src_spawn, (
        "bench._spawn_cpu_fallback no longer iterates the shared "
        "_WORKLOAD_KNOBS list")
    for knob, klass in sorted(constants.ENV_KNOBS.items()):
        if klass != "workload":
            continue
        assert knob in bench._WORKLOAD_KNOBS or knob in src_replay, (
            f"workload knob {knob} missing from bench._WORKLOAD_KNOBS "
            "and not special-cased in _replay_cached_tpu_result")
        assert knob in bench._WORKLOAD_KNOBS or knob in src_spawn, (
            f"workload knob {knob} missing from bench._WORKLOAD_KNOBS "
            "and not special-cased in _spawn_cpu_fallback")


def test_workload_knobs_are_documented():
    """Docs-drift check: every workload-shaping knob in ENV_KNOBS must be
    mentioned in documentation.md — a knob the docs never name is a knob
    operators discover by reading source (or never), and the doc's knob
    sections silently rot as PRs add knobs."""
    doc = (REPO / "mplc_tpu" / "doc" / "documentation.md").read_text()
    missing = [k for k, klass in sorted(constants.ENV_KNOBS.items())
               if klass == "workload" and k not in doc]
    assert not missing, (
        f"workload knobs {missing} are registered in constants.ENV_KNOBS "
        "but never mentioned in mplc_tpu/doc/documentation.md — document "
        "them (what they shape, defaults, deviation semantics)")


def test_sidecar_knobs_are_stripped_from_fallback():
    """Sidecar/observability knobs must not leak into the CPU-fallback
    child (it writes its own sidecars); they do not refuse replay."""
    src_spawn = inspect.getsource(bench._spawn_cpu_fallback)
    for knob, klass in sorted(constants.ENV_KNOBS.items()):
        if klass == "sidecar":
            assert knob in src_spawn, (
                f"sidecar knob {knob} missing from "
                "bench._spawn_cpu_fallback's env-strip list")


def test_synth_noise_refusal_is_non_default_only(tmp_path, monkeypatch):
    """MPLC_TPU_SYNTH_NOISE is always set by bench.main() before the
    replay gate runs, so the gate must allow the bench's own 0.75 default
    and refuse any other value (a different noise level is different
    synthetic data — a different workload)."""
    from test_bench_helpers import _clean_replay_env, _write_record

    _clean_replay_env(monkeypatch)
    monkeypatch.delenv("MPLC_TPU_FAULT_PLAN", raising=False)
    monkeypatch.delenv("MPLC_TPU_RETRY_BACKOFF_SEC", raising=False)
    _write_record(tmp_path, "r5",
                  "exact_shapley_mnist_10partners_8epochs_wallclock")
    monkeypatch.setenv("MPLC_TPU_SYNTH_NOISE", "0.75")
    assert bench._replay_cached_tpu_result(str(tmp_path)) is True
    monkeypatch.setenv("MPLC_TPU_SYNTH_NOISE", "0.5")
    assert bench._replay_cached_tpu_result(str(tmp_path)) is False


def test_fault_knobs_refuse_replay(tmp_path, monkeypatch, capsys):
    """Any set fault-tolerance knob refuses cached replay — a clean
    cached number must not stand in for a run that was asked to inject
    faults or reshape its recovery schedule (even re-stating a default
    refuses, same strictness as the other workload knobs)."""
    from test_bench_helpers import _clean_replay_env, _write_record

    _clean_replay_env(monkeypatch)
    monkeypatch.delenv("MPLC_TPU_FAULT_PLAN", raising=False)
    monkeypatch.delenv("MPLC_TPU_RETRY_BACKOFF_SEC", raising=False)
    _write_record(tmp_path, "r5",
                  "exact_shapley_mnist_10partners_8epochs_wallclock")
    capsys.readouterr()
    assert bench._replay_cached_tpu_result(str(tmp_path)) is True
    capsys.readouterr()
    for knob, val in (("MPLC_TPU_FAULT_PLAN", "transient@batch3"),
                      ("MPLC_TPU_MAX_RETRIES", "3"),
                      ("MPLC_TPU_RETRY_BACKOFF_SEC", "0.5"),
                      ("MPLC_TPU_MAX_CAP_HALVINGS", "3")):
        monkeypatch.setenv(knob, val)
        assert bench._replay_cached_tpu_result(str(tmp_path)) is False, knob
        monkeypatch.delenv(knob)
    assert capsys.readouterr().out.strip() == ""
