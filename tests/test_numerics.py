"""Numeric-truth plane (obs/numerics.py): ledger, audit, drift tooling.

Covers the PR's acceptance contracts: the ledger round-trips and is
deterministic; audit mode NEVER perturbs v(S) (bit-identity audit-on vs
audit-off, including under the PR-4 fault ladder's transient/OOM/CPU
rungs); deterministic-reduce makes 1-device and N-device engines
bit-identical; the audit localizes reduction-order divergence; and the
drift tooling (scripts/drift_diff.py, scripts/bench_diff.py `numerics`
gate) reports zero drift for same-seed runs, flags injected
perturbations, and stays schema-compatible with pre-numerics sidecars.
"""

import json
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from helpers import build_scenario
from mplc_tpu.contrib.engine import CharacteristicEngine
from mplc_tpu.contrib.shapley import powerset_order
from mplc_tpu.obs import numerics

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "scripts"))
import bench_diff  # noqa: E402
import drift_diff  # noqa: E402


def _scenario(seed=9, partners=4):
    amounts = {3: [0.2, 0.3, 0.5], 4: [0.1, 0.2, 0.3, 0.4]}[partners]
    return build_scenario(partners_count=partners,
                          amounts_per_partner=amounts,
                          dataset_name="titanic", epoch_count=2,
                          gradient_updates_per_pass_count=2, seed=seed)


# ---------------------------------------------------------------------------
# float forensics + ledger
# ---------------------------------------------------------------------------

def test_ulp_distance_basics():
    assert numerics.ulp_distance(1.0, 1.0) == 0
    assert numerics.ulp_distance(0.0, -0.0) == 0
    assert numerics.ulp_distance(1.0, np.nextafter(1.0, 2.0)) == 1
    assert numerics.ulp_distance(1.0, np.nextafter(1.0, 0.0)) == 1
    a = np.float32([1.0, 2.0, -0.0])
    b = np.float32([1.0, np.nextafter(np.float32(2.0), np.float32(3.0)), 0.0])
    np.testing.assert_array_equal(numerics.ulp_distance_f32(a, b), [0, 1, 0])


def test_float_bits_round_trip():
    for v in (0.0, -0.0, 1.0, -1.5, 0.1, 3.14159e-30, float("inf")):
        bits = numerics.float_bits(v)
        assert len(bits) == 16
        back = numerics.bits_to_float(bits)
        assert (back == v) or (np.isnan(back) and np.isnan(v))


def test_ledger_round_trip_and_determinism(tmp_path):
    def build(path):
        led = numerics.ValueLedger("fp123", {"topology": "1d",
                                             "part_shards": 1,
                                             "n_devices": 8,
                                             "reduction_mode": "default"},
                                   path=str(path))
        led.record((0, 1), 0.75, source="exact", slot_width=2)
        led.record((2,), 0.5, source="exact", slot_width=None,
                   cap_halvings=1, degraded=True)
        led.save()
        return led

    a = build(tmp_path / "a.json")
    b = build(tmp_path / "b.json")
    # determinism: identical inputs produce identical documents
    assert a.to_doc() == b.to_doc()
    # content hashes present and stable
    assert all(len(e["content_hash"]) == 16 for e in a.entries.values())
    # round trip through disk
    loaded = numerics.ValueLedger.load(str(tmp_path / "a.json"))
    assert loaded.to_doc()["entries"] == a.to_doc()["entries"]
    assert loaded.engine_fingerprint == "fp123"
    # subset keys are bitmask hex, order-insensitive
    assert numerics.ValueLedger.subset_key((1, 0)) == \
        numerics.ValueLedger.subset_key((0, 1)) == hex(0b11)


def test_kendall_tau_b_matches_bruteforce_and_scales():
    """The O(n log n) Knight tau-b must agree with the O(n^2) definition
    (ties included) and stay fast at full-ledger scale (2^16 subsets)."""
    def brute(a, b):
        n = len(a)
        conc = disc = ta = tb = 0
        for i in range(n):
            for j in range(i + 1, n):
                da, db = a[i] - a[j], b[i] - b[j]
                if da == 0 and db == 0:
                    continue
                if da == 0:
                    ta += 1
                elif db == 0:
                    tb += 1
                elif da * db > 0:
                    conc += 1
                else:
                    disc += 1
        d = ((conc + disc + ta) * (conc + disc + tb)) ** 0.5
        return (conc - disc) / d if d else None

    rng = np.random.default_rng(5)
    for _ in range(20):
        n = int(rng.integers(2, 40))
        # heavy ties: values drawn from a tiny alphabet
        a = list(rng.integers(0, 5, n).astype(float))
        b = list(rng.integers(0, 5, n).astype(float))
        got, want = numerics.kendall_tau_b(a, b), brute(a, b)
        if want is None:
            assert got is None
        else:
            assert got == pytest.approx(want, abs=1e-12), (a, b)
    # identical lists with ties: exactly 1.0
    a = list(rng.uniform(size=30)) + [0.5, 0.5, 0.5]
    assert numerics.kendall_tau_b(a, a) == 1.0
    # full-ledger scale: 2^16 pairs must finish in seconds, not hours
    big = rng.uniform(size=65536)
    t0 = time.perf_counter()
    tau = numerics.kendall_tau_b(big, big + rng.normal(0, 1e-3, 65536))
    assert time.perf_counter() - t0 < 10.0
    assert tau is not None and 0.0 < tau <= 1.0


def test_ledger_hashing_is_cheap():
    """The <5% host-overhead acceptance at ledger scale: recording 5000
    values (3x the full 10-partner sweep with margin) must take well
    under a second of host time — the per-value cost is one small json
    dump + sha256."""
    led = numerics.ValueLedger("fp", {"reduction_mode": "default"})
    t0 = time.perf_counter()
    for i in range(5000):
        led.record((i % 31,), 0.5 + i * 1e-6)
    dt = time.perf_counter() - t0
    assert dt < 1.0, f"ledger hashing took {dt:.2f}s for 5000 records"


def test_diff_ledgers_zero_and_perturbed():
    base = numerics.ValueLedger("fp", {"reduction_mode": "default"})
    pert = numerics.ValueLedger("fp", {"reduction_mode": "default"})
    rng = np.random.default_rng(0)
    subsets = [tuple(sorted(s)) for s in
               [(0,), (1,), (2,), (0, 1), (0, 2), (1, 2), (0, 1, 2)]]
    for s in subsets:
        v = float(rng.uniform(0.5, 0.9))
        base.record(s, v)
        # deliberately perturb every coalition containing partner 1
        pert.record(s, np.nextafter(v, 2.0) if 1 in s else v)
    same = numerics.diff_ledgers(base, base)
    assert not same["drift"] and same["ulp"]["max"] == 0
    assert same["kendall_tau"] == 1.0

    d = numerics.diff_ledgers(base, pert)
    assert d["drift"] and d["ulp"]["max"] == 1
    drifted = {k for k, u in d["per_subset"].items() if u}
    expected = {numerics.ValueLedger.subset_key(s) for s in subsets
                if 1 in s}
    # drift localization: exactly the perturbed partner's coalitions moved
    assert drifted == expected

    other = numerics.ValueLedger("DIFFERENT", {})
    other.record((0,), 0.5)
    dd = numerics.diff_ledgers(base, other)
    assert not dd["same_fingerprint"] and not dd["comparable"]


# ---------------------------------------------------------------------------
# audit never perturbs results
# ---------------------------------------------------------------------------

def test_audit_on_off_bit_identity(monkeypatch):
    subsets = powerset_order(3)
    monkeypatch.delenv("MPLC_TPU_NUMERICS_AUDIT", raising=False)
    monkeypatch.delenv("MPLC_TPU_DEVICE_FENCE_RATE", raising=False)
    ref = CharacteristicEngine(_scenario(partners=3)).evaluate(subsets)

    monkeypatch.setenv("MPLC_TPU_NUMERICS_AUDIT", "1")
    monkeypatch.setenv("MPLC_TPU_DEVICE_FENCE_RATE", "1")  # fence (and
    # therefore audit-sample) every batch — the strictest setting
    eng = CharacteristicEngine(_scenario(partners=3))
    vals = eng.evaluate(subsets)
    np.testing.assert_array_equal(vals, ref)
    # the audit genuinely ran (multis batches were fenced) and localized
    # the default-order grouping divergence with real evidence
    assert eng.numerics_audits, "no audit ran despite fence rate 1"
    res = eng.numerics_audits[0]
    assert res.rounds > 0 and res.shard_counts


def test_audit_bit_identity_across_fault_ladder(monkeypatch, tmp_path):
    """transient retry + OOM cap-halving + the terminal CPU rung, with
    the audit sampling fenced batches throughout: v(S) must equal the
    fault-free, audit-free sweep bit for bit."""
    subsets = powerset_order(3)
    monkeypatch.delenv("MPLC_TPU_NUMERICS_AUDIT", raising=False)
    monkeypatch.delenv("MPLC_TPU_FAULT_PLAN", raising=False)
    ref = CharacteristicEngine(_scenario(partners=3)).evaluate(subsets)

    monkeypatch.setenv("MPLC_TPU_NUMERICS_AUDIT", "1")
    monkeypatch.setenv("MPLC_TPU_DEVICE_FENCE_RATE", "1")
    monkeypatch.setenv("MPLC_TPU_RETRY_BACKOFF_SEC", "0")
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN",
                       "transient@batch1,oom@batch2")
    eng = CharacteristicEngine(_scenario(partners=3))
    vals = eng.evaluate(subsets)
    np.testing.assert_array_equal(vals, ref)
    assert eng._cap_halvings >= 1  # the ladder really moved

    # exhaust the ladder into the CPU rung, audit still on
    monkeypatch.setenv("MPLC_TPU_MAX_CAP_HALVINGS", "1")
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", "oom@batch1,oom@batch2")
    eng2 = CharacteristicEngine(_scenario(partners=3))
    vals2 = eng2.evaluate(subsets)
    np.testing.assert_array_equal(vals2, ref)
    assert eng2._cpu_degraded


def test_ledger_never_perturbs_and_persists(monkeypatch, tmp_path):
    subsets = powerset_order(3)
    monkeypatch.delenv("MPLC_TPU_NUMERICS_LEDGER", raising=False)
    ref = CharacteristicEngine(_scenario(partners=3)).evaluate(subsets)
    path = tmp_path / "ledger.json"
    monkeypatch.setenv("MPLC_TPU_NUMERICS_LEDGER", str(path))
    eng = CharacteristicEngine(_scenario(partners=3))
    vals = eng.evaluate(subsets)
    np.testing.assert_array_equal(vals, ref)
    led = numerics.ValueLedger.load(str(path))
    assert len(led.entries) == len(subsets)
    # the recorded bits ARE the served values
    for s in subsets:
        bits = led.entries[numerics.ValueLedger.subset_key(s)]["value_bits"]
        assert numerics.bits_to_float(bits) == eng.charac_fct_values[s]


# ---------------------------------------------------------------------------
# deterministic-reduce equality + audit verification of the pinned order
# ---------------------------------------------------------------------------

def test_deterministic_reduce_1_vs_n_devices(monkeypatch, tmp_path):
    """The retired-xfail contract at engine level: deterministic part=1
    (unsharded reference) == part=2 == part=4, bit for bit, through the
    full evaluate() stack (memo, buckets, sliced singles) — and the
    value ledgers of the different TOPOLOGIES drift-diff to zero (the
    cross-topology run of the acceptance's same-seed zero-drift
    contract, via the real scripts/drift_diff.py entry point)."""
    subsets = powerset_order(4)
    monkeypatch.setenv("MPLC_TPU_DETERMINISTIC_REDUCE", "1")
    monkeypatch.setenv("MPLC_TPU_NUMERICS_LEDGER",
                       str(tmp_path / "led1.json"))
    monkeypatch.delenv("MPLC_TPU_PARTNER_SHARDS", raising=False)
    ref = CharacteristicEngine(_scenario()).evaluate(subsets)
    for shards in ("2", "4"):
        monkeypatch.setenv("MPLC_TPU_PARTNER_SHARDS", shards)
        monkeypatch.setenv("MPLC_TPU_NUMERICS_LEDGER",
                           str(tmp_path / f"led{shards}.json"))
        vals = CharacteristicEngine(_scenario()).evaluate(subsets)
        np.testing.assert_array_equal(vals, ref)
        assert drift_diff.main([str(tmp_path / "led1.json"),
                                str(tmp_path / f"led{shards}.json"),
                                "--gate"]) == 0


def test_hoisted_streams_respect_resumed_epochs(monkeypatch):
    """The hoisted deterministic streams must follow the SAME rule as
    the in-program generation for a chunk resumed at epoch e > 0 (the
    PVRL pattern: repeated n_epochs=1 chunks on a live state): chunk
    rng folded by POSITION, then by state.epoch — not by position
    twice. A generator that assumed epoch == position would hand a
    resumed chunk epoch-0 permutations."""
    import jax
    import jax.numpy as jnp

    from mplc_tpu.models import TITANIC_LOGREG
    from mplc_tpu.mpl.engine import MplTrainer, TrainConfig

    monkeypatch.setenv("MPLC_TPU_DETERMINISTIC_REDUCE", "1")
    cfg = TrainConfig(approach="fedavg", epoch_count=4, minibatch_count=2,
                      gradient_updates_per_pass=2, is_early_stopping=False,
                      record_partner_val=False, record_val_history=False)
    tr = MplTrainer(TITANIC_LOGREG, cfg)
    assert tr._det_hoist_streams()
    rng = jax.random.PRNGKey(3)
    mask = jnp.ones((4, 16), jnp.float32)
    for e in (0, 2):
        perms, keys = tr.gen_epoch_streams(rng, mask,
                                           jnp.int32(e), n_epochs=1)
        # the in-program rule for chunk position 0 at state.epoch == e:
        re = jax.random.fold_in(jax.random.fold_in(rng, 0), e)
        want_perms = tr._epoch_perms(jax.random.fold_in(re, 0), mask)
        np.testing.assert_array_equal(np.asarray(perms[0]),
                                      np.asarray(want_perms))
        rng_mb = jax.random.fold_in(jax.random.fold_in(re, 1), 1)
        want_keys = jax.vmap(lambda p: jax.random.fold_in(rng_mb, p))(
            jnp.arange(4, dtype=jnp.int32))
        np.testing.assert_array_equal(np.asarray(keys[0, 1]),
                                      np.asarray(want_keys))
    # and the e=2 streams genuinely differ from e=0's (the old bug
    # handed every resumed chunk the epoch-0 streams)
    p0, _ = tr.gen_epoch_streams(rng, mask, jnp.int32(0), n_epochs=1)
    p2, _ = tr.gen_epoch_streams(rng, mask, jnp.int32(2), n_epochs=1)
    assert not np.array_equal(np.asarray(p0), np.asarray(p2))


def test_deterministic_reduce_is_fingerprinted(monkeypatch, tmp_path):
    """A cache written under the default reduction describes a different
    game than a deterministic-mode engine computes — loading it must
    refuse with the fingerprint error, not silently mix orders."""
    monkeypatch.delenv("MPLC_TPU_DETERMINISTIC_REDUCE", raising=False)
    eng = CharacteristicEngine(_scenario(partners=3))
    eng.evaluate(powerset_order(3))
    path = tmp_path / "cache.json"
    eng.save_cache(path)
    monkeypatch.setenv("MPLC_TPU_DETERMINISTIC_REDUCE", "1")
    det = CharacteristicEngine(_scenario(partners=3))
    with pytest.raises(ValueError, match="deterministic_reduce"):
        det.load_cache(path)


def test_audit_verifies_pinned_order_under_det(monkeypatch):
    """Under deterministic-reduce the audit must find ZERO executed-order
    divergence at ANY shard count — the executed fold IS the linear
    reference order — while its hypothetical grouping table still
    quantifies what a psum order would have done (the evidence value).
    A default-mode 2-D engine, by contrast, EXECUTES the grouped order:
    the audit localizes a first divergent (round, leaf, shards) with
    nonzero ulp — the root-cause evidence that retired the xfails."""
    monkeypatch.setenv("MPLC_TPU_DETERMINISTIC_REDUCE", "1")
    monkeypatch.setenv("MPLC_TPU_PARTNER_SHARDS", "2")
    eng = CharacteristicEngine(_scenario())
    res = numerics.audit_coalition(eng, (0, 1, 2, 3))
    assert res is not None
    assert res.executed_shards is None  # det executes the linear order
    assert res.first_divergence is None and res.max_ulp == 0
    # the hypothetical table still shows the order sensitivity det pins
    assert max(res.ulp_by_shards.values()) > 0

    monkeypatch.delenv("MPLC_TPU_DETERMINISTIC_REDUCE", raising=False)
    deng = CharacteristicEngine(_scenario())
    assert deng._pipe2d is not None and deng._pipe2d.part_shards == 2
    dres = numerics.audit_coalition(deng, (0, 1, 2, 3))
    assert dres is not None and dres.executed_shards == 2
    assert dres.first_divergence is not None and dres.max_ulp > 0
    r, leaf, shards = dres.first_divergence
    assert 0 <= r < dres.rounds and shards == 2
    assert dres.partials_at_divergence is not None


def test_audit_drift_dump_rides_flight_recorder(monkeypatch, tmp_path):
    """A localized executed-order divergence must land a postmortem
    through obs/flight.py carrying the divergent leaf and per-device
    partials (the conftest fixture routes dumps into tmp)."""
    monkeypatch.delenv("MPLC_TPU_DETERMINISTIC_REDUCE", raising=False)
    monkeypatch.setenv("MPLC_TPU_PARTNER_SHARDS", "2")
    eng = CharacteristicEngine(_scenario())
    res = numerics.audit_coalition(eng, (0, 1, 2, 3))
    assert res is not None and res.first_divergence is not None
    import os
    flight_dir = os.environ["MPLC_TPU_FLIGHT_RECORDER_DIR"]
    dumps = [p for p in Path(flight_dir).glob("mplc_flight_numerics_drift_*")]
    assert dumps, "numerics.drift produced no flight-recorder postmortem"
    doc = json.loads(dumps[-1].read_text())
    assert doc["extra"]["divergent_leaf"] == res.first_divergence[1]
    assert doc["extra"]["per_device_partials"] is not None


# ---------------------------------------------------------------------------
# drift_diff / bench_diff tooling
# ---------------------------------------------------------------------------

def _mini_ledgers(tmp_path, perturb: bool):
    a = numerics.ValueLedger("fpX", {"reduction_mode": "default"},
                             path=str(tmp_path / "a.json"))
    b = numerics.ValueLedger("fpX", {"reduction_mode": "default"},
                             path=str(tmp_path / "b.json"))
    for i, s in enumerate([(0,), (1,), (0, 1)]):
        v = 0.6 + i * 0.05
        a.record(s, v)
        b.record(s, np.nextafter(v, 1e9) if perturb and i == 1 else v)
    a.save()
    b.save()
    return str(tmp_path / "a.json"), str(tmp_path / "b.json")


def test_drift_diff_same_seed_zero(tmp_path, capsys):
    pa, pb = _mini_ledgers(tmp_path, perturb=False)
    assert drift_diff.main([pa, pb, "--gate"]) == 0
    out = capsys.readouterr().out
    assert "ZERO DRIFT" in out


def test_drift_diff_gates_perturbation(tmp_path, capsys):
    pa, pb = _mini_ledgers(tmp_path, perturb=True)
    assert drift_diff.main([pa, pb, "--gate"]) == 1
    assert "DRIFT DETECTED" in capsys.readouterr().out


def test_drift_diff_refuses_fingerprint_mismatch(tmp_path):
    pa, _ = _mini_ledgers(tmp_path, perturb=False)
    other = numerics.ValueLedger("OTHER", {}, path=str(tmp_path / "o.json"))
    other.record((0,), 0.5)
    other.save()
    assert drift_diff.main([pa, str(tmp_path / "o.json")]) == 2


def _sidecar(values: dict, fingerprint="fpX") -> dict:
    return {"wallclock_s": 10.0, "source": "fresh",
            "report": {"wallclock": {"evaluate_s": 9.0}},
            "numerics": {"engine_fingerprint": fingerprint,
                         "reduction_mode": "deterministic",
                         "values": {k: numerics.float_bits(v)
                                    for k, v in values.items()}}}


def test_bench_diff_numerics_gate_flags_perturbation():
    base = {"0x1": 0.7, "0x2": 0.72, "0x3": 0.8}
    res = bench_diff.diff_sidecars(_sidecar(base), _sidecar(base), 0.10)
    assert not res["regressions"]
    rows = {r["row"]: r for r in res["rows"]}
    assert rows["numerics.max_ulp"]["new"] == 0
    assert rows["numerics.rank_tau"]["new"] == 1.0

    pert = dict(base, **{"0x2": float(np.nextafter(0.72, 2.0))})
    res = bench_diff.diff_sidecars(_sidecar(base), _sidecar(pert), 0.10)
    assert any(r["row"] == "numerics.max_ulp" and r["regressed"]
               for r in res["regressions"])


def test_bench_diff_numerics_skips_different_games():
    base = {"0x1": 0.7}
    res = bench_diff.diff_sidecars(_sidecar(base),
                                   _sidecar(base, fingerprint="OTHER"),
                                   0.10)
    assert not any(r["row"].startswith("numerics") for r in res["rows"])
    assert any("different games" in n for n in res["notes"])


def test_bench_diff_schema_compat_pre_numerics_sidecars():
    """A sidecar that predates the numerics block (every r1-r5 artifact)
    must diff cleanly: no numerics rows, no crash, other rows compared."""
    old = {"wallclock_s": 10.0, "source": "fresh",
           "report": {"wallclock": {"evaluate_s": 9.0, "compile_s": 1.0,
                                    "prep_s": 0.1, "dispatch_s": 0.5,
                                    "harvest_s": 0.2}}}
    new = dict(old, numerics={"engine_fingerprint": "fpX",
                              "values": {"0x1": numerics.float_bits(0.7)}})
    res = bench_diff.diff_sidecars(old, new, 0.10)
    assert not any(r["row"].startswith("numerics") for r in res["rows"])
    assert res["compared_rows"] > 0
    assert not res["regressions"]


def test_bench_diff_dir_mode_exit2_only_when_nothing_comparable(tmp_path):
    """Dir mode: pairs that merely SKIP newer rows still gate the rest
    (exit 0), while pairs sharing NO rows at all exit 2 — a gate that
    compared nothing must not read green."""
    old_dir, new_dir = tmp_path / "old", tmp_path / "new"
    old_dir.mkdir()
    new_dir.mkdir()
    doc = {"wallclock_s": 10.0, "source": "fresh",
           "report": {"wallclock": {"evaluate_s": 9.0}}}
    (old_dir / "telemetry_config1.json").write_text(json.dumps(doc))
    (new_dir / "telemetry_config1.json").write_text(json.dumps(doc))
    assert bench_diff.main([str(old_dir), str(new_dir)]) == 0

    # schema-disjoint pair: nothing comparable anywhere -> exit 2
    (old_dir / "telemetry_config1.json").write_text(json.dumps(
        {"something_else": 1}))
    assert bench_diff.main([str(old_dir), str(new_dir)]) == 2


def test_report_numerics_row_formats(monkeypatch, tmp_path):
    """sweep_report + format_report carry the numerics row when the
    stream has audit/ledger events — and old record streams keep the
    exact old schema (no row)."""
    from mplc_tpu.obs import trace as obs_trace
    from mplc_tpu.obs.report import format_report, sweep_report

    with obs_trace.collect() as rec:
        obs_trace.event("numerics.audit", subset="0xf", rounds=4,
                        shard_counts=[2], max_ulp=32, first_round=0,
                        first_leaf="d1/b", reduction_mode="default",
                        divergent_elements=3)
        obs_trace.event("numerics.drift", subset="0xf", round=0,
                        leaf="d1/b", shards=2, max_ulp=32)
        obs_trace.event("numerics.ledger", path="x.json", entries=15,
                        reduction_mode="default")
    rep = sweep_report(rec)
    nm = rep["numerics"]
    assert nm["audits"] == 1 and nm["drift_events"] == 1
    assert nm["max_ulp"] == 32 and nm["ledger_entries"] == 15
    txt = format_report(rep)
    assert "numerics" in txt and "max_ulp=32" in txt

    assert "numerics" not in sweep_report([])
