"""Overload robustness: priority scheduling, the SLO-driven admission
governor, load shedding, the worker pool, and the /healthz//varz
overload surfaces (mplc_tpu/service/admission.py + scheduler.py).

Governing contracts, asserted throughout:

  - WEIGHTED, NOT STARVED: tier t gets ~(t+1) quanta per tier-0 quantum
    (stride scheduling), FIFO within a tier; a single-tier service
    schedules exactly like the PR-9 deque.
  - SHED, NEVER LOST: when queue-wait p99 crosses the threshold the
    governor defers then sheds lowest-tier never-started jobs with a
    classified, journaled `JobShed` carrying a `retry_after_sec` hint —
    counted separately from rejected/cancelled/quarantined.
  - EXPIRED-WHILE-QUEUED is a deadline miss, not a latency datum: one
    `service.deadline_misses` beat, no queue-wait/ttfv SLO sample.
"""

import os
import time

import numpy as np
import pytest

from mplc_tpu import faults
from mplc_tpu.contrib.engine import CharacteristicEngine
from mplc_tpu.contrib.shapley import powerset_order
from mplc_tpu.obs import metrics, trace
from mplc_tpu.service import (AdmissionController, JobShed,
                              ServiceOverloaded, SweepJob, SweepService,
                              TierQueue)

P = 3
SUBSETS = powerset_order(P)

_KNOBS = ("MPLC_TPU_SERVICE_FAULT_PLAN", "MPLC_TPU_SERVICE_MAX_PENDING",
          "MPLC_TPU_SERVICE_SLICE", "MPLC_TPU_SERVICE_WORKERS",
          "MPLC_TPU_SERVICE_PRIORITY_DEFAULT",
          "MPLC_TPU_SERVICE_SHED_P99_SEC", "MPLC_TPU_FAULT_PLAN",
          "MPLC_TPU_MAX_RETRIES", "MPLC_TPU_SEED_ENSEMBLE",
          "MPLC_TPU_PARTNER_FAULT_PLAN")


@pytest.fixture(autouse=True)
def _env(monkeypatch):
    for k in _KNOBS:
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("MPLC_TPU_RETRY_BACKOFF_SEC", "0")
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "1")
    metrics.reset()
    yield
    metrics.reset()


def scenario(seed):
    from helpers import build_scenario
    return build_scenario(partners_count=P, dataset_name="titanic",
                          epoch_count=2, gradient_updates_per_pass_count=2,
                          seed=seed)


_REF = {}


def solo_values(seed):
    if seed not in _REF:
        _REF[seed] = CharacteristicEngine(scenario(seed)).evaluate(SUBSETS)
    return _REF[seed]


def values_of(job):
    return np.array([job.values[s] for s in SUBSETS])


def _counter(name):
    return metrics.snapshot()["counters"].get(name, 0)


class _FakeJob:
    """Queue-unit stand-in: only priority / first_quantum_at matter."""

    def __init__(self, name, priority=0, started=False):
        self.name = name
        self.priority = priority
        self.first_quantum_at = 0.0 if started else None
        self.submitted_at = time.monotonic()

    def __repr__(self):
        return self.name


# -- TierQueue ----------------------------------------------------------------

def test_tier_queue_single_tier_is_fifo():
    q = TierQueue()
    jobs = [_FakeJob(f"j{i}") for i in range(4)]
    for j in jobs:
        q.push(j)
    assert [q.pop() for _ in range(4)] == jobs
    assert q.pop() is None


def test_tier_queue_stride_weights_quanta_by_tier():
    """Tier 1 (weight 2) gets two quanta per tier-0 (weight 1) quantum;
    neither tier ever starves."""
    q = TierQueue()
    lo, hi = _FakeJob("lo", 0), _FakeJob("hi", 1)
    order = []
    for _ in range(9):
        j = q.pop() if len(q) else None
        if j is None:
            q.push(lo), q.push(hi)
            continue
        order.append(j.name)
        q.push(j)  # round-robin re-queue, like the scheduler
    hi_n, lo_n = order.count("hi"), order.count("lo")
    assert lo_n >= 2  # no starvation
    assert 1.5 <= hi_n / lo_n <= 2.5  # ~weight ratio 2:1


def test_tier_queue_defer_lowest_skips_only_when_another_tier_queued():
    q = TierQueue()
    lo, hi = _FakeJob("lo", 0), _FakeJob("hi", 2)
    q.push(lo)
    # deferral with a single queued tier is a no-op, never a deadlock
    assert q.pop(defer_lowest=True) is lo
    q.push(lo), q.push(hi)
    assert q.pop(defer_lowest=True) is hi
    q.push(hi)
    assert q.pop(defer_lowest=True) is hi  # lo deferred while hi queued
    assert q.pop(defer_lowest=True) is lo  # hi drained -> lo runs again


def test_tier_queue_shed_candidates_newest_first_never_started_only():
    q = TierQueue()
    started = _FakeJob("started", 0, started=True)
    a, b, c = (_FakeJob(n, 0) for n in "abc")
    hi = _FakeJob("hi", 1)
    for j in (started, a, b, c, hi):
        q.push(j)
    victims = q.shed_candidates(2)
    # newest never-started from the LOWEST tier; the started job and the
    # higher tier are untouchable
    assert victims == [c, b]
    assert set(q.jobs()) == {started, a, hi}
    assert q.shed_candidates(0) == []


# -- AdmissionController ------------------------------------------------------

def test_controller_disabled_never_leaves_healthy():
    c = AdmissionController(0.0)
    for _ in range(3):
        assert c.evaluate([100.0, 200.0]) == "healthy"
    assert c.view()["state"] == "healthy"
    assert c.view()["enabled"] is False


def test_controller_escalates_defer_then_shed_and_recovers():
    c = AdmissionController(1.0, defer_dwell_sec=0.0)
    assert c.evaluate([0.1]) == "healthy"
    assert c.evaluate([5.0]) == "deferring"   # first breach: defer
    assert c.evaluate([5.0]) == "shedding"    # still over past dwell: shed
    assert c.evaluate([5.0]) == "shedding"
    assert c.evaluate([0.1]) == "healthy"     # windowed p99 recovered
    assert c.evaluate([5.0]) == "deferring"   # a new breach defers again


def test_controller_dwell_blocks_instant_escalation():
    """Deferral must get wall-clock time to relieve the p99 before jobs
    are destroyed — two scheduling decisions microseconds apart (a
    worker pool's reality) must NOT jump deferring -> shedding."""
    c = AdmissionController(1.0, defer_dwell_sec=0.05)
    assert c.evaluate([5.0]) == "deferring"
    assert c.evaluate([5.0]) == "deferring"   # within the dwell
    time.sleep(0.06)
    assert c.evaluate([5.0]) == "shedding"    # breach outlived the dwell


def test_controller_window_ages_out_a_spike():
    """A post-spike idle service must stop reporting breach-level p99
    even when nothing new is scheduled: stale samples are pruned by AGE,
    not only displaced by count."""
    c = AdmissionController(1.0, defer_dwell_sec=0.0)
    c._waits.append((time.monotonic() - 1e6, 50.0))  # ancient spike wait
    assert c.evaluate([]) == "healthy"
    assert len(c._waits) == 0  # pruned
    # no history: the hint is the retry floor (never 0.0 — a zero hint
    # licenses a hot resubmit loop against an idle-LOOKING service)
    assert c.retry_after_sec() == pytest.approx(c.retry_floor_sec)


def test_controller_sees_stuck_queue_through_live_ages():
    """No samples ever observed (nothing scheduled) — the live queued
    ages alone must trip the governor."""
    c = AdmissionController(1.0)
    assert c.evaluate([]) == "healthy"
    assert c.evaluate([2.0, 3.0]) == "deferring"


def test_controller_retry_after_is_windowed_p50():
    c = AdmissionController(1.0)
    # no history: the floor, not 0.0 (MPLC_TPU_SERVICE_RETRY_FLOOR_SEC)
    assert c.retry_after_sec() == pytest.approx(0.05)
    for w in (0.2, 0.4, 0.6):
        c.observe_queue_wait(w)
    assert c.retry_after_sec() == pytest.approx(0.4)


def test_controller_retry_floor_env_and_p50_dominance(monkeypatch):
    """The floor satellite: a sub-floor p50 is clamped UP to the floor,
    a real p50 above it passes through, and the env knob retunes it."""
    c = AdmissionController(1.0)
    for w in (0.001, 0.002, 0.003):
        c.observe_queue_wait(w)
    assert c.retry_after_sec() == pytest.approx(0.05)   # floored
    monkeypatch.setenv("MPLC_TPU_SERVICE_RETRY_FLOOR_SEC", "0.25")
    c2 = AdmissionController(1.0)
    assert c2.retry_floor_sec == pytest.approx(0.25)
    assert c2.retry_after_sec() == pytest.approx(0.25)
    for w in (0.6, 0.7, 0.8):
        c2.observe_queue_wait(w)
    assert c2.retry_after_sec() == pytest.approx(0.7)   # p50 wins


def test_controller_shed_quota_targets_half_the_bound():
    c = AdmissionController(1.0, defer_dwell_sec=0.0)
    c.evaluate([5.0])
    c.evaluate([5.0])
    assert c.state == "shedding"
    assert c.shed_quota(queued=10, max_pending=8) == 6  # down to 4
    assert c.shed_quota(queued=5, max_pending=8) == 1
    # at or below the half-bound target there is no backlog to cut:
    # the next job must RUN (and land a fresh wait sample), not die to
    # a stale-window breach
    assert c.shed_quota(queued=1, max_pending=8) == 0
    assert c.shed_quota(queued=0, max_pending=8) == 0
    c.evaluate([0.0])
    assert c.shed_quota(queued=10, max_pending=8) == 0  # healthy: none


# -- ServiceOverloaded carries retry_after_sec (satellite) --------------------

def test_overloaded_carries_retry_after_hint():
    svc = SweepService(start=False, max_pending=1, slice_coalitions=3)
    svc.submit(scenario(9), tenant="A")
    with pytest.raises(ServiceOverloaded) as ei:
        svc.submit(scenario(11), tenant="B")
    # no job ever scheduled: the hint is the retry FLOOR, never 0.0/None
    # (a zero hint turns every polite client into a hot resubmit loop)
    assert ei.value.retry_after_sec == pytest.approx(0.05)
    svc.run_until_idle()
    # with queue-wait history the hint is the live p50 (> 0) and is
    # stamped into the message too
    svc.submit(scenario(11), tenant="B")
    with pytest.raises(ServiceOverloaded) as ei:
        svc.submit(scenario(13), tenant="C")
    assert ei.value.retry_after_sec > 0.0
    assert "retry_after_sec" in str(ei.value)


# -- priority scheduling end-to-end -------------------------------------------

def test_higher_priority_job_gets_first_quantum_and_both_complete():
    ref_a, ref_b = solo_values(9), solo_values(11)
    svc = SweepService(start=False, slice_coalitions=2)
    lo = svc.submit(scenario(9), tenant="lo", priority=0)
    hi = svc.submit(scenario(11), tenant="hi", priority=3)
    svc.step()
    assert hi.first_quantum_at is not None  # weight 4 wins the tie
    assert lo.first_quantum_at is None
    svc.run_until_idle()
    assert lo.status == hi.status == "completed"
    np.testing.assert_array_equal(values_of(lo), ref_a)
    np.testing.assert_array_equal(values_of(hi), ref_b)


def test_priority_default_env_applies(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_SERVICE_PRIORITY_DEFAULT", "2")
    svc = SweepService(start=False)
    job = svc.submit(scenario(9), tenant="A")
    assert job.priority == 2
    explicit = svc.submit(scenario(11), tenant="B", priority=0)
    assert explicit.priority == 0
    with pytest.raises(ValueError, match="non-negative"):
        svc.submit(scenario(13), tenant="C", priority=-1)


# -- load shedding end-to-end -------------------------------------------------

def test_overload_sheds_lowest_tier_with_classified_jobshed(tmp_path):
    """The tentpole behavior: under a breached queue-wait SLO the
    governor sheds lowest-tier never-started jobs — classified JobShed
    (with retry_after_sec), journaled, counted in service.jobs_shed —
    and the surviving higher-tier jobs complete bit-identically."""
    ref_b = solo_values(11)
    path = tmp_path / "wal.jsonl"
    # max_pending=4 => shed target is 2 queued: the 3-deep backlog is
    # over target, so the breached governor has a quota to shed
    svc = SweepService(start=False, slice_coalitions=2, max_pending=4,
                       shed_p99_sec=1e-9, journal_path=path)
    lo1 = svc.submit(scenario(9), tenant="lo", priority=0, job_id="lo1")
    lo2 = svc.submit(scenario(9), tenant="lo", priority=0, job_id="lo2")
    hi = svc.submit(scenario(11), tenant="hi", priority=1, job_id="hi")
    time.sleep(0.002)  # any positive queued age breaches the 1ns SLO
    with trace.collect() as recs:
        svc.run_until_idle()
    assert hi.status == "completed"
    np.testing.assert_array_equal(values_of(hi), ref_b)
    shed = [j for j in (lo1, lo2) if j.status == "shed"]
    assert shed, "the breached governor shed no lowest-tier job"
    for job in shed:
        assert isinstance(job.error, JobShed)
        assert job.error.retry_after_sec >= 0.0
        with pytest.raises(JobShed, match="shed by overload"):
            job.result(1.0)
        # shed jobs never ran: no engine, no device buffers, no samples
        assert job.engine is None and job.first_quantum_at is None
    assert _counter("service.jobs_shed") == len(shed)
    assert _counter("service.jobs_cancelled") == 0
    assert _counter("service.jobs_quarantined") == 0
    assert [r for r in recs if r["name"] == "service.shed"]
    # journaled as its own record kind, visible after a restart
    svc.shutdown()
    svc2 = SweepService(journal_path=path, start=False)
    rec = {r["job_id"]: r for r in svc2.recovered_jobs()}
    assert any(rec[j.job_id]["shed"] for j in shed)
    svc2.shutdown()
    # and the report classifies them separately
    from mplc_tpu.obs import report
    rep = report.sweep_report(recs)
    assert rep["service"]["shed"] == len(shed)
    assert f"shed={len(shed)}" in report.format_report(rep)


def test_shed_disabled_by_default_no_governor_interference():
    """With MPLC_TPU_SERVICE_SHED_P99_SEC unset the governor never
    defers or sheds — PR-9 behavior exactly."""
    svc = SweepService(start=False, slice_coalitions=3)
    assert svc._admission.enabled is False
    jobs = [svc.submit(scenario(9), tenant=f"t{i}") for i in range(3)]
    time.sleep(0.002)
    svc.run_until_idle()
    assert all(j.status == "completed" for j in jobs)
    assert _counter("service.jobs_shed") == 0


# -- deadline expiry while still queued (satellite) ---------------------------

def test_deadline_expiry_while_queued_cancels_without_slo_samples():
    """A job whose deadline elapses before its FIRST quantum must cancel
    cleanly, beat service.deadline_misses exactly once, and record
    neither a queue_wait nor a ttfv sample — an expired wait is not a
    latency datum."""
    svc = SweepService(start=False, slice_coalitions=2)
    job = svc.submit(scenario(9), tenant="Q", deadline_sec=1000.0)
    job.submitted_at -= 10_000  # expired while queued
    svc.run_until_idle()
    assert job.status == "cancelled"
    assert job.engine is None
    assert job.first_quantum_at is None and job.first_value_at is None
    assert _counter("service.deadline_misses{tenant=Q}") == 1
    hists = metrics.snapshot()["histograms"]
    assert "service.queue_wait_sec{tenant=Q}" not in hists
    assert "service.time_to_first_value_sec{tenant=Q}" not in hists
    # and the service keeps serving afterwards
    ok = svc.submit(scenario(11), tenant="Q2")
    svc.run_until_idle()
    assert ok.status == "completed"


# -- worker pool --------------------------------------------------------------

def test_worker_pool_completes_tenants_bit_identically():
    ref_a, ref_b = solo_values(9), solo_values(11)
    svc = SweepService(start=True, workers=3, slice_coalitions=3)
    try:
        ja = svc.submit(scenario(9), tenant="A")
        jb = svc.submit(scenario(11), tenant="B")
        ja.result(timeout=300)
        jb.result(timeout=300)
    finally:
        svc.shutdown(drain=True, timeout=60)
    np.testing.assert_array_equal(values_of(ja), ref_a)
    np.testing.assert_array_equal(values_of(jb), ref_b)


def test_workers_env_knob_and_healthz_per_worker_block(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_SERVICE_WORKERS", "2")
    svc = SweepService(start=True)
    try:
        view = svc.health_view()
        assert view["healthy"] is True
        import jax
        n_dev = len(jax.local_devices())
        workers = [w for w in view["workers"] if w["worker"] != "inline"]
        assert len(workers) == 2
        for i, w in enumerate(sorted(workers, key=lambda w: w["worker"])):
            assert w["alive"] is True and w["stalled"] is False
            assert w["device_slot"] == i % n_dev  # round-robin pinning
        assert view["admission"]["state"] == "healthy"
        job = svc.submit(scenario(9), tenant="A")
        job.result(timeout=300)
    finally:
        svc.shutdown(drain=True, timeout=60)


def test_one_wedged_worker_flips_only_its_own_liveness():
    """The per-worker heartbeat contract: with a sibling actively
    beating, a stale worker with a running job marks ITSELF stalled but
    the service stays healthy; when EVERY busy slot is wedged the
    service flips unhealthy (the single-worker degenerate case is the
    PR-10 rule unchanged)."""
    svc = SweepService(start=False)
    try:
        from mplc_tpu.service import scheduler as sched
        w0 = sched._WorkerSlot(0)
        w1 = sched._WorkerSlot(1)
        svc._workers = [w0, w1]
        w0.running_job = _FakeJob("wedged")
        w0.running_job.job_id = "wedged"
        w0.heartbeat = time.monotonic() - (sched.STALL_HEALTHY_SEC + 1)
        w1.running_job = _FakeJob("fine")
        w1.running_job.job_id = "fine"
        w1.heartbeat = time.monotonic()
        view = svc.health_view()
        by_idx = {w["worker"]: w for w in view["workers"]}
        assert by_idx[0]["stalled"] is True
        assert by_idx[1]["stalled"] is False
        assert view["healthy"] is True      # a sibling is alive and well
        assert view["stalled"] is True      # ... but the wedge is visible
        w1.heartbeat = time.monotonic() - (sched.STALL_HEALTHY_SEC + 1)
        assert svc.health_view()["healthy"] is False  # all busy slots wedged
    finally:
        svc._workers = []
        svc.shutdown()


# -- /varz truncation (satellite) ---------------------------------------------

def test_varz_truncates_terminal_jobs_to_most_recent_100():
    svc = SweepService(start=False)
    try:
        # synthesize a load-gen run's worth of terminal jobs (real sweeps
        # would take minutes; the truncation logic only reads bookkeeping)
        for i in range(130):
            job = SweepJob(svc, f"t{i}", "tenant", None, "Shapley values",
                           None, i + 1)
            job.status = "completed"
            job._done.set()
            svc._jobs[job.job_id] = job
            svc._retire(job)
        live = SweepJob(svc, "live", "tenant", None, "Shapley values",
                        None, 999)
        svc._jobs["live"] = live
        view = svc.varz_view()
        terminal_rows = [k for k, v in view["jobs"].items()
                         if v["status"] == "completed"]
        assert len(terminal_rows) == svc.VARZ_TERMINAL_JOBS == 100
        # the most RECENT terminals survive; the oldest are truncated
        assert "t129" in view["jobs"] and "t29" not in view["jobs"]
        assert "live" in view["jobs"]  # non-terminal always listed
        assert view["terminal_jobs_total"] == 130
        assert view["terminal_jobs_truncated"] == 30
        assert view["jobs_total"] == 131
        assert view["admission"]["state"] == "healthy"
    finally:
        svc.shutdown()


# -- chaos plan grammar -------------------------------------------------------

def test_chaos_plan_grammar_and_validation():
    plan = faults.parse_service_fault_plan(
        "chaos@rate0.25:seed7,crash@job2:batch1")
    assert plan["chaos"] == {"rate": 0.25, "seed": 7}
    assert plan[2]["batch"] == {("dispatch", 1): ["crash"]}
    with pytest.warns(UserWarning, match="rate must be in"):
        assert "chaos" not in faults.parse_service_fault_plan(
            "chaos@rate1.5:seed7")
    with pytest.warns(UserWarning, match="duplicate chaos"):
        plan = faults.parse_service_fault_plan(
            "chaos@rate0.1:seed1,chaos@rate0.9:seed2")
    assert plan["chaos"] == {"rate": 0.1, "seed": 1}
    with pytest.warns(UserWarning, match="malformed"):
        faults.parse_service_fault_plan("chaos@rate0.1")


def test_chaos_draws_are_deterministic_in_seed_and_ordinal():
    cfg = {"rate": 0.5, "seed": 7}
    draws = [faults.chaos_entry(cfg, i) for i in range(1, 101)]
    again = [faults.chaos_entry(cfg, i) for i in range(1, 101)]
    assert draws == again  # replayable under any interleaving
    fired = [d for d in draws if d]
    assert 25 <= len(fired) <= 75  # ~rate 0.5
    # every fired entry is one crash/transient batch fault or one stall
    for d in fired:
        kinds = [k for ks in d["batch"].values() for k in ks]
        assert (kinds and set(kinds) <= {"crash", "transient"}) \
            or d["stall_sec"] > 0
        assert not d["reject"]
    assert faults.chaos_entry(None, 1) is None
    assert faults.chaos_entry({"rate": 0.0, "seed": 1}, 1) is None
    # a different seed reshuffles the draws
    other = [faults.chaos_entry({"rate": 0.5, "seed": 8}, i)
             for i in range(1, 101)]
    assert other != draws


def test_merge_service_entries_composes_explicit_and_chaos():
    explicit = {"batch": {("dispatch", 1): ["crash"]}, "reject": False,
                "stall_sec": 0.5}
    chaos = {"batch": {("dispatch", 1): ["transient"]}, "reject": False,
             "stall_sec": 0.1}
    merged = faults.merge_service_entries(explicit, chaos)
    assert merged["batch"][("dispatch", 1)] == ["crash", "transient"]
    assert merged["stall_sec"] == pytest.approx(0.6)
    assert faults.merge_service_entries(None, None) is None
    assert faults.merge_service_entries(explicit, None)["stall_sec"] == 0.5
