"""scripts/analyze_trace.py argument/error handling, on a synthetic
xplane-free path (the real ProfileData parse needs a device trace the
fast tier cannot produce; the selection logic and the CLI error contract
are the part a refactor silently breaks).
"""

import os
import sys
import time
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT / "scripts"))

import analyze_trace  # noqa: E402


def test_file_path_passes_through(tmp_path):
    pb = tmp_path / "direct.xplane.pb"
    pb.write_bytes(b"")
    assert analyze_trace.newest_xplane(str(pb)) == str(pb)


def test_newest_xplane_picks_latest_recursively(tmp_path):
    old = tmp_path / "a" / "one.xplane.pb"
    new = tmp_path / "b" / "deep" / "two.xplane.pb"
    for p in (old, new):
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_bytes(b"")
    t = time.time()
    os.utime(old, (t - 100, t - 100))
    os.utime(new, (t, t))
    assert analyze_trace.newest_xplane(str(tmp_path)) == str(new)


def test_empty_dir_is_a_clean_cli_error(tmp_path):
    with pytest.raises(SystemExit, match="no .*xplane.pb"):
        analyze_trace.newest_xplane(str(tmp_path))


def test_docstring_points_at_the_perfetto_exporter():
    """The satellite contract: this tool covers XLA xplane traces only;
    its docstring must direct span-level (MPLC_TPU_TRACE_FILE) users to
    scripts/trace_to_perfetto.py."""
    assert "trace_to_perfetto" in analyze_trace.__doc__
    assert "MPLC_TPU_TRACE_FILE" in analyze_trace.__doc__
