"""Bounded residency for the live tier (mplc_tpu/live/residency.py).

The contract under test:

1. **Eviction is a latency tier, not a correctness change.** For every
   live query method (exact, GTG-Shapley, SVARM), evict -> restore ->
   query is BIT-identical to the never-evicted answer: the WAL journals
   each round exactly (json repr round-trip), so the restored stack —
   and everything derived from it — is the same arrays.
2. **LRU under the cap.** With `max_resident` games resident, admitting
   one more evicts the least-recently-USED journaled game (touches
   reorder the queue); journal-less games are never evicted (their
   history only exists in RAM).
3. **Admission refusal carries a backoff hint.** When no victim is
   evictable, creating a new game raises `LiveResidencyFull` with a
   `retry_after_sec` hint (p50 of recent restore latencies), same shape
   as `ServiceOverloaded`; an ALREADY-resident game is never refused.
4. **Kill -> restart with a mixed population.** A fresh process (fresh
   LiveGames on the same WALs) answers identically whether the old
   game died resident or evicted — the stub's WAL is as good as RAM.
"""

import numpy as np
import pytest

import jax

from helpers import build_scenario, cluster_mlp_dataset
from mplc_tpu.live import (LiveGame, LiveGameFull, LiveResidencyFull,
                           residency)


def _scenario_3p(seed=3):
    return build_scenario(
        partners_count=3, amounts_per_partner=[0.2, 0.3, 0.5],
        dataset=cluster_mlp_dataset(n=240, seed=9, scale=1.0),
        epoch_count=2, minibatch_count=2, seed=seed)


def _synth_rounds(game, k, seed=0, scale=0.08):
    rng = np.random.default_rng(seed)
    P = game.engine.partners_count
    rounds = []
    for _ in range(k):
        deltas = jax.tree_util.tree_map(
            lambda l: rng.normal(0, scale, (P,) + l.shape).astype(l.dtype),
            game._init_params)
        w = rng.dirichlet(np.ones(P)).astype(np.float32)
        rounds.append((deltas, w))
    return rounds


@pytest.fixture(autouse=True)
def _isolated_residency():
    """Each test starts and ends with clean process-wide books (other
    test modules create games that would otherwise linger as entries)."""
    residency.reset()
    yield
    residency.reset()


@pytest.fixture(scope="module")
def scen3():
    return _scenario_3p()


# ---------------------------------------------------------------------------
# 1. evict -> restore -> query bit-identity, per method
# ---------------------------------------------------------------------------

def test_evict_restore_query_bit_identity_all_methods(scen3, tmp_path):
    game = LiveGame(scen3, journal_path=str(tmp_path / "wal.jsonl"))
    for deltas, w in _synth_rounds(game, 2, seed=41):
        game.append_round(deltas, w)
    gtg_kw = dict(sv_accuracy=1.0, min_iter=8, perm_batch=4)
    svarm_kw = dict(budget=64, block=16)
    before = {
        "exact": game.query("exact").scores,
        "GTG-Shapley": game.query("GTG-Shapley", **gtg_kw).scores,
        "SVARM": game.query("SVARM", **svarm_kw).scores,
    }
    stamp, rounds = game.round_stamp, game.rounds_resident

    assert game.evict() is True
    assert not game.resident
    assert game.rounds_resident == 0  # the stub holds no rounds
    # the query restores through the WAL, then answers bit-identically
    after_exact = game.query("exact")
    assert game.resident
    assert (game.round_stamp, game.rounds_resident) == (stamp, rounds)
    assert after_exact.scores.tobytes() == before["exact"].tobytes()
    for method, kw in (("GTG-Shapley", gtg_kw), ("SVARM", svarm_kw)):
        game.evict()
        r = game.query(method, **kw)
        assert r.scores.tobytes() == before[method].tobytes(), method
    assert residency.stats()["restores"] == 3
    assert game.last_restore_s > 0.0
    game.close()


def test_journal_less_game_is_unevictable(scen3):
    game = LiveGame(scen3)
    game.append_round(*_synth_rounds(game, 1, seed=42)[0])
    assert game.evict() is False  # nothing durable to restore from
    assert game.resident and game.rounds_resident == 1
    game.close()


def test_describe_reports_residency_without_restoring(scen3, tmp_path):
    game = LiveGame(scen3, journal_path=str(tmp_path / "wal.jsonl"))
    game.append_round(*_synth_rounds(game, 1, seed=43)[0])
    assert game.describe()["resident"] is True
    game.evict()
    d = game.describe()
    # an observability read must never trigger a WAL replay
    assert d["resident"] is False and not game.resident
    assert d["rounds_resident"] == 0
    game.close()


# ---------------------------------------------------------------------------
# 2. the LRU under a cap
# ---------------------------------------------------------------------------

def test_lru_evicts_coldest_journaled_game(scen3, tmp_path):
    residency.configure(2)
    g1 = LiveGame(scen3, tenant="t1", journal_path=str(tmp_path / "1.wal"))
    g2 = LiveGame(scen3, tenant="t2", journal_path=str(tmp_path / "2.wal"))
    for g, seed in ((g1, 1), (g2, 2)):
        g.append_round(*_synth_rounds(g, 1, seed=seed)[0])
    # touch g1 so g2 is now the least-recently-used
    g1.query("exact")
    g3 = LiveGame(scen3, tenant="t3", journal_path=str(tmp_path / "3.wal"))
    assert g3.resident and g1.resident and not g2.resident
    st = residency.stats()
    assert st["max_resident"] == 2
    assert st["resident"] == 2 and st["evicted"] == 1
    assert st["evictions"] == 1
    # touching the evicted game restores it, pushing out the new coldest
    g2.query("exact")
    assert g2.resident and not g1.resident
    assert residency.stats()["restores"] == 1
    for g in (g1, g2, g3):
        g.close()
    assert residency.stats()["resident"] == 0


def test_cap_refuses_new_games_with_retry_hint(scen3):
    residency.configure(1)
    g1 = LiveGame(scen3, tenant="pinned")  # journal-less: unevictable
    g1.append_round(*_synth_rounds(g1, 1, seed=44)[0])
    residency.note_restore(0.25)  # seed the hint window
    with pytest.raises(LiveResidencyFull,
                       match="MPLC_TPU_LIVE_MAX_RESIDENT") as ei:
        LiveGame(scen3, tenant="newcomer")
    assert ei.value.retry_after_sec == pytest.approx(0.25)
    assert isinstance(ei.value, LiveGameFull)  # one catch for both caps
    # the resident game is never refused: the cap throttles growth only
    g1.append_round(*_synth_rounds(g1, 1, seed=45)[0])
    assert g1.query("exact").rounds == 2
    g1.close()


def test_live_game_full_carries_retry_after_sec(scen3):
    game = LiveGame(scen3, max_rounds=1)
    rounds = _synth_rounds(game, 2, seed=46)
    game.append_round(*rounds[0])
    with pytest.raises(LiveGameFull) as ei:
        game.append_round(*rounds[1])
    # the round-cap refusal rides the same backoff-hint shape as
    # ServiceOverloaded and LiveResidencyFull
    assert ei.value.retry_after_sec == 0.0
    game.close()


def test_retry_after_sec_is_nearest_rank_p50():
    for s in (0.4, 0.1, 0.2, 0.3):
        residency.note_restore(s)
    assert residency.retry_after_sec() == pytest.approx(0.2)
    assert residency.stats()["last_restore_s"] == pytest.approx(0.3)


# ---------------------------------------------------------------------------
# 4. kill -> restart over a mixed resident/evicted population
# ---------------------------------------------------------------------------

def test_kill_restart_with_mixed_resident_and_evicted_games(tmp_path):
    sc = _scenario_3p()
    wal_a = str(tmp_path / "a.wal")
    wal_b = str(tmp_path / "b.wal")
    ga = LiveGame(sc, tenant="a", journal_path=wal_a)
    gb = LiveGame(sc, tenant="b", journal_path=wal_b)
    for g, seed in ((ga, 47), (gb, 48)):
        for deltas, w in _synth_rounds(g, 2, seed=seed):
            g.append_round(deltas, w)
    ra = ga.query("exact")
    rb = gb.query("exact")
    ga.evict()  # the "kill" catches a at the stub, b resident
    ga.close()
    gb.close()

    residency.reset()
    sc2 = _scenario_3p()
    ga2 = LiveGame(sc2, tenant="a", journal_path=wal_a)
    gb2 = LiveGame(sc2, tenant="b", journal_path=wal_b)
    assert ga2.rounds_resident == 2 and gb2.rounds_resident == 2
    np.testing.assert_array_equal(ga2.query("exact").scores, ra.scores)
    np.testing.assert_array_equal(gb2.query("exact").scores, rb.scores)
    ga2.close()
    gb2.close()


def test_residency_cap_env_knob(scen3, tmp_path, monkeypatch):
    monkeypatch.setenv("MPLC_TPU_LIVE_MAX_RESIDENT", "1")
    assert residency.max_resident() == 1
    g1 = LiveGame(scen3, journal_path=str(tmp_path / "e1.wal"))
    g1.append_round(*_synth_rounds(g1, 1, seed=49)[0])
    g2 = LiveGame(scen3, journal_path=str(tmp_path / "e2.wal"))
    assert g2.resident and not g1.resident
    # configure() overrides the env read (the bench/test hook)
    residency.configure(0)
    assert residency.max_resident() == 0  # unbounded again
    g1.close()
    g2.close()
