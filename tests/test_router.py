"""The fleet router (mplc_tpu/service/router.py) and its satellites.

Governing invariants, asserted throughout:

  - FAILOVER BIT-IDENTITY: a job whose accepting shard is killed
    mid-run is resubmitted to a survivor seeded from the dead shard's
    journal, and its completed v(S) table is BIT-IDENTICAL to a solo
    fault-free run — the caller's handle keeps working across the swap.
  - STICKINESS: a tenant's jobs land on its pinned shard; the pin
    breaks only on shard death or sustained overload, exactly once per
    event, and every break is journaled with its reason.
  - CLASSIFIED EXHAUSTION: when the routing budget runs out the caller
    gets a `RoutedJobFailed` chaining the last shard error — never a
    silent drop, never an unbounded redirect loop.
  - SHED COORDINATION: a deferring/shedding shard is offered nothing
    new while a healthy sibling exists.

Plus the ISSUE 19 satellites: the authenticated submit path
(`tenant_token`), the `retry_after_sec` floor (test_admission.py),
stale-shard exclusion from `cluster_view` least-loaded hints, and the
BENCH_CONFIG=11 wiring.
"""

import json
import os
import time

import numpy as np
import pytest

from mplc_tpu import faults
from mplc_tpu.contrib.engine import CharacteristicEngine
from mplc_tpu.contrib.shapley import powerset_order
from mplc_tpu.obs import export as obs_export
from mplc_tpu.obs import metrics, trace
from mplc_tpu.parallel import fleet
from mplc_tpu.service import (FleetRouter, RoutedJobFailed,
                              ServiceAuthError, SweepJournal, SweepService)
from mplc_tpu.service.router import InProcShard, ShardServer

P = 3
SUBSETS = powerset_order(P)

_KNOBS = ("MPLC_TPU_SERVICE_FAULT_PLAN", "MPLC_TPU_SERVICE_MAX_PENDING",
          "MPLC_TPU_SERVICE_SLICE", "MPLC_TPU_SERVICE_RETRY_FLOOR_SEC",
          "MPLC_TPU_SERVICE_SHED_P99_SEC", "MPLC_TPU_ROUTER_BUDGET",
          "MPLC_TPU_ROUTER_BACKOFF_SEC", "MPLC_TPU_ROUTER_REPIN_OVERLOADS",
          "MPLC_TPU_ROUTER_FAULT_PLAN", "MPLC_TPU_ROUTER_SERVE",
          "MPLC_TPU_FLEET_STALE_SEC", "MPLC_TPU_FLEET_STATE_DIR",
          "MPLC_TPU_FLEET_SHARD_ID", "MPLC_TPU_METRICS_TOKEN",
          "MPLC_TPU_FAULT_PLAN", "MPLC_TPU_MAX_RETRIES",
          "MPLC_TPU_SEED_ENSEMBLE", "MPLC_TPU_PARTNER_FAULT_PLAN")


@pytest.fixture(autouse=True)
def _router_env(monkeypatch):
    for k in _KNOBS:
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("MPLC_TPU_RETRY_BACKOFF_SEC", "0")
    monkeypatch.setenv("MPLC_TPU_COALITIONS_PER_DEVICE", "1")
    metrics.reset()
    yield
    metrics.reset()


def scenario(seed):
    from helpers import build_scenario
    return build_scenario(partners_count=P, dataset_name="titanic",
                          epoch_count=2,
                          gradient_updates_per_pass_count=2, seed=seed)


_REF = {}


def solo_values(seed):
    if seed not in _REF:
        _REF[seed] = CharacteristicEngine(scenario(seed)).evaluate(SUBSETS)
    return _REF[seed]


def values_of(handle):
    vals = handle.values()
    return np.array([vals[s] for s in SUBSETS])


def _two_shard_router(tmp_path, slice_coalitions=2, **router_kw):
    s0 = SweepService(start=False, slice_coalitions=slice_coalitions,
                      journal_path=str(tmp_path / "s0.wal"))
    s1 = SweepService(start=False, slice_coalitions=slice_coalitions,
                      journal_path=str(tmp_path / "s1.wal"))
    r = FleetRouter(shards={"s0": s0, "s1": s1}, backoff_sec=0.0,
                    **router_kw)
    return r, s0, s1


# -- fault-plan grammar -------------------------------------------------------

def test_router_fault_plan_grammar():
    plan = faults.parse_router_fault_plan(
        "shardkill@shard1:sec5, shardkill@pid_a:sec0.5")
    assert plan == [
        {"kind": "shardkill", "shard": "pid_a", "at_sec": 0.5},
        {"kind": "shardkill", "shard": "shard1", "at_sec": 5.0}]
    # malformed entries are warn-and-dropped, never fatal
    with pytest.warns(UserWarning, match="malformed"):
        plan = faults.parse_router_fault_plan("bogus@x, shardkill@s:sec1")
    assert plan == [{"kind": "shardkill", "shard": "s", "at_sec": 1.0}]
    assert faults.parse_router_fault_plan("") == []
    assert faults.parse_router_fault_plan(None) == []


# -- failover ----------------------------------------------------------------

def test_midrun_failover_is_bit_identical_and_journal_seeded(tmp_path):
    """THE tentpole invariant: kill the accepting shard after one
    partial quantum — the survivor is seeded from the dead shard's WAL
    (recovered values > 0: nothing durably harvested retrains) and the
    final table is bit-identical to the solo fault-free run."""
    ref = solo_values(7)
    r, s0, s1 = _two_shard_router(tmp_path,
                                  journal_path=str(tmp_path / "rt.wal"))
    h = r.submit(scenario(7), tenant="t0")
    first = h.shard_id
    r.pump()                      # partial progress on the first shard
    assert not h.done
    r.kill_shard(first)
    assert h.failed_over
    assert h.shard_id != first
    r.run_until_idle(timeout=600)
    assert h.status == "completed"
    # the WAL-seeding proof: the survivor's engine was seeded from the
    # dead shard's journal, not recomputed from scratch
    assert h._inner.recovered_values >= 1
    np.testing.assert_array_equal(values_of(h), ref)
    assert r.stats["failovers"] == 1
    # the death broke the tenant's pin exactly once, journaled
    assert r.stats["repins"] == 1
    records, torn = SweepJournal.replay(str(tmp_path / "rt.wal"))
    repins = [rec for rec in records if rec.get("type") == "repin"]
    assert not torn and len(repins) == 1
    assert repins[0]["reason"] == "death" and repins[0]["from"] == first
    r.close()
    s0.shutdown(drain=False)
    s1.shutdown(drain=False)


def test_repin_once_per_death_even_with_multiple_victims(tmp_path):
    """One kill produces exactly one re-pin per tenant pinned to the
    corpse (not one per resubmitted job) and every victim completes
    bit-identically on a survivor."""
    ref7, ref8 = solo_values(7), solo_values(8)
    r, s0, s1 = _two_shard_router(tmp_path)
    ha = r.submit(scenario(7), tenant="A")
    hb = r.submit(scenario(8), tenant="B", job_id="b1")
    pins = dict(r._pins)
    r.pump()
    victim_shard = ha.shard_id
    repins_expected = len({t for t, sid in pins.items()
                           if sid == victim_shard})
    r.kill_shard(victim_shard)
    assert r.stats["repins"] == repins_expected
    r.run_until_idle(timeout=600)
    assert ha.status == "completed" and hb.status == "completed"
    np.testing.assert_array_equal(values_of(ha), ref7)
    np.testing.assert_array_equal(values_of(hb), ref8)
    r.close()
    s0.shutdown(drain=False)
    s1.shutdown(drain=False)


def test_all_shards_dead_is_classified_not_hung(tmp_path):
    """Killing EVERY shard leaves the in-flight job with a classified
    RoutedJobFailed on its handle — result() raises, nothing hangs."""
    r, s0, s1 = _two_shard_router(tmp_path)
    h = r.submit(scenario(7), tenant="t0")
    r.pump()
    r.kill_shard("s0")
    r.kill_shard("s1")
    assert h.done
    assert h.status == "failed"
    with pytest.raises(RoutedJobFailed):
        h.result(timeout=5)
    r.close()
    s0.shutdown(drain=False)
    s1.shutdown(drain=False)


# -- redirect + budget -------------------------------------------------------

def test_budget_exhaustion_is_classified(monkeypatch):
    """A single overloaded shard and budget=1: the submit fails
    synchronously with RoutedJobFailed chaining ServiceOverloaded —
    classified, counted, never silently dropped."""
    monkeypatch.setenv("MPLC_TPU_SERVICE_RETRY_FLOOR_SEC", "0")
    svc = SweepService(start=False, max_pending=1, slice_coalitions=1)
    svc.submit(scenario(7), tenant="filler")      # queue now full
    r = FleetRouter(shards={"only": svc}, budget=1, backoff_sec=0.0)
    with trace.collect() as recs:
        with pytest.raises(RoutedJobFailed) as ei:
            r.submit(scenario(8), tenant="t0")
    assert ei.value.attempts == 1
    assert "ServiceOverloaded" in type(ei.value.__cause__).__name__
    assert r.stats["budget_exhausted"] == 1
    names = [rec["name"] for rec in recs]
    assert "router.exhausted" in names
    r.close()
    svc.shutdown(drain=False)


def test_redirect_loop_terminates_on_budget(monkeypatch):
    """Two mutually-overloaded shards: the router bounces between them
    following redirects but the budget bounds the loop — RoutedJobFailed
    after exactly `budget` attempts, a redirect event per bounce."""
    monkeypatch.setenv("MPLC_TPU_SERVICE_RETRY_FLOOR_SEC", "0")
    s0 = SweepService(start=False, max_pending=1, slice_coalitions=1)
    s1 = SweepService(start=False, max_pending=1, slice_coalitions=1)
    s0.submit(scenario(7), tenant="filler")
    s1.submit(scenario(8), tenant="filler")
    r = FleetRouter(shards={"s0": s0, "s1": s1}, budget=4,
                    backoff_sec=0.0)
    with trace.collect() as recs:
        with pytest.raises(RoutedJobFailed) as ei:
            r.submit(scenario(9), tenant="t0")
    assert ei.value.attempts == 4
    redirects = [rec for rec in recs if rec["name"] == "router.redirect"]
    assert len(redirects) == 4
    assert r.stats["resubmits"] == 4
    r.close()
    s0.shutdown(drain=False)
    s1.shutdown(drain=False)


# -- shed coordination + stickiness ------------------------------------------

def test_deferring_shard_is_not_offered_new_work(tmp_path):
    """Cluster-wide shed coordination: a shard whose admission governor
    left `healthy` gets no new jobs while a healthy sibling exists —
    even when the degraded shard has the shallower queue."""
    s0 = SweepService(start=False, slice_coalitions=2,
                      shed_p99_sec=0.001)
    s1 = SweepService(start=False, slice_coalitions=2)
    r = FleetRouter(shards={"s0": s0, "s1": s1}, backoff_sec=0.0)
    # trip s0's governor with an ancient queued-age breach
    assert s0._admission.evaluate([10.0]) == "deferring"
    h = r.submit(scenario(7), tenant="t0")
    assert h.shard_id == "s1"
    r.run_until_idle(timeout=600)
    assert h.status == "completed"
    r.close()
    s0.shutdown(drain=False)
    s1.shutdown(drain=False)


def test_tenant_stickiness_overrides_least_loaded(tmp_path):
    """A pinned tenant keeps landing on its shard even when the other
    shard has the shallower queue; a different tenant load-balances."""
    r, s0, s1 = _two_shard_router(tmp_path)
    h1 = r.submit(scenario(7), tenant="sticky")
    pinned = h1.shard_id
    # the pinned shard now has queue depth 1, the other 0 — least
    # loaded would pick the other; the pin must win
    h2 = r.submit(scenario(8), tenant="sticky", job_id="st2")
    assert h2.shard_id == pinned
    other = r.submit(scenario(9), tenant="roamer")
    assert other.shard_id != pinned
    r.run_until_idle(timeout=600)
    assert all(h.status == "completed" for h in (h1, h2, other))
    r.close()
    s0.shutdown(drain=False)
    s1.shutdown(drain=False)


def test_sustained_overload_breaks_pin_deliberately(tmp_path, monkeypatch):
    """`repin_overloads` consecutive overloads from the pinned shard
    break the pin deliberately (reason=overload, journaled); acceptance
    on the redirect target establishes the new pin."""
    monkeypatch.setenv("MPLC_TPU_SERVICE_RETRY_FLOOR_SEC", "0")
    s0 = SweepService(start=False, max_pending=1, slice_coalitions=1)
    s1 = SweepService(start=False, slice_coalitions=1)
    r = FleetRouter(shards={"s0": s0, "s1": s1}, budget=8,
                    backoff_sec=0.0, repin_overloads=1,
                    journal_path=str(tmp_path / "rt.wal"))
    # pin the tenant to s0, then fill s0 so its next submit overloads
    r._pins["t0"] = "s0"
    s0.submit(scenario(7), tenant="filler")
    h = r.submit(scenario(8), tenant="t0")   # overload -> break -> s1
    assert h.shard_id == "s1"
    assert r.stats["repins"] == 1
    assert r._pins["t0"] == "s1"             # stickiness follows work
    records, torn = SweepJournal.replay(str(tmp_path / "rt.wal"))
    repins = [rec for rec in records if rec.get("type") == "repin"]
    assert not torn and len(repins) == 1
    assert repins[0]["reason"] == "overload"
    assert repins[0]["from"] == "s0" and repins[0]["tenant"] == "t0"
    r.run_until_idle(timeout=600)
    assert h.status == "completed"
    r.close()
    s0.shutdown(drain=False)
    s1.shutdown(drain=False)


# -- authenticated submit path (satellite) -----------------------------------

def test_submit_auth_master_and_tenant_token(monkeypatch):
    monkeypatch.setenv("MPLC_TPU_METRICS_TOKEN", "hunter2")
    svc = SweepService(start=False, slice_coalitions=4)
    # the in-process embedder stays trusted: no credential, no check
    ok0 = svc.submit(scenario(7), tenant="A")
    # the master token and the tenant-scoped HMAC both pass
    ok1 = svc.submit(scenario(8), tenant="A", credential="hunter2",
                     job_id="j1")
    ok2 = svc.submit(scenario(9), tenant="B",
                     credential=obs_export.tenant_token("hunter2", "B"),
                     job_id="j2")
    # a wrong credential (or another tenant's token) fails SYNCHRONOUSLY
    with pytest.raises(ServiceAuthError):
        svc.submit(scenario(10), tenant="B", credential="wrong")
    with pytest.raises(ServiceAuthError):
        svc.submit(scenario(10), tenant="B",
                   credential=obs_export.tenant_token("hunter2", "A"))
    assert metrics.snapshot()["counters"].get(
        "service.auth_rejected") == 2
    svc.run_until_idle()
    assert all(j.status == "completed" for j in (ok0, ok1, ok2))
    svc.shutdown(drain=False)


def test_wire_submit_requires_credential_when_token_set(monkeypatch):
    """The trust model's wire half: ShardServer (the HTTP surface)
    REQUIRES a credential when the token is set — the in-process
    trusted-embedder bypass must not extend over the network."""
    monkeypatch.setenv("MPLC_TPU_METRICS_TOKEN", "hunter2")
    svc = SweepService(start=False, slice_coalitions=4)
    srv = ShardServer(svc, lambda spec: scenario(7))
    with pytest.raises(ServiceAuthError):
        srv.handle("submit", {"tenant": "A"})
    ack = srv.handle("submit", {"tenant": "A", "credential": "hunter2"})
    assert ack["tenant"] == "A"
    svc.run_until_idle()
    srv.close()
    svc.shutdown(drain=False)


def test_wire_submit_rejected_credential_mutates_nothing(monkeypatch):
    """An INVALID credential must be rejected BEFORE any state
    mutation: the 403 installs no recover values into the service's
    recovered table and never even builds the scenario — a wire
    attacker cannot pre-seed a future failover's v(S) values under an
    arbitrary job_id on its way to the auth error."""
    monkeypatch.setenv("MPLC_TPU_METRICS_TOKEN", "hunter2")
    svc = SweepService(start=False, slice_coalitions=4)
    built = []
    srv = ShardServer(svc, lambda spec: built.append(spec) or scenario(7))
    evil = {"tenant": "A", "credential": "wrong", "job_id": "poisoned",
            "recover": {"partners_count": P,
                        "values": [[[0], 666.0], [[1], 666.0]]}}
    with pytest.raises(ServiceAuthError):
        srv.handle("submit", evil)
    # another tenant's valid token must not authenticate tenant A either
    evil["credential"] = obs_export.tenant_token("hunter2", "B")
    with pytest.raises(ServiceAuthError):
        srv.handle("submit", evil)
    assert "poisoned" not in svc._recovered   # nothing was installed
    assert built == []                        # no scenario work spent
    # a legitimate later adoption of the same job id starts clean
    svc.adopt_recovered("poisoned", tenant="A", partners_count=P,
                        values={(0,): 0.25})
    assert svc._recovered["poisoned"]["values"] == {(0,): 0.25}
    srv.close()
    svc.shutdown(drain=False)


def test_adopt_recovered_refuses_differing_seed():
    """Re-adoption is idempotent ONLY for an identical seed; a
    differing seed for a known job raises instead of being silently
    swallowed — silent divergence here would break the bit-identity
    failover contract."""
    svc = SweepService(start=False, slice_coalitions=4)
    shard = InProcShard("s", svc)
    req = {"scenario": scenario(7), "method": "Shapley values",
           "tenant": "t0", "job_id": "jD", "deadline_sec": None,
           "priority": None, "credential": None}
    shard._adopt({"values": {(1,): 0.5}, "partners_count": P}, req)
    # identical seed: no-op
    shard._adopt({"values": {(1,): 0.5}, "partners_count": P}, req)
    # differing seed: refused loudly
    with pytest.raises(ValueError, match="differs"):
        shard._adopt({"values": {(1,): 0.75}, "partners_count": P}, req)
    with pytest.raises(ValueError, match="differs"):
        shard._adopt({"values": {(1,): 0.5}, "partners_count": P + 1},
                     req)
    assert svc._recovered["jD"]["values"] == {(1,): 0.5}
    svc.shutdown(drain=False)


# -- cluster_view staleness (satellite) --------------------------------------

def test_cluster_view_excludes_stale_and_closed_from_least_loaded(tmp_path):
    """A dead shard's last published queue depth was probably 0 —
    exactly the bait a naive least-loaded rule would take. Stale and
    closed shards are flagged, kept as evidence, and never recommended."""
    d = str(tmp_path)
    fleet.publish_shard_state(d, "dead", {"queue_depth": 0})
    fleet.publish_shard_state(d, "closing", {"queue_depth": 0,
                                             "closed": True})
    fleet.publish_shard_state(d, "busy", {"queue_depth": 9})
    # age the dead shard's state file past the window
    path = os.path.join(d, "shard_dead.json")
    with open(path) as f:
        doc = json.load(f)
    doc["ts"] = time.time() - 100.0
    with open(path, "w") as f:
        json.dump(doc, f)
    view = fleet.cluster_view(d, stale_sec=30.0)
    assert view["shards"]["dead"]["stale"]
    assert view["live_shards"] == 1 and view["stale_shards"] == 1
    assert view["least_loaded"] == "busy"
    # the env knob retunes the window (satellite: MPLC_TPU_FLEET_STALE_SEC)
    os.environ["MPLC_TPU_FLEET_STALE_SEC"] = "1000"
    try:
        view = fleet.cluster_view(d)
        assert not view["shards"]["dead"]["stale"]
        assert view["least_loaded"] == "dead"
    finally:
        del os.environ["MPLC_TPU_FLEET_STALE_SEC"]


def test_shutdown_publishes_closed_state_immediately(tmp_path, monkeypatch):
    """A shutting-down shard publishes `closed: true` so routers stop
    offering it work — cluster_view never recommends it again."""
    monkeypatch.setenv("MPLC_TPU_FLEET_STATE_DIR", str(tmp_path))
    monkeypatch.setenv("MPLC_TPU_FLEET_SHARD_ID", "sX")
    svc = SweepService(start=False, slice_coalitions=4)
    svc.shutdown(drain=False)
    view = fleet.cluster_view(str(tmp_path))
    assert view["shards"]["sX"]["closed"]
    assert view["least_loaded"] is None


# -- observability ------------------------------------------------------------

def test_router_report_row_and_varz(tmp_path):
    from mplc_tpu.obs.report import format_report, sweep_report
    ref = solo_values(7)
    r, s0, s1 = _two_shard_router(tmp_path)
    with trace.collect() as recs:
        h = r.submit(scenario(7), tenant="t0")
        r.pump()
        r.kill_shard(h.shard_id)
        r.run_until_idle(timeout=600)
    np.testing.assert_array_equal(values_of(h), ref)
    rep = sweep_report(recs)
    row = rep["router"]
    assert row["routed"] == 1 and row["failovers"] == 1
    assert row["repins"] == 1 and row["failover_jobs"] == 1
    assert row["route_s"]["p50"] is not None
    assert "  router " in format_report(rep)
    vz = r.varz_view()
    assert vz["jobs"][h.job_id]["failed_over"]
    assert set(vz["table"]) == {"s0", "s1"}
    counters = metrics.snapshot()["counters"]
    assert counters.get("router.jobs_routed") == 1
    assert counters.get("router.failovers") == 1
    r.close()
    s0.shutdown(drain=False)
    s1.shutdown(drain=False)


def test_router_fault_plan_drives_kill(tmp_path):
    """The chaos grammar end-to-end: a shardkill entry at sec0 fires on
    the first refresh, kills the named shard (`shard0` = insertion
    order), and the job completes bit-identically elsewhere."""
    ref = solo_values(7)
    s0 = SweepService(start=False, slice_coalitions=2)
    s1 = SweepService(start=False, slice_coalitions=2)
    r = FleetRouter(shards={"s0": s0, "s1": s1}, backoff_sec=0.0,
                    fault_plan="shardkill@shard0:sec0")
    with trace.collect() as recs:
        h = r.submit(scenario(7), tenant="t0")
        r.run_until_idle(timeout=600)
    assert [rec for rec in recs if rec["name"] == "router.fault"]
    assert r._shards["s0"].dead
    assert h.status == "completed" and h.shard_id == "s1"
    np.testing.assert_array_equal(values_of(h), ref)
    r.close()
    s0.shutdown(drain=False)
    s1.shutdown(drain=False)


# -- bench + load_gen wiring (satellite) --------------------------------------

def test_bench11_dispatches_to_router():
    import importlib
    import inspect
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    bench = importlib.import_module("bench")
    assert hasattr(bench, "bench_router")
    src = inspect.getsource(bench.main)
    assert 'config == "11"' in src and "bench_router" in src
    # the router knobs are workload-shaping: the bench knob list
    # carries every one of them
    for knob in ("MPLC_TPU_ROUTER_BUDGET", "MPLC_TPU_ROUTER_BACKOFF_SEC",
                 "MPLC_TPU_ROUTER_REPIN_OVERLOADS",
                 "MPLC_TPU_ROUTER_FAULT_PLAN", "MPLC_TPU_ROUTER_SERVE",
                 "MPLC_TPU_FLEET_STALE_SEC",
                 "MPLC_TPU_SERVICE_RETRY_FLOOR_SEC"):
        assert knob in bench._WORKLOAD_KNOBS


def test_load_gen_router_mode_wired():
    import importlib
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "scripts"))
    load_gen = importlib.import_module("load_gen")
    assert hasattr(load_gen, "run_router")
    assert hasattr(load_gen, "run_router_shard")
    sc = load_gen.scenario_from_spec({"partners": 2, "seed": 3})
    assert sc.partners_count == 2


# -- InProcShard surface ------------------------------------------------------

def test_inproc_shard_adoption_is_idempotent():
    """A failover resubmission that bounces (overload) and retries must
    re-adopt the recovered seed without error — the seed values are
    identical by construction, adoption is idempotent."""
    svc = SweepService(start=False, slice_coalitions=4)
    shard = InProcShard("s", svc)
    req = {"scenario": scenario(7), "method": "Shapley values",
           "tenant": "t0", "job_id": "jX", "deadline_sec": None,
           "priority": None, "credential": None}
    recover = {"values": {(1,): 0.5}, "partners_count": P}
    shard._adopt(recover, req)
    shard._adopt(recover, req)          # idempotent re-adoption
    assert svc._jobs.get("jX") is None  # adoption alone submits nothing
    shard.submit(req, recover=recover)
    svc.run_until_idle()
    job = svc._jobs["jX"]
    assert job.status == "completed"
    assert job.recovered_values == 1
    svc.shutdown(drain=False)


def test_backoff_honors_hint_beyond_cap():
    """The 32× cap bounds the router's OWN exponential term, never the
    shard's explicit retry_after_sec hint — retrying sooner than the
    shard asked would defeat the hint's whole purpose."""
    r = FleetRouter(shards={}, backoff_sec=0.0)
    # base 0.0: exponential term and cap are both 0 — only the hint
    # can make the router wait, and it must be honored in full
    t0 = time.monotonic()
    r._backoff_wait(0.12, attempt=1)
    assert time.monotonic() - t0 >= 0.12
    r.close()


def test_terminal_jobs_pruned_into_bounded_varz_archive(tmp_path):
    """A long-lived router must not leak one req+handle per job: the
    refresh retires terminal routed jobs to a small summary archive —
    /varz still shows them, pump/failover no longer iterate them, and
    their ids stay reserved while archived."""
    r, s0, s1 = _two_shard_router(tmp_path)
    h = r.submit(scenario(7), tenant="t0", job_id="jP")
    assert "jP" in r._routed
    r.run_until_idle(timeout=600)
    assert h.status == "completed"
    r._refresh()
    assert "jP" not in r._routed          # full record dropped
    vz = r.varz_view()
    assert vz["jobs"]["jP"]["status"] == "completed"
    assert vz["jobs"]["jP"]["shard"] == h.shard_id
    with pytest.raises(ValueError, match="already routed"):
        r.submit(scenario(8), tenant="t0", job_id="jP")
    r.close()
    s0.shutdown(drain=False)
    s1.shutdown(drain=False)


def test_threaded_shard_kill_stops_workers_before_failover(tmp_path):
    """Killing a THREADED (start=True) in-proc shard stops its worker
    pool at the quantum boundary before failover resubmits its jobs —
    otherwise the 'dead' shard would keep executing the same jobs a
    survivor re-runs (duplicate execution, double metering). The
    journal stays SIGKILL-shaped and the failed-over result is
    bit-identical to a solo fault-free run."""
    ref = solo_values(7)
    s0 = SweepService(start=True, workers=1, slice_coalitions=1,
                      journal_path=str(tmp_path / "s0.wal"))
    s1 = SweepService(start=False, slice_coalitions=2,
                      journal_path=str(tmp_path / "s1.wal"))
    r = FleetRouter(shards={"s0": s0, "s1": s1}, backoff_sec=0.0)
    r._pins["t0"] = "s0"                  # force the threaded shard
    h = r.submit(scenario(7), tenant="t0")
    assert h.shard_id == "s0"
    r.kill_shard("s0")
    # the pool is stopped: no thread left to keep executing the corpse's
    # jobs while the survivor re-runs them
    assert s0._abandoned and s0._workers == []
    if not h.done:                        # completed-before-kill is fine
        assert h.failed_over and h.shard_id == "s1"
    r.run_until_idle(timeout=600)
    assert h.status == "completed"
    np.testing.assert_array_equal(values_of(h), ref)
    r.close()
    s0.shutdown(drain=False)
    s1.shutdown(drain=False)
