"""Widened per-step compute (ISSUE 3): seq-family slot execution, the
fused wide-step mode (MPLC_TPU_STEP_WIDTH_MULT), and the MFU-proxy
observability row.

The contracts under test:
  - seq-pure / seq-with-final-agg / seqavg coalition sweeps through slot
    execution produce BIT-IDENTICAL v(S) to the masked path (the visit
    order is an active-first permutation and rng streams are keyed by
    global partner id / scan position in both), while dispatching at most
    `slot_count` partner passes per coalition-minibatch instead of P;
  - step_width_mult=1 (the default) is bit-identical to the historical
    per-sub-batch stepping across fedavg and the seq family; mult>1 is a
    real deviation (fewer, wider optimizer updates) whose training quality
    is pinned at a fixed seed;
  - the sweep-report compute/MFU-proxy arithmetic.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mplc_tpu.contrib.engine import CharacteristicEngine
from mplc_tpu.contrib.shapley import powerset_order
from mplc_tpu.data.partition import StackedPartners, stack_eval_set
from mplc_tpu.models import TITANIC_LOGREG
from mplc_tpu.mpl.engine import EvalSet, MplTrainer, TrainConfig


def _scenario(approach, n=6, **kw):
    from helpers import build_scenario
    amounts = [(i + 1) / (n * (n + 1) / 2) for i in range(n)]
    params = dict(partners_count=n, amounts_per_partner=amounts,
                  dataset_name="titanic", epoch_count=2,
                  gradient_updates_per_pass_count=2,
                  multi_partner_learning_approach=approach, seed=11)
    params.update(kw)
    return build_scenario(**params)


# -- seq-family slot execution ----------------------------------------------

@pytest.mark.parametrize("approach",
                         ["seq-pure", "seq-with-final-agg", "seqavg"])
def test_seq_slot_sweep_bit_identical_to_masked(approach, monkeypatch):
    """The acceptance contract: the full 6-partner v(S) table of a seq
    sweep is bit-identical between masked full-width execution and slot
    execution — and the slot engine's obs accounting shows <= slot_count
    partner passes per coalition-minibatch where the masked engine shows
    P, for the same |S| < P work."""
    from mplc_tpu.obs import trace

    monkeypatch.delenv("MPLC_TPU_PARTNER_SHARDS", raising=False)
    monkeypatch.delenv("MPLC_TPU_SLOT_POW2", raising=False)
    monkeypatch.delenv("MPLC_TPU_SLOT_MERGE", raising=False)
    subsets = powerset_order(6)

    monkeypatch.setenv("MPLC_TPU_NO_SLOTS", "1")
    masked_eng = CharacteristicEngine(_scenario(approach))
    assert not masked_eng._use_slots
    assert masked_eng.scenario.slot_bucketing == "masked"
    with trace.collect() as masked_recs:
        masked = masked_eng.evaluate(subsets)

    monkeypatch.delenv("MPLC_TPU_NO_SLOTS")
    eng = CharacteristicEngine(_scenario(approach))
    assert eng._use_slots  # the seq family routes through slot buckets now
    with trace.collect() as slot_recs:
        slotted = eng.evaluate(subsets)

    np.testing.assert_array_equal(masked, slotted)
    # the table must discriminate, or the equality contract is vacuous
    assert masked.max() - masked.min() > 1e-3

    def passes_per_coalition_mb(recs):
        # summed engine.batch partner_passes (epochs x MB x passes-per-mb)
        # per slot bucket; None = the singles/masked bucket
        out = {}
        for r in recs:
            if r["name"] != "engine.batch":
                continue
            a = r["attrs"]
            out[a["slot_count"]] = (out.get(a["slot_count"], 0)
                                    + a["partner_passes"])
        return out

    # every masked multi batch dispatched P=6 passes per coalition-mb;
    # every slot batch dispatched exactly its slot_count (< 6 for the
    # merged size-2/3 bucket) — strictly less total pass work
    masked_passes = sum(v for k, v in
                        passes_per_coalition_mb(masked_recs).items())
    slot_by_bucket = passes_per_coalition_mb(slot_recs)
    slot_passes = sum(slot_by_bucket.values())
    assert slot_passes < masked_passes
    for slot_count, passes in slot_by_bucket.items():
        if slot_count is not None:
            assert slot_count <= 6
    # merge-mode widths for 6 partners: sizes 2/3 -> 3, 4/5 -> 5, 6 -> 6
    assert sorted(k for k in slot_by_bucket if k is not None) == [3, 5, 6]


def test_seq_slot_trainer_matches_masked_unit():
    """Trainer-level equality on one coalition, away from the engine's
    batching: a {0, 2} coalition of 4 partners trained via 2 slots (and
    via 3 with one -1 pad) equals the masked seqavg path bit-for-bit."""
    rng_np = np.random.default_rng(5)
    w = rng_np.normal(size=27)

    def make(n):
        x = rng_np.normal(size=(n, 27)).astype(np.float32)
        return x, (x @ w > 0).astype(np.float32)

    from mplc_tpu.data.partner import Partner
    partners = []
    for i, n in enumerate([40, 60, 50, 70]):
        p = Partner(i)
        p.x_train, p.y_train = make(n)
        partners.append(p)
    stacked = StackedPartners.build(partners, 1)
    val = EvalSet(*stack_eval_set(*make(60), 1, 128))
    test = EvalSet(*stack_eval_set(*make(60), 1, 128))

    base = dict(approach="seqavg", aggregator="data-volume", epoch_count=2,
                minibatch_count=2, gradient_updates_per_pass=2,
                is_early_stopping=False, record_partner_val=True)
    rng = jax.random.PRNGKey(7)
    tr_mask = MplTrainer(TITANIC_LOGREG, TrainConfig(**base))
    run_m = jax.jit(tr_mask.epoch_chunk, static_argnames=("n_epochs",))
    s1 = run_m(tr_mask.init_state(rng, 4), stacked, val,
               jnp.array([1., 0., 1., 0.]), rng, n_epochs=2)

    for slot_count, ids in ((2, [0, 2]), (3, [0, 2, -1])):
        tr_slot = MplTrainer(TITANIC_LOGREG,
                             TrainConfig(slot_count=slot_count, **base))
        run_s = jax.jit(tr_slot.epoch_chunk, static_argnames=("n_epochs",))
        s2 = run_s(tr_slot.init_state(rng, 4), stacked, val,
                   jnp.array(ids, jnp.int32), rng, n_epochs=2)
        for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                        jax.tree_util.tree_leaves(s2.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(s1.val_loss_h),
                                      np.asarray(s2.val_loss_h))
        ph1, ph2 = np.asarray(s1.partner_h), np.asarray(s2.partner_h)
        for p in (0, 2):
            np.testing.assert_array_equal(ph1[:, p], ph2[:, p])
        assert np.isnan(ph2[:, 1]).all() and np.isnan(ph2[:, 3]).all()


# -- fused wide-step mode ----------------------------------------------------

def _toy_problem(seed=9):
    rng_np = np.random.default_rng(seed)
    w = rng_np.normal(size=27)

    def make(n):
        x = rng_np.normal(size=(n, 27)).astype(np.float32)
        return x, (x @ w > 0).astype(np.float32)

    from mplc_tpu.data.partner import Partner
    partners = []
    for i, n in enumerate([90, 120, 150]):
        p = Partner(i)
        p.x_train, p.y_train = make(n)
        partners.append(p)
    return (StackedPartners.build(partners, 1),
            EvalSet(*stack_eval_set(*make(90), 1, 128)),
            EvalSet(*stack_eval_set(*make(90), 1, 128)))


@pytest.mark.parametrize("approach", ["fedavg", "seq-pure", "seqavg"])
def test_step_width_mult_one_is_bit_identical(approach):
    """mult=1 (the MPLC_TPU_STEP_WIDTH_MULT default) must reproduce the
    default-config trainer bit-for-bit — same shapes, same index windows,
    same rng folds — across fedavg and the seq family."""
    stacked, val, test = _toy_problem()
    base = dict(approach=approach, aggregator="data-volume", epoch_count=2,
                minibatch_count=2, gradient_updates_per_pass=4,
                is_early_stopping=False, record_partner_val=False)
    rng = jax.random.PRNGKey(3)
    mask = jnp.ones((3,), jnp.float32)

    ref_tr = MplTrainer(TITANIC_LOGREG, TrainConfig(**base))
    assert ref_tr.cfg.step_width_mult == 1  # env default
    s_ref = jax.jit(ref_tr.epoch_chunk, static_argnames=("n_epochs",))(
        ref_tr.init_state(rng, 3), stacked, val, mask, rng, n_epochs=2)

    one_tr = MplTrainer(TITANIC_LOGREG,
                        TrainConfig(step_width_mult=1, **base))
    s_one = jax.jit(one_tr.epoch_chunk, static_argnames=("n_epochs",))(
        one_tr.init_state(rng, 3), stacked, val, mask, rng, n_epochs=2)

    for a, b in zip(jax.tree_util.tree_leaves(s_ref.params),
                    jax.tree_util.tree_leaves(s_one.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    _, acc_ref = jax.jit(ref_tr.finalize)(s_ref, test)
    _, acc_one = jax.jit(one_tr.finalize)(s_one, test)
    assert float(acc_ref) == float(acc_one)


def test_step_width_mult_two_deviates_with_pinned_quality():
    """mult=2 is a REAL deviation — ceil(gup/2) wider optimizer updates
    per pass, different trajectory — but at a fixed seed it must still
    train: the quality pin guards against the fused window silently
    dropping or double-counting samples."""
    stacked, val, test = _toy_problem()
    base = dict(approach="fedavg", aggregator="data-volume", epoch_count=3,
                minibatch_count=2, gradient_updates_per_pass=4,
                is_early_stopping=False, record_partner_val=False)
    rng = jax.random.PRNGKey(3)
    mask = jnp.ones((3,), jnp.float32)

    accs = {}
    for mult in (1, 2):
        tr = MplTrainer(TITANIC_LOGREG,
                        TrainConfig(step_width_mult=mult, **base))
        st = jax.jit(tr.epoch_chunk, static_argnames=("n_epochs",))(
            tr.init_state(rng, 3), stacked, val, mask, rng, n_epochs=3)
        _, acc = jax.jit(tr.finalize)(st, test)
        accs[mult] = float(acc)
        params = jax.tree_util.tree_leaves(st.params)
        assert all(np.isfinite(np.asarray(p)).all() for p in params)
    # the deviation is real (different trajectory)...
    assert accs[2] != accs[1]
    # ...and the fixed-seed quality pin: the planted-logistic problem is
    # separable enough that halving the update count must not collapse
    # training (a windowing bug that trains on garbage rows lands far
    # below this)
    assert accs[2] >= 0.75
    assert accs[2] >= accs[1] - 0.1


def test_subbatch_mult_one_matches_historical_formula():
    """mult=1 parity against an INDEPENDENT transcription of the pre-PR-3
    window arithmetic (not the new code compared with itself): a stride or
    validity regression in the rewritten `_subbatch` that shifted the
    mult=1 window would slip past same-code comparisons but fails here."""
    for size, mbc, gup, mb_i in [(100, 2, 4, 0), (100, 2, 4, 1),
                                 (37, 2, 5, 1), (51, 3, 4, 2)]:
        n_max = size + 10
        rng = np.random.default_rng(size)
        perm = jnp.asarray(rng.permutation(n_max).astype(np.int32))
        cfg = TrainConfig(approach="fedavg", minibatch_count=mbc,
                          gradient_updates_per_pass=gup)
        assert cfg.step_width_mult == 1
        tr = MplTrainer(TITANIC_LOGREG, cfg)
        mb_cap = max(n_max // mbc, 1)
        sb_cap = (mb_cap + gup - 1) // gup
        perm_np = np.asarray(perm)
        for g in range(gup):
            idx, valid = tr._subbatch(perm, jnp.int32(size), mb_i, g,
                                      sb_cap)
            # the historical formula, verbatim from the pre-change code
            valid_mb = size // mbc
            sb = (valid_mb + gup - 1) // gup
            ar = np.arange(sb_cap, dtype=np.int32)
            local = g * sb + ar
            ref_valid = ((ar < sb) & (local < valid_mb)).astype(np.float32)
            pos = mb_i * valid_mb + local
            ref_idx = perm_np[np.clip(pos, 0, n_max - 1)]
            np.testing.assert_array_equal(np.asarray(idx), ref_idx)
            np.testing.assert_array_equal(np.asarray(valid), ref_valid)


def test_subbatch_fused_windows_cover_exactly_once():
    """The fused window arithmetic: for every (valid_mb, gup, mult), the
    union of the fused steps' valid indices equals the union of the base
    steps' — every minibatch row trained exactly once, none double-counted
    (including gup not divisible by mult and ragged final windows)."""
    for size, mbc, gup, mult in [(100, 2, 4, 2), (100, 2, 4, 3),
                                 (37, 2, 5, 2), (64, 4, 8, 4),
                                 (51, 3, 4, 4), (200, 2, 8, 2)]:
        n_max = size
        perm = jnp.arange(n_max, dtype=jnp.int32)

        def windows(width_mult):
            cfg = TrainConfig(approach="fedavg", minibatch_count=mbc,
                              gradient_updates_per_pass=gup,
                              step_width_mult=width_mult)
            tr = MplTrainer(TITANIC_LOGREG, cfg)
            mb_cap = max(n_max // mbc, 1)
            sb_cap = (mb_cap + gup - 1) // gup
            n_steps = (gup + width_mult - 1) // width_mult
            got = []
            for g in range(n_steps):
                idx, valid = tr._subbatch(perm, jnp.int32(size), 0, g,
                                          sb_cap)
                got += np.asarray(idx)[np.asarray(valid) > 0].tolist()
            return got

        base, fused = windows(1), windows(mult)
        assert sorted(base) == sorted(fused), (size, mbc, gup, mult)
        assert len(set(base)) == len(base)          # no double-trains
        assert len(base) == size // mbc             # full minibatch window


def test_engine_sweep_with_mult_two_runs_and_deviates(monkeypatch):
    """End-to-end: a characteristic sweep with step_width_mult=2 trains a
    finite, discriminating v(S) table that differs from the mult=1 table
    (the knob genuinely reaches the compiled coalition programs)."""
    monkeypatch.delenv("MPLC_TPU_PARTNER_SHARDS", raising=False)
    subsets = powerset_order(4)

    def table(mult):
        sc = _scenario("fedavg", n=4)
        eng = CharacteristicEngine(sc)
        # rebuild the multi pipelines at the requested width (the env knob
        # is read at import; tests reach the config field directly)
        from mplc_tpu.contrib.engine import BatchedTrainerPipeline
        eng._multi_cfg = dataclasses.replace(eng._multi_cfg,
                                             step_width_mult=mult)
        eng.multi_pipe = BatchedTrainerPipeline(
            MplTrainer.get(eng.model, eng._multi_cfg), eng.partners_count)
        eng._slot_pipes.clear()
        return eng.evaluate(subsets)

    v1, v2 = table(1), table(2)
    assert np.isfinite(v2).all()
    assert not np.array_equal(v1, v2)
    # singles ran the (untouched) single trainer in both engines
    np.testing.assert_array_equal(v1[:4], v2[:4])


# -- MFU-proxy arithmetic ----------------------------------------------------

def test_zoo_fwd_flops_per_sample():
    from mplc_tpu.models.zoo import fwd_flops_per_sample

    # titanic: one 27 -> 1 dense = 54 FLOPs; the small closed forms keep
    # the arithmetic honest
    assert fwd_flops_per_sample("titanic_logreg") == 2 * 27
    mnist = fwd_flops_per_sample("mnist_cnn")
    assert mnist == (2 * 26 * 26 * 3 * 3 * 1 * 32
                     + 2 * 24 * 24 * 3 * 3 * 32 * 64
                     + 2 * 12 * 12 * 64 * 128
                     + 2 * 128 * 10)
    # conv layers dominate the CNNs by construction
    assert mnist > 2 * (2 * 12 * 12 * 64 * 128)
    for name in ("cifar10_cnn", "imdb_conv1d", "esc50_cnn"):
        v = fwd_flops_per_sample(name)
        assert v is not None and v > 0
    assert fwd_flops_per_sample("cluster_mlp") is None


def test_sweep_report_compute_row_arithmetic():
    from mplc_tpu.obs.report import format_report, sweep_report

    records = [
        {"name": "engine.evaluate", "dur": 10.0,
         "attrs": {"requested": 3, "missing": 3}},
        {"name": "engine.batch", "dur": 4.0,
         "attrs": {"width": 2, "slot_count": 2, "coalitions": 2,
                   "padding": 0, "epochs": 4, "samples": 1000,
                   "partner_passes": 16}},
        {"name": "engine.batch", "dur": 5.0,
         "attrs": {"width": 1, "slot_count": None, "coalitions": 1,
                   "padding": 0, "epochs": 2, "samples": 500,
                   "partner_passes": 4}},
    ]
    rep = sweep_report(records, flops_per_sample=100.0, peak_flops=1e6)
    c = rep["compute"]
    assert c["train_samples"] == 1500
    assert c["partner_passes"] == 20
    assert c["samples_per_s"] == pytest.approx(150.0)
    # fwd+bwd ~ 3x fwd over the evaluate wall-clock
    assert c["model_flops"] == pytest.approx(3.0 * 100.0 * 1500)
    assert c["model_flops_per_s"] == pytest.approx(45000.0)
    assert c["mfu_proxy"] == pytest.approx(45000.0 / 1e6)
    out = format_report(rep)
    assert "mfu_proxy=4.50%" in out
    assert "partner_passes=20" in out

    # no flops input -> the row carries counts only, no rates invented
    rep2 = sweep_report(records)
    assert rep2["compute"]["model_flops_per_s"] is None
    assert rep2["compute"]["mfu_proxy"] is None
    # no peak -> flops/s present, MFU absent (the CPU-mesh case)
    rep3 = sweep_report(records, flops_per_sample=100.0)
    assert rep3["compute"]["model_flops_per_s"] == pytest.approx(45000.0)
    assert rep3["compute"]["mfu_proxy"] is None
    assert "mfu_proxy=n/a" in format_report(rep3)

    # pre-PR-3 records (no samples attr) degrade to an absent row
    old = [{"name": "engine.batch", "dur": 1.0,
            "attrs": {"width": 1, "slot_count": None, "coalitions": 1,
                      "padding": 0, "epochs": 1}}]
    rep4 = sweep_report(old, flops_per_sample=100.0)
    assert rep4["compute"]["train_samples"] == 0
    assert rep4["compute"]["model_flops_per_s"] is None
    assert "compute" not in format_report(rep4)
