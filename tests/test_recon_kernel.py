"""Fused Pallas reconstruction kernel: parity, pass-through, routing.

The contract under test (ops/recon_kernel.py + the evaluator routing in
contrib/reconstruct.py + the program identity in contrib/bank.py):

1. **Mode resolution.** `resolve(mode)` maps MPLC_TPU_RECON_KERNEL to
   `(use_kernel, interpret)`: `off` is always the scan, `interpret` runs
   the kernel through the Pallas interpreter on any backend, `force`
   demands the compiled kernel (raising when Pallas is absent), `auto`
   compiles only where `kernel_available()` (TPU) — so CPU tier-1 runs
   the scan fallback by default.
2. **Interpret-mode parity everywhere.** `reconstruct_batch` with
   `interpret=True` matches a NumPy replay of the per-round masked
   renormalize + accumulate on odd (non-tile-multiple) shapes — the
   padding lanes contribute exact zeros — and a coalition whose every
   round has zero surviving weight reproduces `init` BIT-exactly.
3. **Evaluator routing.** With MPLC_TPU_RECON_KERNEL=interpret the
   ReconstructionEvaluator's values stay within float-reassociation
   distance of the scan path, the PR-4 fault ladder holds bit-identically
   on the kernel path, and the ProgramBank recon key separates
   kernel/scan and fp32/bf16 executables.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from helpers import build_scenario, cluster_mlp_dataset
from mplc_tpu.contrib.bank import ProgramBank
from mplc_tpu.contrib.contributivity import Contributivity
from mplc_tpu.obs import metrics
from mplc_tpu.ops import recon_kernel


# ---------------------------------------------------------------------------
# 1. mode resolution
# ---------------------------------------------------------------------------

def test_resolve_mode_table_on_cpu():
    assert recon_kernel.resolve("off") == (False, False)
    assert recon_kernel.resolve("interpret") == (True, True)
    assert recon_kernel.resolve("force") == (True, False)
    # auto compiles on TPU only — this suite runs on the CPU tier, so
    # auto must fall back to the scan reference
    assert not recon_kernel.kernel_available()
    assert recon_kernel.resolve("auto") == (False, False)


def test_force_without_pallas_raises(monkeypatch):
    monkeypatch.setattr(recon_kernel, "_PALLAS_OK", False)
    assert recon_kernel.resolve("auto") == (False, False)
    assert recon_kernel.resolve("interpret") == (False, False)
    with pytest.raises(RuntimeError, match="force"):
        recon_kernel.resolve("force")


def test_env_mode_reaches_evaluator_plan(monkeypatch):
    from mplc_tpu import constants
    monkeypatch.setenv("MPLC_TPU_RECON_KERNEL", "interpret")
    assert constants.recon_kernel_mode() == "interpret"
    monkeypatch.setenv("MPLC_TPU_RECON_KERNEL", "not-a-mode")
    with pytest.warns(UserWarning):
        assert constants.recon_kernel_mode() == "auto"


# ---------------------------------------------------------------------------
# 2. interpret-mode parity vs a NumPy replay (odd shapes => padding)
# ---------------------------------------------------------------------------

def _fixture_game(B=5, R=3, P=4, seed=0):
    """Odd-shaped random reconstruction inputs: nothing is a multiple of
    the kernel tiles (B=5, K=R*P=12, D=5*3+7=22), so every padding path
    (batch rows, K tail, D tail) is exercised."""
    rng = np.random.default_rng(seed)
    masks = (rng.random((B, P)) < 0.6).astype(np.float32)
    masks[0] = 0.0                       # the zero-weight pass-through row
    masks[1] = 1.0                       # and a grand-coalition row
    weights = rng.random((R, P)).astype(np.float32)
    weights[R - 1] = 0.0                 # an early-stopped (all-zero) round
    init = {"w": rng.standard_normal((5, 3)).astype(np.float32),
            "b": rng.standard_normal((7,)).astype(np.float32)}
    deltas = {k: rng.standard_normal((R, P) + v.shape).astype(np.float32)
              for k, v in init.items()}
    return masks, init, deltas, weights


def _np_reference(masks, init, deltas, weights):
    ws = weights[None, :, :] * masks[:, None, :]          # [B, R, P]
    denom = ws.sum(-1, keepdims=True)
    wn = np.where(denom > 0, ws / np.maximum(denom, 1e-12), 0.0)
    return {k: init[k][None] + np.einsum("brp,rp...->b...", wn, deltas[k])
            for k in init}


def test_interpret_parity_on_odd_shapes():
    masks, init, deltas, weights = _fixture_game()
    ref = _np_reference(masks, init, deltas, weights)
    out = recon_kernel.reconstruct_batch(
        jnp.asarray(masks), {k: jnp.asarray(v) for k, v in init.items()},
        {k: jnp.asarray(v) for k, v in deltas.items()},
        jnp.asarray(weights), interpret=True)
    for k in init:
        got = np.asarray(out[k])
        assert got.shape == (masks.shape[0],) + init[k].shape
        assert got.dtype == np.float32
        np.testing.assert_allclose(got, ref[k], rtol=1e-5, atol=1e-5)


def test_zero_weight_coalition_passes_init_through_bit_exactly():
    masks, init, deltas, weights = _fixture_game()
    out = recon_kernel.reconstruct_batch(
        jnp.asarray(masks), {k: jnp.asarray(v) for k, v in init.items()},
        {k: jnp.asarray(v) for k, v in deltas.items()},
        jnp.asarray(weights), interpret=True)
    # row 0's mask is all-zero: every round renormalizes to exact-zero
    # weights and the matmul contributes exact 0.0 — BIT-equal to init
    for k in init:
        np.testing.assert_array_equal(np.asarray(out[k])[0], init[k])


def test_normalized_round_weights_contract():
    masks, _, _, weights = _fixture_game()
    wn = np.asarray(recon_kernel.normalized_round_weights(
        jnp.asarray(masks), jnp.asarray(weights)))
    B, (R, P) = masks.shape[0], weights.shape
    assert wn.shape == (B, R, P)
    ws = weights[None] * masks[:, None]
    denom = ws.sum(-1)
    np.testing.assert_array_equal(wn[denom == 0], 0.0)    # exact zeros
    np.testing.assert_allclose(wn.sum(-1)[denom > 0], 1.0, rtol=1e-6)


def test_bf16_precision_leaf_dtypes():
    masks, init, deltas, weights = _fixture_game()
    out = recon_kernel.reconstruct_batch(
        jnp.asarray(masks), {k: jnp.asarray(v) for k, v in init.items()},
        {k: jnp.asarray(v) for k, v in deltas.items()},
        jnp.asarray(weights), precision="bf16", interpret=True)
    ref = _np_reference(masks, init, deltas, weights)
    for k in init:
        assert out[k].dtype == jnp.bfloat16
        # bf16 inputs + fp32 accumulation: bounded by bf16 resolution
        np.testing.assert_allclose(
            np.asarray(out[k], dtype=np.float32), ref[k],
            rtol=0.05, atol=0.05)


# ---------------------------------------------------------------------------
# 3. evaluator routing: env-selected kernel path vs the scan reference
# ---------------------------------------------------------------------------

def _small_scenario():
    return build_scenario(
        partners_count=3, amounts_per_partner=[0.2, 0.3, 0.5],
        dataset=cluster_mlp_dataset(n=240, seed=9, scale=1.0),
        epoch_count=2, minibatch_count=2)


_COALITIONS = [(0,), (1,), (2,), (0, 1), (0, 2), (1, 2), (0, 1, 2)]


def _recon_values(monkeypatch, mode):
    if mode is None:
        monkeypatch.delenv("MPLC_TPU_RECON_KERNEL", raising=False)
    else:
        monkeypatch.setenv("MPLC_TPU_RECON_KERNEL", mode)
    c = Contributivity(_small_scenario())
    recon = c._reconstructor()
    expect = recon_kernel.resolve(mode or "auto")
    assert recon.kernel_plan() == expect
    return np.asarray(recon.evaluate(_COALITIONS), dtype=np.float64)


def test_evaluator_interpret_matches_scan(monkeypatch):
    scan = _recon_values(monkeypatch, "off")
    kern = _recon_values(monkeypatch, "interpret")
    # same contraction, different association: ledger-bounded closeness,
    # not bit-equality
    np.testing.assert_allclose(kern, scan, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("plan,expect", [
    ("transient@batch1,transient@batch3", "engine.retries"),
    ("oom@batch2", "engine.cap_halvings"),
])
def test_interpret_fault_ladder_bit_identical(monkeypatch, plan, expect):
    """The PR-4 invariant extends to the kernel path: fault-injected
    kernel-mode reconstruction == fault-free kernel-mode reconstruction,
    bit for bit."""
    monkeypatch.setenv("MPLC_TPU_RECON_KERNEL", "interpret")
    monkeypatch.delenv("MPLC_TPU_FAULT_PLAN", raising=False)

    def run():
        c = Contributivity(_small_scenario())
        c.GTG_Shapley(sv_accuracy=1.0, min_iter=16, perm_batch=8)
        return np.array(c.contributivity_scores)

    clean = run()
    metrics.reset()
    monkeypatch.setenv("MPLC_TPU_FAULT_PLAN", plan)
    monkeypatch.setenv("MPLC_TPU_RETRY_BACKOFF_SEC", "0")
    faulted = run()
    snap = metrics.snapshot()
    assert snap["counters"].get("engine.faults_injected", 0) >= 1
    assert snap["counters"].get(expect, 0) >= 1
    np.testing.assert_array_equal(clean, faulted)


def test_bank_recon_key_separates_kernel_and_precision(monkeypatch):
    """A scan executable must never serve a kernel query (or fp32 a bf16
    one) from a shared bank: the recon key covers both axes."""
    monkeypatch.delenv("MPLC_TPU_RECON_KERNEL", raising=False)
    c = Contributivity(_small_scenario())
    recon = c._reconstructor()
    bank = ProgramBank(c.engine)
    keys = set()
    for kernel_plan in [(False, False), (True, True), (True, False)]:
        for precision in ("fp32", "bf16"):
            recon._kernel = kernel_plan
            recon.precision = precision
            keys.add(bank.recon_key(recon, width=4))
    assert len(keys) == 6
