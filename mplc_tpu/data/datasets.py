"""Datasets: the L1 layer.

Mirrors the reference `Dataset` ABC contract
(/root/reference/mplc/dataset.py:37-106): attributes `x_train/y_train/
x_val/y_val/x_test/y_test, input_shape, num_classes`, a global 90/10
train/val split performed once at construction (random_state=42), and
overridable local split hooks used by the basic partitioner.

Deviation from the reference, by necessity and by design:
  - The reference downloads MNIST/CIFAR10/IMDB/ESC50/Titanic from the
    network (retry loops, /root/reference/mplc/dataset.py:124-142 et al.).
    This environment has no egress, so each loader first looks for a local
    cache (`~/.keras/datasets`, or `$MPLC_TPU_DATA_DIR`) and otherwise
    builds a *deterministic synthetic* dataset with the exact same shapes,
    class structure and learnability profile (class-prototype + noise).
    `Dataset.provenance` records which path was taken. MNIST additionally
    falls back to sklearn's bundled `load_digits` (real handwriting,
    upsampled 8x8 -> 28x28) as prototype stock.
  - Arrays are float32 NHWC from the start (the reference reshapes and
    rescales at download time too).
"""

from __future__ import annotations

import os
from pathlib import Path

import numpy as np
from sklearn.model_selection import train_test_split

from .. import constants
from ..models import zoo as model_zoo
from ..models.core import Model


def to_categorical(y: np.ndarray, num_classes: int) -> np.ndarray:
    out = np.zeros((len(y), num_classes), np.float32)
    out[np.arange(len(y)), y.astype(int)] = 1.0
    return out


class Dataset:
    """Container for one dataset + its model family.

    Matches the reference constructor signature
    (/root/reference/mplc/dataset.py:37-59) with `model` replacing the
    Keras `generate_new_model` factory.
    """

    def __init__(self, dataset_name: str, input_shape: tuple, num_classes: int,
                 x_train: np.ndarray, y_train: np.ndarray,
                 x_test: np.ndarray, y_test: np.ndarray,
                 model: Model | None = None, provenance: str = "user"):
        self.name = dataset_name
        self.input_shape = tuple(input_shape)
        self.num_classes = num_classes
        self.x_train = x_train
        self.x_val = None
        self.x_test = x_test
        self.y_train = y_train
        self.y_val = None
        self.y_test = y_test
        self.model = model
        self.provenance = provenance
        self.train_val_split_global()

    # -- splits (reference: dataset.py:62-77) --------------------------------

    def train_val_split_global(self):
        if self.x_val is not None or self.y_val is not None:
            raise Exception("x_val and y_val should be of NoneType")
        self.x_train, self.x_val, self.y_train, self.y_val = train_test_split(
            self.x_train, self.y_train, test_size=0.1, random_state=42)

    @staticmethod
    def train_test_split_local(x, y):
        return x, np.array([]), y, np.array([])

    @staticmethod
    def train_val_split_local(x, y):
        return x, np.array([]), y, np.array([])

    # -- proportion shrink (reference: dataset.py:83-106) --------------------

    def shorten_dataset_proportion(self, dataset_proportion: float):
        if dataset_proportion == 1:
            return
        if not 0 < dataset_proportion < 1:
            raise ValueError("The dataset proportion should be strictly between 0 and 1")
        keep_train = int(round(len(self.x_train) * dataset_proportion))
        keep_val = int(round(len(self.x_val) * dataset_proportion))
        train_idx = np.arange(len(self.x_train))
        val_idx = np.arange(len(self.x_val))
        rng = np.random.RandomState(42)
        rng.shuffle(train_idx)
        rng.shuffle(val_idx)
        self.x_train = self.x_train[train_idx[:keep_train]]
        self.y_train = self.y_train[train_idx[:keep_train]]
        self.x_val = self.x_val[val_idx[:keep_val]]
        self.y_val = self.y_val[val_idx[:keep_val]]

    def generate_new_model(self) -> Model:
        """Reference-API-compatible alias (`generate_new_model`,
        /root/reference/mplc/dataset.py:79-81) returning the pure-functional
        model family instead of a fresh Keras graph (params come from
        `model.init(rng)`)."""
        return self.model


# ---------------------------------------------------------------------------
# Offline caches and synthetic generators
# ---------------------------------------------------------------------------

def _cache_dirs() -> list[Path]:
    dirs = []
    env = os.environ.get("MPLC_TPU_DATA_DIR")
    if env:
        dirs.append(Path(env))
    dirs.append(Path.home() / ".keras" / "datasets")
    return dirs


def _find_cache(*names: str) -> Path | None:
    for d in _cache_dirs():
        for n in names:
            p = d / n
            if p.exists():
                return p
    return None


def _synth_scale() -> float:
    return float(os.environ.get("MPLC_TPU_SYNTH_SCALE", "1.0"))


def _synth_noise(default: float) -> float:
    """Noise level for the synthetic image datasets. Raising it keeps the
    task learnable but stops accuracy saturating at 1.0, so coalition
    scores — and therefore Shapley values — actually differ (bench.py sets
    this; the quick test fixtures keep the easier default)."""
    return float(os.environ.get("MPLC_TPU_SYNTH_NOISE", str(default)))


def synthetic_image_classification(rng: np.random.Generator, n: int,
                                   shape: tuple, num_classes: int,
                                   signal: float = 1.0, noise: float = 0.35
                                   ) -> tuple[np.ndarray, np.ndarray]:
    """Class-prototype images + Gaussian noise: learnable by a small CNN to
    high accuracy, with per-class structure so label corruption genuinely
    hurts — the property the contributivity oracle tests rely on."""
    protos = rng.uniform(0.0, 1.0, size=(num_classes,) + tuple(shape)).astype(np.float32)
    # Smooth prototypes a little so convs have spatial structure to find.
    if len(shape) == 3:
        protos = 0.5 * protos + 0.25 * np.roll(protos, 1, axis=1) + 0.25 * np.roll(protos, 1, axis=2)
    y = rng.integers(0, num_classes, size=n)
    x = protos[y] * signal + rng.normal(0.0, noise, size=(n,) + tuple(shape)).astype(np.float32)
    return np.clip(x, 0.0, 1.0).astype(np.float32), y.astype(np.int64)


def upsample_digits_28x28(imgs: np.ndarray) -> np.ndarray:
    """[N, 8, 8] sklearn-digits images (0..16 ints) -> [N, 28, 28] float32
    in [0, 1], nearest-neighbor 3x upsample centered on the MNIST canvas.
    One copy of the geometry, shared by the synthetic-MNIST prototype
    stock below and the real-digits e2e gate (tests/test_e2e.py)."""
    up = np.kron(imgs / 16.0, np.ones((3, 3)))  # 8x8 -> 24x24
    out = np.zeros((len(imgs), 28, 28), np.float32)
    out[:, 2:26, 2:26] = up
    return out


def _digits_prototypes() -> np.ndarray | None:
    """Real handwritten-digit prototypes from sklearn's bundled digits set,
    upsampled to 28x28 (no network needed)."""
    try:
        from sklearn.datasets import load_digits
    except Exception:
        return None
    d = load_digits()
    protos = np.stack([
        upsample_digits_28x28(d.images[d.target == c]).mean(axis=0)
        for c in range(10)])
    return protos


# -- raw-data featurization --------------------------------------------------

def featurize_titanic_csv(csv_path) -> tuple[np.ndarray, np.ndarray]:
    """Engineer the 27 model features from a raw Stanford-CS109-format
    Titanic CSV (columns: Survived, Pclass, Name, Sex, Age,
    Siblings/Spouses Aboard, Parents/Children Aboard, Fare).

    Reference semantics (/root/reference/mplc/dataset.py:237-258): family
    size, name length, is-alone and a sex flag are derived; passenger class
    and the honorific (first word of the name) are one-hot encoded; Age and
    Fare stay numeric. Two deliberate fixes over the reference: the sex
    comparison is case-insensitive (upstream compares against "Male" while
    the CSV says "male", zeroing the column), and the honorific one-hot is
    pinned to the 18 most frequent titles so the output width is always
    exactly TITANIC_NUM_FEATURES regardless of CSV contents.
    """
    import pandas as pd
    df = pd.read_csv(csv_path, index_col=False)
    if df.columns[0].startswith("Unnamed"):
        df = df.drop(columns=df.columns[0])
    y = df["Survived"].to_numpy(np.float32)

    sibs = df["Siblings/Spouses Aboard"].to_numpy(np.float32)
    parch = df["Parents/Children Aboard"].to_numpy(np.float32)
    fam_size = sibs + parch
    cols = [
        df["Sex"].str.lower().eq("male").to_numpy(np.float32),
        df["Age"].to_numpy(np.float32),
        df["Fare"].to_numpy(np.float32),
        fam_size,
        df["Name"].str.len().to_numpy(np.float32),
        (fam_size == 0).astype(np.float32),
    ]
    for pclass in (1, 2, 3):
        cols.append(df["Pclass"].eq(pclass).to_numpy(np.float32))

    titles = df["Name"].str.split().str[0]
    n_title_cols = model_zoo.TITANIC_NUM_FEATURES - len(cols)
    counts = titles.value_counts()
    kept = sorted(counts.index[:n_title_cols])
    for t in kept:
        cols.append(titles.eq(t).to_numpy(np.float32))
    while len(cols) < model_zoo.TITANIC_NUM_FEATURES:
        cols.append(np.zeros(len(df), np.float32))

    x = np.stack(cols, axis=1).astype(np.float32)
    return np.nan_to_num(x), y


def load_esc50_raw(folder) -> tuple[np.ndarray, np.ndarray]:
    """MFCC featurization of a raw ESC-50 checkout: `<folder>/esc50.csv`
    (filename + target columns) and `<folder>/audio/*.wav`. Each clip
    becomes a [40, 431, 1] MFCC image (reference mplc/dataset.py:604-617;
    MFCCs computed by mplc_tpu.data.audio, librosa-default parameters).
    """
    import pandas as pd
    from .audio import load_wav, mfcc

    folder = Path(folder)
    df = pd.read_csv(folder / "esc50.csv")
    feats, ys = [], []
    for fname, target in zip(df["filename"], df["target"]):
        samples, sr = load_wav(folder / "audio" / fname)
        m = mfcc(samples, sr, n_mfcc=40)
        # pin the frame axis to the model's 431 (5 s @ 44.1 kHz / hop 512)
        if m.shape[1] < 431:
            m = np.pad(m, ((0, 0), (0, 431 - m.shape[1])))
        feats.append(m[:, :431])
        ys.append(int(target))
    x = np.stack(feats).astype(np.float32)[..., None]
    return x, np.asarray(ys, np.int64)


# -- per-dataset loaders -----------------------------------------------------

def load_mnist() -> Dataset:
    cache = _find_cache("mnist.npz")
    if cache is not None:
        with np.load(cache, allow_pickle=True) as f:
            x_train, y_train = f["x_train"], f["y_train"]
            x_test, y_test = f["x_test"], f["y_test"]
        x_train = (x_train / 255.0).astype(np.float32).reshape(-1, 28, 28, 1)
        x_test = (x_test / 255.0).astype(np.float32).reshape(-1, 28, 28, 1)
        prov = f"cache:{cache}"
    else:
        rng = np.random.default_rng(42)
        n_train = int(60000 * _synth_scale())
        n_test = int(10000 * _synth_scale())
        protos = _digits_prototypes()
        if protos is not None:
            y_train = rng.integers(0, 10, size=n_train)
            y_test = rng.integers(0, 10, size=n_test)
            def make(y):
                # noise high enough that accuracy does not saturate at 1.0 —
                # coalition scores must differ for Shapley values to be
                # informative (and for the contributivity ordering oracle).
                x = protos[y][..., None] + rng.normal(
                    0, _synth_noise(0.45), size=(len(y), 28, 28, 1))
                return np.clip(x, 0, 1).astype(np.float32)
            x_train, x_test = make(y_train), make(y_test)
            prov = "synthetic:sklearn-digits-prototypes"
        else:
            x_train, y_train = synthetic_image_classification(
                rng, n_train, (28, 28, 1), 10, noise=_synth_noise(0.35))
            x_test, y_test = synthetic_image_classification(
                rng, n_test, (28, 28, 1), 10, noise=_synth_noise(0.35))
            prov = "synthetic:prototype-noise"
    return Dataset(constants.MNIST, (28, 28, 1), 10,
                   x_train, to_categorical(y_train, 10),
                   x_test, to_categorical(y_test, 10),
                   model=model_zoo.MNIST_CNN, provenance=prov)


def load_cifar10() -> Dataset:
    cache = _find_cache("cifar10.npz")
    if cache is not None:
        with np.load(cache, allow_pickle=True) as f:
            x_train, y_train = f["x_train"], f["y_train"].reshape(-1)
            x_test, y_test = f["x_test"], f["y_test"].reshape(-1)
        x_train = (x_train / 255.0).astype(np.float32)
        x_test = (x_test / 255.0).astype(np.float32)
        prov = f"cache:{cache}"
    else:
        rng = np.random.default_rng(43)
        n_train = int(50000 * _synth_scale())
        n_test = int(10000 * _synth_scale())
        x_train, y_train = synthetic_image_classification(rng, n_train, (32, 32, 3), 10,
                                                          signal=0.8,
                                                          noise=_synth_noise(0.45))
        x_test, y_test = synthetic_image_classification(rng, n_test, (32, 32, 3), 10,
                                                        signal=0.8,
                                                        noise=_synth_noise(0.45))
        prov = "synthetic:prototype-noise"
    return Dataset(constants.CIFAR10, (32, 32, 3), 10,
                   x_train, to_categorical(y_train, 10),
                   x_test, to_categorical(y_test, 10),
                   model=model_zoo.CIFAR10_CNN, provenance=prov)


class TitanicDataset(Dataset):
    """Titanic keeps its local 10% test/val split hooks
    (/root/reference/mplc/dataset.py:313-321)."""

    @staticmethod
    def train_test_split_local(x, y):
        return train_test_split(x, y, test_size=0.1, random_state=42)

    @staticmethod
    def train_val_split_local(x, y):
        return train_test_split(x, y, test_size=0.1, random_state=42)


def load_titanic() -> Dataset:
    cache = _find_cache("titanic.npz")
    raw = _find_cache("titanic.csv", "titanic/titanic.csv")
    if cache is not None:
        with np.load(cache, allow_pickle=True) as f:
            x, y = f["x"].astype(np.float32), f["y"].astype(np.float32)
        prov = f"cache:{cache}"
    elif raw is not None:
        x, y = featurize_titanic_csv(raw)
        prov = f"raw:{raw}"
    else:
        # Synthetic 27-feature tabular data with a planted logistic rule
        # (reference preprocesses the Kaggle CSV into 27 one-hot/numeric
        # features, input_shape (27,), /root/reference/mplc/dataset.py:214-215).
        rng = np.random.default_rng(44)
        n = 891
        x = rng.normal(0, 1, size=(n, model_zoo.TITANIC_NUM_FEATURES)).astype(np.float32)
        w = rng.normal(0, 1.5, size=(model_zoo.TITANIC_NUM_FEATURES,))
        p = 1.0 / (1.0 + np.exp(-(x @ w)))
        y = (rng.uniform(size=n) < p).astype(np.float32)
        prov = "synthetic:planted-logistic"
    x_tr, x_te, y_tr, y_te = train_test_split(x, y, test_size=0.1, random_state=42)
    return TitanicDataset(constants.TITANIC, (model_zoo.TITANIC_NUM_FEATURES,), 2,
                          x_tr, y_tr, x_te, y_te,
                          model=model_zoo.TITANIC_LOGREG, provenance=prov)


def load_imdb() -> Dataset:
    cache = _find_cache("imdb.npz")
    rng = np.random.default_rng(45)
    if cache is not None:
        with np.load(cache, allow_pickle=True) as f:
            x_train, y_train = f["x_train"], f["y_train"]
            x_test, y_test = f["x_test"], f["y_test"]
        # pad/truncate to 500 tokens like keras.preprocessing.sequence
        def pad(seqs):
            out = np.zeros((len(seqs), model_zoo.IMDB_SEQ_LEN), np.int32)
            for i, s in enumerate(seqs):
                s = np.asarray(s[:model_zoo.IMDB_SEQ_LEN], np.int32)
                out[i, -len(s):] = s
            return out
        x_train, x_test = pad(x_train), pad(x_test)
        prov = f"cache:{cache}"
    else:
        # Synthetic sentiment: each class has a preferred token band; a small
        # Conv1D+embedding model separates them well above chance.
        n_train = int(25000 * _synth_scale())
        n_test = int(25000 * _synth_scale())
        def make(n):
            y = rng.integers(0, 2, size=n).astype(np.float32)
            x = rng.integers(1, model_zoo.IMDB_NUM_WORDS,
                             size=(n, model_zoo.IMDB_SEQ_LEN)).astype(np.int32)
            # plant class-marker tokens at random positions
            marker_count = 40
            for cls, band in ((0, (100, 200)), (1, (300, 400))):
                idx = np.where(y == cls)[0]
                pos = rng.integers(0, model_zoo.IMDB_SEQ_LEN, size=(len(idx), marker_count))
                tok = rng.integers(band[0], band[1], size=(len(idx), marker_count))
                x[idx[:, None], pos] = tok
            return x, y
        x_train, y_train = make(n_train)
        x_test, y_test = make(n_test)
        prov = "synthetic:token-band"
    return Dataset(constants.IMDB, (model_zoo.IMDB_SEQ_LEN,), 2,
                   x_train, y_train.astype(np.float32),
                   x_test, y_test.astype(np.float32),
                   model=model_zoo.IMDB_CONV1D, provenance=prov)


def load_esc50() -> Dataset:
    cache = _find_cache("esc50.npz")
    raw = None
    for d in _cache_dirs():
        if (d / "esc50" / "esc50.csv").exists() and (d / "esc50" / "audio").is_dir():
            raw = d / "esc50"
            break
    if cache is not None:
        with np.load(cache, allow_pickle=True) as f:
            x, y = f["x"].astype(np.float32), f["y"]
        prov = f"cache:{cache}"
    elif raw is not None:
        x, y = load_esc50_raw(raw)
        prov = f"raw:{raw}"
    else:
        rng = np.random.default_rng(46)
        n = int(2000 * max(_synth_scale(), 0.25))
        x, y = synthetic_image_classification(rng, n, (40, 431, 1), 50,
                                              signal=1.0, noise=0.30)
        prov = "synthetic:prototype-noise"
    x_tr, x_te, y_tr, y_te = train_test_split(x, y, test_size=0.1, random_state=42)
    return Dataset(constants.ESC50, (40, 431, 1), 50,
                   x_tr, to_categorical(y_tr, 50),
                   x_te, to_categorical(y_te, 50),
                   model=model_zoo.ESC50_CNN, provenance=prov)


DATASET_LOADERS = {
    constants.MNIST: load_mnist,
    constants.CIFAR10: load_cifar10,
    constants.TITANIC: load_titanic,
    constants.ESC50: load_esc50,
    constants.IMDB: load_imdb,
}


def load_dataset(name: str) -> Dataset:
    try:
        return DATASET_LOADERS[name]()
    except KeyError:
        raise Exception(
            f"Dataset named '{name}' is not supported (yet). You can construct "
            f"your own Dataset object, or add a loader to DATASET_LOADERS.")
