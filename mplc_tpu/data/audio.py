"""Audio feature extraction: librosa-compatible MFCCs in pure NumPy.

The reference preprocesses ESC-50 wav files with `librosa.feature.mfcc(y,
sr, n_mfcc=40)` (/root/reference/mplc/dataset.py:604-617). librosa is not
available in this environment, so the same pipeline — STFT (hann window,
centered/reflect-padded), Slaney-style mel filterbank power spectrogram,
power_to_db with 80 dB dynamic range, orthonormal DCT-II — is implemented
here on NumPy. Defaults match librosa 0.x: n_fft=2048, hop_length=512,
n_mels=128, fmin=0, fmax=sr/2.

For a 5 s, 44.1 kHz ESC-50 clip this yields [40, 431], matching the
reference model's input_shape (40, 431, 1).
"""

from __future__ import annotations

import numpy as np


def hann_window(n: int) -> np.ndarray:
    # periodic hann, like scipy.signal.get_window("hann", n, fftbins=True)
    return 0.5 * (1.0 - np.cos(2.0 * np.pi * np.arange(n) / n))


def stft_power(y: np.ndarray, n_fft: int = 2048, hop_length: int = 512) -> np.ndarray:
    """Power spectrogram |STFT|^2, centered with reflect padding.
    [n_samples] -> [1 + n_fft//2, 1 + n_samples//hop_length]."""
    y = np.asarray(y, np.float64)
    pad = n_fft // 2
    y = np.pad(y, pad, mode="reflect")
    n_frames = 1 + (len(y) - n_fft) // hop_length
    idx = (np.arange(n_fft)[None, :]
           + hop_length * np.arange(n_frames)[:, None])    # [T, n_fft]
    frames = y[idx] * hann_window(n_fft)[None, :]
    spec = np.fft.rfft(frames, n=n_fft, axis=1)            # [T, 1+n_fft/2]
    return (spec.real ** 2 + spec.imag ** 2).T             # [F, T]


def hz_to_mel(f):
    """Slaney mel scale (librosa default, htk=False): linear below 1 kHz,
    logarithmic above."""
    f = np.asarray(f, np.float64)
    f_sp = 200.0 / 3
    mel = f / f_sp
    min_log_hz = 1000.0
    logstep = np.log(6.4) / 27.0
    above = f >= min_log_hz
    mel = np.where(above,
                   min_log_hz / f_sp + np.log(np.maximum(f, 1e-10) / min_log_hz) / logstep,
                   mel)
    return mel


def mel_to_hz(m):
    m = np.asarray(m, np.float64)
    f_sp = 200.0 / 3
    freq = m * f_sp
    min_log_mel = 1000.0 / f_sp
    logstep = np.log(6.4) / 27.0
    above = m >= min_log_mel
    return np.where(above, 1000.0 * np.exp(logstep * (m - min_log_mel)), freq)


def mel_filterbank(sr: int, n_fft: int, n_mels: int = 128,
                   fmin: float = 0.0, fmax: float | None = None) -> np.ndarray:
    """Slaney-normalized triangular mel filterbank [n_mels, 1 + n_fft//2]."""
    if fmax is None:
        fmax = sr / 2.0
    fft_freqs = np.linspace(0, sr / 2.0, 1 + n_fft // 2)
    mel_pts = mel_to_hz(np.linspace(hz_to_mel(fmin), hz_to_mel(fmax), n_mels + 2))
    fb = np.zeros((n_mels, len(fft_freqs)))
    for i in range(n_mels):
        lower = (fft_freqs - mel_pts[i]) / (mel_pts[i + 1] - mel_pts[i])
        upper = (mel_pts[i + 2] - fft_freqs) / (mel_pts[i + 2] - mel_pts[i + 1])
        fb[i] = np.maximum(0.0, np.minimum(lower, upper))
        # Slaney area normalization
        fb[i] *= 2.0 / (mel_pts[i + 2] - mel_pts[i])
    return fb


def power_to_db(S: np.ndarray, top_db: float = 80.0) -> np.ndarray:
    ref = np.maximum(S.max(), 1e-10)
    log_spec = 10.0 * np.log10(np.maximum(S, 1e-10))
    log_spec -= 10.0 * np.log10(ref)
    return np.maximum(log_spec, -top_db)


def dct_ortho(x: np.ndarray, n_out: int) -> np.ndarray:
    """Orthonormal DCT-II over axis 0, truncated to n_out coefficients
    (scipy.fftpack.dct(x, type=2, norm='ortho') equivalent)."""
    n = x.shape[0]
    k = np.arange(n_out)[:, None]                     # [n_out, 1]
    i = np.arange(n)[None, :]                         # [1, n]
    basis = np.cos(np.pi * k * (2 * i + 1) / (2 * n))  # [n_out, n]
    scale = np.full((n_out, 1), np.sqrt(2.0 / n))
    scale[0, 0] = np.sqrt(1.0 / n)
    return (basis * scale) @ x


def mfcc(y: np.ndarray, sr: int, n_mfcc: int = 40, n_fft: int = 2048,
         hop_length: int = 512, n_mels: int = 128) -> np.ndarray:
    """MFCC matrix [n_mfcc, n_frames] with librosa-default semantics."""
    S = stft_power(y, n_fft=n_fft, hop_length=hop_length)
    mel = mel_filterbank(sr, n_fft, n_mels=n_mels) @ S
    return dct_ortho(power_to_db(mel), n_mfcc)


def load_wav(path) -> tuple[np.ndarray, int]:
    """(mono float64 samples in [-1, 1], sample_rate) via scipy."""
    from scipy.io import wavfile
    sr, data = wavfile.read(path)
    data = np.asarray(data)
    if data.ndim == 2:                                # stereo -> mono
        data = data.mean(axis=1)
    if data.dtype.kind == "i":
        data = data / float(np.iinfo(data.dtype).max)
    elif data.dtype.kind == "u":
        data = (data.astype(np.float64) - 128.0) / 128.0
    return data.astype(np.float64), int(sr)
