from .datasets import Dataset, load_dataset, DATASET_LOADERS, to_categorical
from .partner import Partner
from .partition import (StackedPartners, split_basic, split_advanced,
                        compute_batch_sizes, stack_eval_set)

__all__ = [
    "Dataset", "load_dataset", "DATASET_LOADERS", "to_categorical", "Partner",
    "StackedPartners", "split_basic", "split_advanced", "compute_batch_sizes",
    "stack_eval_set",
]
