"""Partner: one data-providing silo, plus its label-corruption operators.

Mirrors the reference `Partner` (/root/reference/mplc/partner.py:14-124)
including the four corruption families (offset "corrupt", permutation,
Dirichlet "random", per-row shuffle) and their semantics on one-hot or
integer labels. Corruption is the reference's *data-plane fault injector*:
contributivity methods are validated by their ability to down-rank corrupted
partners, so these transforms are first-class here too.

Design change: all randomness is drawn from an explicit `numpy` Generator
(default seeded per partner) instead of the global `random`/`np.random`
state, so scenarios are reproducible end-to-end.
"""

from __future__ import annotations

import numpy as np

from .. import constants
from .datasets import to_categorical

# The corruption vocabulary (`Scenario.corrupted_datasets` entries).
# Scenario validates specs against this list at CONSTRUCTION — an unknown
# name raises immediately with the valid options instead of silently
# running an uncorrupted partner through a "corrupted" scenario.
#   not_corrupted  leave the partner alone
#   corrupted      offset labels by one class (deterministic attack)
#   shuffled       per-row shuffle of the one-hot vector
#   permuted       a random fixed K x K class permutation
#   random         resample labels from a per-class Dirichlet row
#   noisy          seeded Gaussian noise on the FEATURES (sigma = spec
#                  parameter) — the feature-skew / sensor-degradation silo
#   glabel         flip a fraction of labels to ONE seeded global target
#                  class — the targeted label-poisoning attack
CORRUPTION_KINDS = ("not_corrupted", "corrupted", "shuffled", "permuted",
                    "random", "noisy", "glabel")


def _ensure_categorical(y: np.ndarray) -> tuple[np.ndarray, bool]:
    """Reference `_Decorator.categorical_needed`
    (/root/reference/mplc/partner.py:37-55): promote 1-D integer labels to
    one-hot for the transform, remember to demote after."""
    if y.ndim == 1:
        return to_categorical(y.astype(int), int(y.max()) + 1 if len(y) else 2), True
    return y, False


class Partner:
    def __init__(self, partner_id: int, seed: int | None = None):
        self.id = partner_id
        self.batch_size = constants.DEFAULT_BATCH_SIZE

        self.cluster_count: int = 0
        self.cluster_split_option: str = ""
        self.clusters_list: list = []
        self.final_nb_samples: int = 0
        self.final_nb_samples_p_cluster: int = 0

        self.x_train = None
        self.x_val = None
        self.x_test = None
        self.y_train = None
        self.y_val = None
        self.y_test = None

        self.corruption_matrix = None
        self._rng = np.random.default_rng(0xC0A1 + partner_id if seed is None else seed)

    @property
    def num_labels(self) -> int:
        return self.y_train.shape[1]

    @property
    def data_volume(self) -> int:
        return len(self.y_train)

    def _check_proportion(self, proportion: float):
        if not 0 <= proportion <= 1:
            raise ValueError(
                f"The proportion of labels to corrupt was {proportion} "
                f"but it must be between 0 and 1.")

    def corrupt_labels(self, proportion_corrupted: float):
        """Offset corruption: argmax label c -> c-1 (reference partner.py:62-79)."""
        self._check_proportion(proportion_corrupted)
        y, demote = _ensure_categorical(self.y_train)
        n = int(len(y) * proportion_corrupted)
        idx = self._rng.choice(len(y), size=n, replace=False)
        hot = np.argmax(y[idx], axis=1)
        y[idx] = 0.0
        y[idx, hot - 1] = 1.0
        self.y_train = np.argmax(y, axis=1) if demote else y

    def permute_labels(self, proportion_corrupted: float = 1):
        """Apply a random K x K permutation matrix (reference partner.py:81-96)."""
        self._check_proportion(proportion_corrupted)
        y, demote = _ensure_categorical(self.y_train)
        n = int(len(y) * proportion_corrupted)
        idx = self._rng.choice(len(y), size=n, replace=False)
        k = y.shape[1]
        self.corruption_matrix = np.zeros((k, k))
        self.corruption_matrix[np.arange(k), self._rng.permutation(k)] = 1
        y[idx] = y[idx] @ self.corruption_matrix.T
        self.y_train = np.argmax(y, axis=1) if demote else y

    def random_labels(self, proportion_corrupted: float = 1):
        """Resample labels from a per-class Dirichlet row (reference partner.py:98-113)."""
        self._check_proportion(proportion_corrupted)
        y, demote = _ensure_categorical(self.y_train)
        n = int(len(y) * proportion_corrupted)
        idx = self._rng.choice(len(y), size=n, replace=False)
        k = y.shape[1]
        self.corruption_matrix = self._rng.dirichlet(np.ones(k), k)
        rows = self.corruption_matrix[np.argmax(y[idx], axis=1)]
        # vectorized categorical draw per row via inverse-CDF
        u = self._rng.uniform(size=(n, 1))
        draw = (u < np.cumsum(rows, axis=1)).argmax(axis=1)
        y[idx] = 0.0
        y[idx, draw] = 1.0
        self.y_train = np.argmax(y, axis=1) if demote else y

    def shuffle_labels(self, proportion_shuffled: float):
        """Shuffle each selected row's one-hot vector (reference partner.py:116-124)."""
        self._check_proportion(proportion_shuffled)
        y, demote = _ensure_categorical(self.y_train)
        n = int(len(y) * proportion_shuffled)
        idx = self._rng.choice(len(y), size=n, replace=False)
        for i in idx:
            self._rng.shuffle(y[i])
        self.y_train = np.argmax(y, axis=1) if demote else y

    def noisy_features(self, sigma: float = 0.1):
        """Seeded Gaussian noise on the train FEATURES: x += N(0, sigma).
        The feature-plane corruption family ('noisy') — degraded sensors,
        preprocessing drift — as opposed to the label attacks above.
        Integer feature spaces (token ids) cannot absorb additive noise."""
        if sigma < 0:
            raise ValueError(f"noise sigma must be >= 0, got {sigma}")
        x = np.asarray(self.x_train)
        if np.issubdtype(x.dtype, np.integer):
            raise ValueError(
                "'noisy' corruption requires float features; partner "
                f"{self.id}'s features are {x.dtype} (token ids?)")
        self.x_train = (x + self._rng.normal(0.0, sigma, x.shape)
                        ).astype(x.dtype, copy=False)

    def flip_to_global_label(self, proportion_corrupted: float = 1.0):
        """'glabel': flip a fraction of rows to ONE seeded target class —
        the targeted poisoning attack (every corrupted sample claims the
        same label), strictly harder to down-rank than uniform noise
        because the corrupted silo is self-consistent."""
        self._check_proportion(proportion_corrupted)
        y, demote = _ensure_categorical(self.y_train)
        n = int(len(y) * proportion_corrupted)
        idx = self._rng.choice(len(y), size=n, replace=False)
        target = int(self._rng.integers(y.shape[1]))
        y[idx] = 0.0
        y[idx, target] = 1.0
        self.corruption_matrix = np.zeros((y.shape[1], y.shape[1]))
        self.corruption_matrix[:, target] = 1.0
        self.y_train = np.argmax(y, axis=1) if demote else y
