"""Partner: one data-providing silo, plus its label-corruption operators.

Mirrors the reference `Partner` (/root/reference/mplc/partner.py:14-124)
including the four corruption families (offset "corrupt", permutation,
Dirichlet "random", per-row shuffle) and their semantics on one-hot or
integer labels. Corruption is the reference's *data-plane fault injector*:
contributivity methods are validated by their ability to down-rank corrupted
partners, so these transforms are first-class here too.

Design change: all randomness is drawn from an explicit `numpy` Generator
(default seeded per partner) instead of the global `random`/`np.random`
state, so scenarios are reproducible end-to-end.
"""

from __future__ import annotations

import numpy as np

from .. import constants
from .datasets import to_categorical


def _ensure_categorical(y: np.ndarray) -> tuple[np.ndarray, bool]:
    """Reference `_Decorator.categorical_needed`
    (/root/reference/mplc/partner.py:37-55): promote 1-D integer labels to
    one-hot for the transform, remember to demote after."""
    if y.ndim == 1:
        return to_categorical(y.astype(int), int(y.max()) + 1 if len(y) else 2), True
    return y, False


class Partner:
    def __init__(self, partner_id: int, seed: int | None = None):
        self.id = partner_id
        self.batch_size = constants.DEFAULT_BATCH_SIZE

        self.cluster_count: int = 0
        self.cluster_split_option: str = ""
        self.clusters_list: list = []
        self.final_nb_samples: int = 0
        self.final_nb_samples_p_cluster: int = 0

        self.x_train = None
        self.x_val = None
        self.x_test = None
        self.y_train = None
        self.y_val = None
        self.y_test = None

        self.corruption_matrix = None
        self._rng = np.random.default_rng(0xC0A1 + partner_id if seed is None else seed)

    @property
    def num_labels(self) -> int:
        return self.y_train.shape[1]

    @property
    def data_volume(self) -> int:
        return len(self.y_train)

    def _check_proportion(self, proportion: float):
        if not 0 <= proportion <= 1:
            raise ValueError(
                f"The proportion of labels to corrupt was {proportion} "
                f"but it must be between 0 and 1.")

    def corrupt_labels(self, proportion_corrupted: float):
        """Offset corruption: argmax label c -> c-1 (reference partner.py:62-79)."""
        self._check_proportion(proportion_corrupted)
        y, demote = _ensure_categorical(self.y_train)
        n = int(len(y) * proportion_corrupted)
        idx = self._rng.choice(len(y), size=n, replace=False)
        hot = np.argmax(y[idx], axis=1)
        y[idx] = 0.0
        y[idx, hot - 1] = 1.0
        self.y_train = np.argmax(y, axis=1) if demote else y

    def permute_labels(self, proportion_corrupted: float = 1):
        """Apply a random K x K permutation matrix (reference partner.py:81-96)."""
        self._check_proportion(proportion_corrupted)
        y, demote = _ensure_categorical(self.y_train)
        n = int(len(y) * proportion_corrupted)
        idx = self._rng.choice(len(y), size=n, replace=False)
        k = y.shape[1]
        self.corruption_matrix = np.zeros((k, k))
        self.corruption_matrix[np.arange(k), self._rng.permutation(k)] = 1
        y[idx] = y[idx] @ self.corruption_matrix.T
        self.y_train = np.argmax(y, axis=1) if demote else y

    def random_labels(self, proportion_corrupted: float = 1):
        """Resample labels from a per-class Dirichlet row (reference partner.py:98-113)."""
        self._check_proportion(proportion_corrupted)
        y, demote = _ensure_categorical(self.y_train)
        n = int(len(y) * proportion_corrupted)
        idx = self._rng.choice(len(y), size=n, replace=False)
        k = y.shape[1]
        self.corruption_matrix = self._rng.dirichlet(np.ones(k), k)
        rows = self.corruption_matrix[np.argmax(y[idx], axis=1)]
        # vectorized categorical draw per row via inverse-CDF
        u = self._rng.uniform(size=(n, 1))
        draw = (u < np.cumsum(rows, axis=1)).argmax(axis=1)
        y[idx] = 0.0
        y[idx, draw] = 1.0
        self.y_train = np.argmax(y, axis=1) if demote else y

    def shuffle_labels(self, proportion_shuffled: float):
        """Shuffle each selected row's one-hot vector (reference partner.py:116-124)."""
        self._check_proportion(proportion_shuffled)
        y, demote = _ensure_categorical(self.y_train)
        n = int(len(y) * proportion_shuffled)
        idx = self._rng.choice(len(y), size=n, replace=False)
        for i in idx:
            self._rng.shuffle(y[i])
        self.y_train = np.argmax(y, axis=1) if demote else y
